# Build/test entrypoints (reference: Makefile + versions.mk targets).
PYTHON ?= python3

.PHONY: all test unit-test e2e bench golden chart-crds chart-verify validate-generated-assets crds render lint racecheck defrag-smoke native images clean

all: native test

test: unit-test

unit-test:
	$(PYTHON) -m pytest tests/ -q

e2e:
	bash tests/scripts/end-to-end.sh

bench:
	$(PYTHON) bench.py

golden:
	$(PYTHON) scripts/update_golden.py

# regenerate the Helm chart's crds/ from the API definitions
chart-crds:
	$(PYTHON) scripts/update_chart_crds.py

# verify the Helm chart renders identically to the tpuop-cfg render path
chart-verify:
	$(PYTHON) -m pytest tests/test_helm_chart.py -q

# reference: validate-generated-assets (Makefile:242-245) — golden drift check
validate-generated-assets:
	$(PYTHON) -m pytest tests/test_render_states.py -q -k golden

crds:
	$(PYTHON) -m tpu_operator.cmd.tpuop_cfg generate crds

render:
	$(PYTHON) -m tpu_operator.cmd.tpuop_cfg render --values deploy/values.yaml

validate:
	$(PYTHON) scripts/validate_rendered.py

# static analysis: manifest rules, RBAC least-privilege proof, drift,
# metrics catalog, concurrency (lock discipline / deadlock / blocking),
# reconcile contracts (ownership-checked deletes, shared-CM key map,
# fail-closed reads, publish-once status, gated retry charges)
lint:
	$(PYTHON) -m tpu_operator.cmd.tpuop_lint

# runtime race harness: the full suite under instrumented locks — any
# lock-order cycle or mutation-tripwire hit fails the owning test
racecheck:
	TPUOP_RACECHECK=1 $(PYTHON) -m pytest tests/ -q -m "not slow"

# capacity-planning gate: fragmented-torus rescue + policy comparison,
# plain and under the race harness (the scripts/ci.sh pair)
defrag-smoke:
	JAX_PLATFORMS=cpu BENCH_SKIP_DEVICE=1 $(PYTHON) bench.py --defrag-smoke
	TPUOP_RACECHECK=1 JAX_PLATFORMS=cpu BENCH_SKIP_DEVICE=1 $(PYTHON) bench.py --defrag-smoke

native:
	$(MAKE) -C native

images:
	docker build -f docker/Dockerfile -t tpu-operator:dev .
	docker build -f docker/Dockerfile.validator -t tpu-operator-validator:dev .

clean:
	$(MAKE) -C native clean
	rm -rf .pytest_cache tests/__pycache__ tpu_operator/__pycache__
