#!/usr/bin/env bash
# Support-bundle dump (reference: hack/must-gather.sh): collect everything
# needed to debug a tpu-operator install into ARTIFACT_DIR.
set -uo pipefail
ARTIFACT_DIR="${ARTIFACT_DIR:-/tmp/tpu-operator-must-gather}"
NS="${OPERATOR_NAMESPACE:-tpu-operator}"
K="${KUBECTL:-kubectl}"
mkdir -p "$ARTIFACT_DIR"
echo "collecting into $ARTIFACT_DIR (namespace $NS)"

$K version > "$ARTIFACT_DIR/version.txt" 2>&1
$K get nodes -o yaml > "$ARTIFACT_DIR/nodes.yaml" 2>&1
$K get nodes --show-labels > "$ARTIFACT_DIR/node-labels.txt" 2>&1
$K get nodes -o custom-columns='NODE:.metadata.name,HEALTH:.metadata.labels.tpu\.google\.com/tpu\.health,REPAIR:.metadata.labels.tpu\.google\.com/tpu\.repair-state,RETRIES:.metadata.annotations.tpu\.google\.com/tpu\.repair-retries,SLICE:.metadata.labels.tpu\.google\.com/slice\.health' > "$ARTIFACT_DIR/node-health.txt" 2>&1
$K get clusterpolicies.tpu.google.com -o yaml > "$ARTIFACT_DIR/clusterpolicies.yaml" 2>&1
$K get tpuslices.tpu.google.com -o yaml > "$ARTIFACT_DIR/tpuslices.yaml" 2>&1
$K -n "$NS" get all -o wide > "$ARTIFACT_DIR/all.txt" 2>&1
$K -n "$NS" get daemonsets -o yaml > "$ARTIFACT_DIR/daemonsets.yaml" 2>&1
$K -n "$NS" get events --sort-by=.lastTimestamp > "$ARTIFACT_DIR/events.txt" 2>&1
mkdir -p "$ARTIFACT_DIR/pod-logs"
for pod in $($K -n "$NS" get pods -o name 2>/dev/null); do
  name="${pod##*/}"
  $K -n "$NS" logs "$pod" --all-containers --tail=2000 > "$ARTIFACT_DIR/pod-logs/$name.log" 2>&1
  $K -n "$NS" describe "$pod" > "$ARTIFACT_DIR/pod-logs/$name.describe.txt" 2>&1
done
echo "done: $(du -sh "$ARTIFACT_DIR" | cut -f1)"
