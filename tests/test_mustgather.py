"""kubectl-free must-gather (reference: hack/must-gather.sh, which
needs a kubectl workstation and therefore has no automated coverage in
either repo). The collector rides HttpClient, so the fake apiserver can
prove the whole bundle end to end: install the operator, let it reach
Ready, collect, and assert the artifacts describe the real install."""

import time

import yaml

from tpu_operator.api.clusterpolicy import (
    CLUSTER_POLICY_API_VERSION,
    CLUSTER_POLICY_KIND,
    new_cluster_policy,
)
from tpu_operator.controllers.clusterpolicy_controller import (
    ClusterPolicyReconciler,
    setup_with_manager,
)
from tpu_operator.kube import errors
from tpu_operator.kube.fake import FakeClient
from tpu_operator.kube.http_client import HttpClient
from tpu_operator.kube.httpserver import FakeApiServer
from tpu_operator.kube.manager import Manager
from tpu_operator.kube.sim import ClusterSim, make_tpu_node
from tpu_operator.mustgather import collect

NS = "tpu-operator"


def wait_for(fn, timeout=30.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return False


def test_bundle_from_live_install(tmp_path):
    store = FakeClient()
    for i in range(2):
        store.create(make_tpu_node(f"tpu-{i}", "tpu-v5-lite-podslice", "2x4"))
    server = FakeApiServer(store).start()
    client = HttpClient(server.base_url, timeout=10.0)
    sim = ClusterSim(store, ready_delay=0.02, tick=0.01).start()
    mgr = Manager(client, namespace=NS)
    setup_with_manager(mgr, ClusterPolicyReconciler(client, NS))
    try:
        mgr.start()
        client.create(new_cluster_policy())
        assert wait_for(
            lambda: (
                store.get_or_none(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND, "cluster-policy")
                or {}
            )
            .get("status", {})
            .get("state")
            == "ready"
        )
        # seed one pod's fake logs so the log path is proven non-trivially
        pods = store.list("v1", "Pod", NS)
        assert pods, "sim created no operand pods"
        pod = pods[0]
        pod["metadata"].setdefault("annotations", {})[
            "tpu.google.com/fake-logs"
        ] = "line-1\nline-2\n"
        store.update(pod)

        # a TPUServing with live bookkeeping so serving.txt is proven
        # non-trivially (replica map, SLO attainment, scale decisions)
        from tpu_operator.api.tpuserving import new_tpu_serving

        store.create(new_tpu_serving("bundle-serving", {
            "model": {"shape": "1x1x1"},
            "replicas": {"min": 1, "max": 2, "targetRps": 10.0},
            "slo": {"ttftP99Seconds": 2.0},
        }))
        store.patch_status(
            "tpu.google.com/v1alpha1", "TPUServing", "bundle-serving",
            {"status": {"state": "Serving", "serving": {
                "phase": "Serving", "desired": 2, "ready": 2, "routable": 1,
                "replicas": {"bundle-serving-replica-0": "Serving",
                             "bundle-serving-replica-1": "Excluded"},
                "slo": {"ttftP99": 0.4, "ttftTarget": 2.0, "attained": True},
                "decisions": [{"step": 3, "action": "scale-up",
                               "reason": "arrival rate 14.0 rps"}],
            }}},
        )

        # a rendered worker pod + published router weights so pods.txt
        # (the data-plane view) is proven non-trivially
        from tpu_operator import consts

        store.create({
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {
                "name": "bundle-serving-decode-0", "namespace": NS,
                "labels": {consts.POD_MAIN_LABEL: consts.POD_MAIN_SERVING_WORKER},
                "annotations": {
                    consts.WORKER_HASH_ANNOTATION: "abc123def456",
                    consts.WORKER_ROUTE_WEIGHT_ANNOTATION: "1.0",
                },
            },
            "spec": {"containers": [{"name": "worker", "env": []}]},
            "status": {"phase": "Running"},
        })
        store.create({
            "apiVersion": "v1", "kind": "ConfigMap",
            "metadata": {
                "name": "bundle-serving" + consts.SERVING_LOAD_SUFFIX,
                "namespace": NS,
            },
            "data": {
                consts.SERVING_ROUTING_KEY: '{"bundle-serving-replica-0": 1.0}',
            },
        })

        written = collect(client, NS, str(tmp_path))

        def collected_state():
            cps = list(yaml.safe_load_all((tmp_path / "clusterpolicies.yaml").read_text()))
            return cps[0]["status"]["state"]

        # Under heavy load (the full suite with TPUOP_RACECHECK=1
        # instrumentation) a reconcile can transiently flip the CR to
        # notReady in the window between the readiness wait above and
        # the snapshot collect() takes; the bundle must describe the
        # steady install, so re-collect once after re-awaiting Ready.
        if collected_state() != "ready":
            assert wait_for(
                lambda: (
                    store.get_or_none(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND, "cluster-policy")
                    or {}
                )
                .get("status", {})
                .get("state")
                == "ready"
            )
            written = collect(client, NS, str(tmp_path))

        # cluster-scoped + namespaced inventories describe the install
        nodes = list(yaml.safe_load_all((tmp_path / "nodes.yaml").read_text()))
        assert {n["metadata"]["name"] for n in nodes} == {"tpu-0", "tpu-1"}
        assert collected_state() == "ready"
        dses = list(yaml.safe_load_all((tmp_path / "daemonsets.yaml").read_text()))
        assert len(dses) == 11
        labels_txt = (tmp_path / "node-labels.txt").read_text()
        assert "tpu.google.com/tpu.present=true" in labels_txt
        # the health subsystem's per-node view rides in the bundle
        health_txt = (tmp_path / "node-health.txt").read_text()
        assert "tpu-0" in health_txt and "health=" in health_txt and "repair=" in health_txt
        events_txt = (tmp_path / "events.txt").read_text()
        assert "ClusterPolicy" in events_txt  # CR transition events landed
        # the placement subsystem's queue + assignment dump rides too
        placement_txt = (tmp_path / "placement.txt").read_text()
        assert "# placement queue" in placement_txt
        assert "# host assignments" in placement_txt
        # the capacity-planning view rides over the wire as well:
        # per-pool posture, defrag decision history, admission what-ifs
        plan_txt = (tmp_path / "plan.txt").read_text()
        assert "# pools" in plan_txt
        assert "# defrag decisions" in plan_txt
        assert "# admission what-ifs" in plan_txt
        # the predictive-health view: per-host risk scores (empty on
        # this healthy install — the section must still exist so
        # support can trust absence) + the planned-migration ledger
        risk_txt = (tmp_path / "risk.txt").read_text()
        assert "# per-host risk" in risk_txt
        assert "# none at risk" in risk_txt
        assert "planned migrations" in risk_txt
        # the data-plane telemetry view: fleet perf rollup + the
        # operator-published floor table (rendered by pre-requisites in
        # this live install) + gang artifacts section
        telemetry_txt = (tmp_path / "telemetry.txt").read_text()
        assert "# fleet perf" in telemetry_txt
        assert "tpu-0" in telemetry_txt and "perf=" in telemetry_txt
        assert "# perf floors (operator-published)" in telemetry_txt
        assert "matmul_tflops" in telemetry_txt  # the live ConfigMap's table
        assert "# gang step-time artifacts" in telemetry_txt
        # the fabric view: link-health map + gang fabric matrices +
        # worst-edge cut + blame split, even when all empty on this
        # install (the sections must exist for support to trust absence)
        fabric_txt = (tmp_path / "fabric.txt").read_text()
        assert "# link health (operator-recorded link blame)" in fabric_txt
        assert "# gang fabric artifacts" in fabric_txt
        assert "# worst 10 measured edges" in fabric_txt
        assert "# blame decisions" in fabric_txt
        # the flight recorder rides along: this process ran the
        # reconciles, so traces.txt must hold real reconcile span trees
        traces_txt = (tmp_path / "traces.txt").read_text()
        assert "# flight recorder:" in traces_txt
        assert "controller=clusterpolicy" in traces_txt
        assert "verb=" in traces_txt  # api spans inside the reconciles
        slow_txt = (tmp_path / "slow-reconciles.txt").read_text()
        assert "# slowest" in slow_txt and "controller=" in slow_txt
        # the serving view: replica map + SLO attainment + scale
        # decisions with reasons, plus the raw CRs beside it
        serving_txt = (tmp_path / "serving.txt").read_text()
        assert "bundle-serving" in serving_txt
        assert "replicas=2/2(window 1-2)" in serving_txt
        assert "sloAttained=True" in serving_txt
        assert "replica bundle-serving-replica-1  Excluded" in serving_txt
        assert "decision pass=3  scale-up  arrival rate 14.0 rps" in serving_txt
        servings = list(yaml.safe_load_all((tmp_path / "tpuservings.yaml").read_text()))
        assert servings[0]["metadata"]["name"] == "bundle-serving"
        # the data-plane view: rendered worker pods with generation hash
        # + route weight, rendezvous handshake state, router weights
        pods_txt = (tmp_path / "pods.txt").read_text()
        assert "# worker pods" in pods_txt
        assert (
            "bundle-serving-decode-0  main=tpu-serving-worker  phase=Running"
            "  hash=abc123def456  routeWeight=1.0" in pods_txt
        )
        assert "# job rendezvous (progress ConfigMap handshake)" in pods_txt
        assert "# serving router weights (load ConfigMap)" in pods_txt
        assert "'bundle-serving-replica-0': 1.0" in pods_txt
        pod_name = pod["metadata"]["name"]
        log_text = (tmp_path / "pod-logs" / f"{pod_name}.log").read_text()
        assert "line-1\nline-2\n" in log_text  # multi-container pods get headers
        assert "v1.29.0-fake" in (tmp_path / "version.txt").read_text()
        all_txt = (tmp_path / "all.txt").read_text()
        assert "DaemonSet" in all_txt and "2/2" in all_txt  # wide-ish summary
        # every bash-script artifact has an analog (describe excepted:
        # pods.yaml already carries the full objects describe prints)
        stems = {w.split("/")[0] for w in written}
        assert {
            "version.txt", "all.txt",
            "nodes.yaml", "node-labels.txt", "node-health.txt", "placement.txt",
            "clusterpolicies.yaml", "tpuslices.yaml", "tpujobs.yaml", "jobs.txt",
            "tpuservings.yaml", "serving.txt", "pods.txt",
            "daemonsets.yaml", "pods.yaml", "services.yaml", "configmaps.yaml",
            "events.txt", "pod-logs", "traces.txt", "slow-reconciles.txt",
            "telemetry.txt", "fabric.txt",
        } <= stems
    finally:
        mgr.stop()
        sim.stop()
        server.stop()


def test_bundle_survives_broken_collections(tmp_path):
    """A half-broken cluster is when bundles matter: a client that fails
    some LISTs must still produce a bundle with the errors recorded."""

    class FlakyClient(FakeClient):
        def list(self, api_version, kind, namespace=None, **kw):
            if kind == "DaemonSet":
                raise errors.ApiError("apiserver timeout")
            return super().list(api_version, kind, namespace, **kw)

    client = FlakyClient()
    client.create(make_tpu_node("tpu-0"))
    written = collect(client, NS, str(tmp_path))
    assert "daemonsets.yaml" in written
    assert "collection failed" in (tmp_path / "daemonsets.yaml").read_text()
    assert "tpu-0" in (tmp_path / "nodes.yaml").read_text()
