"""Traffic-driven elastic serving: the paged-pool decode engine
(continuous vs static batching, prefill/decode split, tuned-kernel
resolution), the seeded diurnal traffic sim, the fragmentation-aware
scale-down oracle, and the TPUServing controller (autoscaler hysteresis,
routing exclusion, retry-budget quarantine, series lifecycle).

The over-the-wire drill lives in tests/drill.py (run under the shipped
RBAC gate in test_rbac_gate.py); the CI gate is `bench.py
--serving-smoke`.
"""

import dataclasses
import json
import time

import numpy as np
import pytest

from tpu_operator import consts
from tpu_operator.api.tpuserving import (
    TPU_SERVING_API_VERSION,
    TPU_SERVING_KIND,
    ServingPhase,
    TPUServing,
    new_tpu_serving,
)
from tpu_operator.api.tpuslice import TPU_SLICE_API_VERSION, TPU_SLICE_KIND
from tpu_operator.controllers.placement_controller import (
    QUEUE_REQUEST,
    PlacementReconciler,
)
from tpu_operator.controllers.serving_controller import (
    ServingReconciler,
    replica_name,
)
from tpu_operator.kube import errors
from tpu_operator.kube.controller import Request
from tpu_operator.kube.fake import FakeClient
from tpu_operator.kube.objects import new_object
from tpu_operator.kube.sim import (
    DiurnalTraffic,
    ServingTrafficSim,
    make_torus_nodes,
)
from tpu_operator.placement.engine import (
    PlacementEngine,
    scale_down_scores,
    scale_down_victim,
)
from tpu_operator.workloads.serving import (
    DecodeEngine,
    PagedKVPool,
    ServingModelConfig,
    ServingRequest,
    make_requests,
    serving_decode_bench,
)

NS = "tpu-operator"


def tiny_cfg(**over) -> ServingModelConfig:
    base = dict(
        d_model=16, n_heads=2, head_dim=8, d_ff=32, vocab=64,
        page_tokens=4, max_pages=32, max_batch=4, max_seq=32,
        prefill_chunk=4,
    )
    base.update(over)
    return ServingModelConfig(**base)


def req(rid: str, prompt_len: int = 4, decode: int = 3, vocab: int = 64) -> ServingRequest:
    rng = np.random.default_rng(hash(rid) % (2**32))
    return ServingRequest(
        rid=rid,
        prompt=rng.integers(0, vocab, size=prompt_len).astype(np.int32),
        decode_tokens=decode,
    )


# ---------------------------------------------------------------------------
# paged KV pool
# ---------------------------------------------------------------------------


class TestPagedKVPool:
    def test_lazy_allocation_and_free_reuse(self):
        cfg = tiny_cfg(max_pages=4, max_batch=2)
        pool = PagedKVPool(cfg)
        a = pool.alloc_slot()
        assert pool.ensure(a, 1) and len(pool.pages[a]) == 1
        assert pool.ensure(a, cfg.page_tokens) and len(pool.pages[a]) == 1
        assert pool.ensure(a, cfg.page_tokens + 1) and len(pool.pages[a]) == 2
        b = pool.alloc_slot()
        assert pool.ensure(b, 2 * cfg.page_tokens)  # takes the last 2 pages
        assert pool.free_pages == 0
        assert not pool.ensure(a, 3 * cfg.page_tokens)  # exhausted, no eviction
        pool.free_slot(b)
        assert pool.free_pages == 2
        assert pool.ensure(a, 3 * cfg.page_tokens)  # freed pages reused

    def test_unallocated_entries_point_at_scratch(self):
        cfg = tiny_cfg()
        pool = PagedKVPool(cfg)
        slot = pool.alloc_slot()
        assert (pool.table[slot] == pool.scratch).all()
        pool.ensure(slot, 1)
        assert pool.table[slot][0] != pool.scratch
        assert (pool.table[slot][1:] == pool.scratch).all()


# ---------------------------------------------------------------------------
# decode engine
# ---------------------------------------------------------------------------


class TestDecodeEngine:
    def test_submit_rejects_over_capacity_request(self):
        engine = DecodeEngine(tiny_cfg())
        with pytest.raises(ValueError):
            engine.submit(req("big", prompt_len=30, decode=10))

    def test_batched_decode_matches_single_request(self):
        """Continuous batching must not change any request's tokens:
        the same request decodes identically alone and in a full batch
        (padding + paged gather are masked, not approximated)."""
        cfg = tiny_cfg()
        alone = DecodeEngine(cfg, seed=7)
        alone.submit(req("r0", prompt_len=6, decode=5))
        alone.run_until_drained()
        together = DecodeEngine(cfg, seed=7)
        for i in range(4):
            together.submit(req(f"r{i}", prompt_len=6, decode=5))
        together.run_until_drained()
        ref = {r.rid: r.output for r in alone.completed}
        got = {r.rid: r.output for r in together.completed}
        assert got["r0"] == ref["r0"]

    def test_continuous_admits_mid_flight_static_drains_first(self):
        """The batching-policy delta itself: when a short request frees
        its slot, continuous admits the queued request while the long
        one still runs; static keeps it queued until the batch drains."""
        cfg = tiny_cfg(max_batch=2)
        for static in (False, True):
            engine = DecodeEngine(cfg, seed=1, static_batch=static)
            engine.submit(req("a", decode=2))
            engine.submit(req("b", decode=8))
            engine.step()
            engine.submit(req("late", decode=2))
            for _ in range(4):  # `a` completes in here; `b` keeps going
                engine.step()
            if static:
                assert engine.queue and engine.queue[0].rid == "late"
            else:
                assert not engine.queue  # admitted into a's freed slot
            engine.run_until_drained()
            assert len(engine.completed) == 3

    def test_continuous_refills_freed_slot_at_step_boundary(self):
        cfg = tiny_cfg(max_batch=2)
        engine = DecodeEngine(cfg, seed=1)
        engine.submit(req("short", decode=1))
        engine.submit(req("long", decode=10))
        engine.submit(req("waiting", decode=2))
        # short: 1 prefill step (emits its only token) -> completes
        engine.step()
        assert engine.queue and engine.queue[0].rid == "waiting"
        engine.step()  # the freed slot admits `waiting` while `long` runs
        assert not engine.queue
        assert any(s.request.rid == "waiting" for s in engine.slots.values())
        assert any(s.request.rid == "long" for s in engine.slots.values())

    def test_chunked_prefill_never_stalls_peers(self):
        """The prefill/decode split: while a long prompt ingests chunk
        by chunk, an in-flight request keeps producing a token every
        step — one long prompt cannot stall the batch."""
        cfg = tiny_cfg(max_seq=32, prefill_chunk=4)
        engine = DecodeEngine(cfg, seed=2)
        engine.submit(req("steady", prompt_len=4, decode=10))
        engine.step()  # steady prefills (1 chunk) and emits token 1
        steady = next(iter(engine.slots.values()))
        assert steady.decoded == 1
        engine.submit(req("novel", prompt_len=24, decode=2))  # 6 chunks
        for expected in (2, 3, 4, 5, 6):
            engine.step()
            assert steady.decoded == expected  # a token EVERY step
        novel = next(
            s for s in engine.slots.values() if s.request.rid == "novel"
        )
        assert novel.prefilled == 20  # still mid-prefill after 5 steps

    def test_pool_pressure_pauses_then_preempts_youngest(self):
        """Two 3-page requests over a 3-page pool: lanes pause while a
        peer might free a page, and when BOTH are starved (true
        deadlock) the youngest is preempted back to the queue — the
        oldest runs to completion, the evictee recomputes after, and
        both finish."""
        cfg = tiny_cfg(max_pages=3, max_batch=2, page_tokens=4, max_seq=16)
        engine = DecodeEngine(cfg, seed=3)
        engine.submit(req("a", prompt_len=4, decode=8))   # worst case 3 pages
        engine.submit(req("b", prompt_len=4, decode=8))
        paused_seen = False
        for _ in range(80):
            report = engine.step()
            paused_seen = paused_seen or report["paused"] > 0
            if engine.idle:
                break
        assert paused_seen, "pool pressure never paused a lane"
        assert engine.evictions >= 1
        assert len(engine.completed) == 2  # deadlock broken, both finish
        # the preempted request regenerated its full budget
        by_rid = {r.rid: r for r in engine.completed}
        assert len(by_rid["b"].output) == 8

    def test_ttft_and_occupancy_favor_continuous(self):
        out = serving_decode_bench(tiny_cfg(max_batch=4), requests=10,
                                   arrival_ticks=3)
        assert out["continuous"]["occupancy_mean"] > out["static"]["occupancy_mean"]
        assert out["continuous"]["ttft_p99_s"] < out["static"]["ttft_p99_s"]
        assert out["continuous_vs_static_speedup"] > 1.0

    def test_flash_prefill_matches_dense_tokens(self):
        cfg_dense = tiny_cfg(head_dim=16)
        cfg_flash = tiny_cfg(head_dim=16, use_flash_prefill=True)
        outs = []
        for cfg in (cfg_dense, cfg_flash):
            engine = DecodeEngine(cfg, seed=5)
            engine.submit(req("x", prompt_len=8, decode=4))
            engine.run_until_drained()
            outs.append(engine.completed[0].output)
        assert outs[0] == outs[1]

    def test_kernel_configs_resolve_through_autotune_winners(self, monkeypatch):
        """The PR 12 consumption path: published winners reach the
        serving engine through TPU_AUTOTUNE_JSON exactly as they reach
        burn-in — serving runs tuned on every generation."""
        winners = {"cpu": {"flash_fwd": {"s32_h2_d8": {"block_q": 16, "block_k": 8}}}}
        monkeypatch.setenv(consts.AUTOTUNE_ENV, json.dumps(winners))
        monkeypatch.setenv("TPU_GENERATION", "cpu")
        from tpu_operator.workloads import autotune

        monkeypatch.setattr(autotune, "_gen_cache", (None, ""))
        engine = DecodeEngine(tiny_cfg())
        assert tuple(engine.flash_blocks) == (16, 8)


# ---------------------------------------------------------------------------
# seeded traffic
# ---------------------------------------------------------------------------


class TestDiurnalTraffic:
    def test_same_seed_same_log(self):
        a = DiurnalTraffic(seed=11)
        b = DiurnalTraffic(seed=11)
        for tick in range(100):
            a.arrivals(tick)
            b.arrivals(tick)
        assert a.log == b.log
        c = DiurnalTraffic(seed=12)
        for tick in range(100):
            c.arrivals(tick)
        assert c.log != a.log

    def test_diurnal_curve_and_bursts(self):
        t = DiurnalTraffic(seed=0, period_ticks=100, base_rps=2.0,
                           peak_rps=10.0, burst_every=37, burst_ticks=3,
                           burst_rps=25.0)
        assert t.rate(0) == pytest.approx(2.0)       # trough, no tick-0 burst
        assert t.rate(50) == pytest.approx(10.0)     # peak of the sinusoid
        assert t.rate(35) == pytest.approx(25.0)     # burst window (34..36)
        rates = [t.rate(i) for i in range(100)]
        assert min(rates) >= 2.0 and max(rates) == 25.0

    def test_sim_routes_by_weights_and_publishes_load(self):
        client = FakeClient()
        sim = ServingTrafficSim(client, NS, "svc", DiurnalTraffic(seed=3),
                                replica_rps=50.0)
        # controller-published weights: replica-1 excluded
        client.create(new_object(
            "v1", "ConfigMap", "svc" + consts.SERVING_LOAD_SUFFIX, NS,
            data={consts.SERVING_ROUTING_KEY: json.dumps(
                {"svc-replica-0": 1.0, "svc-replica-1": 0.0}
            )},
        ))
        for _ in range(30):
            sim.step()
        assert sim.routed.get("svc-replica-0", 0) > 0
        assert sim.routed.get("svc-replica-1", 0) == 0
        cm = client.get("v1", "ConfigMap", "svc" + consts.SERVING_LOAD_SUFFIX, NS)
        data = cm["data"]
        assert float(data[consts.SERVING_LOAD_ARRIVAL_RATE]) > 0
        assert consts.SERVING_LOAD_TTFT_P99 in data
        assert consts.SERVING_LOAD_QUEUE_DEPTH in data

    def test_queue_builds_without_routable_capacity(self):
        client = FakeClient()
        sim = ServingTrafficSim(client, NS, "svc", DiurnalTraffic(seed=3))
        for _ in range(5):
            sim.step()
        assert len(sim.queue) > 0
        assert sim.routed == {}


# ---------------------------------------------------------------------------
# fragmentation-aware scale-down (the allocator oracle)
# ---------------------------------------------------------------------------


def _line_pool(occupied: dict):
    """A 6x1x1 v5e line (mesh: no wrap links) with hand-placed one-host
    gangs: ``occupied`` maps slice name -> host index. Returns (slices,
    nodes)."""
    nodes = make_torus_nodes(
        (6, 1, 1), prefix="line", accelerator="tpu-v5-lite-podslice", chips=4
    )
    slices = []
    for name, idx in occupied.items():
        labels = nodes[idx]["metadata"]["labels"]
        labels[consts.PLACEMENT_LABEL] = name
        labels[consts.PLACEMENT_INDEX_LABEL] = "0"
        slices.append({
            "apiVersion": TPU_SLICE_API_VERSION, "kind": TPU_SLICE_KIND,
            "metadata": {"name": name},
            "spec": {"placement": {"shape": "1x1x1"}},
        })
    return slices, nodes


class TestScaleDownVictim:
    def test_victim_most_reduces_fragmentation_on_fragmented_torus(self):
        """The hand-built pin: R1 at h1 and R2 at h3 checker a 6-host
        line. Removing R2 merges h2..h5 into one 4-run (frag 0.2);
        removing R1 only merges h0..h2 (frag 0.4). The victim must be
        R2, and its removal must be strictly non-increasing on the
        baseline fragmentation (0.5)."""
        slices, nodes = _line_pool({"r1": 1, "r2": 3})
        base = PlacementEngine(slices, nodes).plan()
        frag_before = max(base.fragmentation.values())
        assert frag_before == pytest.approx(0.5)
        scores = scale_down_scores(slices, nodes, ["r1", "r2"])
        assert scores["r1"][0] == pytest.approx(0.4)
        assert scores["r2"][0] == pytest.approx(0.2)
        victim = scale_down_victim(slices, nodes, ["r1", "r2"])
        assert victim == "r2"
        assert scores[victim][0] <= frag_before  # strictly non-increasing

    def test_unplaced_candidate_always_wins(self):
        slices, nodes = _line_pool({"r1": 1})
        slices.append({
            "apiVersion": TPU_SLICE_API_VERSION, "kind": TPU_SLICE_KIND,
            "metadata": {"name": "r-pending"},
            "spec": {"placement": {"shape": "4x4x4"}},  # never places
        })
        assert scale_down_victim(slices, nodes, ["r1", "r-pending"]) == "r-pending"

    def test_deterministic_tiebreak(self):
        slices, nodes = _line_pool({"a": 0, "b": 5})  # symmetric ends
        assert scale_down_victim(slices, nodes, ["a", "b"]) == scale_down_victim(
            list(reversed(slices)), nodes, ["b", "a"]
        )


# ---------------------------------------------------------------------------
# the TPUServing CRD
# ---------------------------------------------------------------------------


class TestServingCRD:
    def test_roundtrip_and_defaults(self):
        sv = TPUServing.from_unstructured(new_tpu_serving("s", {
            "model": {"shape": "2x2x1", "pool": "p1"},
            "replicas": {"min": 2, "max": 5, "targetRps": 40.0},
            "slo": {"ttftP99Seconds": 1.5},
        }))
        assert sv.spec.model.shape == "2x2x1"
        assert sv.spec.replicas.max == 5
        assert sv.spec.slo.ttft_p99_seconds == 1.5
        assert sv.spec.backoff.retry_limit == 5  # default
        assert sv.spec.replicas.cooldown_seconds == 30.0  # default
        out = sv.to_unstructured()
        assert out["spec"]["replicas"]["targetRps"] == 40.0

    def test_crd_registered_and_served_by_fake_apiserver(self):
        from tpu_operator.api.crds import all_crds, tpu_serving_crd

        crd = tpu_serving_crd()
        assert crd["metadata"]["name"] == "tpuservings.tpu.google.com"
        assert crd["spec"]["names"]["shortNames"] == ["tsv"]
        assert any(
            c["metadata"]["name"] == "tpuservings.tpu.google.com"
            for c in all_crds()
        )
        client = FakeClient()
        client.create(new_tpu_serving("s1", {"model": {"shape": "1x1x1"}}))
        got = client.get(TPU_SERVING_API_VERSION, TPU_SERVING_KIND, "s1")
        assert got["spec"]["model"]["shape"] == "1x1x1"


# ---------------------------------------------------------------------------
# the serving controller
# ---------------------------------------------------------------------------


class Harness:
    """FakeClient + torus + reconcilers + traffic sim in one beat-driven
    bundle (the bench/drill loop, test-sized)."""

    def __init__(self, spec=None, dims=(4, 2, 1), name="chat", traffic_seed=1):
        self.client = FakeClient()
        self.name = name
        for node in make_torus_nodes(dims, prefix=f"sv-{name}"):
            node["metadata"]["labels"][consts.TPU_PRESENT_LABEL] = "true"
            self.client.create(node)
        self.client.create(new_tpu_serving(name, spec or {
            "model": {"shape": "2x1x1"},
            "replicas": {"min": 1, "max": 3, "targetRps": 10.0,
                         "cooldownSeconds": 0.05},
            "slo": {"ttftP99Seconds": 3.0},
            "backoff": {"baseSeconds": 0.0, "maxSeconds": 0.0, "retryLimit": 5},
        }))
        self.rec = ServingReconciler(self.client, NS)
        self.place = PlacementReconciler(self.client, NS)
        self.sim = ServingTrafficSim(
            self.client, NS, name, DiurnalTraffic(seed=traffic_seed),
            replica_rps=10.0,
        )
        self.req = Request(name=name)

    def beat(self, n=1, rps=None):
        if rps is not None:
            self.sim.override_rps = rps
        for _ in range(n):
            self.rec.reconcile(self.req)
            self.place.reconcile(QUEUE_REQUEST)
            self.sim.step()

    def block(self):
        obj = self.client.get(TPU_SERVING_API_VERSION, TPU_SERVING_KIND, self.name)
        return (obj.get("status") or {}).get("serving") or {}

    def slices(self):
        return sorted(
            s["metadata"]["name"]
            for s in self.client.list(TPU_SLICE_API_VERSION, TPU_SLICE_KIND)
        )

    def routing(self):
        cm = self.client.get_or_none(
            "v1", "ConfigMap", self.name + consts.SERVING_LOAD_SUFFIX, NS
        )
        raw = ((cm or {}).get("data") or {}).get(consts.SERVING_ROUTING_KEY, "{}")
        return json.loads(raw)


class TestServingController:
    def test_min_replicas_placed_and_owned(self):
        h = Harness()
        h.beat(4, rps=3.0)
        block = h.block()
        assert block["phase"] == ServingPhase.SERVING
        assert block["desired"] == 1 and block["ready"] == 1
        assert h.slices() == [replica_name("chat", 0)]
        obj = h.client.get(TPU_SLICE_API_VERSION, TPU_SLICE_KIND,
                           replica_name("chat", 0))
        refs = obj["metadata"]["ownerReferences"]
        assert refs and refs[0]["kind"] == TPU_SERVING_KIND
        assert h.routing() == {replica_name("chat", 0): 1.0}

    def test_burst_scales_up_through_placement(self):
        h = Harness()
        h.beat(4, rps=3.0)
        h.beat(8, rps=28.0)
        block = h.block()
        assert block["desired"] == 3 and block["ready"] == 3
        assert len(h.slices()) == 3
        assert any(d["action"] == "scale-up" for d in block["decisions"])
        # all three placed by the engine: no double-booked hosts
        owners = {}
        for node in h.client.list("v1", "Node"):
            owner = (node["metadata"].get("labels") or {}).get(consts.PLACEMENT_LABEL)
            if owner:
                assert owners.setdefault(node["metadata"]["name"], owner) == owner

    def test_lull_scales_down_with_hysteresis_and_fragmentation_victim(self):
        h = Harness()
        h.beat(4, rps=3.0)
        h.beat(8, rps=28.0)
        assert h.block()["ready"] == 3
        # lull: the FIRST pass must NOT scale down (cooldown)
        h.beat(1, rps=3.0)
        assert h.block()["desired"] == 3
        deadline = time.monotonic() + 10.0
        while h.block()["desired"] != 1 and time.monotonic() < deadline:
            h.beat(1)
            time.sleep(0.02)
        block = h.block()
        assert block["desired"] == 1 and len(h.slices()) == 1
        victims = [d for d in block["decisions"] if d["action"] == "victim"]
        assert victims and "fragmentation delta" in victims[-1]["reason"]

    def test_scale_down_waits_out_cooldown(self):
        h = Harness(spec={
            "model": {"shape": "2x1x1"},
            "replicas": {"min": 1, "max": 3, "targetRps": 10.0,
                         "cooldownSeconds": 3600.0},
            "slo": {"ttftP99Seconds": 3.0},
        })
        h.beat(4, rps=3.0)
        h.beat(8, rps=28.0)
        assert h.block()["ready"] == 3
        h.beat(10, rps=3.0)
        assert h.block()["desired"] == 3  # an hour of lull required

    def test_burst_trailing_edge_does_not_flap(self):
        """Bursts scale up immediately but their trailing edge must not
        scale down: the lull clock (lowSince) resets whenever demand
        re-breaches inside the cooldown."""
        h = Harness()
        h.beat(4, rps=3.0)
        h.beat(6, rps=28.0)
        assert h.block()["desired"] == 3
        for _ in range(6):  # oscillating demand inside the cooldown
            h.beat(1, rps=3.0)
            h.beat(1, rps=28.0)
        assert h.block()["desired"] == 3
        assert not any(
            d["action"] == "scale-down" for d in h.block()["decisions"]
        )

    def test_fabric_degraded_replica_excluded_from_routing(self):
        h = Harness()
        h.beat(4, rps=3.0)
        h.beat(8, rps=28.0)
        replica = replica_name("chat", 0)
        obj = h.client.get(TPU_SLICE_API_VERSION, TPU_SLICE_KIND, replica)
        members = obj["status"]["placement"]["nodes"]
        artifact = {"members": members, "min_edge_gbps": 4.0,
                    "median_edge_gbps": 100.0}
        h.client.create(new_object("v1", "ConfigMap", f"{replica}-gang", NS))
        h.client.patch(
            "v1", "ConfigMap", f"{replica}-gang",
            {"metadata": {"annotations": {
                consts.GANG_FABRIC_ANNOTATION: json.dumps(artifact)}}},
            NS,
        )
        h.sim.routed = {}
        h.beat(5, rps=28.0)
        block = h.block()
        assert block["phase"] == ServingPhase.DEGRADED
        assert block["replicas"][replica] == "Excluded"
        assert h.routing()[replica] == 0.0
        assert h.sim.routed.get(replica, 0) == 0
        assert sum(h.sim.routed.values()) > 0  # traffic drained to peers
        assert any(
            e.get("reason") == "ServingReplicaExcluded"
            for e in h.client.list("v1", "Event", "default")
        )

    def test_stale_fabric_artifact_does_not_exclude(self):
        """A re-placed replica's old artifact (disjoint members) must
        not exclude the healthy new block — the fabric analyzer's
        staleness convention."""
        h = Harness()
        h.beat(4, rps=3.0)
        replica = replica_name("chat", 0)
        artifact = {"members": ["not-a-member-0", "not-a-member-1"],
                    "min_edge_gbps": 4.0, "median_edge_gbps": 100.0}
        h.client.create(new_object("v1", "ConfigMap", f"{replica}-gang", NS))
        h.client.patch(
            "v1", "ConfigMap", f"{replica}-gang",
            {"metadata": {"annotations": {
                consts.GANG_FABRIC_ANNOTATION: json.dumps(artifact)}}},
            NS,
        )
        h.beat(2, rps=3.0)
        assert h.routing()[replica] == 1.0

    def test_broken_replica_unroutable_and_replaced(self):
        """A replica's host dying drains its weight to zero; the
        placement engine re-places the slice and routing recovers."""
        h = Harness()
        h.beat(4, rps=3.0)
        replica = replica_name("chat", 0)
        obj = h.client.get(TPU_SLICE_API_VERSION, TPU_SLICE_KIND, replica)
        victim_node = obj["status"]["placement"]["nodes"][0]
        h.client.patch("v1", "Node", victim_node, {"metadata": {"labels": {
            consts.TPU_HEALTH_LABEL: consts.HEALTH_DEGRADED}}})
        h.rec.reconcile(h.req)
        assert h.routing()[replica] == 0.0  # drained before re-place
        h.beat(4, rps=3.0)
        obj = h.client.get(TPU_SLICE_API_VERSION, TPU_SLICE_KIND, replica)
        new_nodes = obj["status"]["placement"]["nodes"]
        assert victim_node not in new_nodes
        assert h.routing()[replica] == 1.0
        assert h.block()["phase"] == ServingPhase.SERVING

    def test_gang_step_time_breach_scales_up(self):
        h = Harness(spec={
            "model": {"shape": "2x1x1"},
            "replicas": {"min": 1, "max": 2, "targetRps": 1000.0,
                         "cooldownSeconds": 0.05},
            "slo": {"ttftP99Seconds": 30.0, "stepSeconds": 0.02},
        })
        h.beat(4, rps=3.0)
        assert h.block()["desired"] == 1
        replica = replica_name("chat", 0)
        artifact = {"gang_step_p50_s": 0.5, "straggler_ratio": 1.0}
        h.client.create(new_object("v1", "ConfigMap", f"{replica}-gang", NS))
        h.client.patch(
            "v1", "ConfigMap", f"{replica}-gang",
            {"metadata": {"annotations": {
                consts.GANG_TELEMETRY_ANNOTATION: json.dumps(artifact)}}},
            NS,
        )
        h.beat(3, rps=3.0)
        assert h.block()["desired"] == 2

    # -- retry budget --------------------------------------------------------

    def _unplaceable(self, retry_limit=3, base=60.0):
        return Harness(spec={
            "model": {"shape": "8x8x8"},  # never places on 4x2x1
            "replicas": {"min": 1, "max": 1, "targetRps": 10.0},
            "slo": {"ttftP99Seconds": 3.0},
            "backoff": {"baseSeconds": base, "maxSeconds": base,
                        "retryLimit": retry_limit},
        })

    def test_watch_storm_cannot_outrun_backoff_gate(self):
        """The PR 13 pin, serving edition: reconcile storms must not
        burn the placement retry budget faster than the backoff
        schedule — attempts before the persisted nextAttemptAt are
        free."""
        h = self._unplaceable(retry_limit=3, base=60.0)
        for _ in range(10):  # an event storm
            h.rec.reconcile(h.req)
            h.place.reconcile(QUEUE_REQUEST)
        block = h.block()
        assert block["restarts"] == 1  # one charge, nine gated passes
        assert block["nextAttemptAt"] > time.time()
        assert block["phase"] != ServingPhase.FAILED

    def test_budget_exhaustion_quarantines_with_event_and_sweep(self):
        h = self._unplaceable(retry_limit=2, base=0.0)
        for _ in range(8):
            h.rec.reconcile(h.req)
            h.place.reconcile(QUEUE_REQUEST)
        block = h.block()
        assert block["phase"] == ServingPhase.FAILED
        assert "retry budget exhausted" in block["message"]
        assert h.slices() == []  # quarantine frees the queue slot
        assert any(
            e.get("reason") == "ServingFailed"
            for e in h.client.list("v1", "Event", "default")
        )
        # terminal: no further reconcile churn
        h.rec.reconcile(h.req)
        assert h.block() == block

    def test_scale_up_shortfall_above_min_never_quarantines(self):
        """Review pin: a burst wanting more replicas than the torus fits
        must NOT burn the retry budget while the service is at or above
        its min floor — exhaustion there would delete healthy,
        traffic-serving replicas to punish a full cluster. The fleet
        stays Scaling with the shortfall noted; the budget only charges
        when ready drops below min."""
        h = Harness(spec={
            "model": {"shape": "2x1x1"},
            "replicas": {"min": 1, "max": 3, "targetRps": 10.0,
                         "cooldownSeconds": 3600.0},
            "slo": {"ttftP99Seconds": 3.0},
            "backoff": {"baseSeconds": 0.0, "maxSeconds": 0.0, "retryLimit": 1},
        }, dims=(2, 2, 1))  # room for exactly TWO 2x1x1 replicas
        h.beat(4, rps=3.0)
        assert h.block()["ready"] == 1
        h.beat(20, rps=28.0)  # wants 3; the pool fits 2
        block = h.block()
        assert block["desired"] == 3
        assert block["ready"] == 2
        assert block["phase"] == ServingPhase.SCALING
        assert block["restarts"] == 0  # nothing charged against the budget
        assert "capacity short" in block["message"]
        assert len([s for s in h.slices() if "replica" in s]) == 3

    def test_budget_resets_when_fleet_becomes_ready(self):
        h = Harness(spec={
            "model": {"shape": "2x1x1"},
            "replicas": {"min": 2, "max": 2, "targetRps": 10.0},
            "slo": {"ttftP99Seconds": 3.0},
            "backoff": {"baseSeconds": 0.0, "maxSeconds": 0.0, "retryLimit": 50},
        }, dims=(2, 1, 1))
        # only one 2x1x1 block fits a 2-host pool: replica 1 starves
        for _ in range(4):
            h.rec.reconcile(h.req)
            h.place.reconcile(QUEUE_REQUEST)
        assert h.block()["restarts"] >= 1
        # capacity heals: 2 more hosts join, the second replica places
        for node in make_torus_nodes((2, 1, 1), prefix="heal", nodepool="pool-b"):
            node["metadata"]["labels"][consts.TPU_PRESENT_LABEL] = "true"
            h.client.create(node)
        for _ in range(6):
            h.rec.reconcile(h.req)
            h.place.reconcile(QUEUE_REQUEST)
        block = h.block()
        assert block["ready"] == 2
        assert block["restarts"] == 0
        assert "nextAttemptAt" not in block

    # -- spec validation / lifecycle -----------------------------------------

    def test_invalid_spec_fails_terminally(self):
        h = Harness(spec={
            "model": {"shape": "not-a-shape"},
            "replicas": {"min": 1, "max": 1},
        })
        h.rec.reconcile(h.req)
        block = h.block()
        assert block["phase"] == ServingPhase.FAILED
        assert "invalid serving spec" in block["message"]

    def test_restart_safety_rederives_from_status(self):
        """A fresh reconciler (operator restart) must re-derive the same
        desired count from status instead of snapping back to min."""
        h = Harness()
        h.beat(4, rps=3.0)
        h.beat(8, rps=28.0)
        assert h.block()["desired"] == 3
        fresh = ServingReconciler(h.client, NS)
        fresh.reconcile(h.req)
        assert h.block()["desired"] == 3
        assert len(h.slices()) == 3

    def test_deletion_sweeps_only_owned_slices(self):
        h = Harness()
        h.beat(4, rps=3.0)
        # a user's standalone slice that merely looks like a replica
        h.client.create({
            "apiVersion": TPU_SLICE_API_VERSION, "kind": TPU_SLICE_KIND,
            "metadata": {"name": "chat-replica-99"},
            "spec": {"placement": {"shape": "1x1x1"}},
        })
        h.client.delete(TPU_SERVING_API_VERSION, TPU_SERVING_KIND, "chat")
        h.rec.reconcile(h.req)
        assert h.slices() == ["chat-replica-99"]

    def test_metrics_exported_and_retired_on_deletion(self):
        import prometheus_client

        h = Harness(name="metrics-sv")
        h.beat(4, rps=3.0)
        scrape = prometheus_client.generate_latest(
            prometheus_client.REGISTRY
        ).decode()
        assert 'tpu_operator_serving_replicas{serving="metrics-sv"} 1.0' in scrape
        assert 'tpu_operator_serving_queue_depth{serving="metrics-sv"}' in scrape
        h.client.delete(TPU_SERVING_API_VERSION, TPU_SERVING_KIND, "metrics-sv")
        h.rec.reconcile(h.req)
        scrape = prometheus_client.generate_latest(
            prometheus_client.REGISTRY
        ).decode()
        assert 'serving="metrics-sv"' not in scrape

    def test_scale_to_zero_window(self):
        h = Harness(spec={
            "model": {"shape": "2x1x1"},
            "replicas": {"min": 0, "max": 2, "targetRps": 10.0,
                         "cooldownSeconds": 0.01},
            "slo": {"ttftP99Seconds": 3.0},
        })
        h.rec.reconcile(h.req)
        block = h.block()
        assert block["desired"] == 0
        assert block["phase"] == ServingPhase.SERVING
        assert h.slices() == []


class TestFailClosedOwnedReads:
    """TPUOP-K003 regressions (PR 17): ``_owned_replicas`` gates replica
    deletion and the deleted-serving sweep. It used to swallow a
    transient list ``ApiError`` into ``[]`` — an impersonated "no
    replicas" — so a single flaky LIST during the deleted-CR sweep
    reported the sweep complete and leaked every replica forever (the
    serving was gone; nothing would ever retrigger it). The read now
    fails closed: ``None`` aborts the pass and the caller requeues."""

    @staticmethod
    def _flake_slice_lists(client):
        """Shadow the bound ``list`` with one that 500s TPUSlice LISTs;
        ``del client.list`` restores the real method."""
        real = FakeClient.list

        def flaky(api_version, kind, *a, **kw):
            if kind == TPU_SLICE_KIND:
                raise errors.ApiError("transient 500")
            return real(client, api_version, kind, *a, **kw)

        client.list = flaky

    def test_deleted_serving_sweep_requeues_on_list_failure(self):
        h = Harness(name="sweep-sv")
        h.beat(4, rps=3.0)
        assert h.slices() == [replica_name("sweep-sv", 0)]
        h.client.delete(TPU_SERVING_API_VERSION, TPU_SERVING_KIND, "sweep-sv")

        self._flake_slice_lists(h.client)
        res = h.rec.reconcile(h.req)
        # the flaky read must NOT read as "nothing left to sweep"
        assert res.requeue

        # the flake heals: the requeued pass completes the sweep
        del h.client.list
        res = h.rec.reconcile(h.req)
        assert not res.requeue
        assert h.slices() == []

    def test_live_pass_aborts_scale_decisions_on_list_failure(self):
        h = Harness(name="abort-sv")
        h.beat(4, rps=3.0)
        before = h.slices()
        assert before == [replica_name("abort-sv", 0)]

        self._flake_slice_lists(h.client)
        res = h.rec.reconcile(h.req)
        assert res.requeue

        # no scale decision ran against the impersonated empty world
        del h.client.list
        assert h.slices() == before
