"""nodeinfo / clusterinfo / nodepool tests (reference analogs:
internal/nodeinfo tests, internal/state/nodepool.go cases)."""

from tpu_operator import consts
from tpu_operator.clusterinfo import LiveClusterInfo, detect
from tpu_operator.kube.fake import FakeClient
from tpu_operator.kube.objects import new_object
from tpu_operator.kube.sim import make_tpu_node
from tpu_operator.nodeinfo import parse_topology, tfd_labels, tpu_info
from tpu_operator.nodepool import get_node_pools


def test_parse_topology():
    assert parse_topology("4x4") == [4, 4]
    assert parse_topology("2x2x2") == [2, 2, 2]
    assert parse_topology("") == []
    assert parse_topology("weird") == []


def test_tpu_info_v5e_multihost():
    node = make_tpu_node("n0", "tpu-v5-lite-podslice", "4x4")
    info = tpu_info(node)
    assert info.generation == "v5e"
    assert info.chips_in_slice == 16
    assert info.chips_per_node == 4
    assert info.slice_hosts == 4
    assert info.multi_host


def test_tpu_info_v4_single_host():
    node = make_tpu_node("n0", "tpu-v4-podslice", "2x2x1")
    info = tpu_info(node)
    assert info.generation == "v4"
    assert info.chips_in_slice == 4
    assert info.slice_hosts == 1
    assert not info.multi_host


def test_non_tpu_node():
    assert tpu_info(new_object("v1", "Node", "cpu-node")) is None


def test_tfd_labels():
    info = tpu_info(make_tpu_node("n0", "tpu-v5p-slice", "2x2x2"))
    labels = tfd_labels(info)
    assert labels[consts.TFD_TPU_GENERATION_LABEL] == "v5p"
    assert labels[consts.TFD_CHIPS_PER_NODE_LABEL] == "4"
    assert labels[consts.TFD_SLICE_HOSTS_LABEL] == "2"
    assert labels[consts.TFD_TOPOLOGY_LABEL] == "2x2x2"


def test_clusterinfo_detect():
    client = FakeClient()
    client.create(make_tpu_node("tpu-0"))
    client.create(new_object("v1", "Node", "cpu-0"))
    info = detect(client)
    assert info.container_runtime == "containerd"
    assert info.is_gke
    assert info.tpu_node_count == 1
    assert info.kubernetes_version.startswith("v1.29")


def test_clusterinfo_kubelet_versions():
    client = FakeClient()
    client.create(make_tpu_node("tpu-0"))
    client.create(make_tpu_node("tpu-1"))
    info = detect(client)
    assert sum(info.kubelet_versions.values()) == 2


class CountingClient(FakeClient):
    def __init__(self):
        super().__init__()
        self.node_lists = 0

    def list(self, api_version, kind, namespace=None, label_selector=None, field_selector=None):
        if kind == "Node":
            self.node_lists += 1
        return super().list(api_version, kind, namespace, label_selector, field_selector)


class FakeInformer:
    def __init__(self):
        self.handlers = []

    def add_handler(self, h):
        self.handlers.append(h)

    def fire(self):
        for h in self.handlers:
            h("MODIFIED", {})


class TestLiveClusterInfo:
    def test_unattached_stays_oneshot(self):
        client = CountingClient()
        client.create(make_tpu_node("tpu-0"))
        live = LiveClusterInfo(client)
        live.get()
        live.get()
        assert client.node_lists == 2  # no events feeding invalidate -> no caching

    def test_attached_caches_until_node_event(self):
        client = CountingClient()
        client.create(make_tpu_node("tpu-0"))
        live = LiveClusterInfo(client)
        informer = FakeInformer()
        live.attach(informer)
        first = live.get()
        assert live.get() is first  # zero node re-parsing while clean
        assert client.node_lists == 1
        client.create(make_tpu_node("tpu-1"))
        informer.fire()
        assert live.get().tpu_node_count == 2
        assert client.node_lists == 2

    def test_runtime_default_change_busts_cache(self):
        client = CountingClient()
        client.create(new_object("v1", "Node", "bare"))  # no runtime reported
        live = LiveClusterInfo(client)
        live.attach(FakeInformer())
        assert live.get(default_runtime="containerd").container_runtime == "containerd"
        assert live.get(default_runtime="docker").container_runtime == "docker"

    def test_invalidation_during_recompute_keeps_cache_dirty(self):
        client = CountingClient()
        client.create(make_tpu_node("tpu-0"))
        live = LiveClusterInfo(client)
        live.attach(FakeInformer())
        real_detect = detect

        def racing_detect(*a, **kw):
            live.invalidate()  # event lands mid-recompute
            return real_detect(*a, **kw)

        import tpu_operator.clusterinfo as ci

        orig = ci.detect
        ci.detect = racing_detect
        try:
            live.get()
        finally:
            ci.detect = orig
        live.get()
        assert client.node_lists == 2  # second get recomputed (cache stayed dirty)


def test_node_pools_partition_by_type_topology_pool():
    nodes = [
        make_tpu_node("a0", "tpu-v5-lite-podslice", "4x4", nodepool="pool-a"),
        make_tpu_node("a1", "tpu-v5-lite-podslice", "4x4", nodepool="pool-a"),
        make_tpu_node("b0", "tpu-v5p-slice", "2x2x2", nodepool="pool-b"),
        new_object("v1", "Node", "cpu-0"),
    ]
    pools = get_node_pools(nodes)
    assert len(pools) == 2
    a, b = pools
    assert a.node_names == ["a0", "a1"]
    assert a.selector[consts.GKE_TPU_ACCELERATOR_LABEL] == "tpu-v5-lite-podslice"
    assert a.selector[consts.GKE_TPU_TOPOLOGY_LABEL] == "4x4"
    assert b.info.generation == "v5p"
