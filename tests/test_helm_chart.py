"""Helm chart verification (reference: deployments/gpu-operator).

No helm binary ships in this environment, so the chart is proven correct
by rendering it with the helmlite engine (the text/template subset the
chart uses, Go semantics) and asserting object-for-object parity with
``chart.render_chart()`` — the operator's own values->manifests path —
across representative values configurations.
"""

import base64
import copy
import os

import pytest
import yaml

from tpu_operator import helmlite
from tpu_operator.api.crds import all_crds
from tpu_operator.chart import render_chart

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HELM_CHART = os.path.join(ROOT, "deploy", "helm", "tpu-operator")
DEFAULT_VALUES_FILE = os.path.join(ROOT, "deploy", "values.yaml")


def load_default_values() -> dict:
    with open(DEFAULT_VALUES_FILE) as f:
        return yaml.safe_load(f)


def helm_render(values: dict):
    """Render the Helm chart the way `helm template -n <ns>` would, with
    createNamespace on so the object set matches render_chart exactly."""
    vals = copy.deepcopy(values)
    ns = vals.pop("namespace", "tpu-operator")
    vals["createNamespace"] = True
    return helmlite.template(HELM_CHART, vals, namespace=ns)


def by_key(objs):
    keyed = {(o["kind"], o["metadata"]["name"]): o for o in objs}
    assert len(keyed) == len(objs), "duplicate kind/name in render"
    return keyed


def assert_parity(values: dict):
    want = by_key(render_chart(values))
    got = by_key(helm_render(values))
    assert set(got) == set(want), (
        f"object sets differ:\n helm-only: {set(got) - set(want)}\n"
        f" render_chart-only: {set(want) - set(got)}"
    )
    for key in want:
        assert got[key] == want[key], f"{key} differs:\nhelm: {got[key]}\nrender_chart: {want[key]}"


class TestHelmParity:
    def test_default_values(self):
        assert_parity(load_default_values())

    def test_webhook_enabled_with_certs(self):
        values = load_default_values()
        values["webhook"] = {
            "enabled": True,
            "failurePolicy": "Ignore",
            "caBundle": base64.b64encode(b"ca").decode(),
            "tlsCrt": base64.b64encode(b"crt").decode(),
            "tlsKey": base64.b64encode(b"key").decode(),
        }
        assert_parity(values)

    def test_webhook_managed_certs_mode(self):
        """webhook enabled without user cert material: the Deployment runs
        --webhook-manage-certs with a writable emptyDir instead of the
        read-only Secret mount."""
        values = load_default_values()
        values["webhook"] = {"enabled": True, "failurePolicy": "Fail", "caBundle": ""}
        assert_parity(values)
        dep = [o for o in helm_render(values) if o["kind"] == "Deployment"][0]
        spec = dep["spec"]["template"]["spec"]
        args = spec["containers"][0]["args"]
        assert "--webhook-manage-certs" in args
        assert spec["volumes"][0] == {"name": "webhook-certs", "emptyDir": {}}

    def test_psa_and_no_resources_and_digest_image(self):
        values = load_default_values()
        values["clusterPolicy"]["psa"] = {"enabled": True}
        values["operator"]["resources"] = None
        values["operator"]["leaderElect"] = False
        values["operator"]["version"] = "sha256:" + "a" * 64
        values["namespace"] = "custom-ns"
        assert_parity(values)

    def test_multislice_enabled(self):
        values = load_default_values()
        values["clusterPolicy"]["multiSlice"] = {"enabled": True, "coordinatorPort": 9000}
        assert_parity(values)

    def test_extra_labels(self):
        """operator.extraLabels land on the Deployment through both
        render paths, and can never clobber the chart's own app labels
        (helm: merge gives the chart's dict precedence; jinja: the base
        labels win YAML duplicate-key resolution)."""
        values = load_default_values()
        values["operator"]["extraLabels"] = {
            "team": "ml-infra",
            "app": "evil-override",
            # scalar-looking strings must stay strings through BOTH
            # renderers (raw jinja interpolation once yielded bool true)
            "stage": "true",
        }
        assert_parity(values)
        deploy = [o for o in render_chart(values) if o["kind"] == "Deployment"][0]
        labels = deploy["metadata"]["labels"]
        assert labels["team"] == "ml-infra"
        assert labels["app"] == "tpu-operator"
        assert labels["stage"] == "true"

    def test_partial_values_merge_like_helm(self):
        """A partial overrides file must produce the same install through
        both paths: helm deep-merges over chart defaults, and render_chart
        now does the same over deploy/values.yaml."""
        partial = {"clusterPolicy": {"multiSlice": {"enabled": True}}}
        assert_parity(partial)
        # the merged spec keeps the defaulted operands, not just the override
        cp = [o for o in render_chart(partial) if o["kind"] == "ClusterPolicy"][0]
        assert cp["spec"]["libtpu"] == {"enabled": True}
        assert cp["spec"]["multiSlice"] == {"enabled": True}

    def test_health_monitor_knobs_flow_through_both_paths(self):
        """The nested healthMonitor knobs: a partial override of one knob
        keeps the chart defaults on the rest (deep merge) and renders
        identically through helm and tpuop-cfg render."""
        partial = {"clusterPolicy": {"healthMonitor": {"interval": 60}}}
        assert_parity(partial)
        cp = [o for o in render_chart(partial) if o["kind"] == "ClusterPolicy"][0]
        hm = cp["spec"]["healthMonitor"]
        assert hm["interval"] == 60
        assert hm["remediation"] == {"enable": True, "retryLimit": 3, "timeoutSeconds": 300,
                                     "gracePeriodSeconds": 300}
        # full disable flows too
        off = {"clusterPolicy": {"healthMonitor": {"enabled": False,
                                                   "remediation": {"enable": False}}}}
        assert_parity(off)


class TestChartContents:
    def test_crds_dir_matches_api(self):
        """crds/ ships the same CRDs api.crds generates (regenerate with
        scripts/update_chart_crds.py)."""
        on_disk = {}
        crd_dir = os.path.join(HELM_CHART, "crds")
        for name in sorted(os.listdir(crd_dir)):
            with open(os.path.join(crd_dir, name)) as f:
                crd = yaml.safe_load(f)
            on_disk[crd["metadata"]["name"]] = crd
        generated = {c["metadata"]["name"]: c for c in all_crds()}
        assert on_disk == generated, "chart crds/ drifted (scripts/update_chart_crds.py)"

    def test_image_pull_secrets_parity(self):
        """imagePullSecrets renders through helm's range/with path and the
        jinja render path identically (the range/include ceiling lift)."""
        values = load_default_values()
        values["operator"] = dict(
            values.get("operator") or {}, imagePullSecrets=[{"name": "regcred"}, {"name": "gcr"}]
        )
        assert_parity(values)
        dep = [o for o in helm_render(values) if o["kind"] == "Deployment"][0]
        secrets = dep["spec"]["template"]["spec"]["imagePullSecrets"]
        assert secrets == [{"name": "regcred"}, {"name": "gcr"}]
        labels = dep["metadata"]["labels"]
        assert labels["app.kubernetes.io/instance"] == "tpu-operator"

    def test_default_values_satisfy_schema(self):
        """helm validates values against values.schema.json at install;
        the chart's own defaults (and the render path's) must pass."""
        import jsonschema

        with open(os.path.join(HELM_CHART, "values.schema.json")) as f:
            schema = yaml.safe_load(f)
        with open(os.path.join(HELM_CHART, "values.yaml")) as f:
            jsonschema.validate(yaml.safe_load(f), schema)
        render_vals = load_default_values()
        render_vals.pop("namespace")
        jsonschema.validate(render_vals, schema)
        with pytest.raises(jsonschema.ValidationError):
            jsonschema.validate({"operator": {"imagePullPolicy": "Sometimes"}}, schema)

    def test_chart_yaml(self):
        with open(os.path.join(HELM_CHART, "Chart.yaml")) as f:
            meta = yaml.safe_load(f)
        assert meta["apiVersion"] == "v2"
        assert meta["name"] == "tpu-operator"
        assert meta["version"]

    def test_values_schema_matches_render_path(self):
        """The chart's default values must express the same install the
        tpuop-cfg render path ships (minus the namespace key, which helm
        takes from the release)."""
        with open(os.path.join(HELM_CHART, "values.yaml")) as f:
            helm_vals = yaml.safe_load(f)
        render_vals = load_default_values()
        render_vals.pop("namespace")
        helm_vals.pop("createNamespace")
        # webhook serving material defaults empty in both
        for k in ("tlsCrt", "tlsKey"):
            helm_vals["webhook"].pop(k, None)
        assert helm_vals == render_vals


class TestHelmliteEngine:
    def test_unsupported_construct_raises(self):
        with pytest.raises(helmlite.HelmliteError, match="unknown function"):
            helmlite.render_string("{{ urlquery .Values.x }}", {"Values": {}})

    def test_block_renders_default_body(self):
        out = helmlite.render_string(
            '{{ block "greet" .Values }}hi {{ .who }}{{ end }}',
            {"Values": {"who": "tpu"}},
        )
        assert out == "hi tpu"

    def test_block_overridden_by_define(self):
        """Go/helm semantics: block's body is only the DEFAULT — a
        template defined under the same name wins, regardless of where
        it appears."""
        out = helmlite.render_string(
            '{{ define "greet" }}hello {{ .who }}{{ end }}'
            '{{ block "greet" .Values }}hi {{ .who }}{{ end }}',
            {"Values": {"who": "tpu"}},
        )
        assert out == "hello tpu"

    def test_parenthesized_pipelines(self):
        ctx = {"Values": {"a": "x", "b": "", "n": 3}}
        cases = [
            ('{{ if and (eq .Values.a "x") (not .Values.b) }}y{{ else }}n{{ end }}', "y"),
            ('{{ ternary "@" ":" (hasPrefix "sha256:" "sha256:abc") }}', "@"),
            # nested parens + a pipe INSIDE the parens must not split outside
            ('{{ or (and (.Values.b | not) "inner") "outer" }}', "inner"),
            ('{{ (printf "%s-%d" .Values.a .Values.n) | upper }}', "X-3"),
        ]
        for template, want in cases:
            assert helmlite.render_string(template, ctx) == want, template

    def test_unbalanced_parens_raise(self):
        for template in ("{{ and (eq .x 1 }}", "{{ and eq .x 1) }}"):
            with pytest.raises(helmlite.HelmliteError, match="parenthes"):
                helmlite.render_string(template, {"Values": {}})

    def test_default_and_coalesce(self):
        """sprig default/coalesce (TODO gap 4): the guards the chart uses
        for nested health knobs a partial values file may omit."""
        ctx = {"Values": {"clusterPolicy": {"healthMonitor": {"interval": 60}}}}
        cases = [
            # coalesce: first non-empty argument wins
            ("{{ coalesce .Values.clusterPolicy.healthMonitor.interval 30 }}", "60"),
            ("{{ coalesce .Values.clusterPolicy.healthMonitor.retryLimit 3 }}", "3"),
            ("{{ coalesce .Values.nope .Values.alsoNope }}", ""),  # all empty -> nil
            ('{{ coalesce "" 0 "x" "y" }}', "x"),
            # default: piped form, empty/zero falls back
            ('{{ .Values.clusterPolicy.healthMonitor.interval | default 30 }}', "60"),
            ('{{ .Values.clusterPolicy.healthMonitor.missing | default 30 }}', "30"),
            ('{{ toYaml (default (dict) .Values.noSpec) }}', "{}"),
        ]
        for template, want in cases:
            assert helmlite.render_string(template, ctx) == want, template

    def test_dict_merge_haskey(self):
        ctx = {"Values": {"m": {"a": 1}, "extra": {"b": 2, "nested": {"x": 1}}}}
        cases = [
            ('{{ if hasKey .Values.m "a" }}y{{ end }}', "y"),
            ('{{ if hasKey .Values.m "z" }}y{{ else }}n{{ end }}', "n"),
            ('{{ toYaml (dict "k" "v") }}', "k: v"),
            # merge: leftmost (dst) precedence, deep
            (
                '{{ toYaml (merge (dict "b" 9) .Values.extra (dict "nested" (dict "y" 2))) }}',
                "b: 9\nnested:\n  x: 1\n  y: 2",
            ),
        ]
        for template, want in cases:
            assert helmlite.render_string(template, ctx) == want, template

    def test_range_list_with_vars(self):
        t = "{{ range $i, $v := .Values.items }}{{ $i }}={{ $v }};{{ end }}"
        assert helmlite.render_string(t, {"Values": {"items": ["a", "b"]}}) == "0=a;1=b;"

    def test_range_rebinds_dot_and_else(self):
        t = "{{ range .Values.items }}[{{ .name }}]{{ else }}none{{ end }}"
        ctx = {"Values": {"items": [{"name": "x"}, {"name": "y"}]}}
        assert helmlite.render_string(t, ctx) == "[x][y]"
        assert helmlite.render_string(t, {"Values": {}}) == "none"

    def test_range_map_sorted(self):
        t = "{{ range $k, $v := .Values.m }}{{ $k }}:{{ $v }},{{ end }}"
        assert (
            helmlite.render_string(t, {"Values": {"m": {"b": 2, "a": 1}}}) == "a:1,b:2,"
        )

    def test_with_rebinds_dot_root_stays(self):
        t = "{{ with .Values.sub }}{{ .x }}/{{ $.Values.top }}{{ end }}"
        ctx = {"Values": {"sub": {"x": 1}, "top": 2}}
        assert helmlite.render_string(t, ctx) == "1/2"
        assert helmlite.render_string("{{ with .Values.nope }}y{{ else }}n{{ end }}", {"Values": {}}) == "n"

    def test_variable_assignment(self):
        t = '{{ $name := .Values.n }}{{ $name }}-{{ $name }}'
        assert helmlite.render_string(t, {"Values": {"n": "ab"}}) == "ab-ab"

    def test_assignment_propagates_out_of_range(self):
        """Go semantics: = assigns the enclosing declaration (the standard
        helm found-flag idiom); := inside a block stays block-local."""
        t = (
            "{{ $found := false }}{{ range .Values.items }}{{ $found = true }}"
            "{{ end }}{{ if $found }}yes{{ else }}no{{ end }}"
        )
        assert helmlite.render_string(t, {"Values": {"items": [1]}}) == "yes"
        assert helmlite.render_string(t, {"Values": {"items": []}}) == "no"
        shadow = "{{ $x := 1 }}{{ if true }}{{ $x := 2 }}{{ end }}{{ $x }}"
        assert helmlite.render_string(shadow, {}) == "1"

    def test_block_scoped_variables_do_not_leak(self):
        with pytest.raises(helmlite.HelmliteError, match="undefined"):
            helmlite.render_string("{{ if true }}{{ $x := 1 }}{{ end }}{{ $x }}", {})
        with pytest.raises(helmlite.HelmliteError, match="undeclared"):
            helmlite.render_string("{{ $x = 1 }}", {})
        # else bodies are blocks too (range/with)
        with pytest.raises(helmlite.HelmliteError, match="undefined"):
            helmlite.render_string(
                "{{ range .Values.items }}x{{ else }}{{ $v := 1 }}{{ end }}{{ $v }}",
                {"Values": {}},
            )

    def test_pipe_inside_string_literal(self):
        assert (
            helmlite.render_string('{{ eq .Values.sep "|" }}', {"Values": {"sep": "|"}})
            == "true"
        )
        assert (
            helmlite.render_string('{{ replace "|" "," .Values.s }}', {"Values": {"s": "a|b"}})
            == "a,b"
        )
        with pytest.raises(helmlite.HelmliteError, match="unterminated"):
            helmlite.render_string('{{ eq .Values.x "| }}', {"Values": {}})

    def test_define_include_nindent(self):
        defines = {}
        helmlite.load_defines(
            '{{- define "t.labels" -}}\napp: {{ .app }}\ntier: web\n{{- end }}', defines
        )
        out = helmlite.render_string(
            'meta:\n  labels:{{ include "t.labels" .Values | nindent 4 }}',
            {"Values": {"app": "z"}},
            defines,
        )
        assert yaml.safe_load(out) == {"meta": {"labels": {"app": "z", "tier": "web"}}}

    def test_template_action(self):
        defines = {}
        helmlite.load_defines('{{ define "t.x" }}<{{ . }}>{{ end }}', defines)
        assert (
            helmlite.render_string('{{ template "t.x" .Values.v }}', {"Values": {"v": 7}}, defines)
            == "<7>"
        )

    def test_helper_files_must_not_emit_text(self):
        with pytest.raises(helmlite.HelmliteError, match="only define"):
            helmlite.load_defines('{{ define "t" }}x{{ end }}\nstray', {})

    def test_include_unknown_template_raises(self):
        with pytest.raises(helmlite.HelmliteError, match="no template"):
            helmlite.render_string('{{ include "missing" . }}', {})

    def test_sprig_string_functions(self):
        ctx = {"Values": {"name": "TPU-Op", "tag": "v1.2.3-rc"}}
        cases = [
            ('{{ printf "%s:%d" .Values.name 8080 }}', "TPU-Op:8080"),
            # Go fmt width/precision specs and %f (default 6 decimals)
            ('{{ printf "%.1f" 1.25 }}', "1.2"),
            ('{{ printf "%f" 1.5 }}', "1.500000"),
            ('{{ printf "%5d|%-4s|" 42 "ab" }}', "   42|ab  |"),
            ('{{ printf "100%%" }}', "100%"),
            ("{{ .Values.name | lower }}", "tpu-op"),
            ("{{ .Values.name | upper }}", "TPU-OP"),
            ('{{ .Values.tag | trimPrefix "v" }}', "1.2.3-rc"),
            ('{{ .Values.tag | trimSuffix "-rc" }}', "v1.2.3"),
            ('{{ .Values.name | trunc 3 }}', "TPU"),
            ('{{ .Values.name | replace "-" "_" }}', "TPU_Op"),
            ('{{ if contains "rc" .Values.tag }}pre{{ else }}ga{{ end }}', "pre"),
            ('{{ "a" | ternary "yes" "no" }}', "yes"),
            ("{{ .Values.name | len }}", "6"),
        ]
        for template, want in cases:
            assert helmlite.render_string(template, ctx) == want, template

    def test_required_raises_on_missing(self):
        assert (
            helmlite.render_string('{{ required "need it" .Values.x }}', {"Values": {"x": 1}})
            == "1"
        )
        with pytest.raises(helmlite.HelmliteError, match="need it"):
            helmlite.render_string('{{ required "need it" .Values.x }}', {"Values": {}})

    def test_printf_errors(self):
        with pytest.raises(helmlite.HelmliteError, match="not enough args"):
            helmlite.render_string('{{ printf "%s-%s" "a" }}', {})
        with pytest.raises(helmlite.HelmliteError, match="unsupported verb"):
            helmlite.render_string('{{ printf "%x" 5 }}', {})
        with pytest.raises(helmlite.HelmliteError, match="wants an integer"):
            helmlite.render_string('{{ printf "%d" "v1.2" }}', {})
        # malformed specs fail the engine's error contract, not ValueError
        with pytest.raises(helmlite.HelmliteError, match="malformed spec"):
            helmlite.render_string('{{ printf "%5-d" 3 }}', {})
        with pytest.raises(helmlite.HelmliteError, match="malformed spec"):
            helmlite.render_string('{{ printf "%1.2.3f" 1.0 }}', {})

    def test_len_of_nil_raises_and_missing_key_is_empty_string(self):
        # Go errors on len of untyped nil; answering 0 would silently
        # diverge from real helm
        with pytest.raises(helmlite.HelmliteError, match="len of"):
            helmlite.render_string("{{ .Values.nope | len }}", {"Values": {}})
        # a missing key must stringify as "", never "None"
        assert (
            helmlite.render_string('{{ if hasSuffix "e" .Values.nope }}y{{ else }}n{{ end }}', {"Values": {}})
            == "n"
        )

    def test_trim_markers(self):
        out = helmlite.render_string("a\n{{- if true }}\nb\n{{- end }}\n", {})
        assert out == "a\nb\n"

    def test_pipeline_and_indent(self):
        ctx = {"Values": {"r": {"b": {"c": 1}, "a": 2}}}
        out = helmlite.render_string("x:\n{{ toYaml .Values.r | indent 2 }}", ctx)
        assert yaml.safe_load(out) == {"x": {"a": 2, "b": {"c": 1}}}

    def test_missing_path_is_empty_and_falsey(self):
        assert helmlite.render_string("[{{ .Values.nope.deep }}]", {"Values": {}}) == "[]"
        assert helmlite.render_string("{{ if .Values.nope }}y{{ else }}n{{ end }}", {"Values": {}}) == "n"

    def test_else_if(self):
        t = '{{ if eq .Values.x 1 }}one{{ else if eq .Values.x 2 }}two{{ else }}many{{ end }}'
        assert helmlite.render_string(t, {"Values": {"x": 2}}) == "two"
        assert helmlite.render_string(t, {"Values": {"x": 5}}) == "many"
