"""Pool-sharded control plane: shard keying, per-pool delta feeds,
sharded queues, apply-set writes, and the streamed-LIST bootstrap.

The contract (ISSUE 11): steady-state control-plane cost is O(changes)
to 16k sim nodes. These tests pin the mechanisms — (1) the sharded node
view's per-pool membership is EXACTLY the partition of the global
snapshot (delta-feed equivalence), (2) a re-pooled node lands in exactly
one shard and both affected shards hear about it, (3) one wedged shard
cannot starve another (per-shard queues + workers), (4) apply-set's
field-ownership semantics (set/adopt/cede/remove, force, no-op-free),
over both clients, and (5) an informer bootstrapping over HTTP pays ONE
watch request and zero LIST pages.
"""

import threading
import time

import prometheus_client
import pytest

from tpu_operator import consts
from tpu_operator.kube import trace
from tpu_operator.kube.controller import Controller, Request, Result
from tpu_operator.kube.fake import FakeClient
from tpu_operator.kube.http_client import HttpClient
from tpu_operator.kube.httpserver import FakeApiServer
from tpu_operator.kube.informer import Informer
from tpu_operator.kube.objects import apply_set_merge
from tpu_operator.kube.sharding import UNPOOLED, ShardedNodeView, shard_key
from tpu_operator.kube.sim import make_bare_node, make_tpu_node
from tpu_operator.kube.writers import WriteFanout
from tpu_operator.nodepool import get_node_pools

NS = "tpu-operator"


def wait_for(fn, timeout=5.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return False


class TestShardKey:
    def test_shard_key_matches_nodepool_partition(self):
        """shard_key(n) must equal the NodePool.name get_node_pools puts
        n in — the two partitions can never disagree."""
        nodes = [
            make_tpu_node("a0", "tpu-v5-lite-podslice", "4x4", nodepool="pool-a"),
            make_tpu_node("a1", "tpu-v5-lite-podslice", "4x4", nodepool="pool-a"),
            make_tpu_node("b0", "tpu-v4-podslice", "2x2x1", nodepool="pool-b"),
        ]
        pools = {p.name: set(p.node_names) for p in get_node_pools(nodes)}
        for node in nodes:
            shard = shard_key(node)
            assert node["metadata"]["name"] in pools[shard]

    def test_non_tpu_node_lands_in_unpooled(self):
        assert shard_key(make_bare_node("plain")) == UNPOOLED


class TestShardedNodeView:
    def _wired(self, *nodes):
        client = FakeClient()
        for n in nodes:
            client.create(n)
        informer = Informer(client, "v1", "Node")
        view = ShardedNodeView().attach(informer)
        informer.start()
        return client, informer, view

    def test_delta_feed_equivalence_with_global_snapshot(self):
        """After arbitrary churn, the view's per-shard membership equals
        partitioning the informer's global snapshot by shard_key — the
        per-pool delta feed loses and invents nothing."""
        client, informer, view = self._wired(
            make_tpu_node("a0", nodepool="pool-a"),
            make_tpu_node("b0", nodepool="pool-b"),
        )
        client.create(make_tpu_node("a1", nodepool="pool-a"))
        client.create(make_bare_node("plain"))
        client.patch("v1", "Node", "a0", {"metadata": {"labels": {"x": "1"}}})
        client.delete("v1", "Node", "b0")
        client.create(make_tpu_node("b1", nodepool="pool-b"))

        expected: dict = {}
        for node in informer.cached(copy=False):
            expected.setdefault(shard_key(node), []).append(node["metadata"]["name"])
        expected = {s: sorted(m) for s, m in expected.items()}
        assert view.membership() == expected
        informer.stop()

    def test_repooled_node_lands_in_exactly_one_shard(self):
        """A node whose pool labels change moves atomically: DELETED on
        the old shard, ADDED on the new, never a member of both."""
        client, informer, view = self._wired(make_tpu_node("n0", nodepool="pool-a"))
        events = []
        view.add_handler(lambda shard, et, old, new: events.append((shard, et)))
        old_shard = view.shard_for("n0")
        client.patch(
            "v1", "Node", "n0",
            {"metadata": {"labels": {"cloud.google.com/gke-nodepool": "pool-b"}}},
        )
        new_shard = view.shard_for("n0")
        assert new_shard != old_shard
        membership = view.membership()
        homes = [s for s, members in membership.items() if "n0" in members]
        assert homes == [new_shard]
        assert (old_shard, "DELETED") in events
        assert (new_shard, "ADDED") in events
        informer.stop()

    def test_node_delete_leaves_no_shard_residue(self):
        client, informer, view = self._wired(make_tpu_node("n0", nodepool="pool-a"))
        client.delete("v1", "Node", "n0")
        assert view.membership() == {}
        assert view.shard_for("n0") is None
        informer.stop()


class TestShardedControllerFairness:
    def test_wedged_shard_does_not_starve_others(self):
        """Shard A's reconciler blocks forever; shard B's requests keep
        being served (own queue, own worker) — the fairness property a
        single global queue cannot give."""
        wedge = threading.Event()
        served = []

        class R:
            def reconcile(self, req):
                if req.shard == "wedged":
                    wedge.wait(10)
                served.append(req.shard)
                return Result()

        ctrl = Controller("fairness", R())
        ctrl.start()
        try:
            ctrl.enqueue(Request(name="q", shard="wedged"))
            assert wait_for(lambda: not wedge.is_set())  # worker is parked
            for i in range(3):
                ctrl.enqueue(Request(name=f"q{i}", shard="healthy"))
            assert wait_for(lambda: served.count("healthy") == 3), served
            assert "wedged" not in served
        finally:
            wedge.set()
            ctrl.stop()

    def test_shard_metrics_exist_and_drain_removes_them(self):
        """Each shard exports its own workqueue series; drain_shard
        retires them (the O005 contract) and joins the shard's workers."""
        class R:
            def reconcile(self, req):
                return Result()

        ctrl = Controller("drainer", R())
        ctrl.start()
        try:
            ctrl.enqueue(Request(name="x", shard="pool-z"))
            assert wait_for(
                lambda: prometheus_client.REGISTRY.get_sample_value(
                    "tpu_operator_workqueue_depth",
                    {"controller": "drainer", "shard": "pool-z"},
                ) is not None
            )
            ctrl.drain_shard("pool-z")
            assert prometheus_client.REGISTRY.get_sample_value(
                "tpu_operator_workqueue_depth",
                {"controller": "drainer", "shard": "pool-z"},
            ) is None
            assert "pool-z" not in ctrl.shards()
        finally:
            ctrl.stop()

    def test_reconcile_trace_carries_shard(self):
        rec = trace.reset_recorder()

        class R:
            def reconcile(self, req):
                return Result()

        ctrl = Controller("traced", R())
        ctrl.start()
        try:
            ctrl.enqueue(Request(name="x", shard="pool-t"))
            assert wait_for(lambda: len(rec) >= 1)
            assert rec.traces()[0].root.attrs["shard"] == "pool-t"
        finally:
            ctrl.stop()
            trace.reset_recorder()


class TestApplySetSemantics:
    def _node(self, client):
        client.create(make_tpu_node("n0"))
        return lambda: client.get("v1", "Node", "n0")

    def test_set_remove_via_ownership_record(self):
        client = FakeClient()
        get = self._node(client)
        client.apply_set("v1", "Node", "n0", "mgr", labels={"a": "1", "b": "2"})
        labels = get()["metadata"]["labels"]
        assert labels["a"] == "1" and labels["b"] == "2"
        # drop b from the declaration: the record removes it server-side
        client.apply_set("v1", "Node", "n0", "mgr", labels={"a": "1"})
        labels = get()["metadata"]["labels"]
        assert "b" not in labels and labels["a"] == "1"

    def test_foreign_value_is_not_stolen_and_ownership_cedes(self):
        client = FakeClient()
        get = self._node(client)
        client.apply_set("v1", "Node", "n0", "mgr", labels={"gate": "true"})
        # admin override
        client.patch("v1", "Node", "n0", {"metadata": {"labels": {"gate": "false"}}})
        client.apply_set("v1", "Node", "n0", "mgr", labels={"gate": "true"})
        assert get()["metadata"]["labels"]["gate"] == "false"
        # ...and once ceded, undeclaring does NOT remove the admin's value
        client.apply_set("v1", "Node", "n0", "mgr", labels={})
        assert get()["metadata"]["labels"]["gate"] == "false"

    def test_force_overrides_foreign_value(self):
        client = FakeClient()
        get = self._node(client)
        client.patch("v1", "Node", "n0", {"metadata": {"labels": {"id": "9"}}})
        client.apply_set("v1", "Node", "n0", "mgr", labels={"id": "0"}, force=True)
        assert get()["metadata"]["labels"]["id"] == "0"

    def test_noop_apply_bumps_nothing_and_emits_no_event(self):
        """The steady-state sweep property: an apply that changes nothing
        is free — no rv bump, no watch event."""
        client = FakeClient()
        get = self._node(client)
        client.apply_set("v1", "Node", "n0", "mgr", labels={"a": "1"})
        rv = get()["metadata"]["resourceVersion"]
        events = []
        client.watch("v1", "Node", lambda et, obj: events.append(et))
        client.apply_set("v1", "Node", "n0", "mgr", labels={"a": "1"})
        assert get()["metadata"]["resourceVersion"] == rv
        assert events == []

    def test_concurrent_writer_of_other_fields_never_conflicts(self):
        """Apply-set conflict semantics: no rv travels, so a concurrent
        writer bumping the object between read and apply cannot 409 —
        and both writes survive."""
        client = FakeClient()
        get = self._node(client)
        client.patch("v1", "Node", "n0", {"metadata": {"labels": {"kubelet/zone": "a"}}})
        client.apply_set("v1", "Node", "n0", "mgr", labels={"mine": "1"})
        labels = get()["metadata"]["labels"]
        assert labels["kubelet/zone"] == "a" and labels["mine"] == "1"

    def test_apply_set_merge_is_pure(self):
        md = {"labels": {"a": "1"}, "annotations": {}}
        new_labels, new_annotations, changed = apply_set_merge(md, "m", {"b": "2"})
        assert changed and new_labels == {"a": "1", "b": "2"}
        assert md["labels"] == {"a": "1"}  # input untouched
        assert consts.APPLY_SET_ANNOTATION_PREFIX + "m" in new_annotations

    def test_apply_set_over_http(self):
        """The wire path: one PATCH with the apply-set content type; the
        server performs the merge; removal works across a fresh client
        (the record lives on the object, not in the client)."""
        store = FakeClient()
        store.create(make_tpu_node("n0"))
        server = FakeApiServer(store).start()
        try:
            client = HttpClient(server.base_url, timeout=5.0)
            client.apply_set("v1", "Node", "n0", "mgr", labels={"a": "1", "b": "2"})
            fresh = HttpClient(server.base_url, timeout=5.0)
            fresh.apply_set("v1", "Node", "n0", "mgr", labels={"a": "1"})
            labels = store.get("v1", "Node", "n0")["metadata"]["labels"]
            assert labels["a"] == "1" and "b" not in labels
            assert client.request_counts["PATCH"] == 1
        finally:
            server.stop()


class TestStreamedListBootstrap:
    def test_informer_syncs_with_zero_list_pages(self):
        """The WatchList analog: informer bootstrap over HTTP is ONE
        watch request whose stream carries the snapshot — no paginated
        LIST (at 16k nodes the legacy bootstrap paid 33 pages per
        (re)connect, discarded)."""
        store = FakeClient()
        for i in range(12):
            store.create(make_tpu_node(f"n{i}"))
        server = FakeApiServer(store).start()
        try:
            client = HttpClient(server.base_url, timeout=5.0)
            informer = Informer(client, "v1", "Node")
            informer.start(sync_timeout=10.0)
            assert informer.has_synced()
            assert len(informer.cached(copy=False)) == 12
            assert client.request_counts.get("GET", 0) == 0  # no LIST at all
            assert client.request_counts.get("WATCH", 0) == 1
            # live events still flow after the in-stream snapshot
            store.create(make_tpu_node("late"))
            assert wait_for(lambda: informer.get("late") is not None)
            informer.stop()
        finally:
            server.stop()


class TestWatchListIgnoredFallback:
    def test_server_that_silently_ignores_option_still_syncs_via_fallback(self):
        """A server that accepts the watch but IGNORES sendInitialEvents
        (feature gate off, no 400) streams only plain bookmarks on a
        quiet resource: the bootstrap deadline must kick the client back
        to LIST+watch so the informer still syncs."""
        import json as _json
        import threading as _threading
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # noqa: A003
                pass

            def do_GET(self):  # noqa: N802
                if "watch=true" in self.path:
                    # ignore sendInitialEvents entirely: plain bookmarks only
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Connection", "close")
                    self.end_headers()
                    deadline = time.monotonic() + 8
                    while time.monotonic() < deadline:
                        try:
                            self.wfile.write(
                                _json.dumps({"type": "BOOKMARK", "object": {}}).encode() + b"\n"
                            )
                            self.wfile.flush()
                        except OSError:
                            return
                        time.sleep(0.1)
                    return
                body = _json.dumps({
                    "apiVersion": "v1", "kind": "NodeList",
                    "metadata": {"resourceVersion": "7"},
                    "items": [{"metadata": {"name": "n0", "resourceVersion": "5"}}],
                }).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        httpd.daemon_threads = True
        _threading.Thread(target=httpd.serve_forever, daemon=True).start()
        try:
            client = HttpClient(
                f"http://127.0.0.1:{httpd.server_address[1]}",
                timeout=5.0, watch_stall_seconds=1.0,  # 1s bootstrap deadline
            )
            informer = Informer(client, "v1", "Node")
            informer.start(sync_timeout=15.0)
            assert wait_for(informer.has_synced, timeout=15.0), "fallback never synced"
            assert informer.get("n0") is not None
            informer.stop()
        finally:
            httpd.shutdown()
            httpd.server_close()


class TestWriteFanout:
    def test_results_in_order_and_errors_isolated(self):
        pool = WriteFanout(workers=4)
        try:
            def make(i):
                def call():
                    if i == 3:
                        raise ValueError("boom")
                    return i * 10
                return call

            results = pool.map([make(i) for i in range(6)])
            assert [r for r, e in results if e is None] == [0, 10, 20, 40, 50]
            assert isinstance(results[3][1], ValueError)
        finally:
            pool.close()

    def test_batch_is_actually_concurrent(self):
        pool = WriteFanout(workers=8)
        try:
            barrier = threading.Barrier(6, timeout=5)

            def call():
                barrier.wait()  # deadlocks unless 6 run concurrently
                return True

            results = pool.map([call] * 6)
            assert all(r is True and e is None for r, e in results)
        finally:
            pool.close()

    def test_small_batches_run_inline(self):
        pool = WriteFanout(workers=4)
        try:
            ident = []
            results = pool.map([lambda: ident.append(threading.get_ident()) or 1] * 2)
            assert [r for r, _ in results] == [1, 1]
            assert set(ident) == {threading.get_ident()}  # caller's thread
            assert pool.workers == 0  # nothing spawned
        finally:
            pool.close()

    def test_batch_records_one_api_span_with_request_count(self):
        rec = trace.reset_recorder()
        pool = WriteFanout(workers=4)
        try:
            with trace.start_trace("reconcile", controller="c", request="r"):
                pool.map([lambda: None] * 5, verb="apply_set", kind="Node")
            (t,) = rec.traces()
            api = [s for s in t.spans if s.name == "api"]
            assert len(api) == 1
            assert api[0].attrs["attempts"] == 5
            assert api[0].attrs["verb"] == "apply_set"
            assert t.complete() and t.accounted_fraction() >= 0.95
        finally:
            pool.close()
            trace.reset_recorder()


class TestPlacementPoolPass:
    """Per-pool delta feed equivalence for the placement path: a
    pool-local change replanned through the pool pass converges to the
    same labels/status a global replan produces."""

    def _cluster(self):
        from tpu_operator.api.clusterpolicy import new_cluster_policy
        from tpu_operator.api.tpuslice import new_tpu_slice
        from tpu_operator.kube.sim import make_torus_nodes

        store = FakeClient()
        for node in make_torus_nodes((4, 2, 1), prefix="pa", nodepool="pool-a"):
            node["metadata"]["labels"][consts.TPU_PRESENT_LABEL] = "true"
            store.create(node)
        for node in make_torus_nodes((2, 2, 1), prefix="pb", nodepool="pool-b"):
            node["metadata"]["labels"][consts.TPU_PRESENT_LABEL] = "true"
            store.create(node)
        store.create(new_cluster_policy())
        store.create(new_tpu_slice("gang-a", {"placement": {"shape": "2x2x1"}}))
        return store

    def _pool_pass_world(self, store):
        """Run the same change through the sharded pool pass."""
        from tpu_operator.controllers.placement_controller import (
            QUEUE_REQUEST,
            PlacementReconciler,
        )

        rec = PlacementReconciler(store, NS)
        rec.reconcile(QUEUE_REQUEST)  # initial global placement
        informer = Informer(store, "v1", "Node")
        view = ShardedNodeView().attach(informer)
        informer.start()
        rec.node_view = view
        return rec, view, informer

    def _snapshot(self, store):
        from tpu_operator.api.tpuslice import TPU_SLICE_API_VERSION

        nodes = {
            n["metadata"]["name"]: {
                k: v for k, v in (n["metadata"].get("labels") or {}).items()
                if k.startswith("tpu.google.com/placement")
            }
            for n in store.list("v1", "Node")
        }
        ts = store.get(TPU_SLICE_API_VERSION, "TPUSlice", "gang-a")
        status = dict((ts.get("status") or {}).get("placement") or {})
        status.pop("message", None)  # wording may differ between passes
        return nodes, status

    def test_pool_pass_equivalent_to_global_replan(self):
        from tpu_operator.controllers.placement_controller import (
            QUEUE_REQUEST,
            PlacementReconciler,
        )
        from tpu_operator.kube.controller import Request as KReq

        # world A: pool pass handles the change, draining any requests
        # it hands to the global queue (what the controller wiring does)
        store_a = self._cluster()
        rec_a, view_a, informer_a = self._pool_pass_world(store_a)
        handed_up = []
        rec_a._enqueue = handed_up.append
        ts = store_a.get("tpu.google.com/v1alpha1", "TPUSlice", "gang-a")
        member = ts["status"]["placement"]["nodes"][0]
        shard = view_a.shard_for(member)
        assert shard is not None
        store_a.patch(
            "v1", "Node", member,
            {"metadata": {"labels": {consts.TPU_HEALTH_LABEL: consts.HEALTH_DEGRADED}}},
        )
        rec_a.reconcile(KReq(name=QUEUE_REQUEST.name, shard=shard))
        # the teardown re-places on the next passes: pool first, then
        # whatever the pool pass handed to the global queue
        rec_a.reconcile(KReq(name=QUEUE_REQUEST.name, shard=shard))
        for req in list(dict.fromkeys(handed_up)):
            rec_a.reconcile(req)
        informer_a.stop()

        # world B: the identical change handled by a global replan
        store_b = self._cluster()
        rec_b = PlacementReconciler(store_b, NS)
        rec_b.reconcile(QUEUE_REQUEST)
        store_b.patch(
            "v1", "Node", member,
            {"metadata": {"labels": {consts.TPU_HEALTH_LABEL: consts.HEALTH_DEGRADED}}},
        )
        rec_b.reconcile(QUEUE_REQUEST)
        rec_b.reconcile(QUEUE_REQUEST)

        assert self._snapshot(store_a) == self._snapshot(store_b)
        nodes, status = self._snapshot(store_a)
        assert status.get("phase") == "Scheduled"
        assert member not in (status.get("nodes") or [])

    def test_pool_pass_never_condemns_slice_pinned_elsewhere(self):
        """A slice pinned to pool B but dragged into pool A's pass by a
        stale status.pool must NOT be published Unschedulable by A —
        only the pinned pool's own pass (or the global one) is
        authoritative for that verdict."""
        from tpu_operator.api.tpuslice import new_tpu_slice
        from tpu_operator.controllers.placement_controller import QUEUE_REQUEST
        from tpu_operator.kube.controller import Request as KReq

        store = self._cluster()
        rec, view, informer = self._pool_pass_world(store)
        shard_a = view.shard_for("pa-0")
        shard_b = view.shard_for("pb-0")
        # pinned to pool-b's shard, but status claims pool-a (stale)
        obj = new_tpu_slice("pinned-b", {"placement": {"shape": "2x2x1", "pool": shard_b}})
        store.create(obj)
        store.patch_status(
            "tpu.google.com/v1alpha1", "TPUSlice", "pinned-b",
            {"status": {"placement": {"phase": "Queued", "pool": shard_a}}},
        )
        rec.reconcile(KReq(name=QUEUE_REQUEST.name, shard=shard_a))
        ts = store.get("tpu.google.com/v1alpha1", "TPUSlice", "pinned-b")
        phase = ((ts.get("status") or {}).get("placement") or {}).get("phase")
        assert phase != "Unschedulable", phase
        informer.stop()

    def test_pool_pass_survives_explicit_null_placement(self):
        """spec.placement: null (valid YAML for an optional object) must
        not crash the pool pass."""
        from tpu_operator.controllers.placement_controller import QUEUE_REQUEST
        from tpu_operator.kube.controller import Request as KReq
        from tpu_operator.kube.objects import new_object

        store = self._cluster()
        rec, view, informer = self._pool_pass_world(store)
        ts = store.get("tpu.google.com/v1alpha1", "TPUSlice", "gang-a")
        member = ts["status"]["placement"]["nodes"][0]
        shard = view.shard_for(member)
        null_spec = new_object(
            "tpu.google.com/v1alpha1", "TPUSlice", "null-placement",
            spec={"placement": None},
        )
        store.create(null_spec)
        rec.reconcile(KReq(name=QUEUE_REQUEST.name, shard=shard))  # must not raise
        informer.stop()

    def test_pool_pass_leaves_unpinned_pending_slices_to_global(self):
        """A pool pass never condemns an UNPINNED slice to
        Unschedulable: a new pending slice is simply not a pool pass's
        business (its creation event maps to the global queue in the
        controller wiring), and the global pass places it wherever there
        is room."""
        from tpu_operator.api.tpuslice import new_tpu_slice
        from tpu_operator.controllers.placement_controller import QUEUE_REQUEST
        from tpu_operator.kube.controller import Request as KReq

        store = self._cluster()
        rec, view, informer = self._pool_pass_world(store)
        # a shape only pool-a (4x2x1 grid) can fit; replan pool-b first
        store.create(new_tpu_slice("gang-late", {"placement": {"shape": "4x1x1"}}))
        shard_b = view.shard_for("pb-0")
        rec.reconcile(KReq(name=QUEUE_REQUEST.name, shard=shard_b))
        ts = store.get("tpu.google.com/v1alpha1", "TPUSlice", "gang-late")
        phase = ((ts.get("status") or {}).get("placement") or {}).get("phase")
        assert phase != "Unschedulable"  # untouched, not condemned
        # the slice's own creation event maps to the global queue:
        rec.reconcile(QUEUE_REQUEST)
        ts = store.get("tpu.google.com/v1alpha1", "TPUSlice", "gang-late")
        assert (ts["status"]["placement"]).get("phase") == "Scheduled"
        informer.stop()


class TestMustGatherSharding:
    def test_sharding_artifact_collected(self, tmp_path):
        from tpu_operator.mustgather import collect

        client = FakeClient()
        client.create(make_tpu_node("n0", nodepool="pool-a"))
        written = collect(client, NS, str(tmp_path))
        assert "sharding.txt" in written
        text = (tmp_path / "sharding.txt").read_text()
        assert "shard -> pool assignment" in text
        assert "nodes=1" in text
