"""Render engine + state engine tests (reference analogs:
internal/render/render_test.go, internal/state/driver_test.go golden files,
controllers/object_controls_test.go transform assertions)."""

import os

import pytest
import yaml

from tpu_operator import consts
from tpu_operator.api import ClusterPolicy
from tpu_operator.api.clusterpolicy import new_cluster_policy
from tpu_operator.catalog import InfoCatalog
from tpu_operator.kube.fake import FakeClient
from tpu_operator.kube.objects import new_object
from tpu_operator.render import Renderer, RenderError
from tpu_operator.state import StateManager, SyncStates
from tpu_operator.states import STATE_ORDER, build_render_data, new_cluster_policy_states

GOLDEN_DIR = os.path.join(os.path.dirname(__file__), "golden")


def make_catalog(spec=None, **kwargs) -> InfoCatalog:
    cp = ClusterPolicy.from_unstructured(new_cluster_policy(spec=spec or {}))
    return InfoCatalog(cluster_policy=cp, **kwargs)


def render_state(name, catalog):
    states = {s.name: s for s in new_cluster_policy_states()}
    state = states[name]
    return state.renderer.render_objects(state.get_render_data(catalog))


class TestRenderer:
    def test_missing_dir_raises(self):
        with pytest.raises(RenderError):
            Renderer(["/nonexistent"]).render_objects({})

    def test_strict_undefined(self, tmp_path):
        p = tmp_path / "x.yaml"
        p.write_text("apiVersion: v1\nkind: ConfigMap\nmetadata:\n  name: {{ missing_key }}\n")
        with pytest.raises(RenderError, match="missing render data"):
            Renderer([str(tmp_path)]).render_objects({})

    def test_multi_doc_and_empty_doc(self, tmp_path):
        p = tmp_path / "multi.yaml"
        p.write_text(
            "{% if false %}\nskipped: doc\n{% endif %}\n---\n"
            "apiVersion: v1\nkind: ConfigMap\nmetadata:\n  name: a\n---\n"
            "apiVersion: v1\nkind: ConfigMap\nmetadata:\n  name: b\n"
        )
        objs = Renderer([str(tmp_path)]).render_objects({})
        assert [o["metadata"]["name"] for o in objs] == ["a", "b"]


class TestStateRendering:
    def test_all_states_render_with_default_spec(self):
        catalog = make_catalog()
        for name in STATE_ORDER:
            objs = render_state(name, catalog)
            assert objs, name
            for obj in objs:
                assert obj["apiVersion"] and obj["kind"], (name, obj)

    def test_every_operand_daemonset_has_deploy_node_selector(self):
        catalog = make_catalog()
        found = 0
        for name in STATE_ORDER:
            for obj in render_state(name, catalog):
                if obj["kind"] != "DaemonSet":
                    continue
                found += 1
                sel = obj["spec"]["template"]["spec"]["nodeSelector"]
                deploy_keys = [k for k in sel if k.startswith(consts.COMMON_DEPLOY_LABEL_PREFIX)]
                if name == "state-node-discovery":
                    # the bootstrap's contract is the inverse: it must reach
                    # nodes the operator has NOT recognized yet, so a
                    # tpu.deploy.* gate would defeat it (NFD-worker model)
                    assert not deploy_keys, (name, sel)
                else:
                    assert deploy_keys, (name, sel)
        # discovery, libtpu, plugin, validation, tfd, slice-mgr, metrics,
        # node-status, health-monitor, autotuner, compile-cache
        assert found == 11

    def test_perf_floor_envs_render_into_operand_daemonsets(self):
        """spec.validator.minTflops reaches the workload-validation init
        container; minPsumGbpsPerChip reaches the slice-manager agent
        (which forwards it into every gang worker pod)."""
        catalog = make_catalog(
            spec={"validator": {"minTflops": 120.5, "minPsumGbpsPerChip": 37.0}}
        )
        (ds,) = [
            o
            for o in render_state("state-operator-validation", catalog)
            if o["kind"] == "DaemonSet"
        ]
        workload = [
            c
            for c in ds["spec"]["template"]["spec"]["initContainers"]
            if c["name"] == "workload-validation"
        ][0]
        env = {e["name"]: e.get("value") for e in workload["env"]}
        assert env["MIN_TFLOPS"] == "120.5"
        (sm_ds,) = [
            o
            for o in render_state("state-slice-manager", catalog)
            if o["kind"] == "DaemonSet"
        ]
        sm_env = {
            e["name"]: e.get("value")
            for e in sm_ds["spec"]["template"]["spec"]["containers"][0]["env"]
        }
        assert sm_env["MIN_PSUM_GBPS_PER_CHIP"] == "37.0"
        # no floors configured -> no envs rendered
        plain = make_catalog()
        (ds2,) = [
            o
            for o in render_state("state-operator-validation", plain)
            if o["kind"] == "DaemonSet"
        ]
        for c in ds2["spec"]["template"]["spec"]["initContainers"]:
            assert "MIN_TFLOPS" not in {e["name"] for e in c.get("env", [])}

    def test_custom_images_and_env_flow_into_daemonset(self):
        catalog = make_catalog(
            spec={
                "libtpu": {
                    "repository": "gcr.io/custom",
                    "image": "libtpu",
                    "version": "2.0.0",
                    "env": [{"name": "EXTRA", "value": "on"}],
                },
                "daemonsets": {"tolerations": [{"key": "dedicated", "operator": "Exists"}]},
            }
        )
        (ds,) = [o for o in render_state("state-libtpu", catalog) if o["kind"] == "DaemonSet"]
        ctr = ds["spec"]["template"]["spec"]["containers"][0]
        assert ctr["image"] == "gcr.io/custom/libtpu:2.0.0"
        env = {e["name"]: e.get("value") for e in ctr["env"]}
        assert env["EXTRA"] == "on"
        tol_keys = [t["key"] for t in ds["spec"]["template"]["spec"]["tolerations"]]
        assert consts.TPU_RESOURCE_NAME in tol_keys and "dedicated" in tol_keys

    def test_service_monitor_gated(self):
        off = make_catalog()
        objs = render_state("state-metrics-exporter", off)
        assert not [o for o in objs if o["kind"] == "ServiceMonitor"]
        on = make_catalog(spec={"metricsExporter": {"serviceMonitor": {"enabled": True}}})
        objs = render_state("state-metrics-exporter", on)
        assert [o for o in objs if o["kind"] == "ServiceMonitor"]

    def test_validator_daemonset_has_component_init_containers(self):
        catalog = make_catalog()
        (ds,) = [o for o in render_state("state-operator-validation", catalog) if o["kind"] == "DaemonSet"]
        inits = ds["spec"]["template"]["spec"]["initContainers"]
        comps = []
        for c in inits:
            comps += [e["value"] for e in c["env"] if e["name"] == "COMPONENT"]
        assert comps == ["libtpu", "plugin", "workload"]

    def test_multislice_env_injected(self):
        catalog = make_catalog(spec={"multiSlice": {"enabled": True, "coordinatorPort": 9999}})
        (ds,) = [o for o in render_state("state-operator-validation", catalog) if o["kind"] == "DaemonSet"]
        workload = [c for c in ds["spec"]["template"]["spec"]["initContainers"] if c["name"] == "workload-validation"][0]
        env = {e["name"]: e.get("value") for e in workload["env"]}
        assert env["MULTI_SLICE_ENABLED"] == "true"
        assert env["COORDINATOR_PORT"] == "9999"


class TestGolden:
    """Golden-file render tests (reference: internal/state/driver_test.go +
    testdata/golden). Regenerate with scripts/update_golden.py."""

    @pytest.mark.parametrize("name", STATE_ORDER)
    def test_golden(self, name):
        catalog = make_catalog(
            spec={"metricsExporter": {"serviceMonitor": {"enabled": True}}}
        )
        objs = render_state(name, catalog)
        path = os.path.join(GOLDEN_DIR, f"{name}.yaml")
        if not os.path.exists(path):
            pytest.skip(f"golden missing: {path} (run scripts/update_golden.py)")
        with open(path) as f:
            want = list(yaml.safe_load_all(f))
        assert objs == want, f"{name}: rendered objects drifted from golden (scripts/update_golden.py)"


class TestStateEngine:
    def test_sync_creates_objects_and_reports_not_ready_until_ds_ready(self):
        client = FakeClient()
        catalog = make_catalog()
        states = {s.name: s for s in new_cluster_policy_states()}
        state = states["state-libtpu"]
        # zero desired pods counts as ready (reference: isDaemonSetReady
        # no-scheduled-pods case, object_controls.go:3439) — the fake has no
        # DS controller yet, so the first sync reports ready
        assert state.sync(client, catalog).state == SyncStates.READY
        ds = client.get("apps/v1", "DaemonSet", "libtpu-installer", catalog.namespace)
        assert ds["metadata"]["labels"][consts.STATE_LABEL] == "state-libtpu"
        assert consts.LAST_APPLIED_HASH_ANNOTATION in ds["metadata"]["annotations"]
        # DS controller schedules pods: not all available -> notReady
        ds["status"] = {"desiredNumberScheduled": 2, "numberAvailable": 1, "updatedNumberScheduled": 2}
        client.update_status(ds)
        assert state.sync(client, catalog).state == SyncStates.NOT_READY
        ds = client.get("apps/v1", "DaemonSet", "libtpu-installer", catalog.namespace)
        ds["status"] = {"desiredNumberScheduled": 2, "numberAvailable": 2, "updatedNumberScheduled": 2}
        client.update_status(ds)
        assert state.sync(client, catalog).state == SyncStates.READY

    def test_sync_is_idempotent_no_thrash(self):
        client = FakeClient()
        catalog = make_catalog()
        state = {s.name: s for s in new_cluster_policy_states()}["state-libtpu"]
        state.sync(client, catalog)
        rv1 = client.get("apps/v1", "DaemonSet", "libtpu-installer", catalog.namespace)["metadata"]["resourceVersion"]
        state.sync(client, catalog)
        rv2 = client.get("apps/v1", "DaemonSet", "libtpu-installer", catalog.namespace)["metadata"]["resourceVersion"]
        assert rv1 == rv2  # unchanged spec never rewritten

    def test_spec_change_updates_object(self):
        client = FakeClient()
        catalog = make_catalog()
        state = {s.name: s for s in new_cluster_policy_states()}["state-libtpu"]
        state.sync(client, catalog)
        catalog2 = make_catalog(spec={"libtpu": {"repository": "gcr.io/new", "image": "libtpu", "version": "9"}})
        state.sync(client, catalog2)
        ds = client.get("apps/v1", "DaemonSet", "libtpu-installer", catalog.namespace)
        assert ds["spec"]["template"]["spec"]["containers"][0]["image"] == "gcr.io/new/libtpu:9"

    def test_disabled_state_deletes_owned_objects(self):
        client = FakeClient()
        catalog = make_catalog()
        state = {s.name: s for s in new_cluster_policy_states()}["state-device-plugin"]
        state.sync(client, catalog)
        assert client.get("apps/v1", "DaemonSet", "tpu-device-plugin", catalog.namespace)
        disabled = make_catalog(spec={"devicePlugin": {"enabled": False}})
        result = state.sync(client, disabled)
        assert result.state == SyncStates.IGNORE
        assert client.get_or_none("apps/v1", "DaemonSet", "tpu-device-plugin", catalog.namespace) is None

    def test_no_tpu_nodes_skips_operand_states(self):
        client = FakeClient()
        catalog = make_catalog(has_tpu_nodes=False)
        mgr = StateManager(new_cluster_policy_states())
        results = mgr.sync_state(client, catalog)
        # operand DSes skipped; only cluster-scoped states applied
        assert results.status == SyncStates.READY
        assert client.get_or_none("apps/v1", "DaemonSet", "libtpu-installer", catalog.namespace) is None
        assert client.get("scheduling.k8s.io/v1", "PriorityClass", "tpu-operator-critical")

    def test_state_manager_aggregates(self):
        client = FakeClient()
        catalog = make_catalog()
        mgr = StateManager(new_cluster_policy_states())
        results = mgr.sync_state(client, catalog)
        # no DS controller in the fake -> all DSes have zero desired -> ready
        assert results.status == SyncStates.READY
        assert set(results.states) == set(STATE_ORDER)
        # make one DS unhealthy -> aggregate flips notReady
        ds = client.get("apps/v1", "DaemonSet", "tpu-device-plugin", catalog.namespace)
        ds["status"] = {"desiredNumberScheduled": 1, "numberAvailable": 0, "updatedNumberScheduled": 0}
        client.update_status(ds)
        assert mgr.sync_state(client, catalog).status == SyncStates.NOT_READY


class TestRenderCache:
    def test_memoized_and_isolated(self):
        catalog = make_catalog()
        state = {s.name: s for s in new_cluster_policy_states()}["state-libtpu"]
        a = state.render_all(catalog)
        b = state.render_all(catalog)
        assert a == b and a is not b
        # mutating a returned object must not poison the cache
        b[0]["metadata"]["name"] = "tampered"
        assert state.render_all(catalog)[0]["metadata"]["name"] != "tampered"
        # spec change invalidates the cache
        catalog2 = make_catalog(spec={"libtpu": {"repository": "gcr.io/z", "image": "l", "version": "2"}})
        c = state.render_all(catalog2)
        (ds,) = [o for o in c if o["kind"] == "DaemonSet"]
        assert ds["spec"]["template"]["spec"]["containers"][0]["image"] == "gcr.io/z/l:2"
