"""Stress + fault-injection tests.

The reference runs no race detector and no fault injection (SURVEY.md §5);
this suite goes further: concurrent controllers under node churn, CR
update storms, and injected operand crashes must all converge to Ready
with no stuck states — the level-triggered design's whole claim.
"""

import threading
import time

from tpu_operator import consts
from tpu_operator.api.clusterpolicy import (
    CLUSTER_POLICY_API_VERSION,
    CLUSTER_POLICY_KIND,
    new_cluster_policy,
)
from tpu_operator.controllers.clusterpolicy_controller import (
    ClusterPolicyReconciler,
    setup_with_manager,
)
from tpu_operator.kube import errors
from tpu_operator.kube.fake import FakeClient
from tpu_operator.kube.manager import Manager
from tpu_operator.kube.sim import ClusterSim, make_tpu_node

NS = "tpu-operator"


def wait_for(fn, timeout=30.0, interval=0.03):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return False


def cp_state(client):
    obj = client.get_or_none(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND, "cluster-policy")
    return (obj or {}).get("status", {}).get("state")


def test_node_churn_converges():
    """Nodes joining/leaving while the operator reconciles: the final
    steady state must be Ready with labels exactly on surviving nodes."""
    client = FakeClient()
    sim = ClusterSim(client, ready_delay=0.05).start()
    mgr = Manager(client, namespace=NS)
    setup_with_manager(mgr, ClusterPolicyReconciler(client, NS))
    stop = threading.Event()

    def churn():
        i = 0
        while not stop.is_set():
            name = f"churn-{i % 6}"
            try:
                client.create(make_tpu_node(name))
            except errors.AlreadyExists:
                try:
                    client.delete("v1", "Node", name)
                except errors.NotFound:
                    pass
            i += 1
            time.sleep(0.01)

    try:
        mgr.start()
        client.create(new_cluster_policy())
        churners = [threading.Thread(target=churn, daemon=True) for _ in range(3)]
        for t in churners:
            t.start()
        time.sleep(1.5)
        stop.set()
        for t in churners:
            t.join(timeout=5)
        # after the storm: must converge — Ready AND every surviving node
        # labelled ("ready" can predate the reconcile for the last joiner)
        def settled():
            if cp_state(client) != "ready":
                return False
            return all(
                node["metadata"].get("labels", {}).get(consts.TPU_PRESENT_LABEL) == "true"
                for node in client.list("v1", "Node")
            )

        assert wait_for(settled, timeout=20), (
            cp_state(client),
            [(n["metadata"]["name"], n["metadata"].get("labels", {}).get(consts.TPU_PRESENT_LABEL))
             for n in client.list("v1", "Node")],
        )
    finally:
        stop.set()
        mgr.stop()
        sim.stop()


def test_cr_update_storm_no_thrash():
    """Rapid spec flips must settle; the hash discipline must leave the
    final DaemonSet matching the last spec."""
    client = FakeClient()
    sim = ClusterSim(client, ready_delay=0.0).start()
    mgr = Manager(client, namespace=NS)
    setup_with_manager(mgr, ClusterPolicyReconciler(client, NS))
    try:
        mgr.start()
        client.create(make_tpu_node("tpu-0"))
        client.create(new_cluster_policy())
        assert wait_for(lambda: cp_state(client) == "ready")
        for i in range(20):
            # mid-storm conflicts may be dropped, but the LAST update must
            # land for the final-state assertion to be meaningful
            while True:
                obj = client.get(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND, "cluster-policy")
                obj["spec"].setdefault("libtpu", {}).update(
                    {"repository": "gcr.io/storm", "image": "libtpu", "version": f"v{i}"}
                )
                try:
                    client.update(obj)
                    break
                except errors.Conflict:
                    if i < 19:
                        break  # non-final update: dropping it is fine
        assert wait_for(
            lambda: (client.get("apps/v1", "DaemonSet", "libtpu-installer", NS)["spec"]["template"]
                     ["spec"]["containers"][0]["image"]).endswith("v19"),
            timeout=20,
        )
        assert wait_for(lambda: cp_state(client) == "ready", timeout=20)
    finally:
        mgr.stop()
        sim.stop()


def test_informer_converges_through_apiserver_restarts_with_churn():
    """Round-5 core invariant under stress: an informer watching over the
    wire must converge to EXACTLY the store's state through repeated
    apiserver outages while a mutator concurrently creates and deletes
    objects — deletions lost in the blind windows heal via the reconnect
    SYNC Replace (no phantoms), creations are never lost. 3 restart
    cycles, one mutation every 20 ms throughout (~150+ total)."""
    import random

    from tpu_operator.kube.http_client import HttpClient
    from tpu_operator.kube.httpserver import FakeApiServer
    from tpu_operator.kube.informer import Informer
    from tpu_operator.kube.objects import new_object

    store = FakeClient()
    server = FakeApiServer(store).start()
    port = server.httpd.server_address[1]
    client = HttpClient(server.base_url, timeout=5.0)
    for i in range(6):
        store.create(new_object("v1", "ConfigMap", f"seed-{i}", NS))
    inf = Informer(client, "v1", "ConfigMap", NS)
    inf.start()
    stop = threading.Event()
    rng = random.Random(7)
    names = [f"seed-{i}" for i in range(6)]
    counter = [6]

    def mutate():
        while not stop.is_set():
            try:
                if names and rng.random() < 0.5:
                    store.delete("v1", "ConfigMap", names.pop(rng.randrange(len(names))), NS)
                else:
                    name = f"churn-{counter[0]}"
                    counter[0] += 1
                    store.create(new_object("v1", "ConfigMap", name, NS))
                    names.append(name)
            except errors.ApiError:
                pass
            time.sleep(0.02)

    mutator = threading.Thread(target=mutate, daemon=True)
    mutator.start()
    try:
        assert wait_for(lambda: inf.has_synced(), timeout=10)
        for _ in range(3):
            time.sleep(0.3)  # live churn against a healthy server
            server.stop()
            time.sleep(0.4)  # blind window: mutations keep landing
            server = FakeApiServer(store, port=port).start()
            time.sleep(0.3)
        stop.set()
        mutator.join(5)

        last = {}

        def converged():
            # capture the compared snapshots so a timeout failure prints
            # the ACTUAL diverged sets (recomputing in the assert message
            # could race a late heal and print an empty diff)
            last["want"] = {o["metadata"]["name"] for o in store.list("v1", "ConfigMap", NS)}
            last["got"] = {o["metadata"]["name"] for o in inf.cached()}
            return last["want"] == last["got"]

        assert wait_for(converged, timeout=20), (
            f"cache diverged:\n store-only: {last['want'] - last['got']}\n"
            f" cache-only (phantoms): {last['got'] - last['want']}"
        )
    finally:
        stop.set()
        inf.stop()
        try:
            server.stop()
        except Exception:  # noqa: BLE001 — already stopped
            pass


def test_operand_crashes_recovered():
    """Injected operand crashes (flaking DaemonSets) flip the CR NotReady
    and it must return to Ready once the faults stop."""
    client = FakeClient()
    sim = ClusterSim(client, ready_delay=0.05, flake_rate=0.3).start()
    mgr = Manager(client, namespace=NS)
    setup_with_manager(mgr, ClusterPolicyReconciler(client, NS))
    try:
        mgr.start()
        client.create(make_tpu_node("tpu-0"))
        client.create(new_cluster_policy())
        time.sleep(1.0)  # let faults fire
        sim.flake_rate = 0.0  # outage ends
        assert wait_for(lambda: cp_state(client) == "ready", timeout=20), cp_state(client)
        for ds in client.list("apps/v1", "DaemonSet", NS):
            assert ds["status"]["numberAvailable"] == ds["status"]["desiredNumberScheduled"]
    finally:
        mgr.stop()
        sim.stop()
