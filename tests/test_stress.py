"""Stress + fault-injection tests.

The reference runs no race detector and no fault injection (SURVEY.md §5);
this suite goes further: concurrent controllers under node churn, CR
update storms, and injected operand crashes must all converge to Ready
with no stuck states — the level-triggered design's whole claim.
"""

import threading
import time

from tpu_operator import consts
from tpu_operator.api.clusterpolicy import (
    CLUSTER_POLICY_API_VERSION,
    CLUSTER_POLICY_KIND,
    new_cluster_policy,
)
from tpu_operator.controllers.clusterpolicy_controller import (
    ClusterPolicyReconciler,
    setup_with_manager,
)
from tpu_operator.kube import errors
from tpu_operator.kube.fake import FakeClient
from tpu_operator.kube.manager import Manager
from tpu_operator.kube.sim import ClusterSim, make_tpu_node

NS = "tpu-operator"


def wait_for(fn, timeout=30.0, interval=0.03):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return False


def cp_state(client):
    obj = client.get_or_none(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND, "cluster-policy")
    return (obj or {}).get("status", {}).get("state")


def test_node_churn_converges():
    """Nodes joining/leaving while the operator reconciles: the final
    steady state must be Ready with labels exactly on surviving nodes."""
    client = FakeClient()
    sim = ClusterSim(client, ready_delay=0.05).start()
    mgr = Manager(client, namespace=NS)
    setup_with_manager(mgr, ClusterPolicyReconciler(client, NS))
    stop = threading.Event()

    def churn():
        i = 0
        while not stop.is_set():
            name = f"churn-{i % 6}"
            try:
                client.create(make_tpu_node(name))
            except errors.AlreadyExists:
                try:
                    client.delete("v1", "Node", name)
                except errors.NotFound:
                    pass
            i += 1
            time.sleep(0.01)

    try:
        mgr.start()
        client.create(new_cluster_policy())
        churners = [threading.Thread(target=churn, daemon=True) for _ in range(3)]
        for t in churners:
            t.start()
        time.sleep(1.5)
        stop.set()
        for t in churners:
            t.join(timeout=5)
        # after the storm: must converge — Ready AND every surviving node
        # labelled ("ready" can predate the reconcile for the last joiner)
        def settled():
            if cp_state(client) != "ready":
                return False
            return all(
                node["metadata"].get("labels", {}).get(consts.TPU_PRESENT_LABEL) == "true"
                for node in client.list("v1", "Node")
            )

        assert wait_for(settled, timeout=20), (
            cp_state(client),
            [(n["metadata"]["name"], n["metadata"].get("labels", {}).get(consts.TPU_PRESENT_LABEL))
             for n in client.list("v1", "Node")],
        )
    finally:
        stop.set()
        mgr.stop()
        sim.stop()


def test_cr_update_storm_no_thrash():
    """Rapid spec flips must settle; the hash discipline must leave the
    final DaemonSet matching the last spec."""
    client = FakeClient()
    sim = ClusterSim(client, ready_delay=0.0).start()
    mgr = Manager(client, namespace=NS)
    setup_with_manager(mgr, ClusterPolicyReconciler(client, NS))
    try:
        mgr.start()
        client.create(make_tpu_node("tpu-0"))
        client.create(new_cluster_policy())
        assert wait_for(lambda: cp_state(client) == "ready")
        for i in range(20):
            # mid-storm conflicts may be dropped, but the LAST update must
            # land for the final-state assertion to be meaningful
            while True:
                obj = client.get(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND, "cluster-policy")
                obj["spec"].setdefault("libtpu", {}).update(
                    {"repository": "gcr.io/storm", "image": "libtpu", "version": f"v{i}"}
                )
                try:
                    client.update(obj)
                    break
                except errors.Conflict:
                    if i < 19:
                        break  # non-final update: dropping it is fine
        assert wait_for(
            lambda: (client.get("apps/v1", "DaemonSet", "libtpu-installer", NS)["spec"]["template"]
                     ["spec"]["containers"][0]["image"]).endswith("v19"),
            timeout=20,
        )
        assert wait_for(lambda: cp_state(client) == "ready", timeout=20)
    finally:
        mgr.stop()
        sim.stop()


def test_operand_crashes_recovered():
    """Injected operand crashes (flaking DaemonSets) flip the CR NotReady
    and it must return to Ready once the faults stop."""
    client = FakeClient()
    sim = ClusterSim(client, ready_delay=0.05, flake_rate=0.3).start()
    mgr = Manager(client, namespace=NS)
    setup_with_manager(mgr, ClusterPolicyReconciler(client, NS))
    try:
        mgr.start()
        client.create(make_tpu_node("tpu-0"))
        client.create(new_cluster_policy())
        time.sleep(1.0)  # let faults fire
        sim.flake_rate = 0.0  # outage ends
        assert wait_for(lambda: cp_state(client) == "ready", timeout=20), cp_state(client)
        for ds in client.list("apps/v1", "DaemonSet", NS):
            assert ds["status"]["numberAvailable"] == ds["status"]["desiredNumberScheduled"]
    finally:
        mgr.stop()
        sim.stop()
