"""Multi-tenant fairness (ISSUE 20): TPUQuota parsing fails closed, the
DRF fair-share model (hierarchy rollup, ordering, preemption legality),
the placement engine's preemption economy and its zero-TPUQuota
byte-identity contract, the tenancy ledger, the tenancy controller's
accounting/series lifecycle, and the fleet-sim fairness drills the
bench gates replay (``bench.py --tenant-smoke``).
"""

import copy
import dataclasses
import json

import prometheus_client

from tpu_operator import consts
from tpu_operator.api.tpuquota import (
    TPU_QUOTA_API_VERSION,
    TPU_QUOTA_KIND,
    new_tpu_quota,
)
from tpu_operator.api.tpuslice import new_tpu_slice
from tpu_operator.controllers.placement_controller import (
    QUEUE_REQUEST,
    PlacementReconciler,
)
from tpu_operator.controllers.tenancy_controller import (
    TENANCY_REQUEST,
    TenancyReconciler,
)
from tpu_operator.kube import errors
from tpu_operator.kube.fake import FakeClient
from tpu_operator.kube.objects import new_object
from tpu_operator.kube.sim import GangChurnSchedule, make_torus_nodes
from tpu_operator.placement.engine import (
    PlacementEngine,
    PlacementPhase,
    PreemptionPolicy,
)
from tpu_operator.tenancy import ledger as ledger_mod
from tpu_operator.tenancy.fairshare import (
    FairSharePolicy,
    capacity_by_generation,
    parse_quota,
    policy_from_objects,
    resolve_tenant,
    usage_from_slices,
)

NS = "tpu-operator"


def quota(name, tenant, weight=1.0, guaranteed=None):
    return new_tpu_quota(
        name,
        {"tenant": tenant, "weight": weight, "guaranteed": guaranteed or {}},
    )


def tenant_slice(name, shape, tenant="", priority=0, policy="Never", created=""):
    obj = new_tpu_slice(
        name,
        {"placement": {
            "shape": shape, "priority": priority, "preemptionPolicy": policy,
        }},
    )
    obj["metadata"]["creationTimestamp"] = created or "2026-01-01T00:00:00Z"
    if tenant:
        obj["metadata"].setdefault("labels", {})[consts.TENANT_LABEL] = tenant
    return obj


def apply_plan(plan, nodes, slices):
    """Apply a plan back onto the in-memory objects, the way the
    controller would against the apiserver."""
    by_name = {n["metadata"]["name"]: n for n in nodes}
    for node_name, delta in plan.label_deltas.items():
        labels = by_name[node_name]["metadata"].setdefault("labels", {})
        for key, value in delta.items():
            if value is None:
                labels.pop(key, None)
            else:
                labels[key] = value
    for s in slices:
        if s["metadata"]["name"] in plan.statuses:
            s.setdefault("status", {})["placement"] = plan.statuses[s["metadata"]["name"]]


# ---------------------------------------------------------------------------
# TPUQuota parsing: malformed grants nothing
# ---------------------------------------------------------------------------


class TestParseQuota:
    def test_well_formed(self):
        entry = parse_quota(quota("q", "acme.search", weight=2.0, guaranteed={"v4": 8}))
        assert entry is not None
        assert entry.tenant == "acme.search"
        assert entry.weight == 2.0
        assert entry.guaranteed_map == {"v4": 8}
        assert entry.name == "q"

    def test_defaults(self):
        entry = parse_quota(new_tpu_quota("q", {"tenant": "acme"}))
        assert entry is not None and entry.weight == 1.0 and entry.guaranteed == ()

    def test_tenant_normalizes(self):
        assert parse_quota(quota("q", "  acme. ")).tenant == "acme"

    def test_malformed_specs_parse_to_none(self):
        bad = [
            new_tpu_quota("q"),                                   # no tenant
            quota("q", ""),                                       # empty tenant
            quota("q", "a", weight=0),                            # zero weight
            quota("q", "a", weight=-2.0),                         # negative weight
            quota("q", "a", weight="nan"),                        # non-finite weight
            quota("q", "a", weight="heavy"),                      # non-numeric weight
            quota("q", "a", guaranteed={"v4": -4}),               # negative chips
            quota("q", "a", guaranteed={"v4": True}),             # bool chips
            quota("q", "a", guaranteed={"v4": "lots"}),           # non-int chips
            {"metadata": {"name": "q"}, "spec": {"tenant": "a", "guaranteed": [4]}},
            {"metadata": {"name": "q"}, "spec": "yes please"},    # spec not a map
        ]
        for obj in bad:
            assert parse_quota(obj) is None, obj

    def test_policy_from_objects_fails_closed(self):
        cap = {"v4": 32}
        assert policy_from_objects([], cap) is None
        assert policy_from_objects([quota("q", "")], cap) is None
        # a malformed quota next to a valid one grants nothing itself
        policy = policy_from_objects([quota("bad", ""), quota("ok", "acme")], cap)
        assert policy is not None and set(policy.quotas) == {"acme"}

    def test_duplicate_tenants_resolve_to_first_source_object(self):
        policy = FairSharePolicy(
            [parse_quota(quota("zz", "acme", weight=5.0)),
             parse_quota(quota("aa", "acme", weight=2.0))],
            {"v4": 32},
        )
        assert policy.quotas["acme"].name == "aa"

    def test_resolve_tenant_precedence(self):
        obj = tenant_slice("s", "2x2x1", tenant="from-label")
        obj["spec"]["placement"]["tenant"] = "from-spec"
        assert resolve_tenant(obj) == "from-label"
        del obj["metadata"]["labels"][consts.TENANT_LABEL]
        assert resolve_tenant(obj) == "from-spec"
        del obj["spec"]["placement"]["tenant"]
        assert resolve_tenant(obj) == ""


# ---------------------------------------------------------------------------
# the DRF model: hierarchy rollup, ordering, legality
# ---------------------------------------------------------------------------


class TestHierarchy:
    def _policy(self):
        return policy_from_objects(
            [quota("q-org", "acme", weight=2.0, guaranteed={"v4": 16}),
             quota("q-team", "acme.search", weight=1.0, guaranteed={"v4": 8})],
            {"v4": 32},
        )

    def test_usage_rolls_up_to_ancestors(self):
        policy = self._policy()
        used = {"acme.search": {"v4": 6}, "acme.ads": {"v4": 4}}
        assert policy.level_usage(used, "acme") == {"v4": 10}
        assert policy.level_usage(used, "acme.search") == {"v4": 6}

    def test_headroom_is_the_tightest_declared_level(self):
        policy = self._policy()
        used = {"acme.search": {"v4": 6}, "acme.ads": {"v4": 4}}
        # own level leaves 2, the org level leaves 6: the min binds
        assert policy.guaranteed_headroom("acme.search", used, "v4") == 2
        # no team quota: only the org guarantee binds
        assert policy.guaranteed_headroom("acme.ads", used, "v4") == 6
        # nothing declared anywhere: an undeclared tenant only borrows
        assert policy.guaranteed_headroom("freeloader", used, "v4") == 0

    def test_weight_comes_from_the_nearest_declared_level(self):
        policy = self._policy()
        assert policy.weight("acme.search") == 1.0
        assert policy.weight("acme.ads") == 2.0  # inherits the org weight
        assert policy.weight("freeloader") == 1.0

    def test_borrowed_chips(self):
        policy = self._policy()
        used = {"acme.search": {"v4": 10}, "acme.ads": {"v4": 4}}
        assert policy.borrowed_chips("acme.search", used) == 2  # 10 held, 8 owned
        # declared ancestry but no own quota: everything it holds is borrowed
        assert policy.borrowed_chips("acme.ads", used) == 4

    def test_order_key_tiers(self):
        policy = self._policy()
        used = {"acme.search": {"v4": 6}, "beta": {"v4": 16}}
        demand = (("v4", 2),)

        def key(tenant, priority=0, created="t0", name="g"):
            return policy.order_key(tenant, used, demand, priority, created, name)

        # guaranteed headroom admits before any borrower, share regardless
        assert key("acme.search") < key("beta", priority=9)
        # among borrowers the smaller weighted dominant share goes first:
        # beta holds 16/32 at weight 1; acme holds 6/32 at weight 2
        big = (("v4", 30),)  # fits nobody's guarantee
        assert (policy.order_key("acme", used, big, 0, "t0", "g")
                < policy.order_key("beta", used, big, 0, "t0", "g"))
        # equal tenant: priority then FIFO
        assert key("beta", priority=5) < key("beta", priority=1)
        assert key("beta", created="t1") < key("beta", created="t2")

    def test_preemption_legality_table(self):
        policy = self._policy()
        demand = (("v4", 8),)
        # victim's owner is borrowing: fair game
        used = {"acme.search": {"v4": 10}, "beta": {"v4": 4}}
        assert policy.preemption_legal("beta", "acme.search", used, demand)
        # victim protected, preemptor lands inside ITS guarantee: legal
        used = {"acme.search": {"v4": 6}, "acme.ads": {"v4": 0}}
        assert policy.preemption_legal("acme.ads", "acme.search", used, (("v4", 2),))
        # victim protected, preemptor would borrow: NEVER (the pinned row)
        used = {"acme.search": {"v4": 6}, "beta": {"v4": 0}}
        assert not policy.preemption_legal("beta", "acme.search", used, demand)
        # a victim with no declared quota anywhere is never protected
        used = {"freeloader": {"v4": 2}, "beta": {"v4": 0}}
        assert policy.preemption_legal("beta", "freeloader", used, demand)


# ---------------------------------------------------------------------------
# the engine: zero TPUQuota is byte-identical, the economy reclaims
# borrowers and never protected gangs
# ---------------------------------------------------------------------------


class TestEngineTenancy:
    def test_no_quota_plans_byte_identical(self):
        nodes = make_torus_nodes((2, 2, 2))
        slices = [
            tenant_slice("a", "2x2x1", tenant="acme", created="2026-01-01T00:00:01Z"),
            tenant_slice("b", "2x2x2", tenant="beta", priority=3,
                         policy=PreemptionPolicy.PREEMPT_LOWER,
                         created="2026-01-01T00:00:02Z"),
            tenant_slice("c", "2x2x1", created="2026-01-01T00:00:03Z"),
        ]
        stock = PlacementEngine(copy.deepcopy(slices), copy.deepcopy(nodes)).plan()
        # malformed-only quota set: policy is None, the engine takes the
        # stock path — the fail-closed contract, not merely similar output
        policy = policy_from_objects([quota("junk", "")], capacity_by_generation(nodes))
        assert policy is None
        tenanted = PlacementEngine(
            copy.deepcopy(slices), copy.deepcopy(nodes), tenancy=policy
        ).plan()
        assert dataclasses.asdict(tenanted) == dataclasses.asdict(stock)

    def _seat(self, slices, nodes, policy=None):
        engine = PlacementEngine(slices, nodes, tenancy=policy)
        plan = engine.plan()
        apply_plan(plan, nodes, slices)
        return plan

    def test_borrow_then_reclaim(self):
        # 8-host v4 cube, 4 chips/host = 32 chips. team-a is guaranteed
        # 8 but seats a 16-chip gang: 8 chips borrowed. team-b's
        # priority-1 pod-filling gang reclaims them.
        nodes = make_torus_nodes((2, 2, 2))
        cap = capacity_by_generation(nodes)
        policy = policy_from_objects(
            [quota("qa", "team-a", guaranteed={"v4": 8}),
             quota("qb", "team-b", guaranteed={"v4": 16})], cap,
        )
        borrower = tenant_slice("gang-a", "2x2x1", tenant="team-a",
                                created="2026-01-01T00:00:01Z")
        self._seat([borrower], nodes, policy)
        reclaimer = tenant_slice("gang-b", "2x2x2", tenant="team-b", priority=1,
                                 policy=PreemptionPolicy.PREEMPT_LOWER,
                                 created="2026-01-01T00:00:02Z")
        plan = PlacementEngine([borrower, reclaimer], nodes, tenancy=policy).plan()
        assert plan.statuses["gang-b"]["phase"] == PlacementPhase.SCHEDULED
        assert plan.statuses["gang-a"]["phase"] == PlacementPhase.QUEUED
        assert plan.teardowns == ["gang-a"]
        assert len(plan.preemption_decisions) == 1
        decision = plan.preemption_decisions[0]
        assert decision["victim"] == "gang-a"
        assert decision["victimTenant"] == "team-a"
        assert decision["preemptor"] == "gang-b"
        assert decision["preemptorTenant"] == "team-b"
        assert decision["borrowed"] is True  # the ledger's reclaim marker

    def test_protected_gang_never_feeds_a_borrower(self):
        # the pinned acceptance row: team-a sits wholly inside its
        # guarantee; team-b (no guarantee) out-prioritizes it. The stock
        # engine evicts; the economy refuses.
        nodes = make_torus_nodes((2, 2, 2))
        cap = capacity_by_generation(nodes)
        policy = policy_from_objects(
            [quota("qa", "team-a", guaranteed={"v4": 16}),
             quota("qb", "team-b", weight=4.0)], cap,
        )
        protected = tenant_slice("gang-a", "2x2x1", tenant="team-a",
                                 created="2026-01-01T00:00:01Z")
        self._seat([protected], nodes, policy)
        contender = tenant_slice("gang-b", "2x2x2", tenant="team-b", priority=9,
                                 policy=PreemptionPolicy.PREEMPT_LOWER,
                                 created="2026-01-01T00:00:02Z")
        stock = PlacementEngine(
            copy.deepcopy([protected, contender]), copy.deepcopy(nodes)
        ).plan()
        assert stock.statuses["gang-b"]["phase"] == PlacementPhase.SCHEDULED
        assert stock.statuses["gang-a"]["phase"] == PlacementPhase.QUEUED
        fair = PlacementEngine([protected, contender], nodes, tenancy=policy).plan()
        assert fair.statuses["gang-a"]["phase"] == PlacementPhase.SCHEDULED
        assert fair.statuses["gang-b"]["phase"] != PlacementPhase.SCHEDULED
        assert fair.teardowns == []
        assert fair.preemption_decisions == []


# ---------------------------------------------------------------------------
# the ledger: bounded, auditable, fail-closed
# ---------------------------------------------------------------------------


class _Outage(FakeClient):
    """Every ConfigMap verb 500s — the apiserver outage the K003
    fail-closed contract is about."""

    def get(self, api_version, kind, name, namespace=None):
        if kind == "ConfigMap":
            raise errors.ApiError("cm get: 500")
        return super().get(api_version, kind, name, namespace)

    def patch(self, api_version, kind, name, patch, namespace=None):
        if kind == "ConfigMap":
            raise errors.ApiError("cm patch: 500")
        return super().patch(api_version, kind, name, patch, namespace)


class TestLedger:
    def test_missing_cm_is_a_fresh_ledger(self):
        ledger = ledger_mod.read_ledger(FakeClient(), NS)
        assert ledger == {"decisions": [], "placements": {}}

    def test_garbage_payload_starts_fresh_not_crash(self):
        client = FakeClient()
        client.create(new_object(
            "v1", "ConfigMap", consts.TENANCY_LEDGER_CONFIGMAP, NS,
            data={
                consts.TENANCY_DECISIONS_KEY: "not json {",
                consts.TENANCY_PLACEMENTS_KEY: json.dumps({"a": "not-a-ring"}),
            },
        ))
        ledger = ledger_mod.read_ledger(client, NS)
        assert ledger == {"decisions": [], "placements": {}}

    def test_unreadable_ledger_fails_closed(self):
        client = _Outage()
        assert ledger_mod.read_ledger(client, NS) is None
        ledger = {"decisions": [], "placements": {}}
        booked = ledger_mod.book(
            client, NS, ledger, decisions=[{"victim": "g"}], now=1.0
        )
        assert booked is False  # caller requeues; the eviction stays auditable

    def test_book_appends_and_bounds(self):
        client = FakeClient()
        ledger = ledger_mod.read_ledger(client, NS)
        decisions = [
            {"victim": f"g{i}", "victimTenant": "a", "preemptor": "p",
             "preemptorTenant": "b"}
            for i in range(consts.TENANCY_DECISIONS_LIMIT + 5)
        ]
        assert ledger_mod.book(client, NS, ledger, decisions=decisions, now=9.0)
        reread = ledger_mod.read_ledger(client, NS)
        assert len(reread["decisions"]) == consts.TENANCY_DECISIONS_LIMIT
        assert reread["decisions"][-1]["victim"] == decisions[-1]["victim"]
        assert reread["decisions"][-1]["at"] == 9.0
        newest = ledger_mod.last_decisions(reread, count=2)
        assert [d["victim"] for d in newest] == [
            decisions[-1]["victim"], decisions[-2]["victim"]
        ]

    def test_sample_ring_bounds_and_p99(self):
        client = FakeClient()
        ledger = ledger_mod.read_ledger(client, NS)
        samples = [("acme", float(s)) for s in range(
            consts.TENANCY_PLACEMENT_SAMPLES_LIMIT + 10
        )]
        assert ledger_mod.book(client, NS, ledger, samples=samples)
        reread = ledger_mod.read_ledger(client, NS)
        ring = reread["placements"]["acme"]
        assert len(ring) == consts.TENANCY_PLACEMENT_SAMPLES_LIMIT
        assert ledger_mod.place_p99(reread, "acme") >= ring[-2]
        assert ledger_mod.place_p99(reread, "nobody") is None


# ---------------------------------------------------------------------------
# the tenancy controller: accounting, Invalid fail-closed, O005 series
# retirement, fail-closed inputs
# ---------------------------------------------------------------------------


def _tenant_series(metric_name):
    for metric in prometheus_client.REGISTRY.collect():
        if metric.name == metric_name:
            return {s.labels.get("tenant"): s.value for s in metric.samples}
    return {}


class TestTenancyController:
    def _cluster(self):
        client = FakeClient()
        nodes = make_torus_nodes((2, 2, 1))  # 4 hosts x 4 chips = 16 v4 chips
        for node in nodes:
            client.create(node)
        from tpu_operator.nodepool import get_node_pools

        pool = get_node_pools(nodes)[0].name
        seated = tenant_slice("gang-a", "2x2x1", tenant="acme.search")
        seated["status"] = {"placement": {
            "phase": "Scheduled", "pool": pool,
            "nodes": [n["metadata"]["name"] for n in nodes],
        }}
        client.create(seated)
        return client, nodes

    def test_accounting_publishes_to_status(self):
        client, _ = self._cluster()
        client.create(quota("q-org", "acme", weight=2.0, guaranteed={"v4": 16}))
        client.create(quota("q-team", "acme.search", guaranteed={"v4": 8}))
        rec = TenancyReconciler(client, NS)
        rec.reconcile(TENANCY_REQUEST)
        org = client.get(TPU_QUOTA_API_VERSION, TPU_QUOTA_KIND, "q-org")["status"]
        team = client.get(TPU_QUOTA_API_VERSION, TPU_QUOTA_KIND, "q-team")["status"]
        assert org["state"] == "Active" and team["state"] == "Active"
        # the 16-chip gang rolls up to both levels; the team is 8 over
        # its own guarantee (borrowing), the org is exactly full
        assert org["tenancy"]["usedChips"] == 16
        assert org["tenancy"]["borrowedChips"] == 0
        assert org["tenancy"]["withinGuarantee"] is True
        assert team["tenancy"]["usedChips"] == 16
        assert team["tenancy"]["borrowedChips"] == 8
        assert team["tenancy"]["withinGuarantee"] is False
        assert team["tenancy"]["dominantShare"] == 1.0  # 16/16 v4 chips

    def test_malformed_quota_goes_invalid_and_grants_nothing(self):
        client, _ = self._cluster()
        client.create(quota("q-bad", "acme", weight=-1.0))
        rec = TenancyReconciler(client, NS)
        rec.reconcile(TENANCY_REQUEST)
        status = client.get(TPU_QUOTA_API_VERSION, TPU_QUOTA_KIND, "q-bad")["status"]
        assert status["state"] == "Invalid"
        assert "malformed" in status["tenancy"]["reason"]

    def test_deleted_quota_retires_its_series(self):
        client, _ = self._cluster()
        client.create(quota("q-team", "acme.search", guaranteed={"v4": 8}))
        rec = TenancyReconciler(client, NS)
        rec.reconcile(TENANCY_REQUEST)
        assert _tenant_series("tpu_operator_tenant_used_chips").get(
            "acme.search"
        ) == 16.0
        client.delete(TPU_QUOTA_API_VERSION, TPU_QUOTA_KIND, "q-team")
        client.delete("tpu.google.com/v1alpha1", "TPUSlice", "gang-a")
        rec.reconcile(TENANCY_REQUEST)
        # O005: a deleted tenant must not export its last value forever
        assert "acme.search" not in _tenant_series("tpu_operator_tenant_used_chips")
        assert "acme.search" not in _tenant_series("tpu_operator_tenant_fair_share")

    def test_unlistable_inputs_abort_the_pass(self):
        class Down(FakeClient):
            def list(self, api_version, kind, namespace=None,
                     label_selector=None, field_selector=None):
                raise errors.ApiError("apiserver down")

        result = TenancyReconciler(Down(), NS).reconcile(TENANCY_REQUEST)
        assert result.requeue is True


# ---------------------------------------------------------------------------
# placement controller: the pass books its economy into the ledger
# ---------------------------------------------------------------------------


class TestPlacementBooking:
    def test_pass_books_samples_and_decisions(self):
        client = FakeClient()
        for node in make_torus_nodes((2, 2, 2)):
            client.create(node)
        client.create(quota("qa", "team-a", guaranteed={"v4": 8}))
        client.create(quota("qb", "team-b", guaranteed={"v4": 16}))
        client.create(tenant_slice("gang-a", "2x2x1", tenant="team-a",
                                   created="2026-01-01T00:00:01Z"))
        rec = PlacementReconciler(client, NS)
        rec.reconcile(QUEUE_REQUEST)
        ledger = ledger_mod.read_ledger(client, NS)
        assert list(ledger["placements"]) == ["team-a"]  # time-to-place sample
        assert ledger["decisions"] == []
        client.create(tenant_slice("gang-b", "2x2x2", tenant="team-b", priority=1,
                                   policy=PreemptionPolicy.PREEMPT_LOWER,
                                   created="2026-01-01T00:00:02Z"))
        rec.reconcile(QUEUE_REQUEST)
        ledger = ledger_mod.read_ledger(client, NS)
        assert [d["victim"] for d in ledger["decisions"]] == ["gang-a"]
        assert ledger["decisions"][0]["borrowed"] is True
        assert "team-b" in ledger["placements"]


# ---------------------------------------------------------------------------
# the fleet-sim drills: tag isolation, no-quota identity, weight tracking
# ---------------------------------------------------------------------------


class TestFleetSimFairness:
    def test_tenant_tags_ride_a_separate_rng_stream(self):
        untagged = GangChurnSchedule(seed=7, ticks=40, arrivals_per_tick=1.0)
        tagged = GangChurnSchedule(seed=7, ticks=40, arrivals_per_tick=1.0,
                                   tenants=(("big", 3.0), ("small", 1.0)))
        assert [e[:5] for e in tagged.log] == untagged.log
        assert {e[5] for e in tagged.log} == {"big", "small"}

    def test_no_quota_report_identical_to_stock(self):
        from tpu_operator.planning.sim import FleetSimulator

        def run(tagged):
            sim = FleetSimulator(dims=(4, 4, 4), policy="defrag-aware",
                                 migration_cooldown_ticks=2, defrag_every=1)
            return sim.run(GangChurnSchedule(
                seed=11, ticks=40, arrivals_per_tick=0.8,
                shapes=(((2, 2, 1), 3.0), ((2, 2, 2), 1.0)),
                min_lifetime=10, max_lifetime=30,
                tenants=(("x", 1.0), ("y", 1.0)) if tagged else None,
            ), drain_ticks=10)

        with_tags = run(True)
        with_tags.pop("tenants")  # the only addition tags may make
        assert with_tags == run(False)

    def test_realized_share_tracks_quota_weights(self):
        from tpu_operator.planning.sim import FleetSimulator

        # equal offered demand, 3:1 weights, zero guarantees: the
        # steady-state occupancy split (tail half — the fill-from-empty
        # transient starts 50/50 regardless of policy) must track the
        # 75/25 weight-implied split within 10 points
        sim = FleetSimulator(dims=(8, 8, 8), policy="defrag-aware",
                             migration_cooldown_ticks=2, defrag_every=1,
                             quotas={"gold": (3.0, 0), "bronze": (1.0, 0)})
        report = sim.run(GangChurnSchedule(
            seed=20260807, ticks=200, arrivals_per_tick=5.0,
            shapes=(((2, 2, 1), 4.0), ((2, 2, 2), 3.0), ((4, 2, 2), 1.5)),
            min_lifetime=20, max_lifetime=50, priority_levels=1,
            tenants=(("gold", 1.0), ("bronze", 1.0)),
        ), drain_ticks=20)
        gold = report["tenants"]["gold"]["steady_share_pct"]
        bronze = report["tenants"]["bronze"]["steady_share_pct"]
        assert 65.0 <= gold <= 85.0, report["tenants"]
        assert abs(gold + bronze - 100.0) < 0.1
