"""Per-generation kernel autotuning: harness, cache, agent, controller,
floors folding, workload resolution, and the exporter floors hot-reload.

All on CPU (JAX_PLATFORMS=cpu): the harness tests inject synthetic
runners (controlled timings, no jax), one integration test runs the
real cpu-smoke sweep through interpret-mode pallas, and the control-
plane tests drive the FakeClient.
"""

from __future__ import annotations

import json

import pytest

from tpu_operator import consts
from tpu_operator.agents.autotune_agent import AutotuneAgent
from tpu_operator.api.clusterpolicy import ClusterPolicy, new_cluster_policy
from tpu_operator.controllers.autotune_controller import (
    AutotuneReconciler,
    libtpu_version_for,
)
from tpu_operator.kube.controller import Request
from tpu_operator.kube.fake import FakeClient
from tpu_operator.kube.objects import new_object
from tpu_operator.kube.sim import make_tpu_node
from tpu_operator.perf import FLOOR_FRACTION, default_floors, floors_for, floors_json
from tpu_operator.workloads import autotune
from tpu_operator.workloads.autotune import (
    ConfigResult,
    entry_key,
    entry_valid,
    merge_winner_floors,
    parse_entry,
    sweep,
    tuned_flash_blocks,
    tuned_matmul_unroll,
    winners_blob,
)

NS = "tpu-operator"
REQ = Request(name="cluster-policy")


# ---------------------------------------------------------------------------
# The generic harness.
# ---------------------------------------------------------------------------


class TestSweepHarness:
    def _runner_factory(self, costs):
        """make_runner over a {config-tuple: seconds} table; invalid
        configs raise like a real kernel would."""
        import time

        def make_runner(config):
            key = tuple(sorted(config.items()))
            if costs[key] is None:
                raise ValueError("invalid config")

            def run(seed, n):
                time.sleep(costs[key] * n)

            return run

        return make_runner

    def test_winner_is_fastest_and_default_grid_measured(self):
        costs = {
            (("block", 1),): 0.004,
            (("block", 2),): 0.001,
            (("block", 3),): 0.002,
        }
        records, winner = sweep(
            self._runner_factory(costs),
            [{"block": 1}, {"block": 2}, {"block": 3}],
            flops_per_iter=1e9, iters=2, reps=1, prune_ratio=100.0,
        )
        assert winner.config == {"block": 2}
        assert len(records) == 3
        assert all(not r.pruned and not r.error for r in records)
        # rates order inversely to cost
        by_block = {r.config["block"]: r.rate for r in records}
        assert by_block[2] > by_block[3] > by_block[1]

    def test_dominated_configs_pruned_but_recorded(self):
        costs = {
            (("block", 1),): 0.001,
            (("block", 2),): 0.02,  # 20x slower: dominated
        }
        records, winner = sweep(
            self._runner_factory(costs),
            [{"block": 1}, {"block": 2}],
            flops_per_iter=1e9, iters=2, reps=1, prune_ratio=1.35,
        )
        assert winner.config == {"block": 1}
        pruned = [r for r in records if r.pruned]
        assert [r.config for r in pruned] == [{"block": 2}]
        # pruned keeps the probe-derived estimate, never wins, not stable
        assert pruned[0].rate is not None and not pruned[0].stable

    def test_invalid_config_recorded_not_fatal(self):
        costs = {(("block", 1),): 0.001, (("block", 2),): None}
        records, winner = sweep(
            self._runner_factory(costs),
            [{"block": 1}, {"block": 2}],
            flops_per_iter=1e9, iters=2, reps=1,
        )
        assert winner.config == {"block": 1}
        errored = [r for r in records if r.error]
        assert len(errored) == 1 and "ValueError" in errored[0].error

    def test_all_configs_invalid_yields_no_winner(self):
        costs = {(("block", 1),): None}
        records, winner = sweep(
            self._runner_factory(costs), [{"block": 1}], 1e9, iters=1, reps=1
        )
        assert winner is None and records[0].error

    def test_flash_grid_drops_non_dividing_blocks(self):
        # grid enumeration: blocks not dividing the sequence never build
        # a runner (the records they'd produce don't exist)
        records, winner = autotune.sweep_flash(
            seq_len=256, heads=1, head_dim=64,
            configs=((128, 128), (96, 128), (128, 192)),
            iters=1, reps=1,
        )
        assert [r.config for r in records] == [{"block_q": 128, "block_k": 128}]
        assert winner is not None


class TestRealSweepCpu:
    def test_cpu_smoke_generation_sweep_is_complete(self):
        entry = autotune.run_generation_sweep("v5e", "test-v")
        assert entry["platform"] == "cpu"
        assert entry_valid(entry, "test-v")
        assert not entry_valid(entry, "other-v")  # toolchain bump invalidates
        # the winners blob round-trips the winning configs only
        blob = winners_blob({"v5e": entry})
        flash = blob["v5e"]["flash_fwd"]["s256_h1_d64"]
        assert set(flash) <= {"block_q", "block_k"}


# ---------------------------------------------------------------------------
# Cache keying.
# ---------------------------------------------------------------------------


def _entry(gen="v4", version="1.0.0", platform="tpu", matmul_rate=250.0,
           families=autotune.KERNEL_FAMILIES):
    flash = {"block_q": 512, "block_k": 1024, "rate": 90.0, "stable": True}
    results = {}
    for fam in families:
        if fam in ("flash_fwd", "flash_fwd_bwd"):
            results[fam] = {"s8192_h8_d128": {"winner": flash, "configs": [flash]}}
        elif fam == "matmul":
            results[fam] = {"m8192": {"winner": {"unroll": 16, "rate": matmul_rate,
                                                 "stable": True}, "configs": []}}
        else:
            results[fam] = {"m8192": {"winner": {"unroll": 8, "rate": matmul_rate * 2,
                                                 "stable": True}, "configs": []}}
    return {"generation": gen, "libtpu_version": version, "platform": platform,
            "results": results}


class TestCacheKeying:
    def test_complete_entry_valid(self):
        assert entry_valid(_entry(), "1.0.0")

    def test_libtpu_version_invalidates(self):
        assert not entry_valid(_entry(version="1.0.0"), "1.1.0")

    def test_missing_family_invalid(self):
        entry = _entry()
        del entry["results"]["int8"]
        assert not entry_valid(entry, "1.0.0")

    def test_winnerless_class_invalid(self):
        entry = _entry()
        entry["results"]["matmul"]["m8192"]["winner"] = None
        assert not entry_valid(entry, "1.0.0")

    def test_parse_entry_tolerates_garbage(self):
        assert parse_entry(None) is None
        assert parse_entry("") is None
        assert parse_entry("{not json") is None
        assert parse_entry('["list"]') is None
        assert parse_entry('{"a": 1}') == {"a": 1}


# ---------------------------------------------------------------------------
# Winners -> floors + winners blob.
# ---------------------------------------------------------------------------


class TestWinnerFolding:
    def test_tpu_entry_replaces_matmul_floor_and_adds_int8(self):
        floors = merge_winner_floors({"v4": _entry(matmul_rate=270.0)})
        assert floors["v4"]["matmul_tflops"] == round(270.0 * FLOOR_FRACTION, 1)
        assert floors["v4"]["int8_tops"] == round(540.0 * FLOOR_FRACTION, 1)
        # un-swept generations keep the scaled defaults, triad untouched
        assert floors["v5e"] == default_floors()["v5e"]
        assert floors["v4"]["triad_gbps"] == default_floors()["v4"]["triad_gbps"]

    def test_cpu_entry_never_folds_floors(self):
        floors = merge_winner_floors({"v4": _entry(platform="cpu", matmul_rate=0.01)})
        assert floors["v4"] == default_floors()["v4"]

    def test_winners_blob_strips_measurement_detail(self):
        blob = winners_blob({"v4": _entry()})
        assert blob["v4"]["flash_fwd"]["s8192_h8_d128"] == {
            "block_q": 512, "block_k": 1024,
        }
        assert blob["v4"]["matmul"]["m8192"] == {"unroll": 16}


# ---------------------------------------------------------------------------
# Workload config resolution.
# ---------------------------------------------------------------------------


class TestResolution:
    @pytest.fixture(autouse=True)
    def _gen(self, monkeypatch):
        monkeypatch.setenv("TPU_GENERATION", "v4")
        monkeypatch.delenv("PALLAS_AXON_TPU_GEN", raising=False)

    def _publish(self, monkeypatch, blob):
        monkeypatch.setenv(autotune.AUTOTUNE_ENV, json.dumps(blob))

    def test_exact_class_resolves(self, monkeypatch):
        self._publish(monkeypatch, winners_blob({"v4": _entry()}))
        assert tuned_flash_blocks(8192) == (512, 1024)
        assert tuned_matmul_unroll(8192) == 16
        assert tuned_matmul_unroll(8192, int8=True) == 8

    def test_nearest_class_resolves(self, monkeypatch):
        # a 4k caller rides the 8k winner (nearest swept class)
        self._publish(monkeypatch, winners_blob({"v4": _entry()}))
        assert tuned_flash_blocks(4096) == (512, 1024)
        assert tuned_matmul_unroll(2048) == 16

    def test_unswept_generation_falls_back(self, monkeypatch):
        self._publish(monkeypatch, winners_blob({"v5e": _entry(gen="v5e")}))
        assert tuned_flash_blocks(8192) == (1024, 1024)
        assert tuned_matmul_unroll(8192) == 8

    def test_no_env_falls_back(self, monkeypatch):
        monkeypatch.delenv(autotune.AUTOTUNE_ENV, raising=False)
        assert tuned_flash_blocks(8192) == (1024, 1024)
        assert tuned_flash_blocks(512, default=(256, 256)) == (256, 256)

    def test_malformed_env_falls_back(self, monkeypatch):
        monkeypatch.setenv(autotune.AUTOTUNE_ENV, "{broken")
        assert tuned_flash_blocks(8192) == (1024, 1024)
        monkeypatch.setenv(autotune.AUTOTUNE_ENV, json.dumps(
            {"v4": {"flash_fwd": {"s8192_h8_d128": {"block_q": "x", "block_k": 5}}}}
        ))
        assert tuned_flash_blocks(8192) == (1024, 1024)

    def test_flash_attention_consumes_winner(self, monkeypatch):
        """The kernel entry point actually runs the published blocks:
        pin via a winner whose blocks divide the test sequence and
        check numerics still hold (the resolution path is the same the
        burn-in/validator callers take)."""
        import jax.numpy as jnp
        import jax

        from tpu_operator.workloads.flashattention import flash_attention
        from tpu_operator.workloads.ringattention import dense_attention

        blob = {"v4": {"flash_fwd": {"s256_h2_d64": {"block_q": 64, "block_k": 128}}}}
        self._publish(monkeypatch, blob)
        keys = jax.random.split(jax.random.PRNGKey(0), 3)
        q, k, v = (jax.random.normal(key, (1, 256, 2, 64), dtype=jnp.bfloat16)
                   for key in keys)
        got = flash_attention(q, k, v, causal=True)  # blocks resolved
        want = dense_attention(q, k, v, causal=True)
        err = float(jnp.max(jnp.abs(got.astype(jnp.float32) - want.astype(jnp.float32))))
        assert err < 2e-2


# ---------------------------------------------------------------------------
# The agent.
# ---------------------------------------------------------------------------


class CountingClient:
    WRITE_VERBS = ("create", "patch", "patch_status", "update", "update_status",
                   "delete", "apply", "apply_set")

    def __init__(self, inner):
        self._inner = inner
        self.writes = 0

    def __getattr__(self, name):
        attr = getattr(self._inner, name)
        if name in self.WRITE_VERBS and callable(attr):
            def counted(*a, **kw):
                self.writes += 1
                return attr(*a, **kw)

            return counted
        return attr


def _tpu_node(name, accelerator="tpu-v4-podslice", topology="2x2x1", elected=False,
              extra=None):
    node = make_tpu_node(name, accelerator, topology)
    node["metadata"]["labels"][consts.TPU_PRESENT_LABEL] = "true"
    if elected:
        node["metadata"]["labels"][consts.AUTOTUNE_ELECTED_LABEL] = consts.AUTOTUNE_ELECTED
    node["metadata"]["labels"].update(extra or {})
    return node


def _fake_sweep(calls=None):
    def sweep_fn(gen, version):
        if calls is not None:
            calls.append(gen)
        return _entry(gen=gen, version=version)

    return sweep_fn


class TestAutotuneAgent:
    @pytest.fixture(autouse=True)
    def _pin_version(self, monkeypatch):
        monkeypatch.setenv("LIBTPU_VERSION", "1.0.0")

    def test_not_elected_is_noop(self):
        store = FakeClient()
        store.create(_tpu_node("n-0"))
        client = CountingClient(store)
        agent = AutotuneAgent(client, "n-0", NS, sweep_fn=_fake_sweep())
        assert agent.reconcile_once() == "not-elected"
        assert client.writes == 0

    def test_elected_sweeps_and_publishes(self):
        store = FakeClient()
        store.create(_tpu_node("n-0", elected=True))
        calls = []
        agent = AutotuneAgent(store, "n-0", NS, sweep_fn=_fake_sweep(calls))
        assert agent.reconcile_once() == "swept"
        assert calls == ["v4"]
        cm = store.get("v1", "ConfigMap", consts.AUTOTUNE_RESULTS_CONFIGMAP, NS)
        entry = json.loads(cm["data"][entry_key("v4")])
        assert entry["libtpu_version"] == "1.0.0"
        assert entry["swept_by"] == "n-0"

    def test_cache_hit_issues_zero_writes(self):
        store = FakeClient()
        store.create(_tpu_node("n-0", elected=True))
        store.create(new_object(
            "v1", "ConfigMap", consts.AUTOTUNE_RESULTS_CONFIGMAP, NS,
            data={entry_key("v4"): json.dumps(_entry())},
        ))
        client = CountingClient(store)
        calls = []
        agent = AutotuneAgent(client, "n-0", NS, sweep_fn=_fake_sweep(calls))
        assert agent.reconcile_once() == "cache-hit"
        assert calls == [] and client.writes == 0

    def test_libtpu_bump_re_sweeps(self, monkeypatch):
        store = FakeClient()
        store.create(_tpu_node("n-0", elected=True))
        store.create(new_object(
            "v1", "ConfigMap", consts.AUTOTUNE_RESULTS_CONFIGMAP, NS,
            data={entry_key("v4"): json.dumps(_entry(version="0.9.0"))},
        ))
        calls = []
        agent = AutotuneAgent(store, "n-0", NS, sweep_fn=_fake_sweep(calls))
        assert agent.reconcile_once() == "swept"
        assert calls == ["v4"]
        cm = store.get("v1", "ConfigMap", consts.AUTOTUNE_RESULTS_CONFIGMAP, NS)
        assert json.loads(cm["data"][entry_key("v4")])["libtpu_version"] == "1.0.0"

    def test_unrecognizable_generation_never_sweeps(self):
        store = FakeClient()
        node = new_object("v1", "Node", "bare-0", labels={
            consts.AUTOTUNE_ELECTED_LABEL: consts.AUTOTUNE_ELECTED,
        })
        store.create(node)
        agent = AutotuneAgent(store, "bare-0", NS, sweep_fn=_fake_sweep())
        assert agent.reconcile_once() == "no-generation"


# ---------------------------------------------------------------------------
# The controller.
# ---------------------------------------------------------------------------


def _cluster(nodes, entries=None, floors_cm=True, spec=None):
    store = FakeClient()
    for node in nodes:
        store.create(node)
    store.create(new_cluster_policy(spec=spec))
    if entries is not None:
        store.create(new_object(
            "v1", "ConfigMap", consts.AUTOTUNE_RESULTS_CONFIGMAP, NS,
            data={entry_key(g): json.dumps(e) for g, e in entries.items()},
        ))
    if floors_cm:
        store.create(new_object(
            "v1", "ConfigMap", consts.PERF_FLOORS_CONFIGMAP, NS,
            data={consts.PERF_FLOORS_KEY: floors_json()},
        ))
    return store


def _elected(store):
    return sorted(
        n["metadata"]["name"] for n in store.list("v1", "Node")
        if (n["metadata"].get("labels") or {}).get(consts.AUTOTUNE_ELECTED_LABEL)
        == consts.AUTOTUNE_ELECTED
    )


class TestAutotuneController:
    def test_elects_one_node_per_unswept_generation(self):
        store = _cluster([
            _tpu_node("v4-b"), _tpu_node("v4-a"),
            _tpu_node("v5e-0", "tpu-v5-lite-podslice", "2x4"),
        ])
        AutotuneReconciler(store, NS).reconcile(REQ)
        assert _elected(store) == ["v4-a", "v5e-0"]

    def test_out_of_service_nodes_never_elected(self):
        store = _cluster([
            _tpu_node("v4-a", extra={consts.TPU_PERF_LABEL: consts.PERF_DEGRADED}),
            _tpu_node("v4-b"),
        ])
        AutotuneReconciler(store, NS).reconcile(REQ)
        assert _elected(store) == ["v4-b"]

    def test_election_sticky_while_pending(self):
        # an election already held is kept even when a lexicographically
        # earlier node joins: re-electing mid-sweep would waste the run
        store = _cluster([_tpu_node("v4-z", elected=True), _tpu_node("v4-a")])
        AutotuneReconciler(store, NS).reconcile(REQ)
        assert _elected(store) == ["v4-z"]

    def test_dead_elected_node_re_elected(self):
        store = _cluster([
            _tpu_node("v4-z", elected=True,
                      extra={consts.TPU_HEALTH_LABEL: consts.HEALTH_DEGRADED}),
            _tpu_node("v4-a"),
        ])
        AutotuneReconciler(store, NS).reconcile(REQ)
        assert _elected(store) == ["v4-a"]

    def test_swept_generation_clears_and_never_re_elects(self):
        store = _cluster(
            [_tpu_node("v4-a", elected=True), _tpu_node("v4-b")],
            entries={"v4": _entry()},
        )
        client = CountingClient(store)
        rec = AutotuneReconciler(client, NS)
        rec.reconcile(REQ)
        assert _elected(store) == []
        # a joiner sorting first still isn't elected, and the settled
        # pass issues zero writes
        store.create(_tpu_node("a-joiner"))
        client.writes = 0
        rec.reconcile(REQ)
        assert _elected(store) == [] and client.writes == 0

    def test_fold_tightens_floors_and_publishes_winners(self):
        store = _cluster([_tpu_node("v4-a")], entries={"v4": _entry(matmul_rate=270.0)})
        rec = AutotuneReconciler(store, NS)
        rec.reconcile(REQ)
        floors = json.loads(store.get(
            "v1", "ConfigMap", consts.PERF_FLOORS_CONFIGMAP, NS
        )["data"][consts.PERF_FLOORS_KEY])
        assert floors["v4"]["matmul_tflops"] == round(270.0 * FLOOR_FRACTION, 1)
        winners = json.loads(store.get(
            "v1", "ConfigMap", consts.AUTOTUNE_RESULTS_CONFIGMAP, NS
        )["data"][consts.AUTOTUNE_WINNERS_KEY])
        assert winners["v4"]["flash_fwd"]["s8192_h8_d128"]["block_q"] == 512
        # per-generation data keys stay parseable beside floors.json
        per_gen = json.loads(store.get(
            "v1", "ConfigMap", consts.PERF_FLOORS_CONFIGMAP, NS
        )["data"]["v4"])
        assert per_gen == floors["v4"]

    def test_version_bump_reverts_floors_and_re_elects(self):
        store = _cluster([_tpu_node("v4-a")], entries={"v4": _entry(version="0.9.0")})
        rec = AutotuneReconciler(store, NS)
        rec.reconcile(REQ)
        # stale-toolchain entry: conservative defaults until re-swept
        floors = json.loads(store.get(
            "v1", "ConfigMap", consts.PERF_FLOORS_CONFIGMAP, NS
        )["data"][consts.PERF_FLOORS_KEY])
        assert floors["v4"] == default_floors()["v4"]
        assert _elected(store) == ["v4-a"]

    def test_settled_fold_issues_zero_writes(self):
        store = _cluster([_tpu_node("v4-a")], entries={"v4": _entry()})
        client = CountingClient(store)
        rec = AutotuneReconciler(client, NS)
        rec.reconcile(REQ)
        client.writes = 0
        rec.reconcile(REQ)
        assert client.writes == 0

    def test_missing_floors_cm_is_tolerated(self):
        store = _cluster([_tpu_node("v4-a")], entries={"v4": _entry()}, floors_cm=False)
        AutotuneReconciler(store, NS).reconcile(REQ)  # no raise, no create
        assert store.get_or_none("v1", "ConfigMap", consts.PERF_FLOORS_CONFIGMAP, NS) is None

    def test_disabled_spec_clears_elections(self):
        store = _cluster(
            [_tpu_node("v4-a", elected=True)],
            spec={"autotuner": {"enabled": False}},
        )
        AutotuneReconciler(store, NS).reconcile(REQ)
        assert _elected(store) == []

    def test_disabled_spec_retires_metrics(self):
        # run enabled first (roof series live, pending counted), then
        # disable: frozen gauges would alert on a sweep that will never
        # happen, and the roof series would export yesterday's number
        store = _cluster([_tpu_node("v4-a")], entries={"v4": _entry(matmul_rate=270.0)})
        rec = AutotuneReconciler(store, NS)
        rec.reconcile(REQ)
        assert ("v4",) in rec.metrics.autotune_matmul_roof._metrics
        cp = store.get("tpu.google.com/v1", "ClusterPolicy", "cluster-policy")
        cp["spec"] = {"autotuner": {"enabled": False}}
        store.update(cp)
        rec.reconcile(REQ)
        assert rec._roof_series == set()
        assert ("v4",) not in rec.metrics.autotune_matmul_roof._metrics

    def test_orphan_election_cleared_when_node_leaves_generation(self):
        # an elected node that LOSES its accelerator identity mid-sweep
        # (TFD misreport, de-TPU) drops out of the generation grouping —
        # the orphan sweep must still clear its label (and with it the
        # chip-claiming pod), not hold it forever
        broken = _tpu_node("v4-z", elected=True)
        store = _cluster([broken, _tpu_node("v4-a")])
        node = store.get("v1", "Node", "v4-z")
        for key in (consts.GKE_TPU_ACCELERATOR_LABEL, consts.GKE_TPU_TOPOLOGY_LABEL):
            node["metadata"]["labels"].pop(key, None)
        store.update(node)
        AutotuneReconciler(store, NS).reconcile(REQ)
        labels = store.get("v1", "Node", "v4-z")["metadata"].get("labels") or {}
        assert consts.AUTOTUNE_ELECTED_LABEL not in labels
        assert _elected(store) == ["v4-a"]

    def test_election_requires_schedulable_chip_claim(self):
        # the sweep pod claims spec.autotuner.chips google.com/tpu: a
        # node with fewer chips could never schedule it (Pending
        # forever), so it is never elected; exact-match hosts win over
        # surplus hosts (exclusive ownership beats co-tenancy)
        small = _tpu_node("v5e-small", "tpu-v5-lite-podslice", "2x2")  # 4 chips
        big = _tpu_node("v5e-big", "tpu-v5-lite-device", "4x8")  # 8/host
        store = _cluster([small, big], spec={"autotuner": {"chips": 8}})
        AutotuneReconciler(store, NS).reconcile(REQ)
        assert _elected(store) == ["v5e-big"]

    def test_no_schedulable_node_elects_nobody(self):
        store = _cluster([_tpu_node("v4-a")], spec={"autotuner": {"chips": 16}})
        AutotuneReconciler(store, NS).reconcile(REQ)
        assert _elected(store) == []

    def test_roof_series_retire_with_their_entry(self):
        store = _cluster([_tpu_node("v4-a")], entries={"v4": _entry(matmul_rate=270.0)})
        rec = AutotuneReconciler(store, NS)
        rec.reconcile(REQ)
        assert rec._roof_series == {"v4"}
        gauge = rec.metrics.autotune_matmul_roof
        assert ("v4",) in gauge._metrics
        # toolchain bump invalidates the entry -> the series goes too
        cm = store.get("v1", "ConfigMap", consts.AUTOTUNE_RESULTS_CONFIGMAP, NS)
        cm["data"][entry_key("v4")] = json.dumps(_entry(version="0.9.0"))
        store.update(cm)
        rec.reconcile(REQ)
        assert rec._roof_series == set()
        assert ("v4",) not in gauge._metrics

    def test_libtpu_version_tracks_image_tag(self):
        cp = ClusterPolicy.from_unstructured(new_cluster_policy(spec={
            "libtpu": {"repository": "gcr.io/x", "image": "libtpu", "version": "2.3.4"},
        }))
        assert libtpu_version_for(cp) == "2.3.4"


# ---------------------------------------------------------------------------
# Exporter floors hot-reload (satellite) + perf.py hardening (satellite).
# ---------------------------------------------------------------------------


class TestExporterFloorsHotReload:
    def _exporter(self, store, floors):
        import prometheus_client

        from tpu_operator.agents.metrics_exporter_agent import MetricsExporterAgent

        return MetricsExporterAgent(
            node_name="n-0", client=store, namespace=NS, generation="v4",
            floors=floors, breach_samples=1,
            registry=prometheus_client.CollectorRegistry(),
        )

    def test_updated_floor_changes_next_observe(self):
        """The satellite's regression: a tightened floor published to
        the ConfigMap changes the VERY NEXT observe_probe comparison —
        no DaemonSet restart."""
        store = FakeClient()
        store.create(_tpu_node("n-0"))
        stale = dict(floors_for("v4"))
        store.create(new_object(
            "v1", "ConfigMap", consts.PERF_FLOORS_CONFIGMAP, NS,
            data={consts.PERF_FLOORS_KEY: floors_json()},
        ))
        exporter = self._exporter(store, stale)
        # a sample above the stale floor: no breach
        probe = stale["matmul_tflops"] + 2.0
        assert exporter.observe_probe("matmul_tflops", probe) is False
        # the operator tightens the floor ABOVE that sample
        tightened = dict(stale, matmul_tflops=probe + 1.0)
        cm = store.get("v1", "ConfigMap", consts.PERF_FLOORS_CONFIGMAP, NS)
        cm["data"][consts.PERF_FLOORS_KEY] = json.dumps({"v4": tightened})
        store.update(cm)
        assert exporter.refresh_floors() is True
        assert exporter.floors["matmul_tflops"] == tightened["matmul_tflops"]
        assert exporter.observe_probe("matmul_tflops", probe) is True

    def test_refresh_tolerates_missing_cm_and_no_client(self):
        store = FakeClient()
        exporter = self._exporter(store, {"matmul_tflops": 100.0})
        assert exporter.refresh_floors() is False  # CM absent: keep floors
        assert exporter.floors == {"matmul_tflops": 100.0}
        exporter.client = None
        assert exporter.refresh_floors() is False
        exporter.client = store
        exporter.generation = ""
        assert exporter.refresh_floors() is False

    def test_refresh_noop_when_unchanged(self):
        store = FakeClient()
        store.create(new_object(
            "v1", "ConfigMap", consts.PERF_FLOORS_CONFIGMAP, NS,
            data={consts.PERF_FLOORS_KEY: floors_json()},
        ))
        exporter = self._exporter(store, dict(floors_for("v4")))
        assert exporter.refresh_floors() is False


class TestPerfFloorsHardening:
    """Satellite: floors_for must degrade to the static table (or {})
    on any malformed input — the exporter must never crash on a
    half-written ConfigMap."""

    def test_unknown_generation_returns_empty_not_raise(self):
        assert floors_for("v99") == {}
        assert floors_for("v99", floors_json()) == {}
        assert floors_for("", None) == {}

    def test_malformed_blob_degrades_to_static_table(self):
        for blob in ("{truncated", '"a string"', "[1,2]", "null", ""):
            assert floors_for("v4", blob) == default_floors()["v4"], blob

    def test_half_written_entry_degrades(self):
        # generation key present but not a dict -> {} (detection off)
        assert floors_for("v4", json.dumps({"v4": 17})) == {}
        # non-numeric probe values are skipped, numeric ones survive
        got = floors_for("v4", json.dumps({"v4": {"matmul_tflops": "x", "triad_gbps": 5}}))
        assert got == {"triad_gbps": 5.0}


# ---------------------------------------------------------------------------
# Rendering / wiring.
# ---------------------------------------------------------------------------


class TestAutotunerState:
    def _render(self, spec=None):
        from tpu_operator.catalog import InfoCatalog
        from tpu_operator.states import new_cluster_policy_states

        cp = ClusterPolicy.from_unstructured(new_cluster_policy(spec=spec))
        catalog = InfoCatalog(cluster_policy=cp)
        state = {s.name: s for s in new_cluster_policy_states()}["state-autotuner"]
        return state.renderer.render_objects(state.get_render_data(catalog))

    def test_daemonset_gates_on_election_label(self):
        ds = [o for o in self._render() if o["kind"] == "DaemonSet"][0]
        selector = ds["spec"]["template"]["spec"]["nodeSelector"]
        assert selector[consts.AUTOTUNE_ELECTED_LABEL] == consts.AUTOTUNE_ELECTED
        assert selector["tpu.google.com/tpu.deploy.autotuner"] == "true"

    def test_daemonset_claims_chips_not_privilege(self):
        ds = [o for o in self._render() if o["kind"] == "DaemonSet"][0]
        ctr = ds["spec"]["template"]["spec"]["containers"][0]
        assert ctr["resources"]["limits"][consts.TPU_RESOURCE_NAME] == "4"
        assert "securityContext" not in ctr
        assert "volumes" not in ds["spec"]["template"]["spec"]

    def test_libtpu_version_env_pins_image_tag(self):
        ds = [o for o in self._render(spec={
            "libtpu": {"repository": "gcr.io/x", "image": "libtpu", "version": "9.9.9"},
        }) if o["kind"] == "DaemonSet"][0]
        env = {e["name"]: e.get("value") for e in
               ds["spec"]["template"]["spec"]["containers"][0]["env"]}
        assert env["LIBTPU_VERSION"] == "9.9.9"

    def test_chips_knob(self):
        ds = [o for o in self._render(spec={"autotuner": {"chips": 8}})
              if o["kind"] == "DaemonSet"][0]
        ctr = ds["spec"]["template"]["spec"]["containers"][0]
        assert ctr["resources"]["limits"][consts.TPU_RESOURCE_NAME] == "8"

    def test_winners_env_reaches_consumers(self):
        """The winners blob is wired as optional TPU_AUTOTUNE_JSON into
        the validator + exporter DaemonSets and the gang worker pods."""
        import os

        import yaml

        from tpu_operator.catalog import InfoCatalog
        from tpu_operator.states import new_cluster_policy_states

        cp = ClusterPolicy.from_unstructured(new_cluster_policy())
        catalog = InfoCatalog(cluster_policy=cp)
        states = {s.name: s for s in new_cluster_policy_states()}
        for name in ("state-operator-validation", "state-metrics-exporter"):
            state = states[name]
            rendered = yaml.safe_dump_all(
                state.renderer.render_objects(state.get_render_data(catalog))
            )
            assert "TPU_AUTOTUNE_JSON" in rendered, name
            assert consts.AUTOTUNE_RESULTS_CONFIGMAP in rendered, name
        gang_tpl = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tpu_operator", "manifests", "slice-gang", "0100_worker_pod.yaml",
        )
        with open(gang_tpl) as f:
            assert "TPU_AUTOTUNE_JSON" in f.read()
