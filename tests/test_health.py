"""Node health monitoring & auto-remediation subsystem tests.

Three layers under test (ISSUE 1 tentpole):
  1. the health agent's probes + verdict publication (label, annotation,
     TPUHealthy condition, Events, verdicts file),
  2. the device plugin consuming verdicts: unhealthy chips flip to
     Unhealthy in ListAndWatch over the real gRPC socket,
  3. the remediation controller's bounded repair FSM — driven end to end
     over the wire (fault-injection drill on the served fake apiserver):
     cordon → PDB-honoring eviction → libtpu reinstall → revalidate →
     uncordon, and retry-budget exhaustion → quarantined — with Events
     and both new operator metrics observable.
"""

import json
import os
import time

import grpc
import prometheus_client
import pytest

from tpu_operator import consts
from tpu_operator.agents.dpapi import deviceplugin_pb2 as pb
from tpu_operator.agents.device_plugin_agent import TPUDevicePlugin
from tpu_operator.agents.health_monitor_agent import HealthMonitorAgent
from tpu_operator.api.clusterpolicy import (
    CLUSTER_POLICY_API_VERSION,
    CLUSTER_POLICY_KIND,
    HealthMonitorSpec,
    new_cluster_policy,
)
from tpu_operator.controllers.health_controller import (
    HealthReconciler,
    NodeRepairManager,
    RepairState,
)
from tpu_operator.kube.controller import Request
from tpu_operator.kube.fake import FakeClient
from tpu_operator.kube.http_client import HttpClient
from tpu_operator.kube.httpserver import FakeApiServer
from tpu_operator.kube.objects import new_object
from tpu_operator.kube.sim import make_tpu_node

NS = "tpu-operator"


def make_agent(client, tmp_path, monkeypatch, chips=4, node="tpu-0", **kw):
    """An agent whose probe surfaces are all sandboxed under tmp_path and
    initially HEALTHY: chips device nodes, the libtpu ready marker, the
    plugin socket file. Tests degrade individual surfaces from there."""
    scan = tmp_path / "scan"
    (scan / "dev").mkdir(parents=True, exist_ok=True)
    for i in range(chips):
        (scan / "dev" / f"accel{i}").touch()
    monkeypatch.setenv("TPUINFO_SCAN_ROOT", str(scan))
    install = tmp_path / "install"
    install.mkdir(exist_ok=True)
    (install / consts.LIBTPU_CTR_READY_FILE).touch()
    sockets = tmp_path / "sockets"
    sockets.mkdir(exist_ok=True)
    (sockets / "tpu-device-plugin.sock").touch()
    kw.setdefault("active_probes", "off")
    return HealthMonitorAgent(
        client,
        node,
        install_dir=str(install),
        socket_dir=str(sockets),
        health_dir=str(tmp_path / "health"),
        **kw,
    )


def node_labels(client, name="tpu-0"):
    return client.get("v1", "Node", name)["metadata"].get("labels") or {}


def events_by_reason(client):
    return {e.get("reason") for e in client.list("v1", "Event")}


def metric(name: str):
    return prometheus_client.REGISTRY.get_sample_value(name)


class TestHealthMonitorAgent:
    def test_healthy_node_publishes_everything(self, tmp_path, monkeypatch):
        client = FakeClient()
        client.create(make_tpu_node("tpu-0", chips=4))
        agent = make_agent(client, tmp_path, monkeypatch)
        assert agent.apply_once() is True
        labels = node_labels(client)
        assert labels[consts.TPU_HEALTH_LABEL] == consts.HEALTH_HEALTHY
        node = client.get("v1", "Node", "tpu-0")
        chips = json.loads(
            node["metadata"]["annotations"][consts.TPU_HEALTH_CHIPS_ANNOTATION]
        )
        assert chips == {f"accel{i}": "Healthy" for i in range(4)}
        (cond,) = [
            c
            for c in node["status"]["conditions"]
            if c["type"] == consts.TPU_HEALTH_CONDITION
        ]
        assert cond["status"] == "True"
        with open(tmp_path / "health" / consts.HEALTH_VERDICTS_FILE) as f:
            verdicts = json.load(f)
        assert verdicts["verdict"] == consts.HEALTH_HEALTHY
        # a first-ever healthy verdict is not a transition: no Event noise
        assert "TPUHealthRestored" not in events_by_reason(client)
        # steady state: second pass changes nothing
        assert agent.apply_once() is False

    def test_yanked_chip_degrades_with_per_chip_verdict(self, tmp_path, monkeypatch):
        client = FakeClient()
        client.create(make_tpu_node("tpu-0", chips=4))
        agent = make_agent(client, tmp_path, monkeypatch)
        agent.apply_once()
        os.unlink(tmp_path / "scan" / "dev" / "accel2")  # chip disappears
        assert agent.apply_once() is True
        node = client.get("v1", "Node", "tpu-0")
        assert node["metadata"]["labels"][consts.TPU_HEALTH_LABEL] == consts.HEALTH_DEGRADED
        chips = json.loads(
            node["metadata"]["annotations"][consts.TPU_HEALTH_CHIPS_ANNOTATION]
        )
        assert chips["accel2"] == "Unhealthy"
        assert chips["accel0"] == "Healthy"
        (cond,) = [
            c
            for c in node["status"]["conditions"]
            if c["type"] == consts.TPU_HEALTH_CONDITION
        ]
        assert cond["status"] == "False" and "accel2" in cond["message"]
        assert "TPUHealthDegraded" in events_by_reason(client)
        # the shared verdicts file carries the per-chip map for the plugin
        with open(tmp_path / "health" / consts.HEALTH_VERDICTS_FILE) as f:
            assert json.load(f)["chips"]["accel2"] == "Unhealthy"

    def test_recovery_restores_health_with_event(self, tmp_path, monkeypatch):
        client = FakeClient()
        client.create(make_tpu_node("tpu-0", chips=2))
        agent = make_agent(client, tmp_path, monkeypatch, chips=2, expected_chips=2)
        agent.apply_once()
        os.unlink(tmp_path / "scan" / "dev" / "accel1")
        agent.apply_once()
        assert node_labels(client)[consts.TPU_HEALTH_LABEL] == consts.HEALTH_DEGRADED
        (tmp_path / "scan" / "dev" / "accel1").touch()
        assert agent.apply_once() is True
        assert node_labels(client)[consts.TPU_HEALTH_LABEL] == consts.HEALTH_HEALTHY
        assert "TPUHealthRestored" in events_by_reason(client)

    def test_missing_libtpu_marker_and_socket_degrade(self, tmp_path, monkeypatch):
        client = FakeClient()
        client.create(make_tpu_node("tpu-0", chips=2))
        agent = make_agent(client, tmp_path, monkeypatch, chips=2, expected_chips=2)
        os.unlink(tmp_path / "install" / consts.LIBTPU_CTR_READY_FILE)
        os.unlink(tmp_path / "sockets" / "tpu-device-plugin.sock")
        agent.apply_once()
        node = client.get("v1", "Node", "tpu-0")
        assert node["metadata"]["labels"][consts.TPU_HEALTH_LABEL] == consts.HEALTH_DEGRADED
        (cond,) = [
            c
            for c in node["status"]["conditions"]
            if c["type"] == consts.TPU_HEALTH_CONDITION
        ]
        assert "libtpu" in cond["message"] and "socket" in cond["message"]

    def test_indeterminate_probe_changes_nothing(self, tmp_path, monkeypatch):
        client = FakeClient()
        client.create(make_tpu_node("tpu-0", chips=2))
        agent = make_agent(client, tmp_path, monkeypatch, chips=2, expected_chips=2)
        agent.apply_once()
        before = node_labels(client)[consts.TPU_HEALTH_LABEL]

        def boom():
            raise RuntimeError("probe machinery down")

        monkeypatch.setattr("tpu_operator.native.tpuinfo.probe", boom)
        assert agent.apply_once() is False
        assert node_labels(client)[consts.TPU_HEALTH_LABEL] == before

    def test_timeslicing_replicas_do_not_inflate_expected_chips(self, tmp_path, monkeypatch):
        """Expected chips come from the TFD label / accelerator catalog,
        never the google.com/tpu allocatable: device-plugin time-slicing
        (replicas=N) inflates allocatable, and counting it would brand a
        healthy shared node degraded and auto-repair it."""
        client = FakeClient()
        node = make_tpu_node("tpu-0", chips=4)
        node["status"]["allocatable"]["google.com/tpu"] = "8"  # replicas: 2
        client.create(node)
        agent = make_agent(client, tmp_path, monkeypatch, chips=4)
        agent.apply_once()
        assert node_labels(client)[consts.TPU_HEALTH_LABEL] == consts.HEALTH_HEALTHY

    def test_expected_chips_from_allocatable(self, tmp_path, monkeypatch):
        """A node advertising 4 chips whose probe only sees 2 is degraded
        even though both present chips look fine."""
        client = FakeClient()
        client.create(make_tpu_node("tpu-0", chips=4))
        agent = make_agent(client, tmp_path, monkeypatch, chips=2)
        agent.apply_once()
        chips = json.loads(
            client.get("v1", "Node", "tpu-0")["metadata"]["annotations"][
                consts.TPU_HEALTH_CHIPS_ANNOTATION
            ]
        )
        assert chips == {
            "accel0": "Healthy",
            "accel1": "Healthy",
            "accel2": "Unhealthy",
            "accel3": "Unhealthy",
        }


class TestDevicePluginHealthIntegration:
    """Layer 2: the plugin's health loop consumes the agent's verdicts
    and its own re-probe, flipping devices in ListAndWatch."""

    def dial_stream(self, plugin):
        channel = grpc.insecure_channel(f"unix://{plugin.socket_path}")
        law = channel.unary_stream(
            "/v1beta1.DevicePlugin/ListAndWatch",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=pb.ListAndWatchResponse.FromString,
        )
        return channel, law(pb.Empty())

    def test_yanked_device_reported_unhealthy_not_dropped(self, tmp_path):
        """Satellite bugfix: a device that vanishes must be re-reported
        as Unhealthy (kubelet keeps it in capacity, stops allocating),
        not silently left Healthy — and only CHANGES are published."""
        plugin = TPUDevicePlugin(
            socket_dir=str(tmp_path),
            devices=["/dev/accel0", "/dev/accel1"],
            health_dir=str(tmp_path / "nohealth"),
        )
        plugin._last_health = plugin.current_health()
        assert plugin.health_tick() is False  # steady state: no publish
        plugin._devices_override = ["/dev/accel0"]  # accel1 yanked
        assert plugin.health_tick() is True
        assert plugin._last_health == {"accel0": "Healthy", "accel1": "Unhealthy"}
        assert plugin.health_tick() is False  # change published exactly once
        plugin._devices_override = ["/dev/accel0", "/dev/accel1"]  # restored
        assert plugin.health_tick() is True
        assert plugin._last_health == {"accel0": "Healthy", "accel1": "Healthy"}

    def test_unhealthy_chip_flips_in_listandwatch_over_the_wire(self, tmp_path):
        """Acceptance: agent marks a chip unhealthy (verdicts file) → the
        plugin's next health tick re-publishes → the kubelet-side stream
        sees the device flip to Unhealthy, then recover."""
        health_dir = tmp_path / "health"
        health_dir.mkdir()
        plugin = TPUDevicePlugin(
            socket_dir=str(tmp_path),
            devices=["/dev/accel0", "/dev/accel1"],
            health_dir=str(health_dir),
        )
        try:
            plugin._last_health = plugin.current_health()
            plugin.serve()
            channel, stream = self.dial_stream(plugin)
            first = next(stream)
            assert [(d.ID, d.health) for d in first.devices] == [
                ("accel0", "Healthy"),
                ("accel1", "Healthy"),
            ]
            # the health agent's verdict lands in the shared file
            with open(health_dir / consts.HEALTH_VERDICTS_FILE, "w") as f:
                json.dump({"verdict": "degraded",
                           "chips": {"accel0": "Healthy", "accel1": "Unhealthy"}}, f)
            assert plugin.health_tick() is True
            update = next(stream)
            assert [(d.ID, d.health) for d in update.devices] == [
                ("accel0", "Healthy"),
                ("accel1", "Unhealthy"),
            ]
            # heal: verdicts go back to healthy
            with open(health_dir / consts.HEALTH_VERDICTS_FILE, "w") as f:
                json.dump({"verdict": "healthy",
                           "chips": {"accel0": "Healthy", "accel1": "Healthy"}}, f)
            assert plugin.health_tick() is True
            healed = next(stream)
            assert all(d.health == "Healthy" for d in healed.devices)
            channel.close()
        finally:
            plugin.stop()

    def test_torn_or_missing_verdicts_file_is_ignored(self, tmp_path):
        health_dir = tmp_path / "health"
        health_dir.mkdir()
        plugin = TPUDevicePlugin(
            socket_dir=str(tmp_path), devices=["/dev/accel0"], health_dir=str(health_dir)
        )
        assert plugin.current_health() == {"accel0": "Healthy"}
        (health_dir / consts.HEALTH_VERDICTS_FILE).write_text("{not json")
        assert plugin.current_health() == {"accel0": "Healthy"}

    def test_stale_verdicts_file_is_ignored(self, tmp_path):
        """A dead/disabled health agent must not pin chips Unhealthy
        forever: verdicts older than the TTL are dropped and the plugin's
        own device probe stands."""
        health_dir = tmp_path / "health"
        health_dir.mkdir()
        path = health_dir / consts.HEALTH_VERDICTS_FILE
        path.write_text(json.dumps({"chips": {"accel0": "Unhealthy"}}))
        plugin = TPUDevicePlugin(
            socket_dir=str(tmp_path), devices=["/dev/accel0"], health_dir=str(health_dir)
        )
        assert plugin.current_health() == {"accel0": "Unhealthy"}  # fresh: honored
        old = time.time() - 2 * plugin.VERDICTS_TTL_SECONDS
        os.utime(path, (old, old))  # the agent stopped rewriting it
        assert plugin.current_health() == {"accel0": "Healthy"}

    def test_replicated_devices_inherit_chip_health(self, tmp_path):
        plugin = TPUDevicePlugin(
            socket_dir=str(tmp_path),
            devices=["/dev/accel0"],
            config={"replicas": 2},
            health_dir=str(tmp_path / "nohealth"),
        )
        plugin.current_health()
        plugin._devices_override = []
        resp = plugin._device_list(plugin.current_health())
        assert [(d.ID, d.health) for d in resp.devices] == [
            ("accel0-rep0", "Unhealthy"),
            ("accel0-rep1", "Unhealthy"),
        ]


class TestRemediationFSM:
    """Layer 3 unit coverage on the fake client (the over-the-wire drill
    lives in TestHealthEndToEnd)."""

    def seed(self, client, health=consts.HEALTH_DEGRADED, name="tpu-0", pool=None):
        node = make_tpu_node(name, nodepool=pool or "tpu-pool")
        node["metadata"]["labels"][consts.TPU_PRESENT_LABEL] = "true"
        if health:
            node["metadata"]["labels"][consts.TPU_HEALTH_LABEL] = health
        client.create(node)
        return node

    def spec(self, **remediation):
        remediation.setdefault("enable", True)
        remediation.setdefault("gracePeriodSeconds", 0)
        return HealthMonitorSpec.from_dict({"remediation": remediation})

    def test_degraded_node_enters_repair_and_cordons(self):
        client = FakeClient()
        self.seed(client)
        mgr = NodeRepairManager(client, NS)
        mgr.apply_state(self.spec())
        assert node_labels(client)[consts.REPAIR_STATE_LABEL] == RepairState.CORDON_REQUIRED
        mgr.apply_state(self.spec())
        node = client.get("v1", "Node", "tpu-0")
        assert node["spec"]["unschedulable"] is True
        assert node["metadata"]["labels"][consts.REPAIR_STATE_LABEL] == RepairState.EVICTION_REQUIRED
        assert node["metadata"]["annotations"][consts.REPAIR_RETRIES_ANNOTATION] == "1"

    def test_grace_period_spares_provisioning_nodes(self):
        """A freshly degraded node (e.g. joining: libtpu still installing,
        plugin not registered) is left alone until the degradation
        outlives the grace period — no mid-install cordon, no budget
        burn. An old degradation repairs immediately."""
        client = FakeClient()
        self.seed(client)
        mgr = NodeRepairManager(client, NS)
        spec = self.spec(gracePeriodSeconds=3600)
        mgr.apply_state(spec)
        node = client.get("v1", "Node", "tpu-0")
        # no repair started; the controller stamped health.since and waits
        assert consts.REPAIR_STATE_LABEL not in node["metadata"]["labels"]
        assert not node["spec"].get("unschedulable")
        assert consts.TPU_HEALTH_SINCE_ANNOTATION in node["metadata"]["annotations"]
        assert consts.REPAIR_RETRIES_ANNOTATION not in node["metadata"]["annotations"]
        mgr.apply_state(spec)  # still inside grace
        assert consts.REPAIR_STATE_LABEL not in node_labels(client)
        # age the degradation past the grace window: repair begins
        node = client.get("v1", "Node", "tpu-0")
        node["metadata"]["annotations"][consts.TPU_HEALTH_SINCE_ANNOTATION] = str(
            int(time.time()) - 7200
        )
        client.update(node)
        mgr.apply_state(spec)
        assert node_labels(client)[consts.REPAIR_STATE_LABEL] == RepairState.CORDON_REQUIRED

    def test_healthy_node_untouched(self):
        client = FakeClient()
        self.seed(client, health=consts.HEALTH_HEALTHY)
        NodeRepairManager(client, NS).apply_state(self.spec())
        assert consts.REPAIR_STATE_LABEL not in node_labels(client)

    def test_revalidate_timeout_reenters_without_orphaning_cordon(self):
        """A revalidation timeout must keep the node under FSM ownership
        (straight back to cordon-required): dropping to no-state while
        cordoned would orphan the cordon if the heal lands in the gap."""
        client = FakeClient()
        self.seed(client)
        node = client.get("v1", "Node", "tpu-0")
        node["metadata"]["labels"][consts.REPAIR_STATE_LABEL] = RepairState.REVALIDATE_REQUIRED
        node["metadata"].setdefault("annotations", {})[
            consts.REPAIR_STATE_SINCE_ANNOTATION
        ] = str(int(time.time()) - 100)
        node["metadata"]["annotations"][consts.REPAIR_RETRIES_ANNOTATION] = "1"
        node["spec"]["unschedulable"] = True
        client.update(node)
        # a Running driver pod (the libtpu DaemonSet's) so the reinstall
        # step of the re-entered attempt can advance
        from tpu_operator.upgrade.fsm import (
            DRIVER_POD_COMPONENT,
            DRIVER_POD_COMPONENT_LABEL,
        )

        client.create(new_object(
            "v1", "Pod", "libtpu-tpu-0", NS,
            labels={DRIVER_POD_COMPONENT_LABEL: DRIVER_POD_COMPONENT},
            spec={"nodeName": "tpu-0", "containers": []},
            status={"phase": "Running"},
        ))
        mgr = NodeRepairManager(client, NS)
        mgr.apply_state(self.spec(retryLimit=3, timeoutSeconds=1))
        labels = node_labels(client)
        assert labels[consts.REPAIR_STATE_LABEL] == RepairState.CORDON_REQUIRED
        # budget burned atomically with the state write
        node = client.get("v1", "Node", "tpu-0")
        assert node["metadata"]["annotations"][consts.REPAIR_RETRIES_ANNOTATION] == "2"
        # now the heal lands: the FSM walks the node out and uncordons it
        # (the test plays the DS controller, recreating the driver pod
        # the reinstall entry-action deletes)
        node["metadata"]["labels"][consts.TPU_HEALTH_LABEL] = consts.HEALTH_HEALTHY
        client.update(node)
        for _ in range(6):
            mgr.apply_state(self.spec(retryLimit=3, timeoutSeconds=1))
            if client.get_or_none("v1", "Pod", "libtpu-tpu-0", NS) is None:
                client.create(new_object(
                    "v1", "Pod", "libtpu-tpu-0", NS,
                    labels={DRIVER_POD_COMPONENT_LABEL: DRIVER_POD_COMPONENT},
                    spec={"nodeName": "tpu-0", "containers": []},
                    status={"phase": "Running"},
                ))
        node = client.get("v1", "Node", "tpu-0")
        assert consts.REPAIR_STATE_LABEL not in node["metadata"]["labels"]
        assert not node["spec"].get("unschedulable")

    def test_retry_budget_exhaustion_quarantines(self):
        client = FakeClient()
        node = self.seed(client)
        node = client.get("v1", "Node", "tpu-0")
        node["metadata"].setdefault("annotations", {})[
            consts.REPAIR_RETRIES_ANNOTATION
        ] = "3"
        client.update(node)
        NodeRepairManager(client, NS).apply_state(self.spec(retryLimit=3))
        node = client.get("v1", "Node", "tpu-0")
        assert node["metadata"]["labels"][consts.REPAIR_STATE_LABEL] == RepairState.QUARANTINED
        assert node["spec"]["unschedulable"] is True
        # quarantine is terminal: further passes leave it parked
        NodeRepairManager(client, NS).apply_state(self.spec(retryLimit=3))
        assert node_labels(client)[consts.REPAIR_STATE_LABEL] == RepairState.QUARANTINED

    def test_slice_gang_marked_degraded_and_cleared(self):
        """One sick host poisons its whole multi-host gang (fail fast for
        gang-scheduled workloads); healing clears every member."""
        client = FakeClient()
        self.seed(client, name="v5e-0", pool="pool-a")
        self.seed(client, health=consts.HEALTH_HEALTHY, name="v5e-1", pool="pool-a")
        self.seed(client, health=consts.HEALTH_HEALTHY, name="other-0", pool="pool-b")
        self.seed(client, health=consts.HEALTH_HEALTHY, name="other-1", pool="pool-b")
        mgr = NodeRepairManager(client, NS)
        mgr.apply_state(self.spec())
        assert (
            node_labels(client, "v5e-1")[consts.TPU_SLICE_HEALTH_LABEL]
            == consts.HEALTH_DEGRADED
        )
        assert consts.TPU_SLICE_HEALTH_LABEL not in node_labels(client, "other-0")
        # heal the sick host: the gang label clears everywhere
        node = client.get("v1", "Node", "v5e-0")
        node["metadata"]["labels"][consts.TPU_HEALTH_LABEL] = consts.HEALTH_HEALTHY
        del node["metadata"]["labels"][consts.REPAIR_STATE_LABEL]
        client.update(node)
        mgr.apply_state(self.spec())
        for name in ("v5e-0", "v5e-1"):
            assert consts.TPU_SLICE_HEALTH_LABEL not in node_labels(client, name)

    def test_remediation_disabled_strips_and_uncordons(self):
        client = FakeClient()
        self.seed(client)
        client.create(new_cluster_policy(spec={
            "healthMonitor": {"remediation": {"enable": True, "gracePeriodSeconds": 0}}}))
        r = HealthReconciler(client, NS)
        r.reconcile(Request(name="cluster-policy"))
        r.reconcile(Request(name="cluster-policy"))
        assert client.get("v1", "Node", "tpu-0")["spec"]["unschedulable"] is True
        cp = client.get(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND, "cluster-policy")
        cp["spec"]["healthMonitor"] = {"remediation": {"enable": False}}
        client.update(cp)
        r.reconcile(Request(name="cluster-policy"))
        node = client.get("v1", "Node", "tpu-0")
        assert consts.REPAIR_STATE_LABEL not in node["metadata"]["labels"]
        assert not node["spec"].get("unschedulable")
        # "re-enabling starts clean": the retry budget is wiped too
        assert consts.REPAIR_RETRIES_ANNOTATION not in (
            node["metadata"].get("annotations") or {}
        )

    def test_driver_pod_sweep_requires_daemonset_owner(self):
        """TPUOP-K001 regression (PR 17): the reinstall entry action
        selects driver pods by component label, and a label alone is
        spoofable — a user pod wearing it must never be collateral. Only
        pods carrying the DaemonSet ownerReference are ours to bounce."""
        from tpu_operator.upgrade.fsm import (
            DRIVER_POD_COMPONENT,
            DRIVER_POD_COMPONENT_LABEL,
        )

        client = FakeClient()
        owned = new_object(
            "v1", "Pod", "libtpu-tpu-0", NS,
            labels={DRIVER_POD_COMPONENT_LABEL: DRIVER_POD_COMPONENT},
            spec={"nodeName": "tpu-0", "containers": []},
            status={"phase": "Running"},
        )
        owned["metadata"]["ownerReferences"] = [{
            "apiVersion": "apps/v1", "kind": "DaemonSet",
            "name": "tpu-libtpu-installer", "uid": "ds-uid-1",
        }]
        imposter = new_object(
            "v1", "Pod", "libtpu-imposter", NS,
            labels={DRIVER_POD_COMPONENT_LABEL: DRIVER_POD_COMPONENT},
            spec={"nodeName": "tpu-0", "containers": []},
            status={"phase": "Running"},
        )
        client.create(owned)
        client.create(imposter)
        NodeRepairManager(client, NS)._delete_driver_pods([owned, imposter])
        assert client.get_or_none("v1", "Pod", "libtpu-tpu-0", NS) is None
        assert client.get_or_none("v1", "Pod", "libtpu-imposter", NS) is not None

    def test_retry_charge_rides_persisted_backoff_gate(self):
        """TPUOP-K005 regression (PR 17): a watch-event storm (or a
        crash-looping operator) redelivers the same degradation many
        times per second; each delivery used to burn one retry, so a
        burst could quarantine a node the backoff schedule says still
        has budget. The charge now stamps a persisted nextAttemptAt
        annotation in the same atomic patch, and early arrivals leave
        the node untouched."""
        client = FakeClient()
        self.seed(client)
        mgr = NodeRepairManager(client, NS)
        remediation = self.spec(retryLimit=5).remediation

        node = client.get("v1", "Node", "tpu-0")
        assert mgr._begin_or_quarantine(node, remediation) == RepairState.CORDON_REQUIRED
        ann = client.get("v1", "Node", "tpu-0")["metadata"]["annotations"]
        assert ann[consts.REPAIR_RETRIES_ANNOTATION] == "1"
        # the gate rides the same patch as the counter
        assert float(ann[consts.REPAIR_NEXT_ATTEMPT_ANNOTATION]) >= 0

        # the storm: redeliveries inside the backoff window charge nothing
        node = client.get("v1", "Node", "tpu-0")
        node["metadata"]["annotations"][
            consts.REPAIR_NEXT_ATTEMPT_ANNOTATION
        ] = str(time.time() + 3600)
        client.update(node)
        for _ in range(5):
            node = client.get("v1", "Node", "tpu-0")
            # early arrival: current state reported, no new charge
            assert mgr._begin_or_quarantine(node, remediation) == RepairState.CORDON_REQUIRED
        ann = client.get("v1", "Node", "tpu-0")["metadata"]["annotations"]
        assert ann[consts.REPAIR_RETRIES_ANNOTATION] == "1"

        # once the stamp elapses the next attempt charges normally
        node = client.get("v1", "Node", "tpu-0")
        node["metadata"]["annotations"][
            consts.REPAIR_NEXT_ATTEMPT_ANNOTATION
        ] = str(time.time() - 1)
        client.update(node)
        node = client.get("v1", "Node", "tpu-0")
        mgr._begin_or_quarantine(node, remediation)
        ann = client.get("v1", "Node", "tpu-0")["metadata"]["annotations"]
        assert ann[consts.REPAIR_RETRIES_ANNOTATION] == "2"

        # a hand-mangled stamp degrades to "no gate", never a crash
        node = client.get("v1", "Node", "tpu-0")
        node["metadata"]["annotations"][
            consts.REPAIR_NEXT_ATTEMPT_ANNOTATION
        ] = "not-a-timestamp"
        client.update(node)
        node = client.get("v1", "Node", "tpu-0")
        mgr._begin_or_quarantine(node, remediation)
        ann = client.get("v1", "Node", "tpu-0")["metadata"]["annotations"]
        assert ann[consts.REPAIR_RETRIES_ANNOTATION] == "3"

    def test_quarantined_node_keeps_cordon_when_disabled(self):
        client = FakeClient()
        node = self.seed(client)
        node = client.get("v1", "Node", "tpu-0")
        node["metadata"]["labels"][consts.REPAIR_STATE_LABEL] = RepairState.QUARANTINED
        node["spec"]["unschedulable"] = True
        client.update(node)
        NodeRepairManager(client, NS).remove_repair_labels()
        node = client.get("v1", "Node", "tpu-0")
        assert consts.REPAIR_STATE_LABEL not in node["metadata"]["labels"]
        assert node["spec"]["unschedulable"] is True  # human opted it out


class TestHealthReconciler:
    def test_publishes_status_and_metrics(self):
        client = FakeClient()
        node = make_tpu_node("tpu-0")
        node["metadata"]["labels"][consts.TPU_HEALTH_LABEL] = consts.HEALTH_DEGRADED
        client.create(node)
        client.create(new_cluster_policy(spec={"healthMonitor": {
            "interval": 7, "remediation": {"gracePeriodSeconds": 0}}}))
        r = HealthReconciler(client, NS)
        result = r.reconcile(Request(name="cluster-policy"))
        assert result.requeue_after == 7.0
        cp = client.get(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND, "cluster-policy")
        assert cp["status"]["health"]["remediating"] == 1
        assert cp["status"]["health"]["nodes"]["tpu-0"] == RepairState.CORDON_REQUIRED
        assert metric("tpu_operator_unhealthy_nodes") == 1
        assert metric("tpu_operator_remediations_total") >= 1

    def test_monitoring_only_mode_keeps_observability(self):
        """remediation.enable=false with monitoring on: no repair runs,
        but the gauge, status.health, and the slice fail-fast labels all
        stay live — disabling auto-repair must not blind the operator."""
        client = FakeClient()
        for i in range(2):
            node = make_tpu_node(f"v5e-{i}", nodepool="pool-a")
            node["metadata"]["labels"][consts.TPU_PRESENT_LABEL] = "true"
            node["metadata"]["labels"][consts.TPU_HEALTH_LABEL] = (
                consts.HEALTH_DEGRADED if i == 0 else consts.HEALTH_HEALTHY
            )
            client.create(node)
        client.create(new_cluster_policy(spec={
            "healthMonitor": {"remediation": {"enable": False}}}))
        r = HealthReconciler(client, NS)
        result = r.reconcile(Request(name="cluster-policy"))
        assert result.requeue_after > 0
        node = client.get("v1", "Node", "v5e-0")
        assert consts.REPAIR_STATE_LABEL not in node["metadata"]["labels"]
        assert not node["spec"].get("unschedulable")  # no repair ran
        assert metric("tpu_operator_unhealthy_nodes") == 1
        cp = client.get(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND, "cluster-policy")
        assert cp["status"]["health"]["degraded"] == 1
        # the gang fail-fast label still flows to the sick host's peer
        assert (
            client.get("v1", "Node", "v5e-1")["metadata"]["labels"][
                consts.TPU_SLICE_HEALTH_LABEL
            ]
            == consts.HEALTH_DEGRADED
        )

    def test_healthy_cluster_clears_status_block(self):
        client = FakeClient()
        node = make_tpu_node("tpu-0")
        node["metadata"]["labels"][consts.TPU_HEALTH_LABEL] = consts.HEALTH_HEALTHY
        client.create(node)
        client.create(new_cluster_policy())
        r = HealthReconciler(client, NS)
        r.reconcile(Request(name="cluster-policy"))
        cp = client.get(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND, "cluster-policy")
        assert "health" not in cp.get("status", {})
        assert metric("tpu_operator_unhealthy_nodes") == 0


class TestHealthEndToEnd:
    """The acceptance fault-injection drill, over the wire (HTTP-served
    fake apiserver with real eviction/PDB semantics)."""

    def run_over_wire(self, fn, **kwargs):
        from drill import run_health_drill, run_quarantine_drill  # noqa: F401

        store = FakeClient()
        server = FakeApiServer(store).start()
        client = HttpClient(server.base_url, timeout=10.0)
        try:
            return fn(client, NS, **kwargs), store
        finally:
            server.stop()

    def test_full_remediation_loop(self):
        from drill import assert_health_drill_passed, run_health_drill

        before = metric("tpu_operator_remediations_total") or 0
        obs, store = self.run_over_wire(run_health_drill)
        assert_health_drill_passed(obs)
        # Events at each step: repair transitions + the final remediated
        reasons = {e.get("reason") for e in store.list("v1", "Event")}
        assert "TPUNodeRepair" in reasons and "TPUNodeRemediated" in reasons
        # the remediation counter observed the attempt
        assert metric("tpu_operator_remediations_total") == before + 1

    def test_retry_budget_exhaustion_lands_quarantined(self):
        from drill import assert_quarantine_drill_passed, run_quarantine_drill

        obs, store = self.run_over_wire(run_quarantine_drill, retry_limit=1)
        assert_quarantine_drill_passed(obs, retry_limit=1)
        quarantine_events = [
            e
            for e in store.list("v1", "Event")
            if e.get("reason") == "TPUNodeRepair"
            and RepairState.QUARANTINED in e.get("message", "")
        ]
        assert quarantine_events and quarantine_events[0]["type"] == "Warning"
