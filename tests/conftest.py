"""Test configuration.

JAX-touching tests run on a virtual 8-device CPU mesh so the multi-chip
sharding paths (slice validator payloads, __graft_entry__.dryrun_multichip)
are exercised without TPU hardware. Must be set before jax is imported
anywhere in the test process.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


@pytest.fixture()
def fake_client():
    from tpu_operator.kube.fake import FakeClient

    return FakeClient()
