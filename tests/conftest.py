"""Test configuration.

JAX-touching tests run on a virtual 8-device CPU mesh so the multi-chip
sharding paths (slice validator payloads, __graft_entry__.dryrun_multichip)
are exercised without TPU hardware.

This environment's sitecustomize pre-imports jax and registers the ``axon``
TPU backend at interpreter startup, so setting ``JAX_PLATFORMS`` via
os.environ here is too late — ``jax.config.update("jax_platforms", ...)``
is the override that still works before first backend initialization.
``XLA_FLAGS`` is read when the CPU client first initializes, so appending
the host-device-count flag here is still in time.
"""

import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture()
def fake_client():
    from tpu_operator.kube.fake import FakeClient

    return FakeClient()


@pytest.fixture(autouse=True)
def _racecheck_guard():
    """Under TPUOP_RACECHECK=1 every test runs inside the runtime race
    harness: any lock-order cycle or mutation-tripwire hit recorded
    during the test fails THAT test (attribution beats a session-end
    dump). The order graph itself is kept across tests on purpose — an
    ordering learned in one test legitimately constrains the next; only
    the violation log position is per-test. A no-op when the harness is
    off (the default)."""
    from tpu_operator.kube import racecheck

    if not racecheck.enabled():
        yield
        return
    before = len(racecheck.violations())
    yield
    new = racecheck.violations()[before:]
    assert not new, (
        "racecheck: %d concurrency violation(s) during this test:\n%s"
        % (len(new), "\n".join(repr(v) for v in new))
    )
