"""FakeClient apiserver semantics: CRUD, RV conflicts, watch, GC, selectors."""

import pytest

from tpu_operator.kube import errors
from tpu_operator.kube.client import ADDED, DELETED, MODIFIED
from tpu_operator.kube.fake import FakeClient
from tpu_operator.kube.objects import matches_selector, new_object, set_owner_reference


def mk_pod(name, ns="default", labels=None):
    return new_object("v1", "Pod", name, ns, labels=labels, spec={"containers": []})


def test_create_get_roundtrip(fake_client):
    created = fake_client.create(mk_pod("a"))
    assert created["metadata"]["uid"].startswith("uid-")
    assert created["metadata"]["resourceVersion"] == "1"
    got = fake_client.get("v1", "Pod", "a", "default")
    assert got["metadata"]["name"] == "a"
    # returned copies are detached from the store
    got["spec"]["containers"].append({"name": "x"})
    assert fake_client.get("v1", "Pod", "a", "default")["spec"]["containers"] == []


def test_get_missing_raises(fake_client):
    with pytest.raises(errors.NotFound):
        fake_client.get("v1", "Pod", "nope", "default")


def test_create_duplicate_raises(fake_client):
    fake_client.create(mk_pod("a"))
    with pytest.raises(errors.AlreadyExists):
        fake_client.create(mk_pod("a"))


def test_update_conflict_on_stale_rv(fake_client):
    obj = fake_client.create(mk_pod("a"))
    fresh = fake_client.get("v1", "Pod", "a", "default")
    fresh["spec"]["containers"] = [{"name": "c1"}]
    fake_client.update(fresh)
    obj["spec"]["containers"] = [{"name": "stale"}]
    with pytest.raises(errors.Conflict):
        fake_client.update(obj)


def test_generation_bumps_only_on_spec_change(fake_client):
    obj = fake_client.create(mk_pod("a"))
    assert obj["metadata"]["generation"] == 1
    obj["metadata"]["labels"] = {"x": "y"}
    obj = fake_client.update(obj)
    assert obj["metadata"]["generation"] == 1
    obj["spec"]["containers"] = [{"name": "c"}]
    obj = fake_client.update(obj)
    assert obj["metadata"]["generation"] == 2


def test_update_does_not_touch_status_and_vice_versa(fake_client):
    obj = fake_client.create(mk_pod("a"))
    obj["status"] = {"phase": "Running"}
    fake_client.update_status(obj)
    got = fake_client.get("v1", "Pod", "a", "default")
    assert got["status"]["phase"] == "Running"
    got["spec"]["containers"] = [{"name": "c"}]
    got["status"] = {"phase": "Clobbered"}
    fake_client.update(got)
    assert fake_client.get("v1", "Pod", "a", "default")["status"]["phase"] == "Running"


def test_list_label_selector(fake_client):
    fake_client.create(mk_pod("a", labels={"app": "x", "tier": "fe"}))
    fake_client.create(mk_pod("b", labels={"app": "y"}))
    fake_client.create(mk_pod("c", labels={"app": "x"}))
    assert [o["metadata"]["name"] for o in fake_client.list("v1", "Pod", label_selector="app=x")] == ["a", "c"]
    assert [o["metadata"]["name"] for o in fake_client.list("v1", "Pod", label_selector={"app": "x", "tier": "fe"})] == ["a"]
    assert [o["metadata"]["name"] for o in fake_client.list("v1", "Pod", label_selector="app in (x,y)")] == ["a", "b", "c"]
    assert [o["metadata"]["name"] for o in fake_client.list("v1", "Pod", label_selector="tier")] == ["a"]
    assert [o["metadata"]["name"] for o in fake_client.list("v1", "Pod", label_selector="!tier")] == ["b", "c"]


def test_field_selector(fake_client):
    pod = mk_pod("a")
    pod["spec"]["nodeName"] = "node-1"
    fake_client.create(pod)
    fake_client.create(mk_pod("b"))
    out = fake_client.list("v1", "Pod", field_selector={"spec.nodeName": "node-1"})
    assert [o["metadata"]["name"] for o in out] == ["a"]


def test_watch_events(fake_client):
    events = []
    sub = fake_client.watch("v1", "Pod", lambda t, o: events.append((t, o["metadata"]["name"])))
    fake_client.create(mk_pod("a"))
    obj = fake_client.get("v1", "Pod", "a", "default")
    obj["spec"]["containers"] = [{"name": "c"}]
    fake_client.update(obj)
    fake_client.delete("v1", "Pod", "a", "default")
    assert events == [(ADDED, "a"), (MODIFIED, "a"), (DELETED, "a")]
    sub.stop()
    fake_client.create(mk_pod("b"))
    assert len(events) == 3


def test_owner_reference_gc(fake_client):
    owner = fake_client.create(new_object("apps/v1", "DaemonSet", "ds", "default", spec={}))
    child = mk_pod("child")
    set_owner_reference(child, owner)
    fake_client.create(child)
    orphan = fake_client.create(mk_pod("orphan"))
    fake_client.delete("apps/v1", "DaemonSet", "ds", "default")
    with pytest.raises(errors.NotFound):
        fake_client.get("v1", "Pod", "child", "default")
    assert fake_client.get("v1", "Pod", "orphan", "default")["metadata"]["uid"] == orphan["metadata"]["uid"]


def test_apply_create_then_update(fake_client):
    obj = new_object("v1", "ConfigMap", "cm", "default", data={"k": "1"})
    fake_client.apply(obj)
    obj2 = new_object("v1", "ConfigMap", "cm", "default", data={"k": "2"})
    fake_client.apply(obj2)
    assert fake_client.get("v1", "ConfigMap", "cm", "default")["data"]["k"] == "2"


def test_selector_parsing_edge_cases():
    assert matches_selector({"a": "1"}, "a!=2")
    assert not matches_selector({"a": "2"}, "a!=2")
    assert matches_selector({"a": "1", "b": "2"}, "a=1,b=2")
    assert not matches_selector({"a": "1"}, "a=1,b=2")
    assert matches_selector({}, None)
    assert matches_selector({"k": "v"}, "k notin (a,b)")
