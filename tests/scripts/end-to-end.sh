#!/usr/bin/env bash
# End-to-end harness (reference: tests/scripts/end-to-end.sh + cases/defaults.sh):
# install -> all operands Ready -> run TPU workload -> live ClusterPolicy
# update -> disable/enable operand -> operator restart -> uninstall.
# Runs against the in-memory apiserver + cluster sim (the CPU-only kind
# cluster configuration) so it needs no cluster and no TPUs.
set -euo pipefail
cd "$(dirname "$0")/../.."

python3 - <<'PY'
import time
from tpu_operator.kube.fake import FakeClient
from tpu_operator.kube.manager import Manager
from tpu_operator.kube.sim import ClusterSim, make_tpu_node
from tpu_operator.api.clusterpolicy import new_cluster_policy, CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND
from tpu_operator.controllers.clusterpolicy_controller import ClusterPolicyReconciler, setup_with_manager
from tpu_operator.chart import render_chart
import yaml

def wait(fn, t=30, what=""):
    dl = time.monotonic() + t
    while time.monotonic() < dl:
        if fn():
            return
        time.sleep(0.05)
    raise SystemExit(f"TIMEOUT waiting for {what}")

NS = "tpu-operator"
client = FakeClient()
sim = ClusterSim(client, ready_delay=0.3).start()
for i in range(4):
    client.create(make_tpu_node(f"tpu-{i}", "tpu-v5-lite-podslice", "4x4"))

# 1. "helm install": render the chart and apply the CR it contains
values = yaml.safe_load(open("deploy/values.yaml"))
objs = render_chart(values)
cp = [o for o in objs if o["kind"] == "ClusterPolicy"][0]
mgr = Manager(client, namespace=NS)
setup_with_manager(mgr, ClusterPolicyReconciler(client, NS))
mgr.start()
client.create(cp)

def ready():
    o = client.get(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND, "cluster-policy")
    return o.get("status", {}).get("state") == "ready" and len(client.list("apps/v1", "DaemonSet", NS)) == 11
wait(ready, what="install -> Ready")
print("STEP 1 OK: install -> ClusterPolicy Ready, 11 operand DaemonSets")

# 2. TPU workload (the smoke payload the validator schedules) on whatever
# accelerator is attached (the one real-device step; everything else is
# hermetic). The relayed dev backend occasionally throws transient
# FAILED_PRECONDITION faults (libtpu client/terminal skew) unrelated to
# the operator under test: retry ONCE, only for that fault class, and in
# a fresh subprocess — jax caches a failed backend init for the process
# lifetime, so an in-process retry would just re-raise it.
from tpu_operator.workloads.smoke import run_smoke
try:
    report = run_smoke()
except Exception as first:  # noqa: BLE001 — inspect the fault class below
    if "FAILED_PRECONDITION" not in str(first):
        raise  # a real workload failure must fail the e2e
    print(f"STEP 2 retry (fresh process) after transient device fault: {first}")
    time.sleep(5)
    import json as _json, subprocess, sys as _sys
    proc = subprocess.run(
        [_sys.executable, "-c",
         "import json; from tpu_operator.workloads.smoke import run_smoke; "
         "print('SMOKE:' + json.dumps(run_smoke()))"],
        capture_output=True, text=True, timeout=300,
    )
    if proc.returncode != 0:
        raise RuntimeError(f"smoke retry failed: {proc.stderr[-2000:]}") from first
    report = next(_json.loads(l[len("SMOKE:"):]) for l in proc.stdout.splitlines()
                  if l.startswith("SMOKE:"))
print(f"STEP 2 OK: TPU workload pass ({report['device_count']} {report['platform']} device(s))")

# 2b. gang placement: the slice manager materializes the full multi-host
# contract — worker pods resolvable at every TPU_WORKER_HOSTNAMES entry,
# and a coordinator Service behind MEGASCALE_COORDINATOR_ADDRESS
from tpu_operator.agents.slice_manager_agent import SliceManagerAgent
sm = SliceManagerAgent(client, NS, multi_slice=True, validator_image="tpu-operator-validator:e2e")
slice_names = sm.reconcile_once()
assert slice_names, "no multi-host slices reconciled"
gang_cm = client.get("v1", "ConfigMap", f"{slice_names[0]}-gang", NS)
hostnames = gang_cm["data"]["TPU_WORKER_HOSTNAMES"].split(",")
pods = {p["metadata"]["name"]: p for p in client.list("v1", "Pod", NS)
        if (p["metadata"].get("labels") or {}).get("app") == "tpu-slice-worker"}
assert len(pods) == len(hostnames) == 4, (len(pods), len(hostnames))
for entry in hostnames:
    host, svc = entry.split(".")[:2]
    pod = pods[host]
    assert pod["spec"]["hostname"] == host and pod["spec"]["subdomain"] == svc
    service = client.get("v1", "Service", svc, NS)
    assert all(pod["metadata"]["labels"].get(k) == v for k, v in service["spec"]["selector"].items())
coord_host = gang_cm["data"]["MEGASCALE_COORDINATOR_ADDRESS"].rsplit(":", 1)[0]
assert client.get("v1", "Service", coord_host.split(".")[0], NS) is not None
print(f"STEP 2b OK: gang placement ({len(pods)} worker pods, coordinator Service resolvable)")

# 3. live update: bump libtpu version, expect DS re-render
obj = client.get(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND, "cluster-policy")
obj["spec"].setdefault("libtpu", {}).update({"repository": "gcr.io/new", "image": "libtpu", "version": "9.9"})
client.update(obj)
wait(lambda: client.get("apps/v1", "DaemonSet", "libtpu-installer", NS)["spec"]["template"]["spec"]["containers"][0]["image"] == "gcr.io/new/libtpu:9.9",
     what="live image update")
print("STEP 3 OK: live ClusterPolicy update re-rendered libtpu DaemonSet")

# 4. disable -> DS deleted; enable -> DS back (reference: update-clusterpolicy.sh)
obj = client.get(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND, "cluster-policy")
obj["spec"]["metricsExporter"] = {"enabled": False}
client.update(obj)
wait(lambda: client.get_or_none("apps/v1", "DaemonSet", "tpu-metrics-exporter", NS) is None, what="operand disable")
obj = client.get(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND, "cluster-policy")
obj["spec"]["metricsExporter"] = {"enabled": True}
client.update(obj)
wait(lambda: client.get_or_none("apps/v1", "DaemonSet", "tpu-metrics-exporter", NS) is not None, what="operand enable")
print("STEP 4 OK: operand disable/enable cycle")

# 5. operator restart: stop manager, start a fresh one, still converges
mgr.stop()
mgr2 = Manager(client, namespace=NS)
setup_with_manager(mgr2, ClusterPolicyReconciler(client, NS))
mgr2.start()
wait(ready, what="post-restart Ready")
print("STEP 5 OK: operator restart -> Ready (stateless resume)")

# 6. uninstall: delete CR -> operands GC'd via ownerReferences, and the
# gang objects (owned by the slice-manager DaemonSet) cascade with them
client.delete(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND, "cluster-policy")
wait(lambda: client.list("apps/v1", "DaemonSet", NS) == [], what="uninstall GC")
wait(lambda: client.list("v1", "Pod", NS, label_selector={"app": "tpu-slice-worker"}) == [],
     what="gang pod GC")
assert client.get_or_none("v1", "Service", slice_names[0], NS) is None, "gang Service leaked"
print("STEP 6 OK: uninstall -> operands + gang objects garbage-collected")
mgr2.stop(); sim.stop()
print("END-TO-END: PASS")
PY
