#!/usr/bin/env bash
# Real-Helm render gate (reference: the chart is consumed by actual helm,
# deployments/gpu-operator/). The in-repo parity tests pit helmlite
# against tpuop-cfg render — both in-repo, so a helmlite bug and a chart
# bug could cancel out. This gate runs the REAL `helm template` when a
# helm binary exists and diffs its objects against the helmlite render;
# exit 42 = helm not installed (skip sentinel, same contract as
# kind-e2e.sh). On first success it also writes a golden snapshot to
# tests/golden/helm-template.yaml for the repo to commit.
set -euo pipefail
cd "$(dirname "$0")/../.."

if ! command -v helm >/dev/null 2>&1; then
  echo "helm-golden: no helm binary; skipping (exit 42)"
  exit 42
fi

rc=0
python3 - <<'EOF' || rc=$?
import copy
import os
import subprocess
import sys

import yaml

sys.path.insert(0, os.getcwd())
from tpu_operator import helmlite

CHART = "deploy/helm/tpu-operator"
GOLDEN = "tests/golden/helm-template.yaml"

with open("deploy/helm/tpu-operator/values.yaml") as f:
    values = yaml.safe_load(f)

proc = subprocess.run(
    [
        "helm", "template", "tpu-operator", CHART,
        "-n", "tpu-operator", "--include-crds",
        "--set", "createNamespace=true",
    ],
    capture_output=True, text=True, timeout=300,
)
if proc.returncode != 0:
    sys.exit(f"helm template failed:\n{proc.stderr[-3000:]}")

def by_key(objs):
    return {(o["kind"], o["metadata"]["name"]): o for o in objs if o}

helm_objs = by_key(yaml.safe_load_all(proc.stdout))
vals = copy.deepcopy(values)
vals["createNamespace"] = True
lite_objs = by_key(helmlite.template(CHART, vals, namespace="tpu-operator"))

if set(helm_objs) != set(lite_objs):
    sys.exit(
        "object sets differ:\n"
        f" helm-only: {sorted(set(helm_objs) - set(lite_objs))}\n"
        f" helmlite-only: {sorted(set(lite_objs) - set(helm_objs))}"
    )
diffs = [k for k in helm_objs if helm_objs[k] != lite_objs[k]]
if diffs:
    for k in diffs[:5]:
        print(f"DIFF {k}:\n helm: {helm_objs[k]}\n lite: {lite_objs[k]}")
    sys.exit(f"{len(diffs)} objects differ between helm and helmlite")

if os.path.exists(GOLDEN):
    # the committed snapshot is the gate: today's helm output must match
    # it exactly (catches a regression that helmlite happens to mirror)
    with open(GOLDEN) as f:
        golden = by_key(yaml.safe_load_all(f))
    if golden != helm_objs:
        changed = sorted(
            set(golden) ^ set(helm_objs)
            | {k for k in set(golden) & set(helm_objs) if golden[k] != helm_objs[k]}
        )
        sys.exit(
            f"helm output drifted from committed {GOLDEN}: {changed}\n"
            "(delete the golden and re-run to regenerate intentionally)"
        )
    print(f"helm-golden: {len(helm_objs)} objects agree with helmlite AND {GOLDEN}")
else:
    with open(GOLDEN, "w") as f:
        yaml.safe_dump_all(
            [helm_objs[k] for k in sorted(helm_objs)], f, sort_keys=False
        )
    print(
        f"helm-golden: {len(helm_objs)} objects agree; snapshot bootstrapped -> "
        f"{GOLDEN} — COMMIT IT to arm the gate"
    )
    sys.exit(43)  # bootstrap sentinel: agreement checked, golden gate UNARMED
EOF
if [ "$rc" -eq 43 ]; then
  echo "HELM GOLDEN: PASS (unarmed — snapshot bootstrapped, commit it)"
  exit 43
elif [ "$rc" -ne 0 ]; then
  exit "$rc"
fi
echo "HELM GOLDEN: PASS"
