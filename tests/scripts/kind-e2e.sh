#!/usr/bin/env bash
# Real-apiserver e2e via kind (reference: tests/scripts/end-to-end.sh in
# the upstream operator, which provisions a cluster and installs the
# chart for CI).
#
# This environment has neither a docker daemon nor kind, so the
# real-apiserver path (tests/test_e2e_real.py + the rolling-upgrade
# drill) has only ever run against the HTTP-served fake. The FIRST
# environment that has both should exercise it with zero thought:
#
#     bash tests/scripts/kind-e2e.sh
#
# spins a throwaway kind cluster, points KUBECONFIG at it, runs the
# gated real-cluster suite (install CRDs -> operator -> Ready ->
# live update -> upgrade drill -> uninstall/GC), and tears the cluster
# down again. Exits 42 ("skipped") when docker or kind is missing, so
# ci.sh can call it unconditionally as an optional gate.
set -euo pipefail
cd "$(dirname "$0")/../.."

# outside pytest's exit-code range (0-5): a pytest internal error (rc 3)
# must never masquerade as the intentional "no docker/kind here" skip
SKIP_RC=42
CLUSTER="tpu-operator-e2e-$$"

need() {
  if ! command -v "$1" >/dev/null 2>&1; then
    echo "kind-e2e: '$1' not found — skipping real-apiserver e2e" >&2
    exit "$SKIP_RC"
  fi
}
need docker
need kind
if ! docker info >/dev/null 2>&1; then
  echo "kind-e2e: docker daemon unreachable — skipping real-apiserver e2e" >&2
  exit "$SKIP_RC"
fi

KUBECONFIG_FILE="$(mktemp)"
cleanup() {
  kind delete cluster --name "$CLUSTER" >/dev/null 2>&1 || true
  rm -f "$KUBECONFIG_FILE"
}
trap cleanup EXIT

echo "== kind: creating cluster $CLUSTER =="
kind create cluster --name "$CLUSTER" --kubeconfig "$KUBECONFIG_FILE" --wait 120s
export KUBECONFIG="$KUBECONFIG_FILE"

echo "== real-apiserver e2e (tests/test_e2e_real.py: operator flow + upgrade drill) =="
PYTEST_LOG="$(mktemp)"
python3 -m pytest tests/test_e2e_real.py -v -x -rs | tee "$PYTEST_LOG"

# the suite skip-guards each test at runtime (unreachable apiserver →
# pytest.skip → exit 0): an all-skipped run must FAIL this script, whose
# whole purpose is to finally execute the real-cluster suite
if ! grep -qE "[0-9]+ passed" "$PYTEST_LOG"; then
  echo "kind-e2e: FAIL — cluster came up but no test actually ran (all skipped?)" >&2
  rm -f "$PYTEST_LOG"
  exit 1
fi
rm -f "$PYTEST_LOG"

echo "kind-e2e: PASS"
