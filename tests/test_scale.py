"""O(changes) control-plane scaling tests.

The contract this PR establishes: steady-state apiserver traffic is
proportional to what changed, not to cluster size. Enforced three ways —
(1) the over-the-wire requests-per-reconcile rate stays flat between 64
and 512 simulated nodes, (2) one node label flip costs exactly one
reconcile (queue coalescing + self-write echo suppression), and (3) a
quiet steady state performs zero status (or any other) writes. Plus unit
coverage for the mechanisms underneath: merge-patch label repair under
concurrent kubelet churn, the write-echo filter, queue coalescing, and
the informer label indexes.
"""

import time

import pytest

from tpu_operator import consts
from tpu_operator.api.clusterpolicy import (
    CLUSTER_POLICY_API_VERSION,
    CLUSTER_POLICY_KIND,
    ClusterPolicy,
    new_cluster_policy,
)
from tpu_operator.controllers.clusterpolicy_controller import (
    ClusterPolicyReconciler,
    setup_with_manager,
)
from tpu_operator.kube import errors
from tpu_operator.kube.controller import Request
from tpu_operator.kube.echo import WriteEchoFilter
from tpu_operator.kube.fake import FakeClient
from tpu_operator.kube.http_client import HttpClient
from tpu_operator.kube.httpserver import FakeApiServer
from tpu_operator.kube.informer import Informer
from tpu_operator.kube.manager import Manager
from tpu_operator.kube.queue import RateLimitingQueue
from tpu_operator.kube.sim import ClusterSim, make_tpu_node

NS = "tpu-operator"


def wait_for(fn, timeout=30.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return False


class _Wired:
    """A full operator over real TCP against the fake apiserver."""

    def __init__(self, nodes: int):
        self.nodes = nodes
        self.store = FakeClient()
        for i in range(nodes):
            self.store.create(make_tpu_node(f"tpu-{i}", "tpu-v5-lite-podslice", "4x4"))
        self.server = FakeApiServer(self.store).start()
        self.client = HttpClient(self.server.base_url, timeout=10.0)
        self.sim = ClusterSim(self.store, ready_delay=0.05, tick=0.01).start()
        self.mgr = Manager(self.client, namespace=NS)
        self.reconciler = ClusterPolicyReconciler(self.client, NS)
        setup_with_manager(self.mgr, self.reconciler)

    def __enter__(self):
        import prometheus_client

        from tpu_operator.controllers.operator_metrics import get_metrics

        get_metrics()
        self._registry = prometheus_client.REGISTRY
        self.mgr.start()
        self.store.create(new_cluster_policy())
        assert wait_for(self.ready, timeout=60.0), "never Ready"
        return self

    def __exit__(self, *exc):
        self.mgr.stop()
        self.sim.stop()
        self.server.stop()

    def ready(self):
        cp = self.store.get_or_none(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND, "cluster-policy")
        if (cp or {}).get("status", {}).get("state") != "ready":
            return False
        dses = self.store.list("apps/v1", "DaemonSet", NS)
        # the autotuner DS schedules only onto controller-elected
        # nodes: none in these runs, so it is desired/available 0
        return len(dses) == 11 and all(
            ds.get("status", {}).get("numberAvailable")
            == (0 if ds["metadata"]["name"] in ("tpu-autotuner", "tpu-compile-cache") else self.nodes)
            for ds in dses
        )

    def reconciles(self) -> float:
        return (
            self._registry.get_sample_value("tpu_operator_reconciliation_total") or 0.0
        )

    def requests(self) -> int:
        return sum(self.client.request_counts.values())

    def flip_and_wait(self, node: str, label: str) -> None:
        """Admin-remove one operator-owned label; wait for the repair."""
        self.store.patch("v1", "Node", node, {"metadata": {"labels": {label: None}}})
        assert wait_for(
            lambda: (self.store.get("v1", "Node", node)["metadata"].get("labels") or {}).get(label)
            is not None,
            timeout=15.0,
        ), f"operator never restored {label} on {node}"


def _steady_rpr(wired: _Wired, flips: int = 6) -> float:
    """Post-Ready requests-per-reconcile over a perturbation window."""
    gate = consts.COMMON_DEPLOY_LABEL_PREFIX + "tfd"
    r0, q0 = wired.reconciles(), wired.requests()
    for i in range(flips):
        wired.flip_and_wait(f"tpu-{i % wired.nodes}", gate)
    time.sleep(0.3)  # let the last repair's bookkeeping land
    reconciles = wired.reconciles() - r0
    requests = wired.requests() - q0
    return requests / max(reconciles, 1.0)


class TestScaleFlatness:
    def test_requests_per_reconcile_flat_64_to_512(self):
        """Over the wire at 64 and 512 sim nodes: the steady-state
        requests-per-reconcile rate must not grow with cluster size
        (+-2 tolerance). Before the O(changes) work this rate scaled
        with node count (full-object writes + full-store scans)."""
        with _Wired(64) as w64:
            rpr_64 = _steady_rpr(w64)
        with _Wired(512) as w512:
            rpr_512 = _steady_rpr(w512)
        assert abs(rpr_512 - rpr_64) <= 2.0, (rpr_64, rpr_512)

    def test_single_label_flip_causes_exactly_one_reconcile(self):
        """Coalescing + echo suppression: one admin label flip delivers
        one watch event -> one (coalesced) reconcile; the repair patch's
        own echo event is dropped by the predicate instead of re-waking
        the controller. The flipped label is workload-config, which no
        DaemonSet selects on, so there is no scheduling ripple either."""
        with _Wired(16) as w:
            time.sleep(0.3)  # drain any install-tail events
            label = consts.TPU_WORKLOAD_CONFIG_LABEL
            r0, q0 = w.reconciles(), w.requests()
            w.flip_and_wait("tpu-3", label)
            time.sleep(0.5)  # echo (if any) would re-enqueue in here
            assert w.reconciles() - r0 == 1, f"{w.reconciles() - r0} reconciles for one flip"
            # and the repair itself was one labels-only PATCH
            assert w.requests() - q0 == 1

    def test_quiet_steady_state_has_zero_writes(self):
        """60 sim ticks of quiet steady state: zero status writes (and
        zero writes of any kind) — the status publisher skips byte-
        identical publishes and nothing else has work to do."""
        with _Wired(8) as w:
            time.sleep(0.3)
            before = dict(w.client.request_counts)
            time.sleep(0.6)  # 60 ticks at the sim's 10 ms cadence
            after = dict(w.client.request_counts)
            for verb in ("PUT", "PATCH", "POST", "DELETE"):
                assert after.get(verb, 0) == before.get(verb, 0), (
                    verb, before, after,
                )


class TestLabellerApplySet:
    """The labeller's write path is the apply-set (server-side-apply
    analog): one declaration per node, no resourceVersion, no
    read-modify-write — so the Conflict class the old patch path had to
    retry around cannot occur at all, and write failures still requeue."""

    def test_apply_carries_no_rv_so_storage_races_cannot_conflict(self):
        """A concurrent writer bumping the node's rv between our cache
        read and our write is invisible to the apply: it carries no rv
        to conflict on, and the server merges against current state."""
        client = FakeClient()
        client.create(make_tpu_node("tpu-0"))
        client.create(new_cluster_policy())
        rec = ClusterPolicyReconciler(client, NS)

        real_apply = FakeClient.apply_set

        def racing_apply(self_, api_version, kind, name, manager, **kw):
            # kubelet heartbeat lands first (bumps rv, adds a label)
            FakeClient.patch(
                self_, "v1", "Node", name,
                {"metadata": {"labels": {"kubelet.example/zone": "a"}}},
            )
            return real_apply(self_, api_version, kind, name, manager, **kw)

        client.apply_set = racing_apply.__get__(client, FakeClient)
        rec.reconcile(Request(name="cluster-policy"))
        labels = client.get("v1", "Node", "tpu-0")["metadata"]["labels"]
        assert labels[consts.TPU_PRESENT_LABEL] == "true"  # our write landed
        assert labels["kubelet.example/zone"] == "a"  # kubelet's survived

    def test_failed_apply_propagates_for_requeue(self):
        class _Failing(FakeClient):
            def apply_set(self, *a, **kw):
                raise errors.ServerError("apiserver 500")

        client = _Failing()
        client.create(make_tpu_node("tpu-0"))
        client.create(new_cluster_policy())
        rec = ClusterPolicyReconciler(client, NS)
        result = rec.reconcile(Request(name="cluster-policy"))
        # a failed sweep write must requeue so the labels converge
        # without waiting for an unrelated event
        assert result.requeue

    def test_admin_opt_out_value_is_never_stolen(self):
        """A hand-set \"false\" on a deploy gate survives every sweep:
        the apply cedes ownership of a foreign value instead of forcing
        it back (the old delta writer's leave-explicit-values-alone
        semantics, now enforced server-side)."""
        client = FakeClient()
        client.create(make_tpu_node("tpu-0"))
        client.create(new_cluster_policy())
        rec = ClusterPolicyReconciler(client, NS)
        rec.reconcile(Request(name="cluster-policy"))
        gate = consts.COMMON_DEPLOY_LABEL_PREFIX + "tfd"
        client.patch("v1", "Node", "tpu-0", {"metadata": {"labels": {gate: "false"}}})
        rec.reconcile(Request(name="cluster-policy"))
        labels = client.get("v1", "Node", "tpu-0")["metadata"]["labels"]
        assert labels[gate] == "false"  # the opt-out held

    def test_legacy_gate_on_tpu_node_strips_when_operand_disabled(self):
        """Upgrade path: a deploy gate stamped by a pre-apply-set
        operator version (no ownership record) on a still-TPU node must
        strip when the operand is disabled — the old unconditional
        removal, preserved through the legacy-strip delta."""
        client = FakeClient()
        node = make_tpu_node("tpu-0")
        gate = consts.COMMON_DEPLOY_LABEL_PREFIX + "tfd"
        node["metadata"]["labels"][gate] = "true"  # legacy, unowned
        client.create(node)
        client.create(new_cluster_policy(spec={"tfd": {"enabled": False}}))
        rec = ClusterPolicyReconciler(client, NS)
        rec.reconcile(Request(name="cluster-policy"))
        labels = client.get("v1", "Node", "tpu-0")["metadata"]["labels"]
        assert gate not in labels
        assert labels[consts.TPU_PRESENT_LABEL] == "true"  # still a TPU node

    def test_de_tpu_node_comes_clean_even_without_ownership_record(self):
        """Labels stamped by an operator version that predates the
        apply-set record still strip off a node that no longer has TPUs
        (the legacy-cleanup delta)."""
        from tpu_operator.kube.sim import make_bare_node

        client = FakeClient()
        bare = make_bare_node("ex-tpu")
        bare["metadata"]["labels"][consts.TPU_PRESENT_LABEL] = "true"
        bare["metadata"]["labels"][consts.COMMON_DEPLOY_LABEL_PREFIX + "tfd"] = "true"
        client.create(bare)
        client.create(make_tpu_node("tpu-0"))
        client.create(new_cluster_policy())
        rec = ClusterPolicyReconciler(client, NS)
        rec.reconcile(Request(name="cluster-policy"))
        labels = client.get("v1", "Node", "ex-tpu")["metadata"].get("labels") or {}
        assert consts.TPU_PRESENT_LABEL not in labels
        assert consts.COMMON_DEPLOY_LABEL_PREFIX + "tfd" not in labels


class TestWriteEchoFilter:
    def _node(self, labels):
        return {"metadata": {"name": "n", "labels": dict(labels)}}

    def test_exact_echo_is_suppressed(self):
        f = WriteEchoFilter()
        f.record("n", {"a": "1"})
        assert f.is_echo(self._node({"a": "1"}))

    def test_foreign_change_passes(self):
        f = WriteEchoFilter()
        f.record("n", {"a": "1"})
        assert not f.is_echo(self._node({"a": "1", "kubelet": "x"}))

    def test_unknown_object_passes(self):
        assert not WriteEchoFilter().is_echo(self._node({"a": "1"}))

    def test_expired_record_passes(self):
        f = WriteEchoFilter(ttl_seconds=0.0)
        f.record("n", {"a": "1"})
        time.sleep(0.01)
        assert not f.is_echo(self._node({"a": "1"}))


class TestQueueCoalescing:
    def test_burst_collapses_to_one_item(self):
        q = RateLimitingQueue(coalesce_window=0.05)
        for _ in range(100):
            q.add("req")
        assert q.get(timeout=2.0) == "req"
        q.done("req")
        assert q.get(timeout=0.15) is None  # the burst was ONE item

    def test_add_during_processing_still_redelivers(self):
        q = RateLimitingQueue(coalesce_window=0.02)
        q.add("req")
        assert q.get(timeout=2.0) == "req"
        q.add("req")  # event lands mid-reconcile
        q.done("req")
        assert q.get(timeout=2.0) == "req"  # level-triggered: runs again

    def test_no_window_keeps_immediate_delivery(self):
        q = RateLimitingQueue()
        q.add("req")
        assert q.get(timeout=0.01) == "req"


class TestInformerIndexes:
    def _informer_with(self, *objs):
        client = FakeClient()
        for obj in objs:
            client.create(obj)
        inf = Informer(client, "v1", "Node")
        inf.start()
        return inf

    def test_select_equality_uses_index(self):
        inf = self._informer_with(
            make_tpu_node("a"), make_tpu_node("b", nodepool="other"),
        )
        got = inf.select({"cloud.google.com/gke-nodepool": "other"})
        assert [n["metadata"]["name"] for n in got] == ["b"]
        # candidate narrowing really happened (not a full scan)
        assert inf._candidate_keys({"cloud.google.com/gke-nodepool": "other"}) is not None

    def test_select_existence_string_selector(self):
        node = make_tpu_node("a", extra_labels={consts.TPU_HEALTH_LABEL: "degraded"})
        inf = self._informer_with(node, make_tpu_node("b"))
        got = inf.select(consts.TPU_HEALTH_LABEL)
        assert [n["metadata"]["name"] for n in got] == ["a"]

    def test_index_follows_label_changes(self):
        client = FakeClient()
        client.create(make_tpu_node("a"))
        inf = Informer(client, "v1", "Node")
        inf.start()
        client.patch("v1", "Node", "a", {"metadata": {"labels": {"x": "1"}}})
        assert [n["metadata"]["name"] for n in inf.select({"x": "1"})] == ["a"]
        client.patch("v1", "Node", "a", {"metadata": {"labels": {"x": None}}})
        assert inf.select({"x": "1"}) == []

    def test_custom_index(self):
        inf = self._informer_with(make_tpu_node("a"), make_tpu_node("b"))
        inf.add_index("by-name-prefix", lambda o: [o["metadata"]["name"][0]])
        assert [n["metadata"]["name"] for n in inf.by_index("by-name-prefix", "a")] == ["a"]


class TestMergePatchSemantics:
    def test_patch_preserves_unrelated_and_deletes_nulls(self):
        client = FakeClient()
        client.create(make_tpu_node("n"))
        before = client.get("v1", "Node", "n")
        client.patch(
            "v1", "Node", "n",
            {"metadata": {"labels": {"new": "v", "kubernetes.io/os": None}}},
        )
        after = client.get("v1", "Node", "n")
        assert after["metadata"]["labels"]["new"] == "v"
        assert "kubernetes.io/os" not in after["metadata"]["labels"]
        # unrelated labels, spec, and status untouched; rv bumped
        assert after["metadata"]["labels"]["kubernetes.io/hostname"] == "n"
        assert after["status"] == before["status"]
        assert after["metadata"]["resourceVersion"] != before["metadata"]["resourceVersion"]

    def test_patch_cannot_touch_status_or_identity(self):
        client = FakeClient()
        client.create(make_tpu_node("n"))
        client.patch(
            "v1", "Node", "n",
            {"metadata": {"name": "evil", "uid": "evil"},
             "status": {"allocatable": {"google.com/tpu": "999"}}},
        )
        after = client.get("v1", "Node", "n")
        assert after["metadata"]["name"] == "n"
        assert after["metadata"]["uid"] != "evil"
        assert after["status"]["allocatable"]["google.com/tpu"] == "4"

    def test_patch_status_touches_only_status(self):
        client = FakeClient()
        client.create(make_tpu_node("n"))
        client.patch_status(
            "v1", "Node", "n",
            {"metadata": {"labels": {"sneak": "x"}},
             "status": {"allocatable": {"google.com/tpu": "8"}}},
        )
        after = client.get("v1", "Node", "n")
        assert "sneak" not in after["metadata"].get("labels", {})
        assert after["status"]["allocatable"]["google.com/tpu"] == "8"
        assert after["status"]["capacity"]["google.com/tpu"] == "4"  # merged, not replaced

    def test_patch_missing_object_is_not_found(self):
        client = FakeClient()
        with pytest.raises(errors.NotFound):
            client.patch("v1", "Node", "ghost", {"metadata": {}})
