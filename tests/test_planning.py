"""Capacity planning & scheduled defragmentation (ISSUE 15).

Four layers under test:

- the analytical model (`planning/model.py`): roofline math, autotune
  winner folding, and the perf.floors_for-style input hardening —
  malformed winners / empty fabric matrices / unknown generations fall
  back to the static roof table, never raise;
- the shared replay-minus-candidate helper (`placement/engine.py`):
  scale-down (remove) vs migration (strip + re-place) semantics, and
  the scorer hook;
- the fleet simulator (`planning/sim.py`) + what-if engine
  (`planning/whatif.py`): seeded determinism, policy comparison,
  admission answers;
- the defrag controller (`controllers/defrag_controller.py`) + the job
  controller's checkpoint-barrier migration arm: idle gating, budget +
  cooldown, owner gating, decision records, series retirement.
"""

import json

import pytest

from tpu_operator import consts
from tpu_operator.api.tpujob import JobPhase, new_tpu_job
from tpu_operator.api.tpuslice import TPU_SLICE_API_VERSION, new_tpu_slice
from tpu_operator.controllers.defrag_controller import (
    DEFRAG_REQUEST,
    DefragReconciler,
)
from tpu_operator.controllers.job_controller import JobReconciler
from tpu_operator.controllers.placement_controller import (
    QUEUE_REQUEST,
    PlacementReconciler,
)
from tpu_operator.kube.controller import Request
from tpu_operator.kube.fake import FakeClient
from tpu_operator.kube.objects import new_object
from tpu_operator.kube.sim import GangChurnSchedule, make_torus_nodes
from tpu_operator.placement.engine import (
    PlacementEngine,
    migration_scores,
    pick_migration,
    replay_minus_candidate,
    scale_down_scores,
    strip_assignments,
)
from tpu_operator.placement.torus import Torus
from tpu_operator.planning.model import (
    WorkloadDescriptor,
    calibrated_roofs,
    effective_compute_roof,
    generation_roofs,
    predict_step_time,
    validate_prediction,
)
from tpu_operator.planning.sim import FleetSimulator
from tpu_operator.planning.whatif import (
    admission_answer,
    plan_report,
    queued_shapes,
)
from tpu_operator.workloads.descriptor import (
    reference_descriptor,
    serving_decode_descriptor,
    transformer_descriptor,
)

NS = "tpu-operator"

DESC = WorkloadDescriptor(
    name="t", flops_per_step=1e15, bytes_per_step=1e12,
    collective_bytes_per_axis=(1e9, 0.0, 0.0),
)


# ---------------------------------------------------------------------------
# analytical model
# ---------------------------------------------------------------------------


class TestModel:
    def test_compute_bound_prediction(self):
        d = WorkloadDescriptor(name="c", flops_per_step=1e15)
        p = predict_step_time(d, "v5e", (2, 2, 1), chips_per_host=4)
        # 16 chips x 185 TFLOP/s
        assert p.bound == "compute"
        assert p.step_seconds == pytest.approx(1e15 / (16 * 185e12), rel=1e-6)
        assert p.hosts == 4 and p.chips == 16

    def test_memory_bound_prediction(self):
        d = WorkloadDescriptor(name="m", flops_per_step=1.0, bytes_per_step=1e12)
        p = predict_step_time(d, "v5e", (1, 1, 1), chips_per_host=1)
        assert p.bound == "memory"
        assert p.step_seconds == pytest.approx(1e12 / 665e9, rel=1e-6)

    def test_collective_term_scales_with_axis_length(self):
        small = predict_step_time(DESC, "v4", (2, 1, 1))
        large = predict_step_time(DESC, "v4", (8, 1, 1))
        # ring allreduce: 2(n-1)/n grows with n, and more chips shrink
        # compute — the collective share must grow
        assert large.collective_seconds > small.collective_seconds

    def test_unit_axis_contributes_no_collective(self):
        d = WorkloadDescriptor(
            name="z", flops_per_step=1.0,
            collective_bytes_per_axis=(0.0, 0.0, 1e9),
        )
        p = predict_step_time(d, "v4", (4, 4, 1))
        assert p.collective_seconds == 0.0

    def test_autotune_winner_replaces_roof(self):
        entries = {"v4": {
            "platform": "tpu",
            "results": {"matmul": {"m2048": {"winner": {"rate": 250.0}}}},
        }}
        roofs, fallbacks = generation_roofs("v4", entries)
        assert roofs["matmul_tflops"] == 250.0
        assert fallbacks == ()

    def test_cpu_sweep_entry_never_sets_roof(self):
        entries = {"v4": {
            "platform": "cpu",
            "results": {"matmul": {"m2048": {"winner": {"rate": 0.01}}}},
        }}
        roofs, fallbacks = generation_roofs("v4", entries)
        # the merge_winner_floors discipline: interpret-mode "roofs"
        # would poison every prediction for the generation
        assert roofs["matmul_tflops"] > 1.0
        assert any("unusable-autotune-entry" in f for f in fallbacks)

    # -- the hardening contract (mirrors perf.floors_for) --------------------

    @pytest.mark.parametrize("entries", [
        "garbage", 42, ["not", "a", "dict"],
        {"v4": "torn blob"}, {"v4": {"platform": "tpu", "results": "x"}},
        {"v4": {"platform": "tpu", "results": {"matmul": {"m": {"winner": {"rate": "NaNish"}}}}}},
    ])
    def test_malformed_autotune_inputs_fall_back(self, entries):
        p = predict_step_time(DESC, "v4", (2, 2, 1), autotune_entries=entries)
        assert p.step_seconds > 0.0
        table, _ = generation_roofs("v4")
        assert p.roofs["matmul_tflops"] == table["matmul_tflops"]

    def test_unknown_generation_falls_back_to_static_table(self):
        p = predict_step_time(DESC, "v9-imaginary", (2, 2, 1))
        assert p.step_seconds > 0.0
        assert any("unknown-generation" in f for f in p.fallbacks)
        # the fallback row is the measured one
        assert p.roofs["matmul_tflops"] == generation_roofs("v5e")[0]["matmul_tflops"]

    @pytest.mark.parametrize("artifact", [
        None, {}, {"axis_allreduce_us": {}}, {"axis_allreduce_us": "torn"},
        {"axis_allreduce_us": {"x": "slow"}}, {"edges": {}}, "not-a-dict",
    ])
    def test_degenerate_fabric_matrices_never_raise(self, artifact):
        p = predict_step_time(DESC, "v4", (4, 2, 1), fabric_artifact=artifact)
        assert p.step_seconds > 0.0

    def test_measured_axis_latency_floors_the_collective(self):
        base = predict_step_time(DESC, "v4", (4, 1, 1))
        slow = predict_step_time(
            DESC, "v4", (4, 1, 1),
            fabric_artifact={"axis_allreduce_us": {"x": 5e6}},  # 5 s measured
        )
        assert slow.collective_seconds >= 5.0 > base.collective_seconds

    def test_calibrate_then_predict_roundtrip(self):
        d = WorkloadDescriptor(name="r", flops_per_step=1e12)
        effective = effective_compute_roof(d, 0.5, hosts=1, chips_per_host=2)
        roofs = calibrated_roofs("v5e", effective)
        p = predict_step_time(d, "v5e", (1, 1, 1), chips_per_host=2, roofs=roofs)
        # predicting the workload it was calibrated on reproduces it
        assert p.step_seconds == pytest.approx(0.5, rel=1e-6)

    def test_validate_prediction_bounds(self):
        assert validate_prediction(1.0, 2.0, 3.0)["ok"]
        assert not validate_prediction(1.0, 4.0, 3.0)["ok"]
        assert not validate_prediction(0.0, 1.0)["ok"]  # degenerate fails closed

    def test_descriptors_positive_and_ordered(self):
        ref = reference_descriptor()
        small = transformer_descriptor(
            "s", d_model=256, d_ff=1024, n_layers=2, n_heads=4,
            seq_len=128, batch=4,
        )
        decode = serving_decode_descriptor(
            "d", d_model=256, d_ff=1024, n_layers=2, batch=8
        )
        assert 0 < small.flops_per_step < ref.flops_per_step
        assert small.bytes_per_step > 0 and decode.bytes_per_step > 0
        assert sum(ref.collective_bytes_per_axis) > 0
        assert sum(decode.collective_bytes_per_axis) == 0  # per-replica serving


# ---------------------------------------------------------------------------
# the shared replay-minus-candidate helper + scorer hook
# ---------------------------------------------------------------------------


def _pooled(n_slices, shapes, dims=(4, 4, 1), owner_kind=None):
    client = FakeClient()
    for node in make_torus_nodes(dims, prefix="p"):
        client.create(node)
    for i in range(n_slices):
        body = new_tpu_slice(f"s{i}", {"placement": {"shape": shapes[i % len(shapes)]}})
        if owner_kind:
            body["metadata"]["ownerReferences"] = [{
                "apiVersion": "tpu.google.com/v1alpha1", "kind": owner_kind,
                "name": f"own{i // 2}", "uid": f"u{i // 2}",
            }]
        client.create(body)
    PlacementReconciler(client, NS).reconcile(QUEUE_REQUEST)
    return client


class TestReplayHelper:
    def test_remove_semantics_matches_scale_down_scores(self):
        client = _pooled(4, ["2x2x1", "2x1x1"])
        slices = client.list(TPU_SLICE_API_VERSION, "TPUSlice")
        nodes = client.list("v1", "Node")
        base = PlacementEngine(slices, nodes).plan()
        scores = scale_down_scores(slices, nodes, ["s0"])
        plan = replay_minus_candidate(slices, nodes, "s0", migrate=False)
        pool = (slices[0].get("status") or {}).get("placement", {}).get("pool")
        # the factored helper IS the scorer's replay: identical numbers
        assert scores["s0"][0] == plan.fragmentation.get(pool, 0.0)
        assert scores["s0"][1] == round(
            scores["s0"][0] - base.fragmentation.get(pool, 0.0), 4
        )
        # removed candidate is not re-placed
        assert "s0" not in plan.statuses or plan.statuses["s0"] == {}

    def test_migrate_semantics_reseats_candidate(self):
        client = _pooled(3, ["2x2x1"])
        slices = client.list(TPU_SLICE_API_VERSION, "TPUSlice")
        nodes = client.list("v1", "Node")
        plan = replay_minus_candidate(slices, nodes, "s1", migrate=True)
        assert plan.statuses["s1"]["phase"] == "Scheduled"

    def test_strip_assignments_only_touches_owner(self):
        client = _pooled(2, ["2x2x1"])
        nodes = client.list("v1", "Node")
        stripped = strip_assignments(nodes, ["s0"])
        originals = {n["metadata"]["name"]: n for n in nodes}
        for node in stripped:
            labels = node["metadata"].get("labels") or {}
            owner = (originals[node["metadata"]["name"]]["metadata"]["labels"] or {}).get(
                consts.PLACEMENT_LABEL
            )
            if owner == "s0":
                assert consts.PLACEMENT_LABEL not in labels
                assert consts.PLACEMENT_INDEX_LABEL not in labels
            else:
                assert labels == originals[node["metadata"]["name"]]["metadata"]["labels"]
        # inputs untouched (copies, not mutation)
        assert any(
            (n["metadata"]["labels"] or {}).get(consts.PLACEMENT_LABEL) == "s0"
            for n in nodes
        )

    def test_migration_scores_omit_unseatable_candidates(self):
        # a gang whose shape no longer fits anywhere else AND whose own
        # cells are the only home: stripping it still re-seats it (its
        # old cells are free in the replay) — so to get an omission we
        # ask about a candidate that is not placed at all
        client = _pooled(2, ["2x2x1"])
        slices = client.list(TPU_SLICE_API_VERSION, "TPUSlice")
        nodes = client.list("v1", "Node")
        client.create(new_tpu_slice("unplaced", {"placement": {"shape": "9x9x9"}}))
        slices = client.list(TPU_SLICE_API_VERSION, "TPUSlice")
        scores = migration_scores(slices, nodes, ["unplaced", "s0"])
        assert "unplaced" not in scores
        assert "s0" in scores

    def test_cross_pool_reseat_scores_the_source_pool(self):
        """A candidate the replay re-seats in ANOTHER pool must still
        score frag_before/after on its SOURCE pool — differencing two
        pools' unrelated numbers manufactures phantom improvements."""
        client = FakeClient()
        # pool A: 2x2x1 of v4; pool B: separate nodepool, fully free
        for node in make_torus_nodes((2, 2, 1), prefix="pa", nodepool="pool-a"):
            client.create(node)
        for node in make_torus_nodes((2, 2, 1), prefix="pb", nodepool="pool-b"):
            client.create(node)
        # candidate "c" holds half of pool A; a HIGHER-priority request
        # pinned to A wants the whole pool — in the strip-replay the
        # priority order admits "big" first (taking all of A), so "c"
        # re-seats in pool B: a genuine cross-pool migration
        place = PlacementReconciler(client, NS)
        client.create(new_tpu_slice("c", {"placement": {"shape": "2x1x1"}}))
        place.reconcile(QUEUE_REQUEST)
        c_status = (client.get(TPU_SLICE_API_VERSION, "TPUSlice", "c").get("status") or {})["placement"]
        source_pool = c_status["pool"]
        client.create(new_tpu_slice("big", {"placement": {
            "shape": "2x2x1", "pool": source_pool, "priority": 1,
        }}))
        place.reconcile(QUEUE_REQUEST)
        slices = client.list(TPU_SLICE_API_VERSION, "TPUSlice")
        nodes = client.list("v1", "Node")
        scores = migration_scores(slices, nodes, ["c"])
        assert "c" in scores
        entry = scores["c"]
        assert entry["dest_pool"] != source_pool  # it really moved pools
        assert entry["pool"] == source_pool
        assert "big" in entry["lands_pending"]
        # the source pool's replayed fragmentation, not the dest's
        plan = replay_minus_candidate(slices, nodes, "c", migrate=True)
        assert entry["frag_after"] == plan.fragmentation.get(source_pool, 0.0)

    def test_pick_migration_prefers_seating_pending(self):
        scores = {
            "a": {"lands_pending": [], "frag_delta": -0.5, "frag_after": 0.1},
            "b": {"lands_pending": ["big"], "frag_delta": 0.01, "frag_after": 0.6},
        }
        assert pick_migration(scores) == "b"
        assert pick_migration({"a": {"lands_pending": [], "frag_delta": 0.0,
                                     "frag_after": 0.1}}) is None

    def test_scorer_hook_reorders_clean_fits(self):
        node_at = {(x, y, 0): f"n{x}-{y}" for x in range(4) for y in range(4)}
        torus = Torus((4, 4, 1), node_at, wrap=False)
        # occupy the origin corner so stock best-fit would pick a snug
        # spot beside it; a scorer that prefers the FAR corner overrides
        torus.occupy("a", [(0, 0, 0), (1, 0, 0)])

        def far_corner(origin, oriented, _cells):
            return -float(sum(origin))

        stock = torus.find_block((2, 1, 1))[0]
        scored = torus.find_block((2, 1, 1), scorer=far_corner)[0]
        assert stock.origin != scored.origin
        assert sum(scored.origin) > sum(stock.origin)

    def test_pack_scorer_prefers_origin_corner(self):
        node_at = {(x, y, 0): f"n{x}-{y}" for x in range(4) for y in range(4)}
        torus = Torus((4, 4, 1), node_at, wrap=False)
        found = torus.find_block((2, 2, 1), scorer=torus.pack_scorer())
        assert found[0].origin == (0, 0, 0)

    def test_exposure_cap_prunes_but_never_misranks(self):
        node_at = {(x, y, 0): f"n{x}-{y}" for x in range(4) for y in range(4)}
        torus = Torus((4, 4, 1), node_at, wrap=True)
        torus.occupy("a", [(0, 0, 0)])
        cells = [(2, 2, 0), (3, 2, 0)]
        exact = torus.exposure(cells)
        assert torus.exposure(cells, cap=exact) == exact  # equal cap stays exact
        assert torus.exposure(cells, cap=0) > 0  # pruned value still loses


# ---------------------------------------------------------------------------
# fleet simulator + what-ifs
# ---------------------------------------------------------------------------


class TestFleetSim:
    def _schedule(self):
        return GangChurnSchedule(
            seed=11, ticks=40, arrivals_per_tick=1.0,
            shapes=(((2, 2, 1), 3.0), ((2, 2, 2), 2.0), ((4, 2, 2), 1.0)),
            min_lifetime=10, max_lifetime=25,
        )

    def test_schedule_seeded_determinism(self):
        a = GangChurnSchedule(seed=5, ticks=30)
        b = GangChurnSchedule(seed=5, ticks=30)
        c = GangChurnSchedule(seed=6, ticks=30)
        assert a.log == b.log
        assert a.log != c.log

    def test_sim_deterministic_and_reports(self):
        r1 = FleetSimulator(dims=(4, 4, 4), policy="best-fit").run(self._schedule())
        r2 = FleetSimulator(dims=(4, 4, 4), policy="best-fit").run(self._schedule())
        assert r1 == r2
        assert r1["hosts"] == 64
        assert 0.0 <= r1["utilization_pct"] <= 100.0
        # waiting may double-count preempted gangs that already placed
        # once (they re-queue), so the two sums are bounded separately
        assert r1["gangs_placed"] <= r1["gangs_arrived"]
        assert r1["gangs_waiting"] <= r1["gangs_arrived"]

    def test_unknown_policy_rejected(self):
        with pytest.raises(ValueError):
            FleetSimulator(policy="magic")

    def test_defrag_policy_migrates_within_budget(self):
        sim = FleetSimulator(
            dims=(4, 4, 4), policy="defrag-aware",
            migration_budget=2, migration_cooldown_ticks=1, defrag_every=1,
        )
        sim.run(self._schedule(), drain_ticks=20)
        assert 0 <= sim.migrations <= 2

    def test_best_fit_never_migrates(self):
        sim = FleetSimulator(dims=(4, 4, 4), policy="best-fit")
        report = sim.run(self._schedule(), drain_ticks=10)
        assert report["migrations"] == 0

    def test_preemption_counted(self):
        sched = GangChurnSchedule(
            seed=3, ticks=30, arrivals_per_tick=2.0,
            shapes=(((2, 2, 2), 2.0), ((4, 4, 2), 1.0)),
            min_lifetime=30, max_lifetime=40, priority_levels=3,
        )
        report = FleetSimulator(dims=(4, 4, 2), policy="best-fit").run(sched)
        assert report["preemptions"] >= 1


class TestWhatIf:
    def test_fits_now(self):
        client = _pooled(1, ["2x2x1"])
        answer = admission_answer(
            client.list(TPU_SLICE_API_VERSION, "TPUSlice"),
            client.list("v1", "Node"), "2x2x1",
        )
        assert answer["answer"] == "now"
        assert answer["eta_seconds"] == 0.0

    def test_never_fits(self):
        client = _pooled(1, ["2x2x1"])
        answer = admission_answer(
            client.list(TPU_SLICE_API_VERSION, "TPUSlice"),
            client.list("v1", "Node"), "9x9x9",
        )
        assert answer["answer"] == "no"

    def test_unparseable_shape(self):
        assert admission_answer([], [], "banana")["answer"] == "no"

    def test_existing_queued_slice_answers_from_its_own_replay(self):
        """for_slice: the replay seats the queried request itself —
        demanding a SECOND block of the same shape would double-count
        and answer "no" for a gang the next pass places."""
        client = FakeClient()
        for node in make_torus_nodes((2, 2, 1), prefix="fq"):
            client.create(node)
        client.create(new_tpu_slice("only", {"placement": {"shape": "2x2x1"}}))
        slices = client.list(TPU_SLICE_API_VERSION, "TPUSlice")
        nodes = client.list("v1", "Node")
        # the pool is exactly one 2x2x1 block: a hypothetical EXTRA gang
        # cannot land, but the queued slice itself can
        hypothetical = admission_answer(slices, nodes, "2x2x1")
        assert hypothetical["answer"] == "no"
        own = admission_answer(slices, nodes, "2x2x1", for_slice="only")
        assert own["answer"] == "now"

    def test_queued_shapes_lists_unscheduled_only(self):
        client = _pooled(2, ["2x2x1"])
        client.create(new_tpu_slice("stuck", {"placement": {"shape": "8x8x8"}}))
        PlacementReconciler(client, NS).reconcile(QUEUE_REQUEST)
        queued = queued_shapes(client.list(TPU_SLICE_API_VERSION, "TPUSlice"))
        assert queued == {"stuck": "8x8x8"}

    def test_plan_report_renders(self):
        client = _pooled(2, ["2x2x1"])
        report = plan_report(
            client.list(TPU_SLICE_API_VERSION, "TPUSlice"),
            client.list("v1", "Node"),
            shape="2x2x1", horizon_seconds=300.0,
        )
        assert "capacity posture" in report
        assert "predicted_step=" in report
        assert "what-if: 2x2x1" in report
        assert "now —" in report


# ---------------------------------------------------------------------------
# the defrag controller
# ---------------------------------------------------------------------------


def _fragmented_cluster(with_wanted: bool = True):
    """The defrag smoke's seeded construction, compressed: serving-owned
    pairs churned on the 512-host torus until (``with_wanted``) a 4x4x4
    is Unschedulable and exactly one migration seats it (seed pinned)."""
    import random as random_mod

    client = FakeClient()
    for node in make_torus_nodes((8, 8, 8), prefix="f"):
        client.create(node)
    rng = random_mod.Random(0)
    place = PlacementReconciler(client, NS)
    shapes = ["2x2x2", "4x2x2", "4x4x2", "2x2x1"]
    names = []
    for i in range(32):
        body = new_tpu_slice(f"g{i}", {"placement": {"shape": rng.choice(shapes)}})
        body["metadata"]["ownerReferences"] = [{
            "apiVersion": "tpu.google.com/v1alpha1", "kind": "TPUServing",
            "name": f"svc{i // 2}", "uid": f"u{i // 2}",
        }]
        client.create(body)
        names.append(f"g{i}")
    place.reconcile(QUEUE_REQUEST)
    for name in rng.sample(names, 16):
        client.delete(TPU_SLICE_API_VERSION, "TPUSlice", name)
    place.reconcile(QUEUE_REQUEST)
    place.reconcile(QUEUE_REQUEST)
    if with_wanted:
        client.create(new_tpu_slice("wanted", {"placement": {"shape": "4x4x4"}}))
        place.reconcile(QUEUE_REQUEST)
    return client, place


def _phase(client, name):
    obj = client.get_or_none(TPU_SLICE_API_VERSION, "TPUSlice", name)
    return (((obj or {}).get("status") or {}).get("placement") or {}).get("phase", "")


def _decisions(client):
    cm = client.get_or_none("v1", "ConfigMap", consts.DEFRAG_STATE_CONFIGMAP, NS)
    raw = ((cm or {}).get("data") or {}).get(consts.DEFRAG_STATE_KEY, "")
    try:
        return (json.loads(raw) or {}).get("decisions", [])
    except ValueError:
        return []


class TestDefragController:
    def _controller(self, client, at=1000.0):
        defrag = DefragReconciler(client, NS)
        clock = [at]
        defrag._now = lambda: clock[0]
        return defrag, clock

    def test_idle_gate_no_migration_while_placement_queued(self):
        client, place = _fragmented_cluster()
        client.create(new_tpu_slice("fresh", {"placement": {"shape": "2x2x1"}}))
        defrag, _ = self._controller(client)
        defrag.reconcile(DEFRAG_REQUEST)
        assert all(d.get("executed_at") is None for d in _decisions(client))
        place.reconcile(QUEUE_REQUEST)  # probe seated: now idle
        defrag.reconcile(DEFRAG_REQUEST)
        assert any(d.get("executed_at") is not None for d in _decisions(client))

    def test_pure_consolidation_strictly_reduces_fragmentation(self):
        """With no pending demand, an executed migration's realized
        fragmentation must land strictly below the before value — and
        match the prediction exactly (same replay, same world)."""
        client, place = _fragmented_cluster(with_wanted=False)
        defrag, _ = self._controller(client)
        defrag.reconcile(DEFRAG_REQUEST)
        place.reconcile(QUEUE_REQUEST)
        defrag.reconcile(DEFRAG_REQUEST)
        settled = [d for d in _decisions(client) if d.get("realized_frag") is not None]
        assert settled
        for d in settled:
            assert d["realized_frag"] < d["frag_before"]
            assert d["realized_frag"] == pytest.approx(d["predicted_frag"])

    def test_unschedulable_request_does_not_block_and_gets_seated(self):
        client, place = _fragmented_cluster()
        assert _phase(client, "wanted") == "Unschedulable"
        defrag, clock = self._controller(client)
        for _ in range(4):
            clock[0] += consts.DEFRAG_COOLDOWN_SECONDS + 1
            defrag.reconcile(DEFRAG_REQUEST)
            place.reconcile(QUEUE_REQUEST)
            defrag.reconcile(DEFRAG_REQUEST)
            if _phase(client, "wanted") == "Scheduled":
                break
        assert _phase(client, "wanted") == "Scheduled"
        # the winning decision reclaimed capacity for the parked gang
        # (the seated 64-host block may raise the residual free-space
        # number — that's reclaimed capacity, not a regression; strict
        # decrease is the pure-consolidation test's gate)
        assert any(
            "wanted" in (d.get("lands_pending") or []) for d in _decisions(client)
        )
        events = [e.get("reason") for e in client.list("v1", "Event", "default")]
        assert "DefragProposed" in events and "DefragMigrated" in events

    def test_cooldown_blocks_consecutive_migrations(self):
        client, place = _fragmented_cluster()
        defrag, clock = self._controller(client)
        defrag.reconcile(DEFRAG_REQUEST)
        executed = [d for d in _decisions(client) if d.get("executed_at")]
        assert len(executed) == 1
        place.reconcile(QUEUE_REQUEST)
        clock[0] += 1.0  # inside the cooldown
        defrag.reconcile(DEFRAG_REQUEST)  # settles, must not propose
        defrag.reconcile(DEFRAG_REQUEST)
        executed = [d for d in _decisions(client) if d.get("executed_at")]
        assert len(executed) == 1

    def test_budget_caps_migrations_per_window(self):
        client, place = _fragmented_cluster()
        defrag, clock = self._controller(client)
        for _ in range(consts.DEFRAG_MIGRATION_BUDGET + 3):
            defrag.reconcile(DEFRAG_REQUEST)
            place.reconcile(QUEUE_REQUEST)
            defrag.reconcile(DEFRAG_REQUEST)
            clock[0] += consts.DEFRAG_COOLDOWN_SECONDS + 1  # cooldown passes,
            # but the window doesn't
        executed = [d for d in _decisions(client) if d.get("executed_at")]
        assert len(executed) <= consts.DEFRAG_MIGRATION_BUDGET

    def test_unowned_gangs_never_touched(self):
        client = FakeClient()
        for node in make_torus_nodes((4, 4, 1), prefix="u"):
            client.create(node)
        client.create(new_tpu_slice("bare", {"placement": {"shape": "2x2x1"}}))
        PlacementReconciler(client, NS).reconcile(QUEUE_REQUEST)
        defrag, _ = self._controller(client)
        defrag.reconcile(DEFRAG_REQUEST)
        assert defrag._migratable(
            {s["metadata"]["name"]: s
             for s in client.list(TPU_SLICE_API_VERSION, "TPUSlice")}
        ) == {}
        assert not [d for d in _decisions(client) if d.get("executed_at")]

    def test_last_routable_serving_replica_never_drained(self):
        client = FakeClient()
        for node in make_torus_nodes((4, 4, 1), prefix="lr"):
            client.create(node)
        body = new_tpu_slice("solo-replica-0", {"placement": {"shape": "2x2x1"}})
        body["metadata"]["ownerReferences"] = [{
            "apiVersion": "tpu.google.com/v1alpha1", "kind": "TPUServing",
            "name": "solo", "uid": "u",
        }]
        client.create(body)
        PlacementReconciler(client, NS).reconcile(QUEUE_REQUEST)
        defrag, _ = self._controller(client)
        migratable = defrag._migratable(
            {s["metadata"]["name"]: s
             for s in client.list(TPU_SLICE_API_VERSION, "TPUSlice")}
        )
        assert migratable == {}

    def test_job_gating_requires_running_and_progress_cm(self):
        client = FakeClient()
        for node in make_torus_nodes((4, 4, 1), prefix="jg"):
            client.create(node)
        body = new_tpu_slice("tj-slice", {"placement": {"shape": "2x2x1"}})
        body["metadata"]["ownerReferences"] = [{
            "apiVersion": "tpu.google.com/v1alpha1", "kind": "TPUJob",
            "name": "tj", "uid": "u",
        }]
        client.create(body)
        PlacementReconciler(client, NS).reconcile(QUEUE_REQUEST)
        defrag, _ = self._controller(client)

        def migratable():
            return defrag._migratable(
                {s["metadata"]["name"]: s
                 for s in client.list(TPU_SLICE_API_VERSION, "TPUSlice")}
            )

        assert migratable() == {}  # no TPUJob object at all
        client.create(new_tpu_job("tj", {
            "workload": {"steps": 10}, "gang": {"shape": "2x2x1"},
        }))
        assert migratable() == {}  # job exists but not Running
        client.patch_status(
            "tpu.google.com/v1alpha1", "TPUJob", "tj",
            {"status": {"job": {"phase": JobPhase.RUNNING}}},
        )
        assert migratable() == {}  # no progress CM: nobody to barrier with
        client.create(new_object(
            "v1", "ConfigMap", "tj" + consts.JOB_PROGRESS_SUFFIX, NS, data={}
        ))
        assert "tj-slice" in migratable()

    def test_headroom_blocks_defrag_when_fleet_hot(self, monkeypatch):
        client, place = _fragmented_cluster()
        monkeypatch.setattr(consts, "DEFRAG_UTILIZATION_HEADROOM", 0.01)
        defrag, _ = self._controller(client)
        defrag.reconcile(DEFRAG_REQUEST)
        assert not [d for d in _decisions(client) if d.get("executed_at")]

    def test_unreadable_state_cm_fails_closed(self, monkeypatch):
        """A transient ApiError on the ledger read must abort the pass
        — resetting to an empty ledger would hand the whole migration
        budget back and overwrite the history on the next write."""
        from tpu_operator.kube import errors as kube_errors

        client, place = _fragmented_cluster()
        defrag, _ = self._controller(client)
        real_get = client.get_or_none

        def flaky_get(api_version, kind, name, *a, **kw):
            if kind == "ConfigMap" and name == consts.DEFRAG_STATE_CONFIGMAP:
                raise kube_errors.ApiError("state CM 500")
            return real_get(api_version, kind, name, *a, **kw)

        monkeypatch.setattr(client, "get_or_none", flaky_get)
        defrag.reconcile(DEFRAG_REQUEST)  # must not raise, must not propose
        monkeypatch.undo()
        assert _decisions(client) == []  # nothing written over the ledger

    def test_quiet_pass_writes_nothing(self):
        """An idle pass with nothing to settle or propose performs zero
        state-CM writes (the fabric analyzer's quiet-pass rule)."""
        client = FakeClient()
        for node in make_torus_nodes((4, 4, 1), prefix="qp"):
            client.create(node)
        defrag, _ = self._controller(client)
        defrag.reconcile(DEFRAG_REQUEST)
        defrag.reconcile(DEFRAG_REQUEST)
        assert client.get_or_none(
            "v1", "ConfigMap", consts.DEFRAG_STATE_CONFIGMAP, NS
        ) is None

    def test_sibling_with_out_of_service_member_does_not_count(self):
        """'Never drain the last routable replica': a sibling that is
        placed but dying (member out of service) cannot justify
        draining its peer."""
        client = FakeClient()
        for node in make_torus_nodes((4, 4, 1), prefix="sv"):
            client.create(node)
        for i in (0, 1):
            body = new_tpu_slice(
                f"dup-replica-{i}", {"placement": {"shape": "2x1x1"}}
            )
            body["metadata"]["ownerReferences"] = [{
                "apiVersion": "tpu.google.com/v1alpha1", "kind": "TPUServing",
                "name": "dup", "uid": "u",
            }]
            client.create(body)
        PlacementReconciler(client, NS).reconcile(QUEUE_REQUEST)
        defrag, _ = self._controller(client)

        def migratable():
            return defrag._migratable(
                {s["metadata"]["name"]: s
                 for s in client.list(TPU_SLICE_API_VERSION, "TPUSlice")}
            )

        assert set(migratable()) == {"dup-replica-0", "dup-replica-1"}
        # replica 1's gang host goes out of service: replica 0 loses its
        # healthy sibling and becomes untouchable (and vice versa — the
        # broken gang itself stops being phase-Scheduled only after the
        # next placement pass, so gate on member health, not phase)
        r1 = client.get(TPU_SLICE_API_VERSION, "TPUSlice", "dup-replica-1")
        member = ((r1.get("status") or {}).get("placement") or {})["nodes"][0]
        client.patch(
            "v1", "Node", member,
            {"metadata": {"labels": {consts.TPU_PERF_LABEL: consts.PERF_DEGRADED}}},
        )
        assert "dup-replica-0" not in migratable()

    def test_zero_progress_drain_is_not_an_executed_migration(self, monkeypatch):
        """A drain whose FIRST node patch fails cleared nothing: no
        decision booked, no budget spent, no counter bump — otherwise
        one flaky write blocks defrag behind a phantom in-flight
        decision for the whole timeout."""
        from tpu_operator.kube import errors as kube_errors

        client, place = _fragmented_cluster()
        defrag, _ = self._controller(client)

        def broken_patch(api_version, kind, *a, **kw):
            if kind == "Node":
                raise kube_errors.ApiError("node patch 500")
            return FakeClient.patch(client, api_version, kind, *a, **kw)

        monkeypatch.setattr(client, "patch", broken_patch)
        defrag.reconcile(DEFRAG_REQUEST)
        monkeypatch.undo()
        assert not [d for d in _decisions(client) if d.get("executed_at")]

    def test_malformed_state_cm_never_crashes(self):
        client, place = _fragmented_cluster()
        client.create(new_object(
            "v1", "ConfigMap", consts.DEFRAG_STATE_CONFIGMAP, NS,
            data={consts.DEFRAG_STATE_KEY: "{torn"},
        ))
        defrag, _ = self._controller(client)
        defrag.reconcile(DEFRAG_REQUEST)  # must not raise
        assert isinstance(_decisions(client), list)

    def test_utilization_series_published_and_retired(self):
        client, _ = _fragmented_cluster()
        defrag, _ = self._controller(client)
        defrag.reconcile(DEFRAG_REQUEST)
        assert defrag._util_pools and defrag._pred_generations
        # pool drains: every node deleted
        for node in client.list("v1", "Node"):
            client.delete("v1", "Node", node["metadata"]["name"])
        defrag.reconcile(DEFRAG_REQUEST)
        assert defrag._util_pools == set()
        assert defrag._pred_generations == set()

    def test_failed_link_map_read_aborts_pass(self, monkeypatch):
        client, _ = _fragmented_cluster()
        defrag, _ = self._controller(client)

        def boom(*_a, **_k):
            from tpu_operator.kube import errors

            raise errors.ApiError("link map 500")

        import tpu_operator.controllers.fabric_telemetry as ft

        monkeypatch.setattr(ft, "degraded_link_pairs", boom)
        defrag.reconcile(DEFRAG_REQUEST)
        assert not _decisions(client)  # nothing proposed, nothing written


# ---------------------------------------------------------------------------
# the job controller's checkpoint-barrier migration arm
# ---------------------------------------------------------------------------


class TestJobDefragBarrier:
    def _world(self):
        client = FakeClient()
        for node in make_torus_nodes((4, 2, 1), prefix="jb"):
            client.create(node)
        client.create(new_tpu_job("tj", {
            "workload": {"steps": 1000}, "gang": {"shape": "2x2x1"},
        }))
        job_rec = JobReconciler(client, NS)
        place = PlacementReconciler(client, NS)
        name = "tj" + consts.JOB_PROGRESS_SUFFIX

        def trainer():
            cm = client.get_or_none("v1", "ConfigMap", name, NS)
            if cm is None:
                client.create(new_object("v1", "ConfigMap", name, NS, data={}))
                cm = client.get("v1", "ConfigMap", name, NS)
            slice_obj = client.get_or_none(
                TPU_SLICE_API_VERSION, "TPUSlice", "tj-slice"
            )
            placement = ((slice_obj or {}).get("status") or {}).get("placement") or {}
            data = {
                consts.JOB_PROGRESS_STEP: "42",
                consts.JOB_PROGRESS_CHECKPOINT_STEP: "40",
                consts.JOB_PROGRESS_EPOCH: "4",
                consts.JOB_PROGRESS_WORLD: str(len(placement.get("nodes") or [])),
                consts.JOB_PROGRESS_STATUS: consts.JOB_PROGRESS_RUNNING,
            }
            request = (cm.get("data") or {}).get(consts.JOB_CHECKPOINT_REQUEST, "")
            if request:
                data[consts.JOB_PROGRESS_CHECKPOINT_ACK] = request
            client.patch("v1", "ConfigMap", name, {"data": data}, NS)

        for _ in range(4):
            job_rec.reconcile(Request(name="tj"))
            place.reconcile(QUEUE_REQUEST)
            trainer()
        return client, job_rec, place, trainer

    def _block(self, client):
        job = client.get("tpu.google.com/v1alpha1", "TPUJob", "tj")
        return (job.get("status") or {}).get("job") or {}

    def test_defrag_request_drives_barrier_then_teardown_then_resume(self):
        client, job_rec, place, trainer = self._world()
        assert self._block(client).get("phase") == JobPhase.RUNNING
        client.patch(
            "v1", "ConfigMap", "tj" + consts.JOB_PROGRESS_SUFFIX,
            {"data": {consts.JOB_DEFRAG_REQUEST: "defrag-t1"}}, NS,
        )
        job_rec.reconcile(Request(name="tj"))
        block = self._block(client)
        assert block["phase"] == JobPhase.CHECKPOINTING
        assert str(block.get("barrier", "")).startswith("defrag-")
        trainer()  # ack the barrier
        job_rec.reconcile(Request(name="tj"))
        block = self._block(client)
        # gang torn down (labels cleared) and the job is resuming
        assert block["phase"] in (JobPhase.RESUMING, JobPhase.PLACING)
        assert block.get("defragHandled") == "defrag-t1"
        assert not any(
            (n["metadata"].get("labels") or {}).get(consts.PLACEMENT_LABEL)
            == "tj-slice"
            for n in client.list("v1", "Node")
        )
        for _ in range(4):
            place.reconcile(QUEUE_REQUEST)
            trainer()
            job_rec.reconcile(Request(name="tj"))
        block = self._block(client)
        assert block["phase"] == JobPhase.RUNNING
        assert block["step"] == 42  # watermark intact across the move

    def test_handled_token_is_idempotent(self):
        client, job_rec, place, trainer = self._world()
        client.patch(
            "v1", "ConfigMap", "tj" + consts.JOB_PROGRESS_SUFFIX,
            {"data": {consts.JOB_DEFRAG_REQUEST: "defrag-t1"}}, NS,
        )
        for _ in range(6):
            job_rec.reconcile(Request(name="tj"))
            place.reconcile(QUEUE_REQUEST)
            trainer()
        seq = self._block(client).get("barrierSeq")
        for _ in range(3):
            job_rec.reconcile(Request(name="tj"))
            trainer()
        assert self._block(client).get("barrierSeq") == seq
        assert self._block(client).get("phase") == JobPhase.RUNNING

    def test_grow_barrier_still_wins_over_defrag(self):
        """A shrunk job's grow opportunity outranks a defrag request —
        and the grow path's CHECKPOINTING arm is untouched by the
        defrag branch (token prefix routing)."""
        client, job_rec, place, trainer = self._world()
        client.patch(
            "v1", "ConfigMap", "tj" + consts.JOB_PROGRESS_SUFFIX,
            {"data": {consts.JOB_DEFRAG_REQUEST: "defrag-t9"}}, NS,
        )
        job_rec.reconcile(Request(name="tj"))
        block = self._block(client)
        assert str(block.get("barrier", "")).startswith("defrag-")


# ---------------------------------------------------------------------------
# must-gather plan.txt
# ---------------------------------------------------------------------------


class TestPlanBundle:
    def test_plan_txt_contents(self, tmp_path):
        from tpu_operator.mustgather import collect

        client, place = _fragmented_cluster()
        defrag = DefragReconciler(client, NS)
        defrag._now = lambda: 1000.0
        defrag.reconcile(DEFRAG_REQUEST)
        place.reconcile(QUEUE_REQUEST)
        defrag.reconcile(DEFRAG_REQUEST)
        written = collect(client, NS, str(tmp_path))
        assert "plan.txt" in written
        text = (tmp_path / "plan.txt").read_text()
        assert "# pools" in text
        assert "fragmentation=" in text and "utilization=" in text
        assert "# defrag decisions" in text
        assert "owner=TPUServing" in text
        assert "# admission what-ifs" in text

    def test_plan_txt_empty_cluster(self, tmp_path):
        from tpu_operator.mustgather import collect

        client = FakeClient()
        written = collect(client, NS, str(tmp_path))
        assert "plan.txt" in written
        text = (tmp_path / "plan.txt").read_text()
        assert "# none" in text
