"""Rolling libtpu-upgrade drill against any conformant apiserver.

Transport-agnostic: the same drill runs against the HTTP-served fake
apiserver in the regular suite (tests/test_httpserver.py) and against a
real cluster when KUBECONFIG is supplied (tests/test_e2e_real.py) —
proving the upgrade FSM against real eviction/PDB semantics
(reference: the vendored upgrade lib's drain path,
vendor/.../upgrade/upgrade_state.go:67-101).

The drill provisions a synthetic tainted Node plus a driver DaemonSet/
pod pair and plays the parts the synthetic node lacks (kubelet: pod
status + termination finalizing; DS controller: driver-pod recreation at
the current generation). Everything it creates is namespaced except the
Node, and all of it is cleaned up.
"""

from __future__ import annotations

import time
import uuid

from tpu_operator import consts
from tpu_operator.api.clusterpolicy import UpgradePolicySpec
from tpu_operator.kube import errors
from tpu_operator.kube.objects import new_object
from tpu_operator.upgrade.fsm import (
    DRIVER_POD_COMPONENT,
    DRIVER_POD_COMPONENT_LABEL,
    POD_TEMPLATE_GENERATION_LABEL,
    ClusterUpgradeStateManager,
    UpgradeState,
)

PAUSE_IMAGE = "registry.k8s.io/pause:3.9"
DRILL_TAINT = {"key": "tpu.google.com/upgrade-drill", "effect": "NoSchedule"}


def _mark_running(client, name: str, ns: str) -> None:
    """Play the kubelet: pod Running + Ready (the disruption controller
    counts Ready pods when computing PDB budgets)."""
    pod = client.get_or_none("v1", "Pod", name, ns)
    if pod is None:
        return
    pod["status"] = {
        "phase": "Running",
        "conditions": [{"type": "Ready", "status": "True"}],
    }
    try:
        client.update_status(pod)
    except errors.Conflict:
        pass


def _finalize_terminating(client, ns: str, node_name: str) -> None:
    """Play the kubelet: force-finalize pods the eviction API put into
    Terminating (a synthetic node has no kubelet to confirm)."""
    for pod in client.list("v1", "Pod", ns):
        md = pod["metadata"]
        if md.get("deletionTimestamp") and pod.get("spec", {}).get("nodeName") == node_name:
            try:
                client.delete("v1", "Pod", md["name"], ns, grace_period_seconds=0)
            except errors.ApiError:
                pass


class UpgradeDrill:
    def __init__(self, client, ns: str):
        self.client = client
        self.ns = ns
        suffix = uuid.uuid4().hex[:8]
        self.node_name = f"tpu-drill-{suffix}"
        self.ds_name = f"libtpu-drill-{suffix}"
        self.driver_pod = f"{self.ds_name}-0"
        self.workload_pod = f"drill-workload-{suffix}"
        self.pdb_name = f"drill-pdb-{suffix}"
        self.workload_app = f"drill-critical-{suffix}"

    # -- setup / teardown ----------------------------------------------------

    def setup(self) -> None:
        c = self.client
        c.create(
            new_object(
                "v1",
                "Node",
                self.node_name,
                labels={consts.TPU_PRESENT_LABEL: "true"},
                spec={"taints": [dict(DRILL_TAINT)]},
            )
        )
        # Keep a REAL DaemonSet controller's hands off the drill pod: the
        # DS selector matches a label the pod does not carry (so the
        # controller neither adopts nor deletes it), its nodeSelector
        # matches no node (so it schedules nothing), and the pod's
        # ownerReference below is controller: False. The FSM only needs
        # the ownerReference kind/name to resolve the owning DS.
        c.create(
            new_object(
                "apps/v1",
                "DaemonSet",
                self.ds_name,
                self.ns,
                spec={
                    "selector": {"matchLabels": {"app": f"{self.ds_name}-template"}},
                    "template": {
                        "metadata": {"labels": {"app": f"{self.ds_name}-template"}},
                        "spec": {
                            "nodeSelector": {"tpu.google.com/upgrade-drill-never": "true"},
                            "containers": [
                                {"name": "drill", "image": PAUSE_IMAGE, "env": [{"name": "ROUND", "value": "1"}]}
                            ],
                        },
                    },
                },
            )
        )
        self._create_driver_pod()
        c.create(
            new_object(
                "v1",
                "Pod",
                self.workload_pod,
                self.ns,
                labels={"app": self.workload_app},
                spec={
                    "nodeName": self.node_name,
                    "tolerations": [{"key": DRILL_TAINT["key"], "operator": "Exists"}],
                    "containers": [
                        {
                            "name": "w",
                            "image": PAUSE_IMAGE,
                            "resources": {"limits": {consts.TPU_RESOURCE_NAME: "1"}},
                        }
                    ],
                },
            )
        )
        _mark_running(c, self.workload_pod, self.ns)
        c.create(
            new_object(
                "policy/v1",
                "PodDisruptionBudget",
                self.pdb_name,
                self.ns,
                spec={"minAvailable": 1, "selector": {"matchLabels": {"app": self.workload_app}}},
            )
        )

    def teardown(self) -> None:
        c = self.client
        for kind, name, ns in (
            ("PodDisruptionBudget", self.pdb_name, self.ns),
            ("Pod", self.workload_pod, self.ns),
            ("Pod", self.driver_pod, self.ns),
            ("DaemonSet", self.ds_name, self.ns),
            ("Node", self.node_name, None),
        ):
            api = {"DaemonSet": "apps/v1", "PodDisruptionBudget": "policy/v1"}.get(kind, "v1")
            try:
                c.delete(api, kind, name, ns, grace_period_seconds=0 if kind == "Pod" else None)
            except errors.ApiError:
                pass

    def _create_driver_pod(self) -> None:
        ds = self.client.get("apps/v1", "DaemonSet", self.ds_name, self.ns)
        gen = str(ds["metadata"].get("generation", 1))
        pod = new_object(
            "v1",
            "Pod",
            self.driver_pod,
            self.ns,
            labels={
                DRIVER_POD_COMPONENT_LABEL: DRIVER_POD_COMPONENT,
                POD_TEMPLATE_GENERATION_LABEL: gen,
            },
            spec={
                "nodeName": self.node_name,
                "tolerations": [{"key": DRILL_TAINT["key"], "operator": "Exists"}],
                "containers": [{"name": "drill", "image": PAUSE_IMAGE}],
            },
        )
        pod["metadata"]["ownerReferences"] = [
            {
                "apiVersion": "apps/v1",
                "kind": "DaemonSet",
                "name": self.ds_name,
                "uid": ds["metadata"].get("uid", ""),
                # controller False: a real DS controller must not treat the
                # drill's hand-made pod as its own (it would delete it —
                # shouldRunDaemonPod is false for the synthetic node)
                "controller": False,
            }
        ]
        self.client.create(pod)
        _mark_running(self.client, self.driver_pod, self.ns)

    # -- the drill -----------------------------------------------------------

    def bump_generation(self) -> None:
        """Spec change -> metadata.generation increments (a real apiserver
        does this itself; the fake mirrors it), making the driver pod
        outdated."""
        ds = self.client.get("apps/v1", "DaemonSet", self.ds_name, self.ns)
        ds["spec"]["template"]["spec"]["containers"][0]["env"] = [
            {"name": "ROUND", "value": "2"}
        ]
        self.client.update(ds)

    @staticmethod
    def _state_of(node) -> str:
        return (node["metadata"].get("labels") or {}).get(consts.UPGRADE_STATE_LABEL, "")

    def node_state(self) -> str:
        return self._state_of(self.client.get("v1", "Node", self.node_name))

    def run(self, max_passes: int = 40, pass_interval: float = 0.3) -> dict:
        """Drive FSM passes to completion; returns observations for asserts.

        While the PDB blocks, the node must park in pod-deletion-required
        (the real eviction API answering 429); the drill then relaxes the
        PDB and plays kubelet/DS-controller until the node is DONE.
        """
        mgr = ClusterUpgradeStateManager(self.client, self.ns)
        policy = UpgradePolicySpec.from_dict(
            {
                "autoUpgrade": True,
                "maxParallelUpgrades": 1,
                "maxUnavailable": "100%",
                "drain": {"enable": False},
            }
        )
        self.bump_generation()
        obs = {"cordoned": False, "parked_passes": 0, "pdb_relaxed": False}
        for _ in range(max_passes):
            mgr.apply_state(mgr.build_state(), policy)
            node = self.client.get("v1", "Node", self.node_name)
            if node.get("spec", {}).get("unschedulable"):
                obs["cordoned"] = True
            state = self._state_of(node)
            if state == UpgradeState.POD_DELETION_REQUIRED and not obs["pdb_relaxed"]:
                # the eviction must be blocked while the PDB stands
                obs["parked_passes"] += 1
                assert (
                    self.client.get_or_none("v1", "Pod", self.workload_pod, self.ns)
                    is not None
                ), "PDB-protected workload was removed while eviction should be blocked"
                if obs["parked_passes"] >= 2:
                    pdb = self.client.get(
                        "policy/v1", "PodDisruptionBudget", self.pdb_name, self.ns
                    )
                    pdb["spec"]["minAvailable"] = 0
                    self.client.update(pdb)
                    obs["pdb_relaxed"] = True
            # kubelet/DS-controller duties for the synthetic node
            _finalize_terminating(self.client, self.ns, self.node_name)
            if (
                obs["pdb_relaxed"]
                and self.client.get_or_none("v1", "Pod", self.driver_pod, self.ns) is None
            ):
                self._create_driver_pod()
            if state == UpgradeState.DONE:
                break
            time.sleep(pass_interval)
        node = self.client.get("v1", "Node", self.node_name)
        obs["final_state"] = self._state_of(node)
        obs["uncordoned"] = not node.get("spec", {}).get("unschedulable")
        pod = self.client.get_or_none("v1", "Pod", self.driver_pod, self.ns)
        ds = self.client.get("apps/v1", "DaemonSet", self.ds_name, self.ns)
        obs["driver_generation_current"] = bool(pod) and (
            pod["metadata"]["labels"].get(POD_TEMPLATE_GENERATION_LABEL)
            == str(ds["metadata"].get("generation", 1))
        )
        obs["workload_evicted"] = (
            self.client.get_or_none("v1", "Pod", self.workload_pod, self.ns) is None
        )
        return obs


def run_upgrade_drill(client, ns: str, **run_kwargs) -> dict:
    drill = UpgradeDrill(client, ns)
    try:
        # setup inside the try: a partial setup (e.g. the cluster-scoped
        # Node created but the DaemonSet rejected) must still tear down,
        # or the synthetic TPU-labelled Node leaks into a real cluster
        drill.setup()
        return drill.run(**run_kwargs)
    finally:
        drill.teardown()


def assert_drill_passed(obs: dict) -> None:
    assert obs["final_state"] == UpgradeState.DONE, obs
    assert obs["cordoned"] and obs["uncordoned"], obs
    assert obs["parked_passes"] >= 2, f"PDB never parked the node: {obs}"
    assert obs["pdb_relaxed"] and obs["workload_evicted"], obs
    assert obs["driver_generation_current"], obs


# ---------------------------------------------------------------------------
# Health-remediation drill: inject unhealth -> cordon/evict (PDB-honoring)
# -> libtpu reinstall -> revalidate -> uncordon; and separately, exhaust
# the retry budget -> quarantined. Same synthetic-node pattern as the
# upgrade drill (the drill plays the health agent and the kubelet/DS
# controller; the repair FSM under test plays the operator).
# ---------------------------------------------------------------------------


class HealthRepairDrill(UpgradeDrill):
    """Reuses the upgrade drill's fixture (tainted Node + driver DS/pod +
    PDB-protected TPU workload); drives the repair FSM instead."""

    def _set_health(self, verdict: str) -> None:
        """Play the health agent: publish the node verdict label."""
        node = self.client.get("v1", "Node", self.node_name)
        node["metadata"].setdefault("labels", {})[consts.TPU_HEALTH_LABEL] = verdict
        self.client.update(node)

    def _repair_state(self) -> str:
        node = self.client.get("v1", "Node", self.node_name)
        return (node["metadata"].get("labels") or {}).get(consts.REPAIR_STATE_LABEL, "")

    def _drive_repair_loop(
        self, recover, grace_period_seconds: int = 0,
        max_passes: int = 60, pass_interval: float = 0.2,
    ) -> dict:
        """One shared FSM-walk loop for every entry signal: drive
        repair passes to completion, asserting PDB-parked eviction on
        the way, playing the kubelet/DS controller for the synthetic
        node, and calling ``recover()`` once the FSM reaches
        revalidation (the drill playing whichever agent owns the
        triggering signal). Callers set the signal BEFORE calling and
        read the final node state after."""
        from tpu_operator.api.clusterpolicy import HealthMonitorSpec
        from tpu_operator.controllers.health_controller import NodeRepairManager, RepairState

        mgr = NodeRepairManager(self.client, self.ns)
        spec = HealthMonitorSpec.from_dict(
            {"remediation": {"enable": True, "retryLimit": 3, "timeoutSeconds": 300,
              "gracePeriodSeconds": grace_period_seconds}}
        )
        obs = {
            "cordoned": False,
            "parked_passes": 0,
            "pdb_relaxed": False,
            "driver_pod_recreated": False,
            "states_seen": [],
        }
        for _ in range(max_passes):
            mgr.apply_state(spec)
            node = self.client.get("v1", "Node", self.node_name)
            if node.get("spec", {}).get("unschedulable"):
                obs["cordoned"] = True
            state = self._repair_state()
            if state and (not obs["states_seen"] or obs["states_seen"][-1] != state):
                obs["states_seen"].append(state)
            if state == RepairState.EVICTION_REQUIRED and not obs["pdb_relaxed"]:
                # the eviction must be blocked while the PDB stands
                obs["parked_passes"] += 1
                assert (
                    self.client.get_or_none("v1", "Pod", self.workload_pod, self.ns)
                    is not None
                ), "PDB-protected workload was removed while eviction should be blocked"
                if obs["parked_passes"] >= 2:
                    pdb = self.client.get(
                        "policy/v1", "PodDisruptionBudget", self.pdb_name, self.ns
                    )
                    pdb["spec"]["minAvailable"] = 0
                    self.client.update(pdb)
                    obs["pdb_relaxed"] = True
            # kubelet/DS-controller duties for the synthetic node
            _finalize_terminating(self.client, self.ns, self.node_name)
            if (
                obs["pdb_relaxed"]
                and self.client.get_or_none("v1", "Pod", self.driver_pod, self.ns) is None
            ):
                self._create_driver_pod()
                obs["driver_pod_recreated"] = True
            if state == RepairState.REVALIDATE_REQUIRED and obs["driver_pod_recreated"]:
                # the reinstall landed: the owning agent's next probe
                # passes and the triggering signal clears
                recover()
            if not state and obs["cordoned"]:
                break  # repair complete (label cleared)
            time.sleep(pass_interval)
        node = self.client.get("v1", "Node", self.node_name)
        labels = node["metadata"].get("labels") or {}
        obs["final_repair_state"] = labels.get(consts.REPAIR_STATE_LABEL, "")
        obs["uncordoned"] = not node.get("spec", {}).get("unschedulable")
        obs["retries"] = (node["metadata"].get("annotations") or {}).get(
            consts.REPAIR_RETRIES_ANNOTATION
        )
        obs["workload_evicted"] = (
            self.client.get_or_none("v1", "Pod", self.workload_pod, self.ns) is None
        )
        return obs

    def run_repair(self, **loop_kwargs) -> dict:
        """Full heal loop: degraded -> cordon -> PDB-parked eviction ->
        relax -> driver reinstall -> agent re-probe heals -> uncordon."""
        self._set_health(consts.HEALTH_DEGRADED)
        obs = self._drive_repair_loop(
            recover=lambda: self._set_health(consts.HEALTH_HEALTHY), **loop_kwargs
        )
        node = self.client.get("v1", "Node", self.node_name)
        obs["final_health"] = (node["metadata"].get("labels") or {}).get(
            consts.TPU_HEALTH_LABEL, ""
        )
        return obs

    def run_quarantine(self, retry_limit: int = 1, max_passes: int = 40,
                       pass_interval: float = 0.2) -> dict:
        """Budget-exhaustion loop: the node never heals (the drill
        withholds the agent's healthy verdict), every attempt times out
        at revalidation, and the retry budget lands quarantined."""
        from tpu_operator.api.clusterpolicy import HealthMonitorSpec
        from tpu_operator.controllers.health_controller import NodeRepairManager, RepairState

        mgr = NodeRepairManager(self.client, self.ns)
        # PDB out of the way: this scenario exercises the budget, not
        # eviction parking
        pdb = self.client.get("policy/v1", "PodDisruptionBudget", self.pdb_name, self.ns)
        pdb["spec"]["minAvailable"] = 0
        self.client.update(pdb)
        spec = HealthMonitorSpec.from_dict(
            {"remediation": {"enable": True, "retryLimit": retry_limit, "timeoutSeconds": 1,
              "gracePeriodSeconds": 0}}
        )
        self._set_health(consts.HEALTH_DEGRADED)
        obs = {"attempts_observed": 0, "states_seen": []}
        prev_state = ""
        for _ in range(max_passes):
            mgr.apply_state(spec)
            _finalize_terminating(self.client, self.ns, self.node_name)
            if self.client.get_or_none("v1", "Pod", self.driver_pod, self.ns) is None:
                self._create_driver_pod()
            state = self._repair_state()
            if state and state != prev_state:
                obs["states_seen"].append(state)
                if state == RepairState.CORDON_REQUIRED:
                    obs["attempts_observed"] += 1
            prev_state = state
            if state == RepairState.QUARANTINED:
                break
            time.sleep(pass_interval)
        node = self.client.get("v1", "Node", self.node_name)
        obs["final_repair_state"] = self._repair_state()
        obs["still_cordoned"] = bool(node.get("spec", {}).get("unschedulable"))
        obs["retries"] = (node["metadata"].get("annotations") or {}).get(
            consts.REPAIR_RETRIES_ANNOTATION
        )
        return obs


class GreyFailureDrill(HealthRepairDrill):
    """The grey-failure path: the node enters repair on the metrics
    exporter's sustained perf-floor breach (``tpu.google.com/perf=
    degraded``) with NO health verdict at all — a slow-but-alive chip.
    Same fixture (tainted node, driver DS/pod, PDB-protected TPU
    workload), same FSM walk; revalidation passes when the exporter
    clears the label (the drill plays the exporter the way the health
    drill plays the health agent)."""

    def _set_perf(self, degraded: bool) -> None:
        """Play the exporter: publish/clear the perf breach label via
        the same labels-only merge patch the agent sends."""
        self.client.patch(
            "v1", "Node", self.node_name,
            {"metadata": {"labels": {
                consts.TPU_PERF_LABEL: consts.PERF_DEGRADED if degraded else None
            }}},
        )

    def run_grey(self, **loop_kwargs) -> dict:
        """Full grey heal loop: perf=degraded -> cordon -> PDB-parked
        eviction -> relax -> driver reinstall -> probe recovers (label
        clears) -> uncordon. Rides the shared loop with a NONZERO grace
        period, proving grey entry bypasses it."""
        self._set_perf(True)
        obs = self._drive_repair_loop(
            recover=lambda: self._set_perf(False),
            grace_period_seconds=300, **loop_kwargs
        )
        node = self.client.get("v1", "Node", self.node_name)
        labels = node["metadata"].get("labels") or {}
        annotations = node["metadata"].get("annotations") or {}
        obs["final_perf"] = labels.get(consts.TPU_PERF_LABEL, "")
        obs["reason_cleared"] = consts.REPAIR_REASON_ANNOTATION not in annotations
        return obs


def run_grey_failure_drill(client, ns: str, **run_kwargs) -> dict:
    drill = GreyFailureDrill(client, ns)
    try:
        drill.setup()
        return drill.run_grey(**run_kwargs)
    finally:
        drill.teardown()


def assert_grey_failure_drill_passed(obs: dict) -> None:
    from tpu_operator.controllers.health_controller import RepairState

    assert obs["final_repair_state"] == "", obs
    assert obs["final_perf"] == "", obs
    assert obs["cordoned"] and obs["uncordoned"], obs
    assert obs["parked_passes"] >= 2, f"PDB never parked the node: {obs}"
    assert obs["driver_pod_recreated"], obs
    assert obs["reason_cleared"], obs
    walked = obs["states_seen"]
    for expected in (
        RepairState.CORDON_REQUIRED,
        RepairState.EVICTION_REQUIRED,
        RepairState.REINSTALL_REQUIRED,
        RepairState.REVALIDATE_REQUIRED,
        RepairState.UNCORDON_REQUIRED,
    ):
        assert expected in walked, (expected, walked)


def run_health_drill(client, ns: str, **run_kwargs) -> dict:
    drill = HealthRepairDrill(client, ns)
    try:
        drill.setup()
        return drill.run_repair(**run_kwargs)
    finally:
        drill.teardown()


def run_quarantine_drill(client, ns: str, **run_kwargs) -> dict:
    drill = HealthRepairDrill(client, ns)
    try:
        drill.setup()
        return drill.run_quarantine(**run_kwargs)
    finally:
        drill.teardown()


def assert_health_drill_passed(obs: dict) -> None:
    from tpu_operator.controllers.health_controller import RepairState

    assert obs["final_repair_state"] == "", obs
    assert obs["final_health"] == consts.HEALTH_HEALTHY, obs
    assert obs["cordoned"] and obs["uncordoned"], obs
    assert obs["parked_passes"] >= 2, f"PDB never parked the node: {obs}"
    assert obs["pdb_relaxed"] and obs["workload_evicted"], obs
    assert obs["driver_pod_recreated"], obs
    assert obs["retries"] == "1", obs
    walked = obs["states_seen"]
    for expected in (
        RepairState.EVICTION_REQUIRED,
        RepairState.REINSTALL_REQUIRED,
        RepairState.REVALIDATE_REQUIRED,
        RepairState.UNCORDON_REQUIRED,
    ):
        assert expected in walked, (expected, walked)


def assert_quarantine_drill_passed(obs: dict, retry_limit: int = 1) -> None:
    from tpu_operator.controllers.health_controller import RepairState

    assert obs["final_repair_state"] == RepairState.QUARANTINED, obs
    assert obs["still_cordoned"], obs
    assert obs["attempts_observed"] == retry_limit, obs
    assert obs["retries"] == str(retry_limit), obs


# ---------------------------------------------------------------------------
# Placement preemption drill: fill a small host torus with two low-
# priority gangs, then submit a higher-priority slice with
# preemptionPolicy=PreemptLower — exactly ONE victim gang must be torn
# down (minimal victim set), the preemptor scheduled on contiguous
# hosts, and no host double-booked at any point. Runs over the wire
# against any conformant apiserver; test_rbac_gate replays it under the
# shipped operator ClusterRole.
# ---------------------------------------------------------------------------


class PlacementDrill:
    """4x2x1 host torus (8 synthetic nodes), three TPUSlices. The drill
    plays the admin (provisions nodes + CRs); the placement reconciler
    under test plays the operator."""

    def __init__(self, client, ns: str):
        self.client = client
        self.ns = ns
        suffix = uuid.uuid4().hex[:8]
        self.prefix = f"tpu-place-{suffix}"
        self.low_a = f"drill-low-a-{suffix}"
        self.low_b = f"drill-low-b-{suffix}"
        self.high = f"drill-high-{suffix}"
        self.node_names: list = []

    def setup(self) -> None:
        from tpu_operator.kube.sim import make_torus_nodes

        for node in make_torus_nodes((4, 2, 1), prefix=self.prefix):
            node["metadata"]["labels"][consts.TPU_PRESENT_LABEL] = "true"
            self.client.create(node)
            self.node_names.append(node["metadata"]["name"])
        for name, priority, policy in (
            (self.low_a, 0, "Never"),
            (self.low_b, 0, "Never"),
        ):
            self._create_slice(name, priority, policy)

    def _create_slice(self, name: str, priority: int, policy: str) -> None:
        from tpu_operator.api.tpuslice import new_tpu_slice

        self.client.create(  # tpuop-lint: kinds=tpu.google.com/v1alpha1/TPUSlice
            new_tpu_slice(
                name,
                {"placement": {
                    "shape": "2x2x1", "priority": priority,
                    "preemptionPolicy": policy,
                }},
            )
        )

    def teardown(self) -> None:
        from tpu_operator.api.tpuslice import TPU_SLICE_API_VERSION, TPU_SLICE_KIND

        for name in (self.low_a, self.low_b, self.high):
            try:
                self.client.delete(TPU_SLICE_API_VERSION, TPU_SLICE_KIND, name)
            except errors.ApiError:
                pass
        for name in self.node_names:
            try:
                self.client.delete("v1", "Node", name)
            except errors.ApiError:
                pass

    # -- observations --------------------------------------------------------

    def _phase(self, name: str) -> str:
        from tpu_operator.api.tpuslice import TPU_SLICE_API_VERSION, TPU_SLICE_KIND

        obj = self.client.get(TPU_SLICE_API_VERSION, TPU_SLICE_KIND, name)
        return ((obj.get("status") or {}).get("placement") or {}).get("phase", "")

    def _assignments(self) -> dict:
        """node -> owning placement, from the labels the slice manager
        consumes."""
        owners = {}
        for name in self.node_names:
            node = self.client.get_or_none("v1", "Node", name)
            if node is None:
                continue
            owner = (node["metadata"].get("labels") or {}).get(consts.PLACEMENT_LABEL)
            if owner:
                owners[name] = owner
        return owners

    def run(self) -> dict:
        from tpu_operator.controllers.placement_controller import (
            QUEUE_REQUEST,
            PlacementReconciler,
        )
        from tpu_operator.placement.engine import PlacementPhase

        reconciler = PlacementReconciler(self.client, self.ns)
        obs: dict = {"double_booked": False}

        def booked_twice() -> bool:
            from tpu_operator.api.tpuslice import TPU_SLICE_API_VERSION, TPU_SLICE_KIND

            claimed: dict = {}
            for name in (self.low_a, self.low_b, self.high):
                obj = self.client.get_or_none(TPU_SLICE_API_VERSION, TPU_SLICE_KIND, name)
                if obj is None:
                    continue
                st = (obj.get("status") or {}).get("placement") or {}
                if st.get("phase") != PlacementPhase.SCHEDULED:
                    continue
                for node in st.get("nodes") or []:
                    if claimed.setdefault(node, name) != name:
                        return True
            return False

        # phase 1: both low-priority gangs fill the torus
        reconciler.reconcile(QUEUE_REQUEST)
        obs["low_phases_before"] = (self._phase(self.low_a), self._phase(self.low_b))
        obs["assignments_before"] = self._assignments()
        obs["double_booked"] |= booked_twice()
        # phase 2: the high-priority preemptor arrives
        self._create_slice(self.high, priority=10, policy="PreemptLower")
        reconciler.reconcile(QUEUE_REQUEST)
        obs["high_phase"] = self._phase(self.high)
        obs["low_phases_after"] = (self._phase(self.low_a), self._phase(self.low_b))
        obs["assignments_after"] = self._assignments()
        obs["double_booked"] |= booked_twice()
        # phase 3: one more pass — the surviving world must be stable
        # (the torn-down victim stays queued/unschedulable, nothing flaps)
        reconciler.reconcile(QUEUE_REQUEST)
        obs["high_phase_settled"] = self._phase(self.high)
        obs["double_booked"] |= booked_twice()
        obs["victims"] = [
            name for name, phase in zip(
                (self.low_a, self.low_b), obs["low_phases_after"]
            )
            if phase != PlacementPhase.SCHEDULED
        ]
        return obs


def run_placement_drill(client, ns: str) -> dict:
    drill = PlacementDrill(client, ns)
    try:
        drill.setup()
        return drill.run()
    finally:
        drill.teardown()


def assert_placement_drill_passed(obs: dict) -> None:
    from tpu_operator.placement.engine import PlacementPhase

    assert obs["low_phases_before"] == (
        PlacementPhase.SCHEDULED, PlacementPhase.SCHEDULED
    ), obs
    assert len(obs["assignments_before"]) == 8, obs  # torus fully booked
    assert obs["high_phase"] == PlacementPhase.SCHEDULED, obs
    assert obs["high_phase_settled"] == PlacementPhase.SCHEDULED, obs
    # minimal victim set: exactly one low-priority gang torn down
    assert len(obs["victims"]) == 1, obs
    assert not obs["double_booked"], obs


class JobDrill:
    """Elastic-training drill: a 2x2x1 host torus (4 synthetic nodes)
    and one TPUJob driven over the wire by the real job + placement
    reconcilers, with the in-process gang harness playing the data
    plane. One gang member is killed mid-run (health verdict degraded):
    the job must checkpoint-resume through a shrink to the largest
    placeable sub-block, grow back when the host heals, and finish with
    contiguous epoch history. The drill plays the admin (nodes, the
    TPUJob CR) and the gang (trainer + progress ConfigMap); everything
    the operator does — TPUSlice create/patch/delete, tpujobs/status
    patches, Events — must ride the shipped ClusterRole."""

    def __init__(self, client, ns: str):
        self.client = client
        self.ns = ns
        suffix = uuid.uuid4().hex[:8]
        self.prefix = f"tpu-job-{suffix}"
        self.job_name = f"drill-job-{suffix}"
        self.node_names: list = []
        self._store_dir = None

    def setup(self) -> None:
        import tempfile

        from tpu_operator.api.tpujob import new_tpu_job
        from tpu_operator.kube.sim import make_torus_nodes

        for node in make_torus_nodes((2, 2, 1), prefix=self.prefix):
            node["metadata"]["labels"][consts.TPU_PRESENT_LABEL] = "true"
            self.client.create(node)
            self.node_names.append(node["metadata"]["name"])
        # the spec pins the checkpoint store so every worker-pod
        # generation resumes from the SAME store
        self._store_dir = tempfile.mkdtemp(prefix="tpujob-drill-")
        self.client.create(  # tpuop-lint: kinds=tpu.google.com/v1alpha1/TPUJob
            new_tpu_job(self.job_name, {
                "workload": {"steps": 24},
                "gang": {"shape": "2x2x1", "minShape": "1x1x1"},
                "checkpoint": {"everySteps": 4, "dir": self._store_dir},
                "backoff": {"baseSeconds": 0.01, "maxSeconds": 0.05, "retryLimit": 10},
            })
        )

    def teardown(self) -> None:
        from tpu_operator.api.tpujob import TPU_JOB_API_VERSION, TPU_JOB_KIND
        from tpu_operator.api.tpuslice import TPU_SLICE_API_VERSION, TPU_SLICE_KIND

        for api_version, kind, name, ns in (
            (TPU_JOB_API_VERSION, TPU_JOB_KIND, self.job_name, None),
            (TPU_SLICE_API_VERSION, TPU_SLICE_KIND,
             self.job_name + consts.JOB_SLICE_SUFFIX, None),
            ("v1", "ConfigMap", self.job_name + consts.JOB_PROGRESS_SUFFIX, self.ns),
        ):
            try:
                self.client.delete(api_version, kind, name, ns)
            except errors.ApiError:
                pass
        for index in range(4):
            try:
                self.client.delete(
                    "v1", "Pod",
                    f"{self.job_name}{consts.JOB_WORKER_INFIX}{index}", self.ns,
                )
            except errors.ApiError:
                pass
        for name in self.node_names:
            try:
                self.client.delete("v1", "Node", name)
            except errors.ApiError:
                pass

    def _block(self) -> dict:
        from tpu_operator.api.tpujob import TPU_JOB_API_VERSION, TPU_JOB_KIND

        obj = self.client.get_or_none(TPU_JOB_API_VERSION, TPU_JOB_KIND, self.job_name)
        return ((obj or {}).get("status") or {}).get("job") or {}

    def _gang_member(self) -> str:
        for name in self.node_names:
            node = self.client.get_or_none("v1", "Node", name)
            labels = ((node or {}).get("metadata") or {}).get("labels") or {}
            if labels.get(consts.PLACEMENT_LABEL) == self.job_name + consts.JOB_SLICE_SUFFIX:
                return name
        return ""

    def run(self, max_passes: int = 200) -> dict:
        from tpu_operator.api.tpujob import JobPhase
        from tpu_operator.controllers.job_controller import JobReconciler
        from tpu_operator.controllers.placement_controller import (
            QUEUE_REQUEST,
            PlacementReconciler,
        )
        from tpu_operator.kube.controller import Request
        from tpu_operator.kube.sim import PodKubelet
        from tpu_operator.workloads.training import verify_continuity

        job_rec = JobReconciler(self.client, self.ns)
        place_rec = PlacementReconciler(self.client, self.ns)
        # the data plane: the controller renders one worker Pod per gang
        # member and the sim kubelet runs their mains in threads — each
        # re-place is a fresh pod generation resuming from the shared
        # checkpoint store
        kubelet = PodKubelet(self.client, self.ns)
        obs: dict = {"phases": [], "victim": "", "healed": False}
        request = Request(name=self.job_name)
        for _ in range(max_passes):
            job_rec.reconcile(request)
            place_rec.reconcile(QUEUE_REQUEST)
            kubelet.step()
            block = self._block()
            phase = block.get("phase", "")
            if not obs["phases"] or obs["phases"][-1] != phase:
                obs["phases"].append(phase)
            # kill one gang member once the job is training
            if not obs["victim"] and phase == JobPhase.RUNNING and block.get("step", 0) >= 6:
                obs["victim"] = self._gang_member()
                self.client.patch(
                    "v1", "Node", obs["victim"],
                    {"metadata": {"labels": {consts.TPU_HEALTH_LABEL: consts.HEALTH_DEGRADED}}},
                )
            # heal once the job shrank and is training again
            if (obs["victim"] and not obs["healed"]
                    and phase == JobPhase.RUNNING
                    and block.get("shape") != block.get("desiredShape")):
                self.client.patch(
                    "v1", "Node", obs["victim"],
                    {"metadata": {"labels": {consts.TPU_HEALTH_LABEL: consts.HEALTH_HEALTHY}}},
                )
                obs["healed"] = True
            if phase == JobPhase.SUCCEEDED:
                break
        block = self._block()
        obs["final"] = block
        # continuity across POD GENERATIONS: each re-place retired the
        # old gang's pods and started fresh mains resuming from the
        # shared store — the concatenated chief histories must still
        # satisfy the loss-curve continuity predicate
        trainers = kubelet.job_trainers(self.job_name)
        kubelet.stop()
        obs["generations"] = len(trainers)
        if trainers:
            history = [r for t in trainers for r in t.history]
            checkpoints = [c for t in trainers for c in t.checkpoints]
            obs["continuity"] = verify_continuity(
                history, checkpoints, trainers[-1].total_steps
            )
        else:
            obs["continuity"] = {"ok": False, "violations": ["never trained"]}
        obs["resizes"] = [
            (r.get("kind"), r.get("from"), r.get("to")) for r in block.get("shrinks") or []
        ]
        return obs


class ServingDrill:
    """Elastic-serving drill: a 4x2x1 host torus (8 synthetic nodes) and
    one TPUServing driven over the wire by the real serving + placement
    reconcilers, with the seeded traffic sim playing the users/router.
    The load curve bursts (scale-up admitted through the placement
    engine), then lulls (fragmentation-aware scale-down retires the
    allocator-chosen victim). The drill plays the admin (nodes, the
    TPUServing CR) and the traffic side (load ConfigMap demand keys);
    everything the operator does — TPUSlice create/delete,
    tpuservings/status patches, the routing key, Events — must ride the
    shipped ClusterRole."""

    def __init__(self, client, ns: str):
        self.client = client
        self.ns = ns
        suffix = uuid.uuid4().hex[:8]
        self.prefix = f"tpu-serve-{suffix}"
        self.serving_name = f"drill-serving-{suffix}"
        self.node_names: list = []

    def setup(self) -> None:
        from tpu_operator.api.tpuserving import new_tpu_serving
        from tpu_operator.kube.sim import make_torus_nodes

        for node in make_torus_nodes((4, 2, 1), prefix=self.prefix):
            node["metadata"]["labels"][consts.TPU_PRESENT_LABEL] = "true"
            self.client.create(node)
            self.node_names.append(node["metadata"]["name"])
        self.client.create(  # tpuop-lint: kinds=tpu.google.com/v1alpha1/TPUServing
            new_tpu_serving(self.serving_name, {
                "model": {"shape": "2x1x1"},
                "replicas": {"min": 1, "max": 3, "targetRps": 10.0,
                             "cooldownSeconds": 0.05},
                "slo": {"ttftP99Seconds": 5.0},
                "backoff": {"baseSeconds": 0.01, "maxSeconds": 0.05,
                            "retryLimit": 5},
            })
        )

    def teardown(self) -> None:
        from tpu_operator.api.tpuserving import (
            TPU_SERVING_API_VERSION,
            TPU_SERVING_KIND,
        )
        from tpu_operator.api.tpuslice import TPU_SLICE_API_VERSION, TPU_SLICE_KIND

        try:
            self.client.delete(
                TPU_SERVING_API_VERSION, TPU_SERVING_KIND, self.serving_name
            )
        except errors.ApiError:
            pass
        for index in range(4):
            try:
                self.client.delete(
                    TPU_SLICE_API_VERSION, TPU_SLICE_KIND,
                    f"{self.serving_name}{consts.SERVING_REPLICA_INFIX}{index}",
                )
            except errors.ApiError:
                pass
            for infix in (consts.SERVING_DECODE_INFIX, consts.SERVING_PREFILL_INFIX):
                try:
                    self.client.delete(
                        "v1", "Pod",
                        f"{self.serving_name}{infix}{index}", self.ns,
                    )
                except errors.ApiError:
                    pass
        try:
            self.client.delete(
                "v1", "ConfigMap",
                self.serving_name + consts.SERVING_LOAD_SUFFIX, self.ns,
            )
        except errors.ApiError:
            pass
        for name in self.node_names:
            try:
                self.client.delete("v1", "Node", name)
            except errors.ApiError:
                pass

    def _block(self) -> dict:
        from tpu_operator.api.tpuserving import (
            TPU_SERVING_API_VERSION,
            TPU_SERVING_KIND,
        )

        obj = self.client.get_or_none(
            TPU_SERVING_API_VERSION, TPU_SERVING_KIND, self.serving_name
        )
        return ((obj or {}).get("status") or {}).get("serving") or {}

    def run(self, max_passes: int = 120) -> dict:
        import json as _json
        import time as _time

        from tpu_operator.controllers.placement_controller import (
            QUEUE_REQUEST,
            PlacementReconciler,
        )
        from tpu_operator.controllers.serving_controller import ServingReconciler
        from tpu_operator.kube.controller import Request
        from tpu_operator.kube.sim import DiurnalTraffic, PodKubelet, ServingTrafficSim

        serve_rec = ServingReconciler(self.client, self.ns)
        place_rec = PlacementReconciler(self.client, self.ns)
        sim = ServingTrafficSim(
            self.client, self.ns, self.serving_name,
            DiurnalTraffic(seed=7), replica_rps=10.0,
        )
        # the data plane: the controller renders one worker Pod per ready
        # replica and the sim kubelet runs their engine mains in threads
        kubelet = PodKubelet(self.client, self.ns)
        request = Request(name=self.serving_name)
        obs: dict = {"phases": []}

        def beat(rps: float) -> dict:
            sim.override_rps = rps
            serve_rec.reconcile(request)
            place_rec.reconcile(QUEUE_REQUEST)
            sim.step()
            kubelet.step()
            block = self._block()
            phase = block.get("phase", "")
            if not obs["phases"] or obs["phases"][-1] != phase:
                obs["phases"].append(phase)
            return block

        # steady: the min replica places and routes
        for _ in range(5):
            block = beat(3.0)
        obs["steady_ready"] = block.get("ready")
        # burst: immediate scale-up through the placement engine
        for _ in range(max_passes):
            block = beat(25.0)
            if block.get("ready", 0) >= 3:
                break
        obs["burst_ready"] = block.get("ready")
        obs["routed_at_burst"] = dict(sim.routed)
        obs["worker_pods_at_burst"] = len(
            kubelet.serving_workers(self.serving_name))
        # lull: hysteretic, fragmentation-aware scale-down
        deadline = _time.monotonic() + 15.0
        while _time.monotonic() < deadline:
            block = beat(2.0)
            if block.get("ready") == 1 and block.get("desired") == 1:
                break
            _time.sleep(0.02)
        obs["lull_ready"] = block.get("ready")
        obs["decisions"] = list(block.get("decisions") or [])
        cm = self.client.get_or_none(
            "v1", "ConfigMap",
            self.serving_name + consts.SERVING_LOAD_SUFFIX, self.ns,
        )
        routing = ((cm or {}).get("data") or {}).get(consts.SERVING_ROUTING_KEY, "{}")
        obs["final_routing"] = _json.loads(routing)
        obs["final_worker_pods"] = len(kubelet.serving_workers(self.serving_name))
        kubelet.stop()
        return obs


def run_serving_drill(client, ns: str, **run_kwargs) -> dict:
    drill = ServingDrill(client, ns)
    try:
        drill.setup()
        return drill.run(**run_kwargs)
    finally:
        drill.teardown()


def assert_serving_drill_passed(obs: dict) -> None:
    assert obs["steady_ready"] == 1, obs
    assert obs["burst_ready"] == 3, obs
    assert sum(obs["routed_at_burst"].values()) > 0, obs
    assert obs["worker_pods_at_burst"] == 3, obs
    assert obs["lull_ready"] == 1, obs
    assert any(d.get("action") == "victim" for d in obs["decisions"]), obs
    assert sum(1 for w in obs["final_routing"].values() if w > 0) == 1, obs


def run_job_drill(client, ns: str, **run_kwargs) -> dict:
    drill = JobDrill(client, ns)
    try:
        drill.setup()
        return drill.run(**run_kwargs)
    finally:
        drill.teardown()


def assert_job_drill_passed(obs: dict) -> None:
    from tpu_operator.api.tpujob import JobPhase

    assert obs["final"].get("phase") == JobPhase.SUCCEEDED, obs
    assert obs["victim"] and obs["healed"], obs
    # the fault + heal each replaced the gang's pods: at least the
    # initial, shrunk, and regrown generations trained
    assert obs.get("generations", 0) >= 2, obs
    assert ("shrink", "2x2x1", "2x1x1") in obs["resizes"], obs
    assert ("grow", "2x1x1", "2x2x1") in obs["resizes"], obs
    assert obs["continuity"]["ok"], obs["continuity"]
