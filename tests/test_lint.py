"""tpuop-lint test suite.

Three layers:
  * known-bad fixtures — one minimal manifest per lint rule asserting
    exactly that rule fires, and that a baseline entry suppresses it
  * seeded defects — a dropped ClusterRole verb, an unpinned image, a
    renamed CRD field: each must be caught by its analyzer
  * the acceptance gate — the shipped repo lints clean (zero
    unsuppressed error findings)
"""

import copy
import json
import os

import pytest

from tpu_operator.lint import drift, manifest_rules, rbac_static, runner
from tpu_operator.lint.findings import Baseline, dedupe, failing

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# Minimal fixture objects.
# ---------------------------------------------------------------------------


def make_daemonset(**overrides):
    """A DaemonSet that passes every manifest rule; tests break exactly
    one aspect each."""
    ds = {
        "apiVersion": "apps/v1",
        "kind": "DaemonSet",
        "metadata": {"name": "fix", "namespace": "ns"},
        "spec": {
            "selector": {"matchLabels": {"app": "fix"}},
            "template": {
                "metadata": {"labels": {"app": "fix"}},
                "spec": {
                    "serviceAccountName": "fix-sa",
                    "nodeSelector": {"tpu.google.com/tpu.deploy.fix": "true"},
                    "tolerations": [
                        {"key": "google.com/tpu", "operator": "Exists", "effect": "NoSchedule"}
                    ],
                    "containers": [
                        {
                            "name": "main",
                            "image": "gcr.io/x/img:1.0.0",
                            "resources": {"requests": {"cpu": "10m"}},
                            "readinessProbe": {"exec": {"command": ["true"]}},
                        }
                    ],
                    "volumes": [],
                },
            },
        },
    }
    for path, value in overrides.items():
        node = ds
        keys = path.split(".")
        for k in keys[:-1]:
            node = node[k]
        node[keys[-1]] = value
    return ds


SA = {"apiVersion": "v1", "kind": "ServiceAccount", "metadata": {"name": "fix-sa"}}


def rules_fired(objects):
    return {f.rule for f in manifest_rules.lint_group("fixture", objects)}


class TestManifestRuleFixtures:
    def test_clean_fixture_fires_nothing(self):
        assert rules_fired([SA, make_daemonset()]) == set()

    def test_m001_privileged(self):
        ds = make_daemonset()
        ds["spec"]["template"]["spec"]["containers"][0]["securityContext"] = {
            "privileged": True
        }
        assert rules_fired([SA, ds]) == {"TPUOP-M001"}

    def test_m002_hostpath(self):
        ds = make_daemonset()
        ds["spec"]["template"]["spec"]["volumes"] = [
            {"name": "dev", "hostPath": {"path": "/dev"}}
        ]
        assert rules_fired([SA, ds]) == {"TPUOP-M002"}

    @pytest.mark.parametrize(
        "image", ["gcr.io/x/img:latest", "gcr.io/x/img", "localhost:5000/img"]
    )
    def test_m003_unpinned_image(self, image):
        ds = make_daemonset()
        ds["spec"]["template"]["spec"]["containers"][0]["image"] = image
        assert rules_fired([SA, ds]) == {"TPUOP-M003"}

    @pytest.mark.parametrize(
        "image", ["gcr.io/x/img:1.2.3", "gcr.io/x/img@sha256:abc", "localhost:5000/img:1.0"]
    )
    def test_m003_pinned_images_pass(self, image):
        ds = make_daemonset()
        ds["spec"]["template"]["spec"]["containers"][0]["image"] = image
        assert rules_fired([SA, ds]) == set()

    def test_m004_selector_mismatch(self):
        ds = make_daemonset()
        ds["spec"]["selector"]["matchLabels"] = {"app": "other"}
        assert rules_fired([SA, ds]) == {"TPUOP-M004"}

    def test_m005_dangling_serviceaccount(self):
        assert rules_fired([make_daemonset()]) == {"TPUOP-M005"}

    def test_m006_dangling_configmap(self):
        ds = make_daemonset()
        ds["spec"]["template"]["spec"]["volumes"] = [
            {"name": "cfg", "configMap": {"name": "nope"}}
        ]
        assert rules_fired([SA, ds]) == {"TPUOP-M006"}

    def test_m007_no_probe(self):
        ds = make_daemonset()
        del ds["spec"]["template"]["spec"]["containers"][0]["readinessProbe"]
        assert rules_fired([SA, ds]) == {"TPUOP-M007"}

    def test_m008_no_requests(self):
        ds = make_daemonset()
        del ds["spec"]["template"]["spec"]["containers"][0]["resources"]
        assert rules_fired([SA, ds]) == {"TPUOP-M008"}

    def test_m009_missing_tpu_toleration(self):
        ds = make_daemonset()
        ds["spec"]["template"]["spec"]["tolerations"] = []
        assert rules_fired([SA, ds]) == {"TPUOP-M009"}

    def test_r003_unknown_verb(self):
        role = {
            "apiVersion": "rbac.authorization.k8s.io/v1",
            "kind": "ClusterRole",
            "metadata": {"name": "r"},
            "rules": [{"apiGroups": [""], "resources": ["nodes"], "verbs": ["label"]}],
        }
        assert rules_fired([role]) == {"TPUOP-R003"}

    def test_r004_cluster_scoped_in_role(self):
        role = {
            "apiVersion": "rbac.authorization.k8s.io/v1",
            "kind": "Role",
            "metadata": {"name": "r", "namespace": "ns"},
            "rules": [{"apiGroups": [""], "resources": ["nodes"], "verbs": ["get"]}],
        }
        assert rules_fired([role]) == {"TPUOP-R004"}

    def test_baseline_suppresses_exactly_its_target(self):
        ds = make_daemonset()
        ds["spec"]["template"]["spec"]["containers"][0]["securityContext"] = {
            "privileged": True
        }
        findings = manifest_rules.lint_group("fixture", [SA, ds])
        baseline = Baseline.from_text(
            "TPUOP-M001 DaemonSet/fix/ctr:main  # fixture justification\n"
        )
        applied = baseline.apply(findings)
        assert all(f.suppressed for f in applied if f.rule == "TPUOP-M001")
        assert not failing(applied)
        assert not baseline.unused_entries()

    def test_baseline_prefix_respects_boundaries(self):
        """'vol:dev' must not swallow 'vol:device-plugins'."""
        ds = make_daemonset()
        ds["spec"]["template"]["spec"]["volumes"] = [
            {"name": "dev", "hostPath": {"path": "/dev"}},
            {"name": "device-plugins", "hostPath": {"path": "/var/lib/kubelet"}},
        ]
        findings = manifest_rules.lint_group("fixture", [SA, ds])
        baseline = Baseline.from_text("TPUOP-M002 DaemonSet/fix/vol:dev  # just dev\n")
        applied = baseline.apply(findings)
        suppressed = {f.location for f in applied if f.suppressed}
        assert suppressed == {"DaemonSet/fix/vol:dev"}


# ---------------------------------------------------------------------------
# Seeded RBAC defects.
# ---------------------------------------------------------------------------


class TestRbacSeededDefects:
    @pytest.fixture(scope="class")
    def shipped(self):
        return rbac_static.shipped_subject_rules()

    def test_dropped_clusterrole_verb_is_caught(self, shipped):
        """Remove nodes/status update from the health monitor's rules:
        the analyzer must report the missing grant."""
        rules = copy.deepcopy(shipped)
        rules["state-health-monitor"] = [
            r
            for r in rules["state-health-monitor"]
            if "nodes/status" not in (r.get("resources") or [])
        ]
        findings = rbac_static.analyze(rules_by_subject=rules)
        assert any(
            f.rule == "TPUOP-R001"
            and f.location == "rbac:state-health-monitor/nodes/status/update"
            for f in findings
        ), [f.location for f in findings]

    def test_extra_verb_is_caught_as_excess(self, shipped):
        rules = copy.deepcopy(shipped)
        rules["state-tpu-feature-discovery"] = rules["state-tpu-feature-discovery"] + [
            {"apiGroups": [""], "resources": ["secrets"], "verbs": ["get"]}
        ]
        findings = rbac_static.analyze(rules_by_subject=rules)
        assert any(
            f.rule == "TPUOP-R002"
            and f.location == "rbac:state-tpu-feature-discovery/secrets/get"
            for f in findings
        ), [f.location for f in findings]

    def test_shipped_rules_diff_clean(self, shipped):
        """The committed Roles/ClusterRoles match the static derivation
        exactly — no missing grants, no excess."""
        findings = rbac_static.analyze(rules_by_subject=shipped)
        problems = [f for f in findings if f.severity == "error"]
        assert not problems, [f"{f.location}: {f.message}" for f in problems]

    def test_every_call_site_resolves(self):
        """No TPUOP-R005: every client call site in the package either
        resolves statically or carries a pragma."""
        _, findings = rbac_static.required_grants()
        assert not findings, [f.location for f in findings]


# ---------------------------------------------------------------------------
# Seeded drift defects.
# ---------------------------------------------------------------------------


class TestDriftSeededDefects:
    def test_renamed_crd_field_is_caught(self):
        from tpu_operator.api.crds import all_crds

        shipped = {c["metadata"]["name"]: c for c in all_crds()}
        crd = shipped["clusterpolicies.tpu.google.com"]
        schema = crd["spec"]["versions"][0]["schema"]["openAPIV3Schema"]
        props = schema["properties"]["spec"]["properties"]
        props["libtpuu"] = props.pop("libtpu")  # the rename
        findings = drift.crd_schema_drift(shipped_crds=shipped)
        locs = [f.location for f in findings]
        assert any("libtpu" in loc for loc in locs), locs
        assert all(f.rule == "TPUOP-D001" for f in findings)

    def test_type_change_is_caught(self):
        from tpu_operator.api.crds import all_crds

        shipped = {c["metadata"]["name"]: c for c in all_crds()}
        crd = shipped["tpuslices.tpu.google.com"]
        schema = crd["spec"]["versions"][0]["schema"]["openAPIV3Schema"]
        spec_props = schema["properties"]["spec"]["properties"]
        key = next(iter(spec_props))
        spec_props[key] = {"type": "string"} if spec_props[key] != {"type": "string"} else {"type": "integer"}
        findings = drift.crd_schema_drift(shipped_crds=shipped)
        assert findings and all(f.rule == "TPUOP-D001" for f in findings)

    def test_shipped_crds_clean(self):
        assert drift.crd_schema_drift() == []
        assert drift.helm_kustomize_crd_drift() == []

    def test_goldens_fresh(self):
        assert drift.golden_drift() == []

    def test_kustomize_fresh(self):
        assert drift.kustomize_drift() == []


# ---------------------------------------------------------------------------
# TPUOP-O004: PrometheusRule alert hygiene.
# ---------------------------------------------------------------------------


class TestPrometheusRuleHygiene:
    def rule_obj(self, rule):
        return {
            "apiVersion": "monitoring.coreos.com/v1", "kind": "PrometheusRule",
            "metadata": {"name": "fix"},
            "spec": {"groups": [{"name": "g", "rules": [rule]}]},
        }

    def good_rule(self, **overrides):
        rule = {
            "alert": "A", "expr": "up == 0", "for": "5m",
            "annotations": {"summary": "s", "description": "d"},
        }
        rule.update(overrides)
        return rule

    def analyze(self, rule):
        from tpu_operator.lint.metrics_catalog import analyze_rule_hygiene

        return analyze_rule_hygiene([("state:x", [self.rule_obj(rule)])])

    def test_clean_alert_passes(self):
        assert self.analyze(self.good_rule()) == []

    def test_missing_summary_flagged(self):
        findings = self.analyze(self.good_rule(annotations={"description": "d"}))
        assert [f.rule for f in findings] == ["TPUOP-O004"]
        assert "summary" in findings[0].message

    def test_missing_description_flagged(self):
        findings = self.analyze(
            self.good_rule(annotations={"summary": "s", "description": "  "})
        )
        assert [f.rule for f in findings] == ["TPUOP-O004"]
        assert "description" in findings[0].message

    @pytest.mark.parametrize("duration", [None, "", "0", "0s", "0m"])
    def test_missing_or_zero_for_flagged(self, duration):
        rule = self.good_rule()
        if duration is None:
            del rule["for"]
        else:
            rule["for"] = duration
        findings = self.analyze(rule)
        assert [f.rule for f in findings] == ["TPUOP-O004"]
        assert "for:" in findings[0].message

    def test_recording_rules_exempt(self):
        # recording rules page nobody: no annotations/for contract
        findings = self.analyze({"record": "job:up:sum", "expr": "sum(up)"})
        assert findings == []

    def test_all_defects_reported_once_each(self):
        findings = self.analyze({"alert": "A", "expr": "up == 0"})
        assert sorted(f.rule for f in findings) == ["TPUOP-O004"] * 3

    def test_shipped_rules_all_clean(self):
        """Every alert the states actually render carries summary +
        description and a non-zero for: — the live guarantee the
        satellite asks for, the new fabric alert included."""
        from tpu_operator.lint.metrics_catalog import analyze_rule_hygiene

        groups = runner.manifest_groups()
        alerts = [
            rule.get("alert")
            for _, objs in groups for obj in objs
            if obj.get("kind") == "PrometheusRule"
            for g in (obj.get("spec") or {}).get("groups") or []
            for rule in g.get("rules") or []
            if rule.get("alert")
        ]
        assert "TPUIciLinkDegraded" in alerts  # the check is not vacuous
        assert analyze_rule_hygiene(groups) == []

    def test_seeded_defect_in_rendered_group_is_caught(self):
        """A shipped rule stripped of its for: must fail the gate the
        way a real regression would — through the same rendered groups
        run_lint feeds."""
        from tpu_operator.lint.metrics_catalog import analyze_rule_hygiene

        groups = []
        for name, objs in runner.manifest_groups():
            objs = copy.deepcopy(objs)
            for obj in objs:
                if obj.get("kind") != "PrometheusRule":
                    continue
                for g in (obj.get("spec") or {}).get("groups") or []:
                    for rule in g.get("rules") or []:
                        rule.pop("for", None)
            groups.append((name, objs))
        findings = analyze_rule_hygiene(groups)
        assert findings and all(f.rule == "TPUOP-O004" for f in findings)


# ---------------------------------------------------------------------------
# The acceptance gate + CLI.
# ---------------------------------------------------------------------------


class TestShippedRepoLintsClean:
    @pytest.fixture(scope="class")
    def findings(self):
        return runner.run_lint()

    def test_zero_unsuppressed_errors(self, findings):
        bad = failing(findings)
        assert not bad, [f"{f.rule} {f.location}: {f.message}" for f in bad]

    def test_no_dead_baseline_entries(self, findings):
        dead = [f for f in findings if f.rule == "TPUOP-B001"]
        assert not dead, [f.message for f in dead]

    def test_privileged_surface_is_fully_documented(self, findings):
        """Every privileged/hostPath finding is suppressed by a baseline
        entry — none unsuppressed, and none vanished (the suppression
        count proves the rules still see the surface)."""
        m = [f for f in findings if f.rule in ("TPUOP-M001", "TPUOP-M002")]
        assert m, "the privileged/hostPath surface disappeared entirely?"
        assert all(f.suppressed for f in m)

    def test_cli_json_exit_zero(self, capsys):
        from tpu_operator.cmd.tpuop_lint import main

        assert main(["--format", "json"]) == 0
        report = json.loads(capsys.readouterr().out)
        assert report["summary"]["error"] == 0
        assert {f["rule"] for f in report["findings"] if not f.get("suppressed")} <= {
            "TPUOP-M007"
        }

    def test_cli_fails_on_seeded_error(self, tmp_path, capsys):
        """End to end: an empty baseline un-suppresses the privileged
        findings and the CLI exits nonzero."""
        from tpu_operator.cmd.tpuop_lint import main

        empty = tmp_path / "baseline"
        empty.write_text("")
        assert main(["--baseline", str(empty), "--format", "json"]) == 1

    def test_dedupe_collapses_render_paths(self):
        """The same DaemonSet reaches the linter via state render AND
        golden snapshot; identical findings must collapse to one."""
        groups = runner.manifest_groups()
        all_findings = []
        for group, objects in groups:
            all_findings.extend(manifest_rules.lint_group(group, objects))
        deduped = dedupe(all_findings)
        keys = [(f.rule, f.location, f.message) for f in deduped]
        assert len(keys) == len(set(keys))
        assert len(deduped) < len(all_findings)


# ---------------------------------------------------------------------------
# Seeded concurrency defects (TPUOP-C rules).
# ---------------------------------------------------------------------------


class TestConcurrencySeededDefects:
    """One minimal module per TPUOP-C rule: the seeded defect fires
    exactly once, the corrected version is silent, and a baseline entry
    suppresses the finding (so justified exceptions stay expressible)."""

    def analyze(self, source):
        from tpu_operator.lint import concurrency

        return concurrency.analyze_source(source, "seeded.py")

    UNGUARDED = """
import threading

class Cache:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = {}

    def put(self, k, v):
        with self._lock:
            self._items[k] = v

    def drop(self, k):
        self._items.pop(k, None)
"""

    def test_c001_unguarded_attribute_fires_once(self):
        findings = self.analyze(self.UNGUARDED)
        assert [f.rule for f in findings] == ["TPUOP-C001"]
        assert findings[0].location == "py:seeded.py:Cache._items"
        assert "drop" in findings[0].message

    def test_c001_consistent_locking_is_clean(self):
        fixed = self.UNGUARDED.replace(
            "        self._items.pop(k, None)",
            "        with self._lock:\n            self._items.pop(k, None)",
        )
        assert self.analyze(fixed) == []

    def test_c001_guarded_by_pragma_suppresses(self):
        """A helper the caller locks for declares it instead of re-locking."""
        pragmad = self.UNGUARDED.replace(
            "    def drop(self, k):",
            "    # tpuop-lint: guarded-by=_lock\n    def drop(self, k):",
        )
        assert self.analyze(pragmad) == []

    def test_c001_init_mutations_exempt(self):
        """Construction precedes sharing: __init__ writes are never
        'unguarded'."""
        only_init = """
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def bump(self):
        with self._lock:
            self._n += 1
"""
        assert self.analyze(only_init) == []

    ABBA = """
import threading

class AB:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def forward(self):
        with self._a:
            self._nested()

    def _nested(self):
        with self._b:
            pass

    def backward(self):
        with self._b:
            with self._a:
                pass
"""

    def test_c002_abba_inversion_fires_once_through_call_chain(self):
        findings = self.analyze(self.ABBA)
        assert [f.rule for f in findings] == ["TPUOP-C002"]
        assert findings[0].location.startswith("lockcycle:")
        assert "AB._a" in findings[0].message and "AB._b" in findings[0].message

    def test_c002_consistent_order_is_clean(self):
        fixed = self.ABBA.replace(
            "        with self._b:\n            with self._a:\n                pass",
            "        with self._a:\n            with self._b:\n                pass",
        )
        assert self.analyze(fixed) == []

    SLEEPER = """
import threading
import time

class S:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def slow(self):
        with self._lock:
            time.sleep(0.5)
            self._n += 1
"""

    def test_c003_sleep_under_lock_fires_once(self):
        findings = self.analyze(self.SLEEPER)
        assert [f.rule for f in findings] == ["TPUOP-C003"]
        assert findings[0].location == "py:seeded.py:S.slow"
        assert "time.sleep" in findings[0].message

    def test_c003_sleep_outside_lock_is_clean(self):
        fixed = """
import threading
import time

class S:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def slow(self):
        time.sleep(0.5)
        with self._lock:
            self._n += 1
"""
        assert self.analyze(fixed) == []

    LEAKED = """
import threading

class W:
    def __init__(self):
        self._lock = threading.Lock()

    def start(self):
        self._t = threading.Thread(target=self._run)
        self._t.start()

    def _run(self):
        pass
"""

    def test_c004_leaked_thread_fires_once(self):
        findings = self.analyze(self.LEAKED)
        assert [f.rule for f in findings] == ["TPUOP-C004"]
        assert findings[0].location == "py:seeded.py:W.start"

    def test_c004_daemon_or_joined_is_clean(self):
        daemon = self.LEAKED.replace(
            "threading.Thread(target=self._run)",
            "threading.Thread(target=self._run, daemon=True)",
        )
        assert self.analyze(daemon) == []
        joined = self.LEAKED + """
    def stop(self):
        self._t.join()
"""
        assert self.analyze(joined) == []

    @pytest.mark.parametrize(
        "source,rule,location",
        [
            (UNGUARDED, "TPUOP-C001", "py:seeded.py:Cache._items"),
            (SLEEPER, "TPUOP-C003", "py:seeded.py:S.slow"),
            (LEAKED, "TPUOP-C004", "py:seeded.py:W.start"),
        ],
    )
    def test_c_rules_are_baseline_suppressible(self, source, rule, location):
        findings = self.analyze(source)
        baseline = Baseline.from_text(f"{rule} {location}  # fixture justification\n")
        applied = baseline.apply(findings)
        assert all(f.suppressed for f in applied)
        assert not failing(applied)
        assert not baseline.unused_entries()

    def test_c002_baseline_suppressible(self):
        findings = self.analyze(self.ABBA)
        baseline = Baseline.from_text(
            "TPUOP-C002 lockcycle:AB._a  # fixture justification\n"
        )
        applied = baseline.apply(findings)
        assert all(f.suppressed for f in applied)
        assert not failing(applied)

    def test_shipped_tree_concurrency_clean_or_baselined(self):
        """The acceptance gate for the new family: every TPUOP-C finding
        in the shipped package is suppressed by a justified baseline
        entry — the tree carries no unexplained concurrency debt."""
        findings = runner.run_lint(only=["concurrency"])
        c_rules = [f for f in findings if f.rule.startswith("TPUOP-C")]
        unsuppressed = [f for f in c_rules if not f.suppressed]
        assert not unsuppressed, unsuppressed


# ---------------------------------------------------------------------------
# Seeded gauge-retirement defects (TPUOP-O005).
# ---------------------------------------------------------------------------


class TestGaugeRetirement:
    def _analyze_tree(self, tmp_path, source):
        from tpu_operator.lint import metrics_catalog

        (tmp_path / "mod.py").write_text(source)
        return metrics_catalog.analyze_gauge_retirement(str(tmp_path))

    SEEDED = """
import prometheus_client

gang_latency = prometheus_client.Gauge(
    "tpu_operator_gang_decode_latency_seconds", "doc", ["slice"]
)
"""

    def test_o005_gauge_without_removal_fires_once(self, tmp_path):
        findings = self._analyze_tree(tmp_path, self.SEEDED)
        assert [f.rule for f in findings] == ["TPUOP-O005"]
        assert findings[0].location == "metric:tpu_operator_gang_decode_latency_seconds"

    def test_o005_direct_removal_satisfies(self, tmp_path):
        fixed = self.SEEDED + """
def retire(slice_name):
    gang_latency.remove(slice_name)
"""
        assert self._analyze_tree(tmp_path, fixed) == []

    def test_o005_loop_tuple_removal_satisfies(self, tmp_path):
        """The exporter idiom: several gauges retired through one loop
        variable over a tuple of attributes."""
        source = """
import prometheus_client

class M:
    def __init__(self):
        self.link_bw = prometheus_client.Gauge(
            "tpu_operator_seeded_link_bw", "doc", ["pool", "edge"])
        self.link_bad = prometheus_client.Gauge(
            "tpu_operator_seeded_link_bad", "doc", ["pool", "edge"])

    def retire(self, pool, edge):
        for gauge in (self.link_bw, self.link_bad):
            gauge.remove(pool, edge)
"""
        assert self._analyze_tree(tmp_path, source) == []

    def test_o005_static_label_dimensions_exempt(self, tmp_path):
        """{controller}/{node}-labelled gauges are fixed for the life of
        the process — no retirement needed."""
        source = """
import prometheus_client

depth = prometheus_client.Gauge(
    "tpu_operator_seeded_queue_depth", "doc", ["controller"])
own_node = prometheus_client.Gauge(
    "tpu_exporter_seeded_chip_total", "doc", ["node"])
"""
        assert self._analyze_tree(tmp_path, source) == []

    def test_o005_baseline_suppressible(self, tmp_path):
        findings = self._analyze_tree(tmp_path, self.SEEDED)
        baseline = Baseline.from_text(
            "TPUOP-O005 metric:tpu_operator_gang_decode_latency_seconds  # fixture\n"
        )
        applied = baseline.apply(findings)
        assert all(f.suppressed for f in applied)
        assert not failing(applied)

    def test_all_shipped_collectors_clean(self):
        """Every dynamically-labelled gauge the package registers has a
        reachable retire site — the stale-series class PRs 7 and 8 fixed
        by hand stays fixed."""
        from tpu_operator.lint import metrics_catalog

        assert metrics_catalog.analyze_gauge_retirement() == []


# ---------------------------------------------------------------------------
# Lint runner quality-of-life.
# ---------------------------------------------------------------------------


class TestRunnerQoL:
    def test_json_report_carries_analyzer_wall_time(self, capsys):
        from tpu_operator.cmd.tpuop_lint import main

        assert main(["--only", "concurrency", "--format", "json"]) == 0
        report = json.loads(capsys.readouterr().out)
        assert "concurrency" in report["analyzer_seconds"]
        assert report["analyzer_seconds"]["concurrency"] >= 0

    def test_only_accepts_rule_ids(self, capsys):
        """--only TPUOP-C003 runs just the concurrency family and keeps
        only that rule's rows."""
        from tpu_operator.cmd.tpuop_lint import main

        assert main(["--only", "TPUOP-C003", "--format", "json"]) == 0
        report = json.loads(capsys.readouterr().out)
        assert list(report["analyzer_seconds"]) == ["concurrency"]
        assert {f["rule"] for f in report["findings"]} <= {"TPUOP-C003", "TPUOP-B001"}

    def test_skip_drops_analyzers_and_rules(self, capsys):
        from tpu_operator.cmd.tpuop_lint import main

        assert main([
            "--skip", "manifest,rbac,drift,TPUOP-O005", "--format", "json",
        ]) == 0
        report = json.loads(capsys.readouterr().out)
        assert set(report["analyzer_seconds"]) == {"metrics", "concurrency"}
        assert all(f["rule"] != "TPUOP-O005" for f in report["findings"])

    def test_unknown_selector_token_is_a_usage_error(self, capsys):
        from tpu_operator.cmd.tpuop_lint import main

        assert main(["--only", "bogus"]) == 2
        assert main(["--skip", "TPUOP-Z999"]) == 2

    def test_mustgather_lint_report_includes_new_families(self, tmp_path, fake_client):
        """must-gather's lint-report.json carries the TPUOP-C/O005 rows
        (suppressed ones included) and the per-analyzer timings."""
        from tpu_operator import mustgather

        mustgather.collect(fake_client, "tpu-operator", str(tmp_path))
        report = json.loads((tmp_path / "lint-report.json").read_text())
        assert "concurrency" in report["analyzer_seconds"]
        rules = {f["rule"] for f in report["findings"]}
        assert any(r.startswith("TPUOP-C") for r in rules)
