"""tpuop-lint test suite.

Three layers:
  * known-bad fixtures — one minimal manifest per lint rule asserting
    exactly that rule fires, and that a baseline entry suppresses it
  * seeded defects — a dropped ClusterRole verb, an unpinned image, a
    renamed CRD field: each must be caught by its analyzer
  * the acceptance gate — the shipped repo lints clean (zero
    unsuppressed error findings)
"""

import copy
import json
import os
import time

import pytest

from tpu_operator.lint import drift, manifest_rules, rbac_static, runner
from tpu_operator.lint.findings import Baseline, dedupe, failing

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# Minimal fixture objects.
# ---------------------------------------------------------------------------


def make_daemonset(**overrides):
    """A DaemonSet that passes every manifest rule; tests break exactly
    one aspect each."""
    ds = {
        "apiVersion": "apps/v1",
        "kind": "DaemonSet",
        "metadata": {"name": "fix", "namespace": "ns"},
        "spec": {
            "selector": {"matchLabels": {"app": "fix"}},
            "template": {
                "metadata": {"labels": {"app": "fix"}},
                "spec": {
                    "serviceAccountName": "fix-sa",
                    "nodeSelector": {"tpu.google.com/tpu.deploy.fix": "true"},
                    "tolerations": [
                        {"key": "google.com/tpu", "operator": "Exists", "effect": "NoSchedule"}
                    ],
                    "containers": [
                        {
                            "name": "main",
                            "image": "gcr.io/x/img:1.0.0",
                            "resources": {"requests": {"cpu": "10m"}},
                            "readinessProbe": {"exec": {"command": ["true"]}},
                        }
                    ],
                    "volumes": [],
                },
            },
        },
    }
    for path, value in overrides.items():
        node = ds
        keys = path.split(".")
        for k in keys[:-1]:
            node = node[k]
        node[keys[-1]] = value
    return ds


SA = {"apiVersion": "v1", "kind": "ServiceAccount", "metadata": {"name": "fix-sa"}}


def rules_fired(objects):
    return {f.rule for f in manifest_rules.lint_group("fixture", objects)}


class TestManifestRuleFixtures:
    def test_clean_fixture_fires_nothing(self):
        assert rules_fired([SA, make_daemonset()]) == set()

    def test_m001_privileged(self):
        ds = make_daemonset()
        ds["spec"]["template"]["spec"]["containers"][0]["securityContext"] = {
            "privileged": True
        }
        assert rules_fired([SA, ds]) == {"TPUOP-M001"}

    def test_m002_hostpath(self):
        ds = make_daemonset()
        ds["spec"]["template"]["spec"]["volumes"] = [
            {"name": "dev", "hostPath": {"path": "/dev"}}
        ]
        assert rules_fired([SA, ds]) == {"TPUOP-M002"}

    @pytest.mark.parametrize(
        "image", ["gcr.io/x/img:latest", "gcr.io/x/img", "localhost:5000/img"]
    )
    def test_m003_unpinned_image(self, image):
        ds = make_daemonset()
        ds["spec"]["template"]["spec"]["containers"][0]["image"] = image
        assert rules_fired([SA, ds]) == {"TPUOP-M003"}

    @pytest.mark.parametrize(
        "image", ["gcr.io/x/img:1.2.3", "gcr.io/x/img@sha256:abc", "localhost:5000/img:1.0"]
    )
    def test_m003_pinned_images_pass(self, image):
        ds = make_daemonset()
        ds["spec"]["template"]["spec"]["containers"][0]["image"] = image
        assert rules_fired([SA, ds]) == set()

    def test_m004_selector_mismatch(self):
        ds = make_daemonset()
        ds["spec"]["selector"]["matchLabels"] = {"app": "other"}
        assert rules_fired([SA, ds]) == {"TPUOP-M004"}

    def test_m005_dangling_serviceaccount(self):
        assert rules_fired([make_daemonset()]) == {"TPUOP-M005"}

    def test_m006_dangling_configmap(self):
        ds = make_daemonset()
        ds["spec"]["template"]["spec"]["volumes"] = [
            {"name": "cfg", "configMap": {"name": "nope"}}
        ]
        assert rules_fired([SA, ds]) == {"TPUOP-M006"}

    def test_m007_no_probe(self):
        ds = make_daemonset()
        del ds["spec"]["template"]["spec"]["containers"][0]["readinessProbe"]
        assert rules_fired([SA, ds]) == {"TPUOP-M007"}

    def test_m008_no_requests(self):
        ds = make_daemonset()
        del ds["spec"]["template"]["spec"]["containers"][0]["resources"]
        assert rules_fired([SA, ds]) == {"TPUOP-M008"}

    def test_m009_missing_tpu_toleration(self):
        ds = make_daemonset()
        ds["spec"]["template"]["spec"]["tolerations"] = []
        assert rules_fired([SA, ds]) == {"TPUOP-M009"}

    def test_r003_unknown_verb(self):
        role = {
            "apiVersion": "rbac.authorization.k8s.io/v1",
            "kind": "ClusterRole",
            "metadata": {"name": "r"},
            "rules": [{"apiGroups": [""], "resources": ["nodes"], "verbs": ["label"]}],
        }
        assert rules_fired([role]) == {"TPUOP-R003"}

    def test_r004_cluster_scoped_in_role(self):
        role = {
            "apiVersion": "rbac.authorization.k8s.io/v1",
            "kind": "Role",
            "metadata": {"name": "r", "namespace": "ns"},
            "rules": [{"apiGroups": [""], "resources": ["nodes"], "verbs": ["get"]}],
        }
        assert rules_fired([role]) == {"TPUOP-R004"}

    def test_baseline_suppresses_exactly_its_target(self):
        ds = make_daemonset()
        ds["spec"]["template"]["spec"]["containers"][0]["securityContext"] = {
            "privileged": True
        }
        findings = manifest_rules.lint_group("fixture", [SA, ds])
        baseline = Baseline.from_text(
            "TPUOP-M001 DaemonSet/fix/ctr:main  # fixture justification\n"
        )
        applied = baseline.apply(findings)
        assert all(f.suppressed for f in applied if f.rule == "TPUOP-M001")
        assert not failing(applied)
        assert not baseline.unused_entries()

    def test_baseline_prefix_respects_boundaries(self):
        """'vol:dev' must not swallow 'vol:device-plugins'."""
        ds = make_daemonset()
        ds["spec"]["template"]["spec"]["volumes"] = [
            {"name": "dev", "hostPath": {"path": "/dev"}},
            {"name": "device-plugins", "hostPath": {"path": "/var/lib/kubelet"}},
        ]
        findings = manifest_rules.lint_group("fixture", [SA, ds])
        baseline = Baseline.from_text("TPUOP-M002 DaemonSet/fix/vol:dev  # just dev\n")
        applied = baseline.apply(findings)
        suppressed = {f.location for f in applied if f.suppressed}
        assert suppressed == {"DaemonSet/fix/vol:dev"}


# ---------------------------------------------------------------------------
# Seeded RBAC defects.
# ---------------------------------------------------------------------------


class TestRbacSeededDefects:
    @pytest.fixture(scope="class")
    def shipped(self):
        return rbac_static.shipped_subject_rules()

    def test_dropped_clusterrole_verb_is_caught(self, shipped):
        """Remove nodes/status update from the health monitor's rules:
        the analyzer must report the missing grant."""
        rules = copy.deepcopy(shipped)
        rules["state-health-monitor"] = [
            r
            for r in rules["state-health-monitor"]
            if "nodes/status" not in (r.get("resources") or [])
        ]
        findings = rbac_static.analyze(rules_by_subject=rules)
        assert any(
            f.rule == "TPUOP-R001"
            and f.location == "rbac:state-health-monitor/nodes/status/update"
            for f in findings
        ), [f.location for f in findings]

    def test_extra_verb_is_caught_as_excess(self, shipped):
        rules = copy.deepcopy(shipped)
        rules["state-tpu-feature-discovery"] = rules["state-tpu-feature-discovery"] + [
            {"apiGroups": [""], "resources": ["secrets"], "verbs": ["get"]}
        ]
        findings = rbac_static.analyze(rules_by_subject=rules)
        assert any(
            f.rule == "TPUOP-R002"
            and f.location == "rbac:state-tpu-feature-discovery/secrets/get"
            for f in findings
        ), [f.location for f in findings]

    def test_shipped_rules_diff_clean(self, shipped):
        """The committed Roles/ClusterRoles match the static derivation
        exactly — no missing grants, no excess."""
        findings = rbac_static.analyze(rules_by_subject=shipped)
        problems = [f for f in findings if f.severity == "error"]
        assert not problems, [f"{f.location}: {f.message}" for f in problems]

    def test_every_call_site_resolves(self):
        """No TPUOP-R005: every client call site in the package either
        resolves statically or carries a pragma."""
        _, findings = rbac_static.required_grants()
        assert not findings, [f.location for f in findings]


# ---------------------------------------------------------------------------
# Seeded drift defects.
# ---------------------------------------------------------------------------


class TestDriftSeededDefects:
    def test_renamed_crd_field_is_caught(self):
        from tpu_operator.api.crds import all_crds

        shipped = {c["metadata"]["name"]: c for c in all_crds()}
        crd = shipped["clusterpolicies.tpu.google.com"]
        schema = crd["spec"]["versions"][0]["schema"]["openAPIV3Schema"]
        props = schema["properties"]["spec"]["properties"]
        props["libtpuu"] = props.pop("libtpu")  # the rename
        findings = drift.crd_schema_drift(shipped_crds=shipped)
        locs = [f.location for f in findings]
        assert any("libtpu" in loc for loc in locs), locs
        assert all(f.rule == "TPUOP-D001" for f in findings)

    def test_type_change_is_caught(self):
        from tpu_operator.api.crds import all_crds

        shipped = {c["metadata"]["name"]: c for c in all_crds()}
        crd = shipped["tpuslices.tpu.google.com"]
        schema = crd["spec"]["versions"][0]["schema"]["openAPIV3Schema"]
        spec_props = schema["properties"]["spec"]["properties"]
        key = next(iter(spec_props))
        spec_props[key] = {"type": "string"} if spec_props[key] != {"type": "string"} else {"type": "integer"}
        findings = drift.crd_schema_drift(shipped_crds=shipped)
        assert findings and all(f.rule == "TPUOP-D001" for f in findings)

    def test_shipped_crds_clean(self):
        assert drift.crd_schema_drift() == []
        assert drift.helm_kustomize_crd_drift() == []

    def test_goldens_fresh(self):
        assert drift.golden_drift() == []

    def test_kustomize_fresh(self):
        assert drift.kustomize_drift() == []


# ---------------------------------------------------------------------------
# TPUOP-O004: PrometheusRule alert hygiene.
# ---------------------------------------------------------------------------


class TestPrometheusRuleHygiene:
    def rule_obj(self, rule):
        return {
            "apiVersion": "monitoring.coreos.com/v1", "kind": "PrometheusRule",
            "metadata": {"name": "fix"},
            "spec": {"groups": [{"name": "g", "rules": [rule]}]},
        }

    def good_rule(self, **overrides):
        rule = {
            "alert": "A", "expr": "up == 0", "for": "5m",
            "annotations": {"summary": "s", "description": "d"},
        }
        rule.update(overrides)
        return rule

    def analyze(self, rule):
        from tpu_operator.lint.metrics_catalog import analyze_rule_hygiene

        return analyze_rule_hygiene([("state:x", [self.rule_obj(rule)])])

    def test_clean_alert_passes(self):
        assert self.analyze(self.good_rule()) == []

    def test_missing_summary_flagged(self):
        findings = self.analyze(self.good_rule(annotations={"description": "d"}))
        assert [f.rule for f in findings] == ["TPUOP-O004"]
        assert "summary" in findings[0].message

    def test_missing_description_flagged(self):
        findings = self.analyze(
            self.good_rule(annotations={"summary": "s", "description": "  "})
        )
        assert [f.rule for f in findings] == ["TPUOP-O004"]
        assert "description" in findings[0].message

    @pytest.mark.parametrize("duration", [None, "", "0", "0s", "0m"])
    def test_missing_or_zero_for_flagged(self, duration):
        rule = self.good_rule()
        if duration is None:
            del rule["for"]
        else:
            rule["for"] = duration
        findings = self.analyze(rule)
        assert [f.rule for f in findings] == ["TPUOP-O004"]
        assert "for:" in findings[0].message

    def test_recording_rules_exempt(self):
        # recording rules page nobody: no annotations/for contract
        findings = self.analyze({"record": "job:up:sum", "expr": "sum(up)"})
        assert findings == []

    def test_all_defects_reported_once_each(self):
        findings = self.analyze({"alert": "A", "expr": "up == 0"})
        assert sorted(f.rule for f in findings) == ["TPUOP-O004"] * 3

    def test_shipped_rules_all_clean(self):
        """Every alert the states actually render carries summary +
        description and a non-zero for: — the live guarantee the
        satellite asks for, the new fabric alert included."""
        from tpu_operator.lint.metrics_catalog import analyze_rule_hygiene

        groups = runner.manifest_groups()
        alerts = [
            rule.get("alert")
            for _, objs in groups for obj in objs
            if obj.get("kind") == "PrometheusRule"
            for g in (obj.get("spec") or {}).get("groups") or []
            for rule in g.get("rules") or []
            if rule.get("alert")
        ]
        assert "TPUIciLinkDegraded" in alerts  # the check is not vacuous
        assert analyze_rule_hygiene(groups) == []

    def test_seeded_defect_in_rendered_group_is_caught(self):
        """A shipped rule stripped of its for: must fail the gate the
        way a real regression would — through the same rendered groups
        run_lint feeds."""
        from tpu_operator.lint.metrics_catalog import analyze_rule_hygiene

        groups = []
        for name, objs in runner.manifest_groups():
            objs = copy.deepcopy(objs)
            for obj in objs:
                if obj.get("kind") != "PrometheusRule":
                    continue
                for g in (obj.get("spec") or {}).get("groups") or []:
                    for rule in g.get("rules") or []:
                        rule.pop("for", None)
            groups.append((name, objs))
        findings = analyze_rule_hygiene(groups)
        assert findings and all(f.rule == "TPUOP-O004" for f in findings)


# ---------------------------------------------------------------------------
# The acceptance gate + CLI.
# ---------------------------------------------------------------------------


class TestShippedRepoLintsClean:
    @pytest.fixture(scope="class")
    def findings(self):
        return runner.run_lint()

    def test_zero_unsuppressed_errors(self, findings):
        bad = failing(findings)
        assert not bad, [f"{f.rule} {f.location}: {f.message}" for f in bad]

    def test_no_dead_baseline_entries(self, findings):
        dead = [f for f in findings if f.rule == "TPUOP-B001"]
        assert not dead, [f.message for f in dead]

    def test_privileged_surface_is_fully_documented(self, findings):
        """Every privileged/hostPath finding is suppressed by a baseline
        entry — none unsuppressed, and none vanished (the suppression
        count proves the rules still see the surface)."""
        m = [f for f in findings if f.rule in ("TPUOP-M001", "TPUOP-M002")]
        assert m, "the privileged/hostPath surface disappeared entirely?"
        assert all(f.suppressed for f in m)

    def test_cli_json_exit_zero(self, capsys):
        from tpu_operator.cmd.tpuop_lint import main

        assert main(["--format", "json"]) == 0
        report = json.loads(capsys.readouterr().out)
        assert report["summary"]["error"] == 0
        assert {f["rule"] for f in report["findings"] if not f.get("suppressed")} <= {
            "TPUOP-M007"
        }

    def test_cli_fails_on_seeded_error(self, tmp_path, capsys):
        """End to end: an empty baseline un-suppresses the privileged
        findings and the CLI exits nonzero."""
        from tpu_operator.cmd.tpuop_lint import main

        empty = tmp_path / "baseline"
        empty.write_text("")
        assert main(["--baseline", str(empty), "--format", "json"]) == 1

    def test_dedupe_collapses_render_paths(self):
        """The same DaemonSet reaches the linter via state render AND
        golden snapshot; identical findings must collapse to one."""
        groups = runner.manifest_groups()
        all_findings = []
        for group, objects in groups:
            all_findings.extend(manifest_rules.lint_group(group, objects))
        deduped = dedupe(all_findings)
        keys = [(f.rule, f.location, f.message) for f in deduped]
        assert len(keys) == len(set(keys))
        assert len(deduped) < len(all_findings)


# ---------------------------------------------------------------------------
# Seeded concurrency defects (TPUOP-C rules).
# ---------------------------------------------------------------------------


class TestConcurrencySeededDefects:
    """One minimal module per TPUOP-C rule: the seeded defect fires
    exactly once, the corrected version is silent, and a baseline entry
    suppresses the finding (so justified exceptions stay expressible)."""

    def analyze(self, source):
        from tpu_operator.lint import concurrency

        return concurrency.analyze_source(source, "seeded.py")

    UNGUARDED = """
import threading

class Cache:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = {}

    def put(self, k, v):
        with self._lock:
            self._items[k] = v

    def drop(self, k):
        self._items.pop(k, None)
"""

    def test_c001_unguarded_attribute_fires_once(self):
        findings = self.analyze(self.UNGUARDED)
        assert [f.rule for f in findings] == ["TPUOP-C001"]
        assert findings[0].location == "py:seeded.py:Cache._items"
        assert "drop" in findings[0].message

    def test_c001_consistent_locking_is_clean(self):
        fixed = self.UNGUARDED.replace(
            "        self._items.pop(k, None)",
            "        with self._lock:\n            self._items.pop(k, None)",
        )
        assert self.analyze(fixed) == []

    def test_c001_guarded_by_pragma_suppresses(self):
        """A helper the caller locks for declares it instead of re-locking."""
        pragmad = self.UNGUARDED.replace(
            "    def drop(self, k):",
            "    # tpuop-lint: guarded-by=_lock\n    def drop(self, k):",
        )
        assert self.analyze(pragmad) == []

    def test_c001_init_mutations_exempt(self):
        """Construction precedes sharing: __init__ writes are never
        'unguarded'."""
        only_init = """
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def bump(self):
        with self._lock:
            self._n += 1
"""
        assert self.analyze(only_init) == []

    ABBA = """
import threading

class AB:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def forward(self):
        with self._a:
            self._nested()

    def _nested(self):
        with self._b:
            pass

    def backward(self):
        with self._b:
            with self._a:
                pass
"""

    def test_c002_abba_inversion_fires_once_through_call_chain(self):
        findings = self.analyze(self.ABBA)
        assert [f.rule for f in findings] == ["TPUOP-C002"]
        assert findings[0].location.startswith("lockcycle:")
        assert "AB._a" in findings[0].message and "AB._b" in findings[0].message

    def test_c002_consistent_order_is_clean(self):
        fixed = self.ABBA.replace(
            "        with self._b:\n            with self._a:\n                pass",
            "        with self._a:\n            with self._b:\n                pass",
        )
        assert self.analyze(fixed) == []

    SLEEPER = """
import threading
import time

class S:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def slow(self):
        with self._lock:
            time.sleep(0.5)
            self._n += 1
"""

    def test_c003_sleep_under_lock_fires_once(self):
        findings = self.analyze(self.SLEEPER)
        assert [f.rule for f in findings] == ["TPUOP-C003"]
        assert findings[0].location == "py:seeded.py:S.slow"
        assert "time.sleep" in findings[0].message

    def test_c003_sleep_outside_lock_is_clean(self):
        fixed = """
import threading
import time

class S:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def slow(self):
        time.sleep(0.5)
        with self._lock:
            self._n += 1
"""
        assert self.analyze(fixed) == []

    LEAKED = """
import threading

class W:
    def __init__(self):
        self._lock = threading.Lock()

    def start(self):
        self._t = threading.Thread(target=self._run)
        self._t.start()

    def _run(self):
        pass
"""

    def test_c004_leaked_thread_fires_once(self):
        findings = self.analyze(self.LEAKED)
        assert [f.rule for f in findings] == ["TPUOP-C004"]
        assert findings[0].location == "py:seeded.py:W.start"

    def test_c004_daemon_or_joined_is_clean(self):
        daemon = self.LEAKED.replace(
            "threading.Thread(target=self._run)",
            "threading.Thread(target=self._run, daemon=True)",
        )
        assert self.analyze(daemon) == []
        joined = self.LEAKED + """
    def stop(self):
        self._t.join()
"""
        assert self.analyze(joined) == []

    @pytest.mark.parametrize(
        "source,rule,location",
        [
            (UNGUARDED, "TPUOP-C001", "py:seeded.py:Cache._items"),
            (SLEEPER, "TPUOP-C003", "py:seeded.py:S.slow"),
            (LEAKED, "TPUOP-C004", "py:seeded.py:W.start"),
        ],
    )
    def test_c_rules_are_baseline_suppressible(self, source, rule, location):
        findings = self.analyze(source)
        baseline = Baseline.from_text(f"{rule} {location}  # fixture justification\n")
        applied = baseline.apply(findings)
        assert all(f.suppressed for f in applied)
        assert not failing(applied)
        assert not baseline.unused_entries()

    def test_c002_baseline_suppressible(self):
        findings = self.analyze(self.ABBA)
        baseline = Baseline.from_text(
            "TPUOP-C002 lockcycle:AB._a  # fixture justification\n"
        )
        applied = baseline.apply(findings)
        assert all(f.suppressed for f in applied)
        assert not failing(applied)

    def test_shipped_tree_concurrency_clean_or_baselined(self):
        """The acceptance gate for the new family: every TPUOP-C finding
        in the shipped package is suppressed by a justified baseline
        entry — the tree carries no unexplained concurrency debt."""
        findings = runner.run_lint(only=["concurrency"])
        c_rules = [f for f in findings if f.rule.startswith("TPUOP-C")]
        unsuppressed = [f for f in c_rules if not f.suppressed]
        assert not unsuppressed, unsuppressed


# ---------------------------------------------------------------------------
# Seeded gauge-retirement defects (TPUOP-O005).
# ---------------------------------------------------------------------------


class TestGaugeRetirement:
    def _analyze_tree(self, tmp_path, source):
        from tpu_operator.lint import metrics_catalog

        (tmp_path / "mod.py").write_text(source)
        return metrics_catalog.analyze_gauge_retirement(str(tmp_path))

    SEEDED = """
import prometheus_client

gang_latency = prometheus_client.Gauge(
    "tpu_operator_gang_decode_latency_seconds", "doc", ["slice"]
)
"""

    def test_o005_gauge_without_removal_fires_once(self, tmp_path):
        findings = self._analyze_tree(tmp_path, self.SEEDED)
        assert [f.rule for f in findings] == ["TPUOP-O005"]
        assert findings[0].location == "metric:tpu_operator_gang_decode_latency_seconds"

    def test_o005_direct_removal_satisfies(self, tmp_path):
        fixed = self.SEEDED + """
def retire(slice_name):
    gang_latency.remove(slice_name)
"""
        assert self._analyze_tree(tmp_path, fixed) == []

    def test_o005_loop_tuple_removal_satisfies(self, tmp_path):
        """The exporter idiom: several gauges retired through one loop
        variable over a tuple of attributes."""
        source = """
import prometheus_client

class M:
    def __init__(self):
        self.link_bw = prometheus_client.Gauge(
            "tpu_operator_seeded_link_bw", "doc", ["pool", "edge"])
        self.link_bad = prometheus_client.Gauge(
            "tpu_operator_seeded_link_bad", "doc", ["pool", "edge"])

    def retire(self, pool, edge):
        for gauge in (self.link_bw, self.link_bad):
            gauge.remove(pool, edge)
"""
        assert self._analyze_tree(tmp_path, source) == []

    def test_o005_static_label_dimensions_exempt(self, tmp_path):
        """{controller}/{node}-labelled gauges are fixed for the life of
        the process — no retirement needed."""
        source = """
import prometheus_client

depth = prometheus_client.Gauge(
    "tpu_operator_seeded_queue_depth", "doc", ["controller"])
own_node = prometheus_client.Gauge(
    "tpu_exporter_seeded_chip_total", "doc", ["node"])
"""
        assert self._analyze_tree(tmp_path, source) == []

    def test_o005_baseline_suppressible(self, tmp_path):
        findings = self._analyze_tree(tmp_path, self.SEEDED)
        baseline = Baseline.from_text(
            "TPUOP-O005 metric:tpu_operator_gang_decode_latency_seconds  # fixture\n"
        )
        applied = baseline.apply(findings)
        assert all(f.suppressed for f in applied)
        assert not failing(applied)

    def test_all_shipped_collectors_clean(self):
        """Every dynamically-labelled gauge the package registers has a
        reachable retire site — the stale-series class PRs 7 and 8 fixed
        by hand stays fixed."""
        from tpu_operator.lint import metrics_catalog

        assert metrics_catalog.analyze_gauge_retirement() == []


# ---------------------------------------------------------------------------
# Lint runner quality-of-life.
# ---------------------------------------------------------------------------


class TestRunnerQoL:
    def test_json_report_carries_analyzer_wall_time(self, capsys):
        from tpu_operator.cmd.tpuop_lint import main

        assert main(["--only", "concurrency", "--format", "json"]) == 0
        report = json.loads(capsys.readouterr().out)
        assert "concurrency" in report["analyzer_seconds"]
        assert report["analyzer_seconds"]["concurrency"] >= 0

    def test_only_accepts_rule_ids(self, capsys):
        """--only TPUOP-C003 runs just the concurrency family and keeps
        only that rule's rows."""
        from tpu_operator.cmd.tpuop_lint import main

        assert main(["--only", "TPUOP-C003", "--format", "json"]) == 0
        report = json.loads(capsys.readouterr().out)
        assert list(report["analyzer_seconds"]) == ["concurrency"]
        assert {f["rule"] for f in report["findings"]} <= {"TPUOP-C003", "TPUOP-B001"}

    def test_skip_drops_analyzers_and_rules(self, capsys):
        from tpu_operator.cmd.tpuop_lint import main

        assert main([
            "--skip", "manifest,rbac,drift,TPUOP-O005", "--format", "json",
        ]) == 0
        report = json.loads(capsys.readouterr().out)
        assert set(report["analyzer_seconds"]) == {"metrics", "concurrency", "reconcile"}
        assert all(f["rule"] != "TPUOP-O005" for f in report["findings"])

    def test_unknown_selector_token_is_a_usage_error(self, capsys):
        from tpu_operator.cmd.tpuop_lint import main

        assert main(["--only", "bogus"]) == 2
        assert main(["--skip", "TPUOP-Z999"]) == 2

    def test_mustgather_lint_report_includes_new_families(self, tmp_path, fake_client):
        """must-gather's lint-report.json carries the TPUOP-C/O005 rows
        (suppressed ones included) and the per-analyzer timings — the K
        family rides the same registration, so its timing row appears
        without any must-gather change."""
        from tpu_operator import mustgather

        mustgather.collect(fake_client, "tpu-operator", str(tmp_path))
        report = json.loads((tmp_path / "lint-report.json").read_text())
        assert "concurrency" in report["analyzer_seconds"]
        assert "reconcile" in report["analyzer_seconds"]
        rules = {f["rule"] for f in report["findings"]}
        assert any(r.startswith("TPUOP-C") for r in rules)

    def test_run_lint_rejects_unknown_analyzer_names(self):
        """runner.run_lint(only=...) with a bogus family silently
        selected nothing (every family skipped, empty report, exit 0) —
        it must raise and name the valid families instead. The CLI's
        --only/--skip path already exits 2 via _parse_selector; this
        covers the library entry point every other caller uses."""
        with pytest.raises(ValueError) as exc:
            runner.run_lint(only=["bogus"])
        for name in runner.ANALYZERS:
            assert name in str(exc.value)
        assert "bogus" in str(exc.value)

    def test_lint_suite_wall_time_budget(self):
        """The whole lint suite (all six families) stays under a stated
        wall-time budget, so analyzer growth can't silently double CI
        time. The budget is deliberately loose (CI boxes are slow); the
        point is catching an accidental O(n^2) or a new family that
        re-renders the chart per rule."""
        timings: dict = {}
        t0 = time.monotonic()
        runner.run_lint(timings=timings)
        elapsed = time.monotonic() - t0
        assert set(timings) == set(runner.ANALYZERS)
        assert elapsed < 60.0, (
            f"lint suite took {elapsed:.1f}s (budget 60s): {timings}"
        )


# ---------------------------------------------------------------------------
# Seeded reconcile-contract defects (TPUOP-K rules).
# ---------------------------------------------------------------------------


class TestReconcileContractSeededDefects:
    """One minimal module per TPUOP-K rule: the seeded defect fires
    exactly once, the corrected variant is silent, and both pragma and
    baseline suppression are proven per rule."""

    def analyze(self, source, relpath="controllers/seeded.py"):
        from tpu_operator.lint import reconcile_contracts

        return reconcile_contracts.analyze_source(source, relpath)

    # -- K001: pattern/label-selected delete needs an ownership check --------

    K001_SEEDED = """
DRIVER_LABEL = "example.com/component"

class Sweeper:
    def sweep(self, pods):
        for pod in pods:
            labels = pod["metadata"].get("labels") or {}
            if labels.get(DRIVER_LABEL) != "driver":
                continue
            self.client.delete("v1", "Pod", pod["metadata"]["name"])
"""

    def test_k001_ownerless_label_sweep_fires_once(self):
        findings = self.analyze(self.K001_SEEDED)
        assert [f.rule for f in findings] == ["TPUOP-K001"]
        assert findings[0].location == "py:controllers/seeded.py:Sweeper.sweep"

    def test_k001_owner_checked_sweep_is_clean(self):
        fixed = self.K001_SEEDED.replace(
            '            self.client.delete("v1", "Pod", pod["metadata"]["name"])',
            '            if not any(r.get("kind") == "DaemonSet"\n'
            '                       for r in pod["metadata"].get("ownerReferences", [])):\n'
            "                continue\n"
            '            self.client.delete("v1", "Pod", pod["metadata"]["name"])',
        )
        assert self.analyze(fixed) == []

    def test_k001_pragma_suppresses(self):
        pragma = self.K001_SEEDED.replace(
            'self.client.delete("v1", "Pod", pod["metadata"]["name"])',
            'self.client.delete("v1", "Pod", pod["metadata"]["name"])'
            "  # tpuop-lint: ignore=K001",
        )
        assert self.analyze(pragma) == []

    # -- K002: shared-CM key ownership ---------------------------------------

    K002_SEEDED = {
        "controllers/a.py": """
from tpu_operator import consts

class A:
    def write(self):
        self.client.patch("v1", "ConfigMap", "x-progress",
                          {"data": {consts.JOB_PROGRESS_STATUS: "running"}})
""",
        "workloads/b.py": """
from tpu_operator import consts

class B:
    def write(self):
        self.client.patch("v1", "ConfigMap", "x-progress",
                          {"data": {consts.JOB_PROGRESS_STATUS: "done"}})
""",
    }

    def analyze_many(self, sources, handshakes=None):
        from tpu_operator.lint import reconcile_contracts

        return reconcile_contracts.analyze_sources(sources, handshakes)

    def test_k002_two_writer_key_fires_once(self):
        findings = self.analyze_many(self.K002_SEEDED)
        assert [f.rule for f in findings] == ["TPUOP-K002"]
        assert findings[0].location == "py:workloads/b.py:B.write"
        assert "'status'" in findings[0].message

    def test_k002_disjoint_keys_are_clean(self):
        clean = dict(self.K002_SEEDED)
        clean["workloads/b.py"] = clean["workloads/b.py"].replace(
            "JOB_PROGRESS_STATUS", "JOB_PROGRESS_RESTART_ACK"
        )
        assert self.analyze_many(clean) == []

    def test_k002_declared_handshake_is_legal(self):
        assert self.analyze_many(
            self.K002_SEEDED,
            handshakes={"status": frozenset({"controllers/a", "workloads/b"})},
        ) == []

    def test_k002_pragma_suppresses(self):
        pragma = dict(self.K002_SEEDED)
        pragma["workloads/b.py"] = pragma["workloads/b.py"].replace(
            '{"data": {consts.JOB_PROGRESS_STATUS: "done"}})',
            '{"data": {consts.JOB_PROGRESS_STATUS: "done"}})'
            "  # tpuop-lint: ignore=K002",
        )
        assert self.analyze_many(pragma) == []

    # -- K003: destructive-gating reads fail closed --------------------------

    K003_SEEDED = """
from tpu_operator.kube import errors

class R:
    def _read(self):
        try:
            return self.client.get("v1", "ConfigMap", "state")
        except errors.ApiError:
            return {}

    def reconcile(self, req):
        state = self._read()
        if not state:
            self.client.delete("v1", "Thing", "x")
"""

    def test_k003_fail_open_read_fires_once(self):
        findings = self.analyze(self.K003_SEEDED)
        assert [f.rule for f in findings] == ["TPUOP-K003"]
        assert findings[0].location == "py:controllers/seeded.py:R._read"

    def test_k003_fail_closed_read_is_clean(self):
        assert self.analyze(self.K003_SEEDED.replace("return {}", "return None")) == []

    def test_k003_without_destructive_caller_is_clean(self):
        """The same fail-open shape in a watch mapper (no delete/charge
        in any caller's closure) is legal — only destructive gating
        demands fail-closed."""
        harmless = self.K003_SEEDED.replace(
            '            self.client.delete("v1", "Thing", "x")',
            "            return None",
        )
        assert self.analyze(harmless) == []

    def test_k003_malformed_payload_branch_stays_legal(self):
        """A ValueError (malformed JSON) branch may start fresh — a
        retry can never fix a corrupt payload, so fresh-start is the
        only sane answer there."""
        source = """
import json

from tpu_operator.kube import errors

class R:
    def _read(self):
        try:
            raw = self.client.get("v1", "ConfigMap", "state")
        except errors.ApiError:
            return None
        try:
            return json.loads(raw)
        except ValueError:
            return {}

    def reconcile(self, req):
        state = self._read()
        if not state:
            self.client.delete("v1", "Thing", "x")
"""
        assert self.analyze(source) == []

    def test_k003_pragma_suppresses(self):
        pragma = self.K003_SEEDED.replace(
            "return {}", "return {}  # tpuop-lint: ignore=K003"
        )
        assert self.analyze(pragma) == []

    # -- K004: one status-patch site per kind per reconcile pass -------------

    K004_SEEDED = """
class C:
    def reconcile(self, req):
        self.client.patch_status("v1", "Widget", "a", {"status": {}})
        self._publish()

    def _publish(self):
        self.client.patch_status("v1", "Widget", "b", {"status": {}})
"""

    def test_k004_double_publish_fires_once(self):
        findings = self.analyze(self.K004_SEEDED)
        assert [f.rule for f in findings] == ["TPUOP-K004"]
        assert findings[0].location == "py:controllers/seeded.py:C.reconcile"
        assert "Widget" in findings[0].message

    def test_k004_single_publisher_is_clean(self):
        fixed = self.K004_SEEDED.replace(
            '        self.client.patch_status("v1", "Widget", "a", {"status": {}})\n', ""
        )
        assert self.analyze(fixed) == []

    def test_k004_distinct_kinds_are_clean(self):
        """One publish per kind is the contract — a reconcile touching
        two kinds may patch each once."""
        fixed = self.K004_SEEDED.replace(
            '"Widget", "a"', '"Gadget", "a"'
        )
        assert self.analyze(fixed) == []

    def test_k004_pragma_suppresses(self):
        pragma = self.K004_SEEDED.replace(
            '        self.client.patch_status("v1", "Widget", "a", {"status": {}})',
            '        self.client.patch_status("v1", "Widget", "a", {"status": {}})'
            "  # tpuop-lint: ignore=K004",
        )
        assert self.analyze(pragma) == []

    # -- K005: budget charges behind a persisted gate ------------------------

    K005_SEEDED = """
class J:
    def charge(self, block, budget):
        attempts = int(block.get("restarts") or 0)
        if budget.exhausted(attempts):
            return True
        block["restarts"] = attempts + 1
        return False
"""

    def test_k005_ungated_charge_fires_once(self):
        findings = self.analyze(self.K005_SEEDED)
        assert [f.rule for f in findings] == ["TPUOP-K005"]
        assert findings[0].location == "py:controllers/seeded.py:J.charge"

    def test_k005_next_attempt_gate_is_clean(self):
        gated = self.K005_SEEDED.replace(
            "    def charge(self, block, budget):\n",
            "    def charge(self, block, budget, now):\n"
            '        if now < float(block.get("nextAttemptAt") or 0):\n'
            "            return True\n",
        )
        assert self.analyze(gated) == []

    def test_k005_pragma_suppresses(self):
        pragma = self.K005_SEEDED.replace(
            'block["restarts"] = attempts + 1',
            'block["restarts"] = attempts + 1  # tpuop-lint: ignore=K005',
        )
        assert self.analyze(pragma) == []

    # -- baseline suppression, per rule --------------------------------------

    def test_k_rules_are_baseline_suppressible(self):
        cases = [
            (self.K001_SEEDED, "TPUOP-K001", "py:controllers/seeded.py:Sweeper.sweep"),
            (self.K003_SEEDED, "TPUOP-K003", "py:controllers/seeded.py:R._read"),
            (self.K004_SEEDED, "TPUOP-K004", "py:controllers/seeded.py:C.reconcile"),
            (self.K005_SEEDED, "TPUOP-K005", "py:controllers/seeded.py:J.charge"),
        ]
        for source, rule, location in cases:
            findings = self.analyze(source)
            baseline = Baseline.from_text(f"{rule} {location}  # fixture justification\n")
            applied = baseline.apply(findings)
            assert all(f.suppressed for f in applied), (rule, applied)
            assert not failing(applied)
            assert not baseline.unused_entries()

    def test_k002_baseline_suppressible(self):
        findings = self.analyze_many(self.K002_SEEDED)
        baseline = Baseline.from_text(
            "TPUOP-K002 py:workloads/b.py:B.write  # fixture justification\n"
        )
        applied = baseline.apply(findings)
        assert all(f.suppressed for f in applied)
        assert not failing(applied)

    # -- the acceptance gate -------------------------------------------------

    def test_shipped_tree_reconcile_contracts_clean(self):
        """The shipped tree is K-clean with zero baseline entries: every
        real finding the analyzer surfaced (the ownerless driver-pod
        sweep, the fail-open replica list, the ungated repair charge)
        was fixed outright, each pinned by a regression test."""
        findings = runner.run_lint(only=["reconcile"])
        k_rules = [f for f in findings if f.rule.startswith("TPUOP-K")]
        assert not k_rules, [(f.rule, f.location) for f in k_rules]


class TestReconcileContractReplays:
    """Acceptance criterion: replaying the analyzer against pre-fix
    reconstructions of real PR 13–16 hardening bugs proves each would
    have been a build failure, not a review catch."""

    def analyze(self, source, relpath):
        from tpu_operator.lint import reconcile_contracts

        return reconcile_contracts.analyze_source(source, relpath)

    def test_pr13_ownerless_slice_sweep_would_have_been_caught(self):
        """PR 13's hardening batch: the job sweep deleted every TPUSlice
        named ``<job>-slice*`` — including a user's standalone look-alike
        — until review added the ownerReference check. K001 makes the
        pre-fix shape a build failure."""
        pre_fix = """
SLICE_SUFFIX = "-slice"

class JobReconciler:
    def _sweep_slices(self, job_name):
        for obj in self.client.list("tpu.google.com/v1alpha1", "TPUSlice"):
            if not obj["metadata"]["name"].startswith(job_name + SLICE_SUFFIX):
                continue
            self.client.delete(
                "tpu.google.com/v1alpha1", "TPUSlice", obj["metadata"]["name"])
"""
        findings = self.analyze(pre_fix, "controllers/job_controller.py")
        assert [f.rule for f in findings] == ["TPUOP-K001"]
        assert findings[0].location == (
            "py:controllers/job_controller.py:JobReconciler._sweep_slices"
        )

    def test_pr15_fail_open_defrag_ledger_would_have_been_caught(self):
        """PR 15's hardening batch: ``_read_state`` answered a transient
        ApiError with the fresh ``{"decisions": []}`` ledger, handing the
        defrag controller a reset migration budget on every apiserver
        blip — until review made it fail closed. K003 makes the pre-fix
        shape a build failure (while the shipped fail-closed version and
        its malformed-payload branch stay clean)."""
        pre_fix = """
import json

from tpu_operator import consts
from tpu_operator.kube import errors


class DefragController:
    def _read_state(self):
        try:
            cm = self.client.get_or_none(
                "v1", "ConfigMap", consts.DEFRAG_STATE_CONFIGMAP)
        except errors.ApiError:
            return {"decisions": []}
        raw = ((cm or {}).get("data") or {}).get(consts.DEFRAG_STATE_KEY)
        if not raw:
            return {"decisions": []}
        return json.loads(raw)

    def _write_state(self, state):
        body = {"data": {consts.DEFRAG_STATE_KEY: json.dumps(state, sort_keys=True)}}
        self.client.patch("v1", "ConfigMap", consts.DEFRAG_STATE_CONFIGMAP, body)

    def reconcile(self, req):
        state = self._read_state()
        state["decisions"] = state.get("decisions", [])[-10:]
        self._write_state(state)
"""
        findings = self.analyze(pre_fix, "controllers/defrag_controller.py")
        assert [f.rule for f in findings] == ["TPUOP-K003"]
        assert findings[0].location == (
            "py:controllers/defrag_controller.py:DefragController._read_state"
        )

    def test_pr16_label_spoofed_driver_pod_sweep_would_have_been_caught(self):
        """The driver-pod bounce selected victims by component label
        alone — the exact shape this PR fixed in the health controller
        (now requiring a DaemonSet ownerReference)."""
        pre_fix = """
DRIVER_POD_COMPONENT_LABEL = "app.kubernetes.io/component"

class NodeRepairManager:
    def _delete_driver_pods(self, node_pods):
        for pod in node_pods:
            labels = pod["metadata"].get("labels") or {}
            if labels.get(DRIVER_POD_COMPONENT_LABEL) != "tpu-driver":
                continue
            md = pod["metadata"]
            self.client.delete("v1", "Pod", md["name"], md.get("namespace"))
"""
        findings = self.analyze(pre_fix, "controllers/health_controller.py")
        assert [f.rule for f in findings] == ["TPUOP-K001"]


# ---------------------------------------------------------------------------
# C004 dict-held threads (the PR 16 pod-kubelet idiom).
# ---------------------------------------------------------------------------


class TestDictHeldThreads:
    """PR 16's pod data plane holds worker threads in dicts keyed by pod
    name (``kube/sim.PodKubelet``); the C004 inventory must see through
    that idiom."""

    def analyze(self, source):
        from tpu_operator.lint import concurrency

        return concurrency.analyze_source(source, "seeded.py")

    LEAKED = """
import threading

class Kubelet:
    def __init__(self):
        self.workers = {}

    def start(self, name):
        self.workers[name] = threading.Thread(target=self._run, name=name)
        self.workers[name].start()

    def _run(self):
        pass
"""

    def test_dict_held_leaked_thread_fires_once(self):
        findings = self.analyze(self.LEAKED)
        assert [f.rule for f in findings] == ["TPUOP-C004"]
        assert findings[0].location == "py:seeded.py:Kubelet.start"

    def test_dict_held_daemon_is_clean(self):
        daemon = self.LEAKED.replace(
            "threading.Thread(target=self._run, name=name)",
            "threading.Thread(target=self._run, name=name, daemon=True)",
        )
        assert self.analyze(daemon) == []

    def test_values_loop_join_is_clean(self):
        joined = self.LEAKED + """
    def stop(self):
        for t in self.workers.values():
            t.join()
"""
        assert self.analyze(joined) == []

    def test_items_loop_join_of_local_thread_is_clean(self):
        source = """
import threading

class Kubelet:
    def __init__(self):
        self.workers = {}

    def start(self, name):
        t = threading.Thread(target=self._run, name=name)
        self.workers[name] = t
        t.start()

    def stop(self):
        for name, t in self.workers.items():
            t.join()

    def _run(self):
        pass
"""
        assert self.analyze(source) == []

    def test_shipped_pod_kubelet_stays_clean(self):
        """The real PodKubelet (daemon pod threads, joined in stop)
        must not regress under the extended inventory."""
        findings = runner.run_lint(only=["concurrency"])
        sim = [
            f for f in findings
            if f.rule == "TPUOP-C004" and "sim.py" in f.location and not f.suppressed
        ]
        assert not sim, sim


# ---------------------------------------------------------------------------
# lint/baseline.py: the factored-out suppression plumbing.
# ---------------------------------------------------------------------------


class TestBaselineModule:
    def test_reexport_is_the_same_class(self):
        """findings.Baseline stayed importable (every analyzer test and
        the CLI import it from there) and is the one implementation."""
        from tpu_operator.lint import baseline as baseline_mod
        from tpu_operator.lint import findings as findings_mod

        assert findings_mod.Baseline is baseline_mod.Baseline
        assert findings_mod.BaselineEntry is baseline_mod.BaselineEntry

    def test_dead_entry_is_a_warning_not_info(self):
        """An unused baseline entry warns in every family: WARNING rides
        into the text/JSON reports prominently but still exits 0 (only
        unsuppressed ERRORs fail builds)."""
        from tpu_operator.lint.baseline import unused_entry_findings

        baseline = Baseline.from_text(
            "TPUOP-C003 py:nowhere.py:gone  # stale\n", path="/tmp/b"
        )
        found = unused_entry_findings(
            baseline, set(runner.ANALYZERS), runner.family_of_rule, full_run=True
        )
        assert [f.rule for f in found] == ["TPUOP-B001"]
        assert found[0].severity == "warning"
        assert not failing(found)

    def test_partial_run_judges_only_selected_families(self):
        """--only concurrency can condemn a dead TPUOP-C entry (that
        family DID run and the entry still matched nothing) but must not
        condemn a manifest entry it never gave a chance to match."""
        from tpu_operator.lint.baseline import unused_entry_findings

        baseline = Baseline.from_text(
            "TPUOP-C003 py:nowhere.py:gone  # stale\n"
            "TPUOP-M001 ds:nowhere/ctr:x  # not judged on this run\n",
            path="/tmp/b",
        )
        found = unused_entry_findings(
            baseline, {"concurrency"}, runner.family_of_rule, full_run=False
        )
        assert len(found) == 1
        assert "TPUOP-C003" in found[0].message

    def test_partial_run_through_runner_reports_dead_family_entries(self, tmp_path):
        """End to end: run_lint(only=['concurrency']) with a dead C
        entry in the baseline yields the B001 warning even though the
        run was partial."""
        bl = tmp_path / "baseline"
        bl.write_text("TPUOP-C003 py:nowhere.py:gone  # stale\n")
        findings = runner.run_lint(baseline_path=str(bl), only=["concurrency"])
        dead = [f for f in findings if f.rule == "TPUOP-B001"]
        assert len(dead) == 1
        assert dead[0].severity == "warning"

    def test_unclaimed_rule_entries_judged_only_on_full_runs(self, tmp_path):
        bl = tmp_path / "baseline"
        bl.write_text("TPUOP-Z999 somewhere  # rule no family claims\n")
        partial = runner.run_lint(baseline_path=str(bl), only=["concurrency"])
        assert not [f for f in partial if f.rule == "TPUOP-B001"]
        full = runner.run_lint(baseline_path=str(bl))
        assert [f for f in full if f.rule == "TPUOP-B001"]
