"""Validator operand tests (reference analogs: validator component behavior
main.go:450-565, status-file barrier semantics, metrics.go watchers)."""

import threading
import time

import pytest

from tpu_operator import consts
from tpu_operator.kube.fake import FakeClient
from tpu_operator.kube.sim import make_tpu_node
from tpu_operator.validator import status as status_files
from tpu_operator.validator.main import (
    Context,
    enforce_floor,
    run_component,
    validate_libtpu,
    validate_plugin,
    validate_smoke,
    validate_slice,
    validate_workload,
)
from tpu_operator.validator.metrics import NodeMetrics


@pytest.fixture()
def ctx(tmp_path):
    client = FakeClient()
    client.create(make_tpu_node("tpu-0", chips=4))
    install = tmp_path / "libtpu"
    install.mkdir()
    return Context(
        client=client,
        node_name="tpu-0",
        validation_dir=str(tmp_path / "validations"),
        install_dir=str(install),
        retry_interval=0.01,
        resource_poll_retries=3,
        pod_wait_retries=5,
    )


def install_libtpu(ctx):
    import os

    with open(os.path.join(ctx.install_dir, "libtpu.so"), "wb") as f:
        f.write(b"\x7fELF-fake")
    with open(os.path.join(ctx.install_dir, consts.LIBTPU_CTR_READY_FILE), "w"):
        pass


class TestStatusFiles:
    def test_round_trip(self, tmp_path):
        d = str(tmp_path)
        assert status_files.read_status("x", d) is None
        status_files.write_status("x", d, {"ok": True})
        assert status_files.read_status("x", d) == {"ok": True}
        status_files.clear_status("x", d)
        assert status_files.read_status("x", d) is None

    def test_empty_payload(self, tmp_path):
        status_files.write_status("y", str(tmp_path))
        assert status_files.read_status("y", str(tmp_path)) == {}


class TestLibtpuComponent:
    def test_fails_without_library(self, ctx):
        with pytest.raises(RuntimeError, match="libtpu.so not found"):
            validate_libtpu(ctx)

    def test_passes_and_writes_status(self, ctx):
        install_libtpu(ctx)
        payload = run_component("libtpu", ctx, max_attempts=1)
        assert payload["size"] > 0
        assert status_files.read_status(consts.LIBTPU_READY_FILE, ctx.validation_dir)["size"] > 0

    def test_retry_until_installed(self, ctx):
        def install_later():
            time.sleep(0.05)
            install_libtpu(ctx)

        t = threading.Thread(target=install_later)
        t.start()
        payload = run_component("libtpu", ctx, max_attempts=50)
        t.join()
        assert payload["size"] > 0


class TestPluginComponent:
    def test_sees_allocatable_chips(self, ctx):
        assert validate_plugin(ctx) == {"resource": consts.TPU_RESOURCE_NAME, "chips": 4}

    def test_times_out_without_resource(self, ctx):
        node = ctx.client.get("v1", "Node", "tpu-0")
        node["status"]["allocatable"] = {}
        ctx.client.update_status(node)
        with pytest.raises(RuntimeError, match="never became allocatable"):
            validate_plugin(ctx)


class TestWorkloadComponent:
    def test_pod_schedules_via_selector_not_nodename(self, ctx):
        """The smoke pod must go through the scheduler (hostname selector
        + TPU limit) so it exercises google.com/tpu accounting — nodeName
        pinning would bypass the very allocation plugin validation just
        proved (reference: plugin-workload-validation.yaml)."""
        from tpu_operator.validator.main import workload_pod

        pod = workload_pod(ctx)
        assert "nodeName" not in pod["spec"] or pod["spec"]["nodeName"] is None
        assert pod["spec"]["nodeSelector"] == {"kubernetes.io/hostname": ctx.node_name}
        limits = pod["spec"]["containers"][0]["resources"]["limits"]
        assert consts.TPU_RESOURCE_NAME in limits

    def test_waits_for_pod_success(self, ctx):
        def kubelet():
            # fake kubelet: run the scheduled validation pod to completion
            for _ in range(200):
                pods = ctx.client.list("v1", "Pod", ctx.namespace, label_selector={"app": "tpu-workload-validation"})
                for pod in pods:
                    if pod.get("status", {}).get("phase") != "Succeeded":
                        pod["status"] = {"phase": "Succeeded"}
                        ctx.client.update_status(pod)
                        return
                time.sleep(0.005)

        t = threading.Thread(target=kubelet)
        t.start()
        payload = validate_workload(ctx)
        t.join()
        assert payload["phase"] == "Succeeded"
        # pod cleaned up
        assert ctx.client.list("v1", "Pod", ctx.namespace, label_selector={"app": "tpu-workload-validation"}) == []

    def test_failed_pod_raises(self, ctx):
        def kubelet():
            for _ in range(200):
                pods = ctx.client.list("v1", "Pod", ctx.namespace, label_selector={"app": "tpu-workload-validation"})
                if pods:
                    pod = pods[0]
                    pod["status"] = {"phase": "Failed"}
                    ctx.client.update_status(pod)
                    return
                time.sleep(0.005)

        t = threading.Thread(target=kubelet)
        t.start()
        with pytest.raises(RuntimeError, match="failed"):
            validate_workload(ctx)
        t.join()


class TestPerfFloors:
    """spec.validator.minTflops / minPsumGbpsPerChip: below-floor nodes
    must fail validation (NotReady, status file withheld) — the reference
    gates only on resource presence (main.go:1096-1174), letting degraded
    hardware sail to Ready."""

    def test_enforce_floor(self):
        enforce_floor("x", measured=100.0, floor=None)  # no floor: no-op
        enforce_floor("x", measured=100.0, floor=99.0)
        with pytest.raises(RuntimeError, match="below configured floor"):
            enforce_floor("x", measured=98.9, floor=99.0)

    def test_smoke_fails_below_tflops_floor(self, ctx, monkeypatch):
        import tpu_operator.workloads.matmul_bench as mb
        import tpu_operator.workloads.smoke as smoke_mod

        monkeypatch.setattr(smoke_mod, "run_smoke", lambda **kw: {"ok": True})
        monkeypatch.setattr(mb, "matmul_tflops", lambda **kw: {"tflops": 50.0})
        ctx.min_tflops = 120.0
        with pytest.raises(RuntimeError, match="below configured floor"):
            validate_smoke(ctx)

    def test_smoke_passes_at_or_above_floor(self, ctx, monkeypatch):
        import tpu_operator.workloads.matmul_bench as mb
        import tpu_operator.workloads.smoke as smoke_mod

        monkeypatch.setattr(smoke_mod, "run_smoke", lambda **kw: {"ok": True})
        monkeypatch.setattr(mb, "matmul_tflops", lambda **kw: {"tflops": 150.0})
        ctx.min_tflops = 120.0
        report = validate_smoke(ctx)
        assert report["matmul_bf16_tflops"] == 150.0

    def test_smoke_without_floor_skips_bench(self, ctx, monkeypatch):
        import tpu_operator.workloads.smoke as smoke_mod

        monkeypatch.setattr(smoke_mod, "run_smoke", lambda **kw: {"ok": True})
        # matmul_tflops NOT patched: calling it would hit real hardware —
        # the no-floor path must not
        report = validate_smoke(ctx)
        assert "matmul_bf16_tflops" not in report

    def test_below_floor_withholds_status_file(self, ctx, monkeypatch):
        import tpu_operator.workloads.matmul_bench as mb
        import tpu_operator.workloads.smoke as smoke_mod

        monkeypatch.setattr(smoke_mod, "run_smoke", lambda **kw: {"ok": True})
        monkeypatch.setattr(mb, "matmul_tflops", lambda **kw: {"tflops": 1.0})
        ctx.min_tflops = 120.0
        import tpu_operator.validator.main as vmain

        monkeypatch.setitem(
            vmain.COMPONENTS, "smoke", (validate_smoke, "smoke-perf-ready")
        )
        with pytest.raises(RuntimeError):
            run_component("smoke", ctx, max_attempts=2)
        assert status_files.read_status("smoke-perf-ready", ctx.validation_dir) is None

    def test_slice_fails_below_psum_floor(self, ctx, monkeypatch):
        """The real validate_slice, with the collective measurement
        stubbed to a degraded multi-chip report: the floor check fires
        right after the allreduce, before the heavyweight checks."""
        import types

        from tpu_operator.workloads import allreduce, distributed

        monkeypatch.setattr(
            distributed,
            "initialize",
            lambda: types.SimpleNamespace(num_processes=2, process_id=0),
        )
        monkeypatch.setattr(
            allreduce,
            "run_allreduce",
            lambda **kw: {"devices": 8, "peak_busbw_gbps_per_chip": 12.5},
        )
        ctx.min_psum_gbps_per_chip = 40.0
        with pytest.raises(RuntimeError, match="psum bus GB/s/chip.*below"):
            validate_slice(ctx)

    def test_floor_envs_parse(self, monkeypatch):
        monkeypatch.setenv("MIN_TFLOPS", "120.5")
        monkeypatch.setenv("MIN_PSUM_GBPS_PER_CHIP", "37")
        c = Context.from_env()
        assert c.min_tflops == 120.5
        assert c.min_psum_gbps_per_chip == 37.0
        monkeypatch.setenv("MIN_TFLOPS", "garbage")
        assert Context.from_env().min_tflops is None

    def test_floor_falls_back_to_published_table(self, monkeypatch):
        """With no explicit minTflops, the workload floor comes from the
        operator-published per-generation table (the same floors the
        exporter's grey-failure detection uses); an explicit spec value
        always wins."""
        from tpu_operator.perf import FLOOR_FRACTION, floors_json

        monkeypatch.delenv("MIN_TFLOPS", raising=False)
        monkeypatch.setenv("PERF_FLOORS_JSON", floors_json())
        monkeypatch.setattr(
            "tpu_operator.workloads.matmul_bench.chip_generation", lambda: "v5e"
        )
        assert Context.from_env().min_tflops == pytest.approx(
            185.0 * FLOOR_FRACTION, rel=0.01
        )
        # explicit spec floor wins over the table
        monkeypatch.setenv("MIN_TFLOPS", "42")
        assert Context.from_env().min_tflops == 42.0
        # off-TPU: no generation -> no fallback floor
        monkeypatch.delenv("MIN_TFLOPS", raising=False)
        monkeypatch.setattr(
            "tpu_operator.workloads.matmul_bench.chip_generation", lambda: ""
        )
        assert Context.from_env().min_tflops is None

    def test_workload_pod_carries_floor_env(self, ctx):
        from tpu_operator.validator.main import workload_pod

        ctx.min_tflops = 100.0
        pod = workload_pod(ctx)
        env = {e["name"]: e["value"] for e in pod["spec"]["containers"][0]["env"]}
        assert env["MIN_TFLOPS"] == "100.0"


class TestNodeMetrics:
    def test_collects_status_and_devices(self, ctx):
        install_libtpu(ctx)
        status_files.write_status(consts.LIBTPU_READY_FILE, ctx.validation_dir, {"ok": True})
        status_files.write_status(
            "slice-ready",
            ctx.validation_dir,
            {
                "peak_busbw_gbps_per_chip": 42.5,
                "ring_attention": {"max_abs_err": 3.5e-7},
                "flash_attention": {"max_abs_err": 7.8e-3},
                "ring_flash_attention": {"max_abs_err": 5.4e-7},
                "pipeline": {"ok": True, "stages": 4, "max_abs_err_vs_sequential": 9e-8},
            },
        )
        nm = NodeMetrics(ctx)
        nm.collect_status_files()
        nm.collect_device_count()
        nm.revalidate_libtpu()
        sample = {
            (m.name, tuple(sorted(s.labels.items())), s.value)
            for m in nm.registry.collect()
            for s in m.samples
        }
        values = {m.name: {tuple(sorted(s.labels.items())): s.value for s in m.samples} for m in nm.registry.collect()}
        ready = values["tpu_operator_node_component_ready"]
        assert ready[(("component", consts.LIBTPU_READY_FILE), ("node", "tpu-0"))] == 1
        assert ready[(("component", consts.PLUGIN_READY_FILE), ("node", "tpu-0"))] == 0
        assert values["tpu_operator_node_tpu_chips"][(("node", "tpu-0"),)] == 4
        assert values["tpu_operator_node_slice_allreduce_busbw_gbps"][(("node", "tpu-0"),)] == 42.5
        assert values["tpu_operator_node_slice_ring_attention_max_abs_err"][
            (("node", "tpu-0"),)
        ] == 3.5e-7
        assert values["tpu_operator_node_slice_pipeline_max_abs_err"][
            (("node", "tpu-0"),)
        ] == 9e-8
        assert values["tpu_operator_node_slice_flash_attention_max_abs_err"][
            (("node", "tpu-0"),)
        ] == 7.8e-3
        assert values["tpu_operator_node_slice_ring_flash_attention_max_abs_err"][
            (("node", "tpu-0"),)
        ] == 5.4e-7

    def test_revalidation_failure_clears_barrier(self, ctx):
        status_files.write_status(consts.LIBTPU_READY_FILE, ctx.validation_dir, {"ok": True})
        nm = NodeMetrics(ctx)
        nm.revalidate_libtpu()  # libtpu.so absent -> must clear the file
        assert status_files.read_status(consts.LIBTPU_READY_FILE, ctx.validation_dir) is None


class TestLibtpuInstaller:
    def test_install_and_validate_round_trip(self, tmp_path):
        from tpu_operator.agents import libtpu_installer

        src = tmp_path / "src" / "libtpu.so"
        src.parent.mkdir()
        src.write_bytes(b"\x7fELF fake libtpu " + b"x" * 100)
        install_dir = str(tmp_path / "install")
        report = libtpu_installer.install(str(src), install_dir, version="1.2.3")
        assert report["changed"] is True
        import os

        link = os.path.join(install_dir, "libtpu.so")
        assert os.path.islink(link)
        assert os.readlink(link) == "libtpu-1.2.3.so"
        # the validator's libtpu component now passes against this dir
        ctx = Context(install_dir=install_dir, validation_dir=str(tmp_path / "val"), retry_interval=0.01)
        payload = validate_libtpu(ctx)
        assert payload["size"] > 0
        # idempotent second run
        assert libtpu_installer.install(str(src), install_dir, version="1.2.3")["changed"] is False

    def test_version_upgrade_repoints_symlink(self, tmp_path):
        from tpu_operator.agents import libtpu_installer
        import os

        src1 = tmp_path / "a.so"; src1.write_bytes(b"v1" * 50)
        src2 = tmp_path / "b.so"; src2.write_bytes(b"v2" * 50)
        install_dir = str(tmp_path / "install")
        libtpu_installer.install(str(src1), install_dir, version="1")
        libtpu_installer.install(str(src2), install_dir, version="2")
        assert os.readlink(os.path.join(install_dir, "libtpu.so")) == "libtpu-2.so"
        with open(os.path.join(install_dir, "version")) as f:
            assert f.read().strip() == "2"

    def test_explicit_source_takes_priority(self, tmp_path):
        from tpu_operator.agents import libtpu_installer

        src = tmp_path / "custom-libtpu.so"
        src.write_bytes(b"custom")
        # an explicit existing source wins over any bundled library
        assert libtpu_installer.find_libtpu(str(src)) == str(src)
        # a missing explicit source falls back to the bundled library (this
        # image ships one) or raises when nothing exists — both are valid
        # find_libtpu contracts; just assert it never returns a missing path
        import os

        try:
            found = libtpu_installer.find_libtpu("/nonexistent/libtpu.so")
            assert os.path.exists(found)
        except FileNotFoundError:
            pass
