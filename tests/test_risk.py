"""Predictive health: per-host risk scoring + proactive migration
(ISSUE 19).

Three layers under test:

- the scorer's signal folding (`controllers/risk.py`): absent /
  malformed / STALE telemetry is no-signal, fresh straggler + grey +
  repair signals fold, healed risk decays back to zero and releases the
  migration budget, the gauge retires with the host, and every
  action-gating read fails CLOSED;
- the action layer: owner-safe execution (jobs behind the checkpoint
  barrier, serving replicas drain-then-re-place, unowned gangs never
  touched), the persisted per-host budget, predicted-vs-realized
  settlement;
- the job controller's `risk-` barrier arm: request → checkpoint →
  teardown → resume with the step watermark intact, and token
  redelivery never migrating twice.
"""

import json

import pytest

from tpu_operator import consts
from tpu_operator.api.tpujob import JobPhase, new_tpu_job
from tpu_operator.api.tpuslice import TPU_SLICE_API_VERSION, new_tpu_slice
from tpu_operator.controllers.job_controller import JobReconciler
from tpu_operator.controllers.placement_controller import (
    QUEUE_REQUEST,
    PlacementReconciler,
)
from tpu_operator.controllers.risk import RiskScorer, read_node_risk
from tpu_operator.kube import errors
from tpu_operator.kube.controller import Request
from tpu_operator.kube.fake import FakeClient
from tpu_operator.kube.objects import new_object
from tpu_operator.kube.sim import GangFaultSchedule, make_torus_nodes

NS = "tpu-operator"


def _scorer(client, at=1000.0):
    risk = RiskScorer(client, NS)
    clock = [at]
    risk._now = lambda: clock[0]
    return risk, clock


def _gang_artifact(client, slice_name, artifact):
    """Create-or-patch the slice-manager-owned gang ConfigMap with a
    telemetry artifact (dict → JSON; str → written raw, for the
    malformed cases)."""
    name = f"{slice_name}-gang"
    raw = artifact if isinstance(artifact, str) else json.dumps(artifact)
    if client.get_or_none("v1", "ConfigMap", name, NS) is None:
        obj = new_object("v1", "ConfigMap", name, NS, data={})
        obj["metadata"]["labels"] = {
            "app.kubernetes.io/managed-by": "tpu-slice-manager"
        }
        client.create(obj)
    client.patch(
        "v1", "ConfigMap", name,
        {"metadata": {
            "labels": {"app.kubernetes.io/managed-by": "tpu-slice-manager"},
            "annotations": {consts.GANG_TELEMETRY_ANNOTATION: raw},
        }}, NS,
    )


def _placed_slice(client, name="g1", owner=None, shape="2x2x1"):
    body = new_tpu_slice(name, {"placement": {"shape": shape}})
    if owner:
        kind, owner_name = owner
        body["metadata"]["ownerReferences"] = [{
            "apiVersion": "tpu.google.com/v1alpha1", "kind": kind,
            "name": owner_name, "uid": "u-" + owner_name,
        }]
    client.create(body)
    PlacementReconciler(client, NS).reconcile(QUEUE_REQUEST)
    obj = client.get(TPU_SLICE_API_VERSION, "TPUSlice", name)
    return ((obj.get("status") or {}).get("placement") or {}).get("nodes") or []


def _state(client):
    cm = client.get_or_none("v1", "ConfigMap", consts.RISK_STATE_CONFIGMAP, NS)
    raw = ((cm or {}).get("data") or {}).get(consts.RISK_STATE_KEY, "")
    try:
        return json.loads(raw) or {}
    except ValueError:
        return {}


def _cluster(dims=(4, 4, 1), prefix="rk"):
    client = FakeClient()
    for node in make_torus_nodes(dims, prefix=prefix):
        client.create(node)
    return client


class TestRiskSignals:
    def test_no_telemetry_is_no_signal(self):
        client = _cluster()
        risk, _ = _scorer(client)
        summary = risk.sync()
        assert summary["scores"] == {}
        assert summary["migrated"] == []
        # a quiet pass writes nothing
        assert client.get_or_none(
            "v1", "ConfigMap", consts.RISK_STATE_CONFIGMAP, NS
        ) is None

    @pytest.mark.parametrize("artifact", [
        "not json {",
        json.dumps(["a", "list"]),
        json.dumps({"straggler_ratio": 2.0}),          # no slowest_host
        json.dumps({"slowest_host": "rk-0"}),          # no ratio
        json.dumps({"slowest_host": "rk-0", "straggler_ratio": "NaNsense"}),
    ])
    def test_malformed_artifacts_are_no_signal(self, artifact):
        client = _cluster()
        _placed_slice(client, "g1")
        _gang_artifact(client, "g1", artifact)
        risk, _ = _scorer(client)
        assert risk.sync()["scores"] == {}

    def test_stale_artifact_is_no_signal(self):
        """The fabric analyzer's staleness convention: a re-placed
        gang's old artifact must not convict a host the gang no longer
        runs on."""
        client = _cluster()
        members = _placed_slice(client, "g1")
        _gang_artifact(client, "g1", {
            "straggler_ratio": 2.0, "slowest_host": members[0],
        })
        risk, _ = _scorer(client)
        assert risk.sync()["scores"].get(members[0], 0.0) > 0.0
        # the gang moves away: same CM, same artifact — now stale
        for node in client.list("v1", "Node"):
            labels = node["metadata"].get("labels") or {}
            if labels.get(consts.PLACEMENT_LABEL) == "g1":
                client.patch("v1", "Node", node["metadata"]["name"], {
                    "metadata": {"labels": {
                        consts.PLACEMENT_LABEL: None,
                        consts.PLACEMENT_INDEX_LABEL: None,
                    }}})
        client2 = client
        risk2, _ = _scorer(client2)
        summary = risk2.sync()
        assert "g1" in summary["stale"]
        assert "straggler" not in (summary["signals"].get(members[0]) or {})

    def test_fresh_signals_fold_and_cap(self):
        client = _cluster()
        members = _placed_slice(client, "g1")
        host = members[0]
        _gang_artifact(client, "g1", {
            "straggler_ratio": 1.6, "slowest_host": host,
        })
        client.patch("v1", "Node", host, {"metadata": {
            "labels": {consts.TPU_PERF_LABEL: consts.PERF_DEGRADED},
            "annotations": {consts.REPAIR_RETRIES_ANNOTATION: "4"},
        }})
        risk, _ = _scorer(client)
        summary = risk.sync()
        parts = summary["signals"][host]
        assert parts["straggler"] == pytest.approx(0.6)
        assert parts["grey"] == pytest.approx(consts.RISK_WEIGHT_GREY)
        assert parts["repair"] == pytest.approx(consts.RISK_WEIGHT_REPAIR_CAP)
        assert summary["scores"][host] == pytest.approx(
            min(1.0, 0.6 + consts.RISK_WEIGHT_GREY + consts.RISK_WEIGHT_REPAIR_CAP)
        )

    def test_healed_straggler_decays_to_zero_and_releases_budget(self):
        client = _cluster()
        members = _placed_slice(client, "g1")  # unowned: scored, never acted on
        host = members[0]
        _gang_artifact(client, "g1", {
            "straggler_ratio": 2.0, "slowest_host": host,
        })
        risk, clock = _scorer(client)
        assert risk.sync()["scores"][host] == pytest.approx(1.0)
        # seed a spent budget entry, as a real migration would have
        state = _state(client)
        state["hosts"][host].update({"attempts": 1, "nextAttemptAt": 9999.0})
        client.patch("v1", "ConfigMap", consts.RISK_STATE_CONFIGMAP, {
            "data": {consts.RISK_STATE_KEY: json.dumps(state)}}, NS)
        _gang_artifact(client, "g1", {
            "straggler_ratio": 1.0, "slowest_host": host,  # healed
        })
        clock[0] += 30.0
        summary = risk.sync()
        assert summary["scores"][host] == pytest.approx(1.0 * consts.RISK_DECAY)
        # 0.7 is still over the threshold: the budget stays spent
        assert _state(client)["hosts"][host]["attempts"] == 1
        clock[0] += 30.0
        summary = risk.sync()  # 0.49 < threshold: budget handed back
        entry = _state(client)["hosts"][host]
        assert "attempts" not in entry and "nextAttemptAt" not in entry
        scores = [1.0 * consts.RISK_DECAY, summary["scores"][host]]
        for _ in range(12):
            clock[0] += 30.0
            summary = risk.sync()
            if host not in summary["scores"]:
                break
            scores.append(summary["scores"][host])
        assert host not in summary["scores"]  # below the floor: retired
        assert scores == sorted(scores, reverse=True)

    def test_gauge_retired_when_node_leaves_fleet(self):
        client = _cluster()
        members = _placed_slice(client, "g1")
        host = members[0]
        _gang_artifact(client, "g1", {
            "straggler_ratio": 2.0, "slowest_host": host,
        })
        risk, clock = _scorer(client)
        risk.sync()
        assert host in risk._risk_series
        client.delete("v1", "Node", host)
        clock[0] += 30.0
        summary = risk.sync()
        assert host not in summary["scores"]
        assert host not in risk._risk_series
        assert host not in (_state(client).get("hosts") or {})

    def test_unreadable_state_cm_fails_closed(self, monkeypatch):
        client = _cluster()
        members = _placed_slice(client, "g1", owner=("TPUJob", "tj"))
        _gang_artifact(client, "g1", {
            "straggler_ratio": 2.0, "slowest_host": members[0],
        })
        risk, _ = _scorer(client)
        real = client.get_or_none

        def flaky(api_version, kind, name, namespace=None, **kw):
            if name == consts.RISK_STATE_CONFIGMAP:
                raise errors.ApiError("etcd sneezed")
            return real(api_version, kind, name, namespace, **kw)

        monkeypatch.setattr(client, "get_or_none", flaky)
        summary = risk.sync()
        assert summary["migrated"] == []
        assert summary["scores"] == {}

    def test_unreadable_inputs_fail_closed(self, monkeypatch):
        client = _cluster()
        risk, _ = _scorer(client)
        monkeypatch.setattr(
            client, "list",
            lambda *a, **kw: (_ for _ in ()).throw(errors.ApiError("down")),
        )
        summary = risk.sync()
        assert summary == {
            "scores": {}, "signals": {}, "stale": [],
            "migrated": [], "migrations": [],
        }

    def test_malformed_state_cm_never_crashes(self):
        client = _cluster()
        client.create(new_object(
            "v1", "ConfigMap", consts.RISK_STATE_CONFIGMAP, NS,
            data={consts.RISK_STATE_KEY: "{not json"},
        ))
        risk, _ = _scorer(client)
        risk.sync()  # fresh ledger, no crash
        assert read_node_risk(client, NS) == {}


class TestRiskActions:
    def test_unowned_gang_never_touched(self):
        client = _cluster()
        members = _placed_slice(client, "bare")
        _gang_artifact(client, "bare", {
            "straggler_ratio": 2.0, "slowest_host": members[0],
        })
        risk, _ = _scorer(client)
        summary = risk.sync()
        assert summary["scores"][members[0]] >= consts.RISK_THRESHOLD
        assert summary["migrated"] == []
        assert not _state(client).get("migrations")

    def test_last_routable_serving_replica_never_drained(self):
        client = _cluster()
        members = _placed_slice(client, "solo-0", owner=("TPUServing", "solo"))
        _gang_artifact(client, "solo-0", {
            "straggler_ratio": 2.0, "slowest_host": members[0],
        })
        risk, _ = _scorer(client)
        assert risk.sync()["migrated"] == []
        # the gang keeps its assignment labels
        node = client.get("v1", "Node", members[0])
        assert (node["metadata"]["labels"] or {}).get(
            consts.PLACEMENT_LABEL
        ) == "solo-0"

    def test_serving_with_healthy_sibling_drains(self):
        client = _cluster()
        members = _placed_slice(client, "svc-0", owner=("TPUServing", "svc"))
        _placed_slice(client, "svc-1", owner=("TPUServing", "svc"))
        _gang_artifact(client, "svc-0", {
            "straggler_ratio": 2.0, "slowest_host": members[0],
        })
        risk, _ = _scorer(client)
        summary = risk.sync()
        assert summary["migrated"] == [members[0]]
        node = client.get("v1", "Node", members[0])
        assert not (node["metadata"].get("labels") or {}).get(
            consts.PLACEMENT_LABEL
        )
        migrations = _state(client)["migrations"]
        assert len(migrations) == 1
        assert migrations[0]["owner_kind"] == "TPUServing"
        assert migrations[0]["settled"] is False

    def test_budget_gate_charges_and_blocks_inside_window(self):
        risk, _ = _scorer(FakeClient())
        entry = {}
        assert risk._charge_attempt(entry, 1000.0)
        assert entry["attempts"] == 1
        # a second alarm inside the window never fires (floored at base)
        assert entry["nextAttemptAt"] >= 1000.0 + consts.RISK_MIGRATION_BASE_SECONDS
        assert not risk._charge_attempt(entry, 1001.0)
        assert entry["attempts"] == 1
        # the budget exhausts after the retry limit
        now = 1000.0
        for _ in range(consts.RISK_MIGRATION_RETRY_LIMIT * 2):
            now = float(entry["nextAttemptAt"]) + 1.0
            risk._charge_attempt(entry, now)
        assert entry["attempts"] == consts.RISK_MIGRATION_RETRY_LIMIT

    def test_settlement_books_realized_and_false_alarms(self):
        client = _cluster()
        members = _placed_slice(client, "svc-0", owner=("TPUServing", "svc"))
        _placed_slice(client, "svc-1", owner=("TPUServing", "svc"))
        _gang_artifact(client, "svc-0", {
            "straggler_ratio": 2.0, "slowest_host": members[0],
        })
        risk, clock = _scorer(client)
        risk.sync()
        assert _state(client)["migrations"][0]["realized"] is None
        # the host dies: prediction realized
        client.patch("v1", "Node", members[0], {"metadata": {"labels": {
            consts.TPU_HEALTH_LABEL: consts.HEALTH_DEGRADED}}})
        clock[0] += 30.0
        risk.sync()
        m = _state(client)["migrations"][0]
        assert m["settled"] and m["realized"] is True

    def test_false_alarm_settles_unrealized_after_grace(self):
        client = _cluster()
        members = _placed_slice(client, "svc-0", owner=("TPUServing", "svc"))
        _placed_slice(client, "svc-1", owner=("TPUServing", "svc"))
        _gang_artifact(client, "svc-0", {
            "straggler_ratio": 2.0, "slowest_host": members[0],
        })
        risk, clock = _scorer(client)
        risk.sync()  # drains svc-0 → its artifact goes stale → decay
        for _ in range(20):
            clock[0] += consts.RISK_SETTLE_GRACE_SECONDS / 3.0
            risk.sync()
            migrations = _state(client).get("migrations") or []
            if migrations and migrations[0].get("settled"):
                break
        m = _state(client)["migrations"][0]
        assert m["settled"] and m["realized"] is False
        # budget released with the verdict
        entry = (_state(client).get("hosts") or {}).get(members[0]) or {}
        assert "attempts" not in entry and "nextAttemptAt" not in entry


class TestJobRiskBarrier:
    def _world(self):
        client = FakeClient()
        for node in make_torus_nodes((4, 2, 1), prefix="jb"):
            client.create(node)
        client.create(new_tpu_job("tj", {
            "workload": {"steps": 1000}, "gang": {"shape": "2x2x1"},
        }))
        job_rec = JobReconciler(client, NS)
        place = PlacementReconciler(client, NS)
        name = "tj" + consts.JOB_PROGRESS_SUFFIX

        def trainer():
            cm = client.get_or_none("v1", "ConfigMap", name, NS)
            if cm is None:
                client.create(new_object("v1", "ConfigMap", name, NS, data={}))
                cm = client.get("v1", "ConfigMap", name, NS)
            slice_obj = client.get_or_none(
                TPU_SLICE_API_VERSION, "TPUSlice", "tj-slice"
            )
            placement = ((slice_obj or {}).get("status") or {}).get("placement") or {}
            data = {
                consts.JOB_PROGRESS_STEP: "42",
                consts.JOB_PROGRESS_CHECKPOINT_STEP: "40",
                consts.JOB_PROGRESS_EPOCH: "4",
                consts.JOB_PROGRESS_WORLD: str(len(placement.get("nodes") or [])),
                consts.JOB_PROGRESS_STATUS: consts.JOB_PROGRESS_RUNNING,
            }
            request = (cm.get("data") or {}).get(consts.JOB_CHECKPOINT_REQUEST, "")
            if request:
                data[consts.JOB_PROGRESS_CHECKPOINT_ACK] = request
            client.patch("v1", "ConfigMap", name, {"data": data}, NS)

        for _ in range(4):
            job_rec.reconcile(Request(name="tj"))
            place.reconcile(QUEUE_REQUEST)
            trainer()
        return client, job_rec, place, trainer

    def _block(self, client):
        job = client.get("tpu.google.com/v1alpha1", "TPUJob", "tj")
        return (job.get("status") or {}).get("job") or {}

    def test_risk_request_drives_barrier_teardown_resume(self):
        client, job_rec, place, trainer = self._world()
        assert self._block(client).get("phase") == JobPhase.RUNNING
        client.patch(
            "v1", "ConfigMap", "tj" + consts.JOB_PROGRESS_SUFFIX,
            {"data": {consts.JOB_RISK_MIGRATE_REQUEST: "risk-t1"}}, NS,
        )
        job_rec.reconcile(Request(name="tj"))
        block = self._block(client)
        assert block["phase"] == JobPhase.CHECKPOINTING
        assert str(block.get("barrier", "")).startswith("risk-")
        trainer()  # ack the barrier
        job_rec.reconcile(Request(name="tj"))
        block = self._block(client)
        assert block["phase"] in (JobPhase.RESUMING, JobPhase.PLACING)
        assert block.get("riskHandled") == "risk-t1"
        # the honored barrier key is lifted for the next generation
        progress = client.get(
            "v1", "ConfigMap", "tj" + consts.JOB_PROGRESS_SUFFIX, NS
        )
        assert not (progress.get("data") or {}).get(consts.JOB_CHECKPOINT_REQUEST)
        for _ in range(4):
            place.reconcile(QUEUE_REQUEST)
            trainer()
            job_rec.reconcile(Request(name="tj"))
        block = self._block(client)
        assert block["phase"] == JobPhase.RUNNING
        assert block["step"] == 42  # watermark intact across the move

    def test_redelivered_token_never_migrates_twice(self):
        client, job_rec, place, trainer = self._world()
        client.patch(
            "v1", "ConfigMap", "tj" + consts.JOB_PROGRESS_SUFFIX,
            {"data": {consts.JOB_RISK_MIGRATE_REQUEST: "risk-t1"}}, NS,
        )
        for _ in range(6):
            job_rec.reconcile(Request(name="tj"))
            place.reconcile(QUEUE_REQUEST)
            trainer()
        seq = self._block(client).get("barrierSeq")
        assert self._block(client).get("riskHandled") == "risk-t1"
        # redelivery: the scorer's key still carries the honored token
        client.patch(
            "v1", "ConfigMap", "tj" + consts.JOB_PROGRESS_SUFFIX,
            {"data": {consts.JOB_RISK_MIGRATE_REQUEST: "risk-t1"}}, NS,
        )
        for _ in range(3):
            job_rec.reconcile(Request(name="tj"))
            trainer()
        assert self._block(client).get("barrierSeq") == seq
        assert self._block(client).get("phase") == JobPhase.RUNNING

    def test_broken_gang_auto_satisfies_risk_request(self):
        client, job_rec, place, trainer = self._world()
        client.patch(
            "v1", "ConfigMap", "tj" + consts.JOB_PROGRESS_SUFFIX,
            {"data": {consts.JOB_RISK_MIGRATE_REQUEST: "risk-t2"}}, NS,
        )
        # a member dies before the barrier closes: the re-place IS the
        # migration, and the token must not replay once healthy
        for node in client.list("v1", "Node"):
            labels = node["metadata"].get("labels") or {}
            if labels.get(consts.PLACEMENT_LABEL) == "tj-slice":
                client.patch("v1", "Node", node["metadata"]["name"], {
                    "metadata": {"labels": {
                        consts.TPU_HEALTH_LABEL: consts.HEALTH_DEGRADED}}})
                break
        job_rec.reconcile(Request(name="tj"))
        assert self._block(client).get("riskHandled") == "risk-t2"


class TestSimPrecursors:
    def test_default_schedule_unchanged(self):
        """precursor_passes=0 must reproduce the historical log byte
        for byte — same seed, same driving sequence."""
        logs = []
        for _ in range(2):
            client = _cluster(prefix="sp")
            _placed_slice(client, "sp-slice")
            sched = GangFaultSchedule(
                client, NS, "sp-slice", seed=7, start_at=2, every=4, heal_after=2
            )
            for _ in range(25):
                sched.step()
            logs.append(list(sched.log))
        assert logs[0] == logs[1]
        assert not any(entry[1].startswith("precursor") for entry in logs[0])

    def test_precursor_window_names_the_eventual_victim(self):
        client = _cluster(prefix="pw")
        _placed_slice(client, "pw-slice")
        sched = GangFaultSchedule(
            client, NS, "pw-slice", seed=3, classes=("host-death",),
            start_at=8, every=6, heal_after=2, precursor_passes=4,
        )
        for _ in range(10):
            sched.step()
        precursors = [e for e in sched.log if e[1] == "precursor"]
        kills = [e for e in sched.log if e[1] == "inject"]
        assert len(precursors) == 4 and len(kills) == 1
        victim = kills[0][3]
        assert all(e[3].startswith(victim + " ") for e in precursors)
        assert all(e[0] < kills[0][0] for e in precursors)
        # the artifact the window left behind is real gang telemetry
        cm = client.get("v1", "ConfigMap", "pw-slice-gang", NS)
        artifact = json.loads(
            cm["metadata"]["annotations"][consts.GANG_TELEMETRY_ANNOTATION]
        )
        assert artifact["slowest_host"] == victim

    def test_false_alarm_window_heals_without_killing(self):
        client = _cluster(prefix="fw")
        _placed_slice(client, "fw-slice")
        sched = GangFaultSchedule(
            client, NS, "fw-slice", seed=3, classes=(),
            precursor_passes=3, false_alarm_at=[2],
        )
        for _ in range(8):
            sched.step()
        kinds = [e[1] for e in sched.log]
        assert "inject" not in kinds
        assert kinds.count("precursor") == 3
        assert kinds.count("precursor-heal") == 1
        cm = client.get("v1", "ConfigMap", "fw-slice-gang", NS)
        artifact = json.loads(
            cm["metadata"]["annotations"][consts.GANG_TELEMETRY_ANNOTATION]
        )
        assert artifact["straggler_ratio"] == 1.0  # healed at window end
