"""JAX workload tests on the 8-device virtual CPU mesh (conftest forces
jax_platforms=cpu + xla_force_host_platform_device_count=8)."""

import jax
import numpy as np
import pytest

from tpu_operator.workloads.allreduce import run_allreduce
from tpu_operator.workloads.burnin import (
    BurninConfig,
    build_train_step,
    make_mesh,
    run_burnin,
)
from tpu_operator.workloads.distributed import config_from_env
from tpu_operator.workloads.kernels import hbm_bandwidth_probe, triad
from tpu_operator.workloads.smoke import run_smoke


def _run_gang_check(fn, **kwargs):
    """Run a live multiprocess gang check; the gang contract itself is
    what these tests assert, so an installed jaxlib whose CPU client
    can't execute cross-process collectives is a skip, not a failure."""
    from tpu_operator.workloads.multiproc import CpuCollectivesUnsupportedError

    try:
        return fn(**kwargs)
    except CpuCollectivesUnsupportedError as e:
        pytest.skip(str(e))


def test_virtual_mesh_active():
    assert len(jax.devices()) == 8
    assert jax.devices()[0].platform == "cpu"


class TestSmoke:
    def test_passes(self):
        report = run_smoke(expected_devices=8, size=64)
        assert report["ok"] and report["device_count"] == 8

    def test_insufficient_devices(self):
        with pytest.raises(RuntimeError, match="expected >= 100"):
            run_smoke(expected_devices=100)


class TestAllreduce:
    def test_correct_and_reports_bandwidth(self):
        report = run_allreduce(sizes_mb=(1,), iters=2)
        assert report["devices"] == 8
        assert report["peak_busbw_gbps_per_chip"] > 0
        assert report["results"][0]["busbw_gbps"] == pytest.approx(
            report["results"][0]["algbw_gbps"] * 2 * 7 / 8
        )

    def test_subset_of_devices(self):
        report = run_allreduce(sizes_mb=(1,), devices=jax.devices()[:4], iters=1)
        assert report["devices"] == 4


class TestBurnin:
    def test_mesh_factorization(self):
        mesh = make_mesh()
        assert dict(zip(mesh.axis_names, mesh.devices.shape)) == {"data": 2, "model": 4}
        mesh2 = make_mesh(data=4, model=2)
        assert mesh2.devices.shape == (4, 2)
        with pytest.raises(ValueError):
            make_mesh(data=3, model=3)

    def test_loss_decreases_on_sharded_step(self):
        report = run_burnin(steps=4)
        assert report["ok"]
        assert report["losses"][-1] < report["losses"][0]
        assert all(np.isfinite(report["losses"]))

    def test_params_actually_sharded(self):
        mesh = make_mesh()
        cfg = BurninConfig(n_layers=1)
        step, params, batch = build_train_step(mesh, cfg)
        qkv = params["l0/qkv"]
        # column-parallel over 'model' (4 shards on axis 1)
        shards = qkv.addressable_shards
        assert len(shards) == 8
        assert shards[0].data.shape == (cfg.d_model, 3 * cfg.d_model // 4)

    def test_single_device_mesh(self):
        mesh = make_mesh(devices=jax.devices()[:1], data=1, model=1)
        report = run_burnin(mesh=mesh, steps=2, cfg=BurninConfig(n_layers=1, batch=4, seq_len=32))
        assert report["ok"]


class TestKernels:
    def test_triad_numerics(self):
        import jax.numpy as jnp

        x = jnp.ones((1024, 128), dtype=jnp.float32)
        y = jnp.full((1024, 128), 3.0, dtype=jnp.float32)
        out = triad(x, y, alpha=2.0)
        assert float(out[0, 0]) == 5.0
        assert out.shape == (1024, 128)

    def test_bandwidth_probe(self):
        report = hbm_bandwidth_probe(size_mb=8, iters=2)
        assert report["bandwidth_gbps"] > 0


class TestDistributed:
    def test_single_host(self):
        cfg = config_from_env({})
        assert not cfg.needed and cfg.num_processes == 1

    def test_multi_host_gang(self):
        cfg = config_from_env({"TPU_WORKER_ID": "3", "TPU_WORKER_HOSTNAMES": "a,b,c,d"})
        assert cfg.needed
        assert cfg.coordinator_address == "a:8476"
        assert (cfg.num_processes, cfg.process_id) == (4, 3)

    def test_multislice_coordinator_override(self):
        cfg = config_from_env(
            {"TPU_WORKER_ID": "0", "TPU_WORKER_HOSTNAMES": "a,b",
             "MEGASCALE_COORDINATOR_ADDRESS": "slice0-coord:9000"}
        )
        assert cfg.coordinator_address == "slice0-coord:9000"

    def test_multislice_world_spans_slices(self):
        """MEGASCALE_NUM_SLICES multiplies the process world; a worker's
        global id offsets by its slice's block (slice 1 host 1 of a
        2-slice x 2-host job is process 3 of 4)."""
        cfg = config_from_env(
            {
                "TPU_WORKER_ID": "1",
                "TPU_WORKER_HOSTNAMES": "a,b",
                "MEGASCALE_COORDINATOR_ADDRESS": "slice0-coord:9000",
                "MEGASCALE_NUM_SLICES": "2",
                "MEGASCALE_SLICE_ID": "1",
            }
        )
        assert (cfg.num_processes, cfg.process_id) == (4, 3)
        assert cfg.needed
        # a 1-host slice still needs distributed init when slices > 1
        solo = config_from_env(
            {
                "TPU_WORKER_ID": "0",
                "TPU_WORKER_HOSTNAMES": "a",
                "MEGASCALE_COORDINATOR_ADDRESS": "c:9",
                "MEGASCALE_NUM_SLICES": "2",
                "MEGASCALE_SLICE_ID": "0",
            }
        )
        assert solo.needed and solo.num_processes == 2 and solo.process_id == 0

    def test_multislice_requires_slice_id(self):
        """A pod with a dropped MEGASCALE_SLICE_ID would derive slice 0's
        process block — colliding ids and a hang at initialize, the same
        silent-deadlock class as a missing coordinator. Out-of-range ids
        are equally fatal."""
        base = {
            "TPU_WORKER_ID": "0",
            "TPU_WORKER_HOSTNAMES": "a,b",
            "MEGASCALE_COORDINATOR_ADDRESS": "c:9",
            "MEGASCALE_NUM_SLICES": "2",
        }
        with pytest.raises(ValueError, match="MEGASCALE_SLICE_ID"):
            config_from_env(base)
        with pytest.raises(ValueError, match="outside"):
            config_from_env({**base, "MEGASCALE_SLICE_ID": "2"})
        with pytest.raises(ValueError, match="outside"):
            config_from_env({**base, "MEGASCALE_SLICE_ID": "-1"})

    def test_multislice_requires_coordinator(self):
        """NUM_SLICES>1 without the DCN coordinator would have every slice
        elect its own coordinator while claiming the cross-slice world —
        a silent deadlock; it must fail fast instead."""
        with pytest.raises(ValueError, match="COORDINATOR_ADDRESS"):
            config_from_env(
                {"TPU_WORKER_HOSTNAMES": "a,b", "MEGASCALE_NUM_SLICES": "2"}
            )

    def test_launchers_reject_mismatched_worlds(self):
        from tpu_operator.workloads.multiproc import (
            run_multiprocess_check,
            run_multislice_check,
        )

        # a multi-slice env derives a bigger world than the single-slice
        # launcher spawns
        with pytest.raises(ValueError, match="run_multislice_check"):
            run_multiprocess_check(
                num_workers=2,
                gang_env={
                    "TPU_WORKER_HOSTNAMES": "a,b",
                    "MEGASCALE_COORDINATOR_ADDRESS": "c",
                    "MEGASCALE_NUM_SLICES": "2",
                    "MEGASCALE_SLICE_ID": "0",
                },
            )
        # heterogeneous slices deadlock at initialize; reject up front
        with pytest.raises(ValueError, match="uniform"):
            run_multislice_check(
                num_slices=2,
                gang_envs=[
                    {"TPU_WORKER_HOSTNAMES": "a", "MEGASCALE_NUM_SLICES": "2",
                     "MEGASCALE_COORDINATOR_ADDRESS": "c", "MEGASCALE_SLICE_ID": "0"},
                    {"TPU_WORKER_HOSTNAMES": "a,b", "MEGASCALE_NUM_SLICES": "2",
                     "MEGASCALE_COORDINATOR_ADDRESS": "c", "MEGASCALE_SLICE_ID": "1"},
                ],
            )


class TestCollectives:
    def test_all_primitives_exact(self):
        """psum / all_gather / reduce_scatter / all_to_all / ppermute must
        each be numerically exact on the mesh."""
        from tpu_operator.workloads.collectives import run_collectives_check

        report = run_collectives_check()
        assert report["ok"] and report["devices"] == 8
        assert set(report["errors"]) == {
            "psum", "all_gather", "reduce_scatter", "all_to_all", "ppermute",
        }
        assert max(report["errors"].values()) < 1e-5

    def test_rejects_indivisible_payload(self):
        from tpu_operator.workloads.collectives import run_collectives_check

        with pytest.raises(ValueError, match="divide"):
            run_collectives_check(per_device=2049)


class TestRingAttention:
    def test_flash_local_impl_matches_dense(self):
        """The two-level composition: pallas flash as each ring step's
        local attention (global offsets keep causality across the ring),
        per-step results merged by logsumexp — must match dense."""
        from tpu_operator.workloads.ringattention import run_ring_attention_check

        for causal in (True, False):
            report = run_ring_attention_check(local_impl="flash", causal=causal)
            assert report["ok"] and report["max_abs_err"] < 2e-3

    def test_causal_matches_dense(self):
        from tpu_operator.workloads.ringattention import run_ring_attention_check

        report = run_ring_attention_check(causal=True)
        assert report["ok"] and report["devices"] == 8
        assert report["max_abs_err"] < 2e-4

    def test_non_causal_matches_dense(self):
        from tpu_operator.workloads.ringattention import run_ring_attention_check

        report = run_ring_attention_check(causal=False, seq_len=128)
        assert report["ok"]

    def test_subset_mesh(self):
        import numpy as np
        import jax
        from jax.sharding import Mesh
        from tpu_operator.workloads.ringattention import run_ring_attention_check

        mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))
        report = run_ring_attention_check(mesh=mesh, seq_len=64)
        assert report["devices"] == 4

    def test_indivisible_seq_rejected(self):
        import pytest as _pytest
        from tpu_operator.workloads.ringattention import run_ring_attention_check

        with _pytest.raises(ValueError, match="not divisible"):
            run_ring_attention_check(seq_len=100)

    def test_segment_ids_span_the_ring(self):
        """Packed documents crossing SHARD boundaries: segment ids
        circulate with their K/V block, so same-document attention
        connects across chips and cross-document attention is masked —
        forward and gradients vs the segment-masked dense reference."""
        import numpy as np

        import jax.numpy as jnp
        from jax.sharding import Mesh

        from tpu_operator.workloads.ringattention import (
            dense_attention,
            ring_attention,
        )

        mesh = Mesh(np.array(jax.devices()[:8]), ("sp",))
        b, s, h, d = 2, 64, 2, 8  # 8 chips x 8 local rows
        keys = jax.random.split(jax.random.PRNGKey(17), 3)
        q, k, v = (jax.random.normal(kk, (b, s, h, d), dtype=jnp.float32) for kk in keys)
        # doc boundaries at 13 and 45: both INSIDE shards (local len 8),
        # and every doc spans multiple shards
        seg = jnp.broadcast_to(
            jnp.where(jnp.arange(s) < 13, 0, jnp.where(jnp.arange(s) < 45, 1, 2)),
            (b, s),
        ).astype(jnp.int32)
        for causal in (True, False):
            got = ring_attention(q, k, v, mesh, causal=causal, segment_ids=seg)
            want = dense_attention(q, k, v, causal=causal, segment_ids=seg)
            err = float(jnp.max(jnp.abs(got - want)))
            assert err < 2e-4, f"causal={causal}: {err}"

        def ring_loss(q, k, v):
            return jnp.sum(
                ring_attention(q, k, v, mesh, causal=True, segment_ids=seg) ** 2
            )

        def dense_loss(q, k, v):
            return jnp.sum(dense_attention(q, k, v, causal=True, segment_ids=seg) ** 2)

        g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
        g_dense = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
        for name, a, b_ in zip("qkv", g_ring, g_dense):
            assert float(jnp.max(jnp.abs(a - b_))) < 2e-4, f"d{name} diverges"

    def test_banded_ring_window(self):
        """Sliding-window attention ACROSS the ring: rotation stops once
        the circulating block is beyond every local row's window, so per-
        device ICI traffic is O(window) — and the result still matches
        the full dense banded reference, windows crossing shard
        boundaries included. Composes with packed segments."""
        import numpy as np

        import jax.numpy as jnp
        from jax.sharding import Mesh

        from tpu_operator.workloads.ringattention import (
            _ring_hops,
            ring_attention,
        )

        # the hop bound itself: 8 shards of 8 rows, window 12 -> a row
        # reaches at most ceil((12-1)/8)+1 = 3 blocks back
        assert _ring_hops(8, 8, 12) == 3
        assert _ring_hops(8, 8, 64) == 8  # window >= S degenerates to full
        assert _ring_hops(8, 8, None) == 8
        assert _ring_hops(8, 8, 1) == 1  # self-attention only

        mesh = Mesh(np.array(jax.devices()[:8]), ("sp",))
        b, s, h, d, window = 1, 64, 2, 8, 12
        keys = jax.random.split(jax.random.PRNGKey(23), 3)
        q, k, v = (jax.random.normal(kk, (b, s, h, d), dtype=jnp.float32) for kk in keys)
        pos = jnp.arange(s)
        band = (pos[:, None] >= pos[None, :]) & (pos[:, None] - pos[None, :] < window)

        def dense_ref(extra_mask=None):
            mask = band if extra_mask is None else band & extra_mask
            sc = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(float(d))
            sc = jnp.where(mask[None, None], sc, -jnp.inf)
            return jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(sc, axis=-1), v)

        got = ring_attention(q, k, v, mesh, window=window)
        assert float(jnp.max(jnp.abs(got - dense_ref()))) < 2e-4

        seg = jnp.where(jnp.arange(s) < 29, 0, 1)[None].astype(jnp.int32)
        got = ring_attention(q, k, v, mesh, window=window, segment_ids=seg)
        want = dense_ref(seg[0][:, None] == seg[0][None, :])
        assert float(jnp.max(jnp.abs(got - want))) < 2e-4

        # the banded ring is a TRAINING path: gradients through the
        # truncated rotation + window mask must match dense
        def ring_loss(qq, kk, vv):
            return jnp.sum(ring_attention(qq, kk, vv, mesh, window=window) ** 2)

        def dense_loss(qq, kk, vv):
            sc = jnp.einsum("bqhd,bkhd->bhqk", qq, kk) / np.sqrt(float(d))
            sc = jnp.where(band[None, None], sc, -jnp.inf)
            out = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(sc, axis=-1), vv)
            return jnp.sum(out ** 2)

        g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
        g_dense = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
        for name, a, b_ in zip("qkv", g_ring, g_dense):
            assert float(jnp.max(jnp.abs(a - b_))) < 2e-4, f"d{name} diverges"

        with pytest.raises(ValueError, match="causal"):
            ring_attention(q, k, v, mesh, causal=False, window=window)
        with pytest.raises(ValueError, match="dense"):
            ring_attention(q, k, v, mesh, local_impl="flash", window=window)

    def test_gqa_through_the_ring(self):
        """Grouped-query attention across the ring: only the H_kv heads
        circulate (group-factor less ICI per rotation), each q group
        pairs with its KV head — forward and gradients vs dense over
        repeated KV, composing with window + segments."""
        import numpy as np

        import jax.numpy as jnp
        from jax.sharding import Mesh

        from tpu_operator.workloads.ringattention import (
            dense_attention,
            ring_attention,
        )

        mesh = Mesh(np.array(jax.devices()[:8]), ("sp",))
        b, s, h, hkv, d = 1, 64, 4, 2, 8
        keys = jax.random.split(jax.random.PRNGKey(29), 3)
        q = jax.random.normal(keys[0], (b, s, h, d), dtype=jnp.float32)
        k = jax.random.normal(keys[1], (b, s, hkv, d), dtype=jnp.float32)
        v = jax.random.normal(keys[2], (b, s, hkv, d), dtype=jnp.float32)

        def rep(x):
            return jnp.repeat(x, h // hkv, axis=2)

        got = ring_attention(q, k, v, mesh, causal=True)
        want = dense_attention(q, rep(k), rep(v), causal=True)
        assert float(jnp.max(jnp.abs(got - want))) < 2e-4

        g_ring = jax.grad(
            lambda qq, kk, vv: jnp.sum(ring_attention(qq, kk, vv, mesh) ** 2),
            argnums=(0, 1, 2),
        )(q, k, v)
        g_dense = jax.grad(
            lambda qq, kk, vv: jnp.sum(
                dense_attention(qq, rep(kk), rep(vv), causal=True) ** 2
            ),
            argnums=(0, 1, 2),
        )(q, k, v)
        for name, a, b_ in zip("qkv", g_ring, g_dense):
            assert a.shape == b_.shape
            assert float(jnp.max(jnp.abs(a - b_))) < 2e-4, f"d{name} diverges"

        # GQA + banded window + packed segments in one call
        seg = jnp.where(jnp.arange(s) < 29, 0, 1)[None].astype(jnp.int32)
        got = ring_attention(q, k, v, mesh, window=12, segment_ids=seg)
        pos = jnp.arange(s)
        mask = (
            (pos[:, None] >= pos[None, :])
            & (pos[:, None] - pos[None, :] < 12)
            & (seg[0][:, None] == seg[0][None, :])
        )
        sc = jnp.einsum("bqhd,bkhd->bhqk", q, rep(k)) / np.sqrt(float(d))
        sc = jnp.where(mask[None, None], sc, -jnp.inf)
        want = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(sc, axis=-1), rep(v))
        assert float(jnp.max(jnp.abs(got - want))) < 2e-4

        with pytest.raises(ValueError, match="multiple of kv heads"):
            k3 = jnp.zeros((b, s, 3, d), jnp.float32)
            ring_attention(q, k3, k3, mesh)
        with pytest.raises(ValueError, match="must match"):
            ring_attention(q, k, rep(v), mesh)

    def test_segment_ids_reject_flash_local(self):
        import numpy as np

        import jax.numpy as jnp
        from jax.sharding import Mesh

        from tpu_operator.workloads.ringattention import ring_attention

        mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))
        x = jnp.zeros((1, 64, 2, 8), jnp.float32)
        with pytest.raises(ValueError, match="dense"):
            ring_attention(
                x, x, x, mesh, local_impl="flash",
                segment_ids=jnp.zeros((1, 64), jnp.int32),
            )


class TestPipelineParallel:
    def test_pipeline_matches_sequential_and_trains(self):
        from tpu_operator.workloads.pipeline import make_pp_mesh, run_pipeline_check

        mesh = make_pp_mesh(jax.devices()[:4], stages=4)
        report = run_pipeline_check(mesh=mesh)
        assert report["ok"]
        assert report["max_abs_err_vs_sequential"] < 1e-4
        assert report["losses"][-1] < report["losses"][0]

    def test_pipeline_of_transformer_blocks(self):
        """The burn-in's transformer block pipelines unchanged: each stage
        holds one block's weights, activations ride ppermute."""
        import jax.numpy as jnp

        from tpu_operator.workloads.burnin import BurninConfig, _block, init_params
        from tpu_operator.workloads.pipeline import make_pp_mesh, pipeline_apply

        stages = 2
        mesh = make_pp_mesh(jax.devices()[:stages], stages=stages)
        cfg = BurninConfig(n_layers=1, d_model=64, n_heads=2, d_ff=128, seq_len=16, batch=2)
        per_stage = [init_params(jax.random.PRNGKey(s), cfg) for s in range(stages)]
        block_keys = [k for k in per_stage[0] if k.startswith("l0/")]
        stacked = {k: jnp.stack([p[k] for p in per_stage]) for k in block_keys}

        def stage_fn(p, x):
            return _block(p, 0, x, cfg)

        mb = jax.random.normal(
            jax.random.PRNGKey(9), (3, cfg.batch, cfg.seq_len, cfg.d_model), dtype=cfg.jdtype
        )
        out = pipeline_apply(stacked, mb, stage_fn=stage_fn, mesh=mesh)
        want = mb
        for s in range(stages):
            p = {k: stacked[k][s] for k in block_keys}
            want = jax.vmap(lambda x, p=p: _block(p, 0, x, cfg))(want)
        # bf16 activations of magnitude ~2 carry ~0.016 ulps; a few ulps of
        # accumulation-order noise between the pipelined and vmapped paths
        # is expected
        assert float(jnp.max(jnp.abs(out.astype(jnp.float32) - want.astype(jnp.float32)))) < 0.15


class TestExpertParallelBurnin:
    def test_moe_step_runs_and_converges_on_4d_mesh(self):
        """Full parallelism cross-product: dp x sp (ring attention) x tp x
        ep (GShard MoE dispatch) in one train step."""
        from tpu_operator.workloads.burnin import BurninConfig, make_mesh_4d, run_burnin

        mesh = make_mesh_4d(data=1, sp=2, model=2, ep=2)
        cfg = BurninConfig(
            sequence_parallel=True, moe_experts=4, n_layers=1, seq_len=64, batch=8
        )
        report = run_burnin(mesh=mesh, steps=3, cfg=cfg)
        assert report["ok"]
        assert report["mesh"] == {"data": 1, "sp": 2, "model": 2, "ep": 2}

    def test_expert_weights_sharded_over_ep(self):
        from tpu_operator.workloads.burnin import (
            BurninConfig,
            build_train_step,
            make_mesh_4d,
        )

        mesh = make_mesh_4d(data=1, sp=2, model=2, ep=2)
        cfg = BurninConfig(moe_experts=4, sequence_parallel=True, n_layers=1,
                           seq_len=32, batch=4)
        _, params, _ = build_train_step(mesh, cfg)
        w1 = params["l0/moe_w1"]
        assert w1.shape == (4, cfg.d_model, cfg.d_ff)
        # each ep shard holds 2 of the 4 experts
        assert w1.sharding.shard_shape(w1.shape)[0] == 2

    def test_moe_requires_ep_axis(self):
        import pytest

        from tpu_operator.workloads.burnin import BurninConfig, build_train_step, make_mesh

        with pytest.raises(ValueError, match="ep"):
            build_train_step(make_mesh(data=4, model=2), BurninConfig(moe_experts=4))

    def test_moe_dropped_tokens_pass_through_residual(self):
        """With capacity 1 and many tokens per expert, the step must still
        run and produce finite loss (dropped tokens ride the residual)."""
        from tpu_operator.workloads.burnin import BurninConfig, make_mesh_4d, run_burnin

        mesh = make_mesh_4d(data=1, sp=2, model=2, ep=2)
        cfg = BurninConfig(
            sequence_parallel=True, moe_experts=2, moe_capacity_factor=0.01,
            n_layers=1, seq_len=32, batch=4,
        )
        report = run_burnin(mesh=mesh, steps=2, cfg=cfg)
        assert report["ok"]


class TestSequenceParallelBurnin:
    def test_sp_step_runs_and_converges(self):
        from tpu_operator.workloads.burnin import BurninConfig, make_mesh_3d, run_burnin

        mesh = make_mesh_3d(data=2, sp=2, model=2)
        cfg = BurninConfig(sequence_parallel=True, n_layers=1, seq_len=64, batch=8)
        report = run_burnin(mesh=mesh, steps=3, cfg=cfg)
        assert report["ok"] and report["mesh"] == {"data": 2, "sp": 2, "model": 2}

    def test_sp_matches_dense_numerics(self):
        from tpu_operator.workloads.burnin import (
            BurninConfig,
            build_train_step,
            make_mesh,
            make_mesh_3d,
        )

        dense_cfg = BurninConfig(sequence_parallel=False, n_layers=1, seq_len=64, batch=8)
        sp_cfg = BurninConfig(sequence_parallel=True, n_layers=1, seq_len=64, batch=8)
        step_d, params_d, batch_d = build_train_step(make_mesh(data=2, model=4), dense_cfg)
        _, loss_d = step_d(params_d, batch_d)
        step_s, params_s, batch_s = build_train_step(make_mesh_3d(data=2, sp=2, model=2), sp_cfg)
        _, loss_s = step_s(params_s, batch_s)
        assert abs(float(loss_d) - float(loss_s)) < 1e-2

    def test_sp_requires_sp_axis(self):
        from tpu_operator.workloads.burnin import BurninConfig, build_train_step, make_mesh

        with pytest.raises(ValueError, match="sp"):
            build_train_step(make_mesh(), BurninConfig(sequence_parallel=True))


def _dense_window_reference(q, k, v, window):
    """Banded causal softmax over repeated-KV — the reference for the
    sliding-window (and windowed-GQA) tests."""
    import jax.numpy as jnp

    s, d = q.shape[1], q.shape[-1]
    if k.shape[2] != q.shape[2]:
        reps = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, reps, axis=2)
        v = jnp.repeat(v, reps, axis=2)
    scale = 1.0 / np.sqrt(d)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    q_pos = jnp.arange(s)[:, None]
    k_pos = jnp.arange(s)[None, :]
    keep = (q_pos >= k_pos) & (q_pos - k_pos < window)
    probs = jax.nn.softmax(jnp.where(keep, scores, -jnp.inf), axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


class TestFlashAttention:
    def test_matches_dense_causal_and_full(self):
        from tpu_operator.workloads.flashattention import run_flash_attention_check

        for causal in (True, False):
            report = run_flash_attention_check(
                seq_len=256, block_q=64, block_k=64, causal=causal
            )
            assert report["ok"] and report["max_abs_err"] < 2e-2

    def test_gradients_match_dense(self):
        """The custom VJP (FlashAttention-2 backward) must agree with
        autodiff through dense attention for dq, dk, and dv."""
        import jax.numpy as jnp

        from tpu_operator.workloads.flashattention import flash_attention
        from tpu_operator.workloads.ringattention import dense_attention

        keys = jax.random.split(jax.random.PRNGKey(7), 4)
        shape = (1, 256, 2, 64)
        q, k, v = (jax.random.normal(kk, shape, dtype=jnp.float32) for kk in keys[:3])
        w = jax.random.normal(keys[3], shape, dtype=jnp.float32)

        def loss(attn):
            return lambda q, k, v: jnp.sum(attn(q, k, v) * w)

        flash_grads = jax.grad(
            loss(lambda q, k, v: flash_attention(q, k, v, block_q=64, block_k=64)),
            argnums=(0, 1, 2),
        )(q, k, v)
        dense_grads = jax.grad(
            loss(lambda q, k, v: dense_attention(q, k, v, causal=True)),
            argnums=(0, 1, 2),
        )(q, k, v)
        for name, got, want in zip("qkv", flash_grads, dense_grads):
            err = float(jnp.max(jnp.abs(got - want)))
            assert err < 1e-4, f"d{name} diverges: {err}"

    def test_sliding_window(self):
        """window=W must match dense attention with a banded causal mask
        (0 <= q-k < W), forward and gradients, and reject non-causal use."""
        import jax.numpy as jnp

        from tpu_operator.workloads.flashattention import flash_attention

        keys = jax.random.split(jax.random.PRNGKey(5), 4)
        b, s, h, d, W = 1, 256, 2, 64, 96
        q, k, v = (jax.random.normal(kk, (b, s, h, d), dtype=jnp.float32) for kk in keys[:3])
        w = jax.random.normal(keys[3], (b, s, h, d), dtype=jnp.float32)

        def dense_window(q, k, v):
            return _dense_window_reference(q, k, v, W)

        got = flash_attention(q, k, v, block_q=64, block_k=64, window=W)
        want = dense_window(q, k, v)
        assert float(jnp.max(jnp.abs(got - want))) < 1e-4

        flash_grads = jax.grad(
            lambda q, k, v: jnp.sum(
                flash_attention(q, k, v, block_q=64, block_k=64, window=W) * w
            ),
            argnums=(0, 1, 2),
        )(q, k, v)
        dense_grads = jax.grad(
            lambda q, k, v: jnp.sum(dense_window(q, k, v) * w), argnums=(0, 1, 2)
        )(q, k, v)
        for name, a, b_ in zip("qkv", flash_grads, dense_grads):
            assert float(jnp.max(jnp.abs(a - b_))) < 1e-4, f"d{name} diverges"

        with pytest.raises(ValueError, match="causal"):
            flash_attention(q, k, v, causal=False, window=W, block_q=64, block_k=64)

    def test_sliding_window_edges(self):
        """A window >= seq_len equals plain causal attention; a
        block-aligned window is exact too (band-grid edge cases: negative
        band starts, clamped loads, top-of-range skips)."""
        import jax.numpy as jnp

        from tpu_operator.workloads.flashattention import flash_attention
        from tpu_operator.workloads.ringattention import dense_attention

        keys = jax.random.split(jax.random.PRNGKey(6), 3)
        s = 256
        q, k, v = (
            jax.random.normal(kk, (1, s, 2, 64), dtype=jnp.float32) for kk in keys
        )
        full = dense_attention(q, k, v, causal=True)
        for W in (1000, 256, 64):
            got = flash_attention(q, k, v, block_q=64, block_k=64, window=W)
            want = _dense_window_reference(q, k, v, W)
            assert float(jnp.max(jnp.abs(got - want))) < 1e-4, W
            if W >= s:  # window covering the sequence equals plain causal
                assert float(jnp.max(jnp.abs(got - full))) < 1e-4, W

    def test_sliding_window_mixed_block_sizes(self):
        """The production default uses block_q != block_k; the band-width
        formulas and the dkv base phase ((kj·BK) % BQ != 0) are
        asymmetric, so both orientations must be exact — forward and
        gradients."""
        import jax.numpy as jnp

        from tpu_operator.workloads.flashattention import flash_attention

        keys = jax.random.split(jax.random.PRNGKey(9), 4)
        b, s, h, d, W = 1, 512, 2, 64, 160
        q, k, v = (jax.random.normal(kk, (b, s, h, d), dtype=jnp.float32) for kk in keys[:3])
        w = jax.random.normal(keys[3], (b, s, h, d), dtype=jnp.float32)
        want = _dense_window_reference(q, k, v, W)
        want_grads = jax.grad(
            lambda q, k, v: jnp.sum(_dense_window_reference(q, k, v, W) * w),
            argnums=(0, 1, 2),
        )(q, k, v)
        for bq, bk in ((64, 128), (128, 64)):
            got = flash_attention(q, k, v, block_q=bq, block_k=bk, window=W)
            assert float(jnp.max(jnp.abs(got - want))) < 1e-4, (bq, bk)
            got_grads = jax.grad(
                lambda q, k, v, bq=bq, bk=bk: jnp.sum(
                    flash_attention(q, k, v, block_q=bq, block_k=bk, window=W) * w
                ),
                argnums=(0, 1, 2),
            )(q, k, v)
            for name, a, b_ in zip("qkv", got_grads, want_grads):
                assert float(jnp.max(jnp.abs(a - b_))) < 1e-4, (bq, bk, name)

    def test_window_with_gqa(self):
        """Window and GQA interact through the banded k_spec index map and
        the dK/dV (group, q block) decomposition — exactness of the
        combined path, forward and gradients."""
        import jax.numpy as jnp

        from tpu_operator.workloads.flashattention import flash_attention

        keys = jax.random.split(jax.random.PRNGKey(8), 4)
        b, s, h, hkv, d, W = 1, 256, 4, 2, 64, 96
        q = jax.random.normal(keys[0], (b, s, h, d), dtype=jnp.float32)
        k = jax.random.normal(keys[1], (b, s, hkv, d), dtype=jnp.float32)
        v = jax.random.normal(keys[2], (b, s, hkv, d), dtype=jnp.float32)
        w = jax.random.normal(keys[3], (b, s, h, d), dtype=jnp.float32)
        got = flash_attention(q, k, v, block_q=64, block_k=64, window=W)
        want = _dense_window_reference(q, k, v, W)
        assert float(jnp.max(jnp.abs(got - want))) < 1e-4

        flash_grads = jax.grad(
            lambda q, k, v: jnp.sum(
                flash_attention(q, k, v, block_q=64, block_k=64, window=W) * w
            ),
            argnums=(0, 1, 2),
        )(q, k, v)
        dense_grads = jax.grad(
            lambda q, k, v: jnp.sum(_dense_window_reference(q, k, v, W) * w),
            argnums=(0, 1, 2),
        )(q, k, v)
        for name, a, b_ in zip("qkv", flash_grads, dense_grads):
            assert float(jnp.max(jnp.abs(a - b_))) < 1e-4, f"d{name} diverges"

    def test_rejects_mismatched_kv_seq(self):
        import jax.numpy as jnp

        from tpu_operator.workloads.flashattention import flash_attention

        q = jnp.zeros((1, 512, 2, 64), dtype=jnp.float32)
        kv = jnp.zeros((1, 256, 2, 64), dtype=jnp.float32)
        with pytest.raises(ValueError, match="must equal q's"):
            flash_attention(q, kv, kv, block_q=64, block_k=64)

    def test_grouped_query_attention(self):
        """GQA: 4 query heads sharing 2 KV heads must match dense over
        repeated KV, forward and gradients (the dK/dV kernel accumulates
        over every (group member, q block) pair)."""
        import jax.numpy as jnp

        from tpu_operator.workloads.flashattention import flash_attention
        from tpu_operator.workloads.ringattention import dense_attention

        keys = jax.random.split(jax.random.PRNGKey(7), 4)
        b, s, h, hkv, d = 1, 256, 4, 2, 64
        q = jax.random.normal(keys[0], (b, s, h, d), dtype=jnp.float32)
        k = jax.random.normal(keys[1], (b, s, hkv, d), dtype=jnp.float32)
        v = jax.random.normal(keys[2], (b, s, hkv, d), dtype=jnp.float32)
        w = jax.random.normal(keys[3], (b, s, h, d), dtype=jnp.float32)

        def rep(x):
            return jnp.repeat(x, h // hkv, axis=2)

        got = flash_attention(q, k, v, block_q=64, block_k=64)
        want = dense_attention(q, rep(k), rep(v), causal=True)
        assert float(jnp.max(jnp.abs(got - want))) < 1e-4

        flash_grads = jax.grad(
            lambda q, k, v: jnp.sum(flash_attention(q, k, v, block_q=64, block_k=64) * w),
            argnums=(0, 1, 2),
        )(q, k, v)
        dense_grads = jax.grad(
            lambda q, k, v: jnp.sum(dense_attention(q, rep(k), rep(v), causal=True) * w),
            argnums=(0, 1, 2),
        )(q, k, v)
        for name, a, b_ in zip("qkv", flash_grads, dense_grads):
            assert a.shape == b_.shape
            assert float(jnp.max(jnp.abs(a - b_))) < 1e-4, f"d{name} diverges"

        # 3 kv heads do not divide 4 q heads
        k3 = jax.random.normal(keys[1], (b, s, 3, d), dtype=jnp.float32)
        with pytest.raises(ValueError, match="multiple of kv heads"):
            flash_attention(q, k3, k3, block_q=64, block_k=64)
        # a v whose heads differ from k's would silently read wrong rows
        with pytest.raises(ValueError, match="must match"):
            flash_attention(q, k, rep(v), block_q=64, block_k=64)

    def test_uneven_blocks(self):
        """block_q > block_k puts fully-masked rows on diagonal blocks —
        the -inf guards must keep them finite."""
        from tpu_operator.workloads.flashattention import run_flash_attention_check

        report = run_flash_attention_check(seq_len=256, block_q=128, block_k=64)
        assert report["ok"]

    def test_segment_ids_match_dense(self):
        """Packed sequences: attention stays within segments, forward and
        gradients, on causal AND full attention, with per-batch packing
        layouts (boundaries mid-block)."""
        import jax.numpy as jnp

        from tpu_operator.workloads.flashattention import flash_attention
        from tpu_operator.workloads.ringattention import dense_attention

        keys = jax.random.split(jax.random.PRNGKey(11), 4)
        b, s, h, d = 2, 256, 2, 64
        q, k, v = (jax.random.normal(kk, (b, s, h, d), dtype=jnp.float32) for kk in keys[:3])
        w = jax.random.normal(keys[3], (b, s, h, d), dtype=jnp.float32)
        # two different packings, boundaries NOT on block edges
        seg = jnp.stack(
            [
                jnp.concatenate([jnp.zeros(100), jnp.ones(56), jnp.full(100, 2)]),
                jnp.concatenate([jnp.zeros(37), jnp.ones(219)]),
            ]
        ).astype(jnp.int32)
        for causal in (True, False):
            got = flash_attention(
                q, k, v, causal=causal, block_q=64, block_k=64, segment_ids=seg
            )
            want = dense_attention(q, k, v, causal=causal, segment_ids=seg)
            err = float(jnp.max(jnp.abs(got - want)))
            assert err < 1e-4, f"causal={causal}: {err}"

        flash_grads = jax.grad(
            lambda q, k, v: jnp.sum(
                flash_attention(q, k, v, block_q=64, block_k=64, segment_ids=seg) * w
            ),
            argnums=(0, 1, 2),
        )(q, k, v)
        dense_grads = jax.grad(
            lambda q, k, v: jnp.sum(
                dense_attention(q, k, v, causal=True, segment_ids=seg) * w
            ),
            argnums=(0, 1, 2),
        )(q, k, v)
        for name, a, b_ in zip("qkv", flash_grads, dense_grads):
            assert float(jnp.max(jnp.abs(a - b_))) < 1e-4, f"d{name} diverges"

    def test_segment_ids_compose_with_gqa_and_window(self):
        """The three variants stack: GQA heads + sliding window + packed
        segments in one call must equal the dense reference with the
        intersected mask."""
        import jax.numpy as jnp

        from tpu_operator.workloads.flashattention import flash_attention
        from tpu_operator.workloads.ringattention import dense_attention

        keys = jax.random.split(jax.random.PRNGKey(13), 3)
        b, s, h, hkv, d, window = 1, 256, 4, 2, 64, 96
        q = jax.random.normal(keys[0], (b, s, h, d), dtype=jnp.float32)
        k = jax.random.normal(keys[1], (b, s, hkv, d), dtype=jnp.float32)
        v = jax.random.normal(keys[2], (b, s, hkv, d), dtype=jnp.float32)
        seg = jnp.concatenate([jnp.zeros(129), jnp.ones(127)]).astype(jnp.int32)[None]

        def rep(x):
            return jnp.repeat(x, h // hkv, axis=2)

        got = flash_attention(
            q, k, v, block_q=64, block_k=64, window=window, segment_ids=seg
        )
        # dense reference: causal + window band + segment mask
        scores_mask = (
            (jnp.arange(s)[:, None] >= jnp.arange(s)[None, :])
            & (jnp.arange(s)[:, None] - jnp.arange(s)[None, :] < window)
            & (seg[0][:, None] == seg[0][None, :])
        )
        scale = 1.0 / jnp.sqrt(jnp.float32(d))
        scores = (
            jnp.einsum("bqhd,bkhd->bhqk", q, rep(k)) * scale
        )
        scores = jnp.where(scores_mask[None, None], scores, -jnp.inf)
        want = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(scores, axis=-1), rep(v))
        assert float(jnp.max(jnp.abs(got - want))) < 1e-4

    def test_unequal_length_causal_lse(self):
        """The ring's forward-only entry point allows q longer than k/v.
        That shape must NEVER take the flattened-triangle walk (whose
        finalize condition is unreachable for q rows past the k range —
        their output blocks would stay unwritten garbage); the guard
        keeps it on the rectangular path."""
        import numpy as np

        import jax.numpy as jnp

        from tpu_operator.workloads.flashattention import flash_attention_with_lse

        keys = jax.random.split(jax.random.PRNGKey(2), 3)
        q = jax.random.normal(keys[0], (1, 512, 2, 64), dtype=jnp.float32)
        k = jax.random.normal(keys[1], (1, 256, 2, 64), dtype=jnp.float32)
        v = jax.random.normal(keys[2], (1, 256, 2, 64), dtype=jnp.float32)
        out, _ = flash_attention_with_lse(q, k, v, causal=True, block_q=256, block_k=256)
        scale = 1 / np.sqrt(64.0)
        sc = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        mask = jnp.arange(512)[:, None] >= jnp.arange(256)[None, :]
        sc = jnp.where(mask[None, None], sc, -jnp.inf)
        want = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(sc, axis=-1), v)
        assert float(jnp.max(jnp.abs(out - want))) < 1e-4

    def test_segment_ids_validation(self):
        import jax.numpy as jnp

        from tpu_operator.workloads.flashattention import flash_attention

        q = jnp.zeros((1, 128, 2, 64), dtype=jnp.bfloat16)
        with pytest.raises(ValueError, match="segment_ids must be"):
            flash_attention(q, q, q, block_q=64, block_k=64,
                            segment_ids=jnp.zeros((1, 64), jnp.int32))
        with pytest.raises(ValueError, match="integral"):
            flash_attention(q, q, q, block_q=64, block_k=64,
                            segment_ids=jnp.zeros((1, 128), jnp.float32))

    def test_burnin_trains_through_flash_kernel(self):
        """The burn-in transformer with use_flash_attention trains on the
        sharded mesh (pallas kernel under shard_map, custom VJP through
        jax.grad) and agrees with the dense path's loss."""
        from tpu_operator.workloads.burnin import BurninConfig, make_mesh, run_burnin

        kwargs = dict(d_model=128, n_heads=2, d_ff=256, seq_len=128, batch=8, n_layers=1)
        mesh = make_mesh(data=4, model=2)
        flash = run_burnin(mesh=mesh, cfg=BurninConfig(use_flash_attention=True, **kwargs))
        dense = run_burnin(mesh=mesh, cfg=BurninConfig(**kwargs))
        assert flash["ok"] and dense["ok"]
        assert abs(flash["losses"][0] - dense["losses"][0]) < 2e-2

    def test_burnin_trains_packed_sequences(self):
        """Packed-sequence training end to end: the burn-in transformer
        with packed_segments runs the kernel's segment_ids path under
        shard_map and trains to a finite, decreasing-ish loss; its first
        loss DIFFERS from unpacked flash (the mask really changed)."""
        from tpu_operator.workloads.burnin import BurninConfig, make_mesh, run_burnin

        kwargs = dict(d_model=128, n_heads=2, d_ff=256, seq_len=128, batch=8, n_layers=1)
        mesh = make_mesh(data=4, model=2)
        packed = run_burnin(
            mesh=mesh,
            cfg=BurninConfig(use_flash_attention=True, packed_segments=4, **kwargs),
        )
        plain = run_burnin(mesh=mesh, cfg=BurninConfig(use_flash_attention=True, **kwargs))
        assert packed["ok"]
        assert abs(packed["losses"][0] - plain["losses"][0]) > 1e-5

    def test_burnin_trains_packed_through_the_ring(self):
        """Packed training on the sequence-parallel path: documents span
        sp shards, ids circulate the ring, and the train step runs on
        the 3-D mesh — the same configuration the multichip driver gate
        now exercises."""
        from tpu_operator.workloads.burnin import (
            BurninConfig,
            make_mesh_3d,
            run_burnin,
        )

        mesh = make_mesh_3d(data=2, sp=2, model=2)
        report = run_burnin(
            mesh=mesh,
            cfg=BurninConfig(
                d_model=64, n_heads=2, d_ff=128, seq_len=64, batch=4,
                # 3 docs over 2 shards of 32: boundaries at 22 and 43,
                # both MID-shard, so documents genuinely span chips
                n_layers=1, sequence_parallel=True, packed_segments=3,
            ),
        )
        assert report["ok"]

    def test_burnin_trains_gqa(self):
        """Grouped-query attention in the training payload: the fused
        projection shrinks to q + 2*kv_heads*head_dim, and all three
        attention paths (dense, flash kernel, ring) train the GQA shape
        on their meshes."""
        from tpu_operator.workloads.burnin import (
            BurninConfig,
            build_train_step,
            make_mesh,
            make_mesh_3d,
            run_burnin,
        )

        kwargs = dict(d_model=128, n_heads=4, d_ff=256, seq_len=128, batch=8, n_layers=1)
        mesh = make_mesh(data=4, model=2)
        for path in ({}, {"use_flash_attention": True}):
            report = run_burnin(mesh=mesh, cfg=BurninConfig(kv_heads=2, **kwargs, **path))
            assert report["ok"], path
        ring = run_burnin(
            mesh=make_mesh_3d(data=2, sp=2, model=2),
            cfg=BurninConfig(
                d_model=64, n_heads=4, d_ff=128, seq_len=64, batch=4,
                n_layers=1, sequence_parallel=True, kv_heads=2,
            ),
        )
        assert ring["ok"]
        # 3 kv heads do not divide 4 q heads
        with pytest.raises(ValueError, match="multiple of kv_heads"):
            build_train_step(mesh, BurninConfig(kv_heads=3, **kwargs))
        # kv heads must shard over 'model' like q heads — replicating
        # them would silently mispair GQA groups across shards
        with pytest.raises(ValueError, match="kv_heads"):
            build_train_step(
                make_mesh_3d(data=2, sp=2, model=2),
                BurninConfig(
                    d_model=64, n_heads=2, d_ff=128, seq_len=64, batch=4,
                    n_layers=1, sequence_parallel=True, kv_heads=1,
                ),
            )
        # an indivisible sequence gets the same clean rejection instead
        # of a raw shard_map trace error
        with pytest.raises(ValueError, match="seq_len"):
            build_train_step(
                make_mesh_3d(data=2, sp=2, model=2),
                BurninConfig(
                    d_model=64, n_heads=2, d_ff=128, seq_len=33, batch=4,
                    n_layers=1, sequence_parallel=True,
                ),
            )

    def test_burnin_packed_requires_flash(self):
        from tpu_operator.workloads.burnin import BurninConfig, build_train_step, make_mesh

        with pytest.raises(ValueError, match="packed_segments"):
            build_train_step(
                make_mesh(data=4, model=2),
                BurninConfig(seq_len=128, packed_segments=4),
            )

    def test_burnin_flash_config_validation(self):
        from tpu_operator.workloads.burnin import (
            BurninConfig,
            build_train_step,
            make_mesh,
            make_mesh_3d,
        )

        # heads must divide the model axis (dense path would accept this)
        with pytest.raises(ValueError, match="n_heads"):
            build_train_step(
                make_mesh(data=2, model=4),
                BurninConfig(n_heads=2, seq_len=128, use_flash_attention=True),
            )
        # flash and ring are mutually exclusive attention paths
        with pytest.raises(ValueError, match="separate attention"):
            build_train_step(
                make_mesh_3d(data=2, sp=2, model=2),
                BurninConfig(sequence_parallel=True, use_flash_attention=True),
            )

    def test_rejects_misaligned_seq(self):
        import jax.numpy as jnp

        from tpu_operator.workloads.flashattention import flash_attention

        q = jnp.zeros((1, 96, 2, 32), dtype=jnp.bfloat16)
        with pytest.raises(ValueError, match="divide"):
            flash_attention(q, q, q, block_q=64, block_k=64)


class TestMatmulBench:
    def test_int8_probe_reports_rate(self):
        from tpu_operator.workloads.matmul_bench import int8_matmul_tops

        report = int8_matmul_tops(size=128, iters=2, reps=2)
        assert report["tops"] > 0
        assert report["size"] == 128


class TestMultiprocessDistributed:
    """Live multi-process jax.distributed over localhost TCP — the env the
    slice manager renders, executed for real (VERDICT r02 item 2; reference
    executes its cross-node workload, validator/main.go:1232-1308)."""

    def test_gang_env_drives_real_two_process_bringup(self):
        from tpu_operator import consts
        from tpu_operator.agents.slice_manager_agent import SliceManagerAgent
        from tpu_operator.kube.fake import FakeClient
        from tpu_operator.kube.sim import make_tpu_node
        from tpu_operator.workloads.multiproc import run_multiprocess_check

        client = FakeClient()
        for i in range(2):
            node = make_tpu_node(
                f"v5e-{i}", "tpu-v5-lite-podslice", "2x4", nodepool="pool-a"
            )
            node["metadata"]["labels"][consts.TPU_PRESENT_LABEL] = "true"
            client.create(node)
        agent = SliceManagerAgent(client, "tpu-operator")
        names = agent.reconcile_once()
        assert len(names) == 1
        gang_env = client.get("v1", "ConfigMap", f"{names[0]}-gang", "tpu-operator")[
            "data"
        ]
        # each worker process models one slice host with its 4 chips
        report = _run_gang_check(
            run_multiprocess_check,
            num_workers=int(gang_env["TPU_SLICE_HOSTS"]),
            devices_per_worker=int(gang_env["TPU_CHIPS_PER_HOST"]),
            gang_env=gang_env,
        )
        assert report["ok"] and report["psum_ok"]
        assert report["global_devices"] == 8
        assert report["ring_attention_max_err"] < 1e-4
        # every worker observed the same global topology
        assert {w["num_processes"] for w in report["workers"]} == {2}

    def test_two_slice_world_from_rendered_gang_envs(self):
        """BASELINE config 5 shape, executed live: two slices (two pools)
        rendered by the multi-slice manager, one jax.distributed world
        spanning both over the DCN coordinator — psum and ring attention
        cross the slice boundary for real."""
        from tpu_operator import consts
        from tpu_operator.agents.slice_manager_agent import SliceManagerAgent
        from tpu_operator.kube.fake import FakeClient
        from tpu_operator.kube.sim import make_tpu_node
        from tpu_operator.workloads.multiproc import run_multislice_check

        client = FakeClient()
        for pool in ("pool-a", "pool-b"):
            for i in range(2):
                node = make_tpu_node(
                    f"{pool}-{i}", "tpu-v5-lite-podslice", "2x4", nodepool=pool
                )
                node["metadata"]["labels"][consts.TPU_PRESENT_LABEL] = "true"
                client.create(node)
        agent = SliceManagerAgent(
            client, "tpu-operator", multi_slice=True, coordinator_port=8476
        )
        names = agent.reconcile_once()
        assert len(names) == 2
        gang_envs = [
            client.get("v1", "ConfigMap", f"{name}-gang", "tpu-operator")["data"]
            for name in names
        ]
        assert {env["MEGASCALE_SLICE_ID"] for env in gang_envs} == {"0", "1"}
        report = _run_gang_check(
            run_multislice_check,
            num_slices=2, devices_per_worker=2, gang_envs=gang_envs, timeout=120,
        )
        assert report["ok"] and report["psum_ok"]
        # 2 slices x 2 hosts x 2 devices: the world spans every slice
        assert report["global_devices"] == 8
        assert {w["num_processes"] for w in report["workers"]} == {4}
        assert {w["process_id"] for w in report["workers"]} == {0, 1, 2, 3}

    def test_multislice_env_coordinator_rewritten_to_loopback(self):
        """A multi-slice gang env carries MEGASCALE_COORDINATOR_ADDRESS
        (the DCN coordinator Service DNS), which config_from_env prefers
        over the hostname list — the launcher must point it at loopback
        too or every worker hangs resolving the Service name."""
        from tpu_operator import consts
        from tpu_operator.agents.slice_manager_agent import SliceManagerAgent
        from tpu_operator.kube.fake import FakeClient
        from tpu_operator.kube.sim import make_tpu_node
        from tpu_operator.workloads.multiproc import run_multiprocess_check

        client = FakeClient()
        for i in range(2):
            node = make_tpu_node(
                f"v5e-{i}", "tpu-v5-lite-podslice", "2x4", nodepool="pool-a"
            )
            node["metadata"]["labels"][consts.TPU_PRESENT_LABEL] = "true"
            client.create(node)
        agent = SliceManagerAgent(
            client, "tpu-operator", multi_slice=True, coordinator_port=8476
        )
        names = agent.reconcile_once()
        gang_env = client.get("v1", "ConfigMap", f"{names[0]}-gang", "tpu-operator")[
            "data"
        ]
        assert "MEGASCALE_COORDINATOR_ADDRESS" in gang_env
        report = _run_gang_check(
            run_multiprocess_check,
            num_workers=2, devices_per_worker=2, gang_env=gang_env, timeout=120,
        )
        assert report["ok"] and report["global_devices"] == 4

    def test_four_slice_two_host_world(self):
        """The slice-block process-id derivation past the 2x1 smoke: a
        4-slice x 2-host world (8 processes) where every process id
        0..7 must come out of slice_id * hosts_per_slice + worker_id —
        a collision or gap deadlocks initialize, so a green run proves
        the derivation for a non-trivial block layout."""
        from tpu_operator.workloads.multiproc import run_multislice_check

        report = _run_gang_check(
            run_multislice_check,
            num_slices=4, hosts_per_slice=2, devices_per_worker=1, timeout=240,
        )
        assert report["ok"] and report["psum_ok"]
        assert report["num_slices"] == 4
        assert report["global_devices"] == 8
        assert {w["num_processes"] for w in report["workers"]} == {8}
        assert {w["process_id"] for w in report["workers"]} == set(range(8))
        assert report["ring_attention_max_err"] < 1e-4

    def test_missing_worker_times_out_with_diagnosis(self):
        """One worker of the derived world never starts: initialize()
        blocks forever on every OTHER worker, so the launcher must turn
        the hang into a bounded, named failure — not an indefinite wedge
        (the failure mode ADVICE flagged for silent slice-id defaults)."""
        from tpu_operator.workloads.multiproc import (
            _free_port,
            _launch_workers,
            _localize_gang_env,
        )

        base = _localize_gang_env(
            {
                "TPU_WORKER_HOSTNAMES": "127.0.0.1,127.0.0.1",
                "MEGASCALE_COORDINATOR_ADDRESS": "127.0.0.1",
                "MEGASCALE_NUM_SLICES": "2",
                "MEGASCALE_SLICE_ID": "0",
            },
            _free_port(),
        )
        # the env derives a 4-process world (2 slices x 2 hosts); spawn
        # only slice 0's two workers
        envs = [dict(base, TPU_WORKER_ID=str(i)) for i in range(2)]
        with pytest.raises(RuntimeError, match="timeout") as excinfo:
            _launch_workers(envs, devices_per_worker=1, timeout=30)
        assert "never started" in str(excinfo.value)


def test_graft_entry_dryrun_3d():
    import __graft_entry__

    __graft_entry__.dryrun_multichip(8)


def test_graft_entry_dryrun_driver_invocation():
    """Reproduce the driver's exact invocation context: a fresh process with
    the ambient env (axon TPU platform registered, JAX_PLATFORMS=axon, no
    conftest CPU forcing, no pre-set host-device-count flag).

    r02 regression: the dryrun died on a transient libtpu fault because
    array creation touched the default (TPU) backend. The hermetic dryrun
    must pass regardless of TPU state and must initialize ONLY the cpu
    backend."""
    import os
    import subprocess
    import sys

    env = dict(os.environ)
    flags = env.get("XLA_FLAGS", "")
    env["XLA_FLAGS"] = " ".join(
        f for f in flags.split() if "xla_force_host_platform_device_count" not in f
    )
    code = (
        "import __graft_entry__ as g; g.dryrun_multichip(8)\n"
        "import jax._src.xla_bridge as xb\n"
        "assert sorted(xb._backends) == ['cpu'], sorted(xb._backends)\n"
    )
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, "-c", code],
        cwd=repo_root,
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert proc.returncode == 0, f"stdout={proc.stdout}\nstderr={proc.stderr}"
    assert "dryrun_multichip: mesh=" in proc.stdout
    assert "pp=8 stages" in proc.stdout
