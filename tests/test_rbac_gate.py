"""RBAC completeness gate: the SHIPPED ClusterRole must cover every
request the operator actually makes.

A real apiserver enforces RBAC, so a missing verb surfaces as 403s in
production — a failure mode the permissive in-memory fake could never
show. The reference catches this implicitly by running e2e on a live
cluster (tests/e2e/gpu_operator_test.go:104-170); here the fake
apiserver's enforcing mode (FakeApiServer(authorize=...)) replays the
same check against the chart's rendered ClusterRole while the full
install→Ready flow runs over the wire.
"""

import os
import time

import pytest
import yaml

from tpu_operator.api.clusterpolicy import (
    CLUSTER_POLICY_API_VERSION,
    CLUSTER_POLICY_KIND,
    new_cluster_policy,
)
from tpu_operator.controllers.clusterpolicy_controller import (
    ClusterPolicyReconciler,
    setup_with_manager,
)
from tpu_operator.kube.fake import FakeClient
from tpu_operator.kube.http_client import HttpClient
from tpu_operator.kube.httpserver import FakeApiServer, RbacAuthorizer
from tpu_operator.kube.manager import Manager
from tpu_operator.kube.sim import ClusterSim, make_tpu_node

NS = "tpu-operator"
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def shipped_rules() -> list:
    """The ClusterRole rules every install path ships (chart, tpuop-cfg
    render, kustomize — parity-tested elsewhere, so any one source is
    authoritative)."""
    from tpu_operator.chart import render_chart

    with open(os.path.join(REPO, "deploy", "values.yaml")) as f:
        objs = render_chart(yaml.safe_load(f))
    (role,) = [o for o in objs if o["kind"] == "ClusterRole"]
    return role["rules"]


def wait_for(fn, timeout=30.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return False


class TestRbacAuthorizer:
    def test_rule_matching(self):
        auth = RbacAuthorizer(
            [
                {"apiGroups": [""], "resources": ["pods"], "verbs": ["get", "list"]},
                {"apiGroups": ["apps"], "resources": ["*"], "verbs": ["*"]},
                {"apiGroups": [""], "resources": ["pods/eviction"], "verbs": ["create"]},
            ]
        )
        assert auth.allows("", "pods", "get")
        assert not auth.allows("", "pods", "delete")
        assert auth.allows("apps", "daemonsets", "patch")
        assert auth.allows("", "pods/eviction", "create")
        assert not auth.allows("", "pods/eviction", "delete")
        assert not auth.allows("", "secrets", "get")

    def test_subresource_wildcard(self):
        """kube's ResourceMatches accepts '*/subresource' — and does NOT
        support 'resource/*' (a rule written that way covers nothing)."""
        auth = RbacAuthorizer(
            [{"apiGroups": [""], "resources": ["*/eviction"], "verbs": ["create"]}]
        )
        assert auth.allows("", "pods/eviction", "create")
        assert not auth.allows("", "pods", "create")
        bogus = RbacAuthorizer(
            [{"apiGroups": [""], "resources": ["pods/*"], "verbs": ["create"]}]
        )
        assert not bogus.allows("", "pods/eviction", "create")


def state_rules(state_name: str) -> list:
    """Combined Role + ClusterRole rules one operand state ships for its
    agent's ServiceAccount (namespace scoping collapses — the operator
    is single-namespace, so the union is the agent's effective rules).
    Two same-named Role/ClusterRole objects in one state are rejected
    outright: on a real cluster only the last-applied one exists, so a
    permissive union here could pass a gate production would fail."""
    from tpu_operator.api import ClusterPolicy
    from tpu_operator.api.clusterpolicy import new_cluster_policy
    from tpu_operator.catalog import InfoCatalog
    from tpu_operator.states import new_cluster_policy_states

    cp = ClusterPolicy.from_unstructured(new_cluster_policy())
    catalog = InfoCatalog(cluster_policy=cp)
    states = {s.name: s for s in new_cluster_policy_states()}
    state = states[state_name]
    by_name: dict = {}
    for obj in state.renderer.render_objects(state.get_render_data(catalog)):
        if obj["kind"] in ("Role", "ClusterRole"):
            key = (obj["kind"], obj["metadata"]["name"])
            assert key not in by_name, (
                f"{state_name} renders duplicate {key} — same-named RBAC "
                "objects overwrite each other on a live cluster"
            )
            by_name[key] = obj["rules"]
    rules = []
    for obj_rules in by_name.values():
        rules.extend(obj_rules)
    return rules


# -- agent exercises --------------------------------------------------------
#
# Each function drives one operand agent's core loop over the wire and
# returns normally only on success. They are module-level (not test
# methods) because TWO gates replay them: the per-agent enforcement
# tests below, and TestStaticRuntimeConsistency, which re-runs them to
# prove the static RBAC analyzer's per-operand verb set covers
# everything the runtime actually sends.


def exercise_tfd(store, client, tmp_path, monkeypatch):
    from tpu_operator.agents.tfd_agent import TFDAgent

    (tmp_path / "dev").mkdir(exist_ok=True)
    monkeypatch.setenv("TPUINFO_SCAN_ROOT", str(tmp_path))
    store.create(make_tpu_node("tpu-0"))
    assert TFDAgent(client, "tpu-0").apply_once()


def exercise_node_discovery(store, client, tmp_path, monkeypatch):
    from tpu_operator.agents.node_discovery_agent import NodeDiscoveryAgent
    from tpu_operator.kube.sim import make_bare_node

    (tmp_path / "dev").mkdir(exist_ok=True)
    for i in range(4):
        (tmp_path / "dev" / f"accel{i}").touch()
    monkeypatch.setenv("TPUINFO_SCAN_ROOT", str(tmp_path))
    for var in ("TPU_TOPOLOGY", "TPU_ACCELERATOR_TYPE"):
        monkeypatch.delenv(var, raising=False)
    store.create(make_bare_node("bare-0"))
    assert NodeDiscoveryAgent(client, "bare-0").apply_once()


def exercise_slice_manager(store, client, tmp_path=None, monkeypatch=None):
    from tpu_operator import consts
    from tpu_operator.agents.slice_manager_agent import SliceManagerAgent

    for i in range(4):
        node = make_tpu_node(f"v5e-{i}", "tpu-v5-lite-podslice", "4x4")
        node["metadata"]["labels"][consts.TPU_PRESENT_LABEL] = "true"
        store.create(node)
    names = SliceManagerAgent(client, NS).reconcile_once()
    assert names, "no slice reconciled"


def exercise_device_plugin(store, client, tmp_path=None, monkeypatch=None):
    from tpu_operator.agents.device_plugin_agent import select_plugin_config
    from tpu_operator.kube.objects import new_object

    store.create(make_tpu_node("tpu-0"))
    store.create(
        new_object(
            "v1", "ConfigMap", "plugin-config", NS,
            data={"default": "sharing:\n  chips_per_container: 1\n"},
        )
    )
    cfg = select_plugin_config(client, "tpu-0", "plugin-config", NS, default="default")
    assert cfg == {"sharing": {"chips_per_container": 1}}


def exercise_validator_plugin(store, client, tmp_path=None, monkeypatch=None):
    from tpu_operator.validator.main import Context, validate_plugin

    store.create(make_tpu_node("tpu-0", chips=4))
    ctx = Context(client=client, node_name="tpu-0", retry_interval=0.01)
    report = validate_plugin(ctx)
    assert report["chips"] == 4


def exercise_node_status_exporter(store, client, tmp_path=None, monkeypatch=None):
    """The metrics payload's apiserver surface: the per-node context
    read that used to 403 under the (formerly empty) shipped rules."""
    store.create(make_tpu_node("tpu-0", chips=4))
    node = client.get("v1", "Node", "tpu-0")
    assert node["metadata"]["name"] == "tpu-0"


def run_health_agent(client, tmp_path, monkeypatch):
    """The agent's full publish surface: node get/update, nodes/status
    update (TPUHealthy condition), events create — a DEGRADED pass so
    the event path definitely fires."""
    from tpu_operator.agents.health_monitor_agent import HealthMonitorAgent

    (tmp_path / "dev").mkdir(exist_ok=True)
    monkeypatch.setenv("TPUINFO_SCAN_ROOT", str(tmp_path))
    agent = HealthMonitorAgent(
        client,
        "tpu-0",
        install_dir=str(tmp_path),
        socket_dir=str(tmp_path),
        health_dir=str(tmp_path / "health"),
        active_probes="off",
    )
    return agent.apply_once()


def exercise_health_monitor(store, client, tmp_path, monkeypatch):
    store.create(make_tpu_node("tpu-0", chips=4))
    assert run_health_agent(client, tmp_path, monkeypatch)


def exercise_autotuner(store, client, tmp_path, monkeypatch):
    """The agent's full apiserver surface: node get (election check),
    results-ConfigMap get + patch (an existing CM from another
    generation's sweep) — and a second pass proving the cache-hit read
    path under the same rules."""
    import json

    from tpu_operator import consts
    from tpu_operator.agents.autotune_agent import AutotuneAgent
    from tpu_operator.kube.objects import new_object

    monkeypatch.setenv("LIBTPU_VERSION", "1.0.0")
    node = make_tpu_node("tpu-0", "tpu-v4-podslice", "2x2x1")
    node["metadata"]["labels"][consts.AUTOTUNE_ELECTED_LABEL] = consts.AUTOTUNE_ELECTED
    store.create(node)
    store.create(new_object(
        "v1", "ConfigMap", consts.AUTOTUNE_RESULTS_CONFIGMAP, NS,
        data={"v5e.json": "{}"},
    ))
    flash = {"block_q": 512, "block_k": 1024, "rate": 90.0, "stable": True}
    entry = {
        "generation": "v4", "libtpu_version": "1.0.0", "platform": "tpu",
        "results": {
            fam: {"s8192_h8_d128": {"winner": flash, "configs": [flash]}}
            for fam in ("flash_fwd", "flash_fwd_bwd", "matmul", "int8")
        },
    }
    agent = AutotuneAgent(client, "tpu-0", NS, sweep_fn=lambda g, v: dict(entry))
    assert agent.reconcile_once() == "swept"
    assert json.loads(
        store.get("v1", "ConfigMap", consts.AUTOTUNE_RESULTS_CONFIGMAP, NS)
        ["data"]["v4.json"]
    )["generation"] == "v4"
    assert agent.reconcile_once() == "cache-hit"


AGENT_EXERCISES = {
    "state-tpu-feature-discovery": exercise_tfd,
    "state-node-discovery": exercise_node_discovery,
    "state-slice-manager": exercise_slice_manager,
    "state-device-plugin": exercise_device_plugin,
    "state-operator-validation": exercise_validator_plugin,
    "state-node-status-exporter": exercise_node_status_exporter,
    "state-health-monitor": exercise_health_monitor,
    "state-autotuner": exercise_autotuner,
}


def enforced_server(state_name):
    store = FakeClient()
    authorizer = RbacAuthorizer(state_rules(state_name))
    server = FakeApiServer(store, authorize=authorizer).start()
    client = HttpClient(server.base_url, timeout=10.0)
    return store, server, client, authorizer


class TestAgentsUnderEnforcement:
    """Each operand agent that talks to the apiserver runs its core loop
    under enforcement with exactly the Role/ClusterRole its own state
    ships — the same 403s a real cluster would produce for a missing
    grant."""

    @pytest.mark.parametrize("state_name", sorted(AGENT_EXERCISES))
    def test_agent_under_shipped_rules(self, state_name, tmp_path, monkeypatch):
        store, server, client, auth = enforced_server(state_name)
        try:
            AGENT_EXERCISES[state_name](store, client, tmp_path, monkeypatch)
            assert not auth.denials, auth.denials
        finally:
            server.stop()

    def test_health_monitor_publishes_verdict(self, tmp_path, monkeypatch):
        from tpu_operator import consts

        store, server, client, auth = enforced_server("state-health-monitor")
        try:
            exercise_health_monitor(store, client, tmp_path, monkeypatch)
            node = store.get("v1", "Node", "tpu-0")
            assert node["metadata"]["labels"][consts.TPU_HEALTH_LABEL] == "degraded"
            assert any(
                c["type"] == consts.TPU_HEALTH_CONDITION
                for c in node["status"]["conditions"]
            )
            assert not auth.denials, auth.denials
        finally:
            server.stop()

    def test_health_monitor_grants_actually_needed(self, tmp_path, monkeypatch):
        """Negative control for the new ClusterRole: strip nodes/status
        and the condition write must 403 — proving the grant is load-
        bearing, not cargo cult."""
        from tpu_operator.kube import errors

        rules = [
            r
            for r in state_rules("state-health-monitor")
            if "nodes/status" not in (r.get("resources") or [])
        ]
        store = FakeClient()
        authorizer = RbacAuthorizer(rules)
        server = FakeApiServer(store, authorize=authorizer).start()
        client = HttpClient(server.base_url, timeout=10.0)
        try:
            store.create(make_tpu_node("tpu-0", chips=4))
            try:
                run_health_agent(client, tmp_path, monkeypatch)
            except errors.ApiError:
                pass  # a surfaced 403 is equally acceptable
            assert any(res == "nodes/status" for _, _, res in authorizer.denials), (
                authorizer.denials
            )
        finally:
            server.stop()


class TestOperatorUnderEnforcement:
    def _run_install(self, rules):
        store = FakeClient()
        for i in range(2):
            store.create(make_tpu_node(f"tpu-{i}", "tpu-v5-lite-podslice", "2x4"))
        authorizer = RbacAuthorizer(rules)
        server = FakeApiServer(store, authorize=authorizer).start()
        client = HttpClient(server.base_url, timeout=10.0)
        sim = ClusterSim(store, ready_delay=0.02, tick=0.01).start()
        mgr = Manager(client, namespace=NS)
        setup_with_manager(mgr, ClusterPolicyReconciler(client, NS))
        try:
            mgr.start()
            # the CR install is an ADMIN action (kubectl apply), not the
            # operator's: it goes straight into the store so the shipped
            # ClusterRole doesn't need (and doesn't hold) CR create
            store.create(new_cluster_policy())

            def ready():
                cp = store.get_or_none(
                    CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND, "cluster-policy"
                )
                return (cp or {}).get("status", {}).get("state") == "ready"

            became_ready = wait_for(ready, timeout=30)
            return became_ready, authorizer.denials
        finally:
            mgr.stop()
            sim.stop()
            server.stop()

    def test_shipped_clusterrole_covers_the_whole_install(self):
        """Install→Ready under full RBAC enforcement with exactly the
        rules every install path ships: zero denials allowed. A failure
        here means a production operator would be throwing 403s."""
        became_ready, denials = self._run_install(shipped_rules())
        assert became_ready, f"never Ready under enforcement; denials={sorted(set(denials))}"
        assert not denials, f"ClusterRole gaps: {sorted(set(denials))}"

    # The drill drives the operator FSM AND its own harness (fake kubelet
    # marking pods Running, test-admin managing the PDB fixture) through
    # one client. On a real cluster those harness ops run under kubelet/
    # admin credentials, never the operator's — so the enforcement run
    # supplements the shipped rules with exactly that actor's slice. The
    # operator's own upgrade verbs (node cordon/label updates, pod
    # deletes, pods/eviction create) must still come from shipped_rules.
    HARNESS_RULES = [
        {"apiGroups": [""], "resources": ["pods/status"], "verbs": ["update"]},
        {
            "apiGroups": ["policy"],
            "resources": ["poddisruptionbudgets"],
            "verbs": ["get", "list", "create", "update", "delete"],
        },
        # the drill provisions/tears down its synthetic tainted Node —
        # cloud-controller territory; the operator itself only reads and
        # updates nodes, never creates or deletes them
        {"apiGroups": [""], "resources": ["nodes"], "verbs": ["create", "delete"]},
    ]

    def test_upgrade_drill_runs_under_enforcement(self):
        """The rolling-upgrade FSM (cordon → PDB-parked eviction → drain
        → validate → uncordon) exercises verbs the install alone never
        does — pods/eviction create, node updates mid-walk, grace-period
        pod deletes. All operator-side traffic must be covered by the
        shipped rules (harness-side kubelet/admin ops get their own
        slice, as on a real cluster)."""
        from drill import assert_drill_passed, run_upgrade_drill

        store = FakeClient()
        authorizer = RbacAuthorizer(shipped_rules() + self.HARNESS_RULES)
        server = FakeApiServer(store, authorize=authorizer).start()
        client = HttpClient(server.base_url, timeout=10.0)
        try:
            obs = run_upgrade_drill(client, NS)
            assert_drill_passed(obs)
            assert not authorizer.denials, (
                f"ClusterRole gaps in the upgrade path: {sorted(set(authorizer.denials))}"
            )
        finally:
            server.stop()

    def test_health_drill_runs_under_enforcement(self):
        """The repair FSM (cordon → PDB-parked eviction → driver-pod
        delete → revalidate → uncordon) under the shipped operator rules:
        all operator-side traffic must be covered (harness-side kubelet/
        admin ops get their own slice, as in the upgrade drill)."""
        from drill import assert_health_drill_passed, run_health_drill

        store = FakeClient()
        authorizer = RbacAuthorizer(shipped_rules() + self.HARNESS_RULES)
        server = FakeApiServer(store, authorize=authorizer).start()
        client = HttpClient(server.base_url, timeout=10.0)
        try:
            obs = run_health_drill(client, NS)
            assert_health_drill_passed(obs)
            assert not authorizer.denials, (
                f"ClusterRole gaps in the remediation path: {sorted(set(authorizer.denials))}"
            )
        finally:
            server.stop()

    # the placement drill's admin half provisions TPUSlice CRs (kubectl
    # territory on a real cluster); the operator side only reads them
    # and patches their status
    PLACEMENT_HARNESS_RULES = [
        {
            "apiGroups": ["tpu.google.com"],
            "resources": ["tpuslices"],
            "verbs": ["create", "delete"],
        },
    ]

    def test_placement_drill_runs_under_enforcement(self):
        """The placement controller's whole verb surface — TPUSlice
        reads, tpuslices/status patches, node assignment-label patches,
        Events — exercised by the priority-preemption drill over the
        wire under the shipped operator rules (harness-side node/CR
        provisioning gets its own slice, as in the other drills)."""
        from drill import assert_placement_drill_passed, run_placement_drill

        store = FakeClient()
        authorizer = RbacAuthorizer(
            shipped_rules() + self.HARNESS_RULES + self.PLACEMENT_HARNESS_RULES
        )
        server = FakeApiServer(store, authorize=authorizer).start()
        client = HttpClient(server.base_url, timeout=10.0)
        try:
            obs = run_placement_drill(client, NS)
            assert_placement_drill_passed(obs)
            assert not authorizer.denials, (
                f"ClusterRole gaps in the placement path: {sorted(set(authorizer.denials))}"
            )
        finally:
            server.stop()

    # the job drill's admin half provisions the TPUJob CR (kubectl
    # territory on a real cluster); the operator side reads it, patches
    # its status, and owns the TPUSlice lifecycle end to end
    JOB_HARNESS_RULES = [
        {
            "apiGroups": ["tpu.google.com"],
            "resources": ["tpujobs"],
            "verbs": ["create", "delete"],
        },
    ]

    def test_job_drill_runs_under_enforcement(self):
        """The TPUJob controller's whole verb surface — tpujobs reads +
        status patches, the owned TPUSlice create/patch/delete on
        shrink/grow/teardown, progress-ConfigMap barrier keys, Events —
        exercised by the shrink/grow/resume drill over the wire under
        the shipped operator rules (harness-side node/CR provisioning
        gets its own slice, as in the other drills)."""
        from drill import assert_job_drill_passed, run_job_drill

        store = FakeClient()
        authorizer = RbacAuthorizer(
            shipped_rules() + self.HARNESS_RULES + self.JOB_HARNESS_RULES
        )
        server = FakeApiServer(store, authorize=authorizer).start()
        client = HttpClient(server.base_url, timeout=10.0)
        try:
            obs = run_job_drill(client, NS)
            assert_job_drill_passed(obs)
            assert not authorizer.denials, (
                f"ClusterRole gaps in the job path: {sorted(set(authorizer.denials))}"
            )
        finally:
            server.stop()

    # the serving drill's admin half provisions the TPUServing CR
    # (kubectl territory on a real cluster); the operator side reads it,
    # patches its status, owns the replica TPUSlices, and writes the
    # routing key into the load ConfigMap
    SERVING_HARNESS_RULES = [
        {
            "apiGroups": ["tpu.google.com"],
            "resources": ["tpuservings"],
            "verbs": ["create", "delete"],
        },
    ]

    def test_serving_drill_runs_under_enforcement(self):
        """The TPUServing controller's whole verb surface — tpuservings
        reads + status patches, replica TPUSlice create/delete on
        scale-up/scale-down, the routing key on the load ConfigMap,
        Events — exercised by the burst/route/scale-down drill over the
        wire under the shipped operator rules (harness-side node/CR/
        traffic provisioning gets its own slice, as in the other
        drills)."""
        from drill import assert_serving_drill_passed, run_serving_drill

        store = FakeClient()
        authorizer = RbacAuthorizer(
            shipped_rules() + self.HARNESS_RULES + self.SERVING_HARNESS_RULES
        )
        server = FakeApiServer(store, authorize=authorizer).start()
        client = HttpClient(server.base_url, timeout=10.0)
        try:
            obs = run_serving_drill(client, NS)
            assert_serving_drill_passed(obs)
            assert not authorizer.denials, (
                f"ClusterRole gaps in the serving path: {sorted(set(authorizer.denials))}"
            )
        finally:
            server.stop()

    def test_cert_lifecycle_under_enforcement(self, tmp_path):
        """The webhook cert manager's full converge path (Secret adopt/
        publish, VWC caBundle patch) runs under the shipped rules — the
        install flow never exercises secrets/VWC verbs (webhook defaults
        off), so without this the role's secrets/admissionregistration
        slices were untested claims."""
        pytest.importorskip("cryptography", reason="the cert manager mints real X.509 material")
        from tpu_operator.certs import WebhookCertManager
        from tpu_operator.kube.objects import new_object

        store = FakeClient()
        authorizer = RbacAuthorizer(shipped_rules())
        server = FakeApiServer(store, authorize=authorizer).start()
        client = HttpClient(server.base_url, timeout=10.0)
        try:
            store.create(
                new_object(
                    "admissionregistration.k8s.io/v1",
                    "ValidatingWebhookConfiguration",
                    "tpu-operator",
                    webhooks=[{"name": "clusterpolicy.tpu.google.com", "clientConfig": {}}],
                )
            )
            mgr = WebhookCertManager(client, NS, str(tmp_path))
            assert mgr.ensure()  # mint + publish Secret + patch caBundle
            assert not mgr.ensure()  # converged: second pass is a no-op
            secret = store.get("v1", "Secret", "tpu-operator-webhook-tls", NS)
            assert secret["data"]["tls.crt"]
            vwc = store.get(
                "admissionregistration.k8s.io/v1",
                "ValidatingWebhookConfiguration",
                "tpu-operator",
            )
            assert vwc["webhooks"][0]["clientConfig"]["caBundle"]
            assert not authorizer.denials, sorted(set(authorizer.denials))
        finally:
            server.stop()

    def test_enforcement_actually_bites(self):
        """Negative control: strip daemonsets from the rules and the same
        flow must record denials (proves the gate can fail — without
        this, a broken authorizer that allows everything would make the
        positive test meaningless)."""
        rules = [
            r
            for r in shipped_rules()
            if "daemonsets" not in (r.get("resources") or [])
        ]
        became_ready, denials = self._run_install(rules)
        assert any(res == "daemonsets" for _, _, res in denials), denials
        assert not became_ready, "Ready despite the operator being unable to manage DaemonSets"


class TestStaticRuntimeConsistency:
    """Wire the two RBAC gates together (neither can rot alone): the
    static analyzer's per-operand verb derivation must be a SUPERSET of
    whatever the runtime gate observes over the wire for the same agent
    flows. A static set that misses an observed verb means tpuop-lint
    would bless a Role the runtime needs more from; the excess direction
    is covered by tpuop-lint's own TPUOP-R002 pass."""

    @pytest.fixture(scope="class")
    def static_required(self):
        from tpu_operator.lint.rbac_static import required_grants

        required, _ = required_grants()
        return required

    @pytest.mark.parametrize("state_name", sorted(AGENT_EXERCISES))
    def test_static_covers_observed(self, state_name, static_required, tmp_path, monkeypatch):
        store, server, client, auth = enforced_server(state_name)
        try:
            AGENT_EXERCISES[state_name](store, client, tmp_path, monkeypatch)
        finally:
            server.stop()
        assert auth.checks, "flow sent no requests — the gate observed nothing"
        missing = auth.checks - static_required[state_name]
        assert not missing, (
            f"runtime sent verbs the static analyzer does not attribute to "
            f"{state_name}: {sorted(missing)} — update tpu_operator/lint/"
            "rbac_static.py (SUBJECT_ROOTS or a call-site pragma)"
        )


class TestClientVerbSurface:
    def test_verbs_table_covers_every_client_method(self):
        """HttpClient.VERBS is the one table both gates derive verb
        semantics from; every public Client-interface method that can
        reach the apiserver must be declared there, so adding a client
        method without classifying it fails here instead of silently
        dodging both the static and runtime RBAC gates."""
        import inspect

        from tpu_operator.kube.client import Client

        public = {
            name
            for name, member in inspect.getmembers(Client, predicate=inspect.isfunction)
            if not name.startswith("_")
        }
        undeclared = public - set(HttpClient.VERBS)
        assert not undeclared, (
            f"client methods missing from HttpClient.VERBS: {sorted(undeclared)}"
        )

    def test_verbs_table_has_no_stale_entries(self):
        """Every VERBS key must exist on HttpClient (a renamed method
        must take its table entry along)."""
        for name in HttpClient.VERBS:
            assert callable(getattr(HttpClient, name, None)), name
