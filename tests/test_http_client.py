"""HTTP apiserver client tests against an in-process stub apiserver
(cross-process loopback is blocked in this environment, so the stub serves
from a thread — same pattern as the manager endpoint tests)."""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from tpu_operator.kube import errors
from tpu_operator.kube.http_client import HttpClient, plural_of


class StubApiserver:
    """Just enough of the kube REST API: CRUD on any path + one watch
    stream fed from a queue."""

    def __init__(self):
        self.store = {}
        self.watch_events = []
        self.watch_ready = threading.Event()
        self.evictions_blocked = False  # simulate a PDB rejecting evictions
        self.reject_tokens = set()  # bearer tokens to answer with 401
        stub = self

        class Handler(BaseHTTPRequestHandler):
            def _send(self, code, payload):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.end_headers()
                self.wfile.write(body)

            def _auth_rejected(self):
                auth = self.headers.get("Authorization", "")
                if auth.removeprefix("Bearer ") in stub.reject_tokens:
                    self._send(401, {"reason": "Unauthorized"})
                    return True
                return False

            def do_GET(self):  # noqa: N802
                if self._auth_rejected():
                    return
                path = self.path.split("?")[0]
                if "watch=true" in self.path:
                    if "sendInitialEvents=true" in self.path:
                        # pre-WatchList apiserver: reject the streamed-LIST
                        # probe so the client falls back to LIST+watch
                        self._send(400, {"reason": "Invalid",
                                         "message": "sendInitialEvents not supported"})
                        return
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.end_headers()
                    stub.watch_ready.set()
                    sent = 0
                    deadline = time.monotonic() + 5
                    while time.monotonic() < deadline:
                        while sent < len(stub.watch_events):
                            self.wfile.write(json.dumps(stub.watch_events[sent]).encode() + b"\n")
                            self.wfile.flush()
                            sent += 1
                        time.sleep(0.01)
                    return
                if path in stub.store:
                    self._send(200, stub.store[path])
                elif any(k.startswith(path + "/") for k in stub.store):
                    items = [v for k, v in stub.store.items() if k.startswith(path + "/")]
                    self._send(200, {"kind": "List", "metadata": {"resourceVersion": "1"}, "items": items})
                else:
                    self._send(200, {"items": [], "metadata": {}}) if path.endswith("s") and "/" not in path.rsplit("/", 1)[-1] else self._send(404, {"reason": "NotFound"})

            def do_POST(self):  # noqa: N802
                body = json.loads(self.rfile.read(int(self.headers["Content-Length"])))
                path = self.path.split("?")[0]
                if path.endswith("/eviction"):
                    if stub.evictions_blocked:
                        self._send(429, {"reason": "TooManyRequests",
                                         "message": "disruption budget violated"})
                        return
                    pod_key = path.removesuffix("/eviction")
                    if stub.store.pop(pod_key, None) is None:
                        self._send(404, {"reason": "NotFound"})
                        return
                    self._send(201, {"kind": "Status", "status": "Success"})
                    return
                name = body["metadata"]["name"]
                key = self.path.split("?")[0] + "/" + name
                if key in stub.store:
                    self._send(409, {"reason": "AlreadyExists"})
                    return
                stub.store[key] = body
                self._send(201, body)

            def do_PUT(self):  # noqa: N802
                body = json.loads(self.rfile.read(int(self.headers["Content-Length"])))
                key = self.path.split("?")[0].removesuffix("/status")
                if key not in stub.store:
                    self._send(404, {"reason": "NotFound"})
                    return
                stub.store[key] = body
                self._send(200, body)

            def do_DELETE(self):  # noqa: N802
                key = self.path.split("?")[0]
                if stub.store.pop(key, None) is None:
                    self._send(404, {"reason": "NotFound"})
                    return
                self._send(200, {})

            def log_message(self, *args):
                pass

        self.server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=self.server.serve_forever, daemon=True).start()

    @property
    def url(self):
        host, port = self.server.server_address
        return f"http://{host}:{port}"

    def stop(self):
        self.server.shutdown()


@pytest.fixture()
def stub():
    s = StubApiserver()
    yield s
    s.stop()


def test_token_refresh_on_ttl_and_401(stub, tmp_path):
    token_file = tmp_path / "token"
    token_file.write_text("tok-1")
    client = HttpClient(stub.url, token_path=str(token_file))
    client.create({"apiVersion": "v1", "kind": "ConfigMap", "metadata": {"name": "a", "namespace": "ns"}})
    assert client.token == "tok-1"
    # rotate the bound token on disk; TTL expiry forces a re-read
    token_file.write_text("tok-2")
    client._token_read_at = 0.0
    client.get("v1", "ConfigMap", "a", "ns")
    assert client.token == "tok-2"
    # a 401 (expired bound token) re-reads immediately and retries once
    token_file.write_text("tok-3")
    stub.reject_tokens = {"tok-2"}
    client.get("v1", "ConfigMap", "a", "ns")
    assert client.token == "tok-3"


def test_from_kubeconfig_parses_client_cert_auth(tmp_path):
    """kind/k3s kubeconfigs use inline client-cert auth; the client must
    materialize the CA and load the cert chain without a cluster."""
    import base64

    import yaml

    pytest.importorskip("cryptography", reason="minting the client cert pair needs x509")
    from tpu_operator.webhook import generate_self_signed_cert

    cert, key, ca_b64 = generate_self_signed_cert(str(tmp_path))
    kubeconfig = {
        "current-context": "kind",
        "contexts": [{"name": "kind", "context": {"cluster": "c1", "user": "u1"}}],
        "clusters": [{"name": "c1", "cluster": {
            "server": "https://127.0.0.1:6443",
            "certificate-authority-data": ca_b64}}],
        "users": [{"name": "u1", "user": {
            "client-certificate-data": base64.b64encode(open(cert, "rb").read()).decode(),
            "client-key-data": base64.b64encode(open(key, "rb").read()).decode()}}],
    }
    path = tmp_path / "kubeconfig"
    path.write_text(yaml.safe_dump(kubeconfig))
    client = HttpClient.from_kubeconfig(str(path))
    assert client.base_url == "https://127.0.0.1:6443"
    assert client._ssl is not None
    # token-auth variant
    kubeconfig["users"] = [{"name": "u1", "user": {"token": "tok"}}]
    path.write_text(yaml.safe_dump(kubeconfig))
    assert HttpClient.from_kubeconfig(str(path)).token == "tok"


def test_crd_plurals_from_definitions():
    from tpu_operator.kube import http_client as hc

    assert hc.plural_of("ClusterPolicy") == "clusterpolicies"
    # the CRD definitions, not the naive fallback, must be the source
    assert "TPUSlice" in hc.PLURALS and "ClusterPolicy" in hc.PLURALS


def test_plural_rules():
    assert plural_of("ClusterPolicy") == "clusterpolicies"
    assert plural_of("DaemonSet") == "daemonsets"
    assert plural_of("Ingress") == "ingresses"
    assert plural_of("PriorityClass") == "priorityclasses"


def test_paths():
    c = HttpClient("http://x")
    assert c._path("v1", "Node", None, "n1") == "/api/v1/nodes/n1"
    assert c._path("v1", "Pod", "ns", "p") == "/api/v1/namespaces/ns/pods/p"
    assert c._path("apps/v1", "DaemonSet", "ns") == "/apis/apps/v1/namespaces/ns/daemonsets"
    assert c._path("tpu.google.com/v1", "ClusterPolicy", None, "cp") == "/apis/tpu.google.com/v1/clusterpolicies/cp"
    # cluster-scoped kinds ignore the namespace arg
    assert c._path("v1", "Node", "ignored", "n1") == "/api/v1/nodes/n1"


def test_crud_round_trip(stub):
    client = HttpClient(stub.url)
    obj = {"apiVersion": "v1", "kind": "ConfigMap",
           "metadata": {"name": "cm", "namespace": "ns"}, "data": {"k": "1"}}
    created = client.create(obj)
    assert created["data"]["k"] == "1"
    got = client.get("v1", "ConfigMap", "cm", "ns")
    assert got["data"]["k"] == "1"
    got["data"]["k"] = "2"
    client.update(got)
    assert client.get("v1", "ConfigMap", "cm", "ns")["data"]["k"] == "2"
    listed = client.list("v1", "ConfigMap", "ns")
    assert len(listed) == 1
    client.delete("v1", "ConfigMap", "cm", "ns")
    with pytest.raises(errors.NotFound):
        client.get("v1", "ConfigMap", "cm", "ns")


def test_conflict_and_exists_mapping(stub):
    client = HttpClient(stub.url)
    obj = {"apiVersion": "v1", "kind": "ConfigMap", "metadata": {"name": "cm", "namespace": "ns"}}
    client.create(obj)
    with pytest.raises(errors.AlreadyExists):
        client.create(obj)


def test_eviction_subresource_and_429_mapping(stub):
    client = HttpClient(stub.url)
    pod = {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "p1", "namespace": "ns"}}
    client.create(pod)
    stub.evictions_blocked = True
    with pytest.raises(errors.TooManyRequests):
        client.evict("p1", "ns")
    assert client.get_or_none("v1", "Pod", "p1", "ns") is not None
    stub.evictions_blocked = False
    client.evict("p1", "ns")
    assert client.get_or_none("v1", "Pod", "p1", "ns") is None


def test_watch_streams_events(stub):
    client = HttpClient(stub.url)
    received = []
    sub = client.watch(
        "v1",
        "Node",
        lambda et, obj: et != "SYNC" and received.append((et, obj["metadata"]["name"])),
    )
    assert stub.watch_ready.wait(5)
    stub.watch_events.append(
        {"type": "ADDED", "object": {"metadata": {"name": "n1", "resourceVersion": "2"}}}
    )
    deadline = time.monotonic() + 5
    while not received and time.monotonic() < deadline:
        time.sleep(0.01)
    sub.stop()
    assert ("ADDED", "n1") in received


def test_watch_resumes_from_last_rv_without_relist():
    """client-go Reflector parity: when a stream ends cleanly (apiserver
    watch timeout), the loop must resume the watch from the last seen
    resourceVersion — NOT pay a full re-list — provided the server's
    lists advertise real (nonzero) rvs. The mini-server below acts like
    real kube: rv'd LIST, a first watch session that delivers one event
    then ends, and subsequent sessions that record their start rv."""
    import json as _json
    import threading as _threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    lists = []
    watch_rvs = []
    second_session = _threading.Event()

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):  # noqa: A003
            pass

        def do_GET(self):  # noqa: N802
            if "watch=true" in self.path:
                import urllib.parse as up

                q = up.parse_qs(up.urlsplit(self.path).query)
                if q.get("sendInitialEvents") == ["true"]:
                    # pre-WatchList server: 400 the probe (the client
                    # then falls back to the LIST+watch under test here)
                    body = _json.dumps({"reason": "Invalid"}).encode()
                    self.send_response(400)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                rv = (q.get("resourceVersion") or [""])[0]
                watch_rvs.append(rv)
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Connection", "close")
                self.end_headers()
                if len(watch_rvs) == 1:
                    # first session: one event past the list rv, then a
                    # bookmark advancing progress, then clean stream end
                    for ev in (
                        {"type": "ADDED", "object": {"metadata": {"name": "n1", "resourceVersion": "11"}}},
                        {"type": "BOOKMARK", "object": {"metadata": {"resourceVersion": "12"}}},
                    ):
                        self.wfile.write(_json.dumps(ev).encode() + b"\n")
                        self.wfile.flush()
                    return  # connection closes: clean end
                second_session.set()
                # hold the second session open briefly so the loop doesn't
                # spin through more reconnects while the test asserts
                import time as _time

                _time.sleep(2)
                return
            # LIST: real-kube style nonzero resourceVersion
            lists.append(self.path)
            body = _json.dumps(
                {"apiVersion": "v1", "kind": "NodeList",
                 "metadata": {"resourceVersion": "10"}, "items": []}
            ).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    httpd.daemon_threads = True
    _threading.Thread(target=httpd.serve_forever, daemon=True).start()
    client = HttpClient(f"http://127.0.0.1:{httpd.server_address[1]}", timeout=5.0)
    seen = []
    sub = client.watch("v1", "Node", lambda et, o: seen.append(et))
    try:
        assert second_session.wait(10), "watch never reconnected"
        assert watch_rvs[0] == "10"  # first session starts at the list rv
        # the reconnect resumed from the bookmark's progress rv — and did
        # NOT re-list (one LIST total, no second SYNC delivered)
        assert watch_rvs[1] == "12", watch_rvs
        assert len(lists) == 1, lists
        assert seen.count("SYNC") == 1
    finally:
        sub.stop()
        httpd.shutdown()
        httpd.server_close()


class TestPooledRetryIdempotency:
    """A reused keep-alive connection dying before the status line is an
    ambiguous failure — the server may have processed the request before
    closing. Idempotent methods (GET/DELETE/rv-guarded PUT) silently
    retry on a fresh connection; a POST must surface the error instead
    of risking a double-create (client-go draws the same line)."""

    class _DeadConn:
        def request(self, *a, **kw):
            import http.client

            raise http.client.RemoteDisconnected("server closed idle conn")

        def close(self):
            pass

    class _GoodConn:
        class _Resp:
            status = 200
            will_close = True

            def read(self):
                return b"{}"

        def request(self, *a, **kw):
            pass

        def getresponse(self):
            return self._Resp()

        def close(self):
            pass

    def _client(self, monkeypatch):
        client = HttpClient("http://unused")
        monkeypatch.setattr(client, "_checkout_conn", lambda: (self._DeadConn(), True))
        monkeypatch.setattr(client, "_new_conn", lambda: self._GoodConn())
        return client

    def test_get_retries_on_fresh_connection(self, monkeypatch):
        client = self._client(monkeypatch)
        assert client._request("GET", "/api/v1/nodes") == {}

    def test_put_and_delete_retry(self, monkeypatch):
        client = self._client(monkeypatch)
        assert client._request("PUT", "/api/v1/nodes/n1", body={}) == {}
        assert client._request("DELETE", "/api/v1/nodes/n1") == {}

    def test_post_surfaces_the_ambiguous_failure(self, monkeypatch):
        client = self._client(monkeypatch)
        with pytest.raises(errors.ApiError, match="server closed idle conn"):
            client._request("POST", "/api/v1/nodes", body={})

    class _NotFoundConn(_GoodConn):
        class _Resp:
            status = 404
            will_close = True

            def read(self):
                return b'{"reason":"NotFound"}'

        def getresponse(self):
            return self._Resp()

    def _notfound_retry_client(self, monkeypatch):
        client = HttpClient("http://unused")
        monkeypatch.setattr(client, "_checkout_conn", lambda: (self._DeadConn(), True))
        monkeypatch.setattr(client, "_new_conn", lambda: self._NotFoundConn())
        return client

    def test_retried_delete_normalizes_404_to_success(self, monkeypatch):
        """The first DELETE may have been processed before the pooled
        connection died; a 404 on the retry then IS the successful
        outcome — surfacing NotFound would invert the result for callers
        that don't tolerate NotFound-on-delete (advisor r4)."""
        client = self._notfound_retry_client(monkeypatch)
        assert client._request("DELETE", "/api/v1/nodes/n1") == {}

    def test_retried_get_still_raises_notfound(self, monkeypatch):
        # the normalization is DELETE-specific: a GET 404 is a real answer
        client = self._notfound_retry_client(monkeypatch)
        with pytest.raises(errors.NotFound):
            client._request("GET", "/api/v1/nodes/n1")
