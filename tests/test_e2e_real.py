"""Real-apiserver e2e smoke (reference: tests/e2e against a live cluster).

Runs only when KUBECONFIG points at a reachable cluster (kind/k3s/GKE) —
skip-marked otherwise, so CI without a cluster stays green while any
environment with one exercises HttpClient (watch stream included) and
the operator loop against a genuine apiserver for the first time.

The flow mirrors the sim e2e's spine on BASELINE config 1 (CPU-only
cluster, no TPUs): install CRDs -> start the operator -> ClusterPolicy
goes Ready with NoTPUNodes -> live spec update -> uninstall + GC.
"""

import os
import time
import uuid

import pytest

from tpu_operator.kube import errors


def _real_cluster_client():
    if not os.environ.get("KUBECONFIG") and not os.path.exists(
        os.path.expanduser("~/.kube/config")
    ):
        pytest.skip("no KUBECONFIG: real-apiserver e2e needs a cluster")
    from tpu_operator.kube.http_client import HttpClient

    try:
        client = HttpClient.from_kubeconfig()
        client.list("v1", "Namespace")
    except (errors.ApiError, OSError) as e:
        pytest.skip(f"apiserver unreachable: {e}")
    return client


def wait_for(fn, timeout=60.0, interval=0.5):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return False


@pytest.mark.e2e
class TestRealApiserver:
    def test_install_to_ready_and_uninstall(self):
        client = _real_cluster_client()
        ns = f"tpu-op-e2e-{uuid.uuid4().hex[:8]}"
        from tpu_operator.api.clusterpolicy import (
            CLUSTER_POLICY_API_VERSION,
            CLUSTER_POLICY_KIND,
            new_cluster_policy,
        )
        from tpu_operator.api.crds import all_crds
        from tpu_operator.controllers.clusterpolicy_controller import (
            ClusterPolicyReconciler,
            setup_with_manager,
        )
        from tpu_operator.kube.manager import Manager
        from tpu_operator.kube.objects import new_object

        client.create(new_object("v1", "Namespace", ns))
        for crd in all_crds():
            try:
                client.create(crd)
            except errors.AlreadyExists:
                pass
        # CRD registration is asynchronous
        assert wait_for(
            lambda: _crds_served(client), timeout=30
        ), "CRDs never became served"

        mgr = Manager(client, namespace=ns)
        setup_with_manager(mgr, ClusterPolicyReconciler(client, ns))
        mgr.start()
        try:
            client.create(new_cluster_policy())

            def ready():
                cp = client.get_or_none(
                    CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND, "cluster-policy"
                )
                return (cp or {}).get("status", {}).get("state") == "ready"

            assert wait_for(ready, timeout=120), "ClusterPolicy never became Ready"

            # live update flows through the watch -> reconcile path; retry
            # on conflict — the controller's status writes race this PUT
            for _ in range(10):
                cp = client.get(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND, "cluster-policy")
                cp["spec"].setdefault("libtpu", {})["version"] = "e2e-bump"
                try:
                    client.update(cp)
                    break
                except errors.Conflict:
                    time.sleep(0.2)
            else:
                raise AssertionError("spec update kept conflicting")
            assert wait_for(ready, timeout=60), "not Ready after live update"
        finally:
            mgr.stop()
            try:
                client.delete(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND, "cluster-policy")
            except errors.ApiError:
                pass
            try:
                client.delete("v1", "Namespace", ns)
            except errors.ApiError:
                pass


@pytest.mark.e2e
class TestRealUpgradeDrill:
    def test_rolling_libtpu_upgrade_drill(self):
        """VERDICT r02 item 7: the upgrade FSM against real eviction/PDB
        semantics — cordon, eviction parked by the cluster's disruption
        controller (429), PDB relax, pod restart at the new DaemonSet
        generation, validation, uncordon. Uses a synthetic tainted Node so
        nothing real is disturbed; the drill plays kubelet for it."""
        client = _real_cluster_client()
        ns = f"tpu-op-drill-{uuid.uuid4().hex[:8]}"
        from drill import assert_drill_passed, run_upgrade_drill
        from tpu_operator.kube.objects import new_object

        client.create(new_object("v1", "Namespace", ns))
        try:
            # slower cadence: the real disruption controller needs a beat
            # to observe PDB spec changes before evictions pass
            obs = run_upgrade_drill(client, ns, max_passes=60, pass_interval=1.0)
            assert_drill_passed(obs)
        finally:
            try:
                client.delete("v1", "Namespace", ns)
            except errors.ApiError:
                pass


def _crds_served(client) -> bool:
    try:
        client.list("tpu.google.com/v1", "ClusterPolicy")
        return True
    except errors.ApiError:
        return False
