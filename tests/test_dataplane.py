"""The pod data plane (tpu_operator/dataplane/): worker-pod rendering
and ownership, the rendezvous handshake, the sim kubelet's pod
lifecycle, and the KV-aware router's scoring/admission/handoff logic.

Router tests run against stub engines (pure python) so the scoring
policy is pinned independently of the jax decode engine; the engine
integration is covered by bench.py --pod-smoke and tests/test_serving.
"""

import numpy as np
import pytest

from tpu_operator import consts
from tpu_operator.dataplane.pods import (
    WorkerPodSet,
    job_worker_name,
    rendezvous_state,
    serving_worker_name,
)
from tpu_operator.dataplane.router import KVAwareRouter
from tpu_operator.dataplane.worker import (
    register_pod_main,
    resolve_pod_main,
)
from tpu_operator.kube.fake import FakeClient
from tpu_operator.kube.sim import PodKubelet

NS = "tpu-operator"


# -- naming + rendezvous ------------------------------------------------------


def test_worker_names_carry_the_documented_infixes():
    assert job_worker_name("train", 3) == "train-worker-3"
    assert serving_worker_name("chat", consts.SERVING_POOL_PREFILL, 0) == (
        "chat-prefill-0"
    )
    assert serving_worker_name("chat", consts.SERVING_POOL_DECODE, 1) == (
        "chat-decode-1"
    )
    assert serving_worker_name("chat", "", 2) == "chat-decode-2"  # aggregated


def test_rendezvous_complete_only_when_every_index_holds_current_hash():
    data = {
        f"{consts.JOB_RENDEZVOUS_PREFIX}0": "g2",
        f"{consts.JOB_RENDEZVOUS_PREFIX}1": "g2",
        f"{consts.JOB_RENDEZVOUS_PREFIX}2": "g1",  # prior generation draining
    }
    state = rendezvous_state(data, 3, "g2")
    assert state["checked_in"] == [0, 1]
    assert state["stale"] == [2]
    assert not state["complete"]
    data[f"{consts.JOB_RENDEZVOUS_PREFIX}2"] = "g2"
    assert rendezvous_state(data, 3, "g2")["complete"]


def test_rendezvous_empty_gang_is_never_complete():
    assert not rendezvous_state({}, 0, "g1")["complete"]
    assert not rendezvous_state(None, 2, "g1")["complete"]


# -- WorkerPodSet: render, converge, ownership --------------------------------


def _owner(kind: str, name: str) -> dict:
    return {
        "apiVersion": "tpu.google.com/v1alpha1",
        "kind": kind,
        "metadata": {"name": name, "uid": f"uid-{name}"},
    }


def _workers(n: int, env_extra=None):
    return [
        {"name": f"train{consts.JOB_WORKER_INFIX}{i}",
         "env": {consts.WORKER_ENV_JOB_NAME: "train",
                 consts.WORKER_ENV_WORKER_INDEX: str(i),
                 **(env_extra or {})}}
        for i in range(n)
    ]


def test_converge_creates_owned_hashed_pods():
    client = FakeClient()
    pods = WorkerPodSet(client, NS)
    report = pods.converge(_owner("TPUJob", "train"), consts.POD_MAIN_JOB_WORKER,
                           _workers(2))
    assert report["created"] == ["train-worker-0", "train-worker-1"]
    pod = client.get("v1", "Pod", "train-worker-0", NS)
    meta = pod["metadata"]
    assert meta["labels"][consts.POD_MAIN_LABEL] == consts.POD_MAIN_JOB_WORKER
    assert meta["annotations"][consts.WORKER_HASH_ANNOTATION]
    refs = meta["ownerReferences"]
    assert refs[0]["kind"] == "TPUJob" and refs[0]["name"] == "train"
    env = {e["name"]: e.get("value", "")
           for e in pod["spec"]["containers"][0]["env"]}
    assert env[consts.WORKER_ENV_JOB_NAME] == "train"


def test_converge_is_idempotent_and_replaces_on_spec_change():
    client = FakeClient()
    pods = WorkerPodSet(client, NS)
    owner = _owner("TPUJob", "train")
    pods.converge(owner, consts.POD_MAIN_JOB_WORKER, _workers(1))
    again = pods.converge(owner, consts.POD_MAIN_JOB_WORKER, _workers(1))
    assert again["kept"] == ["train-worker-0"] and not again["created"]
    # an env change (new gang hash) is a delete+recreate, not a patch
    changed = pods.converge(
        owner, consts.POD_MAIN_JOB_WORKER,
        _workers(1, env_extra={consts.WORKER_ENV_GANG_HASH: "g2"}))
    assert changed["replaced"] == ["train-worker-0"]


def test_converge_never_adopts_a_foreign_pod_with_the_same_name():
    client = FakeClient()
    client.create({
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": "train-worker-0", "namespace": NS},
        "spec": {"containers": [{"name": "user"}]},
    })
    pods = WorkerPodSet(client, NS)
    report = pods.converge(_owner("TPUJob", "train"),
                           consts.POD_MAIN_JOB_WORKER, _workers(1))
    assert report["foreign"] == ["train-worker-0"]
    pod = client.get("v1", "Pod", "train-worker-0", NS)
    assert "ownerReferences" not in pod["metadata"]  # untouched
    assert pod["spec"]["containers"][0]["name"] == "user"


def test_sweep_deletes_owned_only_standalone_worker_names_survive():
    """The PR 13/15 ownership pin, extended to pods: a user's standalone
    pod whose name collides with <job>-worker-<i> / <serving>-prefill-<i>
    is NEVER deleted by the sweep — even when it spoofs the managed-by
    label — because only the controller ownerReference licenses it."""
    client = FakeClient()
    pods = WorkerPodSet(client, NS)
    pods.converge(_owner("TPUJob", "train"), consts.POD_MAIN_JOB_WORKER,
                  _workers(2))
    # standalone pods: one bare, one spoofing the managed-by label
    client.create({
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": "train-worker-9", "namespace": NS},
        "spec": {},
    })
    client.create({
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": "chat-prefill-0", "namespace": NS,
                     "labels": {"app.kubernetes.io/managed-by":
                                "tpu-workload-dataplane"}},
        "spec": {},
    })
    deleted = pods.sweep("TPUJob", "train")
    assert sorted(deleted) == ["train-worker-0", "train-worker-1"]
    names = {p["metadata"]["name"] for p in client.list("v1", "Pod", NS)}
    assert {"train-worker-9", "chat-prefill-0"} <= names


def test_sweep_scopes_to_the_live_set_for_shrink():
    client = FakeClient()
    pods = WorkerPodSet(client, NS)
    owner = _owner("TPUJob", "train")
    pods.converge(owner, consts.POD_MAIN_JOB_WORKER, _workers(3))
    deleted = pods.sweep("TPUJob", "train",
                         live=["train-worker-0", "train-worker-1"])
    assert deleted == ["train-worker-2"]


def test_route_weight_patch_reports_a_vanished_pod():
    client = FakeClient()
    pods = WorkerPodSet(client, NS)
    pods.converge(_owner("TPUServing", "chat"),
                  consts.POD_MAIN_SERVING_WORKER,
                  [{"name": "chat-decode-0", "env": {}}])
    assert pods.patch_route_weight("chat-decode-0", 0.5)
    pod = client.get("v1", "Pod", "chat-decode-0", NS)
    assert pod["metadata"]["annotations"][
        consts.WORKER_ROUTE_WEIGHT_ANNOTATION] == "0.5"
    assert not pods.patch_route_weight("chat-decode-9", 1.0)


# -- PodKubelet: the sim's fake-kubelet mode ----------------------------------


class _ScriptedMain:
    """A registered pod main whose step() follows a script: int n = run
    n beats then succeed; "crash" = raise on the first beat."""

    def __init__(self, client, namespace, env):
        self.env = env
        self.beats = 0
        self.script = env.get("SCRIPT", "1")

    def step(self) -> bool:
        if self.script == "crash":
            raise RuntimeError("scripted crash")
        self.beats += 1
        return self.beats >= int(self.script)


@pytest.fixture()
def scripted_main_kind():
    kind = "test-scripted-main"
    register_pod_main(kind, _ScriptedMain)
    return kind


def _scripted_pod(name: str, kind: str, script: str, spec_hash: str = "h1"):
    return {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {
            "name": name, "namespace": NS,
            "labels": {consts.POD_MAIN_LABEL: kind},
            "annotations": {consts.WORKER_HASH_ANNOTATION: spec_hash},
        },
        "spec": {"containers": [{"name": "worker", "env": [
            {"name": "SCRIPT", "value": script},
        ]}]},
    }


def test_kubelet_runs_main_to_succeeded(scripted_main_kind):
    client = FakeClient()
    client.create(_scripted_pod("w-0", scripted_main_kind, "2"))
    kubelet = PodKubelet(client, NS)
    try:
        first = kubelet.step()
        assert first["pods"] == 1 and first["stepped"] == 1
        phase = (client.get("v1", "Pod", "w-0", NS).get("status") or {}).get("phase")
        assert phase == "Running"
        kubelet.step()  # second beat: the script finishes
        kubelet.step()  # terminal phase reported once
        phase = (client.get("v1", "Pod", "w-0", NS).get("status") or {}).get("phase")
        assert phase == "Succeeded"
        # terminal pods are never restarted
        assert kubelet.step()["stepped"] == 0
    finally:
        kubelet.stop()


def test_kubelet_fails_pod_on_crash_and_unknown_kind(scripted_main_kind):
    client = FakeClient()
    client.create(_scripted_pod("w-crash", scripted_main_kind, "crash"))
    client.create(_scripted_pod("w-alien", "no-such-main", "1"))
    kubelet = PodKubelet(client, NS)
    try:
        kubelet.step()
        kubelet.step()
        phases = {
            n: (client.get("v1", "Pod", n, NS).get("status") or {}).get("phase")
            for n in ("w-crash", "w-alien")
        }
        assert phases == {"w-crash": "Failed", "w-alien": "Failed"}
    finally:
        kubelet.stop()


def test_kubelet_hash_change_retires_the_old_generation(scripted_main_kind):
    client = FakeClient()
    client.create(_scripted_pod("w-0", scripted_main_kind, "99", spec_hash="g1"))
    kubelet = PodKubelet(client, NS)
    try:
        kubelet.step()
        gen1 = kubelet.mains()["w-0"]
        # the owning controller replaces the pod (new spec hash)
        client.delete("v1", "Pod", "w-0", NS)
        client.create(_scripted_pod("w-0", scripted_main_kind, "99",
                                    spec_hash="g2"))
        kubelet.step()
        gen2 = kubelet.mains()["w-0"]
        assert gen2 is not gen1
        assert [name for name, _ in kubelet.retired] == ["w-0"]
    finally:
        kubelet.stop()
    # stop() retires the live generation too
    assert len(kubelet.retired) == 2 and not kubelet.mains()


def test_kubelet_deleted_pod_stops_its_main(scripted_main_kind):
    client = FakeClient()
    client.create(_scripted_pod("w-0", scripted_main_kind, "99"))
    kubelet = PodKubelet(client, NS)
    try:
        kubelet.step()
        client.delete("v1", "Pod", "w-0", NS)
        report = kubelet.step()
        assert report["pods"] == 0 and not kubelet.mains()
        assert [name for name, _ in kubelet.retired] == ["w-0"]
    finally:
        kubelet.stop()


def test_registry_resolves_the_shipped_mains():
    assert resolve_pod_main(consts.POD_MAIN_JOB_WORKER) is not None
    assert resolve_pod_main(consts.POD_MAIN_SERVING_WORKER) is not None
    assert resolve_pod_main("bogus") is None


# -- KVAwareRouter: scoring, admission, handoff -------------------------------


class _StubEngine:
    def __init__(self, sessions=(), prefix_tokens=0, load=0,
                 prefilling=0, max_batch=8):
        self._sessions = set(sessions)
        self._prefix_tokens = prefix_tokens
        self.slots = {i: None for i in range(load)}
        self.queue = []
        self.prefilling_lanes = prefilling
        self.completed = []
        self.decoded_tokens = 0
        self.prefilled_done = []

        class _Cfg:
            pass

        self.cfg = _Cfg()
        self.cfg.max_batch = max_batch

    def has_session(self, session):
        return session in self._sessions

    def cached_prefix_tokens(self, prompt):
        return min(self._prefix_tokens, int(prompt.shape[0]))


class _StubMain:
    def __init__(self, serving_name, replica, pool="", **engine_kw):
        self.serving_name = serving_name
        self.replica = replica
        self.pool = pool
        self.engine = _StubEngine(**engine_kw)
        self.submitted = []
        self.handed_off = []

    def submit(self, request):
        self.submitted.append(request)
        self.engine.queue.append(request)

    def submit_prefilled(self, request, kv):
        self.handed_off.append((request, kv))


class _Req:
    def __init__(self, rid, plen=16, session=""):
        self.rid = rid
        self.prompt = np.zeros((plen,), dtype=np.int32)
        self.session = session


def _router(client=None):
    return KVAwareRouter(client or FakeClient(), NS, "chat")


def test_sync_workers_splits_pools_and_filters_other_servings():
    router = _router()
    router.sync_workers({
        "chat-decode-0": _StubMain("chat", "chat-replica-0"),
        "chat-prefill-0": _StubMain("chat", "chat-replica-1",
                                    pool=consts.SERVING_POOL_PREFILL),
        "other-decode-0": _StubMain("other", "other-replica-0"),
    })
    assert set(router.workers) == {"chat-decode-0"}
    assert set(router.prefill_workers) == {"chat-prefill-0"}


def test_session_affinity_outscores_an_emptier_replica():
    router = _router()
    holder = _StubMain("chat", "chat-replica-0", sessions={"conv-1"}, load=3)
    empty = _StubMain("chat", "chat-replica-1")
    router.sync_workers({"chat-decode-0": holder, "chat-decode-1": empty})
    router.submit(_Req("r1", session="conv-1"))
    router.tick()
    assert holder.submitted and not empty.submitted
    assert router.kv_hit_ratio == 0.0  # first routing SETS the map
    router.submit(_Req("r2", session="conv-1"))
    router.tick()
    assert router.kv_hit_ratio == 0.5  # second lands on the holder: a hit


def test_prefix_cache_bonus_breaks_the_tie():
    router = _router()
    cached = _StubMain("chat", "chat-replica-0", prefix_tokens=16)
    cold = _StubMain("chat", "chat-replica-1")
    router.sync_workers({"chat-decode-0": cached, "chat-decode-1": cold})
    router.submit(_Req("r1", plen=16))
    router.tick()
    assert cached.submitted and not cold.submitted
    assert router.prefix_routed == 1


def test_admission_holds_when_every_replica_is_prefill_saturated():
    router = _router()
    busy = _StubMain("chat", "chat-replica-0", prefilling=2)  # at the cap
    router.sync_workers({"chat-decode-0": busy})
    router.submit(_Req("r1"))
    report = router.tick()
    assert report["admitted"] == 0 and report["queued"] == 1
    assert not busy.submitted
    busy.engine.prefilling_lanes = 0  # headroom frees next tick
    assert router.tick()["admitted"] == 1


def test_zero_weight_replica_is_excluded_from_routing():
    client = FakeClient()
    client.create({
        "apiVersion": "v1", "kind": "ConfigMap",
        "metadata": {"name": "chat" + consts.SERVING_LOAD_SUFFIX,
                     "namespace": NS},
        "data": {consts.SERVING_ROUTING_KEY:
                 '{"chat-replica-0": 0.0, "chat-replica-1": 1.0}'},
    })
    router = _router(client)
    excluded = _StubMain("chat", "chat-replica-0")
    routable = _StubMain("chat", "chat-replica-1", load=5)  # busier, but legal
    router.sync_workers({"chat-decode-0": excluded, "chat-decode-1": routable})
    router.submit(_Req("r1"))
    router.tick()
    assert routable.submitted and not excluded.submitted


def test_handoff_moves_prefilled_kv_to_decode_and_meters_bytes():
    router = _router()
    prefill = _StubMain("chat", "chat-replica-p0",
                        pool=consts.SERVING_POOL_PREFILL)
    decode = _StubMain("chat", "chat-replica-0")
    request = _Req("r1", session="conv-1")
    kv = {"k": np.zeros((2, 8, 4), dtype=np.float32),
          "v": np.zeros((2, 8, 4), dtype=np.float32)}
    prefill.engine.prefilled_done.append({"request": request, "kv": kv})
    router.sync_workers({"chat-prefill-0": prefill, "chat-decode-0": decode})
    report = router.tick()
    assert report["handoffs"] == 1
    assert decode.handed_off[0][0] is request
    assert router.handoff_bytes == kv["k"].nbytes + kv["v"].nbytes
    # the session now lives on the DECODE replica the KV landed on
    assert router.sessions["conv-1"] == "chat-replica-0"


def test_handoff_waits_when_the_decode_pool_is_saturated():
    router = _router()
    prefill = _StubMain("chat", "chat-replica-p0",
                        pool=consts.SERVING_POOL_PREFILL)
    decode = _StubMain("chat", "chat-replica-0", prefilling=2)
    prefill.engine.prefilled_done.append(
        {"request": _Req("r1"),
         "kv": {"k": np.zeros((1,), np.float32),
                "v": np.zeros((1,), np.float32)}})
    router.sync_workers({"chat-prefill-0": prefill, "chat-decode-0": decode})
    assert router.tick()["handoffs"] == 0
    assert prefill.engine.prefilled_done  # still queued on the prefill side
    decode.engine.prefilling_lanes = 0
    assert router.tick()["handoffs"] == 1


def test_publish_writes_kv_telemetry_and_pool_signals():
    client = FakeClient()
    router = _router(client)
    prefill = _StubMain("chat", "chat-replica-p0",
                        pool=consts.SERVING_POOL_PREFILL)
    decode = _StubMain("chat", "chat-replica-0")
    decode.engine.decoded_tokens = 40
    router.sync_workers({"chat-prefill-0": prefill, "chat-decode-0": decode})
    router.publish()
    data = client.get("v1", "ConfigMap", "chat" + consts.SERVING_LOAD_SUFFIX,
                      NS)["data"]
    assert consts.SERVING_LOAD_KV_HIT_RATIO in data
    assert consts.SERVING_LOAD_HANDOFF_BYTES in data
    assert float(data[consts.SERVING_LOAD_DECODE_TOKENS_PER_S]) > 0
    assert consts.SERVING_LOAD_PREFILL_TTFT_P99 in data


def test_publish_omits_pool_signals_for_aggregated_serving():
    client = FakeClient()
    router = _router(client)
    router.sync_workers({"chat-decode-0": _StubMain("chat", "chat-replica-0")})
    router.publish()
    data = client.get("v1", "ConfigMap", "chat" + consts.SERVING_LOAD_SUFFIX,
                      NS)["data"]
    assert consts.SERVING_LOAD_PREFILL_TTFT_P99 not in data
    assert consts.SERVING_LOAD_DECODE_TOKENS_PER_S not in data
