"""The 4-D mesh with a NON-degenerate data axis (verdict r4 weak #2).

At n=8 the driver's multichip gate runs {data:1, sp:2, model:2, ep:2} —
data parallelism composed with sp/tp/ep never actually executes. These
tests run the composed mesh at 16 virtual devices ({data:2, sp:2,
model:2, ep:2}) in a fresh interpreter (the suite's own backend is
pinned to 8 devices at startup, so a subprocess is the only way to get
16), asserting the driver gate passes and that training actually learns
with sharded params.
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_TRAIN_SCRIPT = """
import os
import jax
import numpy as np

jax.config.update("jax_platforms", "cpu")
import __graft_entry__  # noqa: F401 — also validates its import path at 16

__graft_entry__.dryrun_multichip(16)

from tpu_operator.workloads.burnin import BurninConfig, build_train_step, make_mesh_4d

devices = jax.devices("cpu")[:16]
mesh = make_mesh_4d(devices, data=2, sp=2, model=2, ep=2)
assert dict(zip(mesh.axis_names, mesh.devices.shape)) == {
    "data": 2, "sp": 2, "model": 2, "ep": 2,
}
cfg = BurninConfig(
    d_model=64, n_heads=4, d_ff=128, seq_len=32, batch=8, n_layers=1,
    sequence_parallel=True, moe_experts=4, packed_segments=3, kv_heads=2,
)
step, params, batch = build_train_step(mesh, cfg)
losses = []
for _ in range(5):
    params, loss = step(params, batch)
    losses.append(float(loss))
assert all(np.isfinite(l) for l in losses), losses
assert losses[-1] < losses[0], f"loss did not decrease on the data=2 mesh: {losses}"
leaves = jax.tree_util.tree_leaves(params)
assert leaves and all(len(l.sharding.device_set) == 16 for l in leaves), \\
    "params not laid out over the full 16-device mesh"
assert any(not l.sharding.is_fully_replicated for l in leaves), \\
    "every param is replicated — nothing is actually sharded"
print("OK dp2-composed:", [round(l, 5) for l in losses])
"""


def _run(script: str, timeout: float = 600.0) -> subprocess.CompletedProcess:
    env = dict(os.environ)
    env.update(
        {
            "XLA_FLAGS": "--xla_force_host_platform_device_count=16",
            "JAX_PLATFORMS": "cpu",
            "PALLAS_AXON_POOL_IPS": "",  # keep the child off the TPU relay
            "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
        }
    )
    return subprocess.run(
        [sys.executable, "-c", script],
        env=env,
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=timeout,
    )


def test_dryrun_and_training_on_data2_composed_mesh():
    proc = _run(_TRAIN_SCRIPT)
    assert proc.returncode == 0, (
        f"stdout:\n{proc.stdout[-3000:]}\nstderr:\n{proc.stderr[-3000:]}"
    )
    assert "OK dp2-composed:" in proc.stdout
    # the driver-gate line proves dryrun_multichip(16) ran the 4-D mesh
    # with a real data axis
    assert "mesh={'data': 2, 'sp': 2, 'model': 2, 'ep': 2}" in proc.stdout
