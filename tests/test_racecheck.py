"""Runtime race harness (kube/racecheck.py) + regression tests for the
real findings the concurrency analyzers surfaced in kube/.

The violation-producing tests use PRIVATE Registry instances so the
suite's own autouse racecheck guard (conftest) never sees a seeded
deadlock as a real one.
"""

import threading
import time

import pytest

from tpu_operator.kube import racecheck
from tpu_operator.kube.racecheck import (
    MutationTripwire,
    Registry,
    TrackedCondition,
    TrackedLock,
)


class TestLockOrderGraph:
    def test_abba_cycle_detected_without_deadlocking(self):
        """The classic: T1 takes A then B, T2 takes B then A — detected
        from the ORDER GRAPH even though this run never interleaves
        fatally (both acquisitions happen on one thread here)."""
        reg = Registry()
        a = TrackedLock("A._lock", registry_=reg)
        b = TrackedLock("B._lock", registry_=reg)
        with a:
            with b:
                pass
        with b:
            with a:
                pass
        violations = reg.violations()
        assert len(violations) == 1
        assert violations[0].kind == "lock-order"
        assert "A._lock" in violations[0].detail and "B._lock" in violations[0].detail

    def test_consistent_order_is_clean(self):
        reg = Registry()
        a = TrackedLock("A", registry_=reg)
        b = TrackedLock("B", registry_=reg)
        for _ in range(3):
            with a:
                with b:
                    pass
        assert reg.violations() == []

    def test_three_lock_cycle(self):
        """A->B, B->C, C->A: no pair inverts, the CYCLE is the bug."""
        reg = Registry()
        a, b, c = (TrackedLock(n, registry_=reg) for n in ("A", "B", "C"))
        with a:
            with b:
                pass
        with b:
            with c:
                pass
        with c:
            with a:
                pass
        violations = [v for v in reg.violations() if v.kind == "lock-order"]
        assert len(violations) == 1
        assert all(n in violations[0].detail for n in ("A", "B", "C"))

    def test_rlock_reentry_is_not_an_edge(self):
        reg = Registry()
        r = TrackedLock("R", reentrant=True, registry_=reg)
        with r:
            with r:
                pass
        assert reg.violations() == []

    def test_duplicate_cycle_reported_once(self):
        reg = Registry()
        a = TrackedLock("A", registry_=reg)
        b = TrackedLock("B", registry_=reg)
        for _ in range(4):
            with a:
                with b:
                    pass
            with b:
                with a:
                    pass
        assert len(reg.violations()) == 1

    def test_cross_thread_edges_combine(self):
        """Each thread individually uses a consistent nesting, but the
        two orders are mutually inverted — the shared graph catches what
        per-thread views cannot."""
        reg = Registry()
        a = TrackedLock("A", registry_=reg)
        b = TrackedLock("B", registry_=reg)

        def t1():
            with a:
                with b:
                    pass

        def t2():
            with b:
                with a:
                    pass

        th1 = threading.Thread(target=t1)
        th1.start()
        th1.join()
        th2 = threading.Thread(target=t2)
        th2.start()
        th2.join()
        assert len(reg.violations()) == 1


class TestTrackedCondition:
    def test_wait_releases_the_hold(self):
        """A waiter parked in Condition.wait is NOT holding: edges from
        locks the waking thread holds must not point through it."""
        reg = Registry()
        cond = TrackedCondition("Q._lock", registry_=reg)
        other = TrackedLock("X", registry_=reg)
        woken = threading.Event()

        def waiter():
            with cond:
                cond.wait(2.0)
                woken.set()

        th = threading.Thread(target=waiter)
        th.start()
        time.sleep(0.05)
        with other:
            with cond:  # waker holds X then the condition: edge X->Q
                cond.notify_all()
        th.join(2.0)
        assert woken.is_set()
        assert reg.violations() == []  # no inversion yet

        # now close the loop: Q held while X acquired -> cycle
        with cond:
            with other:
                pass
        assert len(reg.violations()) == 1

    def test_notify_requires_no_tracking_surprises(self):
        reg = Registry()
        cond = TrackedCondition("C", registry_=reg)
        with cond:
            cond.notify()
            cond.notify_all()
        assert reg.violations() == []


class TestMutationTripwire:
    def test_same_thread_nesting_is_legal(self):
        reg = Registry()
        tw = MutationTripwire("cache", registry_=reg)
        with tw:
            with tw:  # _replace driving _on_event, delete driving GC
                pass
        assert reg.violations() == []

    def test_concurrent_writers_trip(self):
        reg = Registry()
        tw = MutationTripwire("cache", registry_=reg)
        barrier = threading.Barrier(2)

        def writer():
            barrier.wait()
            with tw:
                time.sleep(0.05)

        threads = [threading.Thread(target=writer) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert any(v.kind == "mutation" for v in reg.violations())

    def test_serialized_writers_are_clean(self):
        reg = Registry()
        tw = MutationTripwire("cache", registry_=reg)
        lock = threading.Lock()

        def writer():
            for _ in range(50):
                with lock:
                    with tw:
                        pass

        threads = [threading.Thread(target=writer) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert reg.violations() == []


class TestFactories:
    def test_disabled_returns_plain_primitives(self, monkeypatch):
        monkeypatch.delenv("TPUOP_RACECHECK", raising=False)
        assert not racecheck.enabled()
        assert isinstance(racecheck.lock("x"), type(threading.Lock()))
        assert isinstance(racecheck.rlock("x"), type(threading.RLock()))
        assert isinstance(racecheck.condition("x"), threading.Condition)
        assert racecheck.tripwire("x") is racecheck._NOOP_TRIPWIRE

    def test_enabled_returns_tracked(self, monkeypatch):
        monkeypatch.setenv("TPUOP_RACECHECK", "1")
        assert isinstance(racecheck.lock("x"), TrackedLock)
        assert isinstance(racecheck.rlock("x"), TrackedLock)
        assert isinstance(racecheck.condition("x"), TrackedCondition)
        assert isinstance(racecheck.tripwire("x"), MutationTripwire)

    def test_kube_stack_instruments_under_env(self, monkeypatch):
        """The informer/fake-client stack creates tracked locks when the
        harness is armed, and a normal create->watch->cache flow records
        order edges but zero violations."""
        monkeypatch.setenv("TPUOP_RACECHECK", "1")
        from tpu_operator.kube.fake import FakeClient
        from tpu_operator.kube.informer import Informer
        from tpu_operator.kube.objects import new_object

        before = len(racecheck.violations())
        client = FakeClient()
        assert isinstance(client._lock, TrackedLock)
        assert isinstance(client._tripwire, MutationTripwire)
        informer = Informer(client, "v1", "Node")
        assert isinstance(informer._lock, TrackedLock)
        informer.start()
        client.create(new_object("v1", "Node", "n1"))
        client.patch("v1", "Node", "n1", {"metadata": {"labels": {"a": "b"}}})
        client.delete("v1", "Node", "n1")
        assert racecheck.violations()[before:] == []

    def test_check_raises_on_violation(self):
        reg = Registry()
        a = TrackedLock("A", registry_=reg)
        b = TrackedLock("B", registry_=reg)
        with a:
            with b:
                pass
        with b:
            with a:
                pass
        with pytest.raises(RuntimeError, match="lock-order"):
            racecheck.check(registry_=reg)
        racecheck.check(registry_=Registry())  # clean registry: no raise


class TestShardedControlPlaneUnderHarness:
    """The sharding PR's new threading — per-shard queues/workers, the
    write fan-out pool, the sharded node view — exercised with the
    harness armed: every lock these paths create is tracked, and any
    lock-order cycle or mutation-tripwire hit fails here."""

    def test_sharded_controller_concurrent_enqueue_and_drain(self, monkeypatch):
        monkeypatch.setenv("TPUOP_RACECHECK", "1")
        from tpu_operator.kube.controller import Controller, Request, Result

        before = len(racecheck.violations())

        class R:
            def reconcile(self, req):
                return Result()

        ctrl = Controller("race-shards", R(), max_concurrent=2)
        ctrl.start()
        try:
            def producer(shard):
                for i in range(20):
                    ctrl.enqueue(Request(name=f"r{i}", shard=shard))

            threads = [
                threading.Thread(target=producer, args=(f"pool-{i}",))
                for i in range(4)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and any(
                depth for depth in ctrl.shard_depths().values()
            ):
                time.sleep(0.01)
            for i in range(4):
                ctrl.drain_shard(f"pool-{i}")
        finally:
            ctrl.stop()
        assert racecheck.violations()[before:] == []

    def test_write_fanout_under_harness(self, monkeypatch):
        monkeypatch.setenv("TPUOP_RACECHECK", "1")
        from tpu_operator.kube.writers import WriteFanout

        before = len(racecheck.violations())
        pool = WriteFanout(workers=4)
        try:
            counted = []
            results = pool.map([lambda: counted.append(1)] * 16)
            assert len(results) == 16 and len(counted) == 16
        finally:
            pool.close()
        assert racecheck.violations()[before:] == []

    def test_sharded_node_view_concurrent_churn(self, monkeypatch):
        monkeypatch.setenv("TPUOP_RACECHECK", "1")
        from tpu_operator.kube.fake import FakeClient
        from tpu_operator.kube.informer import Informer
        from tpu_operator.kube.sharding import ShardedNodeView
        from tpu_operator.kube.sim import make_tpu_node

        before = len(racecheck.violations())
        client = FakeClient()
        informer = Informer(client, "v1", "Node")
        view = ShardedNodeView().attach(informer)
        informer.start()

        def churn(prefix, pool):
            for i in range(10):
                client.create(make_tpu_node(f"{prefix}-{i}", nodepool=pool))
                client.patch(
                    "v1", "Node", f"{prefix}-{i}",
                    {"metadata": {"labels": {"cloud.google.com/gke-nodepool": pool + "x"}}},
                )

        threads = [
            threading.Thread(target=churn, args=(f"n{i}", f"pool-{i}"))
            for i in range(3)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        informer.stop()
        # every node ended in exactly one shard
        homes: dict = {}
        for shard, members in view.membership().items():
            for name in members:
                assert name not in homes, (name, shard, homes[name])
                homes[name] = shard
        assert racecheck.violations()[before:] == []

    def test_new_modules_pass_concurrency_analysis(self):
        """Zero C-rule findings for the sharding PR's new threaded
        modules — the same analyzer-is-the-spec pin the earlier fixes
        carry."""
        from tpu_operator.lint import concurrency

        for rel in ("kube/sharding.py", "kube/writers.py", "kube/controller.py"):
            with open(f"tpu_operator/{rel}") as f:
                findings = concurrency.analyze_source(f.read(), rel)
            errors = [x for x in findings if x.severity == "error"]
            assert not errors, (rel, errors)


class TestPodDataPlaneUnderHarness:
    """The pod data plane's threading — one pulsed thread per worker-pod
    main under the sim kubelet, mains mutating shared apiserver state
    (the rendezvous ConfigMap) concurrently — churned with the harness
    armed: the kubelet's registry lock is a racecheck factory lock, so
    any lock-order cycle or store-mutation tripwire hit fails here."""

    def test_pod_start_stop_churn_under_harness(self, monkeypatch):
        monkeypatch.setenv("TPUOP_RACECHECK", "1")
        from tpu_operator import consts
        from tpu_operator.kube.fake import FakeClient
        from tpu_operator.kube.sim import PodKubelet

        before = len(racecheck.violations())
        client = FakeClient()
        ns = "tpu-operator"

        def gang_pod(index: int, gang_hash: str) -> dict:
            # non-chief job workers: every beat re-checks + publishes
            # rendezvous.<i> into ONE shared progress ConfigMap — the
            # real contended write path, exercised from pod threads
            return {
                "apiVersion": "v1", "kind": "Pod",
                "metadata": {
                    "name": f"race-job{consts.JOB_WORKER_INFIX}{index}",
                    "namespace": ns,
                    "labels": {
                        consts.POD_MAIN_LABEL: consts.POD_MAIN_JOB_WORKER},
                    "annotations": {
                        consts.WORKER_HASH_ANNOTATION: gang_hash},
                },
                "spec": {"containers": [{"name": "worker", "env": [
                    {"name": consts.WORKER_ENV_JOB_NAME, "value": "race-job"},
                    {"name": consts.WORKER_ENV_WORKER_INDEX,
                     "value": str(index)},
                    {"name": consts.WORKER_ENV_WORKER_COUNT, "value": "9"},
                    {"name": consts.WORKER_ENV_GANG_HASH, "value": gang_hash},
                ]}]},
            }

        for i in range(1, 5):
            client.create(gang_pod(i, "g1"))
        kubelet = PodKubelet(client, ns)
        try:
            for _ in range(3):
                report = kubelet.step()
            assert report["pods"] == 4 and report["stepped"] == 4
            progress = client.get(
                "v1", "ConfigMap", "race-job-progress", ns)["data"]
            assert all(
                progress.get(f"{consts.JOB_RENDEZVOUS_PREFIX}{i}") == "g1"
                for i in range(1, 5))
            # generation roll: replace two pods (new gang hash), delete
            # one, add one — retire + start + beat in a single step
            for i in (1, 2):
                client.delete(
                    "v1", "Pod", f"race-job{consts.JOB_WORKER_INFIX}{i}", ns)
                client.create(gang_pod(i, "g2"))
            client.delete(
                "v1", "Pod", f"race-job{consts.JOB_WORKER_INFIX}3", ns)
            client.create(gang_pod(5, "g1"))
            for _ in range(3):
                report = kubelet.step()
            assert report["pods"] == 4 and report["stepped"] == 4
            retired = [name for name, _ in kubelet.retired]
            assert sorted(retired) == [
                "race-job-worker-1", "race-job-worker-2", "race-job-worker-3"]
            progress = client.get(
                "v1", "ConfigMap", "race-job-progress", ns)["data"]
            assert progress[f"{consts.JOB_RENDEZVOUS_PREFIX}1"] == "g2"
            assert progress[f"{consts.JOB_RENDEZVOUS_PREFIX}2"] == "g2"
        finally:
            kubelet.stop()
        assert not kubelet.mains() and len(kubelet.retired) == 7
        assert racecheck.violations()[before:] == []

    def test_dataplane_modules_pass_concurrency_analysis(self):
        """Zero C-rule findings for the pod data plane's new modules and
        the sim kubelet that threads them."""
        from tpu_operator.lint import concurrency

        for rel in ("dataplane/worker.py", "dataplane/router.py",
                    "dataplane/pods.py", "kube/sim.py"):
            with open(f"tpu_operator/{rel}") as f:
                findings = concurrency.analyze_source(f.read(), rel)
            errors = [x for x in findings if x.severity == "error"]
            assert not errors, (rel, errors)


class TestRealFindingRegressions:
    """Each real finding the static analyzer surfaced in kube/ got a
    fix; these pin the fixes so a refactor can't quietly undo them."""

    def test_informer_staleness_stamp_is_guarded(self):
        """last_event_at was written lock-free in the event path but
        under _lifecycle in resync(); both writers now share _lock. The
        analyzer is the spec: zero C001 findings for the informer."""
        from tpu_operator.lint import concurrency

        with open("tpu_operator/kube/informer.py") as f:
            findings = concurrency.analyze_source(f.read(), "kube/informer.py")
        assert not [x for x in findings if x.rule == "TPUOP-C001"], findings

    def test_leader_leading_event_transitions_are_guarded(self):
        """_leading.clear() in the renew loop's lost-lease branch ran
        outside _depose_lock while _depose carefully serialized every
        other transition against the watchdog's deadline re-check."""
        from tpu_operator.lint import concurrency

        with open("tpu_operator/kube/leader.py") as f:
            findings = concurrency.analyze_source(f.read(), "kube/leader.py")
        assert not [x for x in findings if x.rule == "TPUOP-C001"], findings

    def test_manager_stop_releases_lifecycle_before_blocking_teardown(self):
        """Manager.stop used to join controller workers (5 s timeout
        each) while HOLDING the lifecycle lock — any worker inside
        informer_for's creation path would deadlock against its own
        teardown. stop() now snapshots under the lock and tears down
        outside it: a component stopped during shutdown can always
        acquire the lifecycle lock from another thread."""
        from tpu_operator.kube.fake import FakeClient
        from tpu_operator.kube.manager import Manager

        manager = Manager(FakeClient())
        lock_was_free = threading.Event()
        probe_done = threading.Event()

        class _ProbingController:
            def start(self):
                pass

            def stop(self):
                # from another thread, try to take the manager lifecycle
                # lock while OUR stop() runs; with the old code the
                # stop()-calling thread held it and this timed out
                def probe():
                    got = manager._lifecycle.acquire(timeout=1.0)
                    if got:
                        lock_was_free.set()
                        manager._lifecycle.release()
                    probe_done.set()

                t = threading.Thread(target=probe, daemon=True)
                t.start()
                t.join(2.0)

        manager.add_controller(_ProbingController())
        manager.start(wait_for_leader=False)
        manager.stop()
        assert probe_done.is_set()
        assert lock_was_free.is_set(), (
            "manager.stop() still holds the lifecycle lock across "
            "component teardown"
        )

    def test_manager_stop_still_refuses_restart_and_late_informers(self):
        """The two-phase stop keeps the old guarantees: a stopped
        manager refuses start(), and an informer created after stop is
        never started (no leaked watch)."""
        from tpu_operator.kube.fake import FakeClient
        from tpu_operator.kube.manager import Manager

        manager = Manager(FakeClient())
        manager.start(wait_for_leader=False)
        manager.stop()
        manager.start(wait_for_leader=False)  # refused, logged
        assert manager.stopped()
        informer = manager.informer_for("v1", "Node")
        assert informer._sub is None  # registered but never started
