"""TPUSlice controller tests (reference analogs:
internal/state/driver_test.go per-pool rendering,
internal/validator/validator_test.go:96 conflict cases,
nvidiadriver_controller behavior)."""

from tpu_operator import consts
from tpu_operator.api.clusterpolicy import new_cluster_policy
from tpu_operator.api.tpuslice import TPU_SLICE_API_VERSION, TPU_SLICE_KIND, TPUSlice, new_tpu_slice
from tpu_operator.controllers.tpuslice_controller import TPUSliceReconciler
from tpu_operator.controllers.tpuslice_validator import (
    ValidationError,
    validate_node_selectors,
)
from tpu_operator.kube.controller import Request
from tpu_operator.kube.fake import FakeClient
from tpu_operator.kube.sim import make_tpu_node

import pytest

NS = "tpu-operator"


def seed_cluster(client):
    client.create(new_cluster_policy(spec={"libtpu": {"useTPUSliceCRD": True}}))
    for i in range(2):
        client.create(make_tpu_node(f"v5e-{i}", "tpu-v5-lite-podslice", "4x4", nodepool="pool-a"))
    client.create(make_tpu_node("v5p-0", "tpu-v5p-slice", "2x2x2", nodepool="pool-b"))


class TestValidator:
    def test_disjoint_ok(self):
        client = FakeClient()
        seed_cluster(client)
        a = TPUSlice.from_unstructured(client.create(new_tpu_slice(
            "a", spec={"nodeSelector": {consts.GKE_TPU_ACCELERATOR_LABEL: "tpu-v5-lite-podslice"}})))
        client.create(new_tpu_slice(
            "b", spec={"nodeSelector": {consts.GKE_TPU_ACCELERATOR_LABEL: "tpu-v5p-slice"}}))
        validate_node_selectors(client, a)  # no raise

    def test_overlap_rejected(self):
        client = FakeClient()
        seed_cluster(client)
        a = TPUSlice.from_unstructured(client.create(new_tpu_slice("a")))  # default: all TPU nodes... none labelled yet
        client.create(new_tpu_slice(
            "b", spec={"nodeSelector": {consts.GKE_TPU_ACCELERATOR_LABEL: "tpu-v5-lite-podslice"}}))
        # make default selector match by labelling nodes tpu.present
        for n in ("v5e-0", "v5e-1", "v5p-0"):
            node = client.get("v1", "Node", n)
            node["metadata"]["labels"][consts.TPU_PRESENT_LABEL] = "true"
            client.update(node)
        with pytest.raises(ValidationError, match="already selected"):
            validate_node_selectors(client, a)


class TestReconcile:
    def test_per_pool_fanout_and_ready(self):
        client = FakeClient()
        seed_cluster(client)
        client.create(new_tpu_slice("all", spec={"nodeSelector": {consts.GKE_NODEPOOL_LABEL: "pool-a"}}))
        # also a second CR on the other pool: disjoint, both reconcile
        client.create(new_tpu_slice("other", spec={"nodeSelector": {consts.GKE_NODEPOOL_LABEL: "pool-b"}}))
        r = TPUSliceReconciler(client, NS)
        r.reconcile(Request(name="all"))
        r.reconcile(Request(name="other"))
        dses = client.list("apps/v1", "DaemonSet", NS)
        names = sorted(ds["metadata"]["name"] for ds in dses)
        assert names == [
            "libtpu-all-tpu-v5-lite-podslice-4-4-pool-a",
            "libtpu-other-tpu-v5p-slice-2-2-2-pool-b",
        ]
        ds = dses[0]
        sel = ds["spec"]["template"]["spec"]["nodeSelector"]
        assert sel[consts.GKE_NODEPOOL_LABEL] == "pool-a"
        assert ds["spec"]["updateStrategy"]["type"] == "OnDelete"
        env = {e["name"]: e.get("value") for e in ds["spec"]["template"]["spec"]["containers"][0]["env"]}
        assert env["SLICE_HOSTS"] == "4"
        # ready status since fake DS has no scheduled pods (desired==0 -> ready)
        assert client.get(TPU_SLICE_API_VERSION, TPU_SLICE_KIND, "all")["status"]["state"] == "ready"

    def test_stale_pool_daemonset_cleaned_up(self):
        client = FakeClient()
        seed_cluster(client)
        client.create(new_tpu_slice("all", spec={"nodeSelector": {consts.GKE_NODEPOOL_LABEL: "pool-a"}}))
        r = TPUSliceReconciler(client, NS)
        r.reconcile(Request(name="all"))
        assert len(client.list("apps/v1", "DaemonSet", NS)) == 1
        # pool disappears (nodes deleted)
        client.delete("v1", "Node", "v5e-0")
        client.delete("v1", "Node", "v5e-1")
        r.reconcile(Request(name="all"))
        assert client.list("apps/v1", "DaemonSet", NS) == []

    def test_requires_cluster_policy(self):
        client = FakeClient()
        client.create(new_tpu_slice("a"))
        r = TPUSliceReconciler(client, NS)
        result = r.reconcile(Request(name="a"))
        assert result.requeue_after == consts.REQUEUE_NOT_READY_SECONDS
        obj = client.get(TPU_SLICE_API_VERSION, TPU_SLICE_KIND, "a")
        assert obj["status"]["state"] == "notReady"
        reasons = {c["type"]: c["reason"] for c in obj["status"]["conditions"]}
        assert reasons["Ready"] == "NoClusterPolicy"

    def test_conflict_sets_error_condition(self):
        client = FakeClient()
        seed_cluster(client)
        sel = {consts.GKE_TPU_ACCELERATOR_LABEL: "tpu-v5-lite-podslice"}
        client.create(new_tpu_slice("a", spec={"nodeSelector": sel}))
        client.create(new_tpu_slice("b", spec={"nodeSelector": sel}))
        r = TPUSliceReconciler(client, NS)
        r.reconcile(Request(name="a"))
        obj = client.get(TPU_SLICE_API_VERSION, TPU_SLICE_KIND, "a")
        conds = {c["type"]: c for c in obj["status"]["conditions"]}
        assert conds["Error"]["status"] == "True"
        assert conds["Error"]["reason"] == "NodeSelectorConflict"
        assert client.list("apps/v1", "DaemonSet", NS) == []


class TestStatusTransitions:
    def test_reason_transition_within_same_state_is_persisted(self):
        """Regression: conditions list aliasing made same-state transitions
        (NoClusterPolicy -> NodeSelectorConflict) invisible."""
        client = FakeClient()
        client.create(new_tpu_slice("a"))
        r = TPUSliceReconciler(client, NS)
        r.reconcile(Request(name="a"))
        obj = client.get(TPU_SLICE_API_VERSION, TPU_SLICE_KIND, "a")
        assert {c["type"]: c["reason"] for c in obj["status"]["conditions"]}["Ready"] == "NoClusterPolicy"
        # now ClusterPolicy exists but a conflicting CR appears
        seed_cluster(client)
        for n in ("v5e-0", "v5e-1", "v5p-0"):
            node = client.get("v1", "Node", n)
            node["metadata"]["labels"][consts.TPU_PRESENT_LABEL] = "true"
            client.update(node)
        client.create(new_tpu_slice("b"))  # default selector overlaps "a"
        r.reconcile(Request(name="a"))
        obj = client.get(TPU_SLICE_API_VERSION, TPU_SLICE_KIND, "a")
        conds = {c["type"]: c for c in obj["status"]["conditions"]}
        assert conds["Ready"]["reason"] == "NodeSelectorConflict"
        assert conds["Error"]["status"] == "True"
