"""Chaos-hardened control plane: fault injection, client resilience,
and the crash-recovery / leader-failover drills.

Layers under test, bottom-up:
- ChaosDirector determinism + scheduling (same seed → same fault log).
- HttpClient retry/backoff: Retry-After honored on 429/503, full-jitter
  retries for idempotent verbs, POSTs never retried, budget + deadline
  bounds.
- Circuit breaker: opens after consecutive transport failures,
  fail-fasts while open, half-open probe closes it on recovery.
- Watch-stream staleness: a silently hung stream (no events, no
  heartbeats) is abandoned at watch_stall_seconds and re-listed.
- Leader elector resilience: transient apiserver errors neither kill
  the elector thread nor depose a leader inside its renew deadline.
- Drills: chaos soak (install→Ready through the standard fault
  schedule, Degraded set and cleared, no stuck queue items, every fault
  class fired), operator crash mid-rollout → restart → idempotent
  convergence with no duplicate/orphaned operands, and two-replica
  leader failover under the SHIPPED operator ClusterRole with the
  exactly-one-active-reconciler invariant held throughout.
"""

import random
import threading
import time

import pytest

from tpu_operator import consts
from tpu_operator.api.clusterpolicy import (
    CLUSTER_POLICY_API_VERSION,
    CLUSTER_POLICY_KIND,
    new_cluster_policy,
)
from tpu_operator.controllers import conditions
from tpu_operator.controllers.clusterpolicy_controller import (
    ClusterPolicyReconciler,
    setup_with_manager,
)
from tpu_operator.kube import errors
from tpu_operator.kube.chaos import (
    FAULT_410,
    FAULT_429,
    FAULT_500,
    FAULT_503,
    FAULT_RESET,
    ChaosClient,
    ChaosDirector,
    FaultRule,
)
from tpu_operator.kube.fake import FakeClient
from tpu_operator.kube.http_client import HttpClient
from tpu_operator.kube.httpserver import FakeApiServer
from tpu_operator.kube.informer import Informer
from tpu_operator.kube.leader import LeaderElector
from tpu_operator.kube.manager import Manager
from tpu_operator.kube.retry import ApiResilience, CircuitBreaker
from tpu_operator.kube.sim import ClusterSim, make_tpu_node

NS = "tpu-operator"


def wait_for(fn, timeout=30.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return False


# ---------------------------------------------------------------------------
# ChaosDirector
# ---------------------------------------------------------------------------


class TestChaosDirector:
    SCHEDULE = dict(
        rules=[
            FaultRule(FAULT_500, rate=0.2),
            FaultRule(FAULT_429, rate=0.1, retry_after=0.5),
            FaultRule(FAULT_410, rate=0.05, verbs=("GET",)),
        ],
    )

    def _drive(self, director):
        for i in range(300):
            director.decide(("GET", "PATCH", "POST")[i % 3], ("Node", "Pod")[i % 2])
        return [(r.seq, r.verb, r.kind, r.fault) for r in director.fault_log]

    def test_same_seed_same_fault_log(self):
        log_a = self._drive(ChaosDirector(seed=42, **self.SCHEDULE))
        log_b = self._drive(ChaosDirector(seed=42, **self.SCHEDULE))
        assert log_a and log_a == log_b

    def test_different_seed_different_fault_log(self):
        log_a = self._drive(ChaosDirector(seed=42, **self.SCHEDULE))
        log_b = self._drive(ChaosDirector(seed=43, **self.SCHEDULE))
        assert log_a != log_b

    def test_scripted_schedule_fires_exactly_n_times(self):
        d = ChaosDirector(
            seed=0,
            rules=[FaultRule(FAULT_500, rate=1.0, times=3, verbs=("PATCH",), kinds=("Node",))],
        )
        for _ in range(10):
            d.decide("PATCH", "Node")
        assert len(d.fault_log) == 3
        assert d.decide("PATCH", "Pod") is None  # kind filter holds

    def test_outage_window_dominates(self):
        d = ChaosDirector(seed=0, outages=((0.0, 60.0),)).start()
        injection = d.decide("GET", "Node")
        assert injection is not None and injection.fault == FAULT_RESET
        assert d.outage_seen()

    def test_chaos_client_raises_mapped_errors(self):
        store = FakeClient()
        store.create(make_tpu_node("n1"))
        client = ChaosClient(
            store,
            ChaosDirector(seed=0, rules=[FaultRule(FAULT_429, rate=1.0, times=1, retry_after=2.0)]),
        )
        with pytest.raises(errors.TooManyRequests) as exc:
            client.get("v1", "Node", "n1")
        assert exc.value.retry_after == 2.0
        # the scripted fault is spent; the wrapped store serves normally
        assert client.get("v1", "Node", "n1")["metadata"]["name"] == "n1"


# ---------------------------------------------------------------------------
# Retry / Retry-After / budget
# ---------------------------------------------------------------------------


def _served(store, chaos=None, **client_kw):
    server = FakeApiServer(store, chaos=chaos).start()
    client = HttpClient(server.base_url, timeout=5.0, **client_kw)
    return server, client


class TestClientRetry:
    def test_5xx_retried_transparently_for_reads(self):
        store = FakeClient()
        store.create(make_tpu_node("n1"))
        chaos = ChaosDirector(seed=1, rules=[FaultRule(FAULT_500, rate=1.0, times=2)])
        server, client = _served(store, chaos)
        try:
            assert client.get("v1", "Node", "n1")["metadata"]["name"] == "n1"
            assert client.resilience.retries["GET"] == 2
            assert client.resilience.failures["http_500"] == 2
        finally:
            server.stop()

    def test_retry_after_header_is_honored(self):
        store = FakeClient()
        store.create(make_tpu_node("n1"))
        chaos = ChaosDirector(
            seed=1, rules=[FaultRule(FAULT_429, rate=1.0, times=1, retry_after=0.4)]
        )
        server, client = _served(store, chaos)
        try:
            t0 = time.monotonic()
            assert client.get("v1", "Node", "n1")["metadata"]["name"] == "n1"
            # the server said "come back in 0.4s" and the client obeyed
            assert time.monotonic() - t0 >= 0.4
        finally:
            server.stop()

    def test_post_is_never_retried(self):
        store = FakeClient()
        chaos = ChaosDirector(seed=1, rules=[FaultRule(FAULT_503, rate=1.0, times=1)])
        server, client = _served(store, chaos)
        try:
            with pytest.raises(errors.ServerError):
                client.create(make_tpu_node("n1"))
            assert client.resilience.retries.get("POST", 0) == 0
            # the fault was consumed by the one attempt; a caller-level
            # retry (what controllers do) succeeds
            client.create(make_tpu_node("n1"))
        finally:
            server.stop()

    def test_retry_budget_bounds_attempts(self):
        store = FakeClient()
        store.create(make_tpu_node("n1"))
        chaos = ChaosDirector(seed=1, rules=[FaultRule(FAULT_500, rate=1.0)])  # fails forever
        server, client = _served(store, chaos, retry_budget=2, request_deadline=5.0)
        try:
            with pytest.raises(errors.ServerError):
                client.get("v1", "Node", "n1")
            assert client.resilience.retries["GET"] == 2  # budget, not infinity
        finally:
            server.stop()

    def test_eviction_429_surfaces_immediately(self):
        """PDB-blocked evictions answer 429 — that is an APPLICATION
        answer the upgrade/repair FSMs park on, and it must never be
        spun on by the retry layer (eviction is a POST)."""
        store = FakeClient()
        chaos = ChaosDirector(seed=1, rules=[FaultRule(FAULT_429, rate=1.0, times=1)])
        server, client = _served(store, chaos)
        try:
            store.create(make_tpu_node("n1"))
            t0 = time.monotonic()
            with pytest.raises(errors.TooManyRequests):
                client.evict("ghost", NS)
            assert time.monotonic() - t0 < 0.5  # no Retry-After sleep
            # and it is an APPLICATION answer, not apiserver degradation:
            # a PDB-protected drain must never stamp Degraded=True
            assert client.resilience.failures.get("http_429", 0) == 0
            assert not client.resilience.degraded()
        finally:
            server.stop()


# ---------------------------------------------------------------------------
# Circuit breaker
# ---------------------------------------------------------------------------


class TestCircuitBreaker:
    def test_opens_after_consecutive_transport_failures_and_recovers(self):
        clock = [0.0]
        b = CircuitBreaker(failure_threshold=3, reset_seconds=5.0, clock=lambda: clock[0])
        for _ in range(3):
            b.before_request()
            b.record_failure()
        assert b.state == CircuitBreaker.OPEN
        with pytest.raises(errors.BreakerOpen):
            b.before_request()  # fail fast, no wire attempt
        clock[0] = 6.0
        b.before_request()  # half-open probe admitted
        assert b.state == CircuitBreaker.HALF_OPEN
        with pytest.raises(errors.BreakerOpen):
            b.before_request()  # second caller NOT admitted during the probe
        b.record_success()
        assert b.state == CircuitBreaker.CLOSED

    def test_failed_probe_reopens(self):
        clock = [0.0]
        b = CircuitBreaker(failure_threshold=1, reset_seconds=1.0, clock=lambda: clock[0])
        b.record_failure()
        assert b.state == CircuitBreaker.OPEN
        clock[0] = 2.0
        b.before_request()
        b.record_failure()
        assert b.state == CircuitBreaker.OPEN
        assert b.open_count == 2

    def test_answered_5xx_does_not_open_the_breaker(self):
        """An apiserver that ANSWERS with 500s has a working transport:
        the breaker is for unreachability, not for server errors."""
        store = FakeClient()
        store.create(make_tpu_node("n1"))
        chaos = ChaosDirector(seed=1, rules=[FaultRule(FAULT_500, rate=1.0)])
        server, client = _served(store, chaos, retry_budget=1, request_deadline=2.0)
        try:
            for _ in range(4):
                with pytest.raises(errors.ServerError):
                    client.get("v1", "Node", "n1")
            assert client.resilience.breaker.state == CircuitBreaker.CLOSED
        finally:
            server.stop()

    def test_outage_opens_breaker_then_recovery_closes_it(self):
        store = FakeClient()
        store.create(make_tpu_node("n1"))
        # outage from t=0 for 1.5s, healthy after
        chaos = ChaosDirector(seed=1, outages=((0.0, 1.5),))
        server, client = _served(store, chaos, retry_budget=1, request_deadline=1.0)
        client.resilience = ApiResilience(
            breaker=CircuitBreaker(failure_threshold=2, reset_seconds=0.3)
        )
        try:
            for _ in range(3):
                with pytest.raises(errors.ApiError):
                    client.get("v1", "Node", "n1")
            assert client.resilience.breaker.state == CircuitBreaker.OPEN
            assert client.resilience.degraded()
            # while open: fail-fast without a wire attempt
            sent_before = client.request_counts["GET"]
            with pytest.raises(errors.BreakerOpen):
                client.get("v1", "Node", "n1")
            assert client.request_counts["GET"] == sent_before
            # after the outage the half-open probe closes the breaker

            def recovered():
                try:
                    return client.get("v1", "Node", "n1")["metadata"]["name"] == "n1"
                except errors.ApiError:
                    return False

            assert wait_for(recovered, timeout=10.0, interval=0.2)
            assert client.resilience.breaker.state == CircuitBreaker.CLOSED
        finally:
            server.stop()


# ---------------------------------------------------------------------------
# Watch staleness
# ---------------------------------------------------------------------------


class TestWatchStaleness:
    def test_hung_stream_is_abandoned_and_relisted(self):
        """The server wedges every watch stream 0.3s after connect (no
        events, no heartbeats — indistinguishable from a quiet cluster
        without the stall detector). The client must abandon the stream
        at watch_stall_seconds and re-list, so an object created during
        the hang still becomes visible."""
        store = FakeClient()
        chaos = ChaosDirector(seed=1, watch_hang_after=0.3, watch_hang_duration=3600.0)
        server = FakeApiServer(store, chaos=chaos).start()
        client = HttpClient(server.base_url, timeout=5.0, watch_stall_seconds=1.0)
        informer = Informer(client, "v1", "Node")
        try:
            informer.start()
            time.sleep(0.6)  # the live stream is hung by now
            store.create(make_tpu_node("late"))
            assert wait_for(lambda: informer.get("late") is not None, timeout=15.0), (
                "stalled watch was never abandoned; informer is blind"
            )
            assert informer.last_event_at is not None
        finally:
            informer.stop()
            server.stop()

    def test_informer_stale_and_resync(self):
        client = FakeClient()
        client.create(make_tpu_node("n1"))
        informer = Informer(client, "v1", "Node")
        informer.start()
        assert informer.has_synced()
        assert not informer.stale(10.0)
        time.sleep(0.05)
        assert informer.stale(0.01)  # nothing delivered since the SYNC
        before = informer.last_sync_at
        informer.resync()
        assert wait_for(lambda: informer.last_sync_at != before, timeout=2.0)
        assert informer.get("n1") is not None
        informer.stop()


# ---------------------------------------------------------------------------
# Leader elector resilience (satellite bugfix)
# ---------------------------------------------------------------------------


class _FlakyClient(FakeClient):
    """Raises a transient 500 on every Lease op while .broken is set."""

    def __init__(self):
        super().__init__()
        self.broken = False

    def _maybe_break(self, kind):
        if self.broken and kind == "Lease":
            raise errors.ServerError("injected 500", status=500)

    def get(self, api_version, kind, name, namespace=None):
        self._maybe_break(kind)
        return super().get(api_version, kind, name, namespace)

    def update(self, obj):
        self._maybe_break(obj["kind"])
        return super().update(obj)

    def create(self, obj):
        self._maybe_break(obj["kind"])
        return super().create(obj)


class TestLeaderElectorResilience:
    def test_transient_error_does_not_kill_elector_thread(self):
        """The old code let any unexpected ApiError propagate out of
        _try_acquire_or_renew and silently kill the elector thread —
        leadership wedged until process restart. A blip must read as
        'not acquired this round' and the loop must keep running."""
        client = _FlakyClient()
        client.broken = True
        elector = LeaderElector(client, namespace="ns", lease_duration=0.6, renew_interval=0.05)
        elector.start()
        time.sleep(0.3)
        assert elector._thread.is_alive(), "transient 500 killed the elector thread"
        assert not elector.is_leader()
        client.broken = False  # apiserver heals
        assert elector.wait_for_leadership(3.0), "elector never recovered from the blip"
        elector.stop()

    def test_leader_rides_out_blip_within_renew_deadline(self):
        """A LEADER seeing transient renew errors keeps the lease until
        renew_deadline (client-go RetryPeriod-until-RenewDeadline);
        losing leadership on the first 500 would bounce the whole
        manager on every apiserver hiccup."""
        client = _FlakyClient()
        lost = []
        elector = LeaderElector(
            client, namespace="ns",
            lease_duration=2.0, renew_interval=0.05, renew_deadline=1.0,
        )
        elector.on_stopped_leading = lambda: lost.append(True)
        elector.start()
        assert elector.wait_for_leadership(3.0)
        client.broken = True
        time.sleep(0.4)  # several failed renews, all inside the deadline
        assert elector.is_leader() and not lost
        client.broken = False
        time.sleep(0.3)
        assert elector.is_leader() and not lost  # renewed again, still leading
        elector.stop()

    def test_leader_deposes_after_renew_deadline(self):
        client = _FlakyClient()
        lost = []
        elector = LeaderElector(
            client, namespace="ns",
            lease_duration=1.0, renew_interval=0.05, renew_deadline=0.3,
        )
        elector.on_stopped_leading = lambda: lost.append(True)
        elector.start()
        assert elector.wait_for_leadership(3.0)
        client.broken = True
        assert wait_for(lambda: bool(lost), timeout=3.0), (
            "leader outlived its renew deadline with the apiserver down"
        )
        elector.stop()

    def test_renew_conflict_on_own_applied_write_keeps_leadership(self):
        """The transport retry layer can re-send a renew PUT whose first
        send was APPLIED before the response died — the retry then 409s
        against the elector's own successful write. That Conflict must
        not read as 'lease lost' (it would depose the leader and bounce
        the manager): the elector re-reads the lease and believes it."""
        class _AppliedThenConflict(FakeClient):
            def __init__(self):
                super().__init__()
                self.arm = False

            def update(self, obj):
                if obj["kind"] == "Lease" and self.arm:
                    self.arm = False
                    super().update(obj)  # the write LANDS…
                    raise errors.Conflict("retried PUT hit its own write")
                return super().update(obj)

        client = _AppliedThenConflict()
        lost = []
        elector = LeaderElector(client, namespace="ns", lease_duration=5.0, renew_interval=0.05)
        elector.on_stopped_leading = lambda: lost.append(True)
        elector.start()
        assert elector.wait_for_leadership(3.0)
        client.arm = True
        time.sleep(0.4)  # several renew cycles, one of them conflicted
        assert elector.is_leader() and not lost, (
            "a Conflict against the elector's own applied renew deposed the leader"
        )
        elector.stop()

    def test_blocked_renew_deposes_at_wall_clock_deadline(self):
        """renew_deadline is a WALL-CLOCK bound: a renew call that HANGS
        (blackholed apiserver — connects block instead of failing fast)
        must not extend leadership past the deadline while the lease
        expires under a standby. The watchdog deposes on time even with
        the renew loop stuck inside the call."""
        hang = threading.Event()

        class _HangingClient(FakeClient):
            def get(self, api_version, kind, name, namespace=None):
                if kind == "Lease" and hang.is_set():
                    time.sleep(5.0)  # far past the 0.4s renew_deadline
                    raise errors.TransportError("blackholed")
                return super().get(api_version, kind, name, namespace)

        client = _HangingClient()
        lost = []
        elector = LeaderElector(
            client, namespace="ns",
            lease_duration=1.0, renew_interval=0.05, renew_deadline=0.4,
        )
        elector.on_stopped_leading = lambda: lost.append(time.monotonic())
        elector.start()
        assert elector.wait_for_leadership(3.0)
        t0 = time.monotonic()
        hang.set()
        assert wait_for(lambda: not elector.is_leader(), timeout=2.0), (
            "hung renew extended leadership past renew_deadline"
        )
        deposed_after = time.monotonic() - t0
        assert deposed_after < 1.0, f"deposed only after {deposed_after:.2f}s (lease already expired)"
        assert wait_for(lambda: bool(lost), timeout=2.0)
        elector._stop.set()  # skip stop()'s release (the client still hangs)

    def test_release_retries_once_on_conflict(self):
        class _ConflictOnce(FakeClient):
            def __init__(self):
                super().__init__()
                self.conflicts_left = 1

            def update(self, obj):
                if obj["kind"] == "Lease" and self.conflicts_left > 0:
                    self.conflicts_left -= 1
                    raise errors.Conflict("race")
                return super().update(obj)

        client = _ConflictOnce()
        elector = LeaderElector(client, namespace="ns", lease_duration=5.0, renew_interval=0.05)
        elector.start()
        assert elector.wait_for_leadership(3.0)
        elector.stop()  # release must survive the injected Conflict
        lease = client.get("coordination.k8s.io/v1", "Lease", elector.lease_name, "ns")
        assert lease["spec"]["holderIdentity"] == "", "conflicted release left the lease held"


# ---------------------------------------------------------------------------
# Drills: chaos soak, crash-restart, leader failover
# ---------------------------------------------------------------------------


def shipped_rules():
    import os

    import yaml

    from tpu_operator.chart import render_chart

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(repo, "deploy", "values.yaml")) as f:
        objs = render_chart(yaml.safe_load(f))
    (role,) = [o for o in objs if o["kind"] == "ClusterRole"]
    return role["rules"]


def _expected_operand_daemonsets(store):
    dses = store.list("apps/v1", "DaemonSet", NS)
    return sorted(ds["metadata"]["name"] for ds in dses)


def _assert_no_orphans(store, cp_uid):
    """Every operator-owned object must be owned by the LIVE ClusterPolicy:
    a crash that left objects owned by nothing (or re-created duplicates
    beside the originals) fails here."""
    dses = store.list("apps/v1", "DaemonSet", NS)
    names = [ds["metadata"]["name"] for ds in dses]
    assert len(names) == len(set(names)) == 11, names
    for ds in dses:
        refs = ds["metadata"].get("ownerReferences") or []
        assert any(r.get("uid") == cp_uid for r in refs), (
            f"orphaned DaemonSet {ds['metadata']['name']}: ownerReferences={refs}"
        )


def _run_soak(nodes, director, ready_timeout, client_kw=None):
    """Shared soak body: full operator over the wire through ``director``'s
    schedule; returns observations for asserts."""
    store = FakeClient()
    for i in range(nodes):
        store.create(make_tpu_node(f"tpu-{i}", "tpu-v5-lite-podslice", "4x4"))
    server = FakeApiServer(store, chaos=director).start()
    client = HttpClient(
        server.base_url, timeout=5.0, watch_stall_seconds=8.0,
        **(client_kw or {}),
    )
    sim = ClusterSim(store, ready_delay=0.05, tick=0.01).start()
    mgr = Manager(client, namespace=NS)
    reconciler = ClusterPolicyReconciler(client, NS)
    ctrl = setup_with_manager(mgr, reconciler)
    # the serving-era request mix: the placement + job + serving
    # controllers ride the same soak — one elastic job places its gang
    # and one TPUServing holds two replicas through the fault schedule
    # (no data-plane runners here; the steady controller traffic is
    # exactly what the schedule must fire every fault class against)
    from tpu_operator.controllers.job_controller import (
        JobReconciler,
        setup_with_manager as setup_job,
    )
    from tpu_operator.controllers.placement_controller import (
        PlacementReconciler,
        setup_with_manager as setup_placement,
    )
    from tpu_operator.controllers.serving_controller import (
        ServingReconciler,
        setup_with_manager as setup_serving,
    )

    setup_placement(mgr, PlacementReconciler(client, NS))
    setup_job(mgr, JobReconciler(client, NS))
    setup_serving(mgr, ServingReconciler(client, NS))
    obs = {"degraded_seen": False}
    stop_sampler = threading.Event()

    def sample_degraded():
        while not stop_sampler.wait(0.05):
            cp = store.get_or_none(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND, "cluster-policy")
            cond = conditions.get_condition(
                (cp or {}).get("status", {}).get("conditions", []), conditions.DEGRADED
            )
            if cond and cond.get("status") == "True":
                obs["degraded_seen"] = True

    sampler = threading.Thread(target=sample_degraded, daemon=True)
    try:
        mgr.start()
        store.create(new_cluster_policy())  # admin-side, like kubectl
        from tpu_operator.api.tpujob import new_tpu_job
        from tpu_operator.api.tpuserving import new_tpu_serving

        store.create(new_tpu_job("soak-job", {
            "workload": {"steps": 50},
            "gang": {"shape": "2x1x1", "minShape": "1x1x1"},
        }))
        store.create(new_tpu_serving("soak-serving", {
            "model": {"shape": "1x1x1"},
            "replicas": {"min": 2, "max": 2, "targetRps": 10.0},
            "slo": {"ttftP99Seconds": 5.0},
            "backoff": {"baseSeconds": 0.1, "maxSeconds": 1.0, "retryLimit": 50},
        }))
        sampler.start()

        def ready():
            cp = store.get_or_none(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND, "cluster-policy")
            if (cp or {}).get("status", {}).get("state") != "ready":
                return False
            dses = store.list("apps/v1", "DaemonSet", NS)
            # election-gated autotuner: desired/available 0 (no elections)
            return len(dses) == 11 and all(
                ds.get("status", {}).get("numberAvailable")
                == (0 if ds["metadata"]["name"] in ("tpu-autotuner", "tpu-compile-cache") else nodes)
                for ds in dses
            )

        obs["became_ready"] = wait_for(ready, timeout=ready_timeout, interval=0.1)

        # a fast install can converge before the rare probabilistic
        # classes (reset-body at ~0.6%) or the time-scheduled ones
        # (outage window, watch drops) ever fire: keep cheap reads
        # flowing until every configured class has landed, then end the
        # chaos run and require the cluster to heal
        probe = HttpClient(server.base_url, timeout=3.0, retry_budget=0, request_deadline=1.0)

        def all_classes_fired():
            try:
                probe.get("v1", "Node", "tpu-0")
            except errors.ApiError:
                pass
            return director.configured_classes() <= director.fired_classes()

        obs["all_classes_fired"] = wait_for(all_classes_fired, timeout=45.0, interval=0.02)

        # a serving replica's host dies MID-SCHEDULE: routing must drain
        # to the surviving replica, the placement engine must re-place
        # the broken one, and the serving must come back fully routable
        import json as _json

        from tpu_operator import consts as _consts

        def _serving_routing() -> dict:
            cm = store.get_or_none(
                "v1", "ConfigMap", "soak-serving" + _consts.SERVING_LOAD_SUFFIX, NS
            )
            raw = ((cm or {}).get("data") or {}).get(_consts.SERVING_ROUTING_KEY)
            try:
                return _json.loads(raw) if raw else {}
            except ValueError:
                return {}

        def _replica_nodes(name: str) -> list:
            ts = store.get_or_none("tpu.google.com/v1alpha1", "TPUSlice", name)
            placement = ((ts or {}).get("status") or {}).get("placement") or {}
            return list(placement.get("nodes") or []) if (
                placement.get("phase") == "Scheduled"
            ) else []

        def serving_placed():
            routing = _serving_routing()
            return sum(1 for w in routing.values() if w > 0) == 2

        obs["serving_placed"] = wait_for(serving_placed, timeout=60.0)
        victim_node = ""
        if obs["serving_placed"]:
            nodes_before = _replica_nodes("soak-serving-replica-0")
            victim_node = nodes_before[0] if nodes_before else ""
        if victim_node:
            store.patch("v1", "Node", victim_node, {"metadata": {"labels": {
                _consts.TPU_HEALTH_LABEL: _consts.HEALTH_DEGRADED,
            }}})

            def serving_drained():
                return _serving_routing().get("soak-serving-replica-0", 1.0) == 0.0

            obs["serving_drained"] = wait_for(serving_drained, timeout=45.0)
        else:
            obs["serving_drained"] = False
        director.quiesce()  # the chaos run ends; the cluster must heal

        # recovery: once faults stop landing, the Degraded condition must
        # CLEAR (the degraded-requeue path keeps reconciling until then)
        def degraded_cleared():
            cp = store.get_or_none(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND, "cluster-policy")
            cond = conditions.get_condition(
                (cp or {}).get("status", {}).get("conditions", []), conditions.DEGRADED
            )
            return cond is not None and cond.get("status") == "False"

        obs["degraded_cleared"] = wait_for(
            degraded_cleared,
            timeout=consts.API_DEGRADED_WINDOW_SECONDS + 3 * consts.REQUEUE_DEGRADED_SECONDS,
            interval=0.2,
        ) if obs["became_ready"] else False
        # zero STUCK queue items once converged and quiet: nothing
        # ready-but-unprocessed and nothing in a failure-backoff spiral.
        # (len(queue)==0 is the wrong check: the Ready heartbeat
        # legitimately parks one delayed requeue at all times.)
        def drained():
            q = ctrl.queue
            with q._lock:
                return not q._queue and not q._failures

        obs["queue_drained"] = wait_for(drained, timeout=15.0)

        # the soak job's gang must come out placed: the job controller's
        # slice create + the placement pass both survived the schedule
        def job_placed():
            ts = store.get_or_none(
                "tpu.google.com/v1alpha1", "TPUSlice", "soak-job-slice"
            )
            placement = ((ts or {}).get("status") or {}).get("placement") or {}
            return placement.get("phase") == "Scheduled"

        obs["job_placed"] = wait_for(job_placed, timeout=30.0)

        # the serving's recovery: replica-0 re-placed OFF the dead host,
        # both replicas routable again
        def serving_recovered():
            nodes_now = _replica_nodes("soak-serving-replica-0")
            if not nodes_now or victim_node in nodes_now:
                return False
            routing = _serving_routing()
            return sum(1 for w in routing.values() if w > 0) == 2

        obs["serving_recovered"] = wait_for(serving_recovered, timeout=45.0)

        # predictive-era leg: a SCHEDULED host death announced by a
        # precursor window (rising straggler telemetry on the eventual
        # victim). The risk scorer must walk the soak job off the dying
        # host BEFORE the kill lands — checkpoint-barrier migration, the
        # same machinery a defrag move uses — and the kill then hits a
        # host the gang already left. Runs admin-side against the store
        # (like the serving kill above) so the chaos director's seeded
        # draw sequence is untouched.
        from tpu_operator.controllers.risk import RiskScorer
        from tpu_operator.kube.sim import GangFaultSchedule

        sched = GangFaultSchedule(
            store, NS, "soak-job-slice", seed=20260807,
            classes=("host-death",), start_at=8, every=1000, heal_after=4,
            precursor_passes=6,
        )
        risk = RiskScorer(store, NS)
        progress_name = "soak-job" + _consts.JOB_PROGRESS_SUFFIX

        def trainer_tick():
            # minimal data-plane stand-in: publish running progress and
            # echo any checkpoint-barrier token (the soak has no real
            # runners; the controllers provide everything else)
            cm = store.get_or_none("v1", "ConfigMap", progress_name, NS)
            if cm is None:
                from tpu_operator.kube.objects import new_object
                store.create(new_object("v1", "ConfigMap", progress_name, NS, data={}))
                cm = store.get("v1", "ConfigMap", progress_name, NS)
            nodes_now = _replica_nodes("soak-job-slice")
            data = {
                _consts.JOB_PROGRESS_STEP: "42",
                _consts.JOB_PROGRESS_CHECKPOINT_STEP: "40",
                _consts.JOB_PROGRESS_EPOCH: "4",
                _consts.JOB_PROGRESS_WORLD: str(len(nodes_now)),
                _consts.JOB_PROGRESS_STATUS: _consts.JOB_PROGRESS_RUNNING,
            }
            request = (cm.get("data") or {}).get(_consts.JOB_CHECKPOINT_REQUEST, "")
            if request:
                data[_consts.JOB_PROGRESS_CHECKPOINT_ACK] = request
            store.patch("v1", "ConfigMap", progress_name, {"data": data}, NS)

        def _job_block() -> dict:
            job = store.get_or_none("tpu.google.com/v1alpha1", "TPUJob", "soak-job")
            return ((job or {}).get("status") or {}).get("job") or {}

        # open the precursor window (passes 0..7; the kill lands on 8)
        for _ in range(8):
            sched.step()
            trainer_tick()
            risk.sync()
            time.sleep(0.15)

        def premigrated():
            trainer_tick()
            risk.sync()
            return str(_job_block().get("riskHandled") or "").startswith("risk-")

        obs["job_premigrated"] = wait_for(premigrated, timeout=30.0, interval=0.1)
        gang_before_kill = set(_replica_nodes("soak-job-slice"))
        sched.step()  # the predicted death fires — on the PRE-CHOSEN host
        kills = [e for e in sched.log if e[1] == "inject"]
        obs["predicted_kill_fired"] = len(kills) == 1
        victim = kills[0][3] if kills else ""
        obs["job_walked_off_before_kill"] = bool(victim) and victim not in gang_before_kill

        def job_healthy_after_kill():
            trainer_tick()
            block = _job_block()
            if block.get("phase") == "Failed":
                return False
            return (
                block.get("phase") == "Running"
                and victim not in _replica_nodes("soak-job-slice")
            )

        obs["job_survived_predicted_death"] = wait_for(
            job_healthy_after_kill, timeout=30.0, interval=0.1
        )
        cp = store.get(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND, "cluster-policy")
        obs["cp_uid"] = cp["metadata"]["uid"]
        obs["store"] = store
        return obs
    finally:
        stop_sampler.set()
        mgr.stop()
        sim.stop()
        server.stop()


class TestChaosSoak:
    def test_install_converges_through_fault_schedule(self):
        """Tier-1 soak: the standard schedule compressed (same classes,
        shorter outage) so the whole drill stays CI-sized. 5% 5xx, 429
        bursts, 410s, resets, a watch drop every 2s, one 3s full outage
        — and the install must come out Ready with the Degraded
        condition having been set and then cleared, no stuck queue
        items, and every configured fault class actually fired. The
        predictive-era rider then schedules a host death WITH a
        precursor window: the job must walk off the dying host before
        the kill (job_premigrated) and stay healthy through it."""
        director = ChaosDirector.standard(
            seed=20260818, outage_at=2.0, outage_duration=3.0, watch_drop_every=2.0,
            rate_scale=2.0,
        )
        obs = _run_soak(nodes=24, director=director, ready_timeout=90.0)
        assert obs["became_ready"], "never Ready under the fault schedule"
        assert obs["degraded_seen"], "Degraded condition never observed during chaos"
        assert obs["degraded_cleared"], "Degraded condition never cleared after recovery"
        assert obs["queue_drained"], "stuck queue items after convergence"
        assert obs["job_placed"], "the soak TPUJob's gang never placed under chaos"
        assert obs["serving_placed"], "the soak TPUServing never became fully routable"
        assert obs["serving_drained"], (
            "routing never drained off the replica whose host died mid-schedule"
        )
        assert obs["serving_recovered"], (
            "the broken serving replica never re-placed + re-routed after the kill"
        )
        assert obs["job_premigrated"], (
            "the risk scorer never migrated the job ahead of the scheduled death"
        )
        assert obs["predicted_kill_fired"], "the scheduled host death never landed"
        assert obs["job_walked_off_before_kill"], (
            "the kill still found the gang on the predicted host"
        )
        assert obs["job_survived_predicted_death"], (
            "the job did not come back Running off the dead host"
        )
        missed = director.configured_classes() - director.fired_classes()
        assert not missed, f"configured fault classes never fired: {missed}"
        _assert_no_orphans(obs["store"], obs["cp_uid"])

    @pytest.mark.slow
    def test_full_soak_256_nodes_30s_outage(self):
        """The acceptance-criteria drill at full strength: 256 nodes,
        the standard schedule verbatim (5% 5xx, watch drop every ~10s,
        429+Retry-After bursts, one 30s full outage), reproducible from
        the seed. (The seed is chosen so every configured fault class
        fires against the CURRENT request mix — the every-class assert
        below guards against a vacuous schedule, so adding a controller
        that shifts the seeded draw sequence can require re-picking it.
        Re-seeded for the serving-era mix: the placement + job + serving
        controllers now ride the soak, an elastic job places its gang
        through the schedule, and a TPUServing survives a replica's host
        dying mid-schedule. The predictive-era rider adds a scheduled
        host death with a precursor window: the job pre-migrates behind
        the checkpoint barrier and the kill lands on an empty host.)"""
        director = ChaosDirector.standard(seed=20260818, outage_at=8.0, outage_duration=30.0)
        obs = _run_soak(nodes=256, director=director, ready_timeout=240.0)
        assert obs["became_ready"], "256-node install never Ready under chaos"
        assert obs["degraded_seen"] and obs["degraded_cleared"]
        assert obs["queue_drained"]
        assert obs["job_placed"], "the soak TPUJob's gang never placed under chaos"
        assert obs["serving_placed"] and obs["serving_drained"], obs
        assert obs["serving_recovered"], (
            "the broken serving replica never re-placed + re-routed after the kill"
        )
        assert obs["job_premigrated"] and obs["predicted_kill_fired"], obs
        assert obs["job_walked_off_before_kill"], obs
        assert obs["job_survived_predicted_death"], obs
        missed = director.configured_classes() - director.fired_classes()
        assert not missed, f"configured fault classes never fired: {missed}"
        _assert_no_orphans(obs["store"], obs["cp_uid"])


class TestTraceChaosRider:
    def test_spans_survive_fault_injection_and_recorder_stays_bounded(self):
        """ISSUE 6 rider: the flight recorder must tell the truth UNDER
        the fault schedule — every completed reconcile trace complete
        (no orphan spans, overflow accounted), retried requests visible
        as attempt children under one logical api span (scripted PATCH
        500s make that deterministic: every PATCH is operator traffic,
        so the retries land inside reconcile spans by construction),
        injected faults attributed to the reconcile that sent the
        request, and the ring bounded throughout."""
        from tpu_operator.kube import trace as trace_mod

        rec = trace_mod.reset_recorder(capacity=64)
        completed = []
        rec.add_listener(completed.append)
        try:
            director = ChaosDirector.standard(
                seed=11, outage_at=2.0, outage_duration=2.0, watch_drop_every=2.0,
            )
            director.rules = [
                FaultRule(FAULT_500, rate=1.0, times=3, verbs=("PATCH",)),
                *director.rules,
            ]
            obs = _run_soak(nodes=16, director=director, ready_timeout=90.0)
            assert obs["became_ready"], "never Ready under the fault schedule"

            assert completed, "no reconcile traces recorded under chaos"
            incomplete = [t for t in completed if not t.complete()]
            assert not incomplete, (
                f"{len(incomplete)} traces with orphan/unaccounted spans, e.g. "
                + "\n".join(rec._render_trace(incomplete[0]))
            )
            bad_accounting = [
                t for t in completed if t.accounted_fraction() < 0.95
            ]
            assert not bad_accounting, "trace components fail to account for wall time"
            retried = [
                s
                for t in completed
                for s in t.spans
                if s.name == "api" and int(s.attrs.get("attempts") or 1) > 1
            ]
            assert retried, "scripted PATCH 500s produced no retried api span"
            # the fault log attributes its scripted PATCH hits to traces
            patch_faults = [r for r in director.fault_log if r.verb == "PATCH"]
            assert patch_faults and all(r.trace for r in patch_faults)
            trace_ids = {t.trace_id for t in completed}
            assert all(r.trace.split("/")[0] in trace_ids for r in patch_faults)
            # bounded: the ring held its cap while listeners saw everything
            assert len(rec) <= 64
            assert rec.traces_recorded == len(completed)
        finally:
            trace_mod.reset_recorder()


class TestCrashRestartDrill:
    def test_crash_mid_rollout_then_restart_converges_idempotently(self):
        """SIGKILL-equivalent drill: mid-install the apiserver goes away
        under the operator (in-flight writes die on the wire, nothing
        graceful runs — from the cluster's view this is
        indistinguishable from the operator process being killed, since
        a dead process also just stops talking). The store (etcd)
        survives. A FRESH operator process (new manager, new client,
        new server port) against the same store must converge with no
        duplicate or orphaned operands — both drills run under the
        shipped operator ClusterRole."""
        from tpu_operator.kube.httpserver import RbacAuthorizer

        rules = shipped_rules()
        store = FakeClient()
        for i in range(8):
            store.create(make_tpu_node(f"tpu-{i}", "tpu-v5-lite-podslice", "2x4"))
        sim = ClusterSim(store, ready_delay=0.1, tick=0.01).start()

        server1 = FakeApiServer(store, authorize=RbacAuthorizer(rules)).start()
        client1 = HttpClient(server1.base_url, timeout=3.0, request_deadline=3.0)
        mgr1 = Manager(client1, namespace=NS)
        setup_with_manager(mgr1, ClusterPolicyReconciler(client1, NS))
        mgr2 = None
        server2 = None
        try:
            mgr1.start()
            store.create(new_cluster_policy())
            # crash point: rollout demonstrably in flight, not yet Ready
            assert wait_for(
                lambda: len(store.list("apps/v1", "DaemonSet", NS)) >= 3, timeout=30.0
            ), "rollout never started"
            cp = store.get(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND, "cluster-policy")
            assert cp.get("status", {}).get("state") != "ready", "crashed too late"
            server1.stop()  # the lights go out mid-write
            mgr1.stop()  # reap threads; nothing can reach the cluster anyway

            auth2 = RbacAuthorizer(rules)
            server2 = FakeApiServer(store, authorize=auth2).start()
            client2 = HttpClient(server2.base_url, timeout=5.0)
            mgr2 = Manager(client2, namespace=NS)
            setup_with_manager(mgr2, ClusterPolicyReconciler(client2, NS))
            mgr2.start()

            def ready():
                cp = store.get_or_none(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND, "cluster-policy")
                if (cp or {}).get("status", {}).get("state") != "ready":
                    return False
                dses = store.list("apps/v1", "DaemonSet", NS)
                return len(dses) == 11 and all(
                    ds.get("status", {}).get("numberAvailable")
                    == (0 if ds["metadata"]["name"] in ("tpu-autotuner", "tpu-compile-cache") else 8)
                    for ds in dses
                )

            assert wait_for(ready, timeout=60.0), "restarted operator never converged"
            assert not auth2.denials, f"RBAC gaps after restart: {sorted(set(auth2.denials))}"
            cp = store.get(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND, "cluster-policy")
            _assert_no_orphans(store, cp["metadata"]["uid"])
        finally:
            if mgr2 is not None:
                mgr2.stop()
            if server2 is not None:
                server2.stop()
            sim.stop()


class TestLeaderFailoverDrill:
    def test_standby_takes_over_within_lease_window(self):
        """Two Manager replicas under the SHIPPED operator ClusterRole,
        leader election on. The leader's renewals start failing (wedged
        replica); it must depose itself at renew_deadline and the
        standby must acquire within the lease window — with the
        exactly-one-active-reconciler invariant (no overlapping
        reconcile intervals between replicas) held throughout."""
        from tpu_operator.kube.httpserver import RbacAuthorizer

        lease_duration, renew_deadline, renew_interval = 2.0, 1.2, 0.1
        store = FakeClient()
        for i in range(4):
            store.create(make_tpu_node(f"tpu-{i}", "tpu-v5-lite-podslice", "2x4"))
        authorizer = RbacAuthorizer(shipped_rules())
        server = FakeApiServer(store, authorize=authorizer).start()
        sim = ClusterSim(store, ready_delay=0.05, tick=0.01).start()

        spans = []  # (replica, start, end) of every reconcile body
        spans_lock = threading.Lock()

        def instrument(reconciler, tag):
            inner = reconciler.reconcile

            def traced(req):
                t0 = time.monotonic()
                try:
                    return inner(req)
                finally:
                    with spans_lock:
                        spans.append((tag, t0, time.monotonic()))

            reconciler.reconcile = traced

        def replica(tag):
            client = HttpClient(server.base_url, timeout=3.0)
            mgr = Manager(
                client, namespace=NS, leader_election=True,
                lease_duration=lease_duration, renew_interval=renew_interval,
                renew_deadline=renew_deadline,
            )
            reconciler = ClusterPolicyReconciler(client, NS)
            setup_with_manager(mgr, reconciler)
            instrument(reconciler, tag)
            return mgr

        mgr_a = replica("A")
        mgr_b = replica("B")
        b_thread = None
        try:
            mgr_a.start()  # blocks until A holds the lease
            assert mgr_a._leader.is_leader()
            store.create(new_cluster_policy())
            assert wait_for(
                lambda: (store.get_or_none(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND, "cluster-policy") or {})
                .get("status", {}).get("state") == "ready",
                timeout=60.0,
            ), "leader A never drove the install Ready"

            # standby: start() blocks on leadership, so run it in a thread
            b_thread = threading.Thread(target=mgr_b.start, daemon=True)
            b_thread.start()
            time.sleep(3 * renew_interval)
            assert not mgr_b._leader.is_leader(), "standby grabbed a held lease"

            # the leader wedges: every renew now fails transiently
            t_wedge = time.monotonic()
            mgr_a._leader._acquire_or_renew = lambda: (_ for _ in ()).throw(
                errors.ServerError("wedged replica", status=500)
            )
            # A must depose itself (renew_deadline) and self-stop…
            assert wait_for(mgr_a.stopped, timeout=renew_deadline + 2.0), (
                "deposed leader kept its manager running (split-brain)"
            )
            # …and B must acquire within the lease window
            assert mgr_b._leader.wait_for_leadership(lease_duration + 2.0), (
                "standby never acquired within the lease window"
            )
            takeover = time.monotonic() - t_wedge
            assert takeover <= lease_duration + 2.0, f"takeover took {takeover:.1f}s"
            b_thread.join(timeout=10.0)
            assert not b_thread.is_alive(), "standby start() never returned"

            # B now reconciles: flip a label and require B to repair it
            gate = consts.COMMON_DEPLOY_LABEL_PREFIX + "tfd"
            store.patch("v1", "Node", "tpu-0", {"metadata": {"labels": {gate: None}}})
            assert wait_for(
                lambda: (store.get("v1", "Node", "tpu-0")["metadata"].get("labels") or {}).get(gate) == "true",
                timeout=15.0,
            ), "new leader never reconciled"

            # exactly-one-active-reconciler: no A span may overlap a B
            # span. Spans are recorded when a reconcile RETURNS, so wait
            # for B's repairing reconcile to finish before reading.
            def b_recorded():
                with spans_lock:
                    return any(tag == "B" for tag, _, _ in spans)

            assert wait_for(b_recorded, timeout=10.0)
            with spans_lock:
                a_spans = [(s, e) for tag, s, e in spans if tag == "A"]
                b_spans = [(s, e) for tag, s, e in spans if tag == "B"]
            assert a_spans and b_spans, (len(a_spans), len(b_spans))
            overlap = [
                (a, b)
                for a in a_spans
                for b in b_spans
                if a[0] < b[1] and b[0] < a[1]
            ]
            assert not overlap, f"replicas reconciled concurrently: {overlap[:3]}"
            assert not authorizer.denials, sorted(set(authorizer.denials))
        finally:
            mgr_b.stop()
            mgr_a.stop()
            sim.stop()
            server.stop()


# ---------------------------------------------------------------------------
# Degraded condition plumbing (unit)
# ---------------------------------------------------------------------------


class TestDegradedCondition:
    def test_publish_sets_and_clears_degraded(self):
        from tpu_operator.controllers.status import publish_status

        client = FakeClient()
        client.create(new_cluster_policy())
        obj = client.get(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND, "cluster-policy")
        publish_status(client, obj, "ready", degraded=True, degraded_detail="breaker=open")
        conds = client.get(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND, "cluster-policy")[
            "status"
        ]["conditions"]
        cond = conditions.get_condition(conds, conditions.DEGRADED)
        assert cond["status"] == "True" and cond["reason"] == "ApiserverDegraded"

        obj = client.get(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND, "cluster-policy")
        publish_status(client, obj, "ready", degraded=False)
        conds = client.get(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND, "cluster-policy")[
            "status"
        ]["conditions"]
        cond = conditions.get_condition(conds, conditions.DEGRADED)
        assert cond["status"] == "False" and cond["reason"] == "ApiserverHealthy"

    def test_fake_client_reconcile_writes_no_degraded_condition(self):
        """In-memory clients have no transport, hence no resilience
        state: the condition must be absent, not 'False' (its presence
        would churn every FakeClient-based golden/status test)."""
        client = FakeClient()
        client.create(make_tpu_node("tpu-0"))
        client.create(new_cluster_policy())
        from tpu_operator.kube.controller import Request

        rec = ClusterPolicyReconciler(client, NS)
        rec.reconcile(Request(name="cluster-policy"))
        conds = client.get(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND, "cluster-policy")[
            "status"
        ]["conditions"]
        assert conditions.get_condition(conds, conditions.DEGRADED) is None

    def test_resilience_degraded_window_drains(self):
        clock = [0.0]
        res = ApiResilience(
            breaker=CircuitBreaker(clock=lambda: clock[0]),
            degraded_window=10.0, degraded_threshold=3, clock=lambda: clock[0],
        )
        for _ in range(3):
            res.note_failure("http_500")
        assert res.degraded()
        clock[0] = 11.0
        assert not res.degraded()  # the window drained

    def test_mustgather_report_includes_breaker_and_retries(self):
        res = ApiResilience()
        res.note_retry("GET")
        res.note_failure("transport")
        report = res.report()
        assert "breaker_state: closed" in report
        assert "GET: 1" in report
        assert "transport: 1" in report


# ---------------------------------------------------------------------------
# Placement chaos rider: the placement queue must converge through the
# standard fault schedule with zero double-booked hosts. Every pass can
# die mid-flight (labels written, status patch eaten by a 5xx; a 429
# between two victims' teardowns) — the label-derived re-planning must
# heal every partial write instead of compounding it.
# ---------------------------------------------------------------------------


class TestPlacementChaosRider:
    def test_placement_queue_converges_through_standard_schedule(self):
        from tpu_operator.api.tpuslice import (
            TPU_SLICE_API_VERSION,
            TPU_SLICE_KIND,
            new_tpu_slice,
        )
        from tpu_operator.controllers.placement_controller import (
            QUEUE_REQUEST,
            PlacementReconciler,
        )
        from tpu_operator.kube.chaos import ChaosClient
        from tpu_operator.kube.sim import make_torus_nodes
        from tpu_operator.placement.engine import PlacementPhase

        store = FakeClient()
        for node in make_torus_nodes((4, 4, 2)):  # 32-host pod
            store.create(node)
        requests = [  # 8 + 8 + 4 + 8 = 28 of 32 hosts: all must place
            ("chaos-a", "2x2x2"), ("chaos-b", "4x2x1"), ("chaos-c", "2x2x1"),
            ("chaos-d", "2x2x2"),
        ]
        for name, shape in requests:
            store.create(new_tpu_slice(name, {"placement": {"shape": shape}}))
        director = ChaosDirector.standard(
            seed=23, outage_at=0.5, outage_duration=1.5, watch_drop_every=2.0,
            rate_scale=2.0,
        )
        reconciler = PlacementReconciler(ChaosClient(store, director), NS)

        def all_scheduled() -> bool:
            for name, _ in requests:
                obj = store.get(TPU_SLICE_API_VERSION, TPU_SLICE_KIND, name)
                st = (obj.get("status") or {}).get("placement") or {}
                if st.get("phase") != PlacementPhase.SCHEDULED:
                    return False
            return True

        deadline = time.time() + 60.0
        converged = False
        faulted_passes = 0
        while time.time() < deadline:
            try:
                reconciler.reconcile(QUEUE_REQUEST)
            except errors.ApiError:
                faulted_passes += 1
                time.sleep(0.02)
                continue
            if all_scheduled():
                converged = True
                break
        assert converged, "placement queue never converged under chaos"
        assert faulted_passes, "the schedule never actually faulted a pass"
        # the world must heal to a consistent, injection-free steady state
        director.quiesce()
        reconciler.reconcile(QUEUE_REQUEST)
        claimed = {}
        for name, shape in requests:
            obj = store.get(TPU_SLICE_API_VERSION, TPU_SLICE_KIND, name)
            st = obj["status"]["placement"]
            assert st["phase"] == PlacementPhase.SCHEDULED
            dims = [int(d) for d in shape.split("x")]
            hosts = st["nodes"]
            expected = 1
            for d in dims:
                expected *= d
            assert len(hosts) == expected, (name, st)
            for host in hosts:
                assert claimed.setdefault(host, name) == name, (
                    f"host {host} double-booked by {claimed[host]} and {name}"
                )
                labels = store.get("v1", "Node", host)["metadata"]["labels"]
                assert labels.get(consts.PLACEMENT_LABEL) == name, (
                    f"status/label divergence on {host}"
                )
        assert len(claimed) == 28  # 8+8+4+8 hosts, see shapes above
