"""API-layer tests (reference analog: api/nvidia/v1alpha1/nvidiadriver_types_test.go,
image-path rules internal/image/image.go tests)."""

import yaml

from tpu_operator import consts
from tpu_operator.api import (
    ClusterPolicy,
    TPUSlice,
)
from tpu_operator.api.clusterpolicy import new_cluster_policy
from tpu_operator.api.common import ImageSpec, merge_env
from tpu_operator.api.crds import all_crds, cluster_policy_crd
from tpu_operator.api.tpuslice import new_tpu_slice


class TestImagePath:
    def test_repo_image_version(self):
        s = ImageSpec(repository="gcr.io/tpu-operator", image="libtpu-installer", version="v1.2.3")
        assert s.image_path() == "gcr.io/tpu-operator/libtpu-installer:v1.2.3"

    def test_digest_version(self):
        s = ImageSpec(repository="gcr.io/x", image="plugin", version="sha256:" + "a" * 64)
        assert s.image_path() == "gcr.io/x/plugin@sha256:" + "a" * 64

    def test_image_only(self):
        s = ImageSpec(image="gcr.io/x/plugin:1.0")
        assert s.image_path() == "gcr.io/x/plugin:1.0"

    def test_env_fallback(self, monkeypatch):
        monkeypatch.setenv("VALIDATOR_IMAGE", "gcr.io/env/validator@sha256:" + "b" * 64)
        s = ImageSpec()
        assert s.image_path("VALIDATOR_IMAGE") == "gcr.io/env/validator@sha256:" + "b" * 64

    def test_empty(self):
        assert ImageSpec().image_path() == ""


class TestClusterPolicy:
    def test_defaults_from_empty_spec(self):
        cp = ClusterPolicy.from_unstructured(new_cluster_policy())
        assert cp.spec.operator.default_runtime == consts.RUNTIME_CONTAINERD
        assert cp.spec.libtpu.is_enabled()
        assert cp.spec.device_plugin.is_enabled()
        assert not cp.spec.psa.is_enabled()
        assert not cp.spec.multi_slice.is_enabled()
        assert cp.spec.libtpu.install_dir == consts.LIBTPU_INSTALL_DIR
        assert cp.spec.daemonsets.priority_class_name == "system-node-critical"

    def test_round_trip(self):
        obj = new_cluster_policy(
            spec={
                "libtpu": {"enabled": False, "repository": "gcr.io/r", "image": "i", "version": "v"},
                "devicePlugin": {"config": {"name": "plugin-config", "default": "default"}},
                "metricsExporter": {"serviceMonitor": {"enabled": True, "interval": "30s"}},
                "daemonsets": {"tolerations": [{"key": "google.com/tpu", "operator": "Exists"}]},
            }
        )
        cp = ClusterPolicy.from_unstructured(obj)
        assert not cp.spec.libtpu.is_enabled()
        assert cp.spec.libtpu.image_path() == "gcr.io/r/i:v"
        assert cp.spec.device_plugin.config.name == "plugin-config"
        assert cp.spec.metrics_exporter.service_monitor.is_enabled()
        assert cp.spec.metrics_exporter.service_monitor.interval == "30s"
        out = cp.to_unstructured()
        assert out["spec"]["libtpu"]["enabled"] is False
        assert out["spec"]["devicePlugin"]["config"]["name"] == "plugin-config"
        assert out["spec"]["daemonsets"]["tolerations"][0]["key"] == "google.com/tpu"

    def test_unknown_fields_tolerated(self):
        cp = ClusterPolicy.from_unstructured(new_cluster_policy(spec={"bogus": {"x": 1}, "libtpu": {"zzz": 2}}))
        assert cp.spec.libtpu.is_enabled()

    def test_status_round_trip(self):
        obj = new_cluster_policy()
        obj["status"] = {"state": "ready", "namespace": "tpu-operator"}
        cp = ClusterPolicy.from_unstructured(obj)
        assert cp.status.state == "ready"


class TestTPUSlice:
    def test_default_node_selector(self):
        ts = TPUSlice.from_unstructured(new_tpu_slice("default"))
        assert ts.spec.get_node_selector() == {consts.TPU_PRESENT_LABEL: "true"}

    def test_explicit_node_selector(self):
        ts = TPUSlice.from_unstructured(
            new_tpu_slice("v5e", spec={"nodeSelector": {"cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice"}})
        )
        sel = ts.spec.get_node_selector()
        assert sel == {"cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice"}

    def test_env_merge(self):
        merged = merge_env(
            [{"name": "A", "value": "1"}, {"name": "B", "value": "2"}],
            [{"name": "B", "value": "3"}],
        )
        assert {e["name"]: e["value"] for e in merged} == {"A": "1", "B": "3"}


class TestCRDs:
    def test_crds_generate_and_serialize(self):
        crds = all_crds()
        assert len(crds) == 5
        names = {c["metadata"]["name"] for c in crds}
        assert names == {
            "clusterpolicies.tpu.google.com",
            "tpuslices.tpu.google.com",
            "tpujobs.tpu.google.com",
            "tpuservings.tpu.google.com",
            "tpuquotas.tpu.google.com",
        }
        # must be valid YAML round-trippable structures
        for crd in crds:
            assert yaml.safe_load(yaml.safe_dump(crd)) == crd

    def test_clusterpolicy_crd_schema_has_subspecs(self):
        crd = cluster_policy_crd()
        props = crd["spec"]["versions"][0]["schema"]["openAPIV3Schema"]["properties"]["spec"]["properties"]
        for key in ("operator", "daemonsets", "libtpu", "devicePlugin", "tfd", "sliceManager",
                    "metricsExporter", "nodeStatusExporter", "validator", "multiSlice", "psa"):
            assert key in props, key
        assert props["libtpu"]["properties"]["installDir"] == {"type": "string"}
        assert crd["spec"]["scope"] == "Cluster"

    def test_tpuslice_crd_placement_policy_is_enum(self):
        from tpu_operator.api.crds import tpu_slice_crd

        crd = tpu_slice_crd()
        props = crd["spec"]["versions"][0]["schema"]["openAPIV3Schema"]["properties"]["spec"]["properties"]
        policy = props["placement"]["properties"]["preemptionPolicy"]
        # a typo'd policy must be rejected at admission, not silently
        # degrade to Never and sit Unschedulable with no hint why
        assert policy == {"type": "string", "enum": ["Never", "PreemptLower"]}
