"""Node-discovery bootstrap: the NFD analog for non-GKE clusters.

Reference: NFD's PCI scan labels GPU nodes on ANY cluster
(state_manager.go:113-117); the gpu-operator then stamps its own state
labels from those (state_manager.go:481-581). These tests prove the TPU
equivalent: a node with NO cloud.google.com/* labels but real (simulated)
/dev/accel* hardware ends up fully labelled and the gated operands
deploy.
"""

import os

import pytest

from tpu_operator import consts
from tpu_operator.agents.node_discovery_agent import (
    NodeDiscoveryAgent,
    parse_vm_accelerator_type,
)
from tpu_operator.kube.fake import FakeClient
from tpu_operator.kube.sim import make_bare_node, make_tpu_node
from tpu_operator.nodeinfo import is_tpu_node, tpu_info

NS = "tpu-operator"


def _clear_ambient_tpu_env(monkeypatch):
    # the axon jax plugin injects TPU_TOPOLOGY etc. into this process at
    # interpreter startup (sitecustomize) — invisible to the shell, but
    # discover() would read them as the VM contract
    for var in ("TPU_TOPOLOGY", "TPU_ACCELERATOR_TYPE", "TPU_CHIPS_PER_HOST_BOUNDS"):
        monkeypatch.delenv(var, raising=False)


@pytest.fixture()
def dev_root(tmp_path, monkeypatch):
    """A simulated TPU-VM device inventory: 4 chips under a scratch root
    the native/python probe scans via TPUINFO_SCAN_ROOT."""
    _clear_ambient_tpu_env(monkeypatch)
    (tmp_path / "dev").mkdir()
    for i in range(4):
        (tmp_path / "dev" / f"accel{i}").touch()
    monkeypatch.setenv("TPUINFO_SCAN_ROOT", str(tmp_path))
    return tmp_path


@pytest.fixture()
def empty_root(tmp_path, monkeypatch):
    _clear_ambient_tpu_env(monkeypatch)
    (tmp_path / "dev").mkdir()
    monkeypatch.setenv("TPUINFO_SCAN_ROOT", str(tmp_path))
    return tmp_path


class TestVMTypeParsing:
    def test_known_generations(self):
        assert parse_vm_accelerator_type("v5litepod-16") == ("tpu-v5-lite-podslice", 16)
        assert parse_vm_accelerator_type("v4-32") == ("tpu-v4-podslice", 16)
        assert parse_vm_accelerator_type("v5p-8") == ("tpu-v5p-slice", 4)
        assert parse_vm_accelerator_type("v6e-4") == ("tpu-v6e-slice", 4)

    def test_unknown_strings(self):
        assert parse_vm_accelerator_type("") is None
        assert parse_vm_accelerator_type("a100-80gb") is None
        assert parse_vm_accelerator_type("v5litepod") is None


class TestDiscoveryAgent:
    def test_probe_and_stamp_with_vm_type(self, dev_root, monkeypatch):
        monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5litepod-16")
        client = FakeClient()
        client.create(make_bare_node("bare-0"))
        agent = NodeDiscoveryAgent(client, "bare-0")
        assert agent.apply_once()
        labels = client.get("v1", "Node", "bare-0")["metadata"]["labels"]
        assert labels[consts.TFD_ACCELERATOR_TYPE_LABEL] == "tpu-v5-lite-podslice"
        assert labels[consts.TFD_TOPOLOGY_LABEL] == "4x4"  # 16 chips, 2D
        assert labels[consts.TFD_CHIPS_PER_NODE_LABEL] == "4"
        # idempotent: second pass sees no diff
        assert not agent.apply_once()

    def test_stamp_without_vm_type_still_recognizable(self, dev_root):
        """No TPU_ACCELERATOR_TYPE env: the node is still recognized as a
        TPU node from the probed inventory alone (degraded, not blocked)."""
        client = FakeClient()
        client.create(make_bare_node("bare-1"))
        NodeDiscoveryAgent(client, "bare-1").apply_once()
        node = client.get("v1", "Node", "bare-1")
        assert is_tpu_node(node)
        info = tpu_info(node)
        # catalog miss: the probed local chip count stands in
        assert info.chips_per_node == 4
        assert info.slice_hosts == 1

    def test_topology_env_override(self, dev_root, monkeypatch):
        monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v4-32")
        monkeypatch.setenv("TPU_TOPOLOGY", "2x2x4")
        client = FakeClient()
        client.create(make_bare_node("bare-2"))
        NodeDiscoveryAgent(client, "bare-2").apply_once()
        labels = client.get("v1", "Node", "bare-2")["metadata"]["labels"]
        assert labels[consts.TFD_ACCELERATOR_TYPE_LABEL] == "tpu-v4-podslice"
        assert labels[consts.TFD_TOPOLOGY_LABEL] == "2x2x4"

    def test_no_hardware_publishes_nothing(self, empty_root):
        client = FakeClient()
        client.create(make_bare_node("cpu-0"))
        assert not NodeDiscoveryAgent(client, "cpu-0").apply_once()
        labels = client.get("v1", "Node", "cpu-0")["metadata"]["labels"]
        assert not any(k in labels for k in consts.TFD_LABELS)

    def test_hardware_gone_strips_labels(self, empty_root):
        client = FakeClient()
        client.create(
            make_bare_node(
                "bare-3",
                extra_labels={
                    consts.TFD_ACCELERATOR_TYPE_LABEL: "tpu-v5-lite-podslice",
                    consts.TFD_CHIPS_PER_NODE_LABEL: "4",
                },
            )
        )
        assert NodeDiscoveryAgent(client, "bare-3").apply_once()
        labels = client.get("v1", "Node", "bare-3")["metadata"]["labels"]
        assert not any(k in labels for k in consts.TFD_LABELS)

    def test_probe_failure_never_strips(self, empty_root, monkeypatch):
        """One bad probe tick must not tear down a labelled node: stripping
        requires a SUCCESSFUL probe that saw no hardware."""
        client = FakeClient()
        client.create(
            make_bare_node(
                "bare-4",
                extra_labels={
                    consts.TFD_ACCELERATOR_TYPE_LABEL: "tpu-v5-lite-podslice",
                    consts.TFD_CHIPS_PER_NODE_LABEL: "4",
                },
            )
        )
        agent = NodeDiscoveryAgent(client, "bare-4")
        monkeypatch.setattr(NodeDiscoveryAgent, "probe_chips", staticmethod(lambda: None))
        assert not agent.apply_once()
        labels = client.get("v1", "Node", "bare-4")["metadata"]["labels"]
        assert labels[consts.TFD_ACCELERATOR_TYPE_LABEL] == "tpu-v5-lite-podslice"

    def test_gke_node_never_gets_identity_guesses(self, dev_root, monkeypatch):
        """On a GKE-labelled node the probe publishes only directly
        measured facts (chip count) — never the guessed accelerator-type,
        which would persist wrongly whenever tfd is disabled."""
        monkeypatch.delenv("TPU_ACCELERATOR_TYPE", raising=False)
        client = FakeClient()
        client.create(make_tpu_node("gke-1", "tpu-v5p-slice", "2x2x1"))
        NodeDiscoveryAgent(client, "gke-1").apply_once()
        labels = client.get("v1", "Node", "gke-1")["metadata"]["labels"]
        assert consts.TFD_ACCELERATOR_TYPE_LABEL not in labels
        assert consts.TFD_TOPOLOGY_LABEL not in labels
        assert labels[consts.TFD_CHIPS_PER_NODE_LABEL] == "4"

    def test_gke_labels_are_authoritative(self, dev_root, monkeypatch):
        """On GKE the platform labels (and the tfd operand's richer
        publication) own tpu.google.com/*; the probe must not overwrite
        an existing value with its guess."""
        monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5litepod-16")
        client = FakeClient()
        node = make_tpu_node("gke-0", "tpu-v6e-slice", "2x2")
        node["metadata"]["labels"][consts.TFD_ACCELERATOR_TYPE_LABEL] = "tpu-v6e-slice"
        client.create(node)
        NodeDiscoveryAgent(client, "gke-0").apply_once()
        labels = client.get("v1", "Node", "gke-0")["metadata"]["labels"]
        assert labels[consts.TFD_ACCELERATOR_TYPE_LABEL] == "tpu-v6e-slice"
        # additive facts (chips-per-node was absent) may still land
        assert labels[consts.TFD_CHIPS_PER_NODE_LABEL] == "4"


class TestNodeinfoFallback:
    def test_tpu_info_from_discovery_labels(self):
        node = make_bare_node(
            "n0",
            extra_labels={
                consts.TFD_ACCELERATOR_TYPE_LABEL: "tpu-v5-lite-podslice",
                consts.TFD_TOPOLOGY_LABEL: "4x4",
            },
        )
        info = tpu_info(node)
        assert info is not None
        assert info.generation == "v5e"
        assert info.chips_in_slice == 16
        assert info.slice_hosts == 4

    def test_gke_labels_win_over_discovery(self):
        node = make_tpu_node(
            "n1",
            "tpu-v5p-slice",
            "2x2x1",
            extra_labels={
                consts.TFD_ACCELERATOR_TYPE_LABEL: "tpu-v5-lite-podslice",
                consts.TFD_TOPOLOGY_LABEL: "4x4",
            },
        )
        assert tpu_info(node).generation == "v5p"

    def test_bare_node_is_not_tpu(self):
        assert not is_tpu_node(make_bare_node("n2"))

    def test_nodepool_selector_uses_discovery_labels(self):
        """Self-managed pools must select on the labels their nodes
        actually carry — a GKE-label selector would match zero nodes and
        hang every per-pool TPUSlice DaemonSet."""
        from tpu_operator.nodepool import get_node_pools

        nodes = [
            make_bare_node(
                f"n{i}",
                extra_labels={
                    consts.TFD_ACCELERATOR_TYPE_LABEL: "tpu-v5-lite-podslice",
                    consts.TFD_TOPOLOGY_LABEL: "4x4",
                },
            )
            for i in range(2)
        ]
        (pool,) = get_node_pools(nodes)
        assert pool.selector == {
            consts.TFD_ACCELERATOR_TYPE_LABEL: "tpu-v5-lite-podslice",
            consts.TFD_TOPOLOGY_LABEL: "4x4",
        }
        # every pool node actually matches its own selector
        for node in nodes:
            labels = node["metadata"]["labels"]
            assert all(labels.get(k) == v for k, v in pool.selector.items())

    def test_nodepool_selector_keeps_gke_labels_on_gke(self):
        from tpu_operator.nodepool import get_node_pools

        (pool,) = get_node_pools([make_tpu_node("g0", "tpu-v5-lite-podslice", "4x4")])
        assert consts.GKE_TPU_ACCELERATOR_LABEL in pool.selector


class TestBootstrapEndToEnd:
    def test_unlabelled_node_with_hardware_gets_operands(self, dev_root, monkeypatch):
        """The verdict-r4 'done' criterion: a node with NO cloud.google.com
        labels but a simulated /dev/accel* inventory ends up fully labelled
        and the gated operand DaemonSets deploy. Flow: operator installs →
        only the discovery bootstrap deploys (no recognized TPU nodes) →
        the discovery agent (standing in for its DaemonSet pod) probes and
        stamps tpu.google.com labels → the node watch re-reconciles →
        deploy gates stamp → all operands deploy."""
        import time

        from tpu_operator.api.clusterpolicy import new_cluster_policy
        from tpu_operator.controllers.clusterpolicy_controller import (
            ClusterPolicyReconciler,
            setup_with_manager,
        )
        from tpu_operator.kube.manager import Manager
        from tpu_operator.kube.sim import ClusterSim

        monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5litepod-4")
        client = FakeClient()
        client.create(make_bare_node("selfmanaged-0"))
        sim = ClusterSim(client, ready_delay=0.0).start()
        mgr = Manager(client, namespace=NS)
        setup_with_manager(mgr, ClusterPolicyReconciler(client, NS))

        def wait_for(fn, timeout=15.0):
            deadline = time.time() + timeout
            while time.time() < deadline:
                if fn():
                    return True
                time.sleep(0.05)
            return False

        try:
            mgr.start()
            client.create(new_cluster_policy())
            # phase 1: nothing recognized — only the bootstrap DS exists
            assert wait_for(
                lambda: [d["metadata"]["name"] for d in client.list("apps/v1", "DaemonSet", NS)]
                == ["tpu-node-discovery"]
            ), client.list("apps/v1", "DaemonSet", NS)
            # phase 2: the discovery pod the sim scheduled runs its probe
            assert NodeDiscoveryAgent(client, "selfmanaged-0").apply_once()
            # phase 3: recognition cascades — present + deploy gates stamp,
            # every gated operand DaemonSet deploys
            assert wait_for(
                lambda: client.get("v1", "Node", "selfmanaged-0")["metadata"]["labels"].get(
                    consts.TPU_PRESENT_LABEL
                )
                == "true"
            )
            assert wait_for(lambda: len(client.list("apps/v1", "DaemonSet", NS)) == 11), [
                d["metadata"]["name"] for d in client.list("apps/v1", "DaemonSet", NS)
            ]
            labels = client.get("v1", "Node", "selfmanaged-0")["metadata"]["labels"]
            assert labels[consts.TFD_ACCELERATOR_TYPE_LABEL] == "tpu-v5-lite-podslice"
            assert labels[consts.TFD_TOPOLOGY_LABEL] == "2x2"
            assert not any(k.startswith("cloud.google.com/") for k in labels)
        finally:
            mgr.stop()
            sim.stop()


class TestTorusCoordsPublication:
    """Placement-subsystem bootstrap: the host's ICI torus coordinate,
    derived from the TPU VM contract's TPU_WORKER_ID + the slice
    topology (row-major over the host grid)."""

    def test_worker_id_maps_to_coords(self, dev_root, monkeypatch):
        monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v4-32")
        monkeypatch.setenv("TPU_TOPOLOGY", "2x2x4")  # 16 chips, 4 hosts
        monkeypatch.setenv("TPU_WORKER_ID", "3")
        client = FakeClient()
        client.create(make_bare_node("bare-c0"))
        NodeDiscoveryAgent(client, "bare-c0").apply_once()
        labels = client.get("v1", "Node", "bare-c0")["metadata"]["labels"]
        # host grid for 2x2x4 chips @ 4-chip (2x2x1) hosts = 1x1x4;
        # worker 3 row-major = (0, 0, 3)
        assert labels[consts.TORUS_COORDS_LABEL] == "0-0-3"

    def test_missing_or_garbage_worker_id_degrades(self, dev_root, monkeypatch):
        monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v4-32")
        monkeypatch.setenv("TPU_TOPOLOGY", "2x2x4")
        for bad in (None, "nope", "99"):
            if bad is None:
                monkeypatch.delenv("TPU_WORKER_ID", raising=False)
            else:
                monkeypatch.setenv("TPU_WORKER_ID", bad)
            client = FakeClient()
            client.create(make_bare_node("bare-c1"))
            NodeDiscoveryAgent(client, "bare-c1").apply_once()
            labels = client.get("v1", "Node", "bare-c1")["metadata"]["labels"]
            assert consts.TORUS_COORDS_LABEL not in labels, bad
            # identity labels still published — coords degrade, not block
            assert labels[consts.TFD_ACCELERATOR_TYPE_LABEL] == "tpu-v4-podslice"

    def test_lost_worker_id_strips_stale_coords(self, dev_root, monkeypatch):
        """Hardware still present but the id is no longer derivable: the
        previously-published coordinate must NOT survive — the host may
        have been re-provisioned into a different torus position."""
        monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v4-32")
        monkeypatch.setenv("TPU_TOPOLOGY", "2x2x4")
        monkeypatch.setenv("TPU_WORKER_ID", "3")
        client = FakeClient()
        client.create(make_bare_node("bare-c3"))
        agent = NodeDiscoveryAgent(client, "bare-c3")
        agent.apply_once()
        assert client.get("v1", "Node", "bare-c3")["metadata"]["labels"][
            consts.TORUS_COORDS_LABEL
        ] == "0-0-3"
        monkeypatch.delenv("TPU_WORKER_ID")
        agent.apply_once()
        labels = client.get("v1", "Node", "bare-c3")["metadata"]["labels"]
        assert consts.TORUS_COORDS_LABEL not in labels
        assert labels[consts.TFD_ACCELERATOR_TYPE_LABEL] == "tpu-v4-podslice"

    def test_lost_topology_strips_stale_topology_and_coords(self, dev_root, monkeypatch):
        """Re-provisioned host whose runtime no longer exposes
        TPU_TOPOLOGY: the stale topology label must not survive — the
        placement engine sizes the pool's host grid from it, and a grid
        the host no longer belongs to corrupts every allocation there."""
        monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v4-32")
        monkeypatch.setenv("TPU_TOPOLOGY", "2x2x4")
        monkeypatch.setenv("TPU_WORKER_ID", "3")
        client = FakeClient()
        client.create(make_bare_node("bare-c4"))
        agent = NodeDiscoveryAgent(client, "bare-c4")
        agent.apply_once()
        labels = client.get("v1", "Node", "bare-c4")["metadata"]["labels"]
        assert labels[consts.TFD_TOPOLOGY_LABEL] == "2x2x4"
        monkeypatch.delenv("TPU_TOPOLOGY")
        agent.apply_once()
        labels = client.get("v1", "Node", "bare-c4")["metadata"]["labels"]
        assert consts.TFD_TOPOLOGY_LABEL not in labels
        assert consts.TORUS_COORDS_LABEL not in labels
        # directly probed facts survive: the hardware is still there
        assert labels[consts.TFD_ACCELERATOR_TYPE_LABEL] == "tpu-v4-podslice"
        assert labels[consts.TFD_CHIPS_PER_NODE_LABEL] == "4"

    def test_hardware_gone_strips_coords_too(self, dev_root, monkeypatch):
        monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v4-32")
        monkeypatch.setenv("TPU_TOPOLOGY", "2x2x4")
        monkeypatch.setenv("TPU_WORKER_ID", "0")
        client = FakeClient()
        client.create(make_bare_node("bare-c2"))
        agent = NodeDiscoveryAgent(client, "bare-c2")
        agent.apply_once()
        assert consts.TORUS_COORDS_LABEL in client.get("v1", "Node", "bare-c2")["metadata"]["labels"]
        for i in range(4):
            (dev_root / "dev" / f"accel{i}").unlink()
        agent.apply_once()
        labels = client.get("v1", "Node", "bare-c2")["metadata"]["labels"]
        assert consts.TORUS_COORDS_LABEL not in labels
