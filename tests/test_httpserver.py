"""FakeApiServer (kube/httpserver.py): the kube REST surface over real TCP.

Drives the HttpClient against the HTTP-served fake apiserver — the same
pairing bench.py measures — covering CRUD semantics, error mapping,
watch streams, and the full operator install→Ready flow over the wire.
Reference counterpart: e2e against a real apiserver
(tests/e2e/gpu_operator_test.go:104-170).
"""

import threading
import time

import pytest

from tpu_operator.kube import errors
from tpu_operator.kube.fake import FakeClient
from tpu_operator.kube.http_client import HttpClient
from tpu_operator.kube.httpserver import FakeApiServer
from tpu_operator.kube.objects import new_object

NS = "tpu-operator"


def wait_for(fn, timeout=30.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return False


@pytest.fixture()
def served():
    store = FakeClient()
    server = FakeApiServer(store).start()
    client = HttpClient(server.base_url, timeout=10.0)
    yield store, client
    server.stop()


class TestCrudOverHttp:
    def test_create_get_update_delete(self, served):
        _, client = served
        cm = new_object("v1", "ConfigMap", "cfg", NS, data={"a": "1"})
        created = client.create(cm)
        assert created["metadata"]["resourceVersion"]
        got = client.get("v1", "ConfigMap", "cfg", NS)
        assert got["data"] == {"a": "1"}
        got["data"]["a"] = "2"
        client.update(got)
        assert client.get("v1", "ConfigMap", "cfg", NS)["data"]["a"] == "2"
        client.delete("v1", "ConfigMap", "cfg", NS)
        with pytest.raises(errors.NotFound):
            client.get("v1", "ConfigMap", "cfg", NS)

    def test_error_mapping(self, served):
        _, client = served
        cm = new_object("v1", "ConfigMap", "cfg", NS)
        client.create(cm)
        with pytest.raises(errors.AlreadyExists):
            client.create(cm)
        stale = client.get("v1", "ConfigMap", "cfg", NS)
        client.update(client.get("v1", "ConfigMap", "cfg", NS))
        with pytest.raises(errors.Conflict):
            client.update(stale)
        with pytest.raises(errors.NotFound):
            client.get("v1", "ConfigMap", "missing", NS)
        with pytest.raises(errors.NotFound):
            client.delete("v1", "ConfigMap", "missing", NS)

    def test_list_with_label_selector(self, served):
        _, client = served
        client.create(new_object("v1", "ConfigMap", "a", NS, labels={"app": "x"}))
        client.create(new_object("v1", "ConfigMap", "b", NS, labels={"app": "y"}))
        names = {
            o["metadata"]["name"]
            for o in client.list("v1", "ConfigMap", NS, label_selector={"app": "x"})
        }
        assert names == {"a"}

    def test_update_status_subresource(self, served):
        _, client = served
        ds = new_object("apps/v1", "DaemonSet", "ds", NS, spec={"x": 1})
        client.create(ds)
        got = client.get("apps/v1", "DaemonSet", "ds", NS)
        got["status"] = {"numberReady": 3}
        client.update_status(got)
        assert client.get("apps/v1", "DaemonSet", "ds", NS)["status"]["numberReady"] == 3

    def test_eviction_respects_pdb(self, served):
        store, client = served
        pod = new_object("v1", "Pod", "p0", NS, labels={"app": "w"})
        store.create(pod)
        store.create(
            new_object(
                "policy/v1",
                "PodDisruptionBudget",
                "pdb",
                NS,
                spec={"selector": {"matchLabels": {"app": "w"}}, "minAvailable": 1},
            )
        )
        with pytest.raises(errors.TooManyRequests):
            client.evict("p0", NS)

    def test_cluster_scoped_node(self, served):
        _, client = served
        client.create(new_object("v1", "Node", "n0"))
        assert client.get("v1", "Node", "n0")["metadata"]["name"] == "n0"


class TestWatchOverHttp:
    def test_stream_replays_existing_state(self, served):
        """resourceVersion=0 semantics, pinned at the raw endpoint (no
        prior LIST, so HttpClient's own list-replay can't mask a broken
        server): an object created BEFORE the stream connects must arrive
        in the opening SYNC snapshot event. Losing it is unrecoverable —
        no resync timer exists; this exact race wedged the install flow
        once keep-alive made request setup fast enough to hit the gap."""
        import json as _json
        import urllib.request

        store, client = served
        store.create(new_object("v1", "ConfigMap", "pre-existing", NS))
        url = (
            client.base_url
            + f"/api/v1/namespaces/{NS}/configmaps?watch=true&resourceVersion=0"
        )
        with urllib.request.urlopen(url, timeout=10) as resp:
            event = _json.loads(resp.readline())
        assert event["type"] == "SYNC"
        names = [o["metadata"]["name"] for o in event["object"]["items"]]
        assert names == ["pre-existing"]

    def test_stream_with_stale_rv_gets_410_error_event(self, served):
        """The store keeps no event history, so a watch from an arbitrary
        nonzero rv CANNOT be served gap-free — streaming live events only
        would silently lose everything between that rv and now. A real
        apiserver answers with a Status 410 (Expired) ERROR event inside
        the stream, forcing the client to re-list; the fake must match or
        raw consumers diverge from kube semantics."""
        import json as _json
        import urllib.request

        store, client = served
        store.create(new_object("v1", "ConfigMap", "old", NS))
        url = (
            client.base_url
            + f"/api/v1/namespaces/{NS}/configmaps?watch=true&resourceVersion=99"
        )
        with urllib.request.urlopen(url, timeout=10) as resp:
            event = _json.loads(resp.readline())
        assert event["type"] == "ERROR"
        assert event["object"]["code"] == 410
        assert event["object"]["reason"] == "Expired"

    def test_watch_streams_events(self, served):
        store, client = served
        seen = []
        got_two = threading.Event()

        def handler(etype, obj):
            if etype == "SYNC":  # opening snapshot (empty here) — not an object
                return
            seen.append((etype, obj["metadata"]["name"]))
            if len(seen) >= 2:
                got_two.set()

        sub = client.watch("v1", "ConfigMap", handler, NS)
        # watch starts with a SYNC snapshot (empty) then streams live
        # events; give the stream a beat to connect before mutating
        time.sleep(0.3)
        store.create(new_object("v1", "ConfigMap", "w1", NS))
        store.delete("v1", "ConfigMap", "w1", NS)
        assert got_two.wait(10), f"saw only {seen}"
        sub.stop()
        assert ("ADDED", "w1") in seen
        assert ("DELETED", "w1") in seen


class TestApiserverRestart:
    def test_operator_survives_apiserver_restart(self):
        """Kill the apiserver mid-run and bring it back on the same port:
        pooled connections go stale (retried), watch streams drop and
        re-list, and the operator converges on state created while it was
        blind — the level-triggered recovery a real apiserver rollout
        exercises."""
        from tpu_operator.api.clusterpolicy import (
            CLUSTER_POLICY_API_VERSION,
            CLUSTER_POLICY_KIND,
            new_cluster_policy,
        )
        from tpu_operator.controllers.clusterpolicy_controller import (
            ClusterPolicyReconciler,
            setup_with_manager,
        )
        from tpu_operator.kube.manager import Manager
        from tpu_operator.kube.sim import ClusterSim, make_tpu_node

        store = FakeClient()
        for i in range(2):
            store.create(make_tpu_node(f"tpu-{i}", "tpu-v5-lite-podslice", "4x4"))
        server = FakeApiServer(store).start()
        port = server.httpd.server_address[1]
        client = HttpClient(server.base_url, timeout=5.0)
        sim = ClusterSim(store, ready_delay=0.05, tick=0.01).start()
        mgr = Manager(client, namespace=NS)
        setup_with_manager(mgr, ClusterPolicyReconciler(client, NS))
        mgr.start()
        try:
            client.create(new_cluster_policy())

            def ready():
                cp = store.get_or_none(
                    CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND, "cluster-policy"
                )
                return (cp or {}).get("status", {}).get("state") == "ready"

            assert wait_for(ready), "never Ready before the restart"

            server.stop()
            # mutate while the operator is blind: bump the libtpu version
            cp = store.get(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND, "cluster-policy")
            cp["spec"].setdefault("libtpu", {}).update(
                {"repository": "gcr.io/x", "image": "libtpu", "version": "post-outage"}
            )
            store.update(cp)
            time.sleep(1.0)
            server2 = FakeApiServer(store, port=port).start()
            try:
                def converged():
                    for ds in store.list("apps/v1", "DaemonSet", NS):
                        image = ds["spec"]["template"]["spec"]["containers"][0].get("image", "")
                        if "post-outage" in image:
                            return True
                    return False

                assert wait_for(
                    converged, timeout=40, interval=0.1
                ), "operator never reconciled the blind-window update"
            finally:
                server2.stop()
        finally:
            mgr.stop()
            sim.stop()
            try:
                server.stop()
            except Exception:  # noqa: BLE001 — already stopped
                pass


class TestInformerPhantomHeal:
    def test_reconnect_sync_drops_object_deleted_during_gap(self):
        """The advisor-r4 phantom scenario, end to end over the wire: an
        object deleted while the watch stream is down must leave the
        informer cache when the stream reconnects — the reconnect SYNC
        snapshot replaces the store (client-go Replace semantics). Before
        that fix the replay was ADDED-only and the deleted object stayed
        cached forever, feeding cached-read reconcilers a phantom."""
        from tpu_operator.kube.informer import Informer

        store = FakeClient()
        server = FakeApiServer(store).start()
        port = server.httpd.server_address[1]
        client = HttpClient(server.base_url, timeout=5.0)
        store.create(new_object("v1", "ConfigMap", "phantom", NS))
        store.create(new_object("v1", "ConfigMap", "survivor", NS))
        inf = Informer(client, "v1", "ConfigMap", NS)
        deleted = []

        def on_event(etype, old, new):
            if etype == "DELETED":
                deleted.append(new["metadata"]["name"])

        inf.add_handler(on_event)
        inf.start()
        server2 = None
        try:
            assert wait_for(lambda: len(inf.cached()) == 2), "informer never synced"
            server.stop()
            # delete while the operator is blind: no stream is connected,
            # so the DELETED event is lost for good
            store.delete("v1", "ConfigMap", "phantom", NS)
            time.sleep(1.0)
            server2 = FakeApiServer(store, port=port).start()
            assert wait_for(
                lambda: {o["metadata"]["name"] for o in inf.cached()} == {"survivor"},
                timeout=20,
            ), "phantom survived the reconnect SYNC"
            assert "phantom" in deleted
        finally:
            inf.stop()
            for s in (server, server2):
                try:
                    if s is not None:
                        s.stop()
                except Exception:  # noqa: BLE001 — already stopped
                    pass


class TestUpgradeDrillOverHttp:
    def test_rolling_upgrade_drill(self, served):
        """The full rolling-upgrade FSM walk (cordon → eviction parked by
        a PDB → relax → pod restart → validate → uncordon → done) against
        the apiserver over the wire — the same drill test_e2e_real.py runs
        against a real cluster when KUBECONFIG is supplied."""
        from drill import assert_drill_passed, run_upgrade_drill

        _, client = served
        obs = run_upgrade_drill(client, NS)
        assert_drill_passed(obs)


class TestOperatorOverHttp:
    def test_install_to_ready_over_http(self):
        """The bench.py http-transport flow: operator on HttpClient, fake
        apiserver over TCP, sim kubelets in-process."""
        import bench

        t = bench.bench_install_to_ready(nodes=2, transport="http")
        assert t < 60


class TestListPagination:
    """LIST chunking (kube limit/continue): the wire client pages through
    large result sets instead of materializing one giant response, and
    the server filters fieldSelector server-side instead of shipping the
    world for the client to discard."""

    def test_client_pages_through_large_lists(self, served, monkeypatch):
        from tpu_operator.kube import http_client as hc

        store, client = served
        for i in range(7):
            store.create(new_object("v1", "ConfigMap", f"cm-{i:02d}", NS))
        monkeypatch.setattr(hc, "LIST_PAGE_SIZE", 3)
        before = dict(client.request_counts)
        items = client.list("v1", "ConfigMap", NS)
        assert sorted(o["metadata"]["name"] for o in items) == [f"cm-{i:02d}" for i in range(7)]
        # 7 objects at page size 3 = 3 GET requests (3 + 3 + 1)
        assert client.request_counts["GET"] - before.get("GET", 0) == 3

    def test_continue_serves_first_page_snapshot_under_concurrent_writes(self, served):
        """kube's paged-list consistency contract: every page of one LIST
        is served from the FIRST page's snapshot — a concurrent create and
        delete mid-pagination are invisible until a fresh list (a real
        apiserver pins the pagination to page 1's resourceVersion; the
        old name-keyed live-view continuation diverged exactly here)."""
        import json as _json
        import urllib.parse as up
        import urllib.request

        store, client = served
        for i in (0, 2, 4, 6):
            store.create(new_object("v1", "ConfigMap", f"cm-{i}", NS))
        base = client.base_url + f"/api/v1/namespaces/{NS}/configmaps?limit=2"
        with urllib.request.urlopen(base, timeout=10) as resp:
            page1 = _json.loads(resp.read())
        cont = page1["metadata"]["continue"]
        assert [o["metadata"]["name"] for o in page1["items"]] == ["cm-0", "cm-2"]
        # mutate mid-pagination: neither write may affect later pages
        store.create(new_object("v1", "ConfigMap", "cm-3", NS))
        store.delete("v1", "ConfigMap", "cm-6", NS)
        with urllib.request.urlopen(base + "&continue=" + up.quote(cont), timeout=10) as resp:
            page2 = _json.loads(resp.read())
        assert [o["metadata"]["name"] for o in page2["items"]] == ["cm-4", "cm-6"]
        # a FRESH list sees the post-write world
        assert [o["metadata"]["name"] for o in store.list("v1", "ConfigMap", NS)] == [
            "cm-0", "cm-2", "cm-3", "cm-4",
        ]

    def test_unknown_continue_token_answers_410_and_pager_recovers(self, monkeypatch):
        """A stale/unknown continue token gets 410 Expired (kube answers a
        compacted snapshot the same way) and HttpClient's pager restarts
        the list from scratch rather than failing the caller."""
        import urllib.error
        import urllib.request

        from tpu_operator.kube import http_client as hc

        store = FakeClient()
        server = FakeApiServer(store).start()
        client = HttpClient(server.base_url, timeout=10.0)
        try:
            for i in range(3):
                store.create(new_object("v1", "ConfigMap", f"cm-{i}", NS))
            url = (
                server.base_url
                + f"/api/v1/namespaces/{NS}/configmaps?limit=2&continue=bogus"
            )
            with pytest.raises(urllib.error.HTTPError) as exc_info:
                urllib.request.urlopen(url, timeout=10)
            assert exc_info.value.code == 410
            # the typed error surfaces through the client request layer
            with pytest.raises(errors.Expired):
                client._request(
                    "GET",
                    f"/api/v1/namespaces/{NS}/configmaps",
                    query={"limit": "2", "continue": "bogus"},
                )
            # and the pager recovers: evict the parked snapshot between
            # page 1 and page 2, then list through the public API
            monkeypatch.setattr(hc, "LIST_PAGE_SIZE", 2)
            real_request = client._request
            calls = {"continues": 0}

            def request_with_eviction(method, path, body=None, query=None, **kw):
                if query and query.get("continue"):
                    calls["continues"] += 1
                    if calls["continues"] == 1:
                        with server._snapshots_lock:
                            server._list_snapshots.clear()
                return real_request(method, path, body=body, query=query, **kw)

            monkeypatch.setattr(client, "_request", request_with_eviction)
            items = client.list("v1", "ConfigMap", NS)
            assert [o["metadata"]["name"] for o in items] == ["cm-0", "cm-1", "cm-2"]
            assert calls["continues"] >= 2  # the expired token then the retry's
        finally:
            server.stop()

    def test_field_selector_filters_server_side(self, served):
        import json as _json
        import urllib.request

        store, client = served
        running = new_object("v1", "Pod", "p-running", NS)
        running["status"] = {"phase": "Running"}
        pending = new_object("v1", "Pod", "p-pending", NS)
        pending["status"] = {"phase": "Pending"}
        store.create(running)
        store.create(pending)
        url = (
            client.base_url
            + f"/api/v1/namespaces/{NS}/pods?fieldSelector=status.phase%3DRunning"
        )
        with urllib.request.urlopen(url, timeout=10) as resp:
            listed = _json.loads(resp.read())
        assert [o["metadata"]["name"] for o in listed["items"]] == ["p-running"]
        # and through the client API
        items = client.list("v1", "Pod", NS, field_selector={"status.phase": "Pending"})
        assert [o["metadata"]["name"] for o in items] == ["p-pending"]


class TestWatch410Recovery:
    def test_raw_stale_stream_surfaces_410_as_apierror(self, served):
        """A watch stream answered with the 410 ERROR event must raise
        ApiError inside _stream_watch — the signal the watch loop's
        recovery branch keys on."""

        class _Sub:
            active = True

        _, client = served
        with pytest.raises(errors.ApiError, match="410"):
            client._stream_watch(
                "v1", "ConfigMap", lambda et, obj: None, NS, _Sub(),
                resource_version="99",
            )

    def test_watch_loop_relists_after_410(self, served, monkeypatch):
        """The recovery loop itself: when the stream dies with the 410
        ApiError, _watch_loop must re-list and re-watch rather than
        wedge — the informer keeps observing objects created after the
        expiry. The first stream attempt is forced to fail exactly the
        way a real apiserver's Gone answer does."""
        store, client = served
        calls = {"n": 0}
        orig = client._stream_watch

        def flaky(*args, **kwargs):
            calls["n"] += 1
            if calls["n"] == 1:
                raise errors.ApiError(
                    "watch error event: {'code': 410, 'reason': 'Expired'}"
                )
            return orig(*args, **kwargs)

        monkeypatch.setattr(client, "_stream_watch", flaky)
        seen = []

        def handler(et, o):
            # consume snapshots like a cache consumer would: an object
            # created in the re-registration window arrives in the SYNC
            # replay, not as a live ADDED (racing which one is flaky)
            if et == "SYNC":
                seen.extend(("ADDED", i["metadata"]["name"]) for i in o.get("items", []))
                return
            seen.append((et, o["metadata"]["name"]))

        sub = client.watch("v1", "ConfigMap", handler)
        assert wait_for(lambda: calls["n"] >= 2, timeout=10), "no re-watch after 410"
        store.create(new_object("v1", "ConfigMap", "after", NS))
        assert wait_for(lambda: ("ADDED", "after") in seen, timeout=10)
        sub.stop()
