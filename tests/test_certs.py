"""Webhook certificate rotation (reference gap: the GPU operator defers
webhook cert lifecycle to helm/OLM/cert-manager; this operator owns it)."""

import base64
import json
import ssl
import urllib.request

import pytest

pytest.importorskip(
    "cryptography",
    reason="the webhook cert lifecycle mints real X.509 material",
)

from tpu_operator.certs import DAY, WebhookCertManager
from tpu_operator.kube.fake import FakeClient
from tpu_operator.kube.objects import new_object
from tpu_operator.webhook import WebhookServer

NS = "tpu-operator"


def make_vwc(client):
    client.create(
        new_object(
            "admissionregistration.k8s.io/v1",
            "ValidatingWebhookConfiguration",
            "tpu-operator",
            webhooks=[
                {"name": "clusterpolicy.tpu.google.com", "clientConfig": {}},
                {"name": "tpuslice.tpu.google.com", "clientConfig": {}},
            ],
        )
    )


class TestWebhookCertManager:
    def test_bootstrap_publishes_secret_and_cabundle(self, tmp_path):
        client = FakeClient()
        make_vwc(client)
        mgr = WebhookCertManager(client, NS, str(tmp_path))
        assert mgr.needs_rotation()
        assert mgr.ensure() is True
        # fresh cert: second pass is a no-op
        assert mgr.ensure() is False
        secret = client.get("v1", "Secret", "tpu-operator-webhook-tls", NS)
        assert base64.b64decode(secret["data"]["tls.crt"]).startswith(b"-----BEGIN CERTIFICATE")
        vwc = client.get(
            "admissionregistration.k8s.io/v1", "ValidatingWebhookConfiguration", "tpu-operator"
        )
        bundles = {h["clientConfig"]["caBundle"] for h in vwc["webhooks"]}
        assert len(bundles) == 1 and bundles.pop()

    def test_expiring_cert_rotates(self, tmp_path):
        client = FakeClient()
        make_vwc(client)
        mgr = WebhookCertManager(
            client, NS, str(tmp_path), validity_seconds=10, rotate_before_seconds=30
        )
        mgr.ensure()
        first_expiry = mgr.expires_at()
        # validity (10s) is inside the rotation window (30s) -> rotates again
        mgr.validity_seconds = 365 * DAY
        assert mgr.ensure() is True
        assert mgr.expires_at() > first_expiry

    def test_restart_adopts_published_secret(self, tmp_path):
        """A fresh replica/restarted pod must converge on the Secret's
        cert instead of minting a competing CA (which would race peers for
        the VWC caBundle)."""
        client = FakeClient()
        make_vwc(client)
        mgr1 = WebhookCertManager(client, NS, str(tmp_path / "a"))
        mgr1.ensure()
        secret = client.get("v1", "Secret", "tpu-operator-webhook-tls", NS)
        mgr2 = WebhookCertManager(client, NS, str(tmp_path / "b"))
        assert mgr2.ensure() is True
        with open(mgr2.cert_path, "rb") as f:
            assert f.read() == base64.b64decode(secret["data"]["tls.crt"])
        # adoption must not have re-published or re-patched anything
        assert client.get("v1", "Secret", "tpu-operator-webhook-tls", NS)["metadata"][
            "resourceVersion"
        ] == secret["metadata"]["resourceVersion"]

    def test_rotation_keeps_old_ca_in_bundle(self, tmp_path):
        """Apiservers cache the caBundle: through a rollover the bundle
        must contain the new AND the previous CA."""
        client = FakeClient()
        make_vwc(client)
        mgr = WebhookCertManager(client, NS, str(tmp_path))
        mgr.ensure()
        mgr.rotate_before_seconds = 366 * DAY
        assert mgr.ensure() is True
        vwc = client.get(
            "admissionregistration.k8s.io/v1", "ValidatingWebhookConfiguration", "tpu-operator"
        )
        bundle = base64.b64decode(vwc["webhooks"][0]["clientConfig"]["caBundle"])
        assert bundle.count(b"-----END CERTIFICATE-----") == 2

    def test_published_state_resynced_while_cert_fresh(self, tmp_path):
        """A wiped caBundle (helm upgrade) or deleted Secret must be
        repaired on the next loop pass, not at the expiry window."""
        client = FakeClient()
        make_vwc(client)
        mgr = WebhookCertManager(client, NS, str(tmp_path))
        mgr.ensure()
        vwc = client.get(
            "admissionregistration.k8s.io/v1", "ValidatingWebhookConfiguration", "tpu-operator"
        )
        for hook in vwc["webhooks"]:
            hook["clientConfig"]["caBundle"] = ""
        client.update(vwc)
        client.delete("v1", "Secret", "tpu-operator-webhook-tls", NS)
        assert mgr.ensure() is False  # cert fresh: no rotation...
        # ...but published state was reconciled from disk
        assert client.get("v1", "Secret", "tpu-operator-webhook-tls", NS)
        vwc = client.get(
            "admissionregistration.k8s.io/v1", "ValidatingWebhookConfiguration", "tpu-operator"
        )
        assert all(h["clientConfig"]["caBundle"] for h in vwc["webhooks"])
        # resync is idempotent: no churn when everything matches
        rv = vwc["metadata"]["resourceVersion"]
        mgr.ensure()
        assert client.get(
            "admissionregistration.k8s.io/v1", "ValidatingWebhookConfiguration", "tpu-operator"
        )["metadata"]["resourceVersion"] == rv

    def test_independently_minted_replicas_converge_without_thrash(self, tmp_path):
        """Two replicas that minted independently (both run the cert
        manager; there is no leader gate) must converge on the published
        Secret instead of rewriting it back and forth every pass."""
        client = FakeClient()
        make_vwc(client)
        mgr1 = WebhookCertManager(client, NS, str(tmp_path / "a"))
        mgr1.ensure()
        secret_rv = client.get("v1", "Secret", "tpu-operator-webhook-tls", NS)[
            "metadata"
        ]["resourceVersion"]
        # replica 2 minted while partitioned from the apiserver
        mgr2 = WebhookCertManager(None, NS, str(tmp_path / "b"))
        mgr2.ensure()
        mgr2.client = client
        # next pass: cert is fresh, sync must ADOPT the Secret, not publish
        assert mgr2.ensure() is False
        secret = client.get("v1", "Secret", "tpu-operator-webhook-tls", NS)
        assert secret["metadata"]["resourceVersion"] == secret_rv
        with open(mgr2.cert_path, "rb") as f:
            assert f.read() == base64.b64decode(secret["data"]["tls.crt"])
        # and replica 1 sees no drift on its next pass either
        assert mgr1.ensure() is False
        assert client.get("v1", "Secret", "tpu-operator-webhook-tls", NS)[
            "metadata"
        ]["resourceVersion"] == secret_rv

    def test_adoption_repairs_wiped_cabundle(self, tmp_path):
        """A replica adopting the Secret's cert must still re-assert the
        VWC caBundle: with failurePolicy=Fail, returning before that check
        leaves admissions broken until the next (hourly) pass."""
        client = FakeClient()
        make_vwc(client)
        mgr1 = WebhookCertManager(client, NS, str(tmp_path / "a"))
        mgr1.ensure()
        vwc = client.get(
            "admissionregistration.k8s.io/v1", "ValidatingWebhookConfiguration", "tpu-operator"
        )
        for hook in vwc["webhooks"]:
            hook["clientConfig"]["caBundle"] = ""  # helm upgrade reapplied it empty
        client.update(vwc)
        mgr2 = WebhookCertManager(client, NS, str(tmp_path / "b"))
        assert mgr2.ensure() is True  # adopted
        vwc = client.get(
            "admissionregistration.k8s.io/v1", "ValidatingWebhookConfiguration", "tpu-operator"
        )
        assert all(h["clientConfig"]["caBundle"] for h in vwc["webhooks"])

    def test_adopt_rejects_mismatched_key(self, tmp_path):
        client = FakeClient()
        make_vwc(client)
        mgr1 = WebhookCertManager(client, NS, str(tmp_path / "a"))
        mgr1.ensure()
        # corrupt the Secret: fresh cert, key from a different pair
        from tpu_operator import certs as certs_mod

        _, other_key = certs_mod.make_ca("other", certs_mod.DAY)
        secret = client.get("v1", "Secret", "tpu-operator-webhook-tls", NS)
        secret["data"]["tls.key"] = base64.b64encode(
            certs_mod._key_pem(other_key)
        ).decode()
        client.update(secret)
        mgr2 = WebhookCertManager(client, NS, str(tmp_path / "b"))
        assert mgr2._adopt_from_secret() is False  # mints fresh instead
        assert mgr2.ensure() is True

    def test_private_key_not_world_readable(self, tmp_path):
        import os
        import stat

        mgr = WebhookCertManager(None, NS, str(tmp_path))
        mgr.ensure()
        mode = stat.S_IMODE(os.stat(mgr.key_path).st_mode)
        assert mode == 0o600

    def test_rotation_does_not_drop_admissions(self, tmp_path):
        """The serving socket reloads the chain in place: requests verify
        against the old CA before rotation and the new CA after, with the
        server never restarting."""
        client = FakeClient()
        make_vwc(client)
        mgr = WebhookCertManager(client, NS, str(tmp_path))
        mgr.ensure()

        def ca_file(tag):
            vwc = client.get(
                "admissionregistration.k8s.io/v1", "ValidatingWebhookConfiguration", "tpu-operator"
            )
            path = tmp_path / f"ca-{tag}.pem"
            path.write_bytes(base64.b64decode(vwc["webhooks"][0]["clientConfig"]["caBundle"]))
            return str(path)

        server = WebhookServer(
            client, addr=("127.0.0.1", 0), cert_file=mgr.cert_path, key_file=mgr.key_path
        ).start()
        mgr.attach(server)
        try:
            host, port = server.address
            # SAN is the service DNS name; connect by IP but verify the
            # hostname the cert carries
            url = f"https://{host}:{port}"
            ca1 = ca_file("old")
            ctx1 = ssl.create_default_context(cafile=ca1)
            ctx1.check_hostname = False  # IP connect; chain still verified
            review = admission_post_with_ctx(url, ctx1)
            assert review["response"]["allowed"] is True

            # force rotation (pretend the cert is expiring)
            mgr.rotate_before_seconds = 366 * DAY
            assert mgr.ensure() is True
            ca2 = ca_file("new")
            assert open(ca1).read() != open(ca2).read()

            # the old CA no longer verifies the new chain... (urllib wraps
            # the handshake failure in URLError)
            try:
                admission_post_with_ctx(url, ctx1)
                raise AssertionError("old CA should not verify the rotated cert")
            except (ssl.SSLError, urllib.error.URLError) as e:
                reason = e.reason if isinstance(e, urllib.error.URLError) else e
                assert isinstance(reason, ssl.SSLError), reason
            # ...but the new bundle from the VWC does, with no restart
            ctx2 = ssl.create_default_context(cafile=ca2)
            ctx2.check_hostname = False
            review = admission_post_with_ctx(url, ctx2)
            assert review["response"]["allowed"] is True
        finally:
            server.stop()


def admission_post_with_ctx(url, ctx):
    review = {"request": {"uid": "u1", "operation": "CREATE", "object": {
        "apiVersion": "tpu.google.com/v1", "kind": "ClusterPolicy",
        "metadata": {"name": "cluster-policy"}, "spec": {}}}}
    req = urllib.request.Request(
        url + "/validate-clusterpolicy",
        data=json.dumps(review).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=10, context=ctx) as resp:
        return json.loads(resp.read())
