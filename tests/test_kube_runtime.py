"""Workqueue, informer, controller, leader election, manager."""

import threading
import time

from tpu_operator.kube.controller import Controller, Request, Result, generation_changed
from tpu_operator.kube.fake import FakeClient
from tpu_operator.kube.informer import Informer
from tpu_operator.kube.leader import LeaderElector
from tpu_operator.kube.manager import Manager
from tpu_operator.kube.objects import new_object
from tpu_operator.kube.queue import RateLimitingQueue


def test_queue_dedup():
    q = RateLimitingQueue()
    q.add("a")
    q.add("a")
    q.add("b")
    assert q.get(0.1) == "a"
    assert q.get(0.1) == "b"
    assert q.get(0.05) is None


def test_queue_dirty_requeue_while_processing():
    q = RateLimitingQueue()
    q.add("a")
    item = q.get(0.1)
    q.add("a")  # arrives while processing → dirty
    assert q.get(0.05) is None  # not ready until done
    q.done(item)
    assert q.get(0.1) == "a"


def test_queue_add_after():
    q = RateLimitingQueue()
    q.add_after("a", 0.1)
    t0 = time.monotonic()
    assert q.get(1.0) == "a"
    assert time.monotonic() - t0 >= 0.09


def test_queue_rate_limit_backoff_grows():
    q = RateLimitingQueue(base_delay=0.02, max_delay=1.0)
    t0 = time.monotonic()
    q.add_rate_limited("a")  # 0.02
    assert q.get(1.0) == "a"
    q.done("a")
    q.add_rate_limited("a")  # 0.04
    assert q.get(1.0) == "a"
    q.done("a")
    assert time.monotonic() - t0 >= 0.06
    q.forget("a")
    assert q._failures.get("a") is None


def test_informer_cache_and_handlers():
    client = FakeClient()
    client.create(new_object("v1", "Node", "n1"))
    inf = Informer(client, "v1", "Node")
    seen = []
    inf.add_handler(lambda t, old, new: seen.append((t, new["metadata"]["name"])))
    inf.start()
    assert ("ADDED", "n1") in seen
    client.create(new_object("v1", "Node", "n2"))
    assert ("ADDED", "n2") in seen
    assert {o["metadata"]["name"] for o in inf.cached()} == {"n1", "n2"}
    inf.stop()


def test_controller_reconciles_and_requeues():
    client = FakeClient()
    calls = []
    done = threading.Event()

    class Reconciler:
        def reconcile(self, req):
            calls.append(req)
            if len(calls) == 1:
                return Result(requeue_after=0.05)
            done.set()
            return Result()

    ctrl = Controller("test", Reconciler())
    inf = Informer(client, "v1", "ConfigMap")
    ctrl.watch(inf)
    ctrl.start()
    inf.start()
    client.create(new_object("v1", "ConfigMap", "cm", "default"))
    assert done.wait(2.0)
    assert calls[0] == Request(name="cm", namespace="default")
    ctrl.stop()
    inf.stop()


def test_generation_changed_predicate():
    old = new_object("v1", "ConfigMap", "x")
    old["metadata"]["generation"] = 1
    new = new_object("v1", "ConfigMap", "x")
    new["metadata"]["generation"] = 1
    assert not generation_changed("MODIFIED", old, new)
    new["metadata"]["generation"] = 2
    assert generation_changed("MODIFIED", old, new)
    assert generation_changed("ADDED", None, new)


def test_leader_election_single_winner():
    client = FakeClient()
    a = LeaderElector(client, namespace="ns", lease_duration=0.5, renew_interval=0.05)
    b = LeaderElector(client, namespace="ns", lease_duration=0.5, renew_interval=0.05)
    a.start()
    assert a.wait_for_leadership(2.0)
    b.start()
    time.sleep(0.2)
    assert not b.is_leader()
    a.stop()  # releases the lease
    assert b.wait_for_leadership(3.0)
    b.stop()


def test_manager_lifecycle():
    client = FakeClient()
    mgr = Manager(client, namespace="ns")
    inf = mgr.informer_for("v1", "Node")
    assert mgr.informer_for("v1", "Node") is inf  # shared
    hits = []

    class R:
        def reconcile(self, req):
            hits.append(req.name)
            return Result()

    ctrl = Controller("nodes", R())
    ctrl.watch(inf)
    mgr.add_controller(ctrl)
    with mgr:
        client.create(new_object("v1", "Node", "n1"))
        deadline = time.monotonic() + 2
        while "n1" not in hits and time.monotonic() < deadline:
            time.sleep(0.01)
    assert "n1" in hits
