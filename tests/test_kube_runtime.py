"""Workqueue, informer, controller, leader election, manager."""

import threading
import time

from tpu_operator.kube.controller import Controller, Request, Result, generation_changed
from tpu_operator.kube.fake import FakeClient
from tpu_operator.kube.informer import Informer
from tpu_operator.kube.leader import LeaderElector
from tpu_operator.kube.manager import Manager
from tpu_operator.kube.objects import new_object
from tpu_operator.kube.queue import RateLimitingQueue


def test_queue_dedup():
    q = RateLimitingQueue()
    q.add("a")
    q.add("a")
    q.add("b")
    assert q.get(0.1) == "a"
    assert q.get(0.1) == "b"
    assert q.get(0.05) is None


def test_queue_dirty_requeue_while_processing():
    q = RateLimitingQueue()
    q.add("a")
    item = q.get(0.1)
    q.add("a")  # arrives while processing → dirty
    assert q.get(0.05) is None  # not ready until done
    q.done(item)
    assert q.get(0.1) == "a"


def test_queue_add_after():
    q = RateLimitingQueue()
    q.add_after("a", 0.1)
    t0 = time.monotonic()
    assert q.get(1.0) == "a"
    assert time.monotonic() - t0 >= 0.09


def test_queue_rate_limit_backoff_grows():
    """With full jitter the delay is uniform(0, base*2^n): the failure
    count still grows the CAP exponentially, each delay stays under it,
    and forget() drops the failure record."""
    import random

    q = RateLimitingQueue(base_delay=0.02, max_delay=1.0, rng=random.Random(7))

    def scheduled_delay():
        # assert on the queue's own schedule, not on wall-clock wakeup
        # latency (a loaded CI runner adds tens of ms of scheduler slack)
        with q._lock:
            return q._delayed[0][0] - time.monotonic()

    q.add_rate_limited("a")  # cap 0.02
    assert scheduled_delay() <= 0.02
    assert q.get(1.0) == "a"
    q.done("a")
    q.add_rate_limited("a")  # cap 0.04
    assert scheduled_delay() <= 0.04
    assert q.get(1.0) == "a"
    q.done("a")
    assert q._failures.get("a") == 2  # the exponent kept growing
    q.forget("a")
    assert q._failures.get("a") is None


def test_queue_rate_limit_jitter_desynchronizes():
    """Thundering-herd protection: many items requeued at the same
    failure count must NOT all become ready at the same instant —
    asserted on the queue's OWN scheduled ready-times, so reverting
    add_rate_limited to a deterministic schedule fails this test."""
    import random

    q = RateLimitingQueue(base_delay=0.5, max_delay=3.0, rng=random.Random(11))
    for i in range(50):
        q.add_rate_limited(f"item-{i}")  # all at failure count 0 -> cap 0.5
    with q._lock:
        ready_times = [t for t, _, _ in q._delayed]
    assert len(ready_times) == 50
    assert len({round(t, 3) for t in ready_times}) > 25  # spread, not a spike
    assert max(ready_times) - min(ready_times) > 0.1  # genuinely desynchronized


def test_queue_failures_map_is_bounded():
    from tpu_operator.kube import queue as queue_mod

    q = RateLimitingQueue(base_delay=0.001, max_delay=0.001)
    for i in range(queue_mod._FAILURES_CAP + 100):
        q.add_rate_limited(f"item-{i}")
    assert len(q._failures) == queue_mod._FAILURES_CAP
    # the OLDEST entries were evicted, the newest survive
    assert "item-0" not in q._failures
    q.shutdown()
    assert not q._failures  # shutdown prunes everything


def test_informer_cache_and_handlers():
    client = FakeClient()
    client.create(new_object("v1", "Node", "n1"))
    inf = Informer(client, "v1", "Node")
    seen = []
    inf.add_handler(lambda t, old, new: seen.append((t, new["metadata"]["name"])))
    inf.start()
    assert ("ADDED", "n1") in seen
    client.create(new_object("v1", "Node", "n2"))
    assert ("ADDED", "n2") in seen
    assert {o["metadata"]["name"] for o in inf.cached()} == {"n1", "n2"}
    inf.stop()


def test_controller_reconciles_and_requeues():
    client = FakeClient()
    calls = []
    done = threading.Event()

    class Reconciler:
        def reconcile(self, req):
            calls.append(req)
            if len(calls) == 1:
                return Result(requeue_after=0.05)
            done.set()
            return Result()

    ctrl = Controller("test", Reconciler())
    inf = Informer(client, "v1", "ConfigMap")
    ctrl.watch(inf)
    ctrl.start()
    inf.start()
    client.create(new_object("v1", "ConfigMap", "cm", "default"))
    assert done.wait(2.0)
    assert calls[0] == Request(name="cm", namespace="default")
    ctrl.stop()
    inf.stop()


def test_generation_changed_predicate():
    old = new_object("v1", "ConfigMap", "x")
    old["metadata"]["generation"] = 1
    new = new_object("v1", "ConfigMap", "x")
    new["metadata"]["generation"] = 1
    assert not generation_changed("MODIFIED", old, new)
    new["metadata"]["generation"] = 2
    assert generation_changed("MODIFIED", old, new)
    assert generation_changed("ADDED", None, new)


def test_leader_election_single_winner():
    client = FakeClient()
    a = LeaderElector(client, namespace="ns", lease_duration=0.5, renew_interval=0.05)
    b = LeaderElector(client, namespace="ns", lease_duration=0.5, renew_interval=0.05)
    a.start()
    assert a.wait_for_leadership(2.0)
    b.start()
    time.sleep(0.2)
    assert not b.is_leader()
    a.stop()  # releases the lease
    assert b.wait_for_leadership(3.0)
    b.stop()


def test_manager_lifecycle():
    client = FakeClient()
    mgr = Manager(client, namespace="ns")
    inf = mgr.informer_for("v1", "Node")
    assert mgr.informer_for("v1", "Node") is inf  # shared
    hits = []

    class R:
        def reconcile(self, req):
            hits.append(req.name)
            return Result()

    ctrl = Controller("nodes", R())
    ctrl.watch(inf)
    mgr.add_controller(ctrl)
    with mgr:
        client.create(new_object("v1", "Node", "n1"))
        deadline = time.monotonic() + 2
        while "n1" not in hits and time.monotonic() < deadline:
            time.sleep(0.01)
    assert "n1" in hits


def test_informer_cache_isolated_from_consumer_mutation():
    client = FakeClient()
    inf = Informer(client, "v1", "Node")
    client.create(new_object("v1", "Node", "n1", labels={"a": "1"}))
    inf.start()
    cached = inf.cached()[0]
    cached["metadata"]["labels"]["a"] = "tampered"
    assert inf.cached()[0]["metadata"]["labels"]["a"] == "1"
    inf.stop()


def test_informer_rejects_stale_resource_version():
    client = FakeClient()
    inf = Informer(client, "v1", "Node")
    inf.start()
    fresh = new_object("v1", "Node", "n1")
    fresh["metadata"]["resourceVersion"] = "7"
    stale = new_object("v1", "Node", "n1")
    stale["metadata"]["resourceVersion"] = "5"
    inf._on_event("ADDED", fresh)
    inf._on_event("MODIFIED", stale)  # reordered delivery
    assert inf.cached()[0]["metadata"]["resourceVersion"] == "7"
    inf.stop()


def test_informer_sync_replaces_store_and_synthesizes_deletes():
    """client-go Reflector Replace semantics: a SYNC snapshot is
    authoritative — objects absent from it were deleted during a watch
    gap and must leave the cache (with a DELETED notification) or they
    linger as phantoms that cached-read reconcilers trust forever."""
    client = FakeClient()
    inf = Informer(client, "v1", "Node")
    seen = []
    inf.add_handler(lambda t, old, new: seen.append((t, new["metadata"]["name"])))
    inf.start()
    client.create(new_object("v1", "Node", "gone"))
    client.create(new_object("v1", "Node", "kept"))
    assert {o["metadata"]["name"] for o in inf.cached()} == {"gone", "kept"}
    seen.clear()
    kept = client.get("v1", "Node", "kept")
    fresh = new_object("v1", "Node", "fresh")
    fresh["metadata"]["resourceVersion"] = "99"
    inf._on_event("SYNC", {"apiVersion": "v1", "kind": "NodeList", "items": [kept, fresh]})
    assert {o["metadata"]["name"] for o in inf.cached()} == {"kept", "fresh"}
    assert ("DELETED", "gone") in seen
    assert ("ADDED", "fresh") in seen
    # the unchanged object must NOT renotify (same rv → dropped)
    assert not any(name == "kept" for _, name in seen)
    inf.stop()


def test_informer_start_unwinds_watch_on_list_failure():
    """If the snapshot replay inside watch() raises (its LIST fails),
    start() must leave no watch registered and stay startable — with
    _sub left set, every later start() would no-op, the informer would
    leak a live watch and never report synced (advisor r4). A second
    start() after the fault must succeed."""
    client = FakeClient()
    client.create(new_object("v1", "Node", "n1"))
    fail = {"on": True}
    real_list = client.list

    def flaky_list(*a, **kw):
        if fail["on"]:
            raise RuntimeError("apiserver hiccup")
        return real_list(*a, **kw)

    client.list = flaky_list
    inf = Informer(client, "v1", "Node")
    try:
        inf.start()
    except RuntimeError:
        pass
    assert inf._sub is None
    assert not inf.has_synced()
    assert client._watchers.get(("", "Node"), []) == []  # no leaked watch
    fail["on"] = False
    inf.start()
    assert inf.has_synced()
    assert {o["metadata"]["name"] for o in inf.cached()} == {"n1"}
    inf.stop()


def test_update_status_conflict_on_stale_resource_version():
    client = FakeClient()
    created = client.create(new_object("v1", "Node", "n1"))
    stale = dict(created)
    client.update(dict(created, spec={"x": 1}, metadata=dict(created["metadata"], resourceVersion=created["metadata"]["resourceVersion"])))
    import pytest as _pytest

    from tpu_operator.kube import errors as kerrors

    with _pytest.raises(kerrors.Conflict):
        client.update_status(dict(stale, status={"s": 1}))


def test_requeue_true_backoff_grows():
    import random

    q = RateLimitingQueue(base_delay=0.01, max_delay=1.0, rng=random.Random(3))

    class R:
        def __init__(self):
            self.calls = 0

        def reconcile(self, req):
            self.calls += 1
            return Result(requeue=True)

    r = R()
    ctrl = Controller("c", r)
    ctrl.queue = q
    ctrl.start()
    q.add(Request(name="x"))
    time.sleep(0.3)
    failures = q._failures.get(Request(name="x"), 0)  # before shutdown prunes
    ctrl.stop()
    # with growing (jittered) backoff the item cannot have run anywhere
    # near 300ms/10ms times — full jitter halves the expected delay, so
    # the upper bound is looser than the old deterministic schedule's
    assert 2 <= r.calls <= 20
    assert failures >= 2


def test_manager_informer_for_after_start_is_live():
    client = FakeClient()
    mgr = Manager(client, namespace="ns")
    hits = []
    with mgr:
        inf = mgr.informer_for("v1", "ConfigMap")  # wired after start
        inf.add_handler(lambda et, old, new: hits.append(new["metadata"]["name"]))
        client.create(new_object("v1", "ConfigMap", "late", "ns"))
        deadline = time.monotonic() + 2
        while "late" not in hits and time.monotonic() < deadline:
            time.sleep(0.01)
    assert "late" in hits


def test_leader_loss_invokes_on_stopped_leading():
    client = FakeClient()
    a = LeaderElector(client, namespace="ns", lease_duration=0.3, renew_interval=0.05)
    lost = []
    a.on_stopped_leading = lambda: lost.append(True)
    a.start()
    assert a.wait_for_leadership(2.0)
    # steal the lease out from under A
    lease = client.get("coordination.k8s.io/v1", "Lease", a.lease_name, "ns")
    lease["spec"]["holderIdentity"] = "intruder"
    lease["spec"]["renewTime"] = time.time() + 1000
    client.update(lease)
    deadline = time.monotonic() + 3
    while not lost and time.monotonic() < deadline:
        time.sleep(0.02)
    assert lost
    a.stop()


class TestCachedReadClient:
    def test_namespaced_read_reuses_namespaced_informer(self):
        """A cached read scoped to a namespace must reuse the namespaced
        informer the manager already runs — not shadow it with a new
        cluster-wide LIST+watch (the apiserver traffic cached reads exist
        to eliminate)."""
        from tpu_operator.kube.cached import CachedReadClient
        from tpu_operator.kube.fake import FakeClient
        from tpu_operator.kube.manager import Manager
        from tpu_operator.kube.objects import new_object

        store = FakeClient()
        store.create(new_object("v1", "Pod", "p1", "ns-a"))
        store.create(new_object("v1", "Pod", "p2", "ns-b"))
        mgr = Manager(store)
        mgr.informer_for("v1", "Pod", "ns-a")
        mgr.start()
        try:
            cached = CachedReadClient(store, mgr)
            assert [o["metadata"]["name"] for o in cached.list("v1", "Pod", "ns-a")] == ["p1"]
            assert set(mgr._informers) == {("v1", "Pod", "ns-a")}
            # a cluster-wide read cannot be served from the namespaced
            # cache; it cold-starts its own informer once
            assert len(cached.list("v1", "Pod")) == 2
            assert ("v1", "Pod", "") in mgr._informers
            # keyed get through the cluster-wide informer
            assert cached.get("v1", "Pod", "p2", "ns-b")["metadata"]["name"] == "p2"
        finally:
            mgr.stop()

    def test_read_before_manager_start_falls_through_live(self):
        from tpu_operator.kube.cached import CachedReadClient
        from tpu_operator.kube.fake import FakeClient
        from tpu_operator.kube.manager import Manager
        from tpu_operator.kube.objects import new_object

        store = FakeClient()
        store.create(new_object("v1", "ConfigMap", "c", "ns"))
        cached = CachedReadClient(store, Manager(store))
        assert cached.get("v1", "ConfigMap", "c", "ns")["metadata"]["name"] == "c"

    def test_apply_object_survives_stale_cache_create_race(self):
        """The cache-staleness contract in action: an object exists LIVE
        but the informer cache hasn't seen it yet (watch delivery in
        flight). apply_object's create hits AlreadyExists and must fall
        back to a live read + rv-guarded update instead of failing the
        whole state sync until the cache catches up."""
        from tpu_operator.kube.cached import CachedReadClient
        from tpu_operator.kube.fake import FakeClient
        from tpu_operator.kube.manager import Manager
        from tpu_operator.kube.objects import new_object
        from tpu_operator.state.skel import StateSkel

        store = FakeClient()
        mgr = Manager(store)
        mgr.start()
        try:
            cached = CachedReadClient(store, mgr)
            # warm the ConfigMap informer, THEN create behind its back by
            # suppressing event delivery: simplest faithful simulation is
            # creating under a key the informer will dedup as stale —
            # instead, create directly and drop the cache entry
            cached.list("v1", "ConfigMap")
            live = new_object("v1", "ConfigMap", "raced", "ns", data={"v": "live"})
            store.create(live)
            informer = mgr.informer_peek("v1", "ConfigMap", None)
            with informer._lock:
                informer._cache.clear()  # cache lags: object invisible
            desired = new_object("v1", "ConfigMap", "raced", "ns", data={"v": "desired"})
            skel = StateSkel.__new__(StateSkel)
            skel.name = "test-state"
            skel._decorate(desired, None)  # stamp the last-applied hash
            skel.apply_object(cached, desired)
            got = store.get("v1", "ConfigMap", "raced", "ns")
            assert got["data"]["v"] == "desired"
        finally:
            mgr.stop()


def test_lazy_informer_start_racing_stop_leaks_no_watch(monkeypatch):
    """The lock-free informer_for starts a lazily-created informer
    OUTSIDE the manager lifecycle lock (so a slow cold LIST cannot
    block stop()). The cost is a race: manager stop landing between
    registration and start. The informer's own lifecycle guard must
    win that race — no watch subscription may survive the stop."""
    from tpu_operator.kube import manager as manager_mod

    entered = threading.Event()
    release = threading.Event()

    class SlowStartInformer(Informer):
        def start(self):
            entered.set()
            release.wait(10)  # hold exactly the race window open
            super().start()

    monkeypatch.setattr(manager_mod, "Informer", SlowStartInformer)
    store = FakeClient()
    mgr = Manager(store)
    mgr.start()
    t = threading.Thread(target=lambda: mgr.informer_for("v1", "Pod"), daemon=True)
    t.start()
    assert entered.wait(10)
    mgr.stop()  # lands while the lazy start is parked pre-subscription
    release.set()
    t.join(10)
    assert not t.is_alive(), "lazy start deadlocked against manager stop"
    informer = mgr.informer_peek("v1", "Pod")
    assert informer is not None and informer._stopped
    live = [sub for subs in store._watchers.values() for sub in subs]
    assert not live, f"watch subscriptions leaked past stop: {live}"
