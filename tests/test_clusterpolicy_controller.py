"""ClusterPolicy controller tests (reference analogs:
controllers/state_manager_test.go, clusterpolicy_controller behavior,
and the bash e2e's install→Ready→update→disable flow)."""

import time

from tpu_operator import consts
from tpu_operator.api.clusterpolicy import (
    CLUSTER_POLICY_API_VERSION,
    CLUSTER_POLICY_KIND,
    new_cluster_policy,
)
from tpu_operator.controllers.clusterpolicy_controller import (
    ClusterPolicyReconciler,
    setup_with_manager,
)
from tpu_operator.kube.controller import Request
from tpu_operator.kube.fake import FakeClient
from tpu_operator.kube.manager import Manager
from tpu_operator.kube.objects import new_object
from tpu_operator.kube.sim import ClusterSim, make_tpu_node

NS = "tpu-operator"


def get_cp(client, name="cluster-policy"):
    return client.get(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND, name)


def wait_for(fn, timeout=10.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return False


class TestReconcileDirect:
    """Single reconcile calls, no manager (fake-client unit style)."""

    def test_no_tpu_nodes_reaches_ready_with_poll_requeue(self):
        client = FakeClient()
        client.create(new_object("v1", "Node", "cpu-0"))
        client.create(new_cluster_policy())
        r = ClusterPolicyReconciler(client, NS)
        result = r.reconcile(Request(name="cluster-policy"))
        assert result.requeue_after == consts.REQUEUE_NO_TPU_NODES_SECONDS
        cp = get_cp(client)
        assert cp["status"]["state"] == "ready"
        reasons = {c["type"]: c["reason"] for c in cp["status"]["conditions"]}
        assert reasons["Ready"] == "NoTPUNodes"
        # no gated operand daemonsets created — only the discovery
        # bootstrap, which by design deploys before any node is recognized
        dses = client.list("apps/v1", "DaemonSet", NS)
        assert [d["metadata"]["name"] for d in dses] == ["tpu-node-discovery"]

    def test_tpu_nodes_get_labelled(self):
        client = FakeClient()
        client.create(make_tpu_node("tpu-0"))
        client.create(new_object("v1", "Node", "cpu-0"))
        client.create(new_cluster_policy())
        r = ClusterPolicyReconciler(client, NS)
        r.reconcile(Request(name="cluster-policy"))
        labels = client.get("v1", "Node", "tpu-0")["metadata"]["labels"]
        assert labels[consts.TPU_PRESENT_LABEL] == "true"
        assert labels[consts.TPU_WORKLOAD_CONFIG_LABEL] == "container"
        for op in ("libtpu", "device-plugin", "tfd", "slice-manager",
                   "metrics-exporter", "node-status-exporter", "operator-validation",
                   "health-monitor"):
            assert labels[consts.COMMON_DEPLOY_LABEL_PREFIX + op] == "true", op
        cpu_labels = client.get("v1", "Node", "cpu-0")["metadata"].get("labels", {})
        assert consts.TPU_PRESENT_LABEL not in cpu_labels

    def test_disabled_operand_label_removed_and_ds_deleted(self):
        client = FakeClient()
        client.create(make_tpu_node("tpu-0"))
        client.create(new_cluster_policy())
        r = ClusterPolicyReconciler(client, NS)
        r.reconcile(Request(name="cluster-policy"))
        assert client.get("apps/v1", "DaemonSet", "tpu-metrics-exporter", NS)
        cp = get_cp(client)
        cp["spec"]["metricsExporter"] = {"enabled": False}
        client.update(cp)
        r.reconcile(Request(name="cluster-policy"))
        assert client.get_or_none("apps/v1", "DaemonSet", "tpu-metrics-exporter", NS) is None
        labels = client.get("v1", "Node", "tpu-0")["metadata"]["labels"]
        assert consts.COMMON_DEPLOY_LABEL_PREFIX + "metrics-exporter" not in labels

    def test_node_losing_tpu_is_stripped(self):
        client = FakeClient()
        client.create(make_tpu_node("tpu-0"))
        client.create(new_cluster_policy())
        r = ClusterPolicyReconciler(client, NS)
        r.reconcile(Request(name="cluster-policy"))
        node = client.get("v1", "Node", "tpu-0")
        del node["metadata"]["labels"]["cloud.google.com/gke-tpu-accelerator"]
        client.update(node)
        r.reconcile(Request(name="cluster-policy"))
        labels = client.get("v1", "Node", "tpu-0")["metadata"]["labels"]
        assert consts.TPU_PRESENT_LABEL not in labels
        assert not any(k.startswith(consts.COMMON_DEPLOY_LABEL_PREFIX) for k in labels)

    def test_singleton_guard_marks_newer_cr_ignored(self):
        client = FakeClient()
        client.create(new_cluster_policy("first"))
        time.sleep(1.1)  # creationTimestamp has 1s resolution
        client.create(new_cluster_policy("second"))
        r = ClusterPolicyReconciler(client, NS)
        r.reconcile(Request(name="second"))
        assert get_cp(client, "second")["status"]["state"] == "ignored"
        r.reconcile(Request(name="first"))
        assert get_cp(client, "first")["status"]["state"] in ("ready", "notReady")

    def test_workload_config_opt_out_blocks_deploy_labels(self):
        client = FakeClient()
        node = make_tpu_node("tpu-0")
        node["metadata"]["labels"][consts.TPU_WORKLOAD_CONFIG_LABEL] = "none"
        client.create(node)
        client.create(new_cluster_policy())
        r = ClusterPolicyReconciler(client, NS)
        r.reconcile(Request(name="cluster-policy"))
        labels = client.get("v1", "Node", "tpu-0")["metadata"]["labels"]
        assert labels[consts.TPU_WORKLOAD_CONFIG_LABEL] == "none"  # preserved
        assert not any(k.startswith(consts.COMMON_DEPLOY_LABEL_PREFIX) for k in labels)


class TestEndToEnd:
    """Full manager + sim: install → Ready (BASELINE config 1/2 shape)."""

    def test_install_to_ready_with_sim(self):
        client = FakeClient()
        for i in range(4):  # a v5e-16 slice: 4 hosts
            client.create(make_tpu_node(f"tpu-{i}", "tpu-v5-lite-podslice", "4x4"))
        sim = ClusterSim(client, ready_delay=0.1).start()
        mgr = Manager(client, namespace=NS)
        reconciler = ClusterPolicyReconciler(client, NS)
        setup_with_manager(mgr, reconciler)
        try:
            mgr.start()
            client.create(new_cluster_policy())

            def settled():
                if get_cp(client).get("status", {}).get("state") != "ready":
                    return False
                dses = client.list("apps/v1", "DaemonSet", NS)
                # the autotuner schedules only onto controller-elected
                # nodes — none here, so its desired count is 0
                return len(dses) == 11 and all(
                    ds.get("status", {}).get("desiredNumberScheduled")
                    == (0 if ds["metadata"]["name"] in ("tpu-autotuner", "tpu-compile-cache") else 4)
                    for ds in dses
                ) and all(
                    ds["status"].get("numberAvailable") == 4
                    for ds in dses
                    if ds["metadata"]["name"] not in ("tpu-autotuner", "tpu-compile-cache")
                )

            assert wait_for(settled, timeout=15), get_cp(client).get("status")
            # sim created operand pods on every node
            pods = client.list("v1", "Pod", NS)
            assert len(pods) == 36  # 9 per-node DaemonSets x 4 nodes
        finally:
            mgr.stop()
            sim.stop()

    def test_new_tpu_node_triggers_relabel_via_watch(self):
        client = FakeClient()
        sim = ClusterSim(client, ready_delay=0.0).start()
        mgr = Manager(client, namespace=NS)
        reconciler = ClusterPolicyReconciler(client, NS)
        setup_with_manager(mgr, reconciler)
        try:
            mgr.start()
            client.create(new_cluster_policy())
            assert wait_for(lambda: get_cp(client).get("status", {}).get("state") == "ready", timeout=10)
            # no TPU nodes yet -> only the discovery bootstrap deploys
            # (it exists precisely to find TPU nodes; every gated operand
            # waits for recognition)
            dses = client.list("apps/v1", "DaemonSet", NS)
            assert [d["metadata"]["name"] for d in dses] == ["tpu-node-discovery"]
            client.create(make_tpu_node("tpu-late"))
            assert wait_for(
                lambda: client.get("v1", "Node", "tpu-late")["metadata"]["labels"].get(consts.TPU_PRESENT_LABEL)
                == "true",
                timeout=10,
            )
            assert wait_for(lambda: len(client.list("apps/v1", "DaemonSet", NS)) == 11, timeout=10)
        finally:
            mgr.stop()
            sim.stop()


class TestPSALabels:
    def test_enable_then_disable_reverts_only_our_labels(self):
        client = FakeClient()
        client.create(new_object("v1", "Namespace", NS))
        client.create(make_tpu_node("tpu-0"))
        client.create(new_cluster_policy(spec={"psa": {"enabled": True}}))
        r = ClusterPolicyReconciler(client, NS)
        r.reconcile(Request(name="cluster-policy"))
        ns = client.get("v1", "Namespace", NS)
        assert ns["metadata"]["labels"]["pod-security.kubernetes.io/enforce"] == "privileged"
        # disable -> our labels removed
        cp = client.get(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND, "cluster-policy")
        cp["spec"]["psa"] = {"enabled": False}
        client.update(cp)
        r.reconcile(Request(name="cluster-policy"))
        ns = client.get("v1", "Namespace", NS)
        assert "pod-security.kubernetes.io/enforce" not in ns["metadata"].get("labels", {})

    def test_admin_set_labels_never_touched(self):
        client = FakeClient()
        ns_obj = new_object("v1", "Namespace", NS,
                            labels={"pod-security.kubernetes.io/enforce": "baseline"})
        client.create(ns_obj)
        client.create(make_tpu_node("tpu-0"))
        client.create(new_cluster_policy())  # psa disabled by default
        r = ClusterPolicyReconciler(client, NS)
        r.reconcile(Request(name="cluster-policy"))
        ns = client.get("v1", "Namespace", NS)
        assert ns["metadata"]["labels"]["pod-security.kubernetes.io/enforce"] == "baseline"
