"""Operand agent + entrypoint + chart tests."""

import io
import sys

import pytest
import yaml

from tpu_operator import consts
from tpu_operator.agents.metrics_exporter_agent import MetricsExporterAgent
from tpu_operator.agents.slice_manager_agent import SliceManagerAgent, WORKER_ID_LABEL
from tpu_operator.agents.tfd_agent import TFDAgent
from tpu_operator.chart import render_chart
from tpu_operator.cmd import tpuop_cfg
from tpu_operator.kube.fake import FakeClient
from tpu_operator.kube.sim import make_tpu_node
from tpu_operator.native import tpuinfo

NS = "tpu-operator"


class TestTFDAgent:
    def test_publishes_labels(self):
        client = FakeClient()
        client.create(make_tpu_node("tpu-0", "tpu-v5-lite-podslice", "4x4"))
        agent = TFDAgent(client, "tpu-0")
        assert agent.apply_once() is True
        labels = client.get("v1", "Node", "tpu-0")["metadata"]["labels"]
        assert labels[consts.TFD_ACCELERATOR_TYPE_LABEL] == "tpu-v5-lite-podslice"
        assert labels[consts.TFD_TOPOLOGY_LABEL] == "4x4"
        assert labels[consts.TFD_SLICE_HOSTS_LABEL] == "4"
        assert labels[consts.TFD_TPU_GENERATION_LABEL] == "v5e"
        # second pass: no change
        assert agent.apply_once() is False

    def test_removes_labels_when_tpu_gone(self, tmp_path, monkeypatch):
        # pin the device probe to an empty inventory: "TPU gone" must mean
        # no GKE label AND no local hardware, or tfd's own published
        # labels would keep the node looking like a TPU forever (the
        # tpu_info bootstrap fallback reads them)
        (tmp_path / "dev").mkdir()
        monkeypatch.setenv("TPUINFO_SCAN_ROOT", str(tmp_path))
        client = FakeClient()
        client.create(make_tpu_node("tpu-0"))
        agent = TFDAgent(client, "tpu-0")
        agent.apply_once()
        node = client.get("v1", "Node", "tpu-0")
        del node["metadata"]["labels"][consts.GKE_TPU_ACCELERATOR_LABEL]
        client.update(node)
        assert agent.apply_once() is True
        labels = client.get("v1", "Node", "tpu-0")["metadata"]["labels"]
        assert not any(k in labels for k in consts.TFD_LABELS)

    def test_keeps_discovery_labels_on_selfmanaged_node(self, tmp_path, monkeypatch):
        """Self-managed regime: no GKE labels, but hardware is present and
        the node-discovery bootstrap published the base labels. tfd must
        enrich (slice-hosts, generation), never strip."""
        (tmp_path / "dev").mkdir()
        for i in range(4):
            (tmp_path / "dev" / f"accel{i}").touch()
        monkeypatch.setenv("TPUINFO_SCAN_ROOT", str(tmp_path))
        from tpu_operator.kube.sim import make_bare_node

        client = FakeClient()
        client.create(
            make_bare_node(
                "bare-0",
                extra_labels={
                    consts.TFD_ACCELERATOR_TYPE_LABEL: "tpu-v5-lite-podslice",
                    consts.TFD_TOPOLOGY_LABEL: "4x4",
                },
            )
        )
        assert TFDAgent(client, "bare-0").apply_once() is True
        labels = client.get("v1", "Node", "bare-0")["metadata"]["labels"]
        assert labels[consts.TFD_ACCELERATOR_TYPE_LABEL] == "tpu-v5-lite-podslice"
        assert labels[consts.TFD_SLICE_HOSTS_LABEL] == "4"
        assert labels[consts.TFD_TPU_GENERATION_LABEL] == "v5e"
        assert labels[consts.TFD_CHIPS_PER_NODE_LABEL] == "4"


class TestSliceManagerAgent:
    def seed(self, client, multihost=True):
        topo = "4x4" if multihost else "2x2"
        for i in range(4 if multihost else 1):
            node = make_tpu_node(f"v5e-{i}", "tpu-v5-lite-podslice", topo, nodepool="pool-a")
            node["metadata"]["labels"][consts.TPU_PRESENT_LABEL] = "true"
            client.create(node)

    def test_psum_floor_env_reaches_gang_workers(self, monkeypatch):
        """The agent-side hop of the ICI-floor chain: MIN_PSUM_GBPS_PER_CHIP
        read from the environment must land in every COMPONENT=slice gang
        worker pod (spec.validator.minPsumGbpsPerChip → slice-manager DS
        env → agent → worker pods)."""
        from tpu_operator.agents.slice_manager_agent import agent_from_env

        client = FakeClient()
        self.seed(client)
        monkeypatch.setenv("MIN_PSUM_GBPS_PER_CHIP", "37.0")
        monkeypatch.setenv(consts.OPERATOR_NAMESPACE_ENV, NS)
        agent = agent_from_env(client)
        names = agent.reconcile_once()
        pods = client.list("v1", "Pod", NS, label_selector={"app": "tpu-slice-worker"})
        assert len(pods) == 4
        for pod in pods:
            env = {e["name"]: e.get("value") for e in pod["spec"]["containers"][0]["env"]}
            assert env["MIN_PSUM_GBPS_PER_CHIP"] == "37.0", pod["metadata"]["name"]
        # and without the env, the floor is absent — not an empty string
        monkeypatch.delenv("MIN_PSUM_GBPS_PER_CHIP")
        client2 = FakeClient()
        self.seed(client2)
        agent_from_env(client2).reconcile_once()
        for pod in client2.list("v1", "Pod", NS, label_selector={"app": "tpu-slice-worker"}):
            names_set = {e["name"] for e in pod["spec"]["containers"][0]["env"]}
            assert "MIN_PSUM_GBPS_PER_CHIP" not in names_set

    def test_creates_gang_plumbing(self):
        client = FakeClient()
        self.seed(client)
        agent = SliceManagerAgent(client, NS)
        names = agent.reconcile_once()
        assert len(names) == 1
        svc = client.get("v1", "Service", names[0], NS)
        assert svc["spec"]["clusterIP"] == "None"
        cm = client.get("v1", "ConfigMap", f"{names[0]}-gang", NS)
        hosts = cm["data"]["TPU_WORKER_HOSTNAMES"].split(",")
        assert len(hosts) == 4 and hosts[0].startswith(names[0] + "-0.")
        assert cm["data"]["TPU_TOPOLOGY"] == "4x4"
        for i in range(4):
            assert client.get("v1", "Node", f"v5e-{i}")["metadata"]["labels"][WORKER_ID_LABEL] == str(i)

    def test_single_host_pool_skipped(self):
        client = FakeClient()
        self.seed(client, multihost=False)
        agent = SliceManagerAgent(client, NS)
        assert agent.reconcile_once() == []

    def test_multislice_coordinator_env(self):
        client = FakeClient()
        self.seed(client)
        agent = SliceManagerAgent(client, NS, multi_slice=True, coordinator_port=9000)
        names = agent.reconcile_once()
        cm = client.get("v1", "ConfigMap", f"{names[0]}-gang", NS)
        assert cm["data"]["MEGASCALE_COORDINATOR_ADDRESS"].endswith(":9000")
        assert cm["data"]["MEGASCALE_NUM_SLICES"] == "1"

    def test_stale_cleanup(self):
        client = FakeClient()
        self.seed(client)
        agent = SliceManagerAgent(client, NS)
        names = agent.reconcile_once()
        assert client.list("v1", "Pod", NS) != []
        for i in range(4):
            client.delete("v1", "Node", f"v5e-{i}")
        agent.reconcile_once()
        assert client.get_or_none("v1", "Service", names[0], NS) is None
        assert client.get_or_none("v1", "ConfigMap", f"{names[0]}-gang", NS) is None
        assert client.list("v1", "Pod", NS) == []

    def test_gang_pods_fulfil_hostnames_contract(self):
        """Every TPU_WORKER_HOSTNAMES entry must resolve: a pod exists whose
        hostname/subdomain produce exactly that DNS name via the headless
        Service (the contract workloads/distributed.py consumes)."""
        client = FakeClient()
        self.seed(client)
        agent = SliceManagerAgent(client, NS, validator_image="img:v1")
        names = agent.reconcile_once()
        cm = client.get("v1", "ConfigMap", f"{names[0]}-gang", NS)
        hostnames = cm["data"]["TPU_WORKER_HOSTNAMES"].split(",")
        assert len(hostnames) == 4
        pods = {p["metadata"]["name"]: p for p in client.list("v1", "Pod", NS)}
        assert len(pods) == 4
        for entry in hostnames:
            host, svc, ns, suffix = entry.split(".")
            assert (ns, suffix) == (NS, "svc")
            pod = pods[host]
            assert pod["spec"]["hostname"] == host
            assert pod["spec"]["subdomain"] == svc
            # the headless Service must select this pod
            service = client.get("v1", "Service", svc, NS)
            for k, v in service["spec"]["selector"].items():
                assert pod["metadata"]["labels"].get(k) == v

    def test_gang_pod_shape(self):
        """Worker pods go through the scheduler (hostname nodeSelector +
        TPU limit, not nodeName), run COMPONENT=slice, and mount the gang
        env (reference: Plugin.runWorkload validator/main.go:941-1028)."""
        client = FakeClient()
        self.seed(client)
        agent = SliceManagerAgent(client, NS, validator_image="img:v1")
        names = agent.reconcile_once()
        pod = client.get("v1", "Pod", f"{names[0]}-0", NS)
        spec = pod["spec"]
        assert "nodeName" not in spec
        assert spec["nodeSelector"] == {"kubernetes.io/hostname": "v5e-0"}
        ctr = spec["containers"][0]
        assert ctr["image"] == "img:v1"
        env = {e["name"]: e.get("value") for e in ctr["env"]}
        assert env["COMPONENT"] == "slice"
        assert env["TPU_WORKER_ID"] == "0"
        assert ctr["envFrom"][0]["configMapRef"]["name"] == f"{names[0]}-gang"
        assert ctr["resources"]["limits"][consts.TPU_RESOURCE_NAME] == "4"

    def test_gang_pod_recreated_on_spec_change(self):
        client = FakeClient()
        self.seed(client)
        agent = SliceManagerAgent(client, NS, validator_image="img:v1")
        names = agent.reconcile_once()
        pod_name = f"{names[0]}-0"
        first = client.get("v1", "Pod", pod_name, NS)
        agent.reconcile_once()  # no change -> no churn
        assert client.get("v1", "Pod", pod_name, NS)["metadata"].get("resourceVersion") == first[
            "metadata"
        ].get("resourceVersion")
        agent.validator_image = "img:v2"
        agent.reconcile_once()
        assert (
            client.get("v1", "Pod", pod_name, NS)["spec"]["containers"][0]["image"] == "img:v2"
        )

    def test_multislice_coordinator_service_created(self):
        """The MEGASCALE_COORDINATOR_ADDRESS must point at a Service that
        exists and selects slice 0's worker-0 pod (round-1 gap: the
        address was a dangling string)."""
        client = FakeClient()
        self.seed(client)
        agent = SliceManagerAgent(client, NS, multi_slice=True, coordinator_port=9000)
        names = agent.reconcile_once()
        cm = client.get("v1", "ConfigMap", f"{names[0]}-gang", NS)
        addr = cm["data"]["MEGASCALE_COORDINATOR_ADDRESS"]
        host, port = addr.rsplit(":", 1)
        assert port == "9000"
        svc_name, ns, suffix = host.split(".")
        assert (ns, suffix) == (NS, "svc")
        svc = client.get("v1", "Service", svc_name, NS)
        worker0 = client.get("v1", "Pod", f"{names[0]}-0", NS)
        for k, v in svc["spec"]["selector"].items():
            assert worker0["metadata"]["labels"].get(k) == v
        # single-slice mode must not leave a coordinator Service behind
        agent.multi_slice = False
        agent.reconcile_once()
        assert client.get_or_none("v1", "Service", svc_name, NS) is None

    def test_gang_objects_owned_by_manager_daemonset(self):
        """Gang Services/ConfigMaps/pods carry an ownerReference to the
        slice-manager DaemonSet so operator uninstall cascades instead of
        leaking them."""
        from tpu_operator.kube.objects import new_object

        client = FakeClient()
        self.seed(client)
        ds = client.create(
            new_object("apps/v1", "DaemonSet", "tpu-slice-manager", NS, spec={})
        )
        agent = SliceManagerAgent(client, NS)
        names = agent.reconcile_once()
        for kind, name in (
            ("Service", names[0]),
            ("ConfigMap", f"{names[0]}-gang"),
            ("Pod", f"{names[0]}-0"),
        ):
            refs = client.get("v1", kind, name, NS)["metadata"]["ownerReferences"]
            assert refs[0]["uid"] == ds["metadata"]["uid"], (kind, name)
        client.delete("apps/v1", "DaemonSet", "tpu-slice-manager", NS)
        assert client.list("v1", "Pod", NS) == []
        assert client.get_or_none("v1", "Service", names[0], NS) is None

    def test_long_pool_names_never_collide(self):
        from tpu_operator.nodeinfo import TPUNodeInfo
        from tpu_operator.nodepool import NodePool

        def pool(suffix):
            name = "tpu-v5-lite-podslice-4-4-" + "verylongnodepoolname" * 3 + suffix
            info = TPUNodeInfo(
                node_name="n", accelerator_type="tpu-v5-lite-podslice", topology="4x4",
                nodepool=name, chips_in_slice=16, chips_per_node=4, slice_hosts=4,
                generation="v5e",
            )
            return NodePool(
                name=name, accelerator_type=info.accelerator_type, topology="4x4",
                gke_nodepool=name, node_names=["n"], info=info,
            )

        a = SliceManagerAgent._slice_name(pool("a"))
        b = SliceManagerAgent._slice_name(pool("b"))
        assert a != b
        assert max(len(a), len(b)) <= 58  # room for "-<worker>" within 63

    def test_slice_component_runs_on_cpu_mesh(self):
        """In-process run of the COMPONENT=slice payload on the forced
        8-device CPU mesh (single-host gang env: no TPU_WORKER_HOSTNAMES,
        so jax.distributed is a no-op and the psum runs locally)."""
        from tpu_operator.validator import main as vmain

        ctx = vmain.Context(validation_dir=None)
        import tempfile

        with tempfile.TemporaryDirectory() as d:
            ctx.validation_dir = d
            report = vmain.run_component("slice", ctx, max_attempts=1)
        assert report["hosts"] == 1
        assert report["ring_attention"]["max_abs_err"] < 2e-2
        assert report["pipeline"]["ok"] and report["pipeline"]["stages"] == 8


class TestMetricsExporterAgent:
    def test_collects_chips_and_hbm(self):
        agent = MetricsExporterAgent(node_name="tpu-0")
        agent.collect_device_stats()
        values = {m.name: {tuple(sorted(s.labels.items())): s.value for s in m.samples}
                  for m in agent.registry.collect()}
        assert values["tpu_exporter_chips"][(("node", "tpu-0"),)] == 8  # cpu test mesh

    def test_utilization_probe_populates(self):
        """The active compute probe (DCGM-utilization analog) must set the
        measured-TFLOPs gauge on any platform; the %-of-peak gauge only
        where the generation peak applies (real TPU)."""
        agent = MetricsExporterAgent(node_name="tpu-0")
        agent.probe_utilization()
        values = {m.name: {tuple(sorted(s.labels.items())): s.value for s in m.samples}
                  for m in agent.registry.collect()}
        assert values["tpu_exporter_matmul_tflops"][(("node", "tpu-0"),)] > 0
        # no passive duty-cycle gauge survives: it had no source anywhere
        assert "tpu_exporter_duty_cycle" not in values

    def test_ici_probe_populates_on_multichip(self):
        """The ICI bus-bandwidth gauge (NVLink-counter analog) must
        populate whenever the node has >1 chip — here the 8-device CPU
        test mesh proves the plumbing; the value only means ICI on real
        hardware."""
        agent = MetricsExporterAgent(node_name="tpu-0")
        agent.probe_ici()
        values = {m.name: {tuple(sorted(s.labels.items())): s.value for s in m.samples}
                  for m in agent.registry.collect()}
        assert values["tpu_exporter_ici_bandwidth_gbps"][(("node", "tpu-0"),)] > 0


class TestNative:
    def test_probe_shape(self):
        report = tpuinfo.probe()
        assert set(report) >= {"chip_count", "devices"}
        assert isinstance(report["chip_count"], int)

    def test_fnv_parity(self):
        from tpu_operator.utils import fnv64a

        for payload in (b"", b"a", b"cluster-policy" * 100):
            assert tpuinfo.fnv64(payload) == fnv64a(payload)


class TestChart:
    def test_render_defaults(self):
        with open("deploy/values.yaml") as f:
            values = yaml.safe_load(f)
        objs = render_chart(values)
        kinds = [o["kind"] for o in objs]
        assert kinds.count("CustomResourceDefinition") == 5
        for kind in ("Namespace", "ServiceAccount", "ClusterRole", "ClusterRoleBinding",
                     "Deployment", "ClusterPolicy"):
            assert kind in kinds, kind
        deploy = [o for o in objs if o["kind"] == "Deployment"][0]
        ctr = deploy["spec"]["template"]["spec"]["containers"][0]
        assert ctr["image"] == "gcr.io/tpu-operator/tpu-operator:1.0.0"
        assert "--leader-elect" in ctr["args"]
        cp = [o for o in objs if o["kind"] == "ClusterPolicy"][0]
        assert cp["spec"]["devicePlugin"]["enabled"] is True

    def test_values_flow_into_cr(self):
        values = {"namespace": "custom-ns",
                  "clusterPolicy": {"metricsExporter": {"enabled": False}}}
        objs = render_chart(values)
        cp = [o for o in objs if o["kind"] == "ClusterPolicy"][0]
        assert cp["spec"]["metricsExporter"]["enabled"] is False
        ns = [o for o in objs if o["kind"] == "Namespace"][0]
        assert ns["metadata"]["name"] == "custom-ns"


class TestTpuopCfg:
    def test_validate_good_clusterpolicy(self, tmp_path, capsys):
        p = tmp_path / "cp.yaml"
        p.write_text(yaml.safe_dump({
            "apiVersion": "tpu.google.com/v1", "kind": "ClusterPolicy",
            "metadata": {"name": "cluster-policy"},
            "spec": {"libtpu": {"repository": "gcr.io/x", "image": "libtpu", "version": "1"}},
        }))
        assert tpuop_cfg.main(["validate", "clusterpolicy", "--input", str(p)]) == 0

    def test_validate_bad_enabled_type(self, tmp_path, capsys):
        p = tmp_path / "cp.yaml"
        p.write_text(yaml.safe_dump({
            "apiVersion": "tpu.google.com/v1", "kind": "ClusterPolicy",
            "metadata": {"name": "x"},
            "spec": {"devicePlugin": {"enabled": "yes"}},
        }))
        assert tpuop_cfg.main(["validate", "clusterpolicy", "--input", str(p)]) == 1
        assert "enabled must be a boolean" in capsys.readouterr().err

    def test_validate_wrong_kind(self, tmp_path, capsys):
        p = tmp_path / "x.yaml"
        p.write_text(yaml.safe_dump({"kind": "Deployment"}))
        assert tpuop_cfg.main(["validate", "clusterpolicy", "--input", str(p)]) == 1

    def test_generate_crds(self, capsys):
        assert tpuop_cfg.main(["generate", "crds"]) == 0
        docs = list(yaml.safe_load_all(capsys.readouterr().out))
        assert {d["metadata"]["name"] for d in docs} == {
            "clusterpolicies.tpu.google.com", "tpuslices.tpu.google.com",
            "tpujobs.tpu.google.com", "tpuservings.tpu.google.com",
            "tpuquotas.tpu.google.com"}

    def test_render(self, capsys):
        assert tpuop_cfg.main(["render", "--values", "deploy/values.yaml"]) == 0
        docs = list(yaml.safe_load_all(capsys.readouterr().out))
        assert any(d["kind"] == "ClusterPolicy" for d in docs)


class TestOperatorMain:
    def test_fake_cluster_boot(self):
        from tpu_operator.cmd.main import build_parser, make_client

        args = build_parser().parse_args(["--fake-cluster", "2"])
        client = make_client(args)
        assert len(client.list("v1", "Node")) == 2

    def test_in_cluster_required_without_fake(self, monkeypatch):
        from tpu_operator.cmd.main import build_parser, make_client
        from tpu_operator.kube import errors

        monkeypatch.delenv("KUBERNETES_SERVICE_HOST", raising=False)
        args = build_parser().parse_args([])
        with pytest.raises(errors.ApiError, match="not running in a cluster"):
            make_client(args)


class TestMultiSliceGang:
    def test_two_pools_get_distinct_slice_ids(self):
        client = FakeClient()
        for i in range(4):
            node = make_tpu_node(f"a-{i}", "tpu-v5p-slice", "2x2x2",
                                 nodepool="pool-a" if i < 2 else "pool-b")
            node["metadata"]["labels"][consts.TPU_PRESENT_LABEL] = "true"
            client.create(node)
        agent = SliceManagerAgent(client, NS, multi_slice=True, coordinator_port=8476)
        names = agent.reconcile_once()
        assert len(names) == 2  # two v5p 2x2x2 pools (2 hosts each)
        ids, nums = set(), set()
        for name in names:
            cm = client.get("v1", "ConfigMap", f"{name}-gang", NS)
            ids.add(cm["data"]["MEGASCALE_SLICE_ID"])
            nums.add(cm["data"]["MEGASCALE_NUM_SLICES"])
            assert cm["data"]["MEGASCALE_COORDINATOR_ADDRESS"].endswith(":8476")
        assert ids == {"0", "1"}
        assert nums == {"2"}


class TestSliceProfiles:
    def test_disabled_profile_skips_family(self):
        from tpu_operator.kube.objects import new_object

        client = FakeClient()
        for i in range(4):
            node = make_tpu_node(f"v5e-{i}", "tpu-v5-lite-podslice", "4x4", nodepool="pool-a")
            node["metadata"]["labels"][consts.TPU_PRESENT_LABEL] = "true"
            client.create(node)
        client.create(new_object(
            "v1", "ConfigMap", "tpu-slice-config", NS,
            data={"config.yaml": (
                "version: v1\n"
                "slice-configs:\n"
                "  default:\n"
                "    - accelerator-type: tpu-v5-lite-podslice\n"
                "      gang: disabled\n"
            )},
        ))
        agent = SliceManagerAgent(client, NS, config_map="tpu-slice-config")
        assert agent.reconcile_once() == []
        # and with no profile entry matching, gangs default on
        client.delete("v1", "ConfigMap", "tpu-slice-config", NS)
        assert len(agent.reconcile_once()) == 1


class TestSliceProfileRobustness:
    def seed_nodes(self, client, pools=("pool-a", "pool-b")):
        from tpu_operator.kube.objects import new_object

        for pool_i, pool in enumerate(pools):
            acc = "tpu-v5-lite-podslice" if pool_i == 0 else "tpu-v5p-slice"
            topo = "4x4" if pool_i == 0 else "2x2x2"
            for i in range(4 if pool_i == 0 else 2):
                node = make_tpu_node(f"{pool}-{i}", acc, topo, nodepool=pool)
                node["metadata"]["labels"][consts.TPU_PRESENT_LABEL] = "true"
                client.create(node)

    def test_malformed_profile_degrades_to_defaults(self):
        from tpu_operator.kube.objects import new_object

        client = FakeClient()
        self.seed_nodes(client, pools=("pool-a",))
        client.create(new_object(
            "v1", "ConfigMap", "cfg", NS,
            data={"config.yaml": "slice-configs:\n  default:\n    gang: disabled\n"},  # mapping, not list
        ))
        agent = SliceManagerAgent(client, NS, config_map="cfg")
        assert len(agent.reconcile_once()) == 1  # degraded to default, no crash

    def test_disabled_family_excluded_from_megascale_count(self):
        from tpu_operator.kube.objects import new_object

        client = FakeClient()
        self.seed_nodes(client)
        client.create(new_object(
            "v1", "ConfigMap", "cfg", NS,
            data={"config.yaml": (
                "slice-configs:\n"
                "  default:\n"
                "    - accelerator-type: tpu-v5-lite-podslice\n"
                "      gang: disabled\n"
            )},
        ))
        agent = SliceManagerAgent(client, NS, multi_slice=True, config_map="cfg")
        names = agent.reconcile_once()
        assert len(names) == 1
        cm = client.get("v1", "ConfigMap", f"{names[0]}-gang", NS)
        assert cm["data"]["MEGASCALE_NUM_SLICES"] == "1"
        assert cm["data"]["MEGASCALE_SLICE_ID"] == "0"
