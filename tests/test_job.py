"""Elastic fault-tolerant training: checkpoint store failure modes, the
shared retry-budget helper, the shrink/grow allocator oracle, the TPUJob
FSM, the resumable trainer, and the chaos acceptance run (loss-curve
continuity across host death + grey failure + link cut + preemption).

The over-the-wire drill lives in tests/drill.py (run under the shipped
RBAC gate in test_rbac_gate.py); the CI gate is `bench.py --job-smoke`.
"""

import io
import json
import os
import tempfile
import threading

import numpy as np
import pytest

from tpu_operator import consts
from tpu_operator.api.tpujob import (
    TPU_JOB_API_VERSION,
    TPU_JOB_KIND,
    JobPhase,
    TPUJob,
    new_tpu_job,
)
from tpu_operator.api.tpuslice import TPU_SLICE_API_VERSION, TPU_SLICE_KIND
from tpu_operator.controllers.job_controller import JobReconciler
from tpu_operator.controllers.placement_controller import (
    QUEUE_REQUEST,
    PlacementReconciler,
)
from tpu_operator.kube.backoff import RetryBudget, read_attempts
from tpu_operator.kube.controller import Request
from tpu_operator.kube.fake import FakeClient
from tpu_operator.kube.objects import new_object
from tpu_operator.kube.sim import GangFaultSchedule, make_torus_nodes
from tpu_operator.placement.engine import (
    largest_placeable_shape,
    shrink_candidates,
)
from tpu_operator.workloads.checkpoint import MANIFEST_NAME, CheckpointStore

NS = "tpu-operator"


# ---------------------------------------------------------------------------
# checkpoint store
# ---------------------------------------------------------------------------


class TestCheckpointStore:
    def _store(self, tmp_path):
        return CheckpointStore(str(tmp_path / "ckpt"))

    def test_roundtrip_and_epoch_monotonicity(self, tmp_path):
        store = self._store(tmp_path)
        a = {"w": np.arange(6, dtype=np.float32).reshape(2, 3), "b": np.ones(3)}
        assert store.save(10, a) == 1
        assert store.save(20, {"w": a["w"] * 2, "b": a["b"]}) == 2
        ckpt = store.latest_good()
        assert ckpt.epoch == 2 and ckpt.step == 20
        np.testing.assert_array_equal(ckpt.arrays["w"], a["w"] * 2)
        older = store.load(1)
        assert older.step == 10
        np.testing.assert_array_equal(older.arrays["w"], a["w"])

    def test_empty_store(self, tmp_path):
        assert self._store(tmp_path).latest_good() is None

    def test_torn_blob_falls_back_to_last_good_epoch(self, tmp_path):
        store = self._store(tmp_path)
        store.save(10, {"w": np.ones(4)})
        store.save(20, {"w": np.full(4, 2.0)})
        # tear the newest blob (partial write / bit rot): checksum fails
        newest = store.manifest()[-1]["file"]
        path = os.path.join(store.directory, newest)
        with open(path, "r+b") as f:
            f.truncate(os.path.getsize(path) // 2)
        ckpt = store.latest_good()
        assert ckpt.epoch == 1 and ckpt.step == 10
        np.testing.assert_array_equal(ckpt.arrays["w"], np.ones(4))

    def test_corrupt_blob_with_valid_size_falls_back(self, tmp_path):
        store = self._store(tmp_path)
        store.save(5, {"w": np.ones(2)})
        store.save(9, {"w": np.zeros(2)})
        newest = store.manifest()[-1]["file"]
        path = os.path.join(store.directory, newest)
        blob = bytearray(open(path, "rb").read())
        blob[len(blob) // 2] ^= 0xFF  # same size, flipped byte
        open(path, "wb").write(bytes(blob))
        assert store.latest_good().step == 5

    def test_vanished_blob_falls_back(self, tmp_path):
        store = self._store(tmp_path)
        store.save(1, {"w": np.ones(1)})
        store.save(2, {"w": np.ones(1)})
        os.unlink(os.path.join(store.directory, store.manifest()[-1]["file"]))
        assert store.latest_good().epoch == 1

    def test_unreadable_manifest_reads_as_empty_store(self, tmp_path):
        store = self._store(tmp_path)
        store.save(10, {"w": np.ones(1)})
        with open(os.path.join(store.directory, MANIFEST_NAME), "w") as f:
            f.write('{"epochs": [{"epo')  # torn mid-write by a crash
        assert store.manifest() == []
        assert store.latest_good() is None
        # the store recovers: the next save rebuilds a valid manifest
        assert store.save(11, {"w": np.ones(1)}) == 1

    def test_crash_mid_checkpoint_resumes_from_previous_epoch(self, tmp_path):
        """Blob published, crash before the manifest names it: the
        previous epoch stays latest-good, and a post-restart save never
        collides with the orphan."""
        store = self._store(tmp_path)
        store.save(10, {"w": np.ones(2)})
        # simulate the crash window: the epoch-2 blob exists on disk but
        # the manifest was never rewritten
        buf = io.BytesIO()
        np.savez(buf, w=np.full(2, 9.0))
        with open(os.path.join(store.directory, store._blob_name(2)), "wb") as f:
            f.write(buf.getvalue())
        assert store.latest_good().step == 10  # orphan invisible
        # post-restart writer reuses epoch 2 cleanly (replace semantics)
        assert store.save(20, {"w": np.full(2, 3.0)}) == 2
        ckpt = store.latest_good()
        assert ckpt.epoch == 2 and ckpt.step == 20
        np.testing.assert_array_equal(ckpt.arrays["w"], np.full(2, 3.0))

    def test_concurrent_writers_never_publish_half_written_manifest(self, tmp_path):
        """N threads saving concurrently: every observable manifest state
        parses, epochs end up distinct and dense, every blob verifies."""
        store = self._store(tmp_path)
        errors = []

        def writer(i):
            try:
                for j in range(5):
                    store.save(i * 100 + j, {"w": np.full(3, float(i))})
                    # readers interleave with writers: every observation
                    # must be a fully-consistent store state
                    store.manifest()
                    ckpt = store.latest_good()
                    assert ckpt is not None
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=writer, args=(i,)) for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        entries = store.manifest()
        epochs = [e["epoch"] for e in entries]
        assert epochs == list(range(1, 21))  # dense, no collisions
        for entry in entries:
            assert store.load(entry["epoch"]) is not None

    def test_prune_keeps_newest(self, tmp_path):
        store = self._store(tmp_path)
        for i in range(5):
            store.save(i, {"w": np.ones(1)})
        assert store.prune(keep=2) == 3
        assert [e["epoch"] for e in store.manifest()] == [4, 5]
        assert store.latest_good().epoch == 5
        # pruned blobs are gone from disk
        assert not os.path.exists(os.path.join(store.directory, store._blob_name(1)))


# ---------------------------------------------------------------------------
# the shared retry budget
# ---------------------------------------------------------------------------


class TestRetryBudget:
    def test_exhaustion_semantics_match_health_controller(self):
        budget = RetryBudget(retry_limit=2)
        assert not budget.exhausted(0)
        assert not budget.exhausted(1)
        assert budget.exhausted(2)  # attempts-allowed, not attempts+1

    def test_zero_and_negative_limits_quarantine_immediately(self):
        assert RetryBudget(retry_limit=0).exhausted(0)
        assert RetryBudget(retry_limit=-3).exhausted(0)

    def test_full_jitter_delay_bounds_and_determinism(self):
        import random

        budget = RetryBudget(retry_limit=5, base_delay_seconds=1.0, max_delay_seconds=4.0)
        for attempt in range(1, 6):
            cap = min(4.0, 1.0 * 2 ** (attempt - 1))
            for _ in range(20):
                d = budget.delay(attempt)
                assert 0.0 <= d <= cap
        a = [budget.delay(n, random.Random(7)) for n in range(1, 4)]
        b = [budget.delay(n, random.Random(7)) for n in range(1, 4)]
        assert a == b  # seeded rng → reproducible schedule

    def test_read_attempts_tolerates_garbage(self):
        assert read_attempts(None, "k") == 0
        assert read_attempts({"k": "3"}, "k") == 3
        assert read_attempts({"k": "banana"}, "k") == 0


# ---------------------------------------------------------------------------
# the shrink/grow allocator oracle
# ---------------------------------------------------------------------------


def torus_cluster(dims=(2, 2, 1), prefix="tj"):
    client = FakeClient()
    for node in make_torus_nodes(dims, prefix=prefix):
        node["metadata"]["labels"][consts.TPU_PRESENT_LABEL] = "true"
        client.create(node)
    return client


class TestShrinkOracle:
    def test_candidates_largest_first_bounded_by_min_volume(self):
        cands = shrink_candidates((2, 2, 1), min_volume=2)
        assert cands[0] == (2, 2, 1)
        assert all(c[0] * c[1] * c[2] >= 2 for c in cands)
        volumes = [c[0] * c[1] * c[2] for c in cands]
        assert volumes == sorted(volumes, reverse=True)
        assert (1, 1, 1) not in cands  # below the floor
        # rotations deduped: one canonical (2,1,1)
        assert cands.count((2, 1, 1)) == 1

    def test_candidates_fit_inside_desired(self):
        for cand in shrink_candidates((4, 2, 1), min_volume=1):
            assert tuple(sorted(cand, reverse=True))[1] <= 2

    def test_free_torus_places_desired(self):
        client = torus_cluster()
        nodes = client.list("v1", "Node")
        assert largest_placeable_shape([], nodes, (2, 2, 1), 1) == (2, 2, 1)

    def test_out_of_service_host_forces_shrink(self):
        client = torus_cluster()
        client.patch("v1", "Node", "tj-0",
                     {"metadata": {"labels": {consts.TPU_HEALTH_LABEL: "degraded"}}})
        nodes = client.list("v1", "Node")
        best = largest_placeable_shape([], nodes, (2, 2, 1), 1)
        assert best is not None and best[0] * best[1] * best[2] == 2

    def test_min_volume_floor_returns_none(self):
        client = torus_cluster()
        for name in ("tj-0", "tj-1", "tj-2"):
            client.patch("v1", "Node", name,
                         {"metadata": {"labels": {consts.TPU_HEALTH_LABEL: "degraded"}}})
        nodes = client.list("v1", "Node")
        assert largest_placeable_shape([], nodes, (2, 2, 1), 2) is None

    def test_exclude_frees_own_assignment(self):
        """A gang's own cells count as free for its grow check."""
        client = torus_cluster()
        place = PlacementReconciler(client, NS)
        from tests.test_placement import placement_slice

        client.create(placement_slice("mine", "2x2x1"))
        place.reconcile(QUEUE_REQUEST)
        slices = client.list(TPU_SLICE_API_VERSION, TPU_SLICE_KIND)
        nodes = client.list("v1", "Node")
        assert largest_placeable_shape(slices, nodes, (2, 2, 1), 4) is None
        assert largest_placeable_shape(
            slices, nodes, (2, 2, 1), 4, exclude=["mine"]
        ) == (2, 2, 1)

    def test_link_cut_constrains_blocks(self):
        client = torus_cluster()
        nodes = client.list("v1", "Node")
        cut = [("tj-0", "tj-1")]
        best = largest_placeable_shape([], nodes, (2, 2, 1), 1, degraded_links=cut)
        assert best is not None and best[0] * best[1] * best[2] == 2


# ---------------------------------------------------------------------------
# FSM units (no jax: the gang is simulated through the progress CM)
# ---------------------------------------------------------------------------


def make_job(name="job1", shape="2x2x1", min_shape="1x1x1", steps=40,
             every=5, retry_limit=3, base=0.0, max_s=0.0):
    return new_tpu_job(name, {
        "workload": {"steps": steps},
        "gang": {"shape": shape, "minShape": min_shape},
        "checkpoint": {"everySteps": every},
        "backoff": {"baseSeconds": base, "maxSeconds": max_s, "retryLimit": retry_limit},
    })


def job_block(client, name="job1"):
    obj = client.get(TPU_JOB_API_VERSION, TPU_JOB_KIND, name)
    return (obj.get("status") or {}).get("job") or {}


def publish_progress(client, name="job1", **kv):
    data = {k: str(v) for k, v in kv.items()}
    cm_name = name + consts.JOB_PROGRESS_SUFFIX
    if client.get_or_none("v1", "ConfigMap", cm_name, NS) is None:
        client.create(new_object("v1", "ConfigMap", cm_name, NS, data=data))
    else:
        client.patch("v1", "ConfigMap", cm_name, {"data": data}, NS)


def events_with_reason(client, reason):
    return [
        e for e in client.list("v1", "Event", "default")
        if e.get("reason") == reason
    ]


class TestJobFSM:
    def _world(self, job=None):
        client = torus_cluster()
        client.create(job or make_job())
        return client, JobReconciler(client, NS), PlacementReconciler(client, NS)

    def test_creates_owned_slice_and_places(self):
        client, job_rec, place_rec = self._world()
        job_rec.reconcile(Request(name="job1"))
        ts = client.get(TPU_SLICE_API_VERSION, TPU_SLICE_KIND, "job1-slice")
        placement = ts["spec"]["placement"]
        assert placement["shape"] == "2x2x1"
        refs = ts["metadata"]["ownerReferences"]
        assert refs and refs[0]["kind"] == TPU_JOB_KIND and refs[0]["name"] == "job1"
        assert job_block(client)["phase"] == JobPhase.PLACING
        place_rec.reconcile(QUEUE_REQUEST)
        job_rec.reconcile(Request(name="job1"))
        assert job_block(client)["hosts"] == 4

    def test_running_once_gang_trains_at_world(self):
        client, job_rec, place_rec = self._world()
        job_rec.reconcile(Request(name="job1"))
        place_rec.reconcile(QUEUE_REQUEST)
        publish_progress(client, step=3, checkpointEpoch=0, checkpointStep=0,
                         world=4, status="running")
        job_rec.reconcile(Request(name="job1"))
        block = job_block(client)
        assert block["phase"] == JobPhase.RUNNING
        assert block["step"] == 3
        assert events_with_reason(client, "JobPlaced")

    def _run_to_running(self, client, job_rec, place_rec, step=6):
        job_rec.reconcile(Request(name="job1"))
        place_rec.reconcile(QUEUE_REQUEST)
        publish_progress(client, step=step, checkpointEpoch=1, checkpointStep=5,
                         world=4, status="running")
        job_rec.reconcile(Request(name="job1"))
        assert job_block(client)["phase"] == JobPhase.RUNNING

    def test_out_of_service_member_shrinks_to_largest_placeable(self):
        client, job_rec, place_rec = self._world()
        self._run_to_running(client, job_rec, place_rec)
        client.patch("v1", "Node", "tj-0",
                     {"metadata": {"labels": {consts.TPU_PERF_LABEL: "degraded"}}})
        job_rec.reconcile(Request(name="job1"))
        block = job_block(client)
        assert block["phase"] == JobPhase.SHRINKING
        assert block["shape"] == "2x1x1"
        assert block["shrinks"][-1]["kind"] == "shrink"
        assert "grey-failure" in block["shrinks"][-1]["cause"]
        ts = client.get(TPU_SLICE_API_VERSION, TPU_SLICE_KIND, "job1-slice")
        assert ts["spec"]["placement"]["shape"] == "2x1x1"
        assert events_with_reason(client, "JobShrunk")
        # the engine re-places the shrunk shape off the sick host
        place_rec.reconcile(QUEUE_REQUEST)
        publish_progress(client, step=6, world=2, status="running")
        job_rec.reconcile(Request(name="job1"))
        block = job_block(client)
        assert block["phase"] == JobPhase.RUNNING
        assert block["hosts"] == 2
        assert block["restarts"] == 0  # a successful shrink burns no budget

    def test_link_cut_shrinks_with_cause(self):
        client, job_rec, place_rec = self._world()
        self._run_to_running(client, job_rec, place_rec)
        ts = client.get(TPU_SLICE_API_VERSION, TPU_SLICE_KIND, "job1-slice")
        a, b = sorted(ts["status"]["placement"]["nodes"])[:2]
        client.create(new_object(
            "v1", "ConfigMap", consts.LINK_HEALTH_CONFIGMAP, NS,
            data={"tpu-pool": json.dumps(
                {"edges": {"|".join(sorted((a, b))): {"bandwidth_gbps": 0.1}}}
            )},
        ))
        job_rec.reconcile(Request(name="job1"))
        block = job_block(client)
        assert block["phase"] == JobPhase.SHRINKING
        assert "link-cut" in block["shrinks"][-1]["cause"]

    def test_preemption_recorded_as_cause(self):
        client, job_rec, place_rec = self._world()
        self._run_to_running(client, job_rec, place_rec)
        from tests.test_placement import placement_slice

        client.create(placement_slice("boss", "2x2x1", priority=100, policy="PreemptLower"))
        place_rec.reconcile(QUEUE_REQUEST)
        job_rec.reconcile(Request(name="job1"))
        block = job_block(client)
        # the whole torus is taken: nothing placeable, budget charged
        assert block["restarts"] == 1
        assert any("preempt" in c or "unschedulable" in c for c in block["causes"])

    def test_grow_waits_for_checkpoint_barrier(self):
        client, job_rec, place_rec = self._world()
        self._run_to_running(client, job_rec, place_rec)
        # shrink via grey failure, re-place, return to Running at 2 hosts
        client.patch("v1", "Node", "tj-0",
                     {"metadata": {"labels": {consts.TPU_PERF_LABEL: "degraded"}}})
        job_rec.reconcile(Request(name="job1"))
        place_rec.reconcile(QUEUE_REQUEST)
        publish_progress(client, step=8, world=2, status="running")
        job_rec.reconcile(Request(name="job1"))
        assert job_block(client)["phase"] == JobPhase.RUNNING
        # heal: grow must checkpoint FIRST
        client.patch("v1", "Node", "tj-0",
                     {"metadata": {"labels": {consts.TPU_PERF_LABEL: None}}})
        job_rec.reconcile(Request(name="job1"))
        block = job_block(client)
        assert block["phase"] == JobPhase.CHECKPOINTING
        token = block["barrier"]
        cm = client.get("v1", "ConfigMap", "job1-progress", NS)
        assert cm["data"][consts.JOB_CHECKPOINT_REQUEST] == token
        # slice NOT resized yet — zero steps may be lost to a planned grow
        ts = client.get(TPU_SLICE_API_VERSION, TPU_SLICE_KIND, "job1-slice")
        assert ts["spec"]["placement"]["shape"] == "2x1x1"
        # the gang acks the barrier → the grow lands
        publish_progress(client, step=9, checkpointEpoch=2, checkpointStep=9,
                         world=2, status="running", checkpointAck=token)
        job_rec.reconcile(Request(name="job1"))
        block = job_block(client)
        assert block["phase"] == JobPhase.GROWING
        assert block["shape"] == "2x2x1"
        assert block["shrinks"][-1]["kind"] == "grow"
        assert events_with_reason(client, "JobGrown")

    def test_trainer_error_restarts_against_budget(self):
        client, job_rec, place_rec = self._world()
        self._run_to_running(client, job_rec, place_rec)
        publish_progress(client, step=7, world=4, status="error", error="injected")
        job_rec.reconcile(Request(name="job1"))
        block = job_block(client)
        assert block["phase"] == JobPhase.RESUMING
        assert block["restarts"] == 1
        assert events_with_reason(client, "JobRestarted")
        cm = client.get("v1", "ConfigMap", "job1-progress", NS)
        token = cm["data"][consts.JOB_RESTART_REQUEST]
        assert token == str(block["totalRestarts"])
        # the gang acks the restart and trains again: streak resets
        publish_progress(client, status="running", restartAck=token, world=4, step=7)
        job_rec.reconcile(Request(name="job1"))
        block = job_block(client)
        assert block["phase"] == JobPhase.RUNNING
        assert block["restarts"] == 0

    def test_retry_budget_exhaustion_quarantines(self):
        client, job_rec, place_rec = self._world(
            make_job(retry_limit=2, min_shape="2x2x1")
        )
        self._run_to_running(client, job_rec, place_rec)
        # every host out of service: min shape can never place
        for node in client.list("v1", "Node"):
            client.patch("v1", "Node", node["metadata"]["name"],
                         {"metadata": {"labels": {consts.TPU_HEALTH_LABEL: "degraded"}}})
        for _ in range(4):
            job_rec.reconcile(Request(name="job1"))
        block = job_block(client)
        assert block["phase"] == JobPhase.FAILED
        assert "retry budget exhausted" in block["message"]
        assert events_with_reason(client, "JobFailed")
        # quarantine frees the gang's capacity and placement-queue slot
        assert client.get_or_none(TPU_SLICE_API_VERSION, TPU_SLICE_KIND, "job1-slice") is None
        # terminal: further passes are inert
        job_rec.reconcile(Request(name="job1"))
        assert job_block(client)["phase"] == JobPhase.FAILED

    def test_backoff_gate_survives_event_driven_wakeups(self):
        """Watch-event storms must not burn the budget faster than the
        backoff schedule: attempts before nextAttemptAt are free."""
        client, job_rec, place_rec = self._world(
            make_job(retry_limit=3, min_shape="2x2x1", base=60.0, max_s=60.0)
        )
        self._run_to_running(client, job_rec, place_rec)
        for node in client.list("v1", "Node"):
            client.patch("v1", "Node", node["metadata"]["name"],
                         {"metadata": {"labels": {consts.TPU_HEALTH_LABEL: "degraded"}}})
        for _ in range(10):  # an event storm
            job_rec.reconcile(Request(name="job1"))
        block = job_block(client)
        assert block["phase"] == JobPhase.PLACING
        assert block["restarts"] == 1  # one attempt, gate held the rest
        assert block["nextAttemptAt"] > 0

    def test_invalid_spec_fails_without_retry(self):
        client, job_rec, _ = self._world(make_job(shape="banana"))
        job_rec.reconcile(Request(name="job1"))
        assert job_block(client)["phase"] == JobPhase.FAILED
        client2, job_rec2, _ = self._world(
            make_job(shape="1x1x1", min_shape="2x2x1")  # min > desired
        )
        job_rec2.reconcile(Request(name="job1"))
        assert job_block(client2)["phase"] == JobPhase.FAILED

    def test_completion_succeeds_and_frees_capacity(self):
        client, job_rec, place_rec = self._world(make_job(steps=10))
        self._run_to_running(client, job_rec, place_rec)
        publish_progress(client, step=10, checkpointEpoch=2, checkpointStep=10,
                         world=4, status="complete")
        job_rec.reconcile(Request(name="job1"))
        block = job_block(client)
        assert block["phase"] == JobPhase.SUCCEEDED
        assert events_with_reason(client, "JobSucceeded")
        assert client.get_or_none(TPU_SLICE_API_VERSION, TPU_SLICE_KIND, "job1-slice") is None
        obj = client.get(TPU_JOB_API_VERSION, TPU_JOB_KIND, "job1")
        assert obj["status"]["state"] == JobPhase.SUCCEEDED

    def test_job_deletion_retires_series_and_sweeps_slice(self):
        client, job_rec, place_rec = self._world()
        self._run_to_running(client, job_rec, place_rec)
        import prometheus_client

        sample = prometheus_client.REGISTRY.get_sample_value(
            "tpu_operator_job_step", {"job": "job1"}
        )
        assert sample is not None
        client.delete(TPU_JOB_API_VERSION, TPU_JOB_KIND, "job1")
        job_rec.reconcile(Request(name="job1"))
        assert prometheus_client.REGISTRY.get_sample_value(
            "tpu_operator_job_step", {"job": "job1"}
        ) is None
        assert client.get_or_none(TPU_SLICE_API_VERSION, TPU_SLICE_KIND, "job1-slice") is None

    def test_foreign_slice_named_like_a_job_is_never_swept(self):
        """A user's standalone TPUSlice whose name merely ends in
        '-slice' must survive the job controller's vanished-job cleanup
        path (review finding: the sweep used to delete it)."""
        from tests.test_placement import placement_slice

        client = torus_cluster()
        client.create(placement_slice("inference-slice", "2x1x1"))
        job_rec = JobReconciler(client, NS)
        # a request for a job that never existed (e.g. mapped from a
        # foreign '*-progress' ConfigMap) takes the cleanup path
        job_rec.reconcile(Request(name="inference"))
        assert client.get_or_none(
            TPU_SLICE_API_VERSION, TPU_SLICE_KIND, "inference-slice"
        ) is not None
        # while a genuinely owned slice IS swept when its job vanishes
        client.create(make_job("gone"))
        job_rec.reconcile(Request(name="gone"))
        client.delete(TPU_JOB_API_VERSION, TPU_JOB_KIND, "gone")
        job_rec.reconcile(Request(name="gone"))
        assert client.get_or_none(
            TPU_SLICE_API_VERSION, TPU_SLICE_KIND, "gone-slice"
        ) is None

    def test_grow_barrier_tokens_never_repeat(self):
        """A stale checkpointAck from an earlier grow must never satisfy
        a later barrier (review finding: a repeated token skipped the
        fresh checkpoint and lost up to a cadence of steps on a PLANNED
        resize) — the persisted sequence makes every token unique."""
        client, job_rec, place_rec = self._world()
        self._run_to_running(client, job_rec, place_rec)

        def shrink_heal_cycle():
            client.patch("v1", "Node", "tj-0",
                         {"metadata": {"labels": {consts.TPU_PERF_LABEL: "degraded"}}})
            job_rec.reconcile(Request(name="job1"))
            place_rec.reconcile(QUEUE_REQUEST)
            publish_progress(client, step=8, world=2, status="running")
            job_rec.reconcile(Request(name="job1"))
            client.patch("v1", "Node", "tj-0",
                         {"metadata": {"labels": {consts.TPU_PERF_LABEL: None}}})
            job_rec.reconcile(Request(name="job1"))  # enters Checkpointing
            block = job_block(client)
            assert block["phase"] == JobPhase.CHECKPOINTING
            token = block["barrier"]
            publish_progress(client, step=8, checkpointEpoch=2, checkpointStep=8,
                             world=2, status="running", checkpointAck=token)
            job_rec.reconcile(Request(name="job1"))  # grow lands
            place_rec.reconcile(QUEUE_REQUEST)
            publish_progress(client, step=8, world=4, status="running")
            job_rec.reconcile(Request(name="job1"))
            assert job_block(client)["phase"] == JobPhase.RUNNING
            return token

        first = shrink_heal_cycle()
        # identical world state (same step, no budget charged): the
        # second cycle's token must still differ
        second = shrink_heal_cycle()
        assert first != second

    def test_status_survives_operator_restart(self):
        """A fresh reconciler re-derives the same world from cluster
        state: no in-memory FSM state is load-bearing."""
        client, job_rec, place_rec = self._world()
        self._run_to_running(client, job_rec, place_rec)
        fresh = JobReconciler(client, NS)
        fresh.reconcile(Request(name="job1"))
        assert job_block(client)["phase"] == JobPhase.RUNNING


class TestMustGatherJobs:
    def test_jobs_txt_carries_fsm_state_and_history(self, tmp_path):
        from tpu_operator import mustgather

        client, job_rec, place_rec = TestJobFSM()._world()
        job_rec.reconcile(Request(name="job1"))
        place_rec.reconcile(QUEUE_REQUEST)
        publish_progress(client, step=6, checkpointEpoch=1, checkpointStep=5,
                         world=4, status="running")
        job_rec.reconcile(Request(name="job1"))
        client.patch("v1", "Node", "tj-0",
                     {"metadata": {"labels": {consts.TPU_HEALTH_LABEL: "degraded"}}})
        job_rec.reconcile(Request(name="job1"))  # shrink lands in history
        written = mustgather.collect(client, NS, str(tmp_path / "bundle"))
        assert "jobs.txt" in written and "tpujobs.yaml" in written
        text = open(tmp_path / "bundle" / "jobs.txt").read()
        assert "job1" in text
        assert "phase=Shrinking" in text
        assert "checkpointEpoch=1" in text
        assert "2x2x1 -> 2x1x1" in text
        assert "host-health" in text


# ---------------------------------------------------------------------------
# resumable trainer + runner (jax)
# ---------------------------------------------------------------------------


class TestResumableTrainer:
    def test_checkpoint_resume_same_curve_across_worlds(self, tmp_path):
        from tpu_operator.workloads.training import ResumableTrainer, trainer_config

        cfg = trainer_config()
        store = CheckpointStore(str(tmp_path / "a"))
        trainer = ResumableTrainer(store, cfg, total_steps=12, checkpoint_every=4)
        trainer.resume(hosts=4)
        trainer.run(8)  # checkpoints at 4 and 8
        assert trainer.checkpoint_step == 8
        losses_first = {h["step"]: h["loss"] for h in trainer.history}
        # a new trainer (fresh process) resumes on a SMALLER world
        resumed = ResumableTrainer(store, cfg, total_steps=12, checkpoint_every=4)
        info = resumed.resume(hosts=2)
        assert info.step == 8 and info.epoch == 2 and info.world <= 2
        resumed.run(10)
        assert resumed.done
        for h in resumed.history:
            if h["step"] in losses_first:
                assert h["loss"] == pytest.approx(
                    losses_first[h["step"]], rel=1e-3, abs=1e-5
                )

    def test_resume_after_lost_steps_rewinds_to_checkpoint(self, tmp_path):
        from tpu_operator.workloads.training import (
            ResumableTrainer,
            trainer_config,
            verify_continuity,
        )

        store = CheckpointStore(str(tmp_path / "b"))
        trainer = ResumableTrainer(store, trainer_config(), total_steps=10, checkpoint_every=4)
        trainer.resume(hosts=4)
        trainer.run(6)  # steps 1-6, checkpoint at 4: steps 5-6 at risk
        trainer.resume(hosts=2)  # the shrink: rewinds to 4
        assert trainer.step == 4
        trainer.run(10)
        assert trainer.done
        report = verify_continuity(trainer.history, trainer.checkpoints, 10)
        assert report["ok"], report
        assert report["rewinds"] == 1
        assert report["max_lost_steps"] == 2

    def test_verify_continuity_flags_violations(self):
        from tpu_operator.workloads.training import verify_continuity

        # a rewind NOT anchored at a checkpoint
        bad = [{"step": s, "loss": 1.0, "world": 2} for s in (1, 2, 3, 2, 3, 4)]
        report = verify_continuity(bad, [{"epoch": 1, "step": 3}], 4)
        assert not report["ok"]
        # a forward gap
        gap = [{"step": s, "loss": 1.0, "world": 2} for s in (1, 2, 4)]
        assert not verify_continuity(gap, [], 4)["ok"]
        # a loss discontinuity on re-execution
        wobble = [
            {"step": 1, "loss": 1.0, "world": 2},
            {"step": 2, "loss": 0.9, "world": 2},
            {"step": 2, "loss": 5.0, "world": 1},
        ]
        assert not verify_continuity(wobble, [{"epoch": 1, "step": 1}], 2)["ok"]

    def test_injected_fault_raises_once(self, tmp_path):
        from tpu_operator.workloads.training import (
            ResumableTrainer,
            TrainerError,
            trainer_config,
        )

        store = CheckpointStore(str(tmp_path / "c"))
        trainer = ResumableTrainer(
            store, trainer_config(), total_steps=6, checkpoint_every=2,
            fail_at_steps=(3,),
        )
        trainer.resume(hosts=2)
        with pytest.raises(TrainerError):
            trainer.run(6)
        assert trainer.step == 2
        trainer.resume(hosts=2)  # restart from the step-2 checkpoint
        trainer.run(10)
        assert trainer.done


class TestInProcessRunner:
    def test_paused_until_gang_placed_and_healthy(self, tmp_path):
        from tpu_operator.workloads.training import InProcessJobRunner

        client = torus_cluster()
        client.create(make_job())
        store = CheckpointStore(str(tmp_path / "r"))
        runner = InProcessJobRunner(client, NS, "job1", store)
        assert "paused" in runner.sync()  # no slice yet
        job_rec = JobReconciler(client, NS)
        place_rec = PlacementReconciler(client, NS)
        job_rec.reconcile(Request(name="job1"))
        place_rec.reconcile(QUEUE_REQUEST)
        acts = runner.sync()
        assert acts.get("steps")  # placed: training
        # a member dies: the runner pauses (collectives would hang)
        client.patch("v1", "Node", "tj-0",
                     {"metadata": {"labels": {consts.TPU_HEALTH_LABEL: "degraded"}}})
        assert "paused" in runner.sync()


# ---------------------------------------------------------------------------
# the chaos acceptance run (the tentpole's proof)
# ---------------------------------------------------------------------------


class TestChaosAcceptance:
    def drive(self, seed=7):
        """A TPUJob through the full seeded schedule — host death, grey
        failure, link cut, preemption — must finish with contiguous
        epoch history, shrinking only to allocator-ranked blocks and
        growing back on every heal."""
        from tpu_operator.workloads.training import (
            InProcessJobRunner,
            verify_continuity,
        )

        client = torus_cluster()
        client.create(make_job(
            steps=120, every=5, retry_limit=10, base=0.01, max_s=0.05
        ))
        job_rec = JobReconciler(client, NS)
        place_rec = PlacementReconciler(client, NS)
        tmp = tempfile.mkdtemp(prefix="tpujob-chaos-")
        runner = InProcessJobRunner(
            client, NS, "job1", CheckpointStore(tmp), steps_per_sync=3
        )
        schedule = GangFaultSchedule(
            client, NS, "job1-slice", seed=seed, start_at=3, every=10, heal_after=4
        )
        for _ in range(400):
            job_rec.reconcile(Request(name="job1"))
            place_rec.reconcile(QUEUE_REQUEST)
            runner.sync()
            schedule.step()
            if job_block(client).get("phase") == JobPhase.SUCCEEDED:
                break
        return client, runner, schedule

    def test_loss_curve_continuity_under_chaos(self):
        from tpu_operator.workloads.training import verify_continuity

        client, runner, schedule = self.drive()
        block = job_block(client)
        assert block["phase"] == JobPhase.SUCCEEDED, block
        assert schedule.done()
        # every configured fault class actually fired (vacuous-schedule guard)
        assert schedule.fired == set(GangFaultSchedule.FAULT_CLASSES)
        trainer = runner.trainer
        report = verify_continuity(trainer.history, trainer.checkpoints, 120)
        assert report["ok"], report
        # lost work bounded by the cadence, the resume guarantee
        assert report["max_lost_steps"] <= 5
        # shrinks landed only on allocator-ranked blocks and grew back
        resizes = block["shrinks"]
        assert any(r["kind"] == "shrink" for r in resizes)
        assert any(r["kind"] == "grow" for r in resizes)
        assert resizes[-1]["to"] == "2x2x1"  # finished at full size
        for r in resizes:
            assert r["to"] in ("2x2x1", "2x1x1", "1x1x1")
        # epoch history contiguous: monotone epochs, steps monotone in epoch
        epochs = [c["epoch"] for c in trainer.checkpoints]
        assert epochs == sorted(set(epochs))

    def test_same_seed_same_fault_log(self):
        _, _, a = self.drive(seed=11)
        _, _, b = self.drive(seed=11)
        assert a.log == b.log

    def test_unplaceable_min_shape_quarantines_not_crashloops(self):
        client = torus_cluster()  # 4 hosts total
        client.create(make_job(
            name="toobig", shape="4x4x4", min_shape="4x4x1", retry_limit=2
        ))
        job_rec = JobReconciler(client, NS)
        place_rec = PlacementReconciler(client, NS)
        for _ in range(8):
            job_rec.reconcile(Request(name="toobig"))
            place_rec.reconcile(QUEUE_REQUEST)
        block = job_block(client, "toobig")
        assert block["phase"] == JobPhase.FAILED
        assert events_with_reason(client, "JobFailed")
        # the dead job holds no placement-queue slot
        assert client.get_or_none(TPU_SLICE_API_VERSION, TPU_SLICE_KIND, "toobig-slice") is None
