"""Flight recorder & reconcile tracing (kube/trace.py) — ISSUE 6.

Covers: span mechanics (parent/child, attrs, error capture), the
bounded ring buffer with overflow aggregation, queue-wait measurement,
controller-produced traces with api child spans from both clients,
wire propagation of the trace header into the chaos fault log, the
breaker fast-fail span, the new histograms, idempotent OperatorMetrics
construction, and the lint metrics-catalog rule.
"""

import time

import pytest

from tpu_operator.kube import errors, trace
from tpu_operator.kube.controller import Controller, Request, Result
from tpu_operator.kube.fake import FakeClient
from tpu_operator.kube.http_client import HttpClient
from tpu_operator.kube.httpserver import FakeApiServer
from tpu_operator.kube.informer import Informer
from tpu_operator.kube.queue import RateLimitingQueue


@pytest.fixture(autouse=True)
def fresh_recorder():
    rec = trace.reset_recorder()
    yield rec
    trace.reset_recorder()


def _cm(name, ns="ns"):
    return {"apiVersion": "v1", "kind": "ConfigMap", "metadata": {"name": name, "namespace": ns}}


class TestSpans:
    def test_parent_child_attrs_and_ids(self, fresh_recorder):
        with trace.start_trace("reconcile", controller="c", request="r") as root:
            assert trace.active() and trace.current() is root
            with trace.span("phase", detail=1) as child:
                assert child.parent_id == root.span_id
                assert child.trace_id == root.trace_id
                assert trace.trace_ref() == f"{root.trace_id}/{child.span_id}"
        assert not trace.active()
        (t,) = fresh_recorder.traces()
        assert [s.name for s in t.spans] == ["reconcile", "phase"]
        assert t.complete()
        assert all(s.end is not None for s in t.spans)

    def test_span_outside_trace_is_noop(self, fresh_recorder):
        with trace.span("orphan") as s:
            assert s is trace.NOOP_SPAN
            s.set(anything="goes")
        assert len(fresh_recorder) == 0
        assert fresh_recorder.spans_started == 0

    def test_exception_recorded_on_span_and_reraised(self, fresh_recorder):
        with pytest.raises(ValueError):
            with trace.start_trace("reconcile", controller="c", request="r"):
                with trace.span("phase"):
                    raise ValueError("boom")
        (t,) = fresh_recorder.traces()
        assert t.complete()
        assert "boom" in t.spans[1].error
        assert "boom" in t.root.error

    def test_accounted_fraction_flags_clock_inconsistency(self):
        root = trace.Span("t", "t", None, "reconcile", {"queue_wait_s": 0.0})
        t = trace.Trace(root, 16)
        child = trace.Span("t", "c1", "t", "api", {})
        t.add(child)
        child.end = child.start + 0.05
        root.end = root.start + 0.1
        assert t.accounted_fraction() > 0.99  # clean nesting
        # a child recorded far past the root's end is unaccountable time
        child.end = root.end + 0.5
        assert t.accounted_fraction() < 0.95


class TestFlightRecorder:
    def test_ring_bound_and_listener_sees_evicted(self):
        rec = trace.reset_recorder(capacity=8)
        seen = []
        rec.add_listener(lambda t: seen.append(t.trace_id))
        for _ in range(20):
            with trace.start_trace("reconcile", controller="c", request="r"):
                pass
        assert len(rec) == 8  # ring wrapped
        assert rec.traces_recorded == 20
        assert len(seen) == 20  # the listener missed nothing
        assert rec.orphan_spans() == 0

    def test_overflow_aggregates_instead_of_losing(self):
        rec = trace.reset_recorder(max_spans_per_trace=4)
        client = FakeClient()
        for i in range(10):
            client.create(_cm(f"x{i}"))
        with trace.start_trace("reconcile", controller="c", request="r"):
            for i in range(10):
                client.get("v1", "ConfigMap", f"x{i}", "ns")
        (t,) = rec.traces()
        assert len(t.spans) == 4 and t.dropped == 7
        count, requests, seconds = t.overflow[("api", "get", "ConfigMap")]
        assert count == 7 and requests == 7 and seconds > 0
        assert t.complete(), "aggregated overflow must not read as orphan spans"
        assert "(aggregated)" in rec.dump()

    def test_dump_and_slowest(self, fresh_recorder):
        for i, sleep in enumerate((0.0, 0.02)):
            with trace.start_trace("reconcile", controller="c", request=f"r{i}"):
                time.sleep(sleep)
        slow = fresh_recorder.dump_slowest(1)
        assert "request=r1" in slow and "request=r0" not in slow
        assert "controller=c" in fresh_recorder.dump()

    def test_byte_estimate_bounded_by_capacity(self):
        rec = trace.reset_recorder(capacity=4, max_spans_per_trace=4)
        client = FakeClient()
        client.create(_cm("x"))
        for _ in range(50):
            with trace.start_trace("reconcile", controller="c", request="r"):
                for _ in range(20):
                    client.get("v1", "ConfigMap", "x", "ns")
        bound = 4 * (4 * 200 + 4 * 5 * 120 + 8 * 160)
        assert rec.byte_estimate() <= bound


class TestQueueWait:
    def test_wait_measured_from_readiness(self):
        q = RateLimitingQueue()
        q.add("a")
        time.sleep(0.03)
        item = q.get(timeout=1.0)
        assert item == "a"
        assert 0.02 <= q.wait_of("a") < 5.0
        q.done("a")
        assert q.wait_of("a") == 0.0  # cleared

    def test_delayed_add_excludes_planned_delay(self):
        q = RateLimitingQueue()
        q.add_after("a", 0.05)
        item = q.get(timeout=1.0)
        assert item == "a"
        # the 50ms planned delay is not queue latency
        assert q.wait_of("a") < 0.04

    def test_oldest_age_tracks_pending(self):
        q = RateLimitingQueue()
        assert q.oldest_age() == 0.0
        q.add("a")
        time.sleep(0.02)
        assert q.oldest_age() >= 0.02


class _Reconciler:
    def __init__(self, client):
        self.client = client
        self.seen = []

    def reconcile(self, req: Request) -> Result:
        self.seen.append(req)
        self.client.get("v1", "ConfigMap", req.name, "ns")
        return Result()


class TestControllerTracing:
    def test_reconcile_produces_trace_with_queue_wait_and_api_children(self, fresh_recorder):
        client = FakeClient()
        client.create(_cm("obj"))
        ctrl = Controller("demo", _Reconciler(client))
        informer = Informer(client, "v1", "ConfigMap")
        ctrl.watch(informer)
        informer.start()
        ctrl.start()
        try:
            client.update({**_cm("obj"), "data": {"k": "v"}})
            deadline = time.time() + 5
            while time.time() < deadline and len(fresh_recorder) < 1:
                time.sleep(0.01)
            traces = fresh_recorder.traces()
            assert traces, "no trace recorded for the reconcile"
            t = traces[0]
            assert t.root.attrs["controller"] == "demo"
            assert t.root.attrs["request"] == "ns/obj"
            assert "queue_wait_s" in t.root.attrs
            api = [s for s in t.spans if s.name == "api"]
            assert api and api[0].attrs["kind"] == "ConfigMap"
            assert t.complete()
        finally:
            ctrl.stop()
            informer.stop()

    def test_reconcile_exception_traced_and_histograms_observe(self, fresh_recorder):
        import prometheus_client

        class Boom:
            def reconcile(self, req):
                raise RuntimeError("bang")

        ctrl = Controller("boomer", Boom())
        ctrl.start()
        try:
            before = prometheus_client.REGISTRY.get_sample_value(
                "tpu_operator_reconcile_duration_seconds_count", {"controller": "boomer", "shard": ""}
            ) or 0.0
            ctrl.queue.add(Request(name="x"))
            deadline = time.time() + 5
            while time.time() < deadline and len(fresh_recorder) < 1:
                time.sleep(0.01)
            (t,) = fresh_recorder.traces()[:1]
            assert "bang" in t.root.error
            assert t.complete()
            after = prometheus_client.REGISTRY.get_sample_value(
                "tpu_operator_reconcile_duration_seconds_count", {"controller": "boomer", "shard": ""}
            )
            assert after >= before + 1
            assert prometheus_client.REGISTRY.get_sample_value(
                "tpu_operator_workqueue_wait_seconds_count", {"controller": "boomer", "shard": ""}
            ) >= 1
        finally:
            ctrl.stop()


class TestWirePropagation:
    def test_trace_header_attributes_chaos_faults_and_retries_nest(self, fresh_recorder):
        from tpu_operator.kube.chaos import FAULT_500, ChaosDirector, FaultRule

        store = FakeClient()
        store.create(_cm("x"))
        director = ChaosDirector(
            seed=3, rules=[FaultRule(FAULT_500, rate=1.0, times=2, verbs=("GET",))]
        )
        server = FakeApiServer(store, chaos=director).start()
        client = HttpClient(server.base_url)
        try:
            with trace.start_trace("reconcile", controller="c", request="x"):
                client.get("v1", "ConfigMap", "x", "ns")
            (t,) = fresh_recorder.traces()
            api = [s for s in t.spans if s.name == "api"]
            attempts = [s for s in t.spans if s.name == "attempt"]
            # one logical call, three attempts under it (two 500s retried)
            assert len(api) == 1 and api[0].attrs["attempts"] == 3
            assert len(attempts) == 3
            assert all(a.parent_id == api[0].span_id for a in attempts)
            assert t.complete()
            # the fault log knows WHICH reconcile its injections hit
            assert len(director.fault_log) == 2
            for rec_ in director.fault_log:
                assert rec_.trace.startswith(t.trace_id + "/")
        finally:
            server.stop()

    def test_breaker_open_fast_fail_is_recorded(self, fresh_recorder):
        store = FakeClient()
        store.create(_cm("x"))
        server = FakeApiServer(store).start()
        client = HttpClient(server.base_url)
        try:
            client.resilience.breaker._set_state("open")
            client.resilience.breaker.opened_at = time.monotonic() + 1000
            with pytest.raises(errors.BreakerOpen):
                with trace.start_trace("reconcile", controller="c", request="x"):
                    client.get("v1", "ConfigMap", "x", "ns")
            (t,) = fresh_recorder.traces()
            (api,) = [s for s in t.spans if s.name == "api"]
            assert "BreakerOpen" in api.error
            assert "attempts" not in api.attrs  # fail-fast: zero wire sends
            assert not [s for s in t.spans if s.name == "attempt"]
            assert t.complete()
        finally:
            server.stop()


class TestInformerLag:
    def test_lag_histogram_observes_per_event(self):
        import prometheus_client

        client = FakeClient()
        informer = Informer(client, "v1", "ConfigMap")
        informer.start()
        try:
            before = prometheus_client.REGISTRY.get_sample_value(
                "tpu_operator_informer_event_lag_seconds_count", {"kind": "ConfigMap"}
            ) or 0.0
            client.create(_cm("x"))
            after = prometheus_client.REGISTRY.get_sample_value(
                "tpu_operator_informer_event_lag_seconds_count", {"kind": "ConfigMap"}
            )
            assert after >= before + 1
        finally:
            informer.stop()


class TestOperatorMetricsIdempotent:
    def test_second_construction_reuses_collectors(self):
        """Regression (ISSUE 6 satellite): a second in-process Manager
        (crash-recovery drills) constructing OperatorMetrics against the
        default registry must not trip prometheus duplicate
        registration."""
        from tpu_operator.controllers.operator_metrics import OperatorMetrics

        a = OperatorMetrics()
        b = OperatorMetrics()  # would raise ValueError before the fix
        assert a.tpu_nodes_total is b.tpu_nodes_total
        assert a.reconciliation_total is b.reconciliation_total
        assert a.torus_fragmentation is b.torus_fragmentation
        # the re-exported process-wide series are singletons too
        assert a.reconcile_duration is b.reconcile_duration
        assert a.apiserver_request_duration is b.apiserver_request_duration

    def test_custom_registry_still_gets_private_collectors(self):
        import prometheus_client

        from tpu_operator.controllers.operator_metrics import OperatorMetrics

        reg = prometheus_client.CollectorRegistry()
        m = OperatorMetrics(registry=reg)
        m.tpu_nodes_total.set(3)
        assert reg.get_sample_value("tpu_operator_tpu_nodes_total") == 3


class TestMetricsCatalogLint:
    def test_repo_catalog_is_in_sync(self):
        from tpu_operator.lint import metrics_catalog

        assert metrics_catalog.analyze() == []

    def test_undocumented_metric_is_flagged(self, tmp_path):
        from tpu_operator.lint import metrics_catalog

        src = tmp_path / "code"
        src.mkdir()
        (src / "m.py").write_text(
            "import prometheus_client\n"
            'g = prometheus_client.Gauge("tpu_operator_phantom_series", "doc")\n'
        )
        doc = tmp_path / "COMPONENTS.md"
        doc.write_text("### Metric catalog\n\n| `tpu_operator_other` | gauge | x |\n")
        findings = metrics_catalog.analyze(str(src), str(doc))
        rules = {(f.rule, f.location) for f in findings}
        assert ("TPUOP-O001", "metric:tpu_operator_phantom_series") in rules
        assert ("TPUOP-O002", "metric:tpu_operator_other") in rules

    def test_factory_style_registration_is_seen(self, tmp_path):
        from tpu_operator.lint import metrics_catalog

        src = tmp_path / "code"
        src.mkdir()
        (src / "m.py").write_text(
            "import prometheus_client\n"
            "def build(factory):\n"
            '    return factory(prometheus_client.Counter, "tpu_operator_via_factory_total", "doc")\n'
        )
        assert "tpu_operator_via_factory_total" in metrics_catalog.registered_metrics(str(src))

    def test_missing_catalog_section_is_an_error(self, tmp_path):
        from tpu_operator.lint import metrics_catalog

        src = tmp_path / "code"
        src.mkdir()
        doc = tmp_path / "COMPONENTS.md"
        doc.write_text("# nothing here\n")
        findings = metrics_catalog.analyze(str(src), str(doc))
        assert findings and findings[0].rule == "TPUOP-O002"
