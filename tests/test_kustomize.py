"""Kustomize overlay parity (reference: config/default/kustomization.yaml
+ crd/rbac/manager bases give non-helm installs a kubectl-apply path).

The committed deploy/kustomize/ tree is GENERATED from the same renderer
`tpuop-cfg render` uses (scripts/update_kustomize.py); these tests are
the drift gate: any change to the chart that isn't regenerated into the
overlay fails here.
"""

import os
import shutil
import subprocess

import pytest
import yaml

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
KUSTOMIZE_DIR = os.path.join(REPO, "deploy", "kustomize")


def load_kustomization(base: str) -> dict:
    with open(os.path.join(KUSTOMIZE_DIR, base, "kustomization.yaml")) as f:
        return yaml.safe_load(f)


def load_base_objects(base: str) -> list:
    """Objects of one base, in kustomization resource order."""
    out = []
    for res in load_kustomization(base)["resources"]:
        path = os.path.join(KUSTOMIZE_DIR, base, res)
        with open(path) as f:
            out.extend(d for d in yaml.safe_load_all(f) if d)
    return out


def key(obj: dict):
    return (obj["kind"], obj["metadata"]["name"])


class TestOverlayParity:
    def test_committed_tree_matches_generator(self):
        """Byte-for-byte drift gate: regenerating must reproduce exactly
        the committed files (same contract as the golden renders)."""
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "update_kustomize", os.path.join(REPO, "scripts", "update_kustomize.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        files = mod.generate()
        on_disk = {}
        for root, _, names in os.walk(KUSTOMIZE_DIR):
            for name in names:
                path = os.path.join(root, name)
                rel = os.path.relpath(path, KUSTOMIZE_DIR)
                with open(path) as f:
                    on_disk[rel] = f.read()
        assert sorted(on_disk) == sorted(files), "file set drifted — regenerate"
        for rel, text in files.items():
            assert on_disk[rel] == text, f"{rel} drifted — run scripts/update_kustomize.py"

    def test_default_base_equals_render_minus_cr(self):
        """default/ (crd + rbac + manager) must contain exactly what
        `tpuop-cfg render` emits, minus the ClusterPolicy CR (samples/)."""
        from tpu_operator.chart import render_chart

        with open(os.path.join(REPO, "deploy", "values.yaml")) as f:
            rendered = render_chart(yaml.safe_load(f))
        want = {key(o): o for o in rendered if o["kind"] != "ClusterPolicy"}
        got = {}
        for base in load_kustomization("default")["resources"]:
            base_name = os.path.basename(base)
            for obj in load_base_objects(base_name):
                got[key(obj)] = obj
        assert sorted(got) == sorted(want)
        for k, obj in want.items():
            assert got[k] == obj, f"{k} differs between render and overlay"
        # the CR is in samples/ and only there
        sample_kinds = {o["kind"] for o in load_base_objects("samples")}
        assert sample_kinds == {"ClusterPolicy"}

    def test_every_resource_listed_and_every_file_listed(self):
        """No orphan files, no dangling resource entries."""
        for base in ("crd", "rbac", "manager", "samples"):
            listed = set(load_kustomization(base)["resources"])
            on_disk = {
                n
                for n in os.listdir(os.path.join(KUSTOMIZE_DIR, base))
                if n != "kustomization.yaml"
            }
            assert listed == on_disk, (base, listed, on_disk)


class TestRealKustomizeBuild:
    def test_kubectl_kustomize_build(self):
        """When a kustomize (or kubectl) binary exists, the overlay must
        actually build and agree object-for-object with the render path
        (exit-42-style skip otherwise, like the kind e2e gate)."""
        exe = None
        if shutil.which("kustomize"):
            exe = ["kustomize", "build"]
        elif shutil.which("kubectl"):
            exe = ["kubectl", "kustomize"]
        if exe is None:
            pytest.skip("no kustomize/kubectl binary in this environment")
        proc = subprocess.run(
            [*exe, os.path.join(KUSTOMIZE_DIR, "default")],
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        built = {key(o) for o in yaml.safe_load_all(proc.stdout) if o}
        from tpu_operator.chart import render_chart

        with open(os.path.join(REPO, "deploy", "values.yaml")) as f:
            rendered = render_chart(yaml.safe_load(f))
        want = {key(o) for o in rendered if o["kind"] != "ClusterPolicy"}
        assert built == want
