"""Image-entrypoint smoke as a suite test (scripts/image_smoke.py is the
CI gate; this keeps Dockerfile drift inside `pytest tests/`).

Runs the harness in a subprocess because the smoke boots real entrypoint
processes with their own env (in-cluster TLS, CPU jax) that must not
inherit this process's initialized backends."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.skipif(
    bool(os.environ.get("TPU_OPERATOR_SKIP_IMAGE_SMOKE_TEST")),
    reason="ci.sh runs scripts/image_smoke.py as its own explicit gate",
)
def test_image_entrypoints_boot():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "image_smoke.py")],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert proc.returncode == 0, f"stdout={proc.stdout[-4000:]}\nstderr={proc.stderr[-2000:]}"
    assert "IMAGE SMOKE: PASS" in proc.stdout
