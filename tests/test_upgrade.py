"""Upgrade FSM tests (reference analog: the vendored upgrade lib's state
machine semantics — stateless, idempotent, bounded parallelism)."""

import time

from tpu_operator import consts
from tpu_operator.api.clusterpolicy import (
    CLUSTER_POLICY_API_VERSION,
    CLUSTER_POLICY_KIND,
    UpgradePolicySpec,
    new_cluster_policy,
)
from tpu_operator.controllers.clusterpolicy_controller import ClusterPolicyReconciler
from tpu_operator.controllers.upgrade_controller import UpgradeReconciler
from tpu_operator.kube.controller import Request
from tpu_operator.kube.fake import FakeClient
from tpu_operator.kube.objects import new_object
from tpu_operator.kube.sim import ClusterSim, make_tpu_node
from tpu_operator.upgrade.fsm import IN_PROGRESS, ClusterUpgradeStateManager, UpgradeState

NS = "tpu-operator"


def seed(client, nodes=2, auto_upgrade=True):
    """Cluster with libtpu DS rolled out via sim, then a spec bump making
    every driver pod outdated."""
    spec = {"libtpu": {"upgradePolicy": {"autoUpgrade": auto_upgrade, "maxParallelUpgrades": 1,
                                          "maxUnavailable": "100%",
                                          "drain": {"enable": False}}}}
    client.create(new_cluster_policy(spec=spec))
    for i in range(nodes):
        client.create(make_tpu_node(f"tpu-{i}"))
    cp_reconciler = ClusterPolicyReconciler(client, NS)
    cp_reconciler.reconcile(Request(name="cluster-policy"))
    sim = ClusterSim(client, namespace=None, ready_delay=0.0)
    sim.step()  # create driver pods at generation 1
    return cp_reconciler, sim


def bump_libtpu_version(client, cp_reconciler):
    cp = client.get(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND, "cluster-policy")
    cp["spec"].setdefault("libtpu", {}).update(
        {"repository": "gcr.io/x", "image": "libtpu", "version": "2.0"}
    )
    client.update(cp)
    cp_reconciler.reconcile(Request(name="cluster-policy"))  # re-renders DS (generation bump)


def node_state(client, name):
    return client.get("v1", "Node", name)["metadata"].get("labels", {}).get(consts.UPGRADE_STATE_LABEL, "")


class TestBuildState:
    def test_outdated_pod_marks_upgrade_required(self):
        client = FakeClient()
        cp_rec, sim = seed(client)
        mgr = ClusterUpgradeStateManager(client, NS)
        state = mgr.build_state()
        assert state.count(UpgradeState.UPGRADE_REQUIRED) == 0
        bump_libtpu_version(client, cp_rec)
        state = mgr.build_state()
        assert state.count(UpgradeState.UPGRADE_REQUIRED) == 2

    def test_up_to_date_cluster_is_quiet(self):
        client = FakeClient()
        seed(client)
        mgr = ClusterUpgradeStateManager(client, NS)
        state = mgr.build_state()
        assert all(n.state == UpgradeState.UNKNOWN for n in state.nodes.values())


class TestApplyState:
    def run_to_completion(self, client, mgr, policy, sim, max_passes=20):
        for _ in range(max_passes):
            state = mgr.build_state()
            if state.nodes and all(n.state == UpgradeState.DONE for n in state.nodes.values()):
                return True
            mgr.apply_state(state, policy)
            sim.step()  # DS controller recreates deleted pods at new generation
        return False

    def test_full_fsm_rolls_all_nodes(self):
        client = FakeClient()
        cp_rec, sim = seed(client)
        bump_libtpu_version(client, cp_rec)
        mgr = ClusterUpgradeStateManager(client, NS)
        policy = UpgradePolicySpec.from_dict(
            {"autoUpgrade": True, "maxParallelUpgrades": 2, "maxUnavailable": "100%", "drain": {"enable": False}}
        )
        assert self.run_to_completion(client, mgr, policy, sim)
        for i in range(2):
            assert node_state(client, f"tpu-{i}") == UpgradeState.DONE
            assert not client.get("v1", "Node", f"tpu-{i}")["spec"].get("unschedulable")
        # driver pods recreated at the new generation
        for pod in client.list("v1", "Pod", NS, label_selector={"app.kubernetes.io/component": "libtpu-installer"}):
            ds = client.get("apps/v1", "DaemonSet", "libtpu-installer", NS)
            assert pod["metadata"]["labels"]["pod-template-generation"] == str(ds["metadata"]["generation"])

    def test_max_parallel_respected(self):
        client = FakeClient()
        cp_rec, sim = seed(client, nodes=3)
        bump_libtpu_version(client, cp_rec)
        mgr = ClusterUpgradeStateManager(client, NS)
        policy = UpgradePolicySpec.from_dict(
            {"autoUpgrade": True, "maxParallelUpgrades": 1, "maxUnavailable": "100%", "drain": {"enable": False}}
        )
        state = mgr.build_state()
        mgr.apply_state(state, policy)
        # only one node may move past upgrade-required in the first pass
        states = [node_state(client, f"tpu-{i}") for i in range(3)]
        moved = [s for s in states if s not in ("", UpgradeState.UPGRADE_REQUIRED)]
        assert len(moved) == 1, states

    def test_drain_deletes_user_pods_not_daemonset_pods(self):
        client = FakeClient()
        cp_rec, sim = seed(client)
        # a user workload pod consuming TPU on tpu-0
        client.create(new_object(
            "v1", "Pod", "train-job", "default",
            spec={"nodeName": "tpu-0",
                  "containers": [{"name": "t", "resources": {"limits": {"google.com/tpu": "4"}}}]},
            status={"phase": "Running"},
        ))
        bump_libtpu_version(client, cp_rec)
        mgr = ClusterUpgradeStateManager(client, NS)
        policy = UpgradePolicySpec.from_dict(
            {"autoUpgrade": True, "maxParallelUpgrades": 2, "maxUnavailable": "100%", "drain": {"enable": True}}
        )
        for _ in range(4):
            mgr.apply_state(mgr.build_state(), policy)
            sim.step()
        assert client.get_or_none("v1", "Pod", "train-job", "default") is None
        # daemonset-owned operand pods survive the drain
        assert client.list("v1", "Pod", NS, label_selector={"app.kubernetes.io/component": "libtpu-installer"})

    def test_pdb_blocked_drain_parks_node(self):
        """A PodDisruptionBudget protecting a workload pod must park the
        node in drain-required (eviction API, 429) instead of the pod
        being hard-deleted; when the PDB frees up, the drain proceeds."""
        client = FakeClient()
        cp_rec, sim = seed(client)
        client.create(new_object(
            "v1", "Pod", "protected", "default",
            labels={"app": "critical"},
            spec={"nodeName": "tpu-0",
                  "containers": [{"name": "t", "resources": {"limits": {"google.com/tpu": "4"}}}]},
            status={"phase": "Running"},
        ))
        client.create(new_object(
            "policy/v1", "PodDisruptionBudget", "critical-pdb", "default",
            spec={"minAvailable": 1, "selector": {"matchLabels": {"app": "critical"}}},
        ))
        bump_libtpu_version(client, cp_rec)
        mgr = ClusterUpgradeStateManager(client, NS)
        policy = UpgradePolicySpec.from_dict(
            {"autoUpgrade": True, "maxParallelUpgrades": 2, "maxUnavailable": "100%",
             "drain": {"enable": True, "timeoutSeconds": 3600}}
        )
        for _ in range(5):
            mgr.apply_state(mgr.build_state(), policy)
            sim.step()
        # the protected pod survives; its node parks mid-upgrade
        assert client.get_or_none("v1", "Pod", "protected", "default") is not None
        assert node_state(client, "tpu-0") in (
            UpgradeState.POD_DELETION_REQUIRED, UpgradeState.DRAIN_REQUIRED
        )
        # drop the PDB -> upgrade completes
        client.delete("policy/v1", "PodDisruptionBudget", "critical-pdb", "default")
        for _ in range(6):
            mgr.apply_state(mgr.build_state(), policy)
            sim.step()
        assert client.get_or_none("v1", "Pod", "protected", "default") is None
        assert node_state(client, "tpu-0") == UpgradeState.DONE

    def test_pdb_blocked_drain_times_out_to_failed(self):
        client = FakeClient()
        cp_rec, sim = seed(client, nodes=1)
        client.create(new_object(
            "v1", "Pod", "protected", "default",
            labels={"app": "critical"},
            spec={"nodeName": "tpu-0",
                  "containers": [{"name": "t", "resources": {"limits": {"google.com/tpu": "4"}}}]},
            status={"phase": "Running"},
        ))
        client.create(new_object(
            "policy/v1", "PodDisruptionBudget", "critical-pdb", "default",
            spec={"minAvailable": 1, "selector": {"matchLabels": {"app": "critical"}}},
        ))
        bump_libtpu_version(client, cp_rec)
        mgr = ClusterUpgradeStateManager(client, NS)
        policy = UpgradePolicySpec.from_dict(
            {"autoUpgrade": True, "maxParallelUpgrades": 1, "maxUnavailable": "100%",
             "podDeletion": {"timeoutSeconds": 1},
             "drain": {"enable": True, "timeoutSeconds": 1}}
        )
        for _ in range(3):
            mgr.apply_state(mgr.build_state(), policy)
            sim.step()
        # let the since-annotation age past the 1s timeout
        time.sleep(1.1)
        for _ in range(3):
            mgr.apply_state(mgr.build_state(), policy)
            sim.step()
        assert node_state(client, "tpu-0") == UpgradeState.FAILED
        assert client.get_or_none("v1", "Pod", "protected", "default") is not None

    def test_drain_force_overrides_pdb(self):
        client = FakeClient()
        cp_rec, sim = seed(client, nodes=1)
        client.create(new_object(
            "v1", "Pod", "protected", "default",
            labels={"app": "critical"},
            spec={"nodeName": "tpu-0", "containers": []},
            status={"phase": "Running"},
        ))
        client.create(new_object(
            "policy/v1", "PodDisruptionBudget", "critical-pdb", "default",
            spec={"minAvailable": 1, "selector": {"matchLabels": {"app": "critical"}}},
        ))
        bump_libtpu_version(client, cp_rec)
        mgr = ClusterUpgradeStateManager(client, NS)
        policy = UpgradePolicySpec.from_dict(
            {"autoUpgrade": True, "maxParallelUpgrades": 1, "maxUnavailable": "100%",
             "drain": {"enable": True, "force": True}}
        )
        for _ in range(8):
            mgr.apply_state(mgr.build_state(), policy)
            sim.step()
        assert client.get_or_none("v1", "Pod", "protected", "default") is None
        assert node_state(client, "tpu-0") == UpgradeState.DONE

    def test_wait_for_jobs_blocks_until_jobs_finish(self):
        client = FakeClient()
        cp_rec, sim = seed(client, nodes=1)
        client.create(new_object(
            "v1", "Pod", "job-pod", "default",
            labels={"job": "training"},
            spec={"nodeName": "tpu-0", "containers": []},
            status={"phase": "Running"},
        ))
        bump_libtpu_version(client, cp_rec)
        mgr = ClusterUpgradeStateManager(client, NS)
        policy = UpgradePolicySpec.from_dict(
            {"autoUpgrade": True, "maxParallelUpgrades": 1, "maxUnavailable": "100%",
             "waitForCompletion": {"podSelector": "job=training"}, "drain": {"enable": False}}
        )
        mgr.apply_state(mgr.build_state(), policy)
        mgr.apply_state(mgr.build_state(), policy)
        assert node_state(client, "tpu-0") == UpgradeState.WAIT_FOR_JOBS_REQUIRED
        # job finishes
        pod = client.get("v1", "Pod", "job-pod", "default")
        pod["status"] = {"phase": "Succeeded"}
        client.update_status(pod)
        assert self.run_to_completion(client, mgr, policy, sim)


class TestUpgradeReconciler:
    def test_auto_upgrade_disabled_strips_labels(self):
        client = FakeClient()
        cp_rec, sim = seed(client, auto_upgrade=False)
        node = client.get("v1", "Node", "tpu-0")
        node["metadata"]["labels"][consts.UPGRADE_STATE_LABEL] = UpgradeState.UPGRADE_REQUIRED
        client.update(node)
        r = UpgradeReconciler(client, NS)
        result = r.reconcile(Request(name="cluster-policy"))
        assert result.requeue_after == 0
        assert node_state(client, "tpu-0") == ""

    def test_reconcile_replans_on_cadence(self):
        client = FakeClient()
        cp_rec, sim = seed(client)
        bump_libtpu_version(client, cp_rec)
        r = UpgradeReconciler(client, NS)
        result = r.reconcile(Request(name="cluster-policy"))
        assert result.requeue_after == consts.UPGRADE_REPLAN_SECONDS
        # first pass moved exactly maxParallel(1) node into the pipeline
        states = [node_state(client, f"tpu-{i}") for i in range(2)]
        assert UpgradeState.UPGRADE_REQUIRED in states
        # loop a few reconciles + sim steps to completion
        for _ in range(15):
            r.reconcile(Request(name="cluster-policy"))
            sim.step()
        assert all(node_state(client, f"tpu-{i}") == UpgradeState.DONE for i in range(2))


    def test_upgrade_progress_published_in_cr_status(self):
        client = FakeClient()
        cp_rec, sim = seed(client)
        bump_libtpu_version(client, cp_rec)
        r = UpgradeReconciler(client, NS)
        r.reconcile(Request(name="cluster-policy"))
        cp = client.get(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND, "cluster-policy")
        upgrade = cp["status"]["upgrade"]
        assert upgrade["inProgress"] + upgrade["pending"] >= 1
        assert set(upgrade["nodes"]) <= {"tpu-0", "tpu-1"}
        # run to completion: every node reports done in status
        for _ in range(15):
            r.reconcile(Request(name="cluster-policy"))
            sim.step()
        cp = client.get(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND, "cluster-policy")
        upgrade = cp["status"]["upgrade"]
        assert upgrade["done"] == 2 and upgrade["inProgress"] == 0
        assert set(upgrade["nodes"].values()) == {UpgradeState.DONE}
        # the ClusterPolicy reconciler's own status writes preserve it
        cp_rec.reconcile(Request(name="cluster-policy"))
        cp = client.get(CLUSTER_POLICY_API_VERSION, CLUSTER_POLICY_KIND, "cluster-policy")
        assert cp["status"]["upgrade"]["done"] == 2


class TestUpgradeTimeout:
    def test_hung_job_parks_node_in_failed(self):
        client = FakeClient()
        cp_rec, sim = seed(client, nodes=1)
        client.create(new_object(
            "v1", "Pod", "hung-job", "default",
            labels={"job": "training"},
            spec={"nodeName": "tpu-0", "containers": []},
            status={"phase": "Running"},
        ))
        bump_libtpu_version(client, cp_rec)
        mgr = ClusterUpgradeStateManager(client, NS)
        policy = UpgradePolicySpec.from_dict(
            {"autoUpgrade": True, "maxParallelUpgrades": 1, "maxUnavailable": "100%",
             "waitForCompletion": {"podSelector": "job=training", "timeoutSeconds": 1},
             "drain": {"enable": False}}
        )
        mgr.apply_state(mgr.build_state(), policy)
        mgr.apply_state(mgr.build_state(), policy)
        assert node_state(client, "tpu-0") == UpgradeState.WAIT_FOR_JOBS_REQUIRED
        # backdate the transition past the timeout
        node = client.get("v1", "Node", "tpu-0")
        node["metadata"]["annotations"][consts.UPGRADE_STATE_SINCE_ANNOTATION] = "0"
        client.update(node)
        mgr.apply_state(mgr.build_state(), policy)
        assert node_state(client, "tpu-0") == UpgradeState.FAILED
        # failed nodes no longer consume the parallel budget
        state = mgr.build_state()
        assert state.count(*IN_PROGRESS) == 0



class TestEvents:
    def test_cp_state_transition_emits_event(self):
        client = FakeClient()
        seed(client, nodes=1)
        events = client.list("v1", "Event", "default")
        assert any(e.get("involvedObject", {}).get("kind") == "ClusterPolicy" for e in events), events

    def test_upgrade_transitions_emit_node_events(self):
        client = FakeClient()
        cp_rec, sim = seed(client, nodes=1)
        bump_libtpu_version(client, cp_rec)
        mgr = ClusterUpgradeStateManager(client, NS)
        policy = UpgradePolicySpec.from_dict(
            {"autoUpgrade": True, "maxParallelUpgrades": 1, "maxUnavailable": "100%", "drain": {"enable": False}}
        )
        mgr.apply_state(mgr.build_state(), policy)
        node_events = [e for e in client.list("v1", "Event", "default")
                       if e.get("involvedObject", {}).get("kind") == "Node"]
        assert node_events
        assert any("cordon-required" in e.get("message", "") for e in node_events)

    def test_repeat_events_aggregate(self):
        from tpu_operator.kube.events import EventRecorder
        from tpu_operator.kube.objects import new_object

        client = FakeClient()
        rec = EventRecorder(client, NS)
        node = client.create(new_object("v1", "Node", "n0"))
        rec.normal(node, "Test", "same message")
        rec.normal(node, "Test", "same message")
        events = client.list("v1", "Event", "default")
        assert len(events) == 1 and events[0]["count"] == 2
