"""ICI fabric observability tests (ISSUE 9 tentpole).

Four layers under test:
  1. the fabric probe (workloads/fabric.py): edge enumeration over
     block shapes (wrap vs mesh), the real shard_map/psum sweep on the
     virtual 8-device mesh, and the coordinate→host translation of
     ``gang_fabric_artifact``,
  2. the fabric analyzer (controllers/fabric_telemetry.py): degraded-
     edge detection against the gang median, LINK blame (recorded map,
     endpoints stay in service) vs HOST blame (perf label → grey-
     failure FSM), stale-artifact rejection, record clearing on a
     healthy re-measure, and series lifecycle incl. pool drain,
  3. edge-aware placement: a cut edge blocks straddling candidates in
     ``find_block``, fails ``is_contiguous_block`` (so an intact gang
     straddling a fresh cut tears down and re-places), counts in the
     fragmentation probe, and reaches the engine/controller from the
     link-health ConfigMap — whose changes fire the replan predicate,
  4. publication: ``publish_gang_fabric`` beside the telemetry
     annotation.
"""

import json

import prometheus_client
import pytest

from tpu_operator import consts
from tpu_operator.agents.slice_manager_agent import SliceManagerAgent
from tpu_operator.api.tpuslice import TPU_SLICE_API_VERSION, new_tpu_slice
from tpu_operator.controllers.fabric_telemetry import (
    FabricTelemetryAggregator,
    parse_link_map,
)
from tpu_operator.controllers.placement_controller import (
    QUEUE_REQUEST,
    PlacementReconciler,
)
from tpu_operator.kube.fake import FakeClient
from tpu_operator.kube.objects import new_object
from tpu_operator.kube.sim import make_torus_nodes
from tpu_operator.placement.engine import PlacementEngine, PlacementPhase
from tpu_operator.placement.torus import Torus, worker_coords
from tpu_operator.workloads.fabric import (
    edge_key,
    enumerate_block_edges,
    gang_fabric_artifact,
    run_fabric_probe,
)

NS = "tpu-operator"


def sample(name, **labels):
    return prometheus_client.REGISTRY.get_sample_value(name, labels or None)


# ---------------------------------------------------------------------------
# layer 1: the probe
# ---------------------------------------------------------------------------


class TestEdgeEnumeration:
    def test_mesh_block_edge_count(self):
        # 2x4x1 mesh: x edges 1*4, y edges 2*3 — no wrap links
        edges = enumerate_block_edges((2, 4, 1))
        assert len(edges) == 4 + 6
        assert all(not wrap for _, _, _, wrap in edges)

    def test_torus_wrap_edges_only_on_long_axes(self):
        # wrap on the 4-long y axis adds 2 links; the 2-long x axis's
        # "wrap" IS its interior link and must not double-count
        edges = enumerate_block_edges((2, 4, 1), wrap=True)
        assert len(edges) == 4 + 6 + 2
        wraps = [(a, b) for a, b, _, wrap in edges if wrap]
        assert wraps == [((0, 3, 0), (0, 0, 0)), ((1, 3, 0), (1, 0, 0))]

    def test_unit_axes_have_no_edges(self):
        assert enumerate_block_edges((1, 1, 1)) == []
        assert len(enumerate_block_edges((4, 1, 1), wrap=True)) == 3 + 1

    def test_every_edge_is_torus_adjacent(self):
        for a, b, axis, wrap in enumerate_block_edges((2, 2, 2), wrap=True):
            diff = [abs(x - y) for x, y in zip(a, b)]
            assert sorted(diff) in ([0, 0, 1],)


class TestFabricProbe:
    def test_probe_sweeps_edges_and_axes(self):
        probe = run_fabric_probe("2x4x1", wrap=True, size_mb=0.1, iters=2)
        assert probe["ok"] and probe["devices"] == 8
        assert len(probe["edges"]) == 12  # 4 x + 6 y + 2 y-wrap
        assert all(m["bw_gbps"] > 0 for m in probe["edges"].values())
        # per-axis latency matrix covers exactly the multi-host axes
        assert set(probe["axis_allreduce_us"]) == {"x", "y"}
        assert all(v > 0 for v in probe["axis_allreduce_us"].values())

    def test_probe_rejects_bad_shape_and_short_devices(self):
        with pytest.raises(ValueError):
            run_fabric_probe("not-a-shape")
        with pytest.raises(ValueError):
            run_fabric_probe("4x4x4")  # needs 64, the mesh has 8

    def test_artifact_maps_coords_to_hosts_in_worker_order(self):
        probe = {
            "shape": "2x2x1",
            "edges": {
                edge_key("0-0-0", "1-0-0"): {"bw_gbps": 10.0, "axis": "x", "wrap": False},
                edge_key("0-0-0", "0-1-0"): {"bw_gbps": 20.0, "axis": "y", "wrap": False},
                edge_key("1-1-0", "0-1-0"): {"bw_gbps": 5.0, "axis": "x", "wrap": False},
            },
            "axis_allreduce_us": {"x": 11.0},
        }
        hosts = ["n0", "n1", "n2", "n3"]  # worker order: row-major, x fastest
        artifact = gang_fabric_artifact(probe, hosts)
        assert artifact["members"] == hosts
        assert artifact["edges"][edge_key("n0", "n1")]["axis"] == "x"
        assert artifact["edges"][edge_key("n0", "n2")]["axis"] == "y"
        assert artifact["worst_edge"] == edge_key("n2", "n3")
        assert artifact["min_edge_gbps"] == 5.0
        assert artifact["median_edge_gbps"] == 10.0
        assert artifact["axis_allreduce_us"] == {"x": 11.0}

    def test_real_probe_roundtrips_into_artifact(self):
        probe = run_fabric_probe("2x2x2", wrap=True, size_mb=0.1, iters=2)
        hosts = [f"h{i}" for i in range(8)]
        artifact = gang_fabric_artifact(probe, hosts)
        assert artifact["hosts"] == 8
        assert len(artifact["edges"]) == len(probe["edges"]) == 12
        # every edge references two distinct gang members
        for edge in artifact["edges"]:
            a, _, b = edge.partition("|")
            assert a in hosts and b in hosts and a != b


# ---------------------------------------------------------------------------
# layer 3 (units first — the analyzer tests build on them):
# edge-aware torus + engine
# ---------------------------------------------------------------------------


def _torus(dims=(4, 2, 1), wrap=True):
    nodes = {}
    for i in range(dims[0] * dims[1] * dims[2]):
        nodes[worker_coords(i, dims)] = f"n{i}"
    return Torus(dims, nodes, wrap=wrap)


class TestTorusDegradedEdges:
    def test_cut_edge_blocks_straddling_candidates(self):
        torus = _torus()
        # n0=(0,0,0), n1=(1,0,0): cut their x link
        torus.set_degraded_edges([("n0", "n1")])
        found = torus.find_block((2, 1, 1))
        assert found is not None
        block, victims = found
        assert not ({(0, 0, 0), (1, 0, 0)} <= set(block.cells))

    def test_endpoints_stay_individually_placeable(self):
        torus = _torus(dims=(2, 1, 1), wrap=False)
        torus.set_degraded_edges([("n0", "n1")])
        # the pair is forbidden...
        assert torus.find_block((2, 1, 1)) is None
        # ...but each endpoint alone still places
        found = torus.find_block((1, 1, 1))
        assert found is not None

    def test_contiguity_fails_across_a_cut(self):
        torus = _torus()
        cells = [torus.coords_of["n0"], torus.coords_of["n1"]]
        assert torus.is_contiguous_block(cells, (2, 1, 1))
        torus.set_degraded_edges([("n0", "n1")])
        assert not torus.is_contiguous_block(cells, (2, 1, 1))

    def test_unknown_endpoints_ignored(self):
        torus = _torus()
        torus.set_degraded_edges([("ghost-a", "ghost-b"), ("n0", "ghost")])
        assert torus.find_block((4, 2, 1)) is not None  # nothing cut

    def test_fragmentation_counts_severed_edges(self):
        # an empty 4x1x1 chain reads 0.0 fragmentation; cutting its
        # middle link halves the largest placeable run
        torus = _torus(dims=(4, 1, 1), wrap=False)
        assert torus.fragmentation() == 0.0
        torus.set_degraded_edges([("n1", "n2")])
        # largest cut-free block is 2 of 4 free hosts -> 0.5
        assert torus.fragmentation() == pytest.approx(0.5)


class TestEngineDegradedLinks:
    def _cluster(self, shape="2x2x1"):
        store = FakeClient()
        for node in make_torus_nodes((4, 4, 1), prefix="eng"):
            node["metadata"]["labels"][consts.TPU_PRESENT_LABEL] = "true"
            store.create(node)
        store.create(new_tpu_slice("gang-a", {"placement": {"shape": shape}}))
        return store

    def test_gang_straddling_fresh_cut_tears_down_and_replaces(self):
        store = self._cluster()
        pl = PlacementReconciler(store, NS)
        pl.reconcile(QUEUE_REQUEST)
        ts = store.get(TPU_SLICE_API_VERSION, "TPUSlice", "gang-a")
        members = ts["status"]["placement"]["nodes"]
        assert len(members) == 4
        # cut the link between workers 0 and 1 (x neighbors of the block)
        slices = store.list(TPU_SLICE_API_VERSION, "TPUSlice")
        nodes = store.list("v1", "Node")
        engine = PlacementEngine(
            slices, nodes, degraded_links=[(members[0], members[1])]
        )
        plan = engine.plan()
        assert "gang-a" in plan.teardowns
        status = plan.statuses["gang-a"]
        assert status["phase"] == PlacementPhase.SCHEDULED
        new_members = status["nodes"]
        assert not (members[0] in new_members and members[1] in new_members)

    def test_unschedulable_when_every_block_is_cut(self):
        store = FakeClient()
        for node in make_torus_nodes((2, 1, 1), prefix="tiny"):
            node["metadata"]["labels"][consts.TPU_PRESENT_LABEL] = "true"
            store.create(node)
        store.create(new_tpu_slice("gang-b", {"placement": {"shape": "2x1x1"}}))
        engine = PlacementEngine(
            store.list(TPU_SLICE_API_VERSION, "TPUSlice"),
            store.list("v1", "Node"),
            degraded_links=[("tiny-0", "tiny-1")],
        )
        plan = engine.plan()
        assert plan.statuses["gang-b"]["phase"] == PlacementPhase.UNSCHEDULABLE

    def test_controller_feeds_engine_from_link_health_configmap(self):
        store = self._cluster()
        pl = PlacementReconciler(store, NS)
        pl.reconcile(QUEUE_REQUEST)
        ts = store.get(TPU_SLICE_API_VERSION, "TPUSlice", "gang-a")
        members = ts["status"]["placement"]["nodes"]
        edge = edge_key(members[0], members[1])
        store.create(new_object(
            "v1", "ConfigMap", consts.LINK_HEALTH_CONFIGMAP, NS,
            data={"pool-x": json.dumps({"edges": {edge: {"bw_gbps": 4.0}}})},
        ))
        pl.reconcile(QUEUE_REQUEST)  # teardown pass
        pl.reconcile(QUEUE_REQUEST)  # re-place pass (teardown requeues)
        ts = store.get(TPU_SLICE_API_VERSION, "TPUSlice", "gang-a")
        st = ts["status"]["placement"]
        assert st["phase"] == PlacementPhase.SCHEDULED
        assert not (members[0] in st["nodes"] and members[1] in st["nodes"])

    def test_link_map_predicate_fires_only_on_real_changes(self):
        """The replan predicate setup_with_manager actually wires: a
        link-map ADD/data-change replans the queue; unrelated ConfigMap
        churn and no-op echoes do not."""
        from tpu_operator.controllers import placement_controller as pc
        from tpu_operator.kube.manager import Manager

        store = self._cluster()
        mgr = Manager(store)
        reconciler = PlacementReconciler(store, NS)
        ctrl = pc.setup_with_manager(mgr, reconciler)
        try:
            # the ConfigMap watch is the last one registered
            _, _, link_map_changed = ctrl._watches[-1]
            cm = new_object(
                "v1", "ConfigMap", consts.LINK_HEALTH_CONFIGMAP, NS,
                data={"p": "{\"edges\": {}}"},
            )
            other = new_object("v1", "ConfigMap", "unrelated", NS, data={"a": "b"})
            assert link_map_changed("ADDED", None, cm)
            assert not link_map_changed("ADDED", None, other)
            changed = json.loads(json.dumps(cm))
            changed["data"] = {"p": "{\"edges\": {\"a|b\": {}}}"}
            assert link_map_changed("MODIFIED", cm, changed)
            assert not link_map_changed("MODIFIED", cm, json.loads(json.dumps(cm)))
        finally:
            mgr.stop()


# ---------------------------------------------------------------------------
# layer 2: the analyzer
# ---------------------------------------------------------------------------


def _build_cluster(dims=(4, 4, 1), shape="2x4x1", prefix="fab"):
    """A placed gang with its plumbing materialized; returns
    (store, placement reconciler, slice manager, member list)."""
    store = FakeClient()
    for node in make_torus_nodes(dims, prefix=prefix):
        node["metadata"]["labels"][consts.TPU_PRESENT_LABEL] = "true"
        store.create(node)
    store.create(new_tpu_slice("fab-gang", {"placement": {"shape": shape}}))
    pl = PlacementReconciler(store, NS)
    pl.reconcile(QUEUE_REQUEST)
    sm = SliceManagerAgent(store, NS)
    sm.reconcile_once()
    ts = store.get(TPU_SLICE_API_VERSION, "TPUSlice", "fab-gang")
    return store, pl, sm, ts["status"]["placement"]["nodes"]


def _matrix(members, shape=(2, 4, 1), slow=(), bw=40.0, slow_bw=4.0):
    """A synthetic fabric artifact over the placed block with the named
    host-pair edges degraded."""
    edges = {}
    for at, to, axis, wrap in enumerate_block_edges(shape, wrap=True):
        key = edge_key("-".join(map(str, at)), "-".join(map(str, to)))
        edges[key] = {"bw_gbps": bw, "axis": axis, "wrap": wrap}
    probe = {
        "shape": "x".join(map(str, shape)),
        "edges": edges,
        "axis_allreduce_us": {"y": 100.0},
    }
    artifact = gang_fabric_artifact(probe, members)
    for edge in slow:
        artifact["edges"][edge]["bw_gbps"] = slow_bw
    return artifact


class TestFabricAnalyzer:
    def test_single_slow_edge_blames_link_not_hosts(self, fake_client):
        store, pl, sm, members = _build_cluster()
        cut = edge_key(members[0], members[2])  # y-neighbors in 2x4x1
        assert sm.publish_gang_fabric("tpu-slice-fab-gang", _matrix(members, slow=[cut]))
        fab = FabricTelemetryAggregator(store, NS)
        summary = fab.sync()
        assert summary["link_blamed"] == [cut]
        assert summary["host_blamed"] == []
        # recorded in the per-pool link map
        cm = store.get("v1", "ConfigMap", consts.LINK_HEALTH_CONFIGMAP, NS)
        link_map = parse_link_map(cm)
        (pool, edges), = link_map.items()
        assert cut in edges and edges[cut]["gang"] == "tpu-slice-fab-gang"
        # neither endpoint labelled: the cable is the finding
        for host in cut.split("|"):
            labels = store.get("v1", "Node", host)["metadata"].get("labels") or {}
            assert labels.get(consts.TPU_PERF_LABEL) is None
        reasons = [e.get("reason") for e in store.list("v1", "Event")]
        assert "IciLinkDegraded" in reasons and "IciHostDegraded" not in reasons
        # series: bandwidth + degraded flag, keyed by pool and edge
        assert sample(
            "tpu_operator_ici_link_degraded", pool=pool, edge=cut
        ) == 1
        assert sample(
            "tpu_operator_ici_link_bandwidth_gbps", pool=pool, edge=cut
        ) == 4.0

    def test_multi_edge_shared_endpoint_blames_host(self):
        store, pl, sm, members = _build_cluster(prefix="hb")
        victim = members[1]  # worker 1: x edge to 0, y edge to 3
        slow = [edge_key(victim, members[0]), edge_key(victim, members[3])]
        sm.publish_gang_fabric("tpu-slice-fab-gang", _matrix(members, slow=slow))
        fab = FabricTelemetryAggregator(store, NS)
        summary = fab.sync()
        assert summary["host_blamed"] == [victim]
        # the host enters the grey-failure path: perf label set
        labels = store.get("v1", "Node", victim)["metadata"].get("labels") or {}
        assert labels.get(consts.TPU_PERF_LABEL) == consts.PERF_DEGRADED
        # the edges that indicted the host are NOT link-blamed
        assert summary["link_blamed"] == []
        cm = store.get_or_none("v1", "ConfigMap", consts.LINK_HEALTH_CONFIGMAP, NS)
        assert not parse_link_map(cm)
        reasons = [e.get("reason") for e in store.list("v1", "Event")]
        assert "IciHostDegraded" in reasons

    def test_host_blame_enters_fsm_and_gang_replaces(self):
        from tpu_operator.api.clusterpolicy import new_cluster_policy
        from tpu_operator.controllers.health_controller import HealthReconciler
        from tpu_operator.kube.controller import Request

        store, pl, sm, members = _build_cluster(prefix="fsm")
        store.create(new_cluster_policy(spec={
            "healthMonitor": {
                "interval": 1,
                "remediation": {"enable": True, "retryLimit": 3,
                                "timeoutSeconds": 300, "gracePeriodSeconds": 0},
            },
        }))
        victim = members[1]
        slow = [edge_key(victim, members[0]), edge_key(victim, members[3])]
        sm.publish_gang_fabric("tpu-slice-fab-gang", _matrix(members, slow=slow))
        health = HealthReconciler(store, NS)
        req = Request(name="cluster-policy")
        health.reconcile(req)  # fabric blame + FSM entry
        health.reconcile(req)
        labels = store.get("v1", "Node", victim)["metadata"].get("labels") or {}
        assert labels.get(consts.REPAIR_STATE_LABEL)  # the FSM owns it now
        pl.reconcile(QUEUE_REQUEST)
        ts = store.get(TPU_SLICE_API_VERSION, "TPUSlice", "fab-gang")
        st = ts["status"]["placement"]
        assert st["phase"] == PlacementPhase.SCHEDULED
        assert victim not in st["nodes"]

    def test_stale_artifact_skipped_wholesale(self):
        store, pl, sm, members = _build_cluster(prefix="st")
        cut = edge_key(members[0], members[2])
        sm.publish_gang_fabric("tpu-slice-fab-gang", _matrix(members, slow=[cut]))
        # the gang re-places before the analyzer runs: strip one member's
        # assignment labels (what a teardown does)
        store.patch("v1", "Node", members[0], {"metadata": {"labels": {
            consts.PLACEMENT_LABEL: None,
        }}})
        fab = FabricTelemetryAggregator(store, NS)
        summary = fab.sync()
        assert summary["stale_artifacts"] == ["tpu-slice-fab-gang"]
        assert summary["link_blamed"] == [] and summary["host_blamed"] == []
        assert store.get_or_none(
            "v1", "ConfigMap", consts.LINK_HEALTH_CONFIGMAP, NS
        ) is None

    def test_healthy_remeasure_clears_link_record(self):
        store, pl, sm, members = _build_cluster(prefix="cl")
        cut = edge_key(members[0], members[2])
        sm.publish_gang_fabric("tpu-slice-fab-gang", _matrix(members, slow=[cut]))
        fab = FabricTelemetryAggregator(store, NS)
        fab.sync()
        assert parse_link_map(
            store.get("v1", "ConfigMap", consts.LINK_HEALTH_CONFIGMAP, NS)
        )
        # the cable was re-seated: the same gang re-probes it healthy
        sm.publish_gang_fabric("tpu-slice-fab-gang", _matrix(members))
        summary = fab.sync()
        assert summary["link_blamed"] == []
        assert not parse_link_map(
            store.get("v1", "ConfigMap", consts.LINK_HEALTH_CONFIGMAP, NS)
        )
        # degraded flag dropped with the record
        pool = list(summary["gangs"].values())[0]["pool"]
        assert sample("tpu_operator_ici_link_degraded", pool=pool, edge=cut) == 0

    def test_pool_drain_removes_records_and_series(self):
        store, pl, sm, members = _build_cluster(prefix="dr")
        cut = edge_key(members[0], members[2])
        sm.publish_gang_fabric("tpu-slice-fab-gang", _matrix(members, slow=[cut]))
        fab = FabricTelemetryAggregator(store, NS)
        summary = fab.sync()
        pool = list(summary["gangs"].values())[0]["pool"]
        assert sample("tpu_operator_ici_link_bandwidth_gbps", pool=pool, edge=cut) is not None
        for node in store.list("v1", "Node"):
            store.delete("v1", "Node", node["metadata"]["name"])
        summary = fab.sync()
        assert summary["link_map"] == {}
        assert sample("tpu_operator_ici_link_bandwidth_gbps", pool=pool, edge=cut) is None
        assert sample("tpu_operator_ici_link_degraded", pool=pool, edge=cut) is None

    def test_recorded_link_keeps_firing_without_fresh_measurements(self):
        store, pl, sm, members = _build_cluster(prefix="kp")
        cut = edge_key(members[0], members[2])
        sm.publish_gang_fabric("tpu-slice-fab-gang", _matrix(members, slow=[cut]))
        fab = FabricTelemetryAggregator(store, NS)
        summary = fab.sync()
        pool = list(summary["gangs"].values())[0]["pool"]
        # the gang re-places off the cut; its stale artifact is skipped,
        # so no fresh measurement covers the edge — the RECORD keeps the
        # alert-driving series alive (the cable is still cut)
        store.patch("v1", "Node", members[0], {"metadata": {"labels": {
            consts.PLACEMENT_LABEL: None,
        }}})
        summary = fab.sync()
        assert summary["stale_artifacts"]
        assert sample("tpu_operator_ici_link_degraded", pool=pool, edge=cut) == 1

    def test_malformed_artifact_and_link_map_are_skipped(self):
        store, pl, sm, members = _build_cluster(prefix="mal")
        store.patch("v1", "ConfigMap", "tpu-slice-fab-gang-gang", {
            "metadata": {"annotations": {consts.GANG_FABRIC_ANNOTATION: "{not json"}}
        }, NS)
        store.create(new_object(
            "v1", "ConfigMap", consts.LINK_HEALTH_CONFIGMAP, NS,
            data={"pool-a": "also not json", "pool-b": json.dumps({"edges": "nope"})},
        ))
        fab = FabricTelemetryAggregator(store, NS)
        summary = fab.sync()  # must not raise
        assert summary["gangs"] == {}
        assert parse_link_map(
            store.get_or_none("v1", "ConfigMap", consts.LINK_HEALTH_CONFIGMAP, NS)
        ) == {}

    def test_failed_link_map_read_aborts_without_erasing_records(self):
        """A transient apiserver error reading the link map must abort
        the pass (the caller isolates it), NOT read as "no records" —
        that would diff {} against the previous pass and overwrite every
        standing link blame with an empty map."""
        from tpu_operator.kube import errors

        store, pl, sm, members = _build_cluster(prefix="er")
        cut = edge_key(members[0], members[2])
        sm.publish_gang_fabric("tpu-slice-fab-gang", _matrix(members, slow=[cut]))
        fab = FabricTelemetryAggregator(store, NS)
        fab.sync()
        assert parse_link_map(
            store.get("v1", "ConfigMap", consts.LINK_HEALTH_CONFIGMAP, NS)
        )

        real_get = store.get

        def flaky_get(api_version, kind, name, namespace=None):
            if name == consts.LINK_HEALTH_CONFIGMAP:
                raise errors.ServerError("boom")
            return real_get(api_version, kind, name, namespace)

        store.get = flaky_get
        with pytest.raises(errors.ApiError):
            fab.sync()
        store.get = real_get
        # the record survived the outage
        assert cut in parse_link_map(
            store.get("v1", "ConfigMap", consts.LINK_HEALTH_CONFIGMAP, NS)
        ).popitem()[1]

    def test_disjoint_replace_makes_old_artifact_stale(self):
        """A gang re-placed onto a fully disjoint block nulls every old
        member's placement label; the old matrix must still read stale
        (owners=={None} is a torn-down placed gang, not an implicit
        one) — or the analyzer would re-blame the repaired host every
        pass."""
        store, pl, sm, members = _build_cluster(prefix="dj")
        victim = members[1]
        slow = [edge_key(victim, members[0]), edge_key(victim, members[3])]
        sm.publish_gang_fabric("tpu-slice-fab-gang", _matrix(members, slow=slow))
        # simulate the re-place onto a disjoint block: old members lose
        # the owner label, other nodes gain it
        others = [
            n["metadata"]["name"] for n in store.list("v1", "Node")
            if n["metadata"]["name"] not in members
        ]
        for i, name in enumerate(members):
            store.patch("v1", "Node", name, {"metadata": {"labels": {
                consts.PLACEMENT_LABEL: None, consts.PLACEMENT_INDEX_LABEL: None,
            }}})
        for i, name in enumerate(others[:8]):
            store.patch("v1", "Node", name, {"metadata": {"labels": {
                consts.PLACEMENT_LABEL: "fab-gang",
                consts.PLACEMENT_INDEX_LABEL: str(i),
            }}})
        fab = FabricTelemetryAggregator(store, NS)
        summary = fab.sync()
        assert summary["stale_artifacts"] == ["tpu-slice-fab-gang"]
        assert summary["host_blamed"] == []
        labels = store.get("v1", "Node", victim)["metadata"].get("labels") or {}
        assert labels.get(consts.TPU_PERF_LABEL) is None

    def test_second_episode_events_again(self):
        """Blame -> repair -> label cleared -> a LATER second failure is
        a new episode: the IciHostDegraded Event must fire again."""
        store, pl, sm, members = _build_cluster(prefix="ep")
        victim = members[1]
        slow = [edge_key(victim, members[0]), edge_key(victim, members[3])]
        sm.publish_gang_fabric("tpu-slice-fab-gang", _matrix(members, slow=slow))
        fab = FabricTelemetryAggregator(store, NS)
        fab.sync()

        def host_events():
            return [
                e for e in store.list("v1", "Event")
                if e.get("reason") == "IciHostDegraded"
            ]

        first = host_events()
        assert len(first) == 1
        # repair completes: label cleared, the gang measures healthy
        store.patch("v1", "Node", victim, {"metadata": {"labels": {
            consts.TPU_PERF_LABEL: None,
        }}})
        sm.publish_gang_fabric("tpu-slice-fab-gang", _matrix(members))
        fab.sync()  # episode closes
        # second failure, same host
        sm.publish_gang_fabric("tpu-slice-fab-gang", _matrix(members, slow=slow))
        fab.sync()
        second = host_events()
        # a fresh Event object or a bumped count on the aggregate both
        # prove the episode surfaced again
        assert len(second) > 1 or second[0].get("count", 1) > first[0].get("count", 1)

    def test_quiet_pass_writes_nothing(self, fake_client):
        """An unchanged world must produce zero link-map writes — an
        every-pass rewrite would echo a watch event into the placement
        controller's replan predicate on every health cadence."""
        fake_client.create(new_object(
            "v1", "ConfigMap", consts.LINK_HEALTH_CONFIGMAP, NS, data={}
        ))
        fab = FabricTelemetryAggregator(fake_client, NS)
        cm = fake_client.get("v1", "ConfigMap", consts.LINK_HEALTH_CONFIGMAP, NS)
        rv = cm["metadata"]["resourceVersion"]
        fab.sync()
        fab.sync()
        cm = fake_client.get("v1", "ConfigMap", consts.LINK_HEALTH_CONFIGMAP, NS)
        assert cm["metadata"]["resourceVersion"] == rv

    def test_single_edge_gang_never_self_blames(self):
        # a 2-host gang has one edge and no peers to compare against:
        # the median IS the edge, so nothing can read degraded
        store, pl, sm, members = _build_cluster(
            dims=(2, 1, 1), shape="2x1x1", prefix="two"
        )
        artifact = _matrix(members, shape=(2, 1, 1))
        for meta in artifact["edges"].values():
            meta["bw_gbps"] = 0.5  # absurdly slow, but nothing to compare
        sm.publish_gang_fabric("tpu-slice-fab-gang", artifact)
        fab = FabricTelemetryAggregator(store, NS)
        summary = fab.sync()
        assert summary["degraded_edges"] == []


# ---------------------------------------------------------------------------
# layer 4: publication
# ---------------------------------------------------------------------------


class TestGangFabricPublication:
    def test_publish_beside_telemetry_annotation(self):
        store, pl, sm, members = _build_cluster(prefix="pub")
        assert sm.publish_gang_telemetry("tpu-slice-fab-gang", {"hosts": 8})
        artifact = _matrix(members)
        assert sm.publish_gang_fabric("tpu-slice-fab-gang", artifact)
        cm = store.get("v1", "ConfigMap", "tpu-slice-fab-gang-gang", NS)
        annotations = cm["metadata"]["annotations"]
        assert consts.GANG_TELEMETRY_ANNOTATION in annotations
        published = json.loads(annotations[consts.GANG_FABRIC_ANNOTATION])
        assert published["edges"] == artifact["edges"]
        # gang env data untouched by the annotation-only patch
        assert cm["data"]["TPU_SLICE_HOSTS"] == "8"

    def test_publish_gone_gang_returns_false(self, fake_client):
        sm = SliceManagerAgent(fake_client, NS)
        assert not sm.publish_gang_fabric("no-such-slice", {"edges": {}})
