"""Fleet compile cache: keying/invalidation (the test_autotune matrix),
the warm-start path, the AOT prewarm handshake (serving controller ->
compile-cache controller election -> agent -> ack), and the planning
layer's warm-vs-cold compile pricing."""

import json
import time

import pytest

from tpu_operator import consts
from tpu_operator.agents.compilecache_agent import CompileCacheAgent
from tpu_operator.api.clusterpolicy import ClusterPolicy, new_cluster_policy
from tpu_operator.api.tpuserving import TPUServing, new_tpu_serving
from tpu_operator.controllers.compilecache_controller import CompileCacheReconciler
from tpu_operator.controllers.serving_controller import ServingReconciler
from tpu_operator.kube import errors
from tpu_operator.kube.controller import Request
from tpu_operator.kube.fake import FakeClient
from tpu_operator.kube.objects import new_object
from tpu_operator.kube.sim import make_tpu_node
from tpu_operator.planning.model import compile_cost_seconds
from tpu_operator.planning.whatif import admission_answer
from tpu_operator.workloads import compilecache
from tpu_operator.workloads.compilecache import (
    WARM_FRACTION,
    CompileCacheStore,
    cache_record,
    cached_entries,
    entry_key,
    entry_valid,
    model_descriptor_hash,
    parse_entry,
    parse_requests,
    record_key,
    request_id,
)

NS = "tpu-operator"
REQ = Request(name="cluster-policy")


def _record(seconds=3.2, source="worker", serving="svc", node="n-0"):
    return {"seconds": seconds, "source": source, "serving": serving, "node": node}


def _centry(gen="v5e", version="1.0.0", records=None):
    if records is None:
        records = {record_key("2x4", "mhash"): _record()}
    return {"generation": gen, "libtpu_version": version, "records": records}


class StubEngine:
    """warm_start only needs ``cfg`` (the content address) and a
    ``warmup`` to time — a stub keeps the matrix off the compiler."""

    def __init__(self, cfg=None, delay=0.0):
        from tpu_operator.workloads.serving import ServingModelConfig

        self.cfg = cfg or ServingModelConfig()
        self.delay = delay
        self.warmups = 0

    def warmup(self, prompt_len):
        self.warmups += 1
        if self.delay:
            time.sleep(self.delay)


class CountingClient:
    WRITE_VERBS = ("create", "patch", "patch_status", "update", "update_status",
                   "delete", "apply", "apply_set")

    def __init__(self, inner):
        self._inner = inner
        self.writes = 0

    def __getattr__(self, name):
        attr = getattr(self._inner, name)
        if name in self.WRITE_VERBS and callable(attr):
            def counted(*a, **kw):
                self.writes += 1
                return attr(*a, **kw)

            return counted
        return attr


class DownClient:
    """Every call raises — the K003 'apiserver unreachable' shape."""

    def __getattr__(self, name):
        def down(*a, **kw):
            raise errors.ApiError("apiserver down")

        return down


def _v5e_node(name, elected=False, extra=None):
    node = make_tpu_node(name, "tpu-v5-lite-podslice", "2x4")
    node["metadata"]["labels"][consts.TPU_PRESENT_LABEL] = "true"
    if elected:
        node["metadata"]["labels"][consts.COMPILE_CACHE_ELECTED_LABEL] = (
            consts.COMPILE_CACHE_ELECTED
        )
    node["metadata"]["labels"].update(extra or {})
    return node


def _cache_cm(entries=None, requests=None):
    data = {}
    for gen, entry in (entries or {}).items():
        data[entry_key(gen)] = json.dumps(entry)
    if requests is not None:
        data[consts.COMPILE_PREWARM_REQUEST_KEY] = json.dumps(
            {"requests": requests})
    return new_object("v1", "ConfigMap", consts.COMPILE_CACHE_CONFIGMAP, NS,
                      data=data)


def _cluster(nodes, entries=None, requests=None, spec=None):
    store = FakeClient()
    for node in nodes:
        store.create(node)
    store.create(new_cluster_policy(spec=spec))
    if entries is not None or requests is not None:
        store.create(_cache_cm(entries, requests))
    return store


def _elected(store):
    return sorted(
        n["metadata"]["name"] for n in store.list("v1", "Node")
        if (n["metadata"].get("labels") or {}).get(
            consts.COMPILE_CACHE_ELECTED_LABEL) == consts.COMPILE_CACHE_ELECTED
    )


# ---------------------------------------------------------------------------
# Cache keying + invalidation (mirrors test_autotune's TestCacheKeying).
# ---------------------------------------------------------------------------


class TestCacheKeying:
    def test_complete_entry_valid(self):
        assert entry_valid(_centry(), "1.0.0")

    def test_libtpu_version_invalidates(self):
        assert not entry_valid(_centry(version="1.0.0"), "1.1.0")

    def test_empty_record_map_invalid(self):
        assert not entry_valid(_centry(records={}), "1.0.0")

    def test_record_resolves_only_its_content_address(self):
        entry = _centry()
        assert cache_record(entry, "2x4", "mhash", "1.0.0") == _record()
        # a different topology or model hash is a different executable
        assert cache_record(entry, "4x4", "mhash", "1.0.0") is None
        assert cache_record(entry, "2x4", "other", "1.0.0") is None
        assert cache_record(entry, "2x4", "mhash", "2.0.0") is None

    def test_parse_entry_tolerates_garbage(self):
        assert parse_entry(None) is None
        assert parse_entry("") is None
        assert parse_entry("{not json") is None
        assert parse_entry('["list"]') is None
        assert parse_entry('{"a": 1}') == {"a": 1}

    def test_parse_requests_tolerates_garbage(self):
        assert parse_requests(None) == {}
        assert parse_requests("{torn") == {}
        assert parse_requests('{"requests": ["not", "a", "map"]}') == {}
        assert parse_requests(
            '{"requests": {"rid": {"generation": "v5e"}, "bad": 3}}'
        ) == {"rid": {"generation": "v5e"}}

    def test_cached_entries_skips_handshake_keys_and_torn_blobs(self):
        data = {
            entry_key("v5e"): json.dumps(_centry()),
            entry_key("v4"): "{torn",
            consts.COMPILE_PREWARM_REQUEST_KEY: json.dumps({"requests": {}}),
            consts.COMPILE_PREWARM_ACK_KEY: json.dumps({"acks": {}}),
            "not-an-entry": "x",
        }
        assert set(cached_entries(data)) == {"v5e"}

    def test_model_hash_tracks_model_geometry(self):
        from tpu_operator.workloads.serving import ServingModelConfig

        base = ServingModelConfig()
        assert model_descriptor_hash(base) == model_descriptor_hash(
            ServingModelConfig())
        assert model_descriptor_hash(base) != model_descriptor_hash(
            ServingModelConfig(max_seq=32))

    def test_request_id_composition(self):
        assert request_id("v5e", "2x4", "mhash") == "v5e/2x4/mhash"
        assert request_id("v5e", "", "mhash") == "v5e/any/mhash"


# ---------------------------------------------------------------------------
# The worker warm-start path.
# ---------------------------------------------------------------------------


class TestWarmStart:
    def _store(self, client):
        return CompileCacheStore(client, NS, libtpu_version="1.0.0")

    def test_miss_measures_and_publishes(self):
        compilecache.reset_stats()
        store = self._store(FakeClient())
        engine = StubEngine(delay=0.01)
        outcome, seconds = store.warm_start(engine, "v5e", "2x4", serving="svc")
        assert outcome == "miss" and engine.warmups == 1
        assert seconds >= 0.01
        entry = parse_entry(store.read_data()[entry_key("v5e")])
        record = cache_record(
            entry, "2x4", model_descriptor_hash(engine.cfg), "1.0.0")
        assert record["source"] == "worker" and record["serving"] == "svc"
        assert record["seconds"] == pytest.approx(seconds, abs=0.01)
        assert compilecache.stats()["misses"] == {"v5e": 1}

    def test_hit_replays_recorded_cost_and_writes_nothing(self):
        # on the CPU sim a hit REPLAYS the recorded cold cost at the warm
        # fraction (there is no executable store to deserialize from) —
        # hit-vs-miss stays an observable, benchable quantity
        compilecache.reset_stats()
        inner = FakeClient()
        store = self._store(inner)
        cold = store.warm_start(StubEngine(delay=0.02), "v5e", "2x4")[1]
        client = CountingClient(inner)
        store = self._store(client)
        outcome, warm = store.warm_start(StubEngine(delay=0.02), "v5e", "2x4")
        assert outcome == "hit" and client.writes == 0
        assert warm == pytest.approx(cold * WARM_FRACTION, abs=0.01)
        assert warm < cold
        assert compilecache.stats()["hits"] == {"v5e": 1}

    def test_unkeyed_engine_skips_cache(self):
        compilecache.reset_stats()
        client = CountingClient(FakeClient())
        outcome, _ = self._store(client).warm_start(StubEngine(), "", "2x4")
        assert outcome == "unkeyed" and client.writes == 0
        assert compilecache.stats()["hits"] == {}
        assert compilecache.stats()["misses"] == {}

    def test_unreachable_api_compiles_cold_without_raising(self):
        # resolve on a dead apiserver counts a miss (compiling is safe,
        # merely cold) and the best-effort publish swallows the failure
        compilecache.reset_stats()
        engine = StubEngine()
        outcome, _ = self._store(DownClient()).warm_start(engine, "v5e", "2x4")
        assert outcome == "miss" and engine.warmups == 1

    def test_read_data_distinguishes_missing_from_unreachable(self):
        assert self._store(FakeClient()).read_data() == {}
        assert self._store(DownClient()).read_data() is None  # K003

    def test_version_bump_replaces_stale_entry_wholesale(self):
        inner = FakeClient()
        inner.create(_cache_cm(entries={"v5e": _centry(version="0.9.0")}))
        store = self._store(inner)
        store.publish("v5e", "4x4", "newhash", 2.0)
        entry = parse_entry(store.read_data()[entry_key("v5e")])
        assert entry["libtpu_version"] == "1.0.0"
        # the stale toolchain's records did not survive into the rewrite
        assert list(entry["records"]) == [record_key("4x4", "newhash")]

    def test_publish_keeps_sibling_records_for_same_toolchain(self):
        inner = FakeClient()
        store = self._store(inner)
        store.publish("v5e", "2x4", "a", 1.0)
        store.publish("v5e", "4x4", "b", 2.0)
        entry = parse_entry(store.read_data()[entry_key("v5e")])
        assert set(entry["records"]) == {
            record_key("2x4", "a"), record_key("4x4", "b")}


# ---------------------------------------------------------------------------
# The serving controller's prewarm scheduling.
# ---------------------------------------------------------------------------


def _serving(name="svc", generation="v5e", shape="2x4"):
    obj = new_tpu_serving(name, {
        "model": {"shape": shape, "generation": generation},
        "minReplicas": 1, "maxReplicas": 2,
    })
    return obj, TPUServing.from_unstructured(obj)


class TestServingPrewarm:
    def test_uncached_key_requests_prewarm(self):
        store = FakeClient()
        obj, serving = _serving()
        ServingReconciler(store, NS)._reconcile_prewarm(obj, serving, {})
        cm = store.get("v1", "ConfigMap", consts.COMPILE_CACHE_CONFIGMAP, NS)
        requests = parse_requests(cm["data"][consts.COMPILE_PREWARM_REQUEST_KEY])
        rid = request_id("v5e", "2x4", model_descriptor_hash())
        assert requests[rid]["serving"] == "svc"
        assert requests[rid]["generation"] == "v5e"

    def test_request_is_idempotent(self):
        store = FakeClient()
        obj, serving = _serving()
        sr = ServingReconciler(store, NS)
        sr._reconcile_prewarm(obj, serving, {})
        client = CountingClient(store)
        ServingReconciler(client, NS)._reconcile_prewarm(obj, serving, {})
        assert client.writes == 0

    def test_cached_key_clears_its_request(self):
        rid = request_id("v5e", "2x4", model_descriptor_hash())
        store = FakeClient()
        store.create(_cache_cm(
            entries={"v5e": _centry(records={
                record_key("2x4", model_descriptor_hash()): _record()})},
            requests={rid: {"generation": "v5e", "topology": "2x4",
                            "model": model_descriptor_hash(), "serving": "svc"}},
        ))
        obj, serving = _serving()
        ServingReconciler(store, NS)._reconcile_prewarm(obj, serving, {})
        cm = store.get("v1", "ConfigMap", consts.COMPILE_CACHE_CONFIGMAP, NS)
        assert parse_requests(cm["data"][consts.COMPILE_PREWARM_REQUEST_KEY]) == {}

    def test_unreadable_cache_fails_closed(self):
        # K003: the cache read GATES the request write — unreachable
        # apiserver means unknown state, so no prewarm is scheduled
        # (a duplicate compile is cheap; the rule is the point)
        obj, serving = _serving()
        client = CountingClient(DownClient())
        ServingReconciler(client, NS)._reconcile_prewarm(obj, serving, {})
        assert client.writes == 0

    def test_generationless_serving_never_requests(self):
        obj, serving = _serving(generation="")
        client = CountingClient(FakeClient())
        ServingReconciler(client, NS)._reconcile_prewarm(obj, serving, {})
        assert client.writes == 0


class _pinned_version:
    def __init__(self, version):
        self.version = version

    def __enter__(self):
        import os

        self._old = os.environ.get("LIBTPU_VERSION")
        os.environ["LIBTPU_VERSION"] = self.version
        return self

    def __exit__(self, *exc):
        import os

        if self._old is None:
            os.environ.pop("LIBTPU_VERSION", None)
        else:
            os.environ["LIBTPU_VERSION"] = self._old


# ---------------------------------------------------------------------------
# The agent (mirrors test_autotune's TestAutotuneAgent).
# ---------------------------------------------------------------------------


def _request(gen="v5e", topology="2x4", model="mhash", serving="svc"):
    return {"generation": gen, "topology": topology, "model": model,
            "serving": serving}


def _fake_warm(calls=None, seconds=1.5):
    def warm_fn(request, version):
        if calls is not None:
            calls.append(request.get("generation"))
        return seconds

    return warm_fn


class TestCompileCacheAgent:
    @pytest.fixture(autouse=True)
    def _pin(self, monkeypatch):
        monkeypatch.setenv("LIBTPU_VERSION", "1.0.0")

    def test_not_elected_is_noop(self):
        store = FakeClient()
        store.create(_v5e_node("n-0"))
        client = CountingClient(store)
        agent = CompileCacheAgent(client, "n-0", NS, warm_fn=_fake_warm())
        assert agent.reconcile_once() == "not-elected"
        assert client.writes == 0

    def test_elected_compiles_publishes_and_acks(self):
        store = FakeClient()
        store.create(_v5e_node("n-0", elected=True))
        store.create(_cache_cm(requests={
            request_id("v5e", "2x4", "mhash"): _request()}))
        calls = []
        agent = CompileCacheAgent(store, "n-0", NS, warm_fn=_fake_warm(calls))
        assert agent.reconcile_once() == "prewarmed"
        assert calls == ["v5e"]
        data = store.get(
            "v1", "ConfigMap", consts.COMPILE_CACHE_CONFIGMAP, NS)["data"]
        record = cache_record(
            parse_entry(data[entry_key("v5e")]), "2x4", "mhash", "1.0.0")
        assert record["seconds"] == 1.5 and record["source"] == "prewarm"
        assert record["node"] == "n-0" and record["serving"] == "svc"
        acks = parse_entry(data[consts.COMPILE_PREWARM_ACK_KEY])["acks"]
        assert acks[request_id("v5e", "2x4", "mhash")]["outcome"] == "prewarmed"

    def test_satisfied_request_is_zero_write_cache_hit(self):
        store = FakeClient()
        store.create(_v5e_node("n-0", elected=True))
        store.create(_cache_cm(
            entries={"v5e": _centry()},
            requests={request_id("v5e", "2x4", "mhash"): _request()},
        ))
        client = CountingClient(store)
        calls = []
        agent = CompileCacheAgent(client, "n-0", NS, warm_fn=_fake_warm(calls))
        assert agent.reconcile_once() == "cache-hit"
        assert calls == [] and client.writes == 0

    def test_other_generations_requests_are_not_mine(self):
        store = FakeClient()
        store.create(_v5e_node("n-0", elected=True))
        store.create(_cache_cm(requests={
            request_id("v4", "4x4x4", "mhash"): _request(gen="v4",
                                                         topology="4x4x4")}))
        agent = CompileCacheAgent(store, "n-0", NS, warm_fn=_fake_warm())
        assert agent.reconcile_once() == "no-requests"

    def test_stale_entry_recompiles(self):
        store = FakeClient()
        store.create(_v5e_node("n-0", elected=True))
        store.create(_cache_cm(
            entries={"v5e": _centry(version="0.9.0")},
            requests={request_id("v5e", "2x4", "mhash"): _request()},
        ))
        calls = []
        agent = CompileCacheAgent(store, "n-0", NS, warm_fn=_fake_warm(calls))
        assert agent.reconcile_once() == "prewarmed"
        assert calls == ["v5e"]


# ---------------------------------------------------------------------------
# The controller (mirrors test_autotune's TestAutotuneController).
# ---------------------------------------------------------------------------


class TestCompileCacheController:
    def test_elects_one_node_per_generation_with_demand(self):
        v4 = make_tpu_node("v4-b", "tpu-v4-podslice", "2x2x1")
        v4["metadata"]["labels"][consts.TPU_PRESENT_LABEL] = "true"
        store = _cluster(
            [_v5e_node("v5e-b"), _v5e_node("v5e-a"), v4],
            requests={
                request_id("v5e", "2x4", "mhash"): _request(),
                request_id("v4", "2x2x1", "mhash"): _request(
                    gen="v4", topology="2x2x1"),
            },
        )
        CompileCacheReconciler(store, NS).reconcile(REQ)
        assert _elected(store) == ["v4-b", "v5e-a"]

    def test_satisfied_demand_holds_no_election(self):
        store = _cluster(
            [_v5e_node("v5e-a")],
            entries={"v5e": _centry()},
            requests={request_id("v5e", "2x4", "mhash"): _request()},
        )
        CompileCacheReconciler(store, NS).reconcile(REQ)
        assert _elected(store) == []

    def test_out_of_service_nodes_never_elected(self):
        store = _cluster(
            [
                _v5e_node("v5e-a",
                          extra={consts.TPU_PERF_LABEL: consts.PERF_DEGRADED}),
                _v5e_node("v5e-b"),
            ],
            requests={request_id("v5e", "2x4", "mhash"): _request()},
        )
        CompileCacheReconciler(store, NS).reconcile(REQ)
        assert _elected(store) == ["v5e-b"]

    def test_election_sticky_while_pending(self):
        store = _cluster(
            [_v5e_node("v5e-z", elected=True), _v5e_node("v5e-a")],
            requests={request_id("v5e", "2x4", "mhash"): _request()},
        )
        CompileCacheReconciler(store, NS).reconcile(REQ)
        assert _elected(store) == ["v5e-z"]

    def test_orphan_election_cleared_when_demand_vanishes(self):
        store = _cluster([_v5e_node("v5e-a", elected=True)])
        CompileCacheReconciler(store, NS).reconcile(REQ)
        assert _elected(store) == []

    def test_settled_pass_issues_zero_writes(self):
        store = _cluster(
            [_v5e_node("v5e-a")],
            entries={"v5e": _centry()},
            requests={request_id("v5e", "2x4", "mhash"): _request()},
        )
        client = CountingClient(store)
        rec = CompileCacheReconciler(client, NS)
        rec.reconcile(REQ)
        client.writes = 0
        rec.reconcile(REQ)
        assert client.writes == 0

    def test_libtpu_bump_invalidates_exactly_the_stale_generation(self):
        store = _cluster(
            [_v5e_node("v5e-a")],
            entries={"v5e": _centry(), "v4": _centry(gen="v4", version="0.9.0")},
        )
        CompileCacheReconciler(store, NS).reconcile(REQ)
        data = store.get(
            "v1", "ConfigMap", consts.COMPILE_CACHE_CONFIGMAP, NS)["data"]
        assert entry_key("v4") not in data  # stale: deleted
        assert entry_key("v5e") in data  # current toolchain: untouched

    def test_invalidated_key_re_elects_and_recompiles_once(self):
        # the full bump loop: stale entry deleted -> the standing request
        # is unsatisfied again -> election -> ONE recompile
        rid = request_id("v5e", "2x4", "mhash")
        store = _cluster(
            [_v5e_node("v5e-a")],
            entries={"v5e": _centry(version="0.9.0")},
            requests={rid: _request()},
        )
        rec = CompileCacheReconciler(store, NS)
        rec.reconcile(REQ)
        assert _elected(store) == ["v5e-a"]
        calls = []
        with _pinned_version("1.0.0"):
            agent = CompileCacheAgent(store, "v5e-a", NS,
                                      warm_fn=_fake_warm(calls))
            assert agent.reconcile_once() == "prewarmed"
            assert calls == ["v5e"]
            # a re-run while still elected (rebooted elected node) is a
            # zero-write cache hit — compile-once, fleet-wide
            client = CountingClient(store)
            rerun = CompileCacheAgent(client, "v5e-a", NS,
                                      warm_fn=_fake_warm(calls))
            assert rerun.reconcile_once() == "cache-hit"
            assert calls == ["v5e"] and client.writes == 0
            # the record satisfies the demand: the election clears
            rec.reconcile(REQ)
            assert _elected(store) == []
            assert rerun.reconcile_once() == "not-elected"

    def test_disabled_spec_clears_elections(self):
        store = _cluster(
            [_v5e_node("v5e-a", elected=True)],
            spec={"compileCache": {"enabled": False}},
        )
        CompileCacheReconciler(store, NS).reconcile(REQ)
        assert _elected(store) == []

    def test_compile_series_retire_with_their_entry(self):
        store = _cluster(
            [_v5e_node("v5e-a")],
            entries={"v5e": _centry(records={
                record_key("2x4", "mhash"): _record(serving="retire-me")})},
        )
        rec = CompileCacheReconciler(store, NS)
        rec.reconcile(REQ)
        assert ("retire-me", "v5e") in rec.metrics.compile_seconds._metrics
        # toolchain bump invalidates the entry -> the series goes too
        cm = store.get("v1", "ConfigMap", consts.COMPILE_CACHE_CONFIGMAP, NS)
        cm["data"][entry_key("v5e")] = json.dumps(_centry(
            version="0.9.0",
            records={record_key("2x4", "mhash"): _record(serving="retire-me")},
        ))
        store.update(cm)
        rec.reconcile(REQ)
        assert ("retire-me", "v5e") not in rec.metrics.compile_seconds._metrics

    def test_hit_miss_counters_export_and_retire(self):
        compilecache.reset_stats()
        store = _cluster([_v5e_node("v5e-a")], entries={"v5e": _centry()})
        cstore = CompileCacheStore(FakeClient(), NS, libtpu_version="1.0.0")
        cstore.resolve("v5e", "2x4", "mhash")  # miss on the empty store
        rec = CompileCacheReconciler(store, NS)
        rec.reconcile(REQ)
        assert ("v5e",) in rec.metrics.compile_cache_misses._metrics
        compilecache.reset_stats()
        rec.reconcile(REQ)
        assert ("v5e",) not in rec.metrics.compile_cache_misses._metrics


# ---------------------------------------------------------------------------
# Planning prices the compile.
# ---------------------------------------------------------------------------


class TestPlanningCompileCost:
    def test_warm_strictly_below_cold(self):
        entries = {"v5e": _centry(records={
            record_key("2x4", "mhash"): _record(seconds=40.0)})}
        warm, warm_flag = compile_cost_seconds(
            "v5e", "2x4", "mhash", entries=entries, libtpu_version="1.0.0")
        cold, cold_flag = compile_cost_seconds(
            "v5e", "2x4", "mhash", entries={}, libtpu_version="1.0.0")
        assert warm_flag and not cold_flag
        assert 0.0 < warm < cold
        # the measured record, not the generation default, is the base
        assert warm == pytest.approx(40.0 * WARM_FRACTION)

    def test_stale_record_prices_cold(self):
        entries = {"v5e": _centry(version="0.9.0")}
        cost, warm = compile_cost_seconds(
            "v5e", "2x4", "mhash", entries=entries, libtpu_version="1.0.0")
        assert not warm and cost == compile_cost_seconds(
            "v5e", "2x4", "mhash", entries={}, libtpu_version="1.0.0")[0]

    def test_whatif_eta_folds_compile(self):
        from tpu_operator.kube.sim import make_torus_nodes

        nodes = make_torus_nodes((2, 2, 1), prefix="plan",
                                 accelerator="tpu-v5-lite-podslice")
        entries = {"v5e": _centry(records={
            record_key("1x1x1", "mhash"): _record(seconds=40.0)})}
        warm = admission_answer([], nodes, "1x1x1", compile_entries=entries,
                                libtpu_version="1.0.0", model_hash="mhash")
        cold = admission_answer([], nodes, "1x1x1", compile_entries={},
                                libtpu_version="1.0.0", model_hash="mhash")
        assert warm["answer"] == "now" and cold["answer"] == "now"
        assert warm["compile_warm"] and not cold["compile_warm"]
        assert warm["eta_seconds"] < cold["eta_seconds"]
        assert "compile" in warm["detail"]

    def test_plan_report_threads_compile_pricing(self):
        from tpu_operator.kube.sim import make_torus_nodes
        from tpu_operator.planning.whatif import plan_report

        nodes = make_torus_nodes((2, 2, 1), prefix="plan",
                                 accelerator="tpu-v5-lite-podslice")
        entries = {"v5e": _centry(records={
            record_key("1x1x1", "mhash"): _record(seconds=40.0)})}
        warm = plan_report([], nodes, shape="1x1x1", compile_entries=entries,
                           libtpu_version="1.0.0", model_hash="mhash")
        cold = plan_report([], nodes, shape="1x1x1", compile_entries={},
                           libtpu_version="1.0.0", model_hash="mhash")
        assert "warm compile" in warm
        assert "cold compile" in cold

    def test_whatif_without_entries_stays_unpriced(self):
        from tpu_operator.kube.sim import make_torus_nodes

        nodes = make_torus_nodes((2, 2, 1), prefix="plan",
                                 accelerator="tpu-v5-lite-podslice")
        legacy = admission_answer([], nodes, "1x1x1")
        assert legacy["answer"] == "now"
        assert "compile_seconds" not in legacy
