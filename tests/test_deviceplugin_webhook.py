"""Device plugin gRPC round-trip (stub kubelet), webhook reviews, typed
clientset tests."""

import json
import urllib.request

import grpc
import pytest

from tpu_operator import consts
from tpu_operator.agents.device_plugin_agent import (
    API_VERSION,
    PLUGIN_SOCKET_NAME,
    TPUDevicePlugin,
)
from tpu_operator.agents.dpapi import deviceplugin_pb2 as pb
from tpu_operator.api.clusterpolicy import new_cluster_policy
from tpu_operator.api.tpuslice import new_tpu_slice
from tpu_operator.api.versioned import Clientset
from tpu_operator.kube.sim import StubKubelet, make_tpu_node
from tpu_operator.webhook import WebhookServer, handle_review


class TestDevicePlugin:
    def test_full_round_trip(self, tmp_path):
        socket_dir = str(tmp_path)
        kubelet_sock = str(tmp_path / "kubelet.sock")
        kubelet = StubKubelet(kubelet_sock)
        plugin = TPUDevicePlugin(
            socket_dir=socket_dir,
            devices=["/dev/accel0", "/dev/accel1", "/dev/accel2", "/dev/accel3"],
        )
        try:
            plugin.serve()
            plugin.register(kubelet_sock)
            assert kubelet.event.wait(5)
            req = kubelet.requests[0]
            assert req.version == API_VERSION
            assert req.resource_name == consts.TPU_RESOURCE_NAME
            assert req.endpoint == PLUGIN_SOCKET_NAME

            # kubelet-side: dial the plugin like the kubelet would
            channel = grpc.insecure_channel(f"unix://{plugin.socket_path}")
            law = channel.unary_stream(
                "/v1beta1.DevicePlugin/ListAndWatch",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=pb.ListAndWatchResponse.FromString,
            )
            stream = law(pb.Empty())
            first = next(stream)
            assert [d.ID for d in first.devices] == ["accel0", "accel1", "accel2", "accel3"]
            assert all(d.health == "Healthy" for d in first.devices)

            allocate = channel.unary_unary(
                "/v1beta1.DevicePlugin/Allocate",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=pb.AllocateResponse.FromString,
            )
            resp = allocate(
                pb.AllocateRequest(
                    container_requests=[pb.ContainerAllocateRequest(devicesIDs=["accel0", "accel2"])]
                )
            )
            ctr = resp.container_responses[0]
            assert [d.host_path for d in ctr.devices] == ["/dev/accel0", "/dev/accel2"]
            assert ctr.envs["TPU_VISIBLE_CHIPS"] == "0,2"
            assert ctr.mounts[0].host_path == consts.LIBTPU_INSTALL_DIR
            channel.close()
        finally:
            plugin.stop()
            kubelet.stop()

    def test_inventory_change_republished(self, tmp_path):
        plugin = TPUDevicePlugin(socket_dir=str(tmp_path), devices=["/dev/accel0"])
        try:
            plugin.serve()
            channel = grpc.insecure_channel(f"unix://{plugin.socket_path}")
            law = channel.unary_stream(
                "/v1beta1.DevicePlugin/ListAndWatch",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=pb.ListAndWatchResponse.FromString,
            )
            stream = law(pb.Empty())
            assert len(next(stream).devices) == 1
            plugin._devices_override = ["/dev/accel0", "/dev/accel1"]
            plugin._publish(plugin.discover())
            assert len(next(stream).devices) == 2
            channel.close()
        finally:
            plugin.stop()


class TestWebhook:
    def review(self, kind, obj, operation="CREATE"):
        return {
            "apiVersion": "admission.k8s.io/v1",
            "kind": "AdmissionReview",
            "request": {"uid": "u1", "operation": operation, "object": obj},
        }

    def test_valid_clusterpolicy_allowed(self):
        cs = Clientset.fake()
        result = handle_review(cs.raw, "/validate-clusterpolicy", self.review("cp", new_cluster_policy()))
        assert result["response"]["allowed"] is True
        assert result["response"]["uid"] == "u1"

    def test_second_clusterpolicy_denied(self):
        cs = Clientset.fake(seed=[new_cluster_policy("first")])
        result = handle_review(
            cs.raw, "/validate-clusterpolicy", self.review("cp", new_cluster_policy("second"))
        )
        assert result["response"]["allowed"] is False
        assert "singleton" in result["response"]["status"]["message"]

    def test_bad_enabled_type_denied(self):
        obj = new_cluster_policy(spec={"devicePlugin": {"enabled": "yes"}})
        result = handle_review(None, "/validate-clusterpolicy", self.review("cp", obj))
        assert result["response"]["allowed"] is False

    def test_overlapping_tpuslice_denied(self):
        node = make_tpu_node("n0")
        node["metadata"]["labels"][consts.TPU_PRESENT_LABEL] = "true"
        cs = Clientset.fake(seed=[node, new_tpu_slice("a")])
        result = handle_review(cs.raw, "/validate-tpuslice", self.review("ts", new_tpu_slice("b")))
        assert result["response"]["allowed"] is False
        assert "already selected" in result["response"]["status"]["message"]

    def test_http_server_round_trip(self):
        server = WebhookServer(None, addr=("127.0.0.1", 0)).start()
        try:
            host, port = server.address
            body = json.dumps(self.review("cp", new_cluster_policy())).encode()
            req = urllib.request.Request(
                f"http://{host}:{port}/validate-clusterpolicy", data=body,
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req) as resp:
                result = json.loads(resp.read())
            assert result["response"]["allowed"] is True
        finally:
            server.stop()


class TestTypedClientset:
    def test_round_trip(self):
        cs = Clientset.fake()
        from tpu_operator.api.clusterpolicy import ClusterPolicy

        cp = ClusterPolicy.from_unstructured(new_cluster_policy())
        created = cs.cluster_policies.create(cp)
        assert created.name == "cluster-policy"
        assert cs.cluster_policies.get("cluster-policy").spec.libtpu.is_enabled()
        created.status.state = "ready"
        cs.cluster_policies.update_status(created)
        assert cs.cluster_policies.get("cluster-policy").status.state == "ready"
        assert len(cs.cluster_policies.list()) == 1
        cs.cluster_policies.delete("cluster-policy")
        assert cs.cluster_policies.get_or_none("cluster-policy") is None

    def test_tpu_slices(self):
        cs = Clientset.fake(seed=[new_tpu_slice("a")])
        slices = cs.tpu_slices.list()
        assert len(slices) == 1 and slices[0].name == "a"


class TestWebhookTLS:
    def test_https_round_trip_with_self_signed_cert(self, tmp_path):
        import ssl as ssl_mod

        pytest.importorskip("cryptography", reason="self-signed serving cert needs x509")
        from tpu_operator.webhook import generate_self_signed_cert

        cert, key, ca_b64 = generate_self_signed_cert(str(tmp_path))
        assert ca_b64
        server = WebhookServer(None, addr=("127.0.0.1", 0), cert_file=cert, key_file=key).start()
        try:
            host, port = server.address
            ctx = ssl_mod.create_default_context(cafile=cert)
            ctx.check_hostname = False
            body = json.dumps({"request": {"uid": "u1", "operation": "CREATE",
                                            "object": new_cluster_policy()}}).encode()
            req = urllib.request.Request(
                f"https://{host}:{port}/validate-clusterpolicy", data=body,
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, context=ctx) as resp:
                result = json.loads(resp.read())
            assert result["response"]["allowed"] is True
        finally:
            server.stop()


class TestChartWebhook:
    def test_webhook_objects_rendered_when_enabled(self):
        from tpu_operator.chart import render_chart

        objs = render_chart({"webhook": {"enabled": True, "caBundle": "QUJD"}})
        vwc = [o for o in objs if o["kind"] == "ValidatingWebhookConfiguration"]
        assert len(vwc) == 1
        hooks = vwc[0]["webhooks"]
        assert {h["name"] for h in hooks} == {"clusterpolicy.tpu.google.com", "tpuslice.tpu.google.com"}
        assert all(h["clientConfig"]["caBundle"] == "QUJD" for h in hooks)
        # disabled by default
        assert not [o for o in render_chart({}) if o["kind"] == "ValidatingWebhookConfiguration"]


class TestChartWebhookServing:
    def test_deployment_wired_when_webhook_enabled(self):
        from tpu_operator.chart import render_chart

        objs = render_chart({"webhook": {"enabled": True, "caBundle": "QUJD",
                                          "tlsCrt": "Y3J0", "tlsKey": "a2V5"}})
        deploy = [o for o in objs if o["kind"] == "Deployment"][0]
        ctr = deploy["spec"]["template"]["spec"]["containers"][0]
        assert "--webhook-cert-dir=/etc/tpu-operator/webhook-certs" in ctr["args"]
        assert {"name": "webhook", "containerPort": 9443} in ctr["ports"]
        assert ctr["volumeMounts"][0]["name"] == "webhook-certs"
        secret = [o for o in objs if o["kind"] == "Secret"][0]
        assert secret["type"] == "kubernetes.io/tls"
        assert secret["data"]["tls.crt"] == "Y3J0"
        # disabled: no webhook plumbing in the deployment
        objs_off = render_chart({})
        deploy_off = [o for o in objs_off if o["kind"] == "Deployment"][0]
        ctr_off = deploy_off["spec"]["template"]["spec"]["containers"][0]
        assert not any("webhook" in a for a in ctr_off["args"])
        assert not [o for o in objs_off if o["kind"] == "Secret"]


class TestPluginConfig:
    def seed_configmap(self, client):
        from tpu_operator.kube.objects import new_object

        client.create(new_object(
            "v1", "ConfigMap", "plugin-config", "tpu-operator",
            data={
                "default": "replicas: 1\n",
                "time-shared": "replicas: 4\n",
                "broken": "{not yaml",
            },
        ))

    def test_default_config_selected(self):
        from tpu_operator.agents.device_plugin_agent import select_plugin_config

        cs = Clientset.fake()
        self.seed_configmap(cs.raw)
        cs.raw.create(make_tpu_node("n0"))
        cfg = select_plugin_config(cs.raw, "n0", "plugin-config", "tpu-operator", default="default")
        assert cfg == {"replicas": 1}

    def test_node_label_overrides(self):
        from tpu_operator.agents.device_plugin_agent import (
            PLUGIN_CONFIG_LABEL,
            select_plugin_config,
        )

        cs = Clientset.fake()
        self.seed_configmap(cs.raw)
        node = make_tpu_node("n0", extra_labels={PLUGIN_CONFIG_LABEL: "time-shared"})
        cs.raw.create(node)
        cfg = select_plugin_config(cs.raw, "n0", "plugin-config", "tpu-operator", default="default")
        assert cfg == {"replicas": 4}

    def test_invalid_yaml_is_empty(self):
        from tpu_operator.agents.device_plugin_agent import (
            PLUGIN_CONFIG_LABEL,
            select_plugin_config,
        )

        cs = Clientset.fake()
        self.seed_configmap(cs.raw)
        cs.raw.create(make_tpu_node("n0", extra_labels={PLUGIN_CONFIG_LABEL: "broken"}))
        assert select_plugin_config(cs.raw, "n0", "plugin-config", "tpu-operator") == {}

    def test_replicas_advertise_shared_chips(self, tmp_path):
        plugin = TPUDevicePlugin(
            socket_dir=str(tmp_path),
            devices=["/dev/accel0", "/dev/accel1"],
            config={"replicas": 2},
        )
        resp = plugin._device_list(plugin.discover())
        assert [d.ID for d in resp.devices] == [
            "accel0-rep0", "accel0-rep1", "accel1-rep0", "accel1-rep1"]
        # allocation of two replicas of the same chip injects ONE device node
        alloc = plugin.Allocate(
            pb.AllocateRequest(container_requests=[
                pb.ContainerAllocateRequest(devicesIDs=["accel0-rep0", "accel0-rep1"])]),
            None,
        )
        ctr = alloc.container_responses[0]
        assert [d.host_path for d in ctr.devices] == ["/dev/accel0"]
        assert ctr.envs["TPU_VISIBLE_CHIPS"] == "0"


class TestGangEnvIntegration:
    def test_slice_manager_configmap_feeds_distributed_config(self):
        """slice manager gang ConfigMap -> the env contract ->
        workloads.distributed bring-up: the full multi-host wiring story."""
        from tpu_operator.agents.slice_manager_agent import SliceManagerAgent
        from tpu_operator.workloads.distributed import config_from_env

        cs = Clientset.fake()
        for i in range(4):
            node = make_tpu_node(f"v5e-{i}", "tpu-v5-lite-podslice", "4x4", nodepool="pool-a")
            node["metadata"]["labels"][consts.TPU_PRESENT_LABEL] = "true"
            cs.raw.create(node)
        agent = SliceManagerAgent(cs.raw, "tpu-operator")
        (name,) = agent.reconcile_once()
        cm = cs.raw.get("v1", "ConfigMap", f"{name}-gang", "tpu-operator")
        # a worker pod gets the ConfigMap as env + its node's worker id
        node = cs.raw.get("v1", "Node", "v5e-2")
        env = dict(cm["data"])
        env["TPU_WORKER_ID"] = node["metadata"]["labels"]["tpu.google.com/worker-id"]
        dist = config_from_env(env)
        assert dist.needed
        assert dist.num_processes == 4
        assert dist.process_id == 2
        assert dist.coordinator_address.startswith(f"{name}-0.{name}.tpu-operator.svc")


class TestPreferredAllocation:
    def test_contiguous_window_preferred(self, tmp_path):
        plugin = TPUDevicePlugin(socket_dir=str(tmp_path), devices=[])
        resp = plugin.GetPreferredAllocation(
            pb.PreferredAllocationRequest(container_requests=[
                pb.ContainerPreferredAllocationRequest(
                    available_deviceIDs=["accel0", "accel3", "accel4", "accel5", "accel7"],
                    allocation_size=3,
                )
            ]),
            None,
        )
        assert list(resp.container_responses[0].deviceIDs) == ["accel3", "accel4", "accel5"]

    def test_must_include_respected(self, tmp_path):
        plugin = TPUDevicePlugin(socket_dir=str(tmp_path), devices=[])
        resp = plugin.GetPreferredAllocation(
            pb.PreferredAllocationRequest(container_requests=[
                pb.ContainerPreferredAllocationRequest(
                    available_deviceIDs=["accel0", "accel1", "accel2", "accel6", "accel7"],
                    must_include_deviceIDs=["accel7"],
                    allocation_size=2,
                )
            ]),
            None,
        )
        assert "accel7" in list(resp.container_responses[0].deviceIDs)


class TestTorusPreferredAllocation:
    def test_2x2_face_beats_index_line(self, tmp_path, monkeypatch):
        """On a 4x4 block, chips 0,1,4,5 form a 2x2 face (pairwise torus
        distance 8) while the index-contiguous 0,1,2,3 is a line (10) —
        coordinates must win over the window heuristic."""
        monkeypatch.setenv("TPU_CHIPS_PER_HOST_BOUNDS", "4,4,1")
        plugin = TPUDevicePlugin(socket_dir=str(tmp_path), devices=[])
        resp = plugin.GetPreferredAllocation(
            pb.PreferredAllocationRequest(container_requests=[
                pb.ContainerPreferredAllocationRequest(
                    available_deviceIDs=[f"accel{i}" for i in range(6)],
                    allocation_size=4,
                )
            ]),
            None,
        )
        assert sorted(resp.container_responses[0].deviceIDs) == [
            "accel0", "accel1", "accel4", "accel5"
        ]

    def test_vertical_adjacency_beats_index_window(self, tmp_path, monkeypatch):
        """chips 0 (0,0) and 4 (0,1) are y-neighbors (dist 1) while the
        index-window pick {3,4} sits at opposite block corners (dist 4) —
        coordinates must win."""
        monkeypatch.setenv("TPU_CHIPS_PER_HOST_BOUNDS", "4,2,1")
        plugin = TPUDevicePlugin(socket_dir=str(tmp_path), devices=[])
        resp = plugin.GetPreferredAllocation(
            pb.PreferredAllocationRequest(container_requests=[
                pb.ContainerPreferredAllocationRequest(
                    available_deviceIDs=["accel0", "accel3", "accel4"],
                    allocation_size=2,
                )
            ]),
            None,
        )
        assert sorted(resp.container_responses[0].deviceIDs) == ["accel0", "accel4"]

    def test_must_include_and_replicas(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TPU_CHIPS_PER_HOST_BOUNDS", "2,2,1")
        plugin = TPUDevicePlugin(socket_dir=str(tmp_path), devices=[])
        resp = plugin.GetPreferredAllocation(
            pb.PreferredAllocationRequest(container_requests=[
                pb.ContainerPreferredAllocationRequest(
                    available_deviceIDs=["accel0-rep0", "accel1-rep0", "accel3-rep1"],
                    must_include_deviceIDs=["accel3-rep1"],
                    allocation_size=2,
                )
            ]),
            None,
        )
        got = list(resp.container_responses[0].deviceIDs)
        # accel1 (1,0) is adjacent to accel3 (1,1); accel0 (0,0) is diagonal
        assert sorted(got) == ["accel1-rep0", "accel3-rep1"]

    def test_chip_coords_native_and_python_agree(self, monkeypatch):
        from tpu_operator.native import tpuinfo

        monkeypatch.setenv("TPU_CHIPS_PER_HOST_BOUNDS", "2,2,2")
        assert tpuinfo.chip_coords() == tpuinfo._python_chip_coords(0)
        monkeypatch.delenv("TPU_CHIPS_PER_HOST_BOUNDS")
        assert tpuinfo.chip_coords(4) == tpuinfo._python_chip_coords(4)


class TestPreferredAllocationContract:
    def test_fallback_still_includes_musts(self, tmp_path):
        plugin = TPUDevicePlugin(socket_dir=str(tmp_path), devices=[])
        resp = plugin.GetPreferredAllocation(
            pb.PreferredAllocationRequest(container_requests=[
                pb.ContainerPreferredAllocationRequest(
                    available_deviceIDs=["accel0", "accel1", "accel6", "accel7"],
                    must_include_deviceIDs=["accel0", "accel7"],
                    allocation_size=2,
                )
            ]),
            None,
        )
        got = list(resp.container_responses[0].deviceIDs)
        assert set(got) >= {"accel0", "accel7"}

    def test_options_advertise_preferred_allocation(self, tmp_path):
        plugin = TPUDevicePlugin(socket_dir=str(tmp_path), devices=[])
        opts = plugin.GetDevicePluginOptions(pb.Empty(), None)
        assert opts.get_preferred_allocation_available is True
