"""Data-plane telemetry pipeline tests (ISSUE 8 tentpole).

Four layers under test:
  1. the step-time recorder (compile-vs-execute split, jitter
     percentiles, achieved TFLOP/s) and the gang merge's straggler
     ratio (workloads/telemetry.py),
  2. the exporter's perf-floor baselining + grey-failure detection:
     sustained breach flips ``tpu_exporter_perf_degraded`` and the
     ``tpu.google.com/perf`` node label, recovery clears both; probe
     FAILURE paths stay indeterminate (no verdict flip); collector
     registration is idempotent against a shared registry,
  3. the health FSM's grey-failure path: a perf-labelled node walks the
     same bounded cordon→revalidate→uncordon FSM, proven over the wire
     by the GreyFailureDrill (PDB-honoring eviction included) and under
     chaos faults by the rider,
  4. fleet aggregation: gang series from published artifacts, straggler
     Events, deliverable-TFLOP/s pricing, stale-series removal.
"""

import json
import time

import prometheus_client
import pytest

from tpu_operator import consts
from tpu_operator.agents.metrics_exporter_agent import MetricsExporterAgent
from tpu_operator.agents.slice_manager_agent import SliceManagerAgent
from tpu_operator.api.clusterpolicy import HealthMonitorSpec, new_cluster_policy
from tpu_operator.controllers.fleet_telemetry import FleetTelemetryAggregator
from tpu_operator.controllers.health_controller import NodeRepairManager, RepairState
from tpu_operator.kube.fake import FakeClient
from tpu_operator.kube.objects import new_object
from tpu_operator.kube.sim import make_tpu_node
from tpu_operator.perf import (
    FLOOR_FRACTION,
    default_floors,
    floors_for,
    floors_json,
    measured_roofs,
)
from tpu_operator.workloads.telemetry import (
    StepTimeRecorder,
    StepTimeReport,
    merge_gang_reports,
    publish_prometheus,
)

NS = "tpu-operator"


def sample(registry, name, **labels):
    return registry.get_sample_value(name, labels or None)


# ---------------------------------------------------------------------------
# layer 1: the step-time recorder + gang merge
# ---------------------------------------------------------------------------


class TestStepTimeRecorder:
    def test_compile_split_and_percentiles(self):
        rec = StepTimeRecorder(host="h0")
        delays = iter([0.03, 0.001, 0.001, 0.001, 0.004])
        rec.run(lambda: time.sleep(next(delays)), 5)
        r = rec.report()
        # the first (compiling) call never pollutes the distribution
        assert r.compile_s >= 0.03
        assert r.step_p50_s < 0.02
        assert r.step_max_s >= r.step_p99_s >= r.step_p50_s
        assert r.steps == 5 and r.total_s > 0
        assert r.host == "h0"

    def test_achieved_tflops(self):
        rec = StepTimeRecorder(flops_per_step=1e9)
        rec.run(lambda: time.sleep(0.001), 3)
        r = rec.report()
        # 1 GFLOP in ~1ms ≈ 1e12 FLOP/s = 1 TFLOP/s (generous bounds:
        # CI wall clocks jitter)
        assert r.tflops is not None and 0.05 < r.tflops < 1.2

    def test_no_steps_raises(self):
        with pytest.raises(RuntimeError):
            StepTimeRecorder().report()

    def test_report_roundtrip(self):
        rec = StepTimeRecorder(flops_per_step=1e9, host="w3")
        rec.run(lambda: time.sleep(0.001), 3)
        d = rec.report().to_dict()
        back = StepTimeReport.from_dict(d)
        assert back.to_dict() == d

    def test_gang_merge_straggler(self):
        reports = {
            f"h{i}": {"step_p50_s": 0.010, "tflops": 10.0} for i in range(3)
        }
        reports["h3"] = {"step_p50_s": 0.020, "tflops": 5.0}
        artifact = merge_gang_reports(reports)
        assert artifact["hosts"] == 4
        assert artifact["slowest_host"] == "h3"
        assert artifact["straggler_ratio"] == pytest.approx(2.0)
        assert artifact["gang_step_p50_s"] == pytest.approx(0.010)
        assert artifact["gang_tflops"] == pytest.approx(35.0)

    def test_gang_merge_uniform(self):
        artifact = merge_gang_reports({f"h{i}": {"step_p50_s": 0.01} for i in range(4)})
        assert artifact["straggler_ratio"] == pytest.approx(1.0)

    def test_gang_merge_empty_raises(self):
        with pytest.raises(ValueError):
            merge_gang_reports({})

    def test_gang_merge_single_host_reads_uniform(self):
        """A single-host gang has nobody to straggle behind: the ratio
        must read exactly 1.0, not divide-by-self noise, and the one
        host is (trivially) the slowest."""
        artifact = merge_gang_reports({"solo": {"step_p50_s": 0.042}})
        assert artifact["hosts"] == 1
        assert artifact["straggler_ratio"] == 1.0
        assert artifact["slowest_host"] == "solo"
        assert artifact["gang_step_p50_s"] == pytest.approx(0.042)

    def test_gang_merge_missing_member_is_reported(self):
        """A member whose report never arrived is a finding, not a
        smaller gang: expected_hosts surfaces it as missing_hosts (and
        the ratio covers only the hosts that measured)."""
        reports = {"h0": {"step_p50_s": 0.01}, "h1": {"step_p50_s": 0.01}}
        artifact = merge_gang_reports(
            reports, expected_hosts=["h0", "h1", "h2", "h3"]
        )
        assert artifact["missing_hosts"] == ["h2", "h3"]
        assert artifact["straggler_ratio"] == pytest.approx(1.0)
        # a complete gang carries no missing_hosts key at all
        full = merge_gang_reports(reports, expected_hosts=["h0", "h1"])
        assert "missing_hosts" not in full

    def test_gang_merge_zero_step_report_excluded_from_ratio(self):
        """A report with zero recorded steps (0.0 median) must not read
        as an infinitely fast host — it is excluded from the ratio; the
        measured hosts still produce an honest artifact."""
        reports = {
            "h0": {"step_p50_s": 0.010},
            "h1": {"step_p50_s": 0.010},
            "h2": {"step_p50_s": 0.0},  # recorded nothing
        }
        artifact = merge_gang_reports(reports)
        assert artifact["hosts"] == 3  # the gang size is the gang size
        assert artifact["straggler_ratio"] == pytest.approx(1.0)
        assert "h2" not in artifact["per_host_step_p50_s"]
        assert artifact["slowest_host"] in ("h0", "h1")

    def test_gang_merge_all_zero_reports(self):
        """Every report empty: a shape-correct artifact that cannot fake
        a measurement (ratio pinned to 1.0, no slowest host)."""
        artifact = merge_gang_reports(
            {"h0": {"step_p50_s": 0.0}, "h1": {}},
            expected_hosts=["h0", "h1", "h2"],
        )
        assert artifact["straggler_ratio"] == 1.0
        assert artifact["slowest_host"] == ""
        assert artifact["gang_step_p50_s"] == 0.0
        assert artifact["missing_hosts"] == ["h2"]

    def test_publish_prometheus_idempotent(self):
        reg = prometheus_client.CollectorRegistry()
        rec = StepTimeRecorder(flops_per_step=1e9)
        rec.run(lambda: time.sleep(0.001), 3)
        publish_prometheus(rec.report(), "n0", registry=reg)
        # second publish into the SAME registry reuses the collectors
        publish_prometheus(rec.report(), "n1", registry=reg)
        for node in ("n0", "n1"):
            assert sample(reg, "tpu_exporter_workload_step_seconds",
                          node=node, stat="p50") is not None
            assert sample(reg, "tpu_exporter_workload_compile_seconds", node=node) is not None
            assert sample(reg, "tpu_exporter_workload_tflops", node=node) is not None

    def test_burnin_telemetry_block(self):
        from tpu_operator.workloads.burnin import BurninConfig, make_mesh, run_burnin

        result = run_burnin(
            mesh=make_mesh(), steps=3,
            cfg=BurninConfig(d_model=64, d_ff=128, seq_len=32, batch=4, n_layers=1),
            record_telemetry=True, telemetry_host="t0",
        )
        t = result["telemetry"]
        assert t["steps"] == 3 and t["compile_s"] > 0
        assert t["host"] == "t0"
        assert t.get("tflops") is not None  # flops estimate wired through


# ---------------------------------------------------------------------------
# the floor table
# ---------------------------------------------------------------------------


class TestPerfFloors:
    def test_peaks_agree_with_matmul_bench(self):
        # perf.py carries a jax-free copy of the published peaks; the
        # two tables must never drift
        from tpu_operator.perf import PEAK_TFLOPS as local
        from tpu_operator.workloads.matmul_bench import PEAK_TFLOPS as bench

        assert local == bench

    def test_v5e_keeps_measured_numbers(self):
        roofs = measured_roofs()
        assert roofs["v5e"] == {"matmul_tflops": 185.0, "triad_gbps": 665.0}

    def test_floors_are_fraction_of_roofs(self):
        floors = default_floors()
        for gen, roof in measured_roofs().items():
            for probe in roof:
                assert floors[gen][probe] == pytest.approx(
                    roof[probe] * FLOOR_FRACTION, rel=0.01
                )

    def test_floors_for_blob_and_fallbacks(self):
        assert floors_for("v5e", floors_json())["matmul_tflops"] == pytest.approx(
            185.0 * FLOOR_FRACTION, rel=0.01
        )
        # malformed blob -> built-in defaults, unknown generation -> {}
        assert floors_for("v5e", "{not json")["matmul_tflops"] > 0
        assert floors_for("v9x", floors_json()) == {}
        assert floors_for("", None) == {}


# ---------------------------------------------------------------------------
# layer 2: exporter grey-failure detection
# ---------------------------------------------------------------------------


def make_exporter(store=None, node="tpu-0", floor=100.0, **kw):
    reg = kw.pop("registry", prometheus_client.CollectorRegistry())
    return MetricsExporterAgent(
        node_name=node, client=store, registry=reg,
        floors={"matmul_tflops": floor} if floor else {}, **kw
    ), reg


class _FakeDevice:
    def __init__(self, i):
        self.id = i
        self.platform = "cpu"

    def memory_stats(self):
        return {"bytes_in_use": 1, "bytes_limit": 2}


class TestStaleSeriesHygiene:
    """Regression (ISSUE 9 satellite): the ICI gauge and the per-probe
    baseline/floor/degraded series used to survive the hardware they
    measured — node discovery strips the labels, but the exporter kept
    publishing the last value forever."""

    @staticmethod
    def _exporter(floors):
        reg = prometheus_client.CollectorRegistry()
        return MetricsExporterAgent(node_name="tpu-0", registry=reg, floors=floors), reg

    def _seeded_exporter(self):
        exp, reg = self._exporter({"matmul_tflops": 100.0, "ici_gbps": 10.0})
        exp.ici_bandwidth.labels("tpu-0").set(42.0)
        exp.hbm_bandwidth.labels("tpu-0").set(600.0)
        exp.matmul_tflops.labels("tpu-0").set(150.0)
        exp.observe_probe("ici_gbps", 42.0)
        exp.observe_probe("matmul_tflops", 150.0)
        assert sample(reg, "tpu_exporter_ici_bandwidth_gbps", node="tpu-0") == 42.0
        assert sample(reg, "tpu_exporter_probe_baseline",
                      node="tpu-0", probe="ici_gbps") is not None
        return exp, reg

    def test_chip_count_drop_to_one_retires_ici_series(self, monkeypatch):
        exp, reg = self._seeded_exporter()
        monkeypatch.setattr("jax.local_devices", lambda: [_FakeDevice(0)])
        exp.collect_device_stats()
        # no interconnect on one chip: the ICI gauge and its probe's
        # baseline/floor/degraded series are gone, not frozen
        assert sample(reg, "tpu_exporter_ici_bandwidth_gbps", node="tpu-0") is None
        for series in ("tpu_exporter_probe_baseline", "tpu_exporter_perf_floor",
                       "tpu_exporter_perf_degraded"):
            assert sample(reg, series, node="tpu-0", probe="ici_gbps") is None
        # the compute-side series survive: one chip still computes
        assert sample(reg, "tpu_exporter_matmul_tflops", node="tpu-0") == 150.0
        assert sample(reg, "tpu_exporter_probe_baseline",
                      node="tpu-0", probe="matmul_tflops") is not None

    def test_hardware_vanishing_retires_every_probe_series(self, monkeypatch):
        exp, reg = self._seeded_exporter()
        monkeypatch.setattr("jax.local_devices", lambda: [])
        exp.collect_device_stats()
        assert sample(reg, "tpu_exporter_chips", node="tpu-0") == 0
        assert sample(reg, "tpu_exporter_ici_bandwidth_gbps", node="tpu-0") is None
        assert sample(reg, "tpu_exporter_hbm_bandwidth_gbps", node="tpu-0") is None
        assert sample(reg, "tpu_exporter_matmul_tflops", node="tpu-0") is None
        for probe in ("ici_gbps", "matmul_tflops"):
            for series in ("tpu_exporter_probe_baseline", "tpu_exporter_perf_floor",
                           "tpu_exporter_perf_degraded"):
                assert sample(reg, series, node="tpu-0", probe=probe) is None

    def test_runtime_failure_also_retires(self, monkeypatch):
        exp, reg = self._seeded_exporter()

        def boom():
            raise RuntimeError("no runtime")

        monkeypatch.setattr("jax.local_devices", boom)
        exp.collect_device_stats()
        assert sample(reg, "tpu_exporter_ici_bandwidth_gbps", node="tpu-0") is None
        assert sample(reg, "tpu_exporter_probe_baseline",
                      node="tpu-0", probe="matmul_tflops") is None

    def test_healthy_chip_count_keeps_series(self, monkeypatch):
        exp, reg = self._seeded_exporter()
        monkeypatch.setattr(
            "jax.local_devices", lambda: [_FakeDevice(i) for i in range(4)]
        )
        exp.collect_device_stats()
        assert sample(reg, "tpu_exporter_ici_bandwidth_gbps", node="tpu-0") == 42.0
        assert sample(reg, "tpu_exporter_probe_baseline",
                      node="tpu-0", probe="ici_gbps") is not None

    def test_vanished_chip_hbm_series_retire(self, monkeypatch):
        """A chip that disappears takes its per-chip HBM series with it:
        frozen at 95% it would keep the near-capacity alert firing for
        hardware that no longer exists."""
        exp, reg = self._exporter({})
        monkeypatch.setattr(
            "jax.local_devices", lambda: [_FakeDevice(i) for i in range(4)]
        )
        exp.collect_device_stats()
        assert sample(reg, "tpu_exporter_hbm_used_bytes", node="tpu-0", chip="3") == 1
        monkeypatch.setattr(
            "jax.local_devices", lambda: [_FakeDevice(i) for i in range(2)]
        )
        exp.collect_device_stats()
        assert sample(reg, "tpu_exporter_hbm_used_bytes", node="tpu-0", chip="3") is None
        assert sample(reg, "tpu_exporter_hbm_limit_bytes", node="tpu-0", chip="3") is None
        assert sample(reg, "tpu_exporter_hbm_used_bytes", node="tpu-0", chip="1") == 1

        def boom():
            raise RuntimeError("runtime gone")

        monkeypatch.setattr("jax.local_devices", boom)
        exp.collect_device_stats()
        assert sample(reg, "tpu_exporter_hbm_used_bytes", node="tpu-0", chip="1") is None

    def test_detection_state_resets_with_the_series(self, monkeypatch):
        """A vanished chip's breach counter must not survive into the
        hardware's replacement: the fresh chip starts clean."""
        exp, reg = self._exporter({"ici_gbps": 10.0})
        for _ in range(consts.PERF_BREACH_SAMPLES - 1):
            exp.observe_probe("ici_gbps", 5.0)  # one short of breach
        monkeypatch.setattr("jax.local_devices", lambda: [_FakeDevice(0)])
        exp.collect_device_stats()
        monkeypatch.setattr(
            "jax.local_devices", lambda: [_FakeDevice(i) for i in range(4)]
        )
        exp.collect_device_stats()
        exp.observe_probe("ici_gbps", 5.0)  # would have breached before
        assert sample(reg, "tpu_exporter_perf_degraded",
                      node="tpu-0", probe="ici_gbps") == 0


class TestGreyFailureDetection:
    def test_sustained_breach_sets_series_and_label(self):
        store = FakeClient()
        store.create(make_tpu_node("tpu-0"))
        exp, reg = make_exporter(store)
        for i in range(consts.PERF_BREACH_SAMPLES):
            labels = store.get("v1", "Node", "tpu-0")["metadata"].get("labels") or {}
            assert labels.get(consts.TPU_PERF_LABEL) is None  # not yet
            exp.observe_probe("matmul_tflops", 60.0)
        assert sample(reg, "tpu_exporter_perf_degraded",
                      node="tpu-0", probe="matmul_tflops") == 1
        labels = store.get("v1", "Node", "tpu-0")["metadata"]["labels"]
        assert labels[consts.TPU_PERF_LABEL] == consts.PERF_DEGRADED

    def test_one_good_sample_resets_the_count(self):
        store = FakeClient()
        store.create(make_tpu_node("tpu-0"))
        exp, reg = make_exporter(store)
        for _ in range(consts.PERF_BREACH_SAMPLES - 1):
            exp.observe_probe("matmul_tflops", 60.0)
        exp.observe_probe("matmul_tflops", 150.0)  # recovery resets
        for _ in range(consts.PERF_BREACH_SAMPLES - 1):
            exp.observe_probe("matmul_tflops", 60.0)
        labels = store.get("v1", "Node", "tpu-0")["metadata"].get("labels") or {}
        assert labels.get(consts.TPU_PERF_LABEL) is None
        assert sample(reg, "tpu_exporter_perf_degraded",
                      node="tpu-0", probe="matmul_tflops") == 0

    def test_recovery_clears_label_and_series(self):
        store = FakeClient()
        store.create(make_tpu_node("tpu-0"))
        exp, reg = make_exporter(store)
        for _ in range(consts.PERF_BREACH_SAMPLES):
            exp.observe_probe("matmul_tflops", 60.0)
        exp.observe_probe("matmul_tflops", 150.0)
        labels = store.get("v1", "Node", "tpu-0")["metadata"].get("labels") or {}
        assert labels.get(consts.TPU_PERF_LABEL) is None
        assert sample(reg, "tpu_exporter_perf_degraded",
                      node="tpu-0", probe="matmul_tflops") == 0

    def test_baseline_and_floor_gauges(self):
        exp, reg = make_exporter()
        for v in (100.0, 120.0, 110.0):
            exp.observe_probe("matmul_tflops", v)
        assert sample(reg, "tpu_exporter_probe_baseline",
                      node="tpu-0", probe="matmul_tflops") == 110.0
        assert sample(reg, "tpu_exporter_perf_floor",
                      node="tpu-0", probe="matmul_tflops") == 100.0

    def test_no_floor_only_feeds_baseline(self):
        exp, reg = make_exporter(floor=None)
        assert exp.observe_probe("mystery_probe", 1.0) is False
        assert sample(reg, "tpu_exporter_probe_baseline",
                      node="tpu-0", probe="mystery_probe") == 1.0
        assert sample(reg, "tpu_exporter_perf_degraded",
                      node="tpu-0", probe="mystery_probe") is None

    def test_no_client_flips_series_without_label_write(self):
        exp, reg = make_exporter(store=None)
        for _ in range(consts.PERF_BREACH_SAMPLES):
            assert exp.observe_probe("matmul_tflops", 60.0) or True
        assert sample(reg, "tpu_exporter_perf_degraded",
                      node="tpu-0", probe="matmul_tflops") == 1

    def test_probe_failure_is_indeterminate_in_auto(self, monkeypatch):
        """A probe that fails to RUN must not move the verdict: auto
        mode skips quietly (chip owned by a tenant), and the breach
        bookkeeping is untouched."""
        store = FakeClient()
        store.create(make_tpu_node("tpu-0"))
        exp, reg = make_exporter(store, active_probes="auto")
        # push to the edge of breach, then fail the next probe run
        for _ in range(consts.PERF_BREACH_SAMPLES - 1):
            exp.observe_probe("matmul_tflops", 60.0)

        def boom(*a, **k):
            raise RuntimeError("chip busy")

        monkeypatch.setattr(
            "tpu_operator.workloads.matmul_bench.matmul_tflops", boom
        )
        exp.probe_utilization()
        labels = store.get("v1", "Node", "tpu-0")["metadata"].get("labels") or {}
        assert labels.get(consts.TPU_PERF_LABEL) is None
        assert sample(reg, "tpu_exporter_collect_errors_total", node="tpu-0") in (None, 0)

    def test_probe_failure_counts_in_on_mode(self, monkeypatch):
        exp, reg = make_exporter(active_probes="on")

        def boom(*a, **k):
            raise RuntimeError("broken")

        monkeypatch.setattr("tpu_operator.workloads.kernels.hbm_bandwidth_probe", boom)
        exp.probe_bandwidth()
        assert sample(reg, "tpu_exporter_collect_errors_total", node="tpu-0") == 1

    def test_failed_label_write_retries_next_sample(self):
        """An apiserver hiccup on the label patch must not lose the
        verdict: the next observe re-derives and re-publishes."""
        store = FakeClient()  # node does NOT exist yet -> patch 404s
        exp, _ = make_exporter(store)
        for _ in range(consts.PERF_BREACH_SAMPLES):
            exp.observe_probe("matmul_tflops", 60.0)
        store.create(make_tpu_node("tpu-0"))
        exp.observe_probe("matmul_tflops", 60.0)  # retry lands
        labels = store.get("v1", "Node", "tpu-0")["metadata"]["labels"]
        assert labels[consts.TPU_PERF_LABEL] == consts.PERF_DEGRADED

    def test_restart_does_not_clear_live_label_without_recovery(self):
        """A restarted exporter (fresh counters) whose FIRST sample is
        still below floor must NOT clear a pre-existing degraded label:
        "no sustained breach observed yet" is not recovery, and a
        premature clear would uncordon a node the FSM is holding at
        revalidation. An at-floor sample is the evidence that clears."""
        store = FakeClient()
        node = make_tpu_node("tpu-0")
        node["metadata"]["labels"][consts.TPU_PERF_LABEL] = consts.PERF_DEGRADED
        store.create(node)
        exp, _ = make_exporter(store)  # the restarted incarnation
        exp.observe_probe("matmul_tflops", 60.0)  # still slow
        labels = store.get("v1", "Node", "tpu-0")["metadata"]["labels"]
        assert labels.get(consts.TPU_PERF_LABEL) == consts.PERF_DEGRADED
        exp.observe_probe("matmul_tflops", 150.0)  # genuine recovery
        labels = store.get("v1", "Node", "tpu-0")["metadata"].get("labels") or {}
        assert labels.get(consts.TPU_PERF_LABEL) is None

    def test_registration_idempotent_against_shared_registry(self):
        """PR 6 fixed OperatorMetrics only; a second in-process exporter
        sharing a registry (one per simulated node in the smoke) must
        reuse the collectors instead of tripping the duplicate-
        registration ValueError."""
        reg = prometheus_client.CollectorRegistry()
        a = MetricsExporterAgent(node_name="n0", registry=reg)
        b = MetricsExporterAgent(node_name="n1", registry=reg)  # must not raise
        a.chips.labels("n0").set(4)
        b.chips.labels("n1").set(4)
        assert sample(reg, "tpu_exporter_chips", node="n0") == 4
        assert sample(reg, "tpu_exporter_chips", node="n1") == 4
        # and against the DEFAULT registry, twice
        c = MetricsExporterAgent(node_name="n2", registry=prometheus_client.REGISTRY)
        d = MetricsExporterAgent(node_name="n2", registry=prometheus_client.REGISTRY)
        assert c.chips is d.chips

    def test_floors_from_env(self, monkeypatch):
        from tpu_operator.agents.metrics_exporter_agent import floors_from_env

        monkeypatch.setattr(
            "tpu_operator.workloads.matmul_bench.chip_generation", lambda: "v5e"
        )
        monkeypatch.setenv("PERF_FLOORS_JSON", floors_json())
        floors = floors_from_env()
        assert floors["matmul_tflops"] == pytest.approx(185.0 * FLOOR_FRACTION, rel=0.01)
        # off-TPU: no generation -> no floors -> detection off
        monkeypatch.setattr(
            "tpu_operator.workloads.matmul_bench.chip_generation", lambda: ""
        )
        assert floors_from_env() == {}


# ---------------------------------------------------------------------------
# layer 3: the grey-failure FSM path
# ---------------------------------------------------------------------------


def grey_node(name="grey-0", pool="pool-a"):
    node = make_tpu_node(name, nodepool=pool)
    node["metadata"]["labels"][consts.TPU_PRESENT_LABEL] = "true"
    node["metadata"]["labels"][consts.TPU_PERF_LABEL] = consts.PERF_DEGRADED
    return node


class TestGreyFailureFSM:
    def spec(self, **remediation):
        base = {"enable": True, "retryLimit": 3, "timeoutSeconds": 300,
                "gracePeriodSeconds": 300}
        base.update(remediation)
        return HealthMonitorSpec.from_dict({"remediation": base})

    def test_perf_label_enters_repair_without_grace(self):
        """Grey entry bypasses the provisioning grace: the exporter's
        breach is already debounced over N probe intervals, and a
        provisioning node has no successful probes to breach."""
        store = FakeClient()
        store.create(grey_node())
        mgr = NodeRepairManager(store, NS)
        states = mgr.apply_state(self.spec())
        assert states["grey-0"] == RepairState.CORDON_REQUIRED
        annotations = store.get("v1", "Node", "grey-0")["metadata"]["annotations"]
        assert annotations[consts.REPAIR_REASON_ANNOTATION] == consts.REPAIR_REASON_PERF

    def test_health_entry_still_respects_grace(self):
        store = FakeClient()
        node = make_tpu_node("h-0")
        node["metadata"]["labels"][consts.TPU_HEALTH_LABEL] = consts.HEALTH_DEGRADED
        store.create(node)
        mgr = NodeRepairManager(store, NS)
        states = mgr.apply_state(self.spec())
        assert states["h-0"] == consts.HEALTH_DEGRADED  # parked in grace

    def test_revalidate_needs_perf_clear_for_perf_entry(self):
        store = FakeClient()
        node = grey_node()
        node["metadata"]["labels"][consts.REPAIR_STATE_LABEL] = RepairState.REVALIDATE_REQUIRED
        node["metadata"]["annotations"] = {
            consts.REPAIR_REASON_ANNOTATION: consts.REPAIR_REASON_PERF,
            consts.REPAIR_STATE_SINCE_ANNOTATION: str(int(time.time())),
        }
        node["spec"]["unschedulable"] = True
        store.create(node)
        mgr = NodeRepairManager(store, NS)
        states = mgr.apply_state(self.spec())
        assert states["grey-0"] == RepairState.REVALIDATE_REQUIRED  # still breached
        # the exporter clears the label -> revalidation passes
        store.patch("v1", "Node", "grey-0",
                    {"metadata": {"labels": {consts.TPU_PERF_LABEL: None}}})
        states = mgr.apply_state(self.spec())
        assert states["grey-0"] == RepairState.UNCORDON_REQUIRED

    def test_revalidate_perf_entry_blocked_by_health_degraded(self):
        """A chip that recovered its speed but now fails health probes
        must NOT uncordon off the perf reason alone."""
        store = FakeClient()
        node = grey_node()
        del node["metadata"]["labels"][consts.TPU_PERF_LABEL]  # perf cleared
        node["metadata"]["labels"][consts.TPU_HEALTH_LABEL] = consts.HEALTH_DEGRADED
        node["metadata"]["labels"][consts.REPAIR_STATE_LABEL] = RepairState.REVALIDATE_REQUIRED
        node["metadata"]["annotations"] = {
            consts.REPAIR_REASON_ANNOTATION: consts.REPAIR_REASON_PERF,
            consts.REPAIR_STATE_SINCE_ANNOTATION: str(int(time.time())),
        }
        node["spec"]["unschedulable"] = True
        store.create(node)
        mgr = NodeRepairManager(store, NS)
        states = mgr.apply_state(self.spec())
        assert states["grey-0"] == RepairState.REVALIDATE_REQUIRED

    def test_health_entry_unchanged_needs_healthy_verdict(self):
        """The health path keeps its strict contract: absence of a
        verdict is indeterminate, not health."""
        store = FakeClient()
        node = make_tpu_node("h-0")
        node["metadata"]["labels"][consts.REPAIR_STATE_LABEL] = RepairState.REVALIDATE_REQUIRED
        node["metadata"]["annotations"] = {
            consts.REPAIR_REASON_ANNOTATION: consts.REPAIR_REASON_HEALTH,
            consts.REPAIR_STATE_SINCE_ANNOTATION: str(int(time.time())),
        }
        node["spec"]["unschedulable"] = True
        store.create(node)
        mgr = NodeRepairManager(store, NS)
        states = mgr.apply_state(self.spec())
        assert states["h-0"] == RepairState.REVALIDATE_REQUIRED

    def test_grey_member_poisons_gang_and_leaves_placement(self):
        from tpu_operator.placement.engine import labels_unavailable

        assert labels_unavailable({consts.TPU_PERF_LABEL: consts.PERF_DEGRADED})
        assert not labels_unavailable({})
        store = FakeClient()
        store.create(grey_node("g-0", pool="p"))
        peer = make_tpu_node("g-1", nodepool="p")
        peer["metadata"]["labels"][consts.TPU_PRESENT_LABEL] = "true"
        store.create(peer)
        mgr = NodeRepairManager(store, NS)
        mgr.apply_state(self.spec())
        labels = store.get("v1", "Node", "g-1")["metadata"]["labels"]
        assert labels.get(consts.TPU_SLICE_HEALTH_LABEL) == consts.HEALTH_DEGRADED

    def test_grey_failure_drill_over_the_wire(self):
        from drill import assert_grey_failure_drill_passed, run_grey_failure_drill
        from tpu_operator.kube.http_client import HttpClient
        from tpu_operator.kube.httpserver import FakeApiServer

        store = FakeClient()
        server = FakeApiServer(store).start()
        try:
            client = HttpClient(server.base_url, timeout=10.0)
            obs = run_grey_failure_drill(client, NS)
            assert_grey_failure_drill_passed(obs)
        finally:
            server.stop()

    def test_grey_failure_drill_chaos_rider(self):
        """The chaos rider: the same grey drill through a seeded fault
        director (GET/PATCH 500s + latency) — the retry layer must ride
        the faults out and the FSM still converge."""
        from drill import assert_grey_failure_drill_passed, run_grey_failure_drill
        from tpu_operator.kube.chaos import FAULT_500, ChaosDirector, FaultRule
        from tpu_operator.kube.http_client import HttpClient
        from tpu_operator.kube.httpserver import FakeApiServer

        store = FakeClient()
        director = ChaosDirector(seed=20260803)
        director.rules = [
            FaultRule(FAULT_500, rate=1.0, times=2, verbs=("GET",)),
            FaultRule(FAULT_500, rate=0.05, verbs=("GET", "PATCH")),
        ]
        server = FakeApiServer(store, chaos=director).start()
        try:
            client = HttpClient(server.base_url, timeout=10.0)
            obs = run_grey_failure_drill(client, NS)
            assert_grey_failure_drill_passed(obs)
        finally:
            server.stop()
        assert director.fault_log  # the schedule actually fired


# ---------------------------------------------------------------------------
# layer 4: fleet aggregation
# ---------------------------------------------------------------------------


def tpu_pool_node(name, healthy=True, perf_degraded=False):
    node = make_tpu_node(name, "tpu-v5-lite-podslice", "4x4")
    node["metadata"]["labels"][consts.TPU_PRESENT_LABEL] = "true"
    if not healthy:
        node["metadata"]["labels"][consts.TPU_HEALTH_LABEL] = consts.HEALTH_DEGRADED
    if perf_degraded:
        node["metadata"]["labels"][consts.TPU_PERF_LABEL] = consts.PERF_DEGRADED
    return node


def gang_cm(store, slice_name, artifact):
    cm = new_object(
        "v1", "ConfigMap", f"{slice_name}-gang", NS,
        labels={"app.kubernetes.io/managed-by": "tpu-slice-manager"},
        data={"TPU_WORKER_HOSTNAMES": "x"},
    )
    cm["metadata"]["annotations"] = {
        consts.GANG_TELEMETRY_ANNOTATION: json.dumps(artifact)
    }
    store.create(cm)
    return cm


class TestFleetAggregation:
    def test_gang_series_and_straggler_event(self):
        store = FakeClient()
        gang_cm(store, "tpu-slice-a", {
            "gang_step_p50_s": 0.01, "straggler_ratio": 1.6, "slowest_host": "n3",
        })
        gang_cm(store, "tpu-slice-b", {
            "gang_step_p50_s": 0.02, "straggler_ratio": 1.0, "slowest_host": "n7",
        })
        agg = FleetTelemetryAggregator(store, NS)
        summary = agg.sync()
        assert summary["gangs"]["tpu-slice-a"]["straggler_ratio"] == 1.6
        assert summary["stragglers"] == ["tpu-slice-a"]
        reg = prometheus_client.REGISTRY
        assert sample(reg, "tpu_operator_gang_step_seconds",
                      **{"slice": "tpu-slice-a"}) == 0.01
        assert sample(reg, "tpu_operator_gang_straggler_ratio",
                      **{"slice": "tpu-slice-b"}) == 1.0
        events = [e for e in store.list("v1", "Event") if e.get("reason") == "PerfDegraded"]
        assert len(events) == 1 and "n3" in events[0]["message"]
        # a second pass must not duplicate the event for the same episode
        agg.sync()
        events = [e for e in store.list("v1", "Event") if e.get("reason") == "PerfDegraded"]
        assert sum(e.get("count", 1) for e in events) <= 2

    def test_stale_gang_series_removed(self):
        store = FakeClient()
        cm = gang_cm(store, "tpu-slice-gone", {
            "gang_step_p50_s": 0.01, "straggler_ratio": 1.0, "slowest_host": "n0",
        })
        agg = FleetTelemetryAggregator(store, NS)
        agg.sync()
        reg = prometheus_client.REGISTRY
        assert sample(reg, "tpu_operator_gang_step_seconds",
                      **{"slice": "tpu-slice-gone"}) == 0.01
        store.delete("v1", "ConfigMap", cm["metadata"]["name"], NS)
        agg.sync()
        assert sample(reg, "tpu_operator_gang_step_seconds",
                      **{"slice": "tpu-slice-gone"}) is None

    def test_fleet_healthy_tflops_prices_in_service_nodes(self):
        store = FakeClient()
        store.create(tpu_pool_node("n0"))
        store.create(tpu_pool_node("n1"))
        store.create(tpu_pool_node("n2", healthy=False))
        store.create(tpu_pool_node("n3", perf_degraded=True))
        agg = FleetTelemetryAggregator(store, NS)
        summary = agg.sync()
        # v5e measured roof x 4 chips x 2 in-service hosts
        expected = measured_roofs()["v5e"]["matmul_tflops"] * 4 * 2
        assert summary["fleet_healthy_tflops"] == pytest.approx(expected)
        assert summary["perf_degraded_nodes"] == ["n3"]
        reg = prometheus_client.REGISTRY
        assert sample(reg, "tpu_operator_fleet_healthy_tflops") == pytest.approx(expected)
        assert sample(reg, "tpu_operator_perf_degraded_nodes") == 1

    def test_malformed_artifact_skipped(self):
        store = FakeClient()
        cm = new_object(
            "v1", "ConfigMap", "bad-gang", NS,
            labels={"app.kubernetes.io/managed-by": "tpu-slice-manager"},
            data={},
        )
        cm["metadata"]["annotations"] = {consts.GANG_TELEMETRY_ANNOTATION: "{broken"}
        store.create(cm)
        agg = FleetTelemetryAggregator(store, NS)
        summary = agg.sync()  # must not raise
        assert summary["gangs"] == {}


# ---------------------------------------------------------------------------
# the slice manager's publication hop
# ---------------------------------------------------------------------------


class TestGangTelemetryPublication:
    def test_publish_annotates_gang_configmap(self):
        store = FakeClient()
        store.create(new_object(
            "v1", "ConfigMap", "tpu-slice-x-gang", NS,
            labels={"app.kubernetes.io/managed-by": "tpu-slice-manager"},
            data={"TPU_WORKER_HOSTNAMES": "a,b"},
        ))
        agent = SliceManagerAgent(store, NS)
        artifact = {"gang_step_p50_s": 0.01, "straggler_ratio": 1.0,
                    "slowest_host": "a", "hosts": 2}
        assert agent.publish_gang_telemetry("tpu-slice-x", artifact)
        cm = store.get("v1", "ConfigMap", "tpu-slice-x-gang", NS)
        stored = json.loads(
            cm["metadata"]["annotations"][consts.GANG_TELEMETRY_ANNOTATION]
        )
        assert stored == artifact
        # the gang env data is untouched by the annotation patch
        assert cm["data"]["TPU_WORKER_HOSTNAMES"] == "a,b"

    def test_publish_gone_gang_returns_false(self):
        agent = SliceManagerAgent(FakeClient(), NS)
        assert agent.publish_gang_telemetry("tpu-slice-x", {}) is False


# ---------------------------------------------------------------------------
# lint: TPUOP-O003
# ---------------------------------------------------------------------------


class TestPrometheusRuleLint:
    def rule_obj(self, expr, name="r", alert="A"):
        return {
            "apiVersion": "monitoring.coreos.com/v1", "kind": "PrometheusRule",
            "metadata": {"name": name},
            "spec": {"groups": [{"name": "g", "rules": [{"alert": alert, "expr": expr}]}]},
        }

    def test_typod_metric_flagged(self):
        from tpu_operator.lint.metrics_catalog import analyze_rules

        findings = analyze_rules(
            [("state:x", [self.rule_obj("tpu_operator_nonexistent_series > 0")])]
        )
        assert [f.rule for f in findings] == ["TPUOP-O003"]
        assert "tpu_operator_nonexistent_series" in findings[0].message

    def test_registered_metric_passes(self):
        from tpu_operator.lint.metrics_catalog import analyze_rules

        findings = analyze_rules(
            [("state:x", [self.rule_obj(
                "rate(tpu_operator_reconciliation_total[5m]) "
                "/ tpu_exporter_perf_degraded > 0"
            )])]
        )
        assert findings == []

    def test_shipped_rules_all_clean(self):
        """Every PrometheusRule the states actually render references
        only registered series — the live guarantee the satellite asks
        for."""
        from tpu_operator.lint.metrics_catalog import analyze_rules
        from tpu_operator.lint.runner import manifest_groups

        groups = manifest_groups()
        assert any(
            obj.get("kind") == "PrometheusRule"
            for _, objs in groups for obj in objs
        )  # the check is not vacuous
        assert analyze_rules(groups) == []
